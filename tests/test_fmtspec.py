"""fmtspec parser/formatter tests (SURVEY.md component #5).

Parity target is C printf (``acg/fmtspec.c`` delegates application to
libc): beyond round-trip and validation unit tests, a compiled C oracle
checks FmtSpec.format against the platform printf over a grid of specs
and values, including the %a/%A hexfloat conversions Python lacks.
"""

import shutil
import subprocess
import sys

import pytest

from acg_tpu.fmtspec import (STAR, Flags, FmtSpec, FmtSpecError, parse,
                             parse_prefix)


# -- parsing ---------------------------------------------------------------

@pytest.mark.parametrize("s", [
    "%g", "%.17g", "%e", "%12.6f", "%-+12.6e", "%#016.8G", "% .3F",
    "%d", "%5u", "%08x", "%llX", "%hhd", "%zd", "%Lg", "%s", "%c", "%%",
    "%*d", "%.*f", "%*.*g", "%.f", "%.0e",
])
def test_parse_roundtrip(s):
    spec = parse(s)
    # canonical form re-parses to the same spec (fmtspecstr round-trip)
    assert parse(str(spec)) == spec


def test_parse_fields():
    spec = parse("%-+012.6le")
    assert spec.flags == Flags.MINUS | Flags.PLUS | Flags.ZERO
    assert spec.width == 12 and spec.precision == 6
    assert spec.length == "l" and spec.conversion == "e"
    assert spec.is_float and not spec.is_integer


def test_parse_star_and_bare_dot():
    assert parse("%*.*f").width == STAR
    assert parse("%*.*f").precision == STAR
    assert parse("%.g").precision == 0  # bare '.' means precision 0
    assert parse("%.17g").needs_star_args is False
    assert parse("%*g").needs_star_args is True


def test_parse_prefix_endptr():
    spec, end = parse_prefix("%8.3f seconds", 0)
    assert spec.width == 8 and spec.conversion == "f"
    assert "%8.3f seconds"[end:] == " seconds"


@pytest.mark.parametrize("s", ["", "g", "%", "%q", "%5", "%.3", "%ly ",
                               "%hhh", "%5.2", "%gg"])
def test_parse_invalid(s):
    with pytest.raises(FmtSpecError):
        parse(s)


def test_length_longest_match():
    assert parse("%lld").length == "ll"
    assert parse("%ld").length == "l"
    assert parse("%hhu").length == "hh"


# -- application -----------------------------------------------------------

def test_format_matches_python_percent():
    for s, v in [("%.17g", 3.141592653589793), ("%e", 1e-300),
                 ("%12.6f", -2.5), ("%+g", 2.0), ("%05d", 42),
                 ("%x", 255), ("%s", "hi"), ("%10.3E", 6.02e23)]:
        assert parse(s).format(v) == s % v


def test_format_star_args():
    assert parse("%*.*f").format(2.5, 8, 2) == "%8.2f" % 2.5
    with pytest.raises(FmtSpecError):
        parse("%g").format(1.0, 8)  # unused star arg


def test_format_strips_length_modifier():
    assert parse("%lg").format(0.5) == "%g" % 0.5
    assert parse("%lld").format(7) == "7"


def test_format_integer_conversion_truncates_explicitly():
    # the CLI rejects %d for --numfmt; the module itself follows printf
    assert parse("%d").format(3) == "3"


def test_format_percent_and_n():
    assert parse("%%").format(None) == "%"
    assert parse("%n").format(None) == ""


def test_hexfloat_basic():
    assert parse("%a").format(1.5) == "0x1.8p+0"
    assert parse("%a").format(0.0) == "0x0p+0"
    assert parse("%A").format(1.5) == "0X1.8P+0"
    assert parse("%.0a").format(1.5) == "0x2p+0"
    assert parse("%.3a").format(1.5) == "0x1.800p+0"
    assert parse("%+a").format(1.5) == "+0x1.8p+0"
    assert parse("%a").format(-2.0) == "-0x1p+1"


# -- C printf oracle -------------------------------------------------------

_CC = shutil.which("gcc") or shutil.which("cc") or shutil.which("g++")


@pytest.mark.skipif(_CC is None, reason="no C compiler")
def test_format_against_c_printf(tmp_path):
    """Grid of float specs x values against the platform printf."""
    specs = ["%g", "%.17g", "%e", "%.3E", "%12.6f", "%-12.4g", "%+e",
             "% g", "%#.5G", "%015.6f", "%a", "%A", "%.4a", "%20.3a",
             "%010.2a", "%.1a", "%-14.1a"]
    vals = [0.0, 1.0, -1.0, 1.5, 3.141592653589793, -6.02e23, 1e-300,
            0.1, 123456.789, -0.0078125,
            float.fromhex("0x1.28p+0"),   # tie: rounds half-to-even
            float.fromhex("0x1.38p+0")]   # tie the other parity
    src = tmp_path / "oracle.c"
    lines = ["#include <stdio.h>", "int main(void){"]
    for s in specs:
        for v in vals:
            lines.append(f'printf("{s}\\n", {v!r});')
    lines += ["return 0;}"]
    src.write_text("\n".join(lines))
    exe = tmp_path / "oracle"
    subprocess.run([_CC, str(src), "-o", str(exe)], check=True)
    out = subprocess.run([str(exe)], capture_output=True, text=True,
                         check=True).stdout.splitlines()
    i = 0
    for s in specs:
        spec = parse(s)
        for v in vals:
            got = spec.format(v)
            assert got == out[i], f"{s} % {v!r}: ours {got!r} != C {out[i]!r}"
            i += 1


# -- CLI integration -------------------------------------------------------

def test_cli_numfmt_validation():
    from acg_tpu.cli import _validate_numfmt
    assert _validate_numfmt("%.17g") == "%.17g"
    assert _validate_numfmt("%lg") == "%g"        # length stripped for %
    assert _validate_numfmt("%-+12.6e") == "%-+12.6e"
    for bad in ["%d", "%s", "%*g", "%.*f", "%a", "plain", "%gg"]:
        with pytest.raises(SystemExit):
            _validate_numfmt(bad)
