"""Multi-controller (multi-host) smoke test.

Runs the full CLI solve over a genuine 2-process JAX multi-controller
"pod" on CPU (2 processes x 2 virtual devices = 4 global devices, gloo
collectives over localhost).  This is the TPU build's analog of the
reference's multi-rank MPI launches (``cuda/acg-cuda.c:891-1203``): same
program, real cross-process collectives, no mocks.
"""

import os
import socket
import subprocess
import sys

import pytest

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.io.mtxfile import write_mtx


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def matrix_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("mh") / "poisson2d_n12.mtx"
    write_mtx(path, poisson_mtx(12, dim=2))
    return path


def _launch(matrix_file, port, process_id, nparts=4, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    argv = [sys.executable, "-m", "acg_tpu.cli", str(matrix_file),
            "--nparts", str(nparts), "--manufactured-solution",
            "--max-iterations", "300", "--residual-rtol", "1e-8",
            "--dtype", "f64", "--warmup", "0",
            "--coordinator", f"localhost:{port}",
            "--num-processes", "2", "--process-id", str(process_id),
            *extra]
    return subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)


# nparts=4 uses every global device; nparts=2 exercises the round-robin
# device selection (one mesh device per controller -- devices[:2] would
# instead drop process 1 from the mesh entirely)
def test_restricted_build_owned_parts_only():
    """owned_parts builds matrix blocks and fills host arrays only for
    the listed parts -- the per-controller preprocessing restriction
    (the reference's only-local-data-per-rank property,
    ``graph.c:1529-1897``).  Non-owned parts keep A_local=None and
    all-zero (untouched calloc) stacked pages, while the owned shards
    match the unrestricted build exactly."""
    import numpy as np
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.parallel.dist import DistributedProblem
    from acg_tpu.partition import partition_rows

    r, c, v, N = poisson2d_coo(32)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 4, seed=0, method="band")
    full = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    rest = DistributedProblem.build(csr, part, 4, dtype=jnp.float64,
                                    owned_parts=(0, 1))
    assert rest.subs[0].A_local is not None
    assert rest.subs[2].A_local is None and rest.subs[3].A_local is None
    assert rest.local.format == full.local.format == "dia"
    assert rest.local.offsets == full.local.offsets
    for d in range(len(full.local.arrays)):
        fa, ra = np.asarray(full.local.arrays[d]), rest.local.arrays[d]
        np.testing.assert_array_equal(ra[:2], fa[:2])   # owned: identical
        assert not ra[2:].any()                         # non-owned: untouched
    b = np.ones(N)
    sf, sr = full.scatter(b), rest.scatter(b)
    np.testing.assert_array_equal(sr[:2], sf[:2])
    assert not sr[2:].any()


def test_restricted_build_graph_partition_falls_back_to_ell():
    """A restricted build of a NON-contiguous (graph) partition cannot
    prove mesh-uniform DIA offsets from global structure (local-index
    diagonals are unrelated to global ones), so it must take the ELL
    path -- and still solve correctly (regression: this crashed with
    'diagonals outside the given offset set')."""
    import numpy as np
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.stats import StoppingCriteria

    r, c, v, N = poisson2d_coo(24)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 4, seed=0, method="graph")
    rest = DistributedProblem.build(csr, part, 4, dtype=jnp.float64,
                                    owned_parts=(0, 1, 2, 3))
    assert rest.local.format == "ell"
    solver = DistCGSolver(rest)
    b = np.ones(N)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-8))
    assert np.linalg.norm(b - csr @ x) <= 1e-6 * np.linalg.norm(b)


def test_restricted_build_rss_scales_with_owned_fraction():
    """Peak host RSS of the stacked-problem build measured in a child
    process: owning 1/8 of the parts must cost well under half the
    full-replication build at a size where the difference is visible
    (VERDICT round 2 'done' criterion)."""
    import subprocess

    code = """
import sys
import numpy as np, jax.numpy as jnp
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.graph import partition_matrix
from acg_tpu.parallel.dist import DistributedProblem
from acg_tpu.partition import partition_rows

def rss_kb():
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * 4  # pages -> KB (4 KB pages)

owned = (0,) if sys.argv[1] == "restricted" else None
r, c, v, N = poisson2d_coo(1024)  # N=1.05M; full f64 DIA stack ~42 MB
csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
part = partition_rows(csr, 8, seed=0, method="band")
subs = partition_matrix(csr, part, 8, owned_parts=owned)
before = rss_kb()
prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64,
                                subs=subs, owned_parts=owned)
assert prob.local.arrays[0] is not None
print(rss_kb() - before)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"

    def run(mode):
        out = subprocess.run([sys.executable, "-c", code, mode],
                             capture_output=True, text=True, env=env,
                             timeout=300)
        assert out.returncode == 0, out.stderr
        return int(out.stdout.strip().splitlines()[-1])  # KB

    full = run("full")
    rest = run("restricted")
    # the stacked f64 arrays are ~42 MB fully filled; owning 1 of 8
    # parts touches ~1/8 of those pages (the rest stay virtual calloc
    # pages).  Resident-set growth across the stack step must reflect
    # that -- allow generous allocator noise either side.
    assert rest + 15_000 < full, (rest, full)


@pytest.mark.parametrize("nparts", [4, 2])
@pytest.mark.two_process_collectives
def test_cli_two_process_solve(matrix_file, nparts):
    """Both controllers solve; only process 0 prints stats + solution;
    the manufactured-solution error matches a single-process solve."""
    port = _free_port()
    procs = [_launch(matrix_file, port, i, nparts=nparts) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    # rank-0-only output convention (mtxfile_fwrite_mpi_double analog)
    assert "total solver time" in se0
    # rc==0 already implies convergence (divergence raises and exits 1)
    niter = int(se0.split("total iterations: ")[1].split()[0].replace(",", ""))
    assert niter > 0
    # gloo writes a connection banner to stdout ahead of our output
    assert "%%MatrixMarket matrix array" in so0
    assert "%%MatrixMarket" not in so1 and "total solver time" not in se1
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-7, se0
