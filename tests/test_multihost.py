"""Multi-controller (multi-host) smoke test.

Runs the full CLI solve over a genuine 2-process JAX multi-controller
"pod" on CPU (2 processes x 2 virtual devices = 4 global devices, gloo
collectives over localhost).  This is the TPU build's analog of the
reference's multi-rank MPI launches (``cuda/acg-cuda.c:891-1203``): same
program, real cross-process collectives, no mocks.
"""

import os
import socket
import subprocess
import sys

import pytest

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.io.mtxfile import write_mtx


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def matrix_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("mh") / "poisson2d_n12.mtx"
    write_mtx(path, poisson_mtx(12, dim=2))
    return path


def _launch(matrix_file, port, process_id, nparts=4, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    argv = [sys.executable, "-m", "acg_tpu.cli", str(matrix_file),
            "--nparts", str(nparts), "--manufactured-solution",
            "--max-iterations", "300", "--residual-rtol", "1e-8",
            "--dtype", "f64", "--warmup", "0",
            "--coordinator", f"localhost:{port}",
            "--num-processes", "2", "--process-id", str(process_id),
            *extra]
    return subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)


# nparts=4 uses every global device; nparts=2 exercises the round-robin
# device selection (one mesh device per controller -- devices[:2] would
# instead drop process 1 from the mesh entirely)
@pytest.mark.parametrize("nparts", [4, 2])
def test_cli_two_process_solve(matrix_file, nparts):
    """Both controllers solve; only process 0 prints stats + solution;
    the manufactured-solution error matches a single-process solve."""
    port = _free_port()
    procs = [_launch(matrix_file, port, i, nparts=nparts) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    # rank-0-only output convention (mtxfile_fwrite_mpi_double analog)
    assert "total solver time" in se0
    # rc==0 already implies convergence (divergence raises and exits 1)
    niter = int(se0.split("total iterations: ")[1].split()[0].replace(",", ""))
    assert niter > 0
    # gloo writes a connection banner to stdout ahead of our output
    assert "%%MatrixMarket matrix array" in so0
    assert "%%MatrixMarket" not in so1 and "total solver time" not in se1
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-7, se0
