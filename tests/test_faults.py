"""Fault-injection + solver-resilience subsystem (acg_tpu.faults,
acg_tpu.solvers.resilience).

The reference suite ships no fault injection; this matrix exercises the
TPU build's hardening on the virtual 8-device CPU mesh: deterministic
NaN/Inf/scalar faults at chosen iterations are detected in the jitted
loops, recovered by bounded host-side restarts (converging to the SAME
tolerance as the fault-free run), escalated down the fallback ladder
(dma->xla transport, host solver), agreed across controllers
(erragree), and bounded at the platform layer (the backend probe that
fixes the round-5 dryrun wedge).
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import faults
from acg_tpu.errors import BreakdownError
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.resilience import RecoveryPolicy

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    """No test may leak an armed injector into the rest of the suite --
    neither the installed spec nor the env var the CLI exports for its
    subprocess children."""
    yield
    faults.install(None)
    os.environ.pop(faults.ENV_VAR, None)


@pytest.fixture(scope="module")
def problem():
    r, c, v, N = poisson2d_coo(20)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    return csr, np.ones(N)


# -- spec grammar -------------------------------------------------------

def test_parse_fault_spec_grammar():
    s = faults.parse_fault_spec("spmv:nan@7")
    assert (s.site, s.mode, s.iteration) == ("spmv", "nan", 7)
    s = faults.parse_fault_spec("halo:inf@3:part=2:seed=5")
    assert (s.site, s.part, s.seed) == ("halo", 2, 5)
    s = faults.parse_fault_spec("peer:dead:proc=1")
    assert (s.site, s.mode, s.proc) == ("peer", "dead", 1)
    s = faults.parse_fault_spec("backend:hang:secs=12")
    assert s.secs == 12.0
    for bad in ("spmv", "spmv:frob@1", "nosite:nan@1", "dot:nan@x",
                "spmv:nan@1:bogus=2", "spmv:nan"):
        with pytest.raises(ValueError):
            faults.parse_fault_spec(bad)


def test_fault_spec_shift():
    s = faults.parse_fault_spec("spmv:nan@7")
    assert s.shift(3).iteration == 4
    assert s.shift(8) is None          # already fired: restarts are clean
    p = faults.parse_fault_spec("peer:dead:proc=1")
    assert p.shift(100) is p           # non-device sites never shift


# -- single-device detection + recovery --------------------------------

@pytest.mark.parametrize("pipelined", [False, True])
@pytest.mark.parametrize("spec", ["spmv:nan@5", "spmv:inf@5", "dot:neg@4",
                                  "dot:nan@4"])
def test_jax_cg_fault_detected_restarted_converges(problem, spec, pipelined):
    """The acceptance contract: a mid-solve fault is detected, the solve
    restarts from the recomputed true residual, and converges to the
    SAME tolerance as the fault-free run -- restart visible in stats."""
    csr, b = problem
    crit = StoppingCriteria(maxits=500, residual_rtol=1e-8)
    clean = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                        pipelined=pipelined)
    x_clean = clean.solve(b, criteria=crit)

    s = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                    pipelined=pipelined, recovery=RecoveryPolicy())
    with faults.injected(spec):
        x = s.solve(b, criteria=crit)
    st = s.stats
    assert st.converged
    assert st.nbreakdowns >= 1 and st.nrestarts >= 1
    # same tolerance as fault-free: the restarted solve honours the
    # ORIGINAL residual target
    assert st.rnrm2 <= crit.residual_rtol * st.r0nrm2 * (1 + 1e-6)
    rel = np.linalg.norm(x - x_clean) / np.linalg.norm(x_clean)
    assert rel < 1e-6
    report = st.fwrite()
    assert "resilience:" in report and "restart" in report


def test_unfireable_fault_configs_refuse(problem):
    """An armed injector that could never fire must refuse, not report
    a clean 'fault-tested' solve: halo faults on haloless topologies,
    and any device fault under the replacement-segment program."""
    from acg_tpu.errors import AcgError
    csr, b = problem
    crit = StoppingCriteria(maxits=100, residual_rtol=1e-4)
    with faults.injected("halo:nan@3"):
        with pytest.raises(AcgError, match="no halo"):
            JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64)
                        ).solve(b, criteria=crit)
    with faults.injected("spmv:nan@3"):
        s = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.bfloat16),
                        replace_every=8)
        with pytest.raises(AcgError, match="replacement-segment"):
            s.solve(np.ones(len(b), np.float32), criteria=crit)


def test_jax_cg_fault_without_recovery_raises(problem):
    """An injected fault with no recovery policy must surface as a
    BreakdownError, never launder into a returned x."""
    csr, b = problem
    s = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64))
    with faults.injected("spmv:nan@5"):
        with pytest.raises(BreakdownError):
            s.solve(b, criteria=StoppingCriteria(maxits=200,
                                                 residual_rtol=1e-8))
    assert s.stats.nbreakdowns == 1 and s.stats.nrestarts == 0


def test_jax_cg_host_fallback_rung(problem):
    """Retries exhausted + a host matrix available -> the final rung
    re-solves on the host oracle and still returns a good x."""
    csr, b = problem
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-8)
    s = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                    recovery=RecoveryPolicy(max_restarts=0),
                    host_matrix=csr)
    with faults.injected("spmv:nan@5"):
        x = s.solve(b, criteria=crit)
    st = s.stats
    assert st.converged and st.nfallbacks == 1
    assert "fallback: host reference solver" in st.fwrite()
    assert np.linalg.norm(b - csr @ np.asarray(x, np.float64)) \
        <= 1e-7 * np.linalg.norm(b)


def test_host_cg_fault_detected_and_restarted(problem):
    """The eager host solver runs the same detect-restart policy."""
    csr, b = problem
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-10)
    clean = HostCGSolver(csr)
    x_clean = clean.solve(b, criteria=crit)
    s = HostCGSolver(csr, recovery=RecoveryPolicy())
    with faults.injected("spmv:nan@6"):
        x = s.solve(b, criteria=crit)
    assert s.stats.converged and s.stats.nrestarts == 1
    assert np.linalg.norm(x - x_clean) <= 1e-8 * np.linalg.norm(x_clean)
    with faults.injected("dot:zero@3"):
        with pytest.raises(BreakdownError):
            HostCGSolver(csr).solve(b, criteria=crit)


# -- distributed (8-part virtual mesh) ---------------------------------

@pytest.mark.parametrize("spec", ["spmv:nan@3:part=2", "halo:nan@2",
                                  "dot:neg@4"])
def test_dist_cg_fault_recovers_on_mesh(problem, spec):
    """NaN at iteration k on the 8-part mesh -> detected (the flag is
    psum-derived, so the early exit is mesh-uniform), restarted,
    converges to the fault-free solution."""
    csr, b = problem
    part = partition_rows(csr, 8, seed=0)
    crit = StoppingCriteria(maxits=500, residual_rtol=1e-8)
    prob0 = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    clean = DistCGSolver(prob0)
    x_clean = clean.solve(b, criteria=crit)

    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s = DistCGSolver(prob, recovery=RecoveryPolicy())
    with faults.injected(spec):
        x = s.solve(b, criteria=crit)
    st = s.stats
    assert st.converged and st.nbreakdowns >= 1 and st.nrestarts >= 1
    assert np.linalg.norm(x - x_clean) <= 1e-6 * np.linalg.norm(x_clean)
    assert "resilience:" in st.fwrite()


def test_dist_cg_host_fallback_rung(problem):
    csr, b = problem
    part = partition_rows(csr, 8, seed=0)
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-8)
    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s = DistCGSolver(prob, recovery=RecoveryPolicy(max_restarts=0))
    with faults.injected("spmv:nan@3"):
        x = s.solve(b, criteria=crit)
    st = s.stats
    assert st.converged and st.nfallbacks == 1
    assert np.linalg.norm(b - csr @ x) <= 1e-7 * np.linalg.norm(b)


# -- CLI wiring ---------------------------------------------------------

def test_cli_fault_inject_restart_in_stats(capsys):
    """--fault-inject through the CLI: the solve recovers and the stats
    block surfaces the restart (acceptance criterion)."""
    from acg_tpu import cli
    rc = cli.main(["gen:poisson2d:16", "--fault-inject", "spmv:nan@4",
                   "--nparts", "1", "--max-iterations", "500",
                   "--residual-rtol", "1e-8", "--dtype", "f64",
                   "--warmup", "0", "--quiet"])
    err = capsys.readouterr().err
    assert rc == 0, err
    assert "resilience:" in err and "restart 1/" in err
    faults.install(None)


def test_cli_rejects_bad_fault_spec():
    from acg_tpu import cli
    with pytest.raises(SystemExit):
        cli.main(["gen:poisson2d:8", "--fault-inject", "spmv:frobnicate",
                  "--quiet"])


# -- bounded backend probe (the round-5 dryrun/bench wedge) ------------

def test_probe_bounded_under_backend_hang():
    """tunnel-down simulation: a hung backend init must fail the probe
    within its timeout -- well under a minute -- not wedge the caller."""
    from acg_tpu import _platform
    env_prev = os.environ.get(faults.ENV_VAR)
    cache_prev = _platform._probe_cache
    os.environ[faults.ENV_VAR] = "backend:hang:secs=120"
    _platform._probe_cache = None
    try:
        t0 = time.monotonic()
        ok, detail = _platform.probe_backend(timeout=6)
        elapsed = time.monotonic() - t0
    finally:
        _platform._probe_cache = cache_prev
        if env_prev is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = env_prev
    assert not ok and "exceeded" in detail
    assert elapsed < 60


def test_probe_skip_paths():
    from acg_tpu import _platform
    # plain-CPU platform: no probe needed (the in-process init is local)
    assert os.environ.get("JAX_PLATFORMS") == "cpu"
    assert not _platform.backend_probe_needed()
    # explicit opt-out wins regardless
    os.environ["ACG_TPU_SKIP_BACKEND_PROBE"] = "1"
    try:
        ok, detail = _platform.probe_backend()
        assert ok and "skipped" in detail
    finally:
        del os.environ["ACG_TPU_SKIP_BACKEND_PROBE"]


def test_dryrun_multichip_degrades_when_backend_unreachable():
    """The acceptance wedge: a cold parent with an unreachable backend
    must complete dryrun_multichip via the CPU-mesh child (rc=0) instead
    of hanging on jax.devices() (round-5 MULTICHIP ok=false)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # cold parent: platform undecided
    env[faults.ENV_VAR] = "backend:hang:secs=300"
    env["ACG_TPU_PROBE_TIMEOUT"] = "6"
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {ROOT!r}); "
         f"import __graft_entry__; __graft_entry__.dryrun_multichip(2)"],
        capture_output=True, text=True, timeout=540, env=env, cwd=ROOT)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "backend unreachable" in proc.stderr
    # the probe bounded the wait: parent-side stall is seconds, the rest
    # is the CPU-mesh child doing real (bounded) work
    assert elapsed < 480


# -- dead peer -> erragree abort ---------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_dead_peer_fault_trips_erragree_watchdog():
    """peer:dead:proc=1 kills controller 1 at its checkpoint; controller
    0's error-agreement watchdog must abort it within the timeout."""
    from acg_tpu.parallel.erragree import PEER_LOST_EXIT
    port = _free_port()
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import sys; sys.path.insert(0, {root!r}); "
            "from acg_tpu.parallel.multihost import initialize; "
            "initialize('localhost:{port}', 2, {pid}); "
            "jax.devices(); "
            "from acg_tpu.parallel.erragree import agree_status; "
            "rc = agree_status(0, what='ingest', timeout=8); "
            "raise SystemExit(rc)")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env[faults.ENV_VAR] = "peer:dead:proc=1"
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         code.format(root=ROOT, port=port, pid=pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=ROOT) for pid in range(2)]
    t0 = time.monotonic()
    outs = [p.communicate(timeout=120) for p in procs]
    elapsed = time.monotonic() - t0
    assert procs[1].returncode == 86          # the injected death
    assert procs[0].returncode != 0           # survivor aborts...
    assert elapsed < 60                       # ...within the timeout
    if procs[0].returncode == PEER_LOST_EXIT:
        assert ("timed out" in outs[0][1]
                or "peer controller died" in outs[0][1])
