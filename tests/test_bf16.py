"""bf16 storage tier: half-traffic solves with f32 scalars.

The reference is strictly f64 (``comm.h:180-183``); the bf16 tier is the
designed TPU deviation (SURVEY.md section 7 "hard parts", VERDICT round
2 item 1): matrix planes and vectors stored in bf16 (halving HBM/ICI
traffic -- the only lever past the v5e roofline), every scalar and every
accumulation in f32, and ``--refine`` recovering the accuracy the
storage rounding costs.  These tests pin the numerical contract of that
tier.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson2d_coo, poisson_dia
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import (DiaMatrix, device_matrix_from_csr, dia_mv,
                              spmv)
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.refine import RefinedSolver
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def problem():
    r, c, v, N = poisson2d_coo(48)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    rng = np.random.default_rng(0)
    xsol = rng.standard_normal(N)
    xsol /= np.linalg.norm(xsol)
    return csr, xsol, csr @ xsol


def test_poisson_planes_lossless_in_bf16():
    """The Poisson stencil values (-1, 4/6) are exactly representable in
    bf16, so plane storage itself rounds nothing."""
    planes, offsets, N = poisson_dia(16, dim=3)
    for p in planes:
        assert np.array_equal(np.asarray(p),
                              np.asarray(p).astype(np.float32)
                              .astype(jnp.bfloat16).astype(np.float32))


def test_bf16_spmv_accumulates_in_f32(problem):
    """SpMV over bf16 planes must accumulate in f32: the result then
    carries only the input rounding (~4e-3 relative), not the ~7x larger
    error of a bf16-accumulated sum of 5 products."""
    csr, xsol, _ = problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    assert isinstance(A, DiaMatrix)
    y = np.asarray(spmv(A, jnp.asarray(xsol, jnp.bfloat16)),
                   dtype=np.float64)
    y_ref = csr @ xsol
    rel = np.linalg.norm(y - y_ref) / np.linalg.norm(y_ref)
    # input rounding alone: |x - bf16(x)| <= 2^-9 |x|; the stencil
    # amplifies by ~kappa of one row (~8): budget 2e-2, but a bf16
    # accumulator would land ~5-10x higher
    assert rel < 2e-2


def test_bf16_matches_f32_at_loose_tolerance(problem):
    csr, xsol, b = problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, kernels="xla")
    x = s.solve(b, criteria=StoppingCriteria(maxits=400, residual_rtol=1e-2),
                raise_on_divergence=False)
    x = np.asarray(x, dtype=np.float64)
    rel = np.linalg.norm(b - csr @ x) / np.linalg.norm(b)
    assert s.stats.converged
    # the device-side test uses the f32-accumulated recurrence gamma;
    # the true residual may lag it by the bf16 storage noise floor
    assert rel < 5e-2


@pytest.mark.parametrize("pipelined", [False, True])
def test_bf16_scalars_are_f32(problem, pipelined):
    """The stats scalars must come out of the f32 scalar path: finite,
    and reproducing the true residual to f32-class (not bf16-class)
    relative error at convergence."""
    csr, xsol, b = problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, pipelined=pipelined, kernels="xla")
    x = s.solve(b, criteria=StoppingCriteria(maxits=60, residual_rtol=3e-2),
                raise_on_divergence=False)
    x = np.asarray(x, dtype=np.float64)
    true_r = float(np.linalg.norm(b - csr @ x))
    assert np.isfinite(s.stats.rnrm2)
    # the carried gamma tracks the recurrence residual; with f32 scalars
    # it stays within the bf16 storage noise of the true residual
    assert s.stats.rnrm2 == pytest.approx(true_r, rel=0.5)


def test_bf16_refine_recovers_accuracy(problem):
    """Outer f64 refinement over the bf16 inner solve reaches residuals
    far below the bf16 stall (~2e-2) -- the accuracy-recovery half of
    the tier's contract."""
    csr, xsol, b = problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    ref = RefinedSolver(JaxCGSolver(A, kernels="xla"), csr, inner_rtol=3e-2)
    x = ref.solve(b, criteria=StoppingCriteria(maxits=20000,
                                               residual_rtol=1e-5),
                  raise_on_divergence=False)
    rel = np.linalg.norm(b - csr @ x) / np.linalg.norm(b)
    assert rel < 1e-5
    assert ref.stats.nrefine >= 2


def test_mixed_tier_bitwise_equals_f32(problem):
    """--dtype mixed (bf16 planes + f32 vectors): for Poisson the plane
    values (-1, 4) are exactly representable in bf16 and the SpMV
    accumulates in f32, so the whole solve is ARITHMETIC-IDENTICAL to
    all-f32 -- at half the matrix HBM traffic.  Bitwise equality is the
    test."""
    csr, xsol, b = problem
    crit = StoppingCriteria(maxits=150)
    A16 = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    x_mixed = np.asarray(JaxCGSolver(A16, kernels="xla",
                                     vector_dtype=jnp.float32)
                         .solve(b, criteria=crit))
    A32 = device_matrix_from_csr(csr, dtype=jnp.float32)
    x_f32 = np.asarray(JaxCGSolver(A32, kernels="xla").solve(b, criteria=crit))
    assert np.array_equal(x_mixed, x_f32)


def test_mixed_tier_distributed(problem):
    """The distributed mixed tier (bf16 blocks + f32 vectors) solves to
    the same accuracy as distributed f32."""
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    csr, xsol, b = problem
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-6)
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.bfloat16,
                                    vector_dtype=jnp.float32)
    d = DistCGSolver(prob)
    x = d.solve(b, criteria=crit)
    assert d.stats.converged
    rel = np.linalg.norm(b - csr @ np.asarray(x, np.float64)) / np.linalg.norm(b)
    assert rel < 1e-5


def test_bf16_distributed_matches_single(problem):
    """The distributed bf16 program (f32 psum'd scalars, bf16 halo
    traffic) agrees with the single-device bf16 solve."""
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    csr, xsol, b = problem
    crit = StoppingCriteria(maxits=120, residual_rtol=1e-2)

    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, kernels="xla")
    x1 = np.asarray(s.solve(b, criteria=crit, raise_on_divergence=False),
                    dtype=np.float64)

    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.bfloat16)
    d = DistCGSolver(prob)
    x4 = d.solve(b, criteria=crit, raise_on_divergence=False)
    assert d.stats.converged
    rel1 = np.linalg.norm(b - csr @ x1) / np.linalg.norm(b)
    rel4 = np.linalg.norm(b - csr @ np.asarray(x4, np.float64)) / np.linalg.norm(b)
    # both land at the bf16 noise floor; iteration counts may differ by
    # a few (different reduction orders), the achieved residual must not
    assert rel4 < max(5e-2, 3 * rel1)


@pytest.fixture(scope="module")
def hard_problem():
    """2D Poisson n=128 (kappa ~ 6.6e3): far beyond the ~500 kappa limit
    where plain bf16 vector storage converges (BASELINE.md)."""
    r, c, v, N = poisson2d_coo(128)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    rng = np.random.default_rng(1)
    xsol = rng.standard_normal(N)
    xsol /= np.linalg.norm(xsol)
    return csr, xsol, csr @ xsol


def _true_rel_residual(csr, b, x):
    x = np.asarray(x, dtype=np.float64)
    return np.linalg.norm(b - csr @ x) / np.linalg.norm(b)


@pytest.mark.parametrize("restart", [True, False])
def test_replaced_bf16_sound_beyond_kappa_limit(hard_problem, restart):
    """Periodic f32 residual replacement (replace_every) makes the bf16
    tier converge where the plain tier stalls at its storage noise
    floor: the sound-bf16 contract (VERDICT round 3 item 4)."""
    csr, xsol, b = hard_problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    crit = StoppingCriteria(maxits=1500)

    plain = JaxCGSolver(A, kernels="xla")
    rel_plain = _true_rel_residual(
        csr, b, plain.solve(b, criteria=crit, raise_on_divergence=False))

    rr = JaxCGSolver(A, kernels="xla", replace_every=50,
                     replace_restart=restart)
    rel_rr = _true_rel_residual(
        csr, b, rr.solve(b, criteria=crit, raise_on_divergence=False))

    # the replaced tier must be *sound* (f32-class residual), not merely
    # better than the stalled plain tier (whose residual may be NaN --
    # outright divergence -- at this kappa)
    assert rel_rr < 1e-5
    assert np.isnan(rel_plain) or rel_rr < 0.1 * rel_plain


def test_replaced_reported_residual_is_true(hard_problem):
    """The convergence test and the reported rnrm2 come from the f32
    residual recompute, not the drifting bf16 recurrence -- so the
    reported residual must match the true one to f32 class."""
    csr, xsol, b = hard_problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, kernels="xla", replace_every=50)
    x = s.solve(b, criteria=StoppingCriteria(maxits=3000,
                                             residual_rtol=1e-5),
                raise_on_divergence=False)
    assert s.stats.converged
    true_r = np.linalg.norm(b - csr @ np.asarray(x, np.float64))
    assert abs(true_r - s.stats.rnrm2) <= 1e-5 * np.linalg.norm(b) + \
        1e-2 * true_r
    # converged within tolerance per the TRUE residual
    assert true_r <= 1.01 * 1e-5 * s.stats.r0nrm2
    # iteration count honors maxits quantized to whole segments
    assert s.stats.niterations <= 3000


def test_replaced_honors_maxits_exactly(problem):
    """maxits that is not a multiple of K still stops at maxits
    (the last segment runs short)."""
    csr, xsol, b = problem
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, kernels="xla", replace_every=64)
    s.solve(b, criteria=StoppingCriteria(maxits=130),
            raise_on_divergence=False)
    assert s.stats.niterations == 130


def test_replaced_validation():
    planes, offsets, N = poisson_dia(8, dim=2)
    A32 = DiaMatrix(data=tuple(jnp.asarray(p, jnp.float32) for p in planes),
                    offsets=offsets, nrows=N, ncols_padded=N)
    A16 = DiaMatrix(data=tuple(jnp.asarray(p, jnp.bfloat16) for p in planes),
                    offsets=offsets, nrows=N, ncols_padded=N)
    with pytest.raises(ValueError, match="bf16"):
        JaxCGSolver(A32, kernels="xla", replace_every=50)
    with pytest.raises(ValueError, match="classic"):
        JaxCGSolver(A16, kernels="xla", replace_every=50, pipelined=True)
    with pytest.raises(ValueError, match="precise"):
        JaxCGSolver(A16, kernels="xla", replace_every=50, precise_dots=True)
    with pytest.raises(ValueError, match="diff"):
        JaxCGSolver(A16, kernels="xla", replace_every=50).solve(
            np.ones(N), criteria=StoppingCriteria(maxits=10, diff_rtol=1e-3))


def test_replaced_bf16_distributed_sound(hard_problem):
    """The distributed replaced program (inner bf16 CG over the mesh +
    per-segment f32 replacement) reaches f32-class residuals at a kappa
    where plain distributed bf16 stalls, and agrees with the
    single-device replaced solver."""
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    csr, xsol, b = hard_problem
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.bfloat16)
    d = DistCGSolver(prob, replace_every=50)
    x = d.solve(b, criteria=StoppingCriteria(maxits=1500),
                raise_on_divergence=False)
    rel = _true_rel_residual(csr, b, x)
    assert rel < 1e-5

    plain = DistCGSolver(DistributedProblem.build(csr, part, 4,
                                                  dtype=jnp.bfloat16))
    rel_plain = _true_rel_residual(
        csr, b, plain.solve(b, criteria=StoppingCriteria(maxits=1500),
                            raise_on_divergence=False))
    assert np.isnan(rel_plain) or rel < 0.1 * rel_plain


def test_replaced_distributed_validation(problem):
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    csr, xsol, b = problem
    part = partition_rows(csr, 2, seed=0, method="band")
    prob32 = DistributedProblem.build(csr, part, 2, dtype=jnp.float32)
    with pytest.raises(ValueError, match="bf16"):
        DistCGSolver(prob32, replace_every=50)
    prob16 = DistributedProblem.build(csr, part, 2, dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="classic"):
        DistCGSolver(prob16, replace_every=50, pipelined=True)
