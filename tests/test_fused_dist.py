"""Distributed fused iteration (``kernels='fused'`` on the mesh tier):
interior|border overlapped SpMV with the halo exchange in flight.

ISSUE 13 acceptance: the fused tier is the builder's classic/pipelined
emission over ``make_dist_spmv_overlapped`` -- the halo puts are issued
first, the interior rows' SpMV runs while they are in flight, and the
border rows are finished after the receive side lands (the reference's
device-initiated interior/border split, ``cg-kernels-cuda.cu:713-899``).
The split is BITWISE equal to the unsplit SpMV per row, so the fused
programs' trajectories equal the unsplit ones exactly; the armed
collective counts are pinned at the HLO level and the disarmed
(``kernels='auto'``) program lowers byte-identical to the xla tier.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from acg_tpu._platform import shard_map as _shard_map
from acg_tpu.errors import AcgError
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import (DistCGSolver, DistributedProblem,
                                   interior_border_split, make_dist_spmv,
                                   make_dist_spmv_overlapped)
from acg_tpu.parallel.mesh import PARTS_AXIS
from acg_tpu.partition import partition_rows
from acg_tpu.solvers.stats import StoppingCriteria

NDEV = len(jax.devices())

pytestmark = pytest.mark.skipif(NDEV < 4, reason="needs a multi-device mesh")


def _problem(side=20, nparts=None, method="band", dtype=jnp.float64):
    r, c, v, N = poisson2d_coo(side)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    nparts = min(NDEV, 8) if nparts is None else nparts
    part = partition_rows(csr, nparts, seed=0, method=method)
    return csr, DistributedProblem.build(csr, part, nparts, dtype=dtype)


@pytest.fixture(scope="module")
def problem():
    return _problem()


def test_interior_border_split_partitions_owned_rows(problem):
    """Interior + border must partition each part's owned rows: the
    split is exhaustive and disjoint, with border == the stacked ghost
    block's coupled-row list."""
    _, prob = problem
    irows = interior_border_split(prob)
    brows = np.asarray(prob.ghost.rows)
    for p, s in enumerate(prob.subs):
        ir = irows[p][irows[p] < prob.nmax_owned]
        br = brows[p][brows[p] < prob.nmax_owned]
        assert np.intersect1d(ir, br).size == 0
        got = np.sort(np.concatenate([ir, br]))
        np.testing.assert_array_equal(got, np.arange(s.nowned))
        # every border row really couples to ghosts
        coupled = np.flatnonzero(np.diff(s.A_ghost.indptr))
        np.testing.assert_array_equal(br, coupled)


@pytest.mark.parametrize("comm", ["xla", "dma"])
def test_split_spmv_bitwise_equals_unsplit(problem, comm):
    """The acceptance pin: interior+border results are bitwise equal to
    the unsplit SpMV on the multi-part CPU mesh, for both transports."""
    csr, prob = problem
    interpret = True
    unsplit = make_dist_spmv(prob, comm, interpret)
    split = make_dist_spmv_overlapped(prob, comm, interpret)
    s = DistCGSolver(prob, kernels="fused", comm=comm)
    b, x0, la, ga4, sidx, gsrc, gval, scnt, rcnt = s.device_args(
        np.ones(prob.n))
    ga3 = ga4[:3]
    rng = np.random.default_rng(3)
    x = jax.device_put(
        prob.scatter(rng.standard_normal(prob.n)),
        jax.sharding.NamedSharding(s.mesh, P(PARTS_AXIS)))
    pspec = P(PARTS_AXIS)

    def body_unsplit(la, ga, sidx, gsrc, gval, scnt, rcnt, x):
        la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
        sidx, gsrc, gval, scnt, rcnt, x = (
            a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
        return unsplit(x, la, ga, sidx, gsrc, gval, scnt, rcnt)[None]

    def body_split(la, ga, sidx, gsrc, gval, scnt, rcnt, x):
        la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
        sidx, gsrc, gval, scnt, rcnt, x = (
            a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
        return split(x, la, ga, sidx, gsrc, gval, scnt, rcnt)[None]

    specs = (pspec,) * 8
    yu = jax.jit(_shard_map(body_unsplit, mesh=s.mesh, in_specs=specs,
                            out_specs=pspec))(
        la, ga3, sidx, gsrc, gval, scnt, rcnt, x)
    ys = jax.jit(_shard_map(body_split, mesh=s.mesh, in_specs=specs,
                            out_specs=pspec))(
        la, ga4, sidx, gsrc, gval, scnt, rcnt, x)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(yu))


@pytest.mark.parametrize("pipelined", [False, True])
def test_fused_solve_bitwise_matches_unsplit_tier(problem, pipelined):
    """classic AND pipelined ride the fused tier (the acceptance), and
    -- because the split SpMV is bitwise-equal and the builder bodies
    trace the same scalar ladder -- the whole solve trajectory equals
    the unsplit tier's exactly."""
    csr, prob = problem
    N = csr.shape[0]
    rng = np.random.default_rng(1)
    b = rng.standard_normal(N)
    crit = StoppingCriteria(maxits=200, residual_rtol=1e-9)
    ref = DistCGSolver(prob, pipelined=pipelined, kernels="xla")
    x_ref = ref.solve(b, criteria=crit)
    s = DistCGSolver(prob, pipelined=pipelined, kernels="fused")
    x = s.solve(b, criteria=crit)
    assert s.stats.converged and ref.stats.converged
    assert s.stats.niterations == ref.stats.niterations
    np.testing.assert_array_equal(x, x_ref)


def test_fused_dma_transport(problem):
    """The fused tier composes with the one-sided transport: same
    answer as fused/xla to transport rounding."""
    csr, prob = problem
    N = csr.shape[0]
    b = np.ones(N)
    crit = StoppingCriteria(maxits=200, residual_rtol=1e-8)
    xs = {}
    for comm in ("xla", "dma"):
        s = DistCGSolver(prob, kernels="fused", comm=comm)
        xs[comm] = s.solve(b, criteria=crit)
        assert s.stats.converged
    np.testing.assert_allclose(xs["dma"], xs["xla"], atol=1e-9)


def test_fused_scattered_partition_rides_ell(problem):
    """Scattered (graph) partitions stack ELL local blocks; the split
    SpMV's ELL gather form must agree bitwise with the unsplit tier."""
    csr, _ = _problem(side=16, nparts=min(NDEV, 4), method="graph")
    _, prob = _problem(side=16, nparts=min(NDEV, 4), method="graph")
    if prob.local.format == "dia":
        pytest.skip("partition stayed banded; ELL form not exercised")
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=150, residual_rtol=1e-9)
    ref = DistCGSolver(prob, kernels="xla").solve(b, criteria=crit)
    s = DistCGSolver(prob, kernels="fused")
    x = s.solve(b, criteria=crit)
    np.testing.assert_array_equal(x, ref)


# -- HLO pins --------------------------------------------------------------

def _counts(txt):
    return (len(re.findall(r"all_reduce", txt)),
            len(re.findall(r"all_to_all", txt)))


def test_fused_collective_counts_pinned(problem):
    """Armed collective inventory of the fused programs (the
    test_hlo_structure discipline): the overlapped split adds ZERO
    collectives -- classic keeps 5 all_reduces / 2 all_to_alls,
    pipelined 5 / 3 (identical to the unsplit tier: the overlap is a
    dependency restructuring, not extra traffic).  Under comm='dma'
    the halo leaves the all_to_all inventory entirely (the one-sided
    DMA path), allreduces unchanged."""
    _, prob = problem
    b = np.ones(prob.n)
    for pipelined, want in ((False, (5, 2)), (True, (5, 3))):
        s = DistCGSolver(prob, pipelined=pipelined, kernels="fused")
        assert _counts(s.lower_solve(b).as_text()) == want
        d = DistCGSolver(prob, pipelined=pipelined, kernels="fused",
                         comm="dma")
        ar, ata = _counts(d.lower_solve(b).as_text())
        assert ar == want[0] and ata == 0


def test_fused_disarmed_is_byte_identical(problem):
    """kernels='auto' must lower byte-identical HLO to a build that
    never mentions the fused tier (the disarmament contract): auto
    resolves to the xla program off-TPU, untouched by the fused
    plumbing."""
    _, prob = problem
    b = np.ones(prob.n)
    auto = DistCGSolver(prob, kernels="auto").lower_solve(b).as_text()
    xla = DistCGSolver(prob, kernels="xla").lower_solve(b).as_text()
    assert auto == xla
    fused = DistCGSolver(prob, kernels="fused").lower_solve(b).as_text()
    assert fused != xla


# -- composition refusals (the could-never-fire discipline) ---------------

def test_fused_refusals(problem):
    _, prob = problem
    from acg_tpu.checkpoint import CheckpointConfig
    from acg_tpu.health import make_spec
    from acg_tpu.solvers.resilience import RecoveryPolicy

    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", precise_dots=True)
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", precond="jacobi")
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", health=make_spec(every=4))
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused",
                     ckpt=CheckpointConfig(path="/tmp/_fused_ck",
                                           every=8))
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", algorithm="sstep:4")
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", recovery=RecoveryPolicy())
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused", trace=64)


def test_fused_refuses_diff_criteria_and_faults(problem):
    from acg_tpu import faults

    _, prob = problem
    s = DistCGSolver(prob, kernels="fused")
    with pytest.raises(ValueError, match="residual"):
        s.solve(np.ones(prob.n),
                criteria=StoppingCriteria(maxits=10, diff_atol=1e-3))
    faults.install(faults.parse_fault_spec("halo:nan@3"))
    try:
        with pytest.raises(AcgError, match="fused"):
            s.solve(np.ones(prob.n),
                    criteria=StoppingCriteria(maxits=10))
    finally:
        faults.install(None)


def test_fused_refuses_binnedell_local_blocks():
    """The length-binned stacked layout has no per-row gather form; the
    fused tier must say so at setup."""
    from acg_tpu.io.generators import irregular_spd_coo

    r, c, v, N = irregular_spd_coo(600, avg_degree=7.0, seed=0)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    nparts = min(NDEV, 4)
    part = partition_rows(csr, nparts, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, nparts,
                                    dtype=jnp.float32)
    if prob.local.format != "binnedell":
        pytest.skip("workload did not bin (plain ELL waste in bounds)")
    with pytest.raises(ValueError, match="fused"):
        DistCGSolver(prob, kernels="fused")


# -- ledger + explain overlap model ---------------------------------------

def test_fused_comm_profile_declares_overlap(problem):
    _, prob = problem
    s = DistCGSolver(prob, kernels="fused")
    led = s.comm_profile()
    ov = led["overlap"]
    assert ov["split"] == "interior|border"
    assert ov["interior_rows"] > 0 and ov["border_rows"] > 0
    assert 0 < ov["interior_nnz"] < prob.nnz_total
    assert ov["interior_matrix_bytes"] > 0
    # the unsplit tier declares no overlap
    assert "overlap" not in DistCGSolver(prob).comm_profile()


def test_predicted_overlap_seconds_model():
    """The --explain comm verdict's overlap pricing: exposed halo
    seconds = max(0, halo - interior SpMV), hidden_frac comparable to
    the measured overlap-efficiency score."""
    from acg_tpu.perfmodel import predicted_overlap_seconds

    led = {"halo_bytes_per_iteration": 90_000,
           "overlap": {"interior_matrix_bytes": 450_000}}
    # 90 kB halo at 45 GB/s = 2e-6 s; 450 kB interior at 100 GB/s =
    # 4.5e-6 s -> fully hidden
    ov = predicted_overlap_seconds(led, bw_gbs=100.0, ici_gbs=45.0)
    assert ov["exposed_halo_s"] == 0.0
    assert ov["hidden_frac"] == 1.0
    # starve the interior work -> partially exposed
    led["overlap"]["interior_matrix_bytes"] = 100_000
    ov = predicted_overlap_seconds(led, bw_gbs=100.0, ici_gbs=45.0)
    assert 0 < ov["exposed_halo_s"] < ov["halo_s"]
    assert 0 < ov["hidden_frac"] < 1
    assert predicted_overlap_seconds(led, None, 45.0) is None


def test_fused_single_part_runs_plain(problem):
    """nparts=1 (no halo at all): the fused tier still dispatches (the
    plain-jit bypass) and matches the xla tier bitwise."""
    csr, _ = _problem(side=12, nparts=1)
    _, prob = _problem(side=12, nparts=1)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=100, residual_rtol=1e-9)
    x_ref = DistCGSolver(prob, kernels="xla").solve(b, criteria=crit)
    x = DistCGSolver(prob, kernels="fused").solve(b, criteria=crit)
    np.testing.assert_array_equal(x, x_ref)


# -- multi-controller dma downgrade (the capability-probe satellite) ------

def test_dma_multicontroller_downgrade(problem, monkeypatch):
    """Multi-controller comm='dma' no longer hard-refuses: the
    capability probe downgrades to the xla transport with a recorded
    self-describing event."""
    from acg_tpu.parallel import dist as dist_mod
    from acg_tpu.parallel import halo_dma

    from acg_tpu.parallel.mesh import solve_mesh

    _, prob = problem
    mesh = solve_mesh(prob.nparts)   # built BEFORE the patched topology
    monkeypatch.setattr(dist_mod.jax, "process_count", lambda: 2)
    monkeypatch.setattr(halo_dma, "_dma_status",
                        (False, "probe says no (test)"))
    s = DistCGSolver(prob, comm="dma", mesh=mesh)
    assert s.comm == "xla"
    assert "probe says no" in s._comm_downgrade
    # single-controller arming is untouched: no downgrade, no caveat
    monkeypatch.setattr(dist_mod.jax, "process_count", lambda: 1)
    s1 = DistCGSolver(prob, comm="dma", mesh=mesh)
    assert s1.comm == "dma" and s1._comm_downgrade is None


def test_dma_transport_status_single_controller():
    from acg_tpu.parallel.halo_dma import dma_transport_status

    ok, why = dma_transport_status(refresh=True)
    assert ok and why == ""
