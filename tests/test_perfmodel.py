"""The compiled-program performance-observability layer (acg_tpu/
perfmodel.py): XLA cost/memory introspection against the analytic
counters, the static communication ledger, the --explain CLI tier, and
the bench regression gate.

The cross-check test is the PR's central promise: the analytic flop/byte
counters (stats.cg_flops_per_iteration, bench._our_bytes_per_iter) can
no longer drift silently -- they are pinned against the compiler's own
HloCostAnalysis of the exact solve program, within a documented
tolerance band."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import perfmodel
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr, spmv_flops
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria, cg_flops_per_iteration

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(24)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


# -- analytic counters vs the compiler's cost analysis -------------------

# Documented tolerance band for the cross-check (see
# perfmodel.per_iteration_cost): the counting CONVENTIONS differ by
# design -- the analytic model bills 3 flops per stored nonzero (the
# reference's convention, symmetric entries twice) where XLA bills 2 per
# multiply-add over PADDED DIA/ELL plane elements, and the analytic
# bytes model is a fixed pass count where XLA's is fusion-aware.
# Measured on this backend: flops ratio ~0.78 (classic) / ~0.89
# (pipelined), bytes ratio ~1.6.  The band catches silent DRIFT (wrong
# pass counts, dropped terms, double billing -- all order-of-magnitude
# or factor-several errors) without chasing convention gaps.
FLOPS_BAND = (0.35, 2.5)
BYTES_BAND = (0.25, 4.0)


@pytest.mark.parametrize("pipelined", [False, True])
def test_analytic_counters_cross_check(csr, pipelined):
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    s = JaxCGSolver(A, pipelined=pipelined, kernels="xla")
    b = np.ones(csr.shape[0], np.float32)
    per = perfmodel.per_iteration_cost(s, b)
    if per is None:
        pytest.skip("cost_analysis unsupported on this jax/backend")
    n = csr.shape[0]
    analytic_flops = cg_flops_per_iteration(spmv_flops(A) / 3.0, n,
                                            pipelined)
    ratio_f = per["flops"] / analytic_flops
    assert FLOPS_BAND[0] < ratio_f < FLOPS_BAND[1], (
        f"analytic flop counter drifted from the compiler's: "
        f"ratio {ratio_f:.3f} outside {FLOPS_BAND}")
    from acg_tpu.ops.spmv import matrix_index_bytes
    analytic_bytes = bench._our_bytes_per_iter(
        csr.nnz, n, matrix_index_bytes(A), 4, 4, pipelined)
    ratio_b = per["bytes_accessed"] / analytic_bytes
    assert BYTES_BAND[0] < ratio_b < BYTES_BAND[1], (
        f"analytic byte counter drifted from the compiler's: "
        f"ratio {ratio_b:.3f} outside {BYTES_BAND}")


def test_analyze_solver_memory(csr):
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    s = JaxCGSolver(A, kernels="xla")
    b = np.ones(csr.shape[0], np.float32)
    an = perfmodel.analyze_solver(s, b)
    if not an.get("available"):
        pytest.skip(an.get("why", "analysis unavailable"))
    mem = an.get("memory")
    if mem is None:
        pytest.skip("memory_analysis unsupported on this backend")
    # the arguments include the DIA planes (5 x N f32) and b/x0
    assert mem["argument_bytes"] >= 5 * csr.shape[0] * 4
    assert mem["total_hbm_bytes"] >= mem["argument_bytes"]


def test_attach_and_stats_twin(csr):
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    s = JaxCGSolver(A, kernels="xla")
    b = np.ones(csr.shape[0], np.float32)
    an = perfmodel.analyze_solver(s, b)
    perfmodel.attach(s.stats, an, ledger={"halo_bytes_per_iteration": 0},
                     per_iteration={"flops": 1.0})
    d = s.stats.to_dict()
    assert "costmodel" in d and "memory" in d
    assert d["costmodel"]["per_iteration"]["flops"] == 1.0
    assert d["costmodel"]["comm"]["halo_bytes_per_iteration"] == 0
    txt = s.stats.fwrite()
    assert "costmodel:" in txt
    if an.get("available") and an.get("memory"):
        assert "memory:" in txt


def test_analyze_unavailable_degrades():
    """A solver whose lowering fails reports why instead of raising --
    the graceful-degradation contract."""
    class Broken:
        def lower_solve(self, b, x0=None, criteria=None):
            raise RuntimeError("no backend here")

    an = perfmodel.analyze_solver(Broken(), np.ones(4))
    assert an["available"] is False
    assert "no backend here" in an["why"]


# -- communication ledger -------------------------------------------------

def test_comm_ledger_dist(csr):
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    s = DistCGSolver(prob)
    led = perfmodel.comm_ledger(s)
    # per-iteration halo payload = total send entries x itemsize, the
    # same quantity the halo op-class counter bills per exchange
    expect = sum(int(x.halo.total_send) for x in prob.subs
                 if x.halo is not None) * 8
    assert led["halo_bytes_per_iteration"] == expect
    assert led["halo_exchanges_per_iteration"] == 1
    assert led["allreduce_per_iteration"] == 2  # classic: (p,t) and (r,r)
    assert led["allreduce_scalars"] == 1
    assert led["max_hops"] >= 1
    assert led["nparts"] == 4
    # band partition of a banded matrix: only adjacent neighbours
    assert all(nb["hops"] == 1 for nb in led["neighbors"])
    # the communication-avoiding property, in the ledger: pipelined
    # fuses both scalars into ONE psum; compensated dots double the
    # payload (hi+lo pairs) without adding reductions
    sp = DistCGSolver(prob, pipelined=True, precise_dots=True)
    ledp = perfmodel.comm_ledger(sp)
    assert ledp["allreduce_per_iteration"] == 1
    assert ledp["allreduce_scalars"] == 4


def test_comm_ledger_sharded_roll():
    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    s = build_sharded_poisson_solver(8, 2, nparts=4)
    led = perfmodel.comm_ledger(s)
    # derived halo: offsets +-1, +-8 -> 18 boundary elements per shard,
    # f32
    assert led["halo_bytes_per_shard"] == 18 * 4
    assert led["halo_bytes_per_iteration"] == 18 * 4 * 4
    assert led["transport"].startswith("xla-roll")
    assert led["max_hops"] == 1
    # each nonzero offset's roll is its own boundary collective-permute
    assert led["halo_exchanges_per_iteration"] == 4


def test_comm_ledger_absent_on_single_device(csr):
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    assert perfmodel.comm_ledger(JaxCGSolver(A)) is None


# -- bench regression gate ------------------------------------------------

def _stats_doc(metric, niter, tsolve, **manifest):
    return {"schema": "acg-tpu-stats/2",
            "manifest": {"metric": metric, **manifest},
            "stats": {"niterations": niter, "tsolve": tsolve}}


def test_load_cases_stats_jsonl(tmp_path):
    p = tmp_path / "stats.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_stats_doc("m1", 1000, 1.0)) + "\n")
        f.write(json.dumps(_stats_doc("m1", 1000, 2.0)) + "\n")  # slower dup
        f.write(json.dumps(_stats_doc("m2", 500, 1.0)) + "\n")
        f.write("# a comment line bench interleaves\n")
    cases = perfmodel.load_cases(p)
    assert cases == {"m1": 1000.0, "m2": 500.0}  # best-of per metric


def test_load_cases_single_document(tmp_path):
    """The CLI's --stats-json writes ONE indented document; the case key
    falls back to solver:matrix."""
    p = tmp_path / "stats.json"
    doc = {"schema": "acg-tpu-stats/2",
           "manifest": {"solver": "acg", "matrix": "gen:poisson2d:24"},
           "stats": {"niterations": 30, "tsolve": 0.5}}
    p.write_text(json.dumps(doc, indent=2))
    cases = perfmodel.load_cases(p)
    assert cases == {"acg:gen:poisson2d:24": 60.0}


def test_load_cases_bench_rows(tmp_path):
    p = tmp_path / "BENCH.json"
    p.write_text('{"metric": "m1", "value": 123.0, "unit": "iters/s"}\n'
                 '# setup: commentary\n'
                 '{"metric": "m2", "value": 7.5}\n')
    assert perfmodel.load_cases(p) == {"m1": 123.0, "m2": 7.5}
    # the growth driver's BENCH_r0N.json wrapper: the row under "parsed"
    w = tmp_path / "BENCH_r0X.json"
    w.write_text(json.dumps({"n": 4, "cmd": "python bench.py", "rc": 0,
                             "parsed": {"metric": "m1", "value": 99.0}}))
    assert perfmodel.load_cases(w) == {"m1": 99.0}


def test_compare_cases_regression_and_tolerance():
    old = {"a": 100.0, "b": 100.0, "gone": 5.0}
    new = {"a": 95.0, "b": 80.0, "fresh": 1.0}
    lines, nreg, ncmp = perfmodel.compare_cases(old, new, 10.0)
    assert ncmp == 2
    assert nreg == 1  # b fell 20% > 10%; a fell 5% (tolerated)
    joined = "\n".join(lines)
    assert "REGRESSION" in joined
    assert "baseline-only" in joined and "new case" in joined


def test_check_regression_exit_codes(tmp_path):
    base = tmp_path / "base.jsonl"
    with open(base, "w") as f:
        f.write(json.dumps(_stats_doc("cg_case", 1000, 1.0)) + "\n")
    # synthetically slowed case (2x): gate fires
    slowed = [{"metric": "cg_case", "value": 500.0}]
    assert perfmodel.check_regression(slowed, base, 10.0) == 1
    # improved: clean pass
    faster = [{"metric": "cg_case", "value": 1500.0}]
    assert perfmodel.check_regression(faster, base, 10.0) == 0
    # nothing comparable (renamed metric): own failure code
    renamed = [{"metric": "other_case", "value": 500.0}]
    assert perfmodel.check_regression(renamed, base, 10.0) == 2
    # unreadable baseline
    assert perfmodel.check_regression(slowed, tmp_path / "nope", 10.0) == 2


def test_bench_baseline_gate(tmp_path):
    """The acceptance shape: bench.py --baseline <prior stats-json>
    --fail-on-regress 10 exits nonzero on a synthetically slowed case
    (bench._finish is the exact code path main() funnels through)."""
    import argparse

    base = tmp_path / "prior_stats.jsonl"
    with open(base, "w") as f:
        f.write(json.dumps(_stats_doc(
            "cg_iters_per_sec_poisson2d_n2048_f32", 1000, 0.2,
            dtype="f32", kernels="xla")) + "\n")
    args = argparse.Namespace(baseline=str(base), fail_on_regress=10.0)
    slowed_row = {"metric": "cg_iters_per_sec_poisson2d_n2048_f32",
                  "value": 2500.0, "unit": "iters/s"}  # 5000 -> 2500
    assert bench._finish(args, [slowed_row], 0) != 0
    ok_row = dict(slowed_row, value=4990.0)  # -0.2%: inside tolerance
    assert bench._finish(args, [ok_row], 0) == 0
    # no baseline flag: gate disarmed
    args_off = argparse.Namespace(baseline=None, fail_on_regress=10.0)
    assert bench._finish(args_off, [slowed_row], 0) == 0


# -- scripts/bench_diff.py CLI -------------------------------------------

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "bench_diff.py")


def test_bench_diff_help_without_backend():
    """--help must answer fast with no jax import (the CI smoke)."""
    r = subprocess.run([sys.executable, _SCRIPT, "--help"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "fail-on-regress" in r.stdout


def test_bench_diff_cli(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text('{"metric": "m1", "value": 100.0}\n'
                   '{"metric": "m2", "value": 50.0}\n')
    new.write_text('{"metric": "m1", "value": 120.0}\n'
                   '{"metric": "m2", "value": 30.0}\n')
    r = subprocess.run(
        [sys.executable, _SCRIPT, str(old), str(new),
         "--fail-on-regress", "10"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    # same capture twice: clean exit
    r2 = subprocess.run([sys.executable, _SCRIPT, str(old), str(old)],
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    # disjoint metrics: exit 2 (nothing comparable must not green a gate)
    other = tmp_path / "other.json"
    other.write_text('{"metric": "zz", "value": 1.0}\n')
    r3 = subprocess.run([sys.executable, _SCRIPT, str(old), str(other)],
                        capture_output=True, text=True, timeout=120)
    assert r3.returncode == 2


# -- the --explain CLI tier ----------------------------------------------

def test_cli_explain_end_to_end(tmp_path):
    """Acceptance: --explain on a generated Poisson system prints, for
    the classic + pipelined single-chip tiers and one distributed tier,
    compiler-reported bytes/flops (or the documented degradation), HBM
    footprint, comm-ledger bytes, predicted vs measured iteration time,
    and a bound classification."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    sj = tmp_path / "explain_stats.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson2d:16",
         "--explain", "--dtype", "f32", "--max-iterations", "20",
         "--warmup", "0", "--stats-json", str(sj), "-q"],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr
    err = r.stderr
    for tier in ("== explain: cg ", "== explain: cg-pipelined",
                 "== explain: dist-cg"):
        assert tier in err, err
    assert "costmodel:" in err
    assert ("memory (HBM footprint):" in err
            or "analysis unavailable" in err)
    assert "comm ledger: halo" in err       # distributed tier's bytes
    assert "predicted" in err and "measured" in err
    assert "verdict: " in err and "-bound" in err
    # the structured twin carries the new schema keys per tier
    docs = [json.loads(line) for line in sj.read_text().splitlines()
            if line.strip()]
    assert len(docs) == 3
    assert all("costmodel" in d["stats"] for d in docs)
    dist_docs = [d for d in docs
                 if "dist-cg" in d["manifest"]["metric"]]
    assert dist_docs and "comm" in dist_docs[0]["stats"]["costmodel"]
