"""Pallas remote-DMA halo transport (--comm dma) vs the XLA collective
transport, on the virtual CPU mesh (interpret mode).

The reference validates its NVSHMEM transport by running the same solve
with --comm mpi|nccl|nvshmem and comparing (scripts/*_combined.sh); these
tests do the same for xla vs dma.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.parallel.halo_dma import _exchange
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.partition import partition_rows
from acg_tpu.solvers.stats import StoppingCriteria
from acg_tpu._platform import shard_map as _shard_map

NDEV = len(jax.devices())

pytestmark = pytest.mark.skipif(NDEV < 4, reason="needs a multi-device mesh")


def test_exchange_routes_all_pairs():
    """recvbuf[p, q] must equal sendbuf[q, p] for every pair."""
    nparts, maxcnt = 4, 3
    sb = np.zeros((nparts, nparts, maxcnt), np.float32)
    for p in range(nparts):
        for q in range(nparts):
            sb[p, q] = 100 * p + 10 * q + np.arange(maxcnt)
    scnt = jnp.full((nparts, nparts), maxcnt, jnp.int32)
    mesh = solve_mesh(nparts)
    pspec = P(PARTS_AXIS)

    def body(sbuf, sc, rc):
        return _exchange(sbuf[0], sc[0], rc[0], PARTS_AXIS, True)[None]

    f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(pspec,) * 3,
                              out_specs=pspec))
    out = np.asarray(f(jnp.asarray(sb), scnt, scnt))
    for p in range(nparts):
        for q in range(nparts):
            if q == p:
                continue
            np.testing.assert_allclose(out[p, q],
                                       100 * q + 10 * p + np.arange(maxcnt))


def test_exchange_count_gating_ring():
    """Count-gated puts on a ring neighbour structure (gate pattern
    globally uniform per rotation round, so interpret mode can run it):
    only real neighbours' rows arrive; the rest stay unwritten."""
    nparts, maxcnt = 4, 3
    sb = np.zeros((nparts, nparts, maxcnt), np.float32)
    for p in range(nparts):
        for q in range(nparts):
            sb[p, q] = 100 * p + 10 * q + np.arange(maxcnt)
    scnt = np.zeros((nparts, nparts), np.int32)
    for p in range(nparts):
        scnt[p, (p + 1) % nparts] = maxcnt
        scnt[p, (p - 1) % nparts] = maxcnt
    rcnt = scnt.T.copy()
    mesh = solve_mesh(nparts)
    pspec = P(PARTS_AXIS)

    def body(sbuf, sc, rc):
        return _exchange(sbuf[0], sc[0], rc[0], PARTS_AXIS, True,
                         gate_by_counts=True)[None]

    f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(pspec,) * 3,
                              out_specs=pspec))
    out = np.asarray(f(jnp.asarray(sb), jnp.asarray(scnt),
                       jnp.asarray(rcnt)))
    for p in range(nparts):
        for q in range(nparts):
            if scnt[q, p] > 0:
                np.testing.assert_allclose(
                    out[p, q], 100 * q + 10 * p + np.arange(maxcnt))


@pytest.fixture(scope="module")
def small_problem():
    r, c, v, N = poisson2d_coo(20)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    nparts = min(NDEV, 8)
    part = partition_rows(csr, nparts, seed=0)
    prob = DistributedProblem.build(csr, part, nparts, dtype=jnp.float32)
    return csr, prob


def test_dma_matches_xla_transport(small_problem):
    csr, prob = small_problem
    N = csr.shape[0]
    rng = np.random.default_rng(1)
    xsol = rng.standard_normal(N).astype(np.float32)
    xsol /= np.linalg.norm(xsol)
    b = (csr @ xsol).astype(np.float32)
    crit = StoppingCriteria(maxits=60, residual_rtol=1e-4)
    xs = {}
    for comm in ("xla", "dma"):
        solver = DistCGSolver(prob, comm=comm)
        xs[comm] = solver.solve(b, criteria=crit)
        assert solver.stats.converged
    # same algorithm, same data, different transport: identical to f32
    # rounding noise
    np.testing.assert_allclose(xs["dma"], xs["xla"], atol=1e-5)


def test_dma_pipelined(small_problem):
    csr, prob = small_problem
    N = csr.shape[0]
    b = np.ones(N, np.float32)
    solver = DistCGSolver(prob, comm="dma", pipelined=True)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=40))
    assert np.isfinite(x).all()
    assert solver.stats.niterations == 40


def test_dma_rejects_unknown_comm(small_problem):
    _, prob = small_problem
    with pytest.raises(ValueError):
        DistCGSolver(prob, comm="nvshmem")


def test_exchange_count_gating_distance2():
    """Count-gated puts with a two-ring neighbour structure (distances 1
    and 2, both directions -- gates uniform per rotation round, so
    interpret mode can execute the gated kernel): multiple gated
    neighbours per shard exercise the multi-round gating arithmetic the
    single-ring test cannot."""
    nparts, maxcnt = min(NDEV, 8), 3
    sb = np.zeros((nparts, nparts, maxcnt), np.float32)
    for p in range(nparts):
        for q in range(nparts):
            sb[p, q] = 100 * p + 10 * q + np.arange(maxcnt)
    scnt = np.zeros((nparts, nparts), np.int32)
    for p in range(nparts):
        for d in (1, 2):
            scnt[p, (p + d) % nparts] = maxcnt
            scnt[p, (p - d) % nparts] = maxcnt
    rcnt = scnt.T.copy()
    mesh = solve_mesh(nparts)
    pspec = P(PARTS_AXIS)

    def body(sbuf, sc, rc):
        return _exchange(sbuf[0], sc[0], rc[0], PARTS_AXIS, True,
                         gate_by_counts=True)[None]

    f = jax.jit(_shard_map(body, mesh=mesh, in_specs=(pspec,) * 3,
                              out_specs=pspec))
    out = np.asarray(f(jnp.asarray(sb), jnp.asarray(scnt),
                       jnp.asarray(rcnt)))
    for p in range(nparts):
        for q in range(nparts):
            if scnt[q, p] > 0:
                np.testing.assert_allclose(
                    out[p, q], 100 * q + 10 * p + np.arange(maxcnt))


def _topology_partition(csr, kind, nparts, side):
    """Partition vectors with qualitatively different neighbour graphs."""
    n = csr.shape[0]
    if kind == "line":
        # chain of bands: each part talks to at most 2 neighbours
        from acg_tpu.partition import partition_rows_band
        return partition_rows_band(csr, nparts)
    if kind == "star":
        # hub-and-spokes: part 0 is a central patch touching every other
        part = np.zeros((side, side), np.int32)
        c0, c1 = side // 4, 3 * side // 4
        # spokes: quadrants
        part[: side // 2, : side // 2] = 1
        part[: side // 2, side // 2:] = 2
        part[side // 2:, : side // 2] = 3
        part[side // 2:, side // 2:] = min(4, nparts - 1)
        part[c0:c1, c0:c1] = 0  # hub overwrites the centre
        return part.reshape(-1) % nparts
    if kind == "clustered":
        # random scatter: dense neighbour graph, ragged window sizes
        return np.random.default_rng(0).integers(0, nparts, n).astype(np.int32)
    raise ValueError(kind)


def test_halo_exchange_dma_parity_8part(small_problem):
    """Interpret-mode parity of the TRANSPORT itself (not a whole
    solve): the ghost vector halo_exchange_dma delivers on the 8-part
    CPU mesh equals the xla all_to_all transport's, slot for slot
    (scripts/dma_probe.py promoted from a dated one-off note into CI).
    The dma unpack masks padding ghost slots (ghost_valid); the xla
    unpack reads zero-filled receive rows there, so both sides are
    comparable everywhere."""
    from acg_tpu.parallel.dist import DistCGSolver
    from acg_tpu.parallel.halo import halo_exchange
    from acg_tpu.parallel.halo_dma import halo_exchange_dma

    csr, prob = small_problem
    s = DistCGSolver(prob, comm="xla")
    b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = s.device_args(
        np.ones(prob.n))
    rng = np.random.default_rng(11)
    x = jax.device_put(
        prob.scatter(rng.standard_normal(prob.n).astype(np.float32)),
        jax.sharding.NamedSharding(s.mesh, P(PARTS_AXIS)))
    pspec = P(PARTS_AXIS)

    def body(sidx, gsrc, gval, scnt, rcnt, x):
        sidx, gsrc, gval, scnt, rcnt, x = (
            a[0] for a in (sidx, gsrc, gval, scnt, rcnt, x))
        g_dma = halo_exchange_dma(x, sidx, gsrc, gval, scnt, rcnt,
                                  PARTS_AXIS, interpret=True)
        g_xla = halo_exchange(x, sidx, gsrc, PARTS_AXIS)
        # mask the xla side like the dma unpack: padding slots beyond a
        # part's real ghost count are never consumed by the SpMV
        g_xla = jnp.where(gval, g_xla, 0)
        return g_dma[None], g_xla[None]

    f = jax.jit(_shard_map(body, mesh=s.mesh, in_specs=(pspec,) * 6,
                           out_specs=(pspec, pspec)))
    g_dma, g_xla = f(sidx, gsrc, gval, scnt, rcnt, x)
    np.testing.assert_array_equal(np.asarray(g_dma), np.asarray(g_xla))


def test_dma_to_xla_fallback_under_halo_fault(small_problem, monkeypatch):
    """The recovery ladder's transport rung under ``halo:`` fault
    injection: a breakdown that RECURS on the dma transport (a faulty
    one-sided link keeps corrupting payloads, so the first restart does
    not cure it) makes the driver retire dma for the xla collectives --
    its own rung, not billed to the restart budget -- and the solve
    converges there.  The injector's one-shot ``shift`` is patched to
    keep the fault armed exactly while the solver is still on dma: the
    persistent-transport-fault scenario the rung exists for."""
    from acg_tpu import faults
    from acg_tpu.parallel.dist import DistCGSolver
    from acg_tpu.solvers.resilience import RecoveryPolicy

    csr, prob = small_problem
    N = csr.shape[0]
    b = np.ones(N, np.float32)
    pol = RecoveryPolicy(max_restarts=3, fallback_comm=True,
                         fallback_host=False)
    solver = DistCGSolver(prob, comm="dma", recovery=pol)
    orig_shift = faults.FaultSpec.shift

    def shift_persistent_while_dma(spec, consumed):
        if solver.comm == "dma":
            return spec           # the faulty link keeps corrupting
        return orig_shift(spec, consumed)

    monkeypatch.setattr(faults.FaultSpec, "shift",
                        shift_persistent_while_dma)
    faults.install(faults.parse_fault_spec("halo:nan@5"))
    try:
        x = solver.solve(b, criteria=StoppingCriteria(
            maxits=200, residual_rtol=1e-4))
    finally:
        faults.install(None)
    st = solver.stats
    assert st.converged
    assert solver.comm == "xla", "dma transport was not retired"
    assert st.nfallbacks >= 1
    assert "dma -> xla" in st.fwrite()
    assert np.isfinite(x).all()


@pytest.mark.parametrize("kind", ["line", "star", "clustered"])
def test_dma_matches_xla_topologies(kind):
    """xla-vs-dma agreement across qualitatively different partition
    topologies (star/line/clustered): same solve, different transport,
    same answer.  The reference's mpi/nccl/nvshmem cross-validation
    (scripts/*_combined.sh) for varied communication patterns."""
    side = 24
    r, c, v, N = poisson2d_coo(side)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    nparts = min(NDEV, 5)
    part = _topology_partition(csr, kind, nparts, side)
    nparts = int(part.max()) + 1
    prob = DistributedProblem.build(csr, part, nparts, dtype=jnp.float64)
    rng = np.random.default_rng(7)
    xsol = rng.standard_normal(N)
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=300, residual_rtol=1e-8)
    xs = {}
    for comm in ("xla", "dma"):
        solver = DistCGSolver(prob, comm=comm)
        xs[comm] = solver.solve(b, criteria=crit)
        assert solver.stats.converged
    np.testing.assert_allclose(xs["dma"], xs["xla"], atol=1e-9)
