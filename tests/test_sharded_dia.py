"""Sharded on-device stencil assembly + solve (parallel/sharded_dia).

The north-star route (VERDICT round 2 item 2): per-shard on-device DIA
assembly, halo exchange DERIVED by the SPMD partitioner from the
cyclic-shift SpMV, same code path single-chip / multi-chip /
multi-controller.  Tests pin correctness against scipy, agreement with
the unsharded solver, the compiled communication structure (neighbour
collective-permutes, no all-gathers), and the 2-process CLI run.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from acg_tpu.io.generators import poisson2d_coo, poisson3d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import DiaMatrix, dia_mv_roll, device_matrix_from_csr
from acg_tpu.parallel.mesh import solve_mesh
from acg_tpu.parallel.sharded_dia import (build_sharded_poisson_solver,
                                          sharded_poisson_dia)
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


def _csr(n, dim):
    gen = poisson2d_coo if dim == 2 else poisson3d_coo
    r, c, v, N = gen(n)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


@pytest.mark.parametrize("dim,n", [(2, 32), (3, 16)])
def test_sharded_spmv_matches_scipy(dim, n):
    mesh = solve_mesh(8)
    planes, offsets, N = sharded_poisson_dia(n, dim, mesh)
    x = np.random.default_rng(0).standard_normal(N).astype(np.float32)
    xs = jax.device_put(x, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("parts")))
    y = np.asarray(jax.jit(
        lambda p, v: dia_mv_roll(p, offsets, v))(planes, xs), np.float64)
    y_ref = _csr(n, dim) @ x.astype(np.float64)
    assert np.linalg.norm(y - y_ref) <= 1e-5 * np.linalg.norm(y_ref)


def test_sharded_solve_matches_unsharded():
    """The 8-way sharded solve and the single-device solve run the same
    recurrences; iteration counts and solutions must agree closely."""
    n, dim = 24, 2
    csr = _csr(n, dim)
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-6)
    solver = build_sharded_poisson_solver(n, dim, nparts=8)
    b = solver.ones_b()
    x = np.asarray(solver.solve(b, criteria=crit, host_result=False),
                   np.float64)
    k_sharded = solver.stats.niterations

    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    ref = JaxCGSolver(A, kernels="xla")
    x1 = np.asarray(ref.solve(np.ones(csr.shape[0], np.float32),
                              criteria=crit), np.float64)
    # iteration counts only agree loosely: near the f32 recurrence-vs-
    # true-residual drift the crossing point shifts with reduction order
    # (measured: trajectories track to <20% at every checkpoint).  The
    # hard invariants are convergence and solution agreement.
    assert solver.stats.converged and ref.stats.converged
    assert abs(k_sharded - ref.stats.niterations) <= 0.3 * ref.stats.niterations
    bnrm = np.linalg.norm(np.ones(csr.shape[0]))
    assert np.linalg.norm(x - x1) <= 1e-4 * bnrm


def test_sharded_hlo_has_permutes_not_gathers():
    """The compiled sharded SpMV must exchange halos via
    collective-permute (the derived neighbour exchange) and must NOT
    all-gather the vector -- the property that makes the route scale."""
    mesh = solve_mesh(8)
    planes, offsets, N = sharded_poisson_dia(16, 3, mesh)
    sh = jax.sharding.NamedSharding(mesh,
                                    jax.sharding.PartitionSpec("parts"))
    x = jax.device_put(np.ones(N, np.float32), sh)
    f = jax.jit(lambda p, v: dia_mv_roll(p, offsets, v))
    hlo = f.lower(planes, x).compile().as_text()
    assert re.search(r"collective-permute", hlo)
    assert not re.search(r"all-gather", hlo)


def test_sharded_manufactured_b_matches_scipy():
    n, dim = 16, 3
    solver = build_sharded_poisson_solver(n, dim, nparts=8)
    xsol, b = solver.manufactured(seed=7)
    xs = np.asarray(xsol, np.float64)
    np.testing.assert_allclose(np.asarray(b, np.float64),
                               _csr(n, dim) @ xs, atol=1e-5)
    assert np.linalg.norm(xs) == pytest.approx(1.0, abs=1e-5)


def test_sharded_mixed_dtype():
    """The mixed tier (bf16 planes + f32 vectors) on the sharded route
    matches the all-f32 sharded solve bitwise (Poisson planes are
    bf16-exact)."""
    n, dim = 24, 2
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-6)
    s32 = build_sharded_poisson_solver(n, dim, nparts=8)
    x32 = np.asarray(s32.solve(s32.ones_b(), criteria=crit,
                               host_result=False))
    sm = build_sharded_poisson_solver(n, dim, nparts=8,
                                      dtype=jnp.bfloat16,
                                      vector_dtype=jnp.float32)
    xm = np.asarray(sm.solve(sm.ones_b(), criteria=crit, host_result=False))
    assert np.array_equal(x32, xm)


def test_epsilon_shift_applies():
    """--epsilon adds to the diagonal plane on the sharded route."""
    s = build_sharded_poisson_solver(8, 2, nparts=2, epsilon=1.5)
    d = s.A.offsets.index(0)
    assert float(np.asarray(s.A.data[d])[0]) == pytest.approx(4.0 + 1.5)


# -- 2-process multi-controller run of the full gen-direct sharded CLI --

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.mark.two_process_collectives
def test_cli_two_process_gen_direct():
    """gen:poisson3d under --multihost --nparts 4: the north-star
    configuration shape, on the 2-process CPU pod.  Both controllers
    run the sharded assembly (no host matrix anywhere); only process 0
    prints stats; the manufactured-solution error converges."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["ACG_TPU_GEN_DIRECT_MIN"] = "100"  # force the direct path at 16^3

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:16",
                "--nparts", "4", "--manufactured-solution",
                "--max-iterations", "2000", "--residual-rtol", "1e-6",
                "--dtype", "f32", "--warmup", "0", "--quiet",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    assert "total solver time" in se0
    assert "total solver time" not in se1
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-4, se0


# -- round 4: df64 refinement, independent oracle ------------------------

def test_dia_mv_roll_df_matches_f64():
    """The double-float roll SpMV must agree with numpy f64 to df64
    class (~1e-14 relative), far beyond plain f32 (~1e-7)."""
    from acg_tpu.parallel.sharded_dia import dia_mv_roll_df
    from acg_tpu.ops.spmv import dia_from_csr

    csr = _csr(16, 3)
    A = dia_from_csr(csr, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = rng.standard_normal(csr.shape[0]).astype(np.float32)
    yh, yl = dia_mv_roll_df(A.data, A.offsets,
                            jnp.asarray(x), jnp.zeros_like(jnp.asarray(x)))
    y = np.asarray(yh, np.float64) + np.asarray(yl, np.float64)
    ref = csr @ x.astype(np.float64)
    rel = np.linalg.norm(y - ref) / np.linalg.norm(ref)
    assert rel < 1e-13


@pytest.mark.parametrize("kernels", ["xla-roll", "pallas-roll"])
def test_sharded_refine_reaches_f64_class_error(kernels):
    """gen-direct sharded --refine: df64 outer residual + f32 inner
    solves reach 1e-9-class solution error (round-3 verdict item 3) --
    the error a plain f32 solve caps at ~1e-6.  Parametrized over the
    kernel tiers so the pallas-roll inner solves carry the identical
    refine contract (round 5)."""
    s = build_sharded_poisson_solver(16, 3, nparts=8, kernels=kernels)
    xsol, b = s.manufactured_df(seed=0)
    xh, xl = s.solve_refined(b, criteria=StoppingCriteria(
        maxits=20000, residual_rtol=1e-11), inner_rtol=1e-5)
    err0, err = s.error_norms_df(xh, xl, xsol)
    assert err0 == pytest.approx(1.0, rel=1e-5)
    assert err < 1e-8
    assert s.stats.nrefine >= 2
    # and the refined solution satisfies the ORIGINAL system in f64
    csr = _csr(16, 3)
    x64 = np.asarray(xh, np.float64) + np.asarray(xl, np.float64)
    b64 = (np.asarray(b[0], np.float64) + np.asarray(b[1], np.float64))
    rel = np.linalg.norm(b64 - csr @ x64) / np.linalg.norm(b64)
    assert rel < 1e-10


def test_spot_check_catches_corrupt_b():
    """The analytic-stencil spot check accepts a correct manufactured b
    and rejects a corrupted one (the de-circularised oracle, round-3
    verdict item 5)."""
    from acg_tpu.parallel.sharded_dia import spot_check_manufactured

    s = build_sharded_poisson_solver(12, 2, nparts=4)
    xsol, b = s.manufactured(seed=1)
    dev = spot_check_manufactured(s, xsol, b, nsample=64)
    assert dev < 1e-6
    bad = b.at[137].multiply(1.01)
    dev_bad = spot_check_manufactured(s, xsol, bad, nsample=4096)
    assert dev_bad > 1e-4


# -- round 5: the per-shard Pallas kernel tier on the sharded route -----

def _build_pallas_roll(n, dim, nparts):
    """(f, A2, sharding): the windowed kernel callable and its padded
    plane twin -- the one construction both pallas-roll unit tests pin."""
    from acg_tpu.parallel.sharded_dia import (PallasRollSpmv, _halo_sizes,
                                              sharded_poisson_dia_padded)
    from acg_tpu.ops.spmv import DiaMatrix

    mesh = solve_mesh(nparts)
    N = n ** dim
    nloc = N // nparts
    offsets = tuple(sorted([s for a in range(dim)
                            for s in (-(n ** a), n ** a)] + [0]))
    Lh, Rh = _halo_sizes(offsets, nloc)
    padded, offs, nwin = sharded_poisson_dia_padded(n, dim, mesh, nloc,
                                                    Lh, Rh)
    assert offs == offsets and nwin == Lh + nloc + Rh
    A2 = DiaMatrix(data=tuple(padded), offsets=offs, nrows=N,
                   ncols_padded=N)
    f = PallasRollSpmv(mesh, nloc, Lh, Rh, offs, interpret=True)
    return f, A2, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("parts"))


def test_pallas_roll_spmv_matches_scipy():
    """The shard_map + ppermute-halo Pallas SpMV (padded per-shard
    planes) computes the same operator as scipy, interpret mode on the
    CPU mesh (round-4 verdict item 7)."""
    n, dim = 16, 3
    f, A2, sh = _build_pallas_roll(n, dim, 8)
    N = A2.nrows
    x = np.random.default_rng(0).standard_normal(N).astype(np.float32)
    xs = jax.device_put(x, sh)
    y = np.asarray(jax.jit(lambda v: f(A2, v))(xs), np.float64)
    y_ref = _csr(n, dim) @ x.astype(np.float64)
    assert np.linalg.norm(y - y_ref) <= 1e-5 * np.linalg.norm(y_ref)


def test_sharded_pallas_roll_solver_matches_xla_roll():
    """build_sharded_poisson_solver(kernels='pallas-roll') solves the
    same system to the same answer as the xla-roll route."""
    n, dim = 24, 2
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-6)
    sp = build_sharded_poisson_solver(n, dim, nparts=8,
                                      kernels="pallas-roll")
    assert getattr(sp.kernels, "name", None) == "pallas-roll"
    xsol, b = sp.manufactured(seed=5)
    xp = np.asarray(sp.solve(b, criteria=crit, host_result=False),
                    np.float64)
    sx = build_sharded_poisson_solver(n, dim, nparts=8)
    xx = np.asarray(sx.solve(b, criteria=crit, host_result=False),
                    np.float64)
    assert sp.stats.converged and sx.stats.converged
    bnrm = float(np.linalg.norm(np.asarray(b, np.float64)))
    assert np.linalg.norm(xp - xx) <= 1e-4 * bnrm
    err = np.linalg.norm(xp - np.asarray(xsol, np.float64))
    assert err < 1e-3


def test_pallas_roll_hlo_permutes_no_gathers():
    """The pallas-roll tier's compiled SpMV must exchange its halo via
    exactly two collective-permutes (left + right edge slices) and no
    all-gathers -- the same scaling property the xla-roll HLO test pins
    for the GSPMD-derived halo."""
    f, A2, sh = _build_pallas_roll(16, 3, 8)
    x = jax.device_put(np.ones(A2.nrows, np.float32), sh)
    hlo = jax.jit(lambda v: f(A2, v)).lower(x).compile().as_text()
    # newer XLA merges the edge-slice exchanges into exactly 2 permutes;
    # older compilers leave up to one pair per offset group unmerged --
    # still O(1) neighbour traffic, which is the property that scales
    assert 2 <= len(re.findall(r"collective-permute", hlo)) <= 8
    assert not re.search(r"all-gather", hlo)


def test_sharded_pallas_roll_with_bf16rr():
    """The kernel tier composes with the sound-bf16 replacement
    programs (the 512^3 target configuration: pallas-roll + bf16rr)."""
    sp = build_sharded_poisson_solver(
        32, 2, nparts=8, dtype=jnp.bfloat16, vector_dtype=jnp.bfloat16,
        replace_every=25, kernels="pallas-roll")
    xsol, b = sp.manufactured(seed=1)
    x = sp.solve(b, criteria=StoppingCriteria(maxits=800,
                                              residual_rtol=1e-5),
                 host_result=False, raise_on_divergence=False)
    csr = _csr(32, 2)
    b64 = np.asarray(b, np.float64)
    rel = (np.linalg.norm(b64 - csr @ np.asarray(x, np.float64))
           / np.linalg.norm(b64))
    assert rel < 1e-4


# -- round 5: the sound bf16 tier on the north-star (sharded) path ------

def test_sharded_bf16rr_sound_at_high_kappa():
    """Sharded bf16 storage with periodic f32 residual replacement
    reaches f32-class true residuals at a conditioning where plain bf16
    storage stalls (round-4 verdict item 1: the half-traffic accuracy
    contract must run on the sharded route)."""
    n, dim = 64, 2  # kappa ~ 4n^2/pi^2 ~ 1.7e3 >> the bf16 limit (~500)
    crit = StoppingCriteria(maxits=1500, residual_rtol=1e-5)
    s_rr = build_sharded_poisson_solver(
        n, dim, nparts=8, dtype=jnp.bfloat16, vector_dtype=jnp.bfloat16,
        replace_every=25)
    xsol, b = s_rr.manufactured(seed=3)
    # the replacement tier's outer iteration owns b in f32: a bf16 b
    # would bake a u_bf16 backward error into every recomputed residual
    assert b.dtype == jnp.float32
    x = s_rr.solve(b, criteria=crit, host_result=False,
                   raise_on_divergence=False)
    csr = _csr(n, dim)
    b64 = np.asarray(b, np.float64)
    rel_rr = (np.linalg.norm(b64 - csr @ np.asarray(x, np.float64))
              / np.linalg.norm(b64))
    assert rel_rr < 1e-4

    s_plain = build_sharded_poisson_solver(
        n, dim, nparts=8, dtype=jnp.bfloat16, vector_dtype=jnp.bfloat16)
    xp = s_plain.solve(b.astype(jnp.bfloat16), criteria=crit,
                       host_result=False, raise_on_divergence=False)
    rel_plain = (np.linalg.norm(b64 - csr @ np.asarray(xp, np.float64))
                 / np.linalg.norm(b64))
    assert rel_plain > 10 * rel_rr  # the drift the replacement removes


def test_sharded_bf16rr_refine_nest_reaches_f64_class():
    """replacement-inner + df64-refine-outer: the rtol-1e-9 nest for
    bf16 storage on the sharded route (sound bf16 CG inner solves under
    solve_refined's df64 outer residual)."""
    s = build_sharded_poisson_solver(
        16, 3, nparts=8, dtype=jnp.bfloat16, vector_dtype=jnp.bfloat16,
        replace_every=25)
    xsol, b = s.manufactured_df(seed=0)
    xh, xl = s.solve_refined(b, criteria=StoppingCriteria(
        maxits=40000, residual_rtol=1e-10), inner_rtol=1e-4)
    err0, err = s.error_norms_df(xh, xl, xsol)
    assert err0 == pytest.approx(1.0, rel=1e-5)
    assert err < 1e-7
    assert s.stats.nrefine >= 2


def test_cli_sharded_replace_every():
    """CLI end-to-end: the sharded gen-direct route accepts
    --replace-every (previously rejected, round-4 verdict item 1) and
    passes the analytic spot check with its f32-manufactured b."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["ACG_TPU_GEN_DIRECT_MIN"] = "0"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson2d:48",
         "--nparts", "8", "--dtype", "bf16", "--replace-every", "25",
         "--manufactured-solution", "--max-iterations", "4000",
         "--residual-rtol", "1e-5", "--warmup", "0", "--quiet"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "manufactured-b spot check" in r.stderr
    dev = float(r.stderr.split("max rel dev ")[1].split()[0])
    assert dev < 1e-5  # f32-manufactured b, not bf16-rounded


def test_cli_sharded_plain_bf16_spot_check_threshold():
    """Plain bf16 (no replacement) manufactures b in bf16 storage; the
    spot check must scale its threshold to that dtype instead of
    failing a documented configuration (round-4 advisor finding)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["ACG_TPU_GEN_DIRECT_MIN"] = "0"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson2d:24",
         "--nparts", "8", "--dtype", "bf16",
         "--manufactured-solution", "--max-iterations", "400",
         "--warmup", "0", "--quiet"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "FAILED the independent spot check" not in r.stderr


def test_cli_sharded_refine(tmp_path):
    """CLI end-to-end: gen: sharded path with --refine reports
    1e-9-class error and the spot-check line."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["ACG_TPU_GEN_DIRECT_MIN"] = "0"  # force the sharded direct route
    out = tmp_path / "xref.bin.mtx"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:16",
         "--nparts", "8", "--refine", "--dtype", "f32",
         "--manufactured-solution", "--max-iterations", "20000",
         "--residual-rtol", "1e-11", "--warmup", "0", "--quiet",
         "-o", str(out)],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "manufactured-b spot check" in r.stderr
    err = float([ln for ln in r.stderr.splitlines()
                 if ln.startswith("error 2-norm:")][0].split(":")[1])
    assert err < 1e-8
    # the EMITTED solution must carry the refined (df64) accuracy, not
    # just the f32 hi part: assert the written values are NOT everywhere
    # f32-representable -- true only if the hi+lo df64 sum was emitted
    # (the b is device-generated, so the file's residual itself is not
    # reconstructable here; the df64 accuracy is pinned by
    # test_sharded_refine_reaches_f64_class_error)
    from acg_tpu.io.mtxfile import read_mtx
    x = np.asarray(read_mtx(out, binary=True).vals).reshape(-1)
    assert not np.array_equal(x, x.astype(np.float32).astype(np.float64))
