"""Extended-precision primitives and mixed-precision solves.

The reference sidesteps all of this by being strictly f64 (comm.h:180-183);
on TPU these are the mechanisms that recover f64-quality results from
f32-native hardware (SURVEY.md section 7 "hard parts").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.precision import (df_sum, dot2, dot_compensated, two_prod,
                                   two_sum)
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.solvers import StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.refine import RefinedSolver


def test_two_sum_exact():
    """s + e must equal a + b exactly (checked in f64)."""
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal(1000) * 1e6, jnp.float32)
    b = jnp.asarray(rng.standard_normal(1000) * 1e-3, jnp.float32)
    s, e = jax.jit(two_sum)(a, b)
    exact = np.asarray(a, np.float64) + np.asarray(b, np.float64)
    got = np.asarray(s, np.float64) + np.asarray(e, np.float64)
    np.testing.assert_array_equal(got, exact)


def test_two_prod_exact():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal(1000), jnp.float32)
    b = jnp.asarray(rng.standard_normal(1000), jnp.float32)
    p, e = jax.jit(two_prod)(a, b)
    exact = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    got = np.asarray(p, np.float64) + np.asarray(e, np.float64)
    np.testing.assert_array_equal(got, exact)


def test_df_sum_beats_plain_sum():
    """Adversarial cancellation: df_sum must track the f64 sum far more
    closely than a plain f32 sum."""
    rng = np.random.default_rng(2)
    n = 1 << 16
    x64 = rng.standard_normal(n) * 10.0 ** rng.integers(0, 6, n)
    x64 = np.concatenate([x64, -x64 * (1 + 1e-7)])  # heavy cancellation
    x = jnp.asarray(x64, jnp.float32)
    x64 = np.asarray(x, np.float64)  # the exactly-representable inputs
    exact = np.sum(x64)
    hi, lo = jax.jit(df_sum)(x)
    df_err = abs((float(hi) + float(lo)) - exact)
    plain_err = abs(float(jnp.sum(x)) - exact)
    assert df_err <= plain_err / 64 or df_err < 1e-6 * abs(exact) + 1e-6


def test_dot2_matches_f64():
    rng = np.random.default_rng(3)
    n = 1 << 15
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), jnp.float32)
    exact = np.dot(np.asarray(x, np.float64), np.asarray(y, np.float64))
    hi, lo = jax.jit(dot_compensated)(x, y)
    assert abs((float(hi) + float(lo)) - exact) < 1e-5 * abs(exact) + 1e-8
    # compensated beats plain by a wide margin on this size
    plain_err = abs(float(jnp.dot(x, y)) - exact)
    comp_err = abs(float(jax.jit(dot2)(x, y)) - exact)
    assert comp_err <= plain_err + 1e-12


@pytest.fixture(scope="module")
def poisson32():
    return SymCsrMatrix.from_mtx(poisson_mtx(32, dim=2))


def test_precise_dots_f32_converges_deeper(poisson32):
    """With compensated dots, f32 CG reaches tolerances where the plain
    f32 recurrence typically stalls."""
    csr = poisson32.to_csr()
    n = csr.shape[0]
    rng = np.random.default_rng(4)
    xsol = rng.standard_normal(n)
    xsol /= np.linalg.norm(xsol)
    b = (csr @ xsol).astype(np.float32)
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    crit = StoppingCriteria(maxits=5000, residual_rtol=2e-6)
    solver = JaxCGSolver(A, precise_dots=True)
    x = solver.solve(b, criteria=crit)
    assert solver.stats.converged
    assert np.linalg.norm(x - xsol) < 5e-4


def test_refined_solver_reaches_f64_accuracy(poisson32):
    """f32 inner solves + f64 outer refinement: solution error at f64
    levels, far beyond single-precision reach."""
    csr = poisson32.to_csr()
    n = csr.shape[0]
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(n)
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    inner = JaxCGSolver(A)
    solver = RefinedSolver(inner, csr, inner_rtol=1e-4)
    crit = StoppingCriteria(maxits=20000, residual_rtol=1e-12)
    x = solver.solve(b, criteria=crit)
    assert solver.stats.converged
    assert solver.stats.nrefine >= 2
    assert np.linalg.norm(x - xsol) < 1e-10
    assert solver.stats.rnrm2 < 1e-12 * solver.stats.r0nrm2 * 1.01


def test_refined_solver_stagnation_raises(poisson32):
    """An unreachable tolerance must raise NotConvergedError, not loop."""
    from acg_tpu.errors import NotConvergedError
    csr = poisson32.to_csr()
    n = csr.shape[0]
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    inner = JaxCGSolver(A)
    solver = RefinedSolver(inner, csr, inner_rtol=1e-4)
    with pytest.raises(NotConvergedError):
        solver.solve(np.ones(n),
                     criteria=StoppingCriteria(maxits=200,
                                               residual_rtol=1e-300))


def test_refined_solver_unbounded_mode(poisson32):
    """maxits-only criteria: spend the budget, report converged (the
    direct solvers' unbounded semantics)."""
    csr = poisson32.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    solver = RefinedSolver(JaxCGSolver(A), csr, inner_rtol=1e-4)
    x = solver.solve(np.ones(csr.shape[0]),
                     criteria=StoppingCriteria(maxits=50))
    assert solver.stats.converged
    assert solver.stats.niterations <= 50
    assert np.isfinite(x).all()


def test_refined_solver_budget_not_exceeded(poisson32):
    """Total inner iterations must respect --max-iterations."""
    csr = poisson32.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    solver = RefinedSolver(JaxCGSolver(A), csr, inner_rtol=1e-6)
    try:
        solver.solve(np.ones(csr.shape[0]),
                     criteria=StoppingCriteria(maxits=37,
                                               residual_rtol=1e-14))
    except Exception:
        pass
    assert solver.stats.niterations <= 37


def test_split_dtype_aware():
    """The Dekker split constant must track the input dtype: f64 splits
    must be exact in f64 (27+26 bits)."""
    from acg_tpu.ops.precision import split
    rng = np.random.default_rng(7)
    a64 = jnp.asarray(rng.standard_normal(100), jnp.float64)
    hi, lo = split(a64)
    np.testing.assert_array_equal(np.asarray(hi) + np.asarray(lo),
                                  np.asarray(a64))
    # hi has at most 27 significant bits: hi * 2^27 rounds exactly
    p, e = two_prod(a64, a64)
    exact = np.asarray(a64, np.float64) ** 2
    # in f64, p + e must reproduce the square to quad-ish accuracy:
    # p is the rounded product, e the exact error
    assert np.all(np.asarray(p) + np.asarray(e) == exact)
