"""Single-device JAX CG solvers vs the host oracle (reference: cgcuda.c)."""

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import (CooMatrix, EllMatrix, device_matrix_from_csr,
                              spmv)
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver


@pytest.fixture(scope="module")
def poisson16():
    m = poisson_mtx(16, dim=2)
    return SymCsrMatrix.from_mtx(m)


def test_spmv_formats_match_scipy(poisson16):
    csr = poisson16.to_csr()
    x = np.random.default_rng(0).standard_normal(csr.shape[0])
    want = csr @ x
    for fmt in ("ell", "coo", "dia"):
        A = device_matrix_from_csr(csr, dtype=jnp.float64, format=fmt)
        got = np.asarray(spmv(A, jnp.asarray(x)))
        np.testing.assert_allclose(got, want, rtol=1e-13)


def test_spmv_dia_nonsymmetric_band():
    """DIA with asymmetric offsets (incl. out-of-band clipping at edges)."""
    import scipy.sparse as sp
    n = 50
    A = sp.diags([np.arange(1, n - 1, dtype=float), np.full(n, 4.0),
                  -np.ones(n - 3)], [-2, 0, 3]).tocsr()
    x = np.random.default_rng(1).standard_normal(n)
    from acg_tpu.ops.spmv import dia_from_csr
    D = dia_from_csr(A, dtype=jnp.float64)
    assert D.offsets == (-2, 0, 3)
    np.testing.assert_allclose(np.asarray(spmv(D, jnp.asarray(x))), A @ x,
                               rtol=1e-13)


def test_format_auto_choice(poisson16):
    from acg_tpu.ops.spmv import DiaMatrix
    csr = poisson16.to_csr()
    A = device_matrix_from_csr(csr, format="auto")
    assert isinstance(A, DiaMatrix)  # stencil: 5 diagonals -> DIA
    # scrambled rows destroy the diagonal structure -> ELL
    rng = np.random.default_rng(0)
    perm = rng.permutation(csr.shape[0])
    import scipy.sparse as sp
    Pm = sp.eye(csr.shape[0], format="csr")[perm]
    scrambled = (Pm @ csr @ Pm.T).tocsr()
    B = device_matrix_from_csr(scrambled, format="auto")
    assert isinstance(B, EllMatrix)
    import scipy.sparse as sp
    # arrow matrix: one dense row -> ELL would waste n*K
    n = 200
    arrow = sp.lil_matrix((n, n))
    arrow[0, :] = 1.0
    arrow[:, 0] = 1.0
    arrow.setdiag(n)
    B = device_matrix_from_csr(arrow.tocsr(), format="auto")
    # round 3: skewed row lengths pick binned ELL over COO (the dense
    # row lands in a wide bin of its own; tails engage past the widest
    # bin -- tests/test_binned_ell.py)
    from acg_tpu.ops.spmv import BinnedEllMatrix
    assert isinstance(B, BinnedEllMatrix)


@pytest.mark.parametrize("pipelined", [False, True])
@pytest.mark.parametrize("fmt", ["ell", "coo"])
def test_jax_cg_matches_host(poisson16, pipelined, fmt):
    csr = poisson16.to_csr()
    rng = np.random.default_rng(7)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)

    host = HostCGSolver(poisson16)
    xh = host.solve(b, criteria=crit)

    A = device_matrix_from_csr(csr, dtype=jnp.float64, format=fmt)
    solver = JaxCGSolver(A, pipelined=pipelined)
    xd = solver.solve(b, criteria=crit)

    assert np.linalg.norm(xd - xsol) < 1e-7
    assert np.linalg.norm(xd - xh) < 1e-7
    st = solver.stats
    assert st.converged
    assert st.rnrm2 < 1e-10 * st.r0nrm2 * 1.001
    # similar iteration count (+1: the pipelined variant's convergence
    # test is one iteration stale, like the reference's deferred test)
    assert abs(st.niterations - host.stats.niterations) <= 4


@pytest.mark.parametrize("pipelined", [False, True])
def test_jax_cg_maxits_only(poisson16, pipelined):
    csr = poisson16.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(A, pipelined=pipelined)
    solver.solve(np.ones(csr.shape[0]), criteria=StoppingCriteria(maxits=13))
    assert solver.stats.niterations == 13
    assert solver.stats.converged


def test_jax_cg_float32(poisson16):
    """f32 path (the TPU-native dtype) still reaches a loose tolerance."""
    csr = poisson16.to_csr()
    rng = np.random.default_rng(3)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = (csr @ xsol).astype(np.float32)
    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    solver = JaxCGSolver(A)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=3000, residual_rtol=1e-4))
    assert solver.stats.converged
    assert np.linalg.norm(x - xsol) < 1e-2


def test_jax_cg_diff_criterion(poisson16):
    csr = poisson16.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(A)
    solver.solve(np.ones(csr.shape[0]),
                 criteria=StoppingCriteria(maxits=5000, diff_atol=1e-9))
    assert solver.stats.converged
    assert solver.stats.dxnrm2 < 1e-9


def test_stats_flops_positive(poisson16):
    csr = poisson16.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(A)
    solver.solve(np.ones(csr.shape[0]),
                 criteria=StoppingCriteria(maxits=50, residual_rtol=1e-6))
    st = solver.stats
    assert st.nflops > 0 and st.tsolve > 0
    text = st.fwrite()
    assert "total solver time: " in text


@pytest.mark.parametrize("pipelined", [False, True])
def test_jax_cg_zero_rhs_converges_immediately(poisson16, pipelined):
    """b = 0 with x0 = 0 is converged at entry: the solver must return x0
    in 0 iterations, not divide 0/0 in the first pipelined update."""
    csr = poisson16.to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(A, pipelined=pipelined)
    b = np.zeros(csr.shape[0])
    x = solver.solve(b, criteria=StoppingCriteria(maxits=50, residual_rtol=1e-8,
                                                  residual_atol=1e-30))
    assert np.all(np.isfinite(x))
    assert np.all(x == 0.0)
    assert solver.stats.niterations == 0
    assert solver.stats.converged


def test_poisson_dia_direct_assembly_matches_csr_path():
    """poisson_dia builds the DIA planes directly (no COO/CSR/sort);
    they must equal dia_from_csr's output exactly, and the solve must
    match the host oracle."""
    import jax.numpy as jnp

    from acg_tpu.io.generators import (poisson2d_coo, poisson3d_coo,
                                       poisson_dia)
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import DiaMatrix, device_matrix_from_csr
    from acg_tpu.solvers.host_cg import HostCGSolver

    from acg_tpu.io.generators import poisson_dia_device

    for n, dim, gen in ((9, 2, poisson2d_coo), (5, 3, poisson3d_coo)):
        planes, offsets, N = poisson_dia(n, dim)
        r, c, v, _ = gen(n)
        csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
        ref = device_matrix_from_csr(csr, dtype=jnp.float64, format="dia")
        assert ref.offsets == offsets
        for p, q in zip(planes, ref.data):
            np.testing.assert_array_equal(p, np.asarray(q))
        # the on-device builder (what the 512^3 bench row uses) must
        # agree with the host builder, plane order and all
        dplanes, doffsets, dN = poisson_dia_device(n, dim)
        assert doffsets == offsets and dN == N
        for p, q in zip(planes, dplanes):
            np.testing.assert_array_equal(np.float32(p), np.asarray(q))
        A = DiaMatrix(data=tuple(jnp.asarray(p) for p in planes),
                      offsets=offsets, nrows=N, ncols_padded=N)
        b = np.ones(N)
        crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
        x = JaxCGSolver(A).solve(b, criteria=crit)
        xh = HostCGSolver(csr).solve(b, criteria=crit)
        np.testing.assert_allclose(x, xh, atol=1e-8)


def test_solve_host_result_false():
    """host_result=False keeps x on device (the 512^3 transfer-avoiding
    mode) with identical values and a faithful NaN/Inf report."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import device_matrix_from_csr

    r, c, v, N = poisson2d_coo(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    b = np.ones(N)
    crit = StoppingCriteria(maxits=500, residual_rtol=1e-10)
    s1, s2 = JaxCGSolver(A), JaxCGSolver(A)
    x_host = s1.solve(b, criteria=crit)
    x_dev = s2.solve(b, criteria=crit, host_result=False)
    assert isinstance(x_dev, jax.Array)
    np.testing.assert_array_equal(np.asarray(x_dev), x_host)
    assert "none" in s2.stats.fwrite()  # fp exceptions: none
    # a solve that overflows must report Inf (not the NaN sentinel)
    bad = device_matrix_from_csr(csr * jnp.inf, dtype=jnp.float64)
    sb = JaxCGSolver(bad)
    sb.solve(b, criteria=StoppingCriteria(maxits=2), host_result=False,
             raise_on_divergence=False)
    report = sb.stats.fwrite()
    line = [l for l in report.splitlines()
            if "floating-point exceptions" in l][0]
    assert "none" not in line


def test_solver_construction_zero_transfers():
    """A solver over on-device DIA planes must construct with NO
    host<->device transfers at all: the round-2 regression was an
    O(matrix) device->host fetch (np.count_nonzero per plane) at init --
    ~3.8 GB for the 512^3 planes -- for a flop statistic
    (ops/spmv.py spmv_flops; now counted on device, lazily)."""
    import jax
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.ops.spmv import DiaMatrix

    planes, offsets, N = poisson_dia_device(16, 2, dtype=jnp.float32)
    planes = tuple(jnp.asarray(p).block_until_ready() for p in planes)
    with jax.transfer_guard("disallow"):
        A = DiaMatrix(data=planes, offsets=offsets, nrows=N, ncols_padded=N)
        solver = JaxCGSolver(A, kernels="xla")
    # the flop statistic is still available (device count, one scalar)
    assert solver._spmv_flops == 3.0 * (5 * N - 4 * 16)
