"""Survivability tier: solver-state checkpoint/restore, the ABFT
checksum SpMV, and the rollback rung (acg_tpu.checkpoint).

The acceptance contract (ISSUE 7):
  * a chunked (--ckpt) solve follows the IDENTICAL trajectory as an
    uninterrupted one (bitwise x) on every tier;
  * a solve killed by crash:exit@K and relaunched with --resume reaches
    the original tolerance with pre-crash + post-resume iterations
    within 10% of the uninterrupted count (measured: exactly equal);
  * an injected sdc:flip fault -- finite, invisible to every non-finite
    guard -- is detected on device by the ABFT checksum test and routed
    through the rollback rung; disarmed, the same fault converges to a
    WRONG answer (the negative control);
  * disarmed programs lower byte-identical code; armed collective
    deltas are pinned.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import faults, health
from acg_tpu.checkpoint import (CheckpointConfig, SolverSnapshot,
                                agree_seq, ca_carry_names, carry_names,
                                load_snapshot, save_snapshot,
                                validate_resume, vector_checksum)
from acg_tpu.errors import AcgError
from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.resilience import RecoveryDriver, RecoveryPolicy
from acg_tpu.solvers.stats import SolverStats

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


@pytest.fixture(scope="module")
def system():
    csr = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2)).to_csr()
    rng = np.random.default_rng(0)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    return csr, xsol, csr @ xsol


@pytest.fixture(scope="module")
def prob8(system):
    csr, _, _ = system
    part = partition_rows(csr, 8, seed=0)
    return DistributedProblem.build(csr, part, 8, dtype=jnp.float64)


CRIT = StoppingCriteria(residual_rtol=1e-8, maxits=2000)


# -- the snapshot container ----------------------------------------------

def test_snapshot_roundtrip_preserves_scalars(tmp_path):
    """Scalar carry leaves (gamma/alpha/rr) must survive as 0-d arrays:
    a (1,)-promoted scalar re-entering the loop carry breaks the while
    predicate (the ascontiguousarray 0-d promotion regression)."""
    p = str(tmp_path / "s")
    save_snapshot(p, {"iteration": 3},
                  {"x": np.arange(5.0, dtype=np.float32),
                   "gamma": np.float32(2.5)})
    s = load_snapshot(p)
    assert s.iteration == 3
    assert s.arrays["x"].shape == (5,)
    assert s.arrays["gamma"].shape == ()
    assert float(s.arrays["gamma"]) == 2.5
    assert s.arrays["x"].dtype == np.float32


def test_corrupted_snapshot_refuses(tmp_path):
    """Any integrity failure -- bad magic, truncation, a flipped byte
    in header or payload -- must refuse with a typed error: a resumed
    solve must never start from garbage."""
    p = str(tmp_path / "s")
    save_snapshot(p, {"iteration": 1}, {"x": np.ones(64)})
    blob = open(p, "rb").read()

    def expect_refusal(mutated, why):
        bad = str(tmp_path / "bad")
        with open(bad, "wb") as f:
            f.write(mutated)
        with pytest.raises(AcgError):
            load_snapshot(bad)

    expect_refusal(b"NOTACKPT" + blob[8:], "magic")
    expect_refusal(blob[: len(blob) // 2], "truncated")
    # flip one byte inside the payload (the trailing array bytes)
    flipped = bytearray(blob)
    flipped[-7] ^= 0xFF
    expect_refusal(bytes(flipped), "payload crc")
    # flip one byte inside the JSON header region
    hdr = bytearray(blob)
    idx = blob.index(b'"arrays"')
    hdr[idx + 1] ^= 0x01
    expect_refusal(bytes(hdr), "header crc")
    with pytest.raises(AcgError):
        load_snapshot(str(tmp_path / "never-written"))


def test_validate_resume_refuses_mismatches():
    snap = SolverSnapshot(
        meta={"tier": "jax-cg", "pipelined": False, "precond": None,
              "n": 64, "dtype": "float32", "b_crc": 7, "iteration": 5},
        arrays={})
    ok = dict(tier="jax-cg", pipelined=False, precond=None, n=64,
              dtype=np.float32, b_crc=7)
    validate_resume(snap, **ok)
    for key, bad in (("tier", "dist-cg"), ("pipelined", True),
                     ("precond", "jacobi"), ("n", 65),
                     ("dtype", np.float64), ("b_crc", 8)):
        kw = dict(ok)
        kw[key] = bad
        with pytest.raises(AcgError):
            validate_resume(snap, **kw)


def test_carry_names_layouts():
    assert carry_names(False, False) == ("x", "r", "p", "gamma")
    assert carry_names(False, True) == ("x", "r", "p", "gamma", "rr")
    assert carry_names(True, False) == ("x", "r", "w", "p", "t", "z",
                                        "gamma", "alpha")
    assert carry_names(True, True)[-1] == "rr"
    assert len(carry_names(True, True)) == 11


def test_agree_seq_single_process_is_free():
    agree_seq(3, 48)  # no coordination service: must return instantly


# -- chunked trajectory parity + resume, per tier ------------------------

@pytest.mark.parametrize("pipelined", [False, True])
@pytest.mark.parametrize("precond", [None, "jacobi"])
def test_single_device_chunk_parity_and_resume(system, tmp_path,
                                               pipelined, precond):
    """--ckpt chunks the solve WITHOUT changing the trajectory (bitwise
    x), and --resume continues it so pre-crash + post-resume iterations
    EQUAL the uninterrupted count."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    ref = JaxCGSolver(A, pipelined=pipelined, precond=precond)
    x_ref = ref.solve(b, criteria=CRIT)
    it_ref = ref.stats.niterations

    p = str(tmp_path / "ck")
    s1 = JaxCGSolver(A, pipelined=pipelined, precond=precond,
                     ckpt=CheckpointConfig(path=p, every=16))
    x_ck = s1.solve(b, criteria=CRIT)
    assert np.array_equal(np.asarray(x_ref), np.asarray(x_ck))
    assert s1.stats.niterations == it_ref
    assert s1.stats.ckpt["snapshots"] >= 2

    snap = load_snapshot(p)
    assert snap.meta["tier"] == "jax-cg"
    s2 = JaxCGSolver(A, pipelined=pipelined, precond=precond,
                     ckpt=CheckpointConfig(resume=snap))
    x_rs = s2.solve(b, criteria=CRIT)
    total = snap.iteration + s2.stats.niterations
    # the acceptance criterion allows 10% slack; the carry makes it 0
    assert total == it_ref
    assert np.allclose(np.asarray(x_rs), np.asarray(x_ref),
                       rtol=1e-7, atol=1e-10)
    assert s2.stats.ckpt["resumed_from"] == snap.iteration


@pytest.mark.parametrize("pipelined", [False, True])
def test_dist8_chunk_parity_and_resume(system, prob8, tmp_path,
                                       pipelined):
    """The 8-part explicit-mesh twin of the single-device parity: the
    shard_map'd chunked solve is bitwise-identical and resumes to the
    exact uninterrupted iteration count (per-part state committed
    under one agreed sequence number)."""
    csr, _, b = system
    ref = DistCGSolver(prob8, pipelined=pipelined)
    x_ref = ref.solve(b, criteria=CRIT)
    it_ref = ref.stats.niterations

    p = str(tmp_path / "ck")
    s1 = DistCGSolver(prob8, pipelined=pipelined,
                      ckpt=CheckpointConfig(path=p, every=16))
    x_ck = s1.solve(b, criteria=CRIT)
    assert np.array_equal(x_ref, x_ck)
    assert s1.stats.niterations == it_ref

    snap = load_snapshot(p)
    assert snap.meta["tier"] == "dist-cg"
    assert snap.meta["nparts"] == 8
    assert snap.arrays["x"].shape[0] == 8  # stacked per-part leaves
    s2 = DistCGSolver(prob8, pipelined=pipelined,
                      ckpt=CheckpointConfig(resume=snap))
    x_rs = s2.solve(b, criteria=CRIT)
    assert snap.iteration + s2.stats.niterations == it_ref
    assert np.allclose(x_rs, x_ref, rtol=1e-7, atol=1e-10)


# -- CA recurrence checkpoint carry (ROADMAP 4c, ISSUE 16) ----------------

def test_ca_carry_names_layouts():
    assert ca_carry_names("sstep") == ("x", "r", "p", "gamma")
    pl = ca_carry_names("pl")
    assert pl[:2] == ("x", "q")
    assert "j" in pl and "adv" in pl  # frame-absolute pipe counters
    assert len(pl) == 12


@pytest.mark.parametrize("algorithm", ["sstep:4", "pipelined:2"])
def test_ca_chunk_parity_and_resume(system, tmp_path, algorithm):
    """--ckpt under the CA recurrences: the chunked solve is bitwise
    identical to the monolithic one (the sstep carry snapshots at
    BLOCK boundaries, where its state is exactly classic-shaped; the
    pl carry round-trips the full pipeline working set), and --resume
    continues to the exact uninterrupted iteration count."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    ref = JaxCGSolver(A, algorithm=algorithm)
    x_ref = ref.solve(b, criteria=CRIT)
    it_ref = ref.stats.niterations

    p = str(tmp_path / "ck")
    # every=16 keeps each chunk boundary s-aligned for sstep:4 (an
    # unaligned cap would truncate a masked block -- a mathematically
    # equivalent restart, not the monolithic mid-block state)
    s1 = JaxCGSolver(A, algorithm=algorithm,
                     ckpt=CheckpointConfig(path=p, every=16))
    x_ck = s1.solve(b, criteria=CRIT)
    assert np.array_equal(np.asarray(x_ref), np.asarray(x_ck))
    assert s1.stats.niterations == it_ref
    assert s1.stats.ckpt["snapshots"] >= 2

    snap = load_snapshot(p)
    assert snap.meta["algorithm"] == algorithm
    for name in ca_carry_names(algorithm.split(":")[0]
                               .replace("pipelined", "pl")):
        assert name in snap.arrays
    s2 = JaxCGSolver(A, algorithm=algorithm,
                     ckpt=CheckpointConfig(resume=snap))
    x_rs = s2.solve(b, criteria=CRIT)
    assert snap.iteration + s2.stats.niterations == it_ref
    assert np.allclose(np.asarray(x_rs), np.asarray(x_ref),
                       rtol=1e-7, atol=1e-10)
    assert s2.stats.ckpt["resumed_from"] == snap.iteration


def test_ca_cross_recurrence_resume_refuses(system, tmp_path):
    """A snapshot names its recurrence; resuming it under ANY other
    recurrence must refuse -- the sstep block-boundary carry is
    byte-shaped exactly like the classic carry, so only the declared
    algorithm key separates them."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    p = str(tmp_path / "ck")
    JaxCGSolver(A, algorithm="sstep:4",
                ckpt=CheckpointConfig(path=p, every=16)).solve(
        b, criteria=CRIT)
    snap = load_snapshot(p)
    for other in ("pipelined:2", None):
        s = JaxCGSolver(A, algorithm=other,
                        ckpt=CheckpointConfig(resume=snap))
        with pytest.raises(AcgError, match="recurrence"):
            s.solve(b, criteria=CRIT)
    # and the reverse: a classic snapshot refused under a CA resume
    p2 = str(tmp_path / "ck2")
    JaxCGSolver(A, ckpt=CheckpointConfig(path=p2, every=16)).solve(
        b, criteria=CRIT)
    s = JaxCGSolver(A, algorithm="sstep:4",
                    ckpt=CheckpointConfig(resume=load_snapshot(p2)))
    with pytest.raises(AcgError, match="recurrence"):
        s.solve(b, criteria=CRIT)


def test_ca_ckpt_refusal_matrix(system, tmp_path):
    """The two combinations the CA carry cannot honour stay typed
    refusals: repartition (the carry layout is not in the
    field-compatible set) and pl+trace (absolute vs chunk-relative
    iteration frames)."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    p = str(tmp_path / "ck")
    JaxCGSolver(A, ckpt=CheckpointConfig(path=p, every=16)).solve(
        b, criteria=CRIT)
    snap = load_snapshot(p)
    with pytest.raises(ValueError, match="repartition"):
        JaxCGSolver(A, algorithm="sstep:4",
                    ckpt=CheckpointConfig(resume=snap,
                                          repartition=True))
    with pytest.raises(ValueError, match="trace"):
        JaxCGSolver(A, algorithm="pipelined:2", trace=8,
                    ckpt=CheckpointConfig(path=p, every=16))
    # sstep keeps its trace ring (classic iteration frame), and both
    # CA kinds keep plain --ckpt
    JaxCGSolver(A, algorithm="sstep:4", trace=8,
                ckpt=CheckpointConfig(path=p, every=16))
    JaxCGSolver(A, algorithm="pipelined:2",
                ckpt=CheckpointConfig(path=p, every=16))


def test_cross_tier_resume_refuses(system, prob8, tmp_path):
    """A single-device snapshot must not resume on the mesh (and vice
    versa): the carry layouts are tier-specific, and continuing the
    wrong one would converge to a green wrong answer."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    p = str(tmp_path / "ck")
    JaxCGSolver(A, ckpt=CheckpointConfig(path=p, every=8)).solve(
        b, criteria=CRIT)
    snap = load_snapshot(p)
    s = DistCGSolver(prob8, ckpt=CheckpointConfig(resume=snap))
    with pytest.raises(AcgError, match="does not match this solve"):
        s.solve(b, criteria=CRIT)
    # and a different right-hand side refuses via the stored checksum
    s2 = JaxCGSolver(A, ckpt=CheckpointConfig(resume=snap))
    with pytest.raises(AcgError, match="right-hand-side checksum"):
        s2.solve(b + 1.0, criteria=CRIT)


def test_sharded_dia_chunk_parity_and_resume(tmp_path):
    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    crit = StoppingCriteria(residual_rtol=1e-8, maxits=2000)
    ref = build_sharded_poisson_solver(24, 2, dtype=jnp.float64)
    xsol, b = ref.manufactured()
    x_ref = ref.solve(b, criteria=crit)
    it_ref = ref.stats.niterations

    p = str(tmp_path / "ck")
    s1 = build_sharded_poisson_solver(
        24, 2, dtype=jnp.float64, ckpt=CheckpointConfig(path=p, every=16))
    x_ck = s1.solve(b, criteria=crit)
    assert np.array_equal(np.asarray(x_ref), np.asarray(x_ck))
    snap = load_snapshot(p)
    assert snap.meta["tier"] == "sharded-dia"
    s2 = build_sharded_poisson_solver(
        24, 2, dtype=jnp.float64, ckpt=CheckpointConfig(resume=snap))
    s2.solve(b, criteria=crit)
    assert snap.iteration + s2.stats.niterations == it_ref


def test_host_chunk_parity_resume_and_rollback(system, tmp_path):
    """The eager oracle: same contract, plus the rollback rung restores
    the exact snapshot state on a detected breakdown."""
    csr, xsol, b = system
    crit = StoppingCriteria(residual_rtol=1e-10, maxits=2000)
    ref = HostCGSolver(csr)
    x_ref = ref.solve(b, criteria=crit)
    it_ref = ref.stats.niterations

    p = str(tmp_path / "ck")
    s1 = HostCGSolver(csr, ckpt=CheckpointConfig(path=p, every=16))
    x_ck = s1.solve(b, criteria=crit)
    assert np.array_equal(x_ref, x_ck)
    snap = load_snapshot(p)
    assert snap.meta["tier"] == "host-cg"
    s2 = HostCGSolver(csr, ckpt=CheckpointConfig(resume=snap))
    s2.solve(b, criteria=crit)
    assert snap.iteration + s2.stats.niterations == it_ref

    # rollback: an injected flip at an audited iteration rolls the
    # eager Krylov state back to the last snapshot and still converges
    spec = faults.parse_fault_spec("sdc:flip@9")
    hs = health.make_spec(every=5, abft=True)
    with faults.injected(spec):
        s3 = HostCGSolver(csr, health=hs, recovery=RecoveryPolicy(),
                          ckpt=CheckpointConfig(path=str(tmp_path / "r"),
                                                every=8))
        x3 = s3.solve(b, criteria=crit)
    assert s3.stats.nrollbacks == 1
    assert s3.stats.converged
    assert np.linalg.norm(b - csr @ x3) / np.linalg.norm(b) < 1e-8


# -- ABFT: detection where every other guard is blind --------------------

@pytest.mark.parametrize("pipelined", [False, True])
def test_abft_detects_sdc_flip_and_rolls_back(system, tmp_path,
                                              pipelined):
    """The acceptance proof: a sign-flipped SpMV element at an audited
    iteration is FINITE -- no non-finite guard can see it -- yet the
    checksum test trips on device, the breakdown routes into the
    rollback rung, and the solve still converges to a RIGHT answer."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    spec = faults.parse_fault_spec("sdc:flip@9")
    hs = health.make_spec(every=5, abft=True)
    with faults.injected(spec):
        s = JaxCGSolver(A, pipelined=pipelined, health=hs,
                        recovery=RecoveryPolicy(),
                        ckpt=CheckpointConfig(path=str(tmp_path / "ck"),
                                              every=8))
        x = s.solve(b, criteria=CRIT)
    ab = s.stats.health["abft"]
    assert ab["ntrips"] >= 1
    # a flipped element's signature is macroscopic (~2/n of the
    # denominator), many orders above the rounding-noise floor
    assert ab["rel_max"] > 1e-6
    assert s.stats.nrollbacks == 1
    assert s.stats.nrestarts == 0  # rollback spends its OWN budget
    assert s.stats.converged
    assert np.linalg.norm(b - csr @ np.asarray(x)) / np.linalg.norm(b) \
        < 1e-7


def test_sdc_flip_without_abft_is_a_wrong_answer(system):
    """The negative control: the same fault with ABFT disarmed sails
    through every guard (and a record-only gap audit) to a CONVERGED
    report whose true residual misses the tolerance by orders of
    magnitude -- exactly the failure class ABFT exists for."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    spec = faults.parse_fault_spec("sdc:flip@9")
    # record-only audit: measures the drift but gates nothing
    hs = health.make_spec(every=5)
    with faults.injected(spec):
        s = JaxCGSolver(A, health=hs)
        x = s.solve(b, criteria=CRIT, raise_on_divergence=False)
    assert s.stats.converged  # the recurrence lied
    true_rel = (np.linalg.norm(b - csr @ np.asarray(x))
                / np.linalg.norm(b))
    assert true_rel > 1e-5  # vs the requested 1e-8: wrong answer
    # the gap audit SAW the drift (evidence) but could not act on it
    assert s.stats.health["gap_max"] > 1e-7


@pytest.mark.parametrize("pipelined", [False, True])
def test_abft_dist8(system, prob8, tmp_path, pipelined):
    """Mesh twin: the checksum test rides ONE fused psum, the gap is
    replicated, and the rollback restores the agreed snapshot."""
    csr, _, b = system
    spec = faults.parse_fault_spec("sdc:flip@9")
    hs = health.make_spec(every=5, abft=True)
    with faults.injected(spec):
        s = DistCGSolver(prob8, pipelined=pipelined, health=hs,
                         recovery=RecoveryPolicy(),
                         ckpt=CheckpointConfig(path=str(tmp_path / "ck"),
                                               every=8))
        x = s.solve(b, criteria=CRIT)
    assert s.stats.health["abft"]["ntrips"] >= 1
    assert s.stats.nrollbacks == 1
    assert np.linalg.norm(b - csr @ x) / np.linalg.norm(b) < 1e-7


def test_abft_spec_validation():
    with pytest.raises(ValueError, match="audit cadence"):
        health.HealthSpec(abft=True)
    with pytest.raises(ValueError, match="abft_threshold needs abft"):
        health.HealthSpec(every=4, abft_threshold=1e-3)
    spec = health.make_spec(every=4, abft=True)
    assert spec.arms_detect  # an ABFT trip must be able to exit the loop
    assert "abft" in str(spec)


# -- the rollback rung in the recovery ladder ----------------------------

def test_rollback_rung_ordering():
    """on_rollback spends its OWN budget (max_rollbacks), leaves the
    restart budget untouched, and refuses once exhausted -- the caller
    then falls through to on_breakdown's restart rung."""
    st = SolverStats(unknowns=8)
    drv = RecoveryDriver(RecoveryPolicy(max_restarts=2, max_rollbacks=1),
                         st, "test")
    drv.note_breakdown(10)
    assert st.nbreakdowns == 1
    assert drv.on_rollback(10, 8) is True
    assert st.nrollbacks == 1 and st.nrestarts == 0
    # budget exhausted: the second breakdown falls to the restart rung
    drv.note_breakdown(12)
    assert drv.on_rollback(12, 8) is False
    assert drv.on_breakdown(12, noted=True) is True
    assert st.nrestarts == 1 and st.nbreakdowns == 2
    # rollbacks disabled entirely
    drv0 = RecoveryDriver(RecoveryPolicy(max_rollbacks=0),
                          SolverStats(unknowns=8), "test")
    assert drv0.on_rollback(5, 0) is False


def test_crash_refuses_without_ckpt(system):
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    with faults.injected(faults.parse_fault_spec("crash:exit@5")):
        with pytest.raises(AcgError, match="crash:exit"):
            JaxCGSolver(A).solve(b, criteria=CRIT)
        with pytest.raises(AcgError, match="crash:exit"):
            HostCGSolver(csr).solve(b, criteria=CRIT)


def test_fault_spec_parsing_new_sites():
    s = faults.parse_fault_spec("sdc:flip@7")
    assert s.site == "sdc" and s.mode == "flip" and s.iteration == 7
    assert s.device_site
    c = faults.parse_fault_spec("crash:exit@20")
    assert c.site == "crash" and c.iteration == 20
    assert not c.device_site
    with pytest.raises(ValueError):
        faults.parse_fault_spec("crash:exit")     # needs @K
    with pytest.raises(ValueError):
        faults.parse_fault_spec("sdc:nan@7")      # flip only
    with pytest.raises(ValueError):
        faults.parse_fault_spec("crash:boom@7")


def test_maybe_crash_crossing_semantics():
    """crash:exit fires when the chunk CROSSES K -- a resumed solve
    whose snapshot already lies at-or-past K must not re-kill itself."""
    calls = []
    with faults.injected(faults.parse_fault_spec("crash:exit@20")):
        orig = os._exit
        os._exit = lambda code: calls.append(code)
        try:
            faults.maybe_crash(0, 16)    # not yet crossed
            assert calls == []
            faults.maybe_crash(24, 32)   # resumed past K: no re-fire
            assert calls == []
            faults.maybe_crash(16, 24)   # crossing: fires
            assert calls == [94]
        finally:
            os._exit = orig


# -- CLI end-to-end: crash at K, then --resume ---------------------------

def test_cli_crash_then_resume(tmp_path):
    """The acceptance flow on the single-device tier: kill a solve
    mid-flight via crash:exit@K (exit 94), relaunch with --resume,
    converge with total iterations within 10% of uninterrupted."""
    ck = str(tmp_path / "ck")
    base = ["gen:poisson2d:24", "--manufactured-solution", "--dtype",
            "f32", "--comm", "none", "--max-iterations", "500",
            "--residual-rtol", "1e-5", "--warmup", "0", "--quiet"]
    r0 = run_cli(base + ["--stats-json", str(tmp_path / "ref.json")])
    assert r0.returncode == 0, r0.stderr
    ref = json.load(open(tmp_path / "ref.json"))["stats"]

    r1 = run_cli(base + ["--ckpt", ck, "--ckpt-every", "8",
                         "--fault-inject", "crash:exit@20"])
    assert r1.returncode == 94, (r1.returncode, r1.stderr)
    assert os.path.exists(ck)

    r2 = run_cli(base + ["--resume", ck,
                         "--stats-json", str(tmp_path / "res.json")])
    assert r2.returncode == 0, r2.stderr
    doc = json.load(open(tmp_path / "res.json"))
    st = doc["stats"]
    assert st["converged"] is True
    resumed_from = st["ckpt"]["resumed_from"]
    total = resumed_from + st["niterations"]
    assert abs(total - ref["niterations"]) <= 0.1 * ref["niterations"]
    assert doc["schema"] == "acg-tpu-stats/12"
    # the resume event is in the structured sink
    assert any(e["kind"] == "resume" for e in st["events"])


def test_cli_crash_then_resume_dist8(tmp_path):
    """The 8-part mesh twin of the crash/resume acceptance flow."""
    ck = str(tmp_path / "ck")
    base = ["gen:poisson2d:20", "--manufactured-solution", "--nparts",
            "8", "--max-iterations", "500", "--residual-rtol", "1e-8",
            "--warmup", "0", "--quiet"]
    r0 = run_cli(base + ["--stats-json", str(tmp_path / "ref.json")])
    assert r0.returncode == 0, r0.stderr
    ref = json.load(open(tmp_path / "ref.json"))["stats"]

    r1 = run_cli(base + ["--ckpt", ck, "--ckpt-every", "8",
                         "--fault-inject", "crash:exit@20"])
    assert r1.returncode == 94, (r1.returncode, r1.stderr)

    r2 = run_cli(base + ["--resume", ck,
                         "--stats-json", str(tmp_path / "res.json")])
    assert r2.returncode == 0, r2.stderr
    st = json.load(open(tmp_path / "res.json"))["stats"]
    assert st["converged"] is True
    total = st["ckpt"]["resumed_from"] + st["niterations"]
    assert abs(total - ref["niterations"]) <= 0.1 * ref["niterations"]


def test_cli_flag_validation(tmp_path):
    r = run_cli(["gen:poisson2d:12", "--ckpt", str(tmp_path / "c")])
    assert r.returncode != 0 and "--ckpt-every" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--ckpt-every", "8"])
    assert r.returncode != 0 and "--ckpt" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--abft"])
    assert r.returncode != 0 and "--audit-every" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--fault-inject", "crash:exit@5"])
    assert r.returncode != 0 and "crash:exit" in r.stderr
    # a corrupted snapshot refuses BEFORE anything expensive
    bad = tmp_path / "bad"
    bad.write_bytes(b"ACGCKPT1\ngarbage")
    r = run_cli(["gen:poisson2d:12", "--resume", str(bad)])
    assert r.returncode != 0 and "snapshot" in r.stderr
    # --resume under --soak would re-resume every repetition
    ok = tmp_path / "ok"
    save_snapshot(str(ok), {"iteration": 1}, {"x": np.ones(4)})
    r = run_cli(["gen:poisson2d:12", "--resume", str(ok), "--soak", "3"])
    assert r.returncode != 0 and "--soak" in r.stderr


def test_cli_soak_with_ckpt_bills_ckpt_phase(tmp_path):
    """--soak + --ckpt: snapshots carry across the repetitions, the
    serialisation bills to its OWN timings phase, and the latency
    histogram/percentiles describe the solves alone."""
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "200", "--residual-rtol", "1e-6",
                 "--warmup", "0", "--quiet", "--soak", "3",
                 "--ckpt", str(tmp_path / "ck"), "--ckpt-every", "16",
                 "--stats-json", str(tmp_path / "s.json"),
                 "--metrics-file", str(tmp_path / "m.prom")])
    assert r.returncode == 0, r.stderr
    doc = json.load(open(tmp_path / "s.json"))
    st = doc["stats"]
    assert st["soak"]["nsolves"] == 3
    assert st["timings"].get("ckpt", 0) > 0
    assert st["ckpt"]["snapshots"] >= 1
    # the ckpt write seconds live in their OWN histogram, and solve
    # latency percentiles are finite (not polluted into absurdity)
    m = doc["metrics"]
    assert m["acg_ckpt_snapshots_total"]["samples"][0]["value"] >= 1
    prom = open(tmp_path / "m.prom").read()
    assert "acg_ckpt_write_seconds_bucket" in prom


# -- disarmed byte-identity + armed collective pins ----------------------

def test_disarmed_state_io_is_byte_identical(system, prob8):
    """A lowering that never names state_io/carry/k_offset and one that
    passes the disarmed defaults must be the SAME program text --
    single-device and mesh (the --ckpt off = byte-identical pin)."""
    csr, _, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    for pipelined in (False, True):
        s = JaxCGSolver(A, pipelined=pipelined, kernels="xla")
        b_dev = jnp.asarray(b)
        program, base, kwargs, _tr = s._select_program(
            b_dev, jnp.zeros_like(b_dev), CRIT, detect=False, fault=None)
        plain = program.lower(*base, **kwargs).as_text()
        explicit = program.lower(*base, state_io=False, carry=None,
                                 k_offset=None, **kwargs).as_text()
        assert explicit == plain

    for pipelined in (False, True):
        s = DistCGSolver(prob8, pipelined=pipelined)
        dev = s.device_args(b)
        bb, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = dev
        tols = jnp.zeros(4)
        args = (la, ga, sidx, gsrc, gval, scnt, rcnt, bb, x0, tols,
                jnp.int32(5))
        kw = dict(unbounded=True, needs_diff=False)
        plain = s._program.lower(*args, **kw).as_text()
        explicit = s._program.lower(*args, carry=None, k_offset=None,
                                    **kw).as_text()
        assert explicit == plain
        # the state_io chunk program is a DIFFERENT program (it returns
        # the carry) but must add ZERO collectives to the loop
        chunk = s._compile(state_io=True)
        ctxt = chunk.lower(*args, **kw).as_text()
        assert ctxt != plain

        def counts(txt):
            return (len(re.findall(r"all_reduce", txt)),
                    len(re.findall(r"all_to_all", txt)))

        assert counts(ctxt) == counts(plain)


def test_abft_armed_collective_counts(prob8):
    """The ABFT test rides the audit: armed, the dist program gains
    EXACTLY one fused psum (+1 all_reduce) for the 3-scalar checksum
    reduction and one setup SpMV (+1 all_to_all) for the column
    checksum, on top of the audit's own +1/+1."""
    b = np.ones(prob8.n)

    def counts(pipelined, hs):
        s = DistCGSolver(prob8, pipelined=pipelined, health=hs)
        txt = s.lower_solve(b).as_text()
        return (len(re.findall(r"all_reduce", txt)),
                len(re.findall(r"all_to_all", txt)))

    base_c = counts(False, None)
    audit_c = counts(False, health.make_spec(every=4))
    abft_c = counts(False, health.make_spec(every=4, abft=True))
    assert audit_c == (base_c[0] + 1, base_c[1] + 1)
    assert abft_c == (audit_c[0] + 1, audit_c[1] + 1)
    base_p = counts(True, None)
    abft_p = counts(True, health.make_spec(every=4, abft=True))
    assert abft_p == (base_p[0] + 2, base_p[1] + 2)


# -- the deadline heartbeat ----------------------------------------------

class _FakeCoordClient:
    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def key_value_set(self, k, v):
        with self.lock:
            self.kv[k] = v

    def key_value_dir_get(self, prefix):
        with self.lock:
            return [(k, v) for k, v in self.kv.items()
                    if k.startswith(prefix)]


def test_heartbeat_detects_dead_peer():
    from acg_tpu.parallel.erragree import DeadlineHeartbeat

    lost = []
    hb = DeadlineHeartbeat(period=0.05, deadline=0.2,
                           on_lost=lambda p, a: lost.append(p),
                           client=_FakeCoordClient(), nprocs=2, me=0)
    hb.start()
    deadline = time.monotonic() + 5.0
    while not lost and time.monotonic() < deadline:
        time.sleep(0.05)
    hb.stop()
    assert lost and lost[0] == 1


def test_heartbeat_tolerates_healthy_peer():
    from acg_tpu.parallel.erragree import DeadlineHeartbeat

    lost = []
    client = _FakeCoordClient()
    hb = DeadlineHeartbeat(period=0.05, deadline=0.35,
                           on_lost=lambda p, a: lost.append(p),
                           client=client, nprocs=2, me=0)
    hb.start()
    stop = threading.Event()

    def beat():
        i = 0
        while not stop.wait(0.05):
            i += 1
            client.key_value_set(
                f"acg_tpu/heartbeat/{hb._gen}/1/{i}", "1")

    t = threading.Thread(target=beat, daemon=True)
    t.start()
    time.sleep(1.0)
    stop.set()
    hb.stop()
    assert lost == []


def test_heartbeat_validation_and_noop():
    from acg_tpu.parallel.erragree import DeadlineHeartbeat

    with pytest.raises(ValueError):
        DeadlineHeartbeat(period=5.0, deadline=5.0)
    with pytest.raises(ValueError):
        DeadlineHeartbeat(period=0.0, deadline=1.0)
    # single-process: start is a no-op (no thread, no client needed)
    hb = DeadlineHeartbeat(period=1.0, deadline=5.0, nprocs=1, me=0)
    with hb:
        assert hb._thread is None


# -- config validation ---------------------------------------------------

def test_checkpoint_config_validation(system):
    csr, _, _ = system
    with pytest.raises(ValueError, match="snapshot cadence"):
        CheckpointConfig(path="x", every=0)
    with pytest.raises(ValueError, match="snapshot path"):
        CheckpointConfig()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    with pytest.raises(ValueError, match="replace_every"):
        JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.bfloat16),
                    replace_every=10,
                    ckpt=CheckpointConfig(path="x", every=4))
    with pytest.raises(ValueError, match="ckpt must be"):
        JaxCGSolver(A, ckpt="not-a-config")


def test_buildinfo_advertises_survivability():
    r = run_cli(["--buildinfo", "gen:ignored"])
    out = r.stdout
    assert "survivability" in out
    for token in ("--ckpt", "--resume", "--abft", "sdc:flip",
                  "crash:exit", "--heartbeat", "acg-tpu-stats/12"):
        assert token in out, token
