"""Telemetry tier (acg_tpu.telemetry): in-loop convergence ring buffer,
structured stats export, phase timings, and the CLI sinks.

Covers the PR-2 satellite checklist: ring wrap-around beyond the buffer
length, breakdown-early-exit partial windows, and JSONL records
round-tripping through ``SolverStats.to_dict()`` on the 8-device CPU
mesh (tests/conftest.py provisions it)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from acg_tpu import telemetry
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(12)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def _jax_solver(csr, **kw):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    return JaxCGSolver(A, **kw)


# -- ring-buffer semantics ----------------------------------------------

def test_ring_wraparound(csr):
    """A solve longer than the ring keeps exactly the trailing window,
    with contiguous ascending iteration numbers."""
    s = _jax_solver(csr, trace=8)
    s.solve(np.ones(csr.shape[0]), criteria=StoppingCriteria(maxits=30),
            raise_on_divergence=False)
    t = s.last_trace
    assert t is not None and t.wrapped and t.capacity == 8
    assert t.niterations == 30
    np.testing.assert_array_equal(t.iterations, np.arange(22, 30))
    assert np.isfinite(t.records).all()
    # rnrm2 is stored squared on device and rooted once on fetch: the
    # final record must equal the stats block's residual exactly
    assert t.records[-1, 0] == pytest.approx(s.stats.rnrm2, rel=0, abs=0)


def test_ring_no_wrap_short_solve(csr):
    s = _jax_solver(csr, trace=256)
    s.solve(np.ones(csr.shape[0]),
            criteria=StoppingCriteria(maxits=500, residual_rtol=1e-10))
    t = s.last_trace
    assert not t.wrapped
    assert t.iterations[0] == 0
    assert t.niterations == s.stats.niterations == len(t.records)
    # residual history is the convergence evidence: it must reach the
    # tolerance the solve reported
    assert t.records[-1, 0] <= 1e-10 * t.records[0, 0] * 10


def test_pipelined_trace_matches_stats(csr):
    s = _jax_solver(csr, pipelined=True, trace=512)
    s.solve(np.ones(csr.shape[0]),
            criteria=StoppingCriteria(maxits=500, residual_rtol=1e-9))
    t = s.last_trace
    assert t.solver == "cg-pipelined"
    assert t.niterations == s.stats.niterations
    # the pipelined record carries the one-iteration-stale gamma; the
    # window must still be a decreasing-to-tolerance residual history
    assert t.records[-1, 0] < t.records[0, 0]


def test_breakdown_partial_window(csr):
    """A breakdown early-exit leaves a partial window whose last record
    shows the poisoned scalar (the evidence the recovery log quotes)."""
    from acg_tpu import faults
    from acg_tpu.errors import BreakdownError

    s = _jax_solver(csr, trace=16)
    with faults.injected("dot:nan@3"):
        with pytest.raises(BreakdownError):
            s.solve(np.ones(csr.shape[0]),
                    criteria=StoppingCriteria(maxits=50))
    t = s.last_trace
    assert t is not None and not t.wrapped
    # the loop exits on the iteration after the poison lands (the
    # deferred-bad flag); the window is partial, not the full maxits
    assert 1 <= t.niterations <= 6
    assert not np.isfinite(t.records[-1]).all()
    # the recovery driver logged the trailing window next to the event
    assert any("trailing residual window" in ev
               for ev in s.stats.recovery_log)
    assert any(ev["kind"] == "breakdown" for ev in s.stats.events)
    assert any(ev["kind"] == "fault-armed" for ev in s.stats.events)


def test_host_eager_trace_matches_device(csr):
    """The host solver's eager recorder produces the same trajectory as
    the compiled ring (f64 both sides, same recurrences)."""
    from acg_tpu.solvers.host_cg import HostCGSolver

    n = csr.shape[0]
    hs = HostCGSolver(csr, trace=64)
    hs.solve(np.ones(n), criteria=StoppingCriteria(maxits=40),
             raise_on_divergence=False)
    ds = _jax_solver(csr, trace=64)
    ds.solve(np.ones(n), criteria=StoppingCriteria(maxits=40),
             raise_on_divergence=False)
    ht, dt = hs.last_trace, ds.last_trace
    assert ht.niterations == dt.niterations
    m = min(10, len(ht.records))
    np.testing.assert_allclose(ht.records[:m, 0], dt.records[:m, 0],
                               rtol=1e-8)


def test_telemetry_refused_on_replacement_tier(csr):
    import jax.numpy as jnp

    from acg_tpu.errors import AcgError
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    s = JaxCGSolver(A, replace_every=10, trace=16)
    with pytest.raises(AcgError, match="telemetry"):
        s.solve(np.ones(csr.shape[0]),
                criteria=StoppingCriteria(maxits=20))


# -- distributed ring + JSONL round trip (8-device CPU mesh) ------------

def test_dist_trace_jsonl_roundtrip(csr, tmp_path):
    """The acceptance path: a dist solve over the 8-device mesh, the
    JSONL sink, and the records round-tripping through
    SolverStats.to_dict()."""
    import jax.numpy as jnp

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    part = partition_rows(csr, 8, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s = DistCGSolver(prob, trace=64)
    s.solve(np.ones(csr.shape[0]),
            criteria=StoppingCriteria(maxits=300, residual_rtol=1e-9))
    t = s.last_trace
    assert t.solver == "dist-cg"
    # final trace residual == stats block residual (same psum'd gamma)
    assert t.records[-1, 0] == pytest.approx(s.stats.rnrm2, rel=0, abs=0)

    path = tmp_path / "conv.jsonl"
    t.write_jsonl(path)
    meta, records = telemetry.read_convergence_log(path)
    assert meta["schema"] == telemetry.CONVERGENCE_SCHEMA
    assert meta["niterations"] == s.stats.niterations
    assert not meta["wrapped"]
    # round trip: JSONL data lines == the trace dict inside to_dict()
    d = s.stats.to_dict()
    assert d["trace"]["records"] == records
    assert [r["it"] for r in records] == list(range(len(records)))
    # the whole document is JSON-serialisable (the --stats-json writer)
    json.dumps(telemetry.stats_document(s.stats))


def test_dist_wrap_and_partial_budget(csr):
    """Wrap-around on the mesh: trailing window only, mesh-uniform."""
    import jax.numpy as jnp

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    s = DistCGSolver(prob, trace=8)
    s.solve(np.ones(csr.shape[0]), criteria=StoppingCriteria(maxits=25),
            raise_on_divergence=False)
    t = s.last_trace
    assert t.wrapped and t.niterations == 25
    np.testing.assert_array_equal(t.iterations, np.arange(17, 25))


# -- aggregation / manifest ---------------------------------------------

def test_aggregate_ranks_straggler():
    payloads = [
        {"process": 0, "tsolve": 1.0, "niterations": 10,
         "parts": [{"part": 0, "rows": 100, "nnz": 500,
                    "halo_send_bytes": 80}]},
        {"process": 1, "tsolve": 2.0, "niterations": 10,
         "parts": [{"part": 1, "rows": 300, "nnz": 1500,
                    "halo_send_bytes": 80}]},
    ]
    agg = telemetry.aggregate_ranks(payloads)
    assert agg["solve_time"]["max"] == 2.0
    assert agg["straggler"]["process"] == 1
    assert agg["parts"]["imbalance"]["rows"]["imbalance"] == pytest.approx(
        1.5)
    line = telemetry.format_rank_report(agg)
    assert "straggler: process 1" in line
    # single-process gather degenerates to the local payload
    assert telemetry.gather_rank_stats(payloads[0]) == [payloads[0]]


def test_allgather_blobs_two_process():
    """The cross-rank gather on a real 2-process pod: variable-length
    JSON blobs over the coordination-service KV store (no device
    collective -- works where multiprocess CPU computations do not)."""
    import os
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    code = (
        "import jax, json, sys; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "from acg_tpu.parallel.multihost import initialize; "
        "initialize('localhost:%d', 2, int(sys.argv[1])); "
        "from acg_tpu.parallel.erragree import allgather_blobs; "
        "blobs = allgather_blobs(json.dumps({'p': int(sys.argv[1]), "
        "'pad': 'x' * (10 * (1 + int(sys.argv[1])))}), "
        "tag='test', timeout=60); "
        "print(json.dumps(blobs))" % port)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen([sys.executable, "-c", code, str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for i in range(2)]
    outs = [p.communicate(timeout=120) for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    for out, _ in outs:
        blobs = json.loads(out.strip().splitlines()[-1])
        got = [json.loads(b) for b in blobs]
        assert [g["p"] for g in got] == [0, 1]
        assert len(got[1]["pad"]) == 20  # lengths preserved per rank


def test_run_manifest_fields():
    man = telemetry.run_manifest(matrix="gen:poisson2d:8", nparts=4)
    assert man["schema"] == telemetry.STATS_SCHEMA
    assert man["matrix"] == "gen:poisson2d:8"
    assert "jax" in man and "backend" in man
    assert man["backend"]["ndevices"] >= 1


def test_phase_timer_order_and_consume():
    from acg_tpu.solvers.stats import SolverStats

    timer = telemetry.PhaseTimer()
    timer.add("solve", 1.0)
    timer.add("ingest", 0.5)
    st = SolverStats()
    st.timings["transfer"] = 0.25
    timer.merge_into(st.timings)
    assert list(st.timings) == ["ingest", "transfer", "solve"]
    # consumed: a second merge adds nothing
    timer.merge_into(st.timings)
    assert st.timings["solve"] == 1.0
    text = st.fwrite()
    assert "timings:" in text
    assert "  ingest: 0.500000 seconds" in text


# -- CLI sinks (subprocess, 8-device CPU mesh) --------------------------

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, **kw):
    import os

    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


def test_cli_telemetry_dist_solve(tmp_path):
    """The acceptance criterion end-to-end: --convergence-log +
    --stats-json on a dist solve over the 8-device CPU mesh; schema-
    valid output whose final residual matches the stats block, and the
    reference-format stats lines intact."""
    conv = tmp_path / "conv.jsonl"
    stats = tmp_path / "stats.json"
    r = run_cli(["gen:poisson2d:24", "--nparts", "8",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--progress", "20",
                 "--convergence-log", str(conv),
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    # the reference-format block is intact (grep contract) and the
    # heartbeat fired from inside the compiled loop
    assert "total solver time: " in r.stderr
    assert "iteration 20: residual 2-norm" in r.stderr
    assert "timings:" in r.stderr

    meta, records = telemetry.read_convergence_log(conv)
    assert meta["schema"] == telemetry.CONVERGENCE_SCHEMA
    doc = json.loads(stats.read_text())
    assert doc["schema"] == telemetry.STATS_SCHEMA
    st = doc["stats"]
    block_rnrm2 = float([l for l in r.stderr.splitlines()
                         if l.startswith("  residual 2-norm:")][0]
                        .split(":")[1])
    assert st["rnrm2"] == pytest.approx(block_rnrm2, rel=1e-12)
    assert records[-1]["rnrm2"] == pytest.approx(block_rnrm2, rel=1e-12)
    assert st["trace"]["records"] == records
    # manifest carries the run's identity + partition/halo sizing
    man = doc["manifest"]
    assert man["matrix"] == "gen:poisson2d:24"
    assert man["partition"]["nparts"] == 8
    assert man["partition"]["local_format"]
    # phase timings include the pipeline stages
    for phase in ("ingest", "partition", "transfer", "solve"):
        assert phase in st["timings"], phase
    # single-controller aggregation still reports per-part imbalance
    assert doc["ranks"]["aggregate"]["parts"]["count"] == 8


def test_cli_telemetry_single_device(tmp_path):
    conv = tmp_path / "conv.jsonl"
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "200", "--residual-rtol", "1e-8",
                 "--warmup", "1", "--quiet",
                 "--telemetry-window", "16",
                 "--convergence-log", str(conv)])
    assert r.returncode == 0, r.stderr
    meta, records = telemetry.read_convergence_log(conv)
    assert meta["capacity"] == 16
    if meta["wrapped"]:
        assert meta["truncated_before"] == meta["first_iteration"]
    assert records, "no records written"


def test_cli_stats_json_host_solver(tmp_path):
    """--stats-json works for the host oracle too (eager recorder)."""
    stats = tmp_path / "stats.json"
    conv = tmp_path / "conv.jsonl"
    r = run_cli(["gen:poisson2d:12", "--solver", "host", "--comm",
                 "none", "--max-iterations", "200", "--residual-rtol",
                 "1e-8", "--quiet", "--stats-json", str(stats),
                 "--convergence-log", str(conv)])
    assert r.returncode == 0, r.stderr
    doc = json.loads(stats.read_text())
    assert doc["stats"]["converged"] is True
    assert doc["stats"]["trace"]["records"]
    meta, records = telemetry.read_convergence_log(conv)
    assert len(records) == doc["stats"]["niterations"]


def test_cli_convergence_log_on_failed_solve(tmp_path):
    """The log is most needed when the solve fails: a not-converged
    exit still writes the window."""
    conv = tmp_path / "conv.jsonl"
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "3", "--residual-rtol", "1e-14",
                 "--warmup", "0", "--quiet",
                 "--convergence-log", str(conv)])
    assert r.returncode == 1
    meta, records = telemetry.read_convergence_log(conv)
    assert meta["niterations"] == 3 and len(records) == 3


def test_cli_buildinfo_advertises_telemetry():
    r = run_cli(["--buildinfo"])
    assert r.returncode == 0, r.stderr
    assert "--convergence-log" in r.stdout
    assert "--stats-json" in r.stdout
    assert telemetry.STATS_SCHEMA in r.stdout


def test_read_convergence_log_truncated_tail(tmp_path):
    """A SIGTERM landing mid-write leaves a half JSON line at the end;
    the reader must return the parseable prefix with a truncated
    marker instead of raising (PR-4 satellite)."""
    t = telemetry.ConvergenceTrace(
        capacity=8, niterations=8,
        records=np.column_stack([np.logspace(0, -7, 8),
                                 np.ones(8), np.ones(8), np.ones(8)]),
        iterations=np.arange(8), wrapped=False)
    path = tmp_path / "c.jsonl"
    t.write_jsonl(path)
    whole_meta, whole_records = telemetry.read_convergence_log(path)
    text = path.read_text()
    # chop mid-way through the LAST record line
    path.write_text(text[:text.rstrip().rfind('"')])
    meta, records = telemetry.read_convergence_log(path)
    assert meta["truncated"] is True
    assert records == whole_records[:-1]
    assert meta["schema"] == whole_meta["schema"]


def test_read_convergence_log_mid_corruption_still_raises(tmp_path):
    """A malformed line FOLLOWED by valid JSON is corruption, not a
    truncated tail -- that must still raise."""
    path = tmp_path / "c.jsonl"
    path.write_text('{"meta": true, "schema": "x"}\n'
                    '{"it": 0, "rnrm2": 1.0\n'
                    '{"it": 1, "rnrm2": 0.5}\n')
    with pytest.raises(ValueError):
        telemetry.read_convergence_log(path)


def test_load_cases_tolerates_truncated_tail(tmp_path):
    """bench_diff's reader keeps the parseable prefix of a capture
    whose final JSONL line was cut mid-write."""
    from acg_tpu.perfmodel import load_cases

    path = tmp_path / "cap.jsonl"
    good = json.dumps({"metric": "case_a", "value": 10.0})
    path.write_text(good + "\n"
                    + json.dumps({"metric": "case_b",
                                  "value": 20.0})[:17] + "\n")
    cases = load_cases(path)
    assert cases == {"case_a": 10.0}


def test_plot_convergence_sparkline(tmp_path):
    """The tooling satellite: text fallback renders any log."""
    import os

    t = telemetry.ConvergenceTrace(
        capacity=8, niterations=12,
        records=np.column_stack([np.logspace(0, -7, 8),
                                 np.ones(8), np.ones(8), np.ones(8)]),
        iterations=np.arange(4, 12), wrapped=True)
    path = tmp_path / "c.jsonl"
    t.write_jsonl(path)
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "plot_convergence.py")
    r = subprocess.run([sys.executable, script, str(path), "--ascii"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "wrapped" in r.stdout and "final" in r.stdout
