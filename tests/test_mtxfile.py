"""Round-trip and format tests for Matrix Market I/O (reference: mtxfile.c)."""

import gzip

import numpy as np
import pytest

from acg_tpu.errors import AcgError
from acg_tpu.io.generators import poisson2d_coo, poisson3d_coo, poisson_mtx
from acg_tpu.io.mtxfile import MtxFile, read_mtx, write_mtx, vector_mtx


def small_mtx():
    return MtxFile(object="matrix", format="coordinate", field="real",
                   symmetry="general", nrows=3, ncols=3, nnz=5,
                   rowidx=np.array([0, 0, 1, 2, 2]),
                   colidx=np.array([0, 1, 1, 0, 2]),
                   vals=np.array([1.0, 2.5, -3.0, 4.0, 1e-12]))


def test_text_roundtrip(tmp_path):
    m = small_mtx()
    path = tmp_path / "a.mtx"
    write_mtx(path, m)
    m2 = read_mtx(path)
    assert (m2.nrows, m2.ncols, m2.nnz) == (3, 3, 5)
    assert m2.symmetry == "general"
    np.testing.assert_array_equal(m2.rowidx, m.rowidx)
    np.testing.assert_array_equal(m2.colidx, m.colidx)
    np.testing.assert_allclose(m2.vals, m.vals, rtol=0, atol=0)


def test_binary_roundtrip(tmp_path):
    m = small_mtx()
    path = tmp_path / "a.bin.mtx"
    write_mtx(path, m, binary=True)
    m2 = read_mtx(path, binary=True)
    np.testing.assert_array_equal(m2.rowidx, m.rowidx)
    np.testing.assert_array_equal(m2.colidx, m.colidx)
    np.testing.assert_array_equal(m2.vals, m.vals)  # bitwise for binary


def test_binary_layout_matches_reference(tmp_path):
    """Data section must be rowidx[],colidx[],vals[] as raw int64/double,
    1-based (mtxfile.c:1492-1497), so reference binaries interoperate."""
    m = small_mtx()
    path = tmp_path / "a.bin.mtx"
    write_mtx(path, m, binary=True)
    raw = path.read_bytes()
    header_end = raw.index(b"3 3 5\n") + len(b"3 3 5\n")
    data = raw[header_end:]
    assert len(data) == 5 * 8 * 3
    rows = np.frombuffer(data[:40], dtype=np.int64)
    np.testing.assert_array_equal(rows, m.rowidx + 1)
    vals = np.frombuffer(data[80:], dtype=np.float64)
    np.testing.assert_array_equal(vals, m.vals)


def test_gzip_autodetect(tmp_path):
    m = small_mtx()
    plain = tmp_path / "a.mtx"
    write_mtx(plain, m)
    gz = tmp_path / "a.mtx.gz"
    gz.write_bytes(gzip.compress(plain.read_bytes()))
    m2 = read_mtx(gz)
    np.testing.assert_allclose(m2.vals, m.vals)


def test_pattern_and_vector(tmp_path):
    m = MtxFile(object="matrix", format="coordinate", field="pattern",
                symmetry="general", nrows=2, ncols=2, nnz=2,
                rowidx=np.array([0, 1]), colidx=np.array([1, 0]))
    p = tmp_path / "p.mtx"
    write_mtx(p, m)
    m2 = read_mtx(p)
    assert m2.field == "pattern" and m2.vals is None
    r, c, v = m2.to_coo()
    np.testing.assert_array_equal(v, [1.0, 1.0])

    x = np.linspace(0, 1, 7)
    vpath = tmp_path / "x.mtx"
    write_mtx(vpath, vector_mtx(x))
    x2 = read_mtx(vpath)
    assert x2.format == "array"
    np.testing.assert_allclose(x2.vals, x, atol=1e-16)


def test_scipy_interop(tmp_path):
    """Files written by scipy.io.mmwrite (as the reference's generator does)
    must read back identically."""
    import scipy.io as sio
    import scipy.sparse as sp
    rng = np.random.default_rng(0)
    A = sp.random(10, 10, density=0.3, random_state=rng, format="coo")
    A = (A + A.T).tocoo()  # symmetric; mmwrite will detect and fold
    path = tmp_path / "s.mtx"
    sio.mmwrite(str(path), A)
    m = read_mtx(path)
    from acg_tpu.matrix import SymCsrMatrix
    ours = SymCsrMatrix.from_mtx(m).to_csr().toarray()
    np.testing.assert_allclose(ours, A.toarray(), rtol=1e-14)


def test_bad_header(tmp_path):
    path = tmp_path / "bad.mtx"
    path.write_text("%%NotMatrixMarket matrix coordinate real general\n1 1 0\n")
    with pytest.raises(AcgError):
        read_mtx(path)


def test_index_bounds(tmp_path):
    path = tmp_path / "oob.mtx"
    path.write_text("%%MatrixMarket matrix coordinate real general\n2 2 1\n3 1 1.0\n")
    with pytest.raises(AcgError):
        read_mtx(path)


def test_poisson_generators():
    r, c, v, N = poisson2d_coo(4)
    assert N == 16
    import scipy.sparse as sp
    A = sp.coo_matrix((v, (r, c)), shape=(N, N)).toarray()
    np.testing.assert_allclose(A, A.T)
    # row sums: interior rows sum to 0, boundary rows positive
    assert A.sum() > 0
    assert np.linalg.eigvalsh(A).min() > 0  # SPD

    r, c, v, N = poisson3d_coo(3)
    assert N == 27
    A = sp.coo_matrix((v, (r, c)), shape=(N, N)).toarray()
    np.testing.assert_allclose(A, A.T)
    assert np.linalg.eigvalsh(A).min() > 0

    m = poisson_mtx(4, dim=2)
    assert m.symmetry == "symmetric"
    assert (m.rowidx >= m.colidx).all()
