"""Numerical-health tier (acg_tpu.health): in-loop true-residual
audits, Lanczos spectrum estimation, accuracy gates, and the
surrounding surfaces (telemetry audit column, metrics, soak, CLI,
bench_diff satellite).

The PR-6 acceptance in test form: the fp32 pipelined solver on the
ill-conditioned aniso-Poisson family shows a measurably larger
residual gap than classic CG at the same budget (ground truth from
f64 host arithmetic), ``--on-gap replace`` recovers the solve to the
requested tolerance through the recovery driver, kappa estimates from
the recorded (alpha, beta) land within a documented band of
``scipy.sparse.linalg.eigsh``, and single vs 8-part audit records
agree.
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from acg_tpu import health, telemetry
from acg_tpu.io.generators import aniso_poisson2d_coo, poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.stats import SolverStats, StoppingCriteria

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def aniso_csr():
    """The ill-conditioned SPD family (diagonal varies ~1/eps)."""
    r, c, v, N = aniso_poisson2d_coo(24, 0.1)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


@pytest.fixture(scope="module")
def poisson_csr():
    r, c, v, N = poisson2d_coo(16)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def _jax_solver(csr, dtype=None, **kw):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A = device_matrix_from_csr(csr, dtype=dtype or jnp.float64)
    return JaxCGSolver(A, kernels="xla", **kw)


# -- spec semantics -------------------------------------------------------

def test_spec_validation():
    assert health.make_spec() is None
    assert health.make_spec(every=0, stall_window=0) is None
    spec = health.make_spec(every=5)
    assert spec.armed and not spec.arms_detect
    assert health.make_spec(stall_window=3).arms_detect
    assert health.make_spec(every=5, threshold=1e-4,
                            action="replace").arms_detect
    with pytest.raises(ValueError, match="on-gap action"):
        health.make_spec(every=5, action="replace")  # no threshold
    with pytest.raises(ValueError, match="on-gap action"):
        health.make_spec(threshold=1e-4, action="abort")  # no audit
    with pytest.raises(ValueError, match="unknown on-gap"):
        health.make_spec(every=5, action="explode")
    with pytest.raises(ValueError):
        health.make_spec(every=-1)


def test_solver_refusals(poisson_csr):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A16 = device_matrix_from_csr(poisson_csr, dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="replace_every"):
        JaxCGSolver(A16, replace_every=10,
                    health=health.make_spec(every=5))
    with pytest.raises(ValueError, match="HealthSpec"):
        _jax_solver(poisson_csr, health="audit-every=5")


# -- device-helper semantics (stall counter + trip) -----------------------

def test_stall_and_trip_primitives():
    import jax
    import jax.numpy as jnp

    spec = health.HealthSpec(every=2, threshold=0.5, action="replace",
                             stall_window=3)

    @jax.jit
    def run(progress_seq):
        aud = health.audit_init(jnp.float32)

        def body(i, aud):
            return health.stall_update(aud, spec, progress_seq[i])

        return jax.lax.fori_loop(0, progress_seq.shape[0], body, aud)

    # decreasing -> counter stays 0; three flat iterations trip
    aud = run(jnp.asarray([True, True, False, False, False]))
    assert float(aud[health.AUD_STALL]) == 3.0
    assert bool(health.trip(aud, spec))
    aud = run(jnp.asarray([False, False, True, False, False]))
    assert float(aud[health.AUD_STALL]) == 2.0
    assert not bool(health.trip(aud, spec))
    # a gap past the threshold trips regardless of the stall counter
    aud2 = aud.at[health.AUD_GAP].set(0.6)
    assert bool(health.trip(aud2, spec))
    # NaN gap (never audited) never trips
    assert not bool(health.trip(health.audit_init(jnp.float32), spec))


# -- the audit oracle: pipelined drift vs classic, f64 ground truth -------

def test_fp32_pipelined_gap_exceeds_classic(aniso_csr):
    """The communication-hiding trade-off, measured: at the same f32
    budget on the ill-conditioned family, the pipelined recurrences
    drift measurably further from b - Ax than classic CG's -- both by
    the in-loop audit and by independent f64 host arithmetic."""
    import jax.numpy as jnp

    n = aniso_csr.shape[0]
    b = np.ones(n)
    gaps, true_gaps = {}, {}
    for pipelined in (False, True):
        s = _jax_solver(aniso_csr, dtype=jnp.float32,
                        pipelined=pipelined,
                        health=health.make_spec(every=10))
        x = s.solve(b, criteria=StoppingCriteria(maxits=400,
                                                 residual_rtol=1e-6),
                    raise_on_divergence=False)
        gaps[pipelined] = s.stats.health["gap_last"]
        # f64 ground truth: the reported recurrence residual vs the
        # true one -- |  ||b - Ax||_f64 - rnrm2_reported | / ||b|| is a
        # lower bound on ||r_true - r_rec|| / ||b||
        rtrue = float(np.linalg.norm(b - aniso_csr
                                     @ np.asarray(x, np.float64)))
        true_gaps[pipelined] = abs(rtrue - s.stats.rnrm2) / np.linalg.norm(b)
    assert gaps[True] > 5.0 * gaps[False], (gaps, true_gaps)
    assert true_gaps[True] > 5.0 * true_gaps[False], (gaps, true_gaps)
    # the in-loop audit must AGREE with the oracle: the measured drift
    # cannot exceed what the audit reported (plus f32 noise)
    assert true_gaps[True] <= 2.0 * gaps[True] + 1e-6


def test_on_gap_replace_recovers_to_tolerance(aniso_csr):
    """--on-gap replace: the gap trip exits through the breakdown path
    and the recovery driver's restart recomputes the true residual (a
    residual-replacement restart) -- the f32 pipelined solve then
    reaches the tolerance its ungated twin misses by an order of
    magnitude (f64 host arithmetic as the judge)."""
    import jax.numpy as jnp

    from acg_tpu.solvers.resilience import RecoveryPolicy

    n = aniso_csr.shape[0]
    b = np.ones(n)
    rtol = 1e-5
    crit = StoppingCriteria(maxits=4000, residual_rtol=rtol)
    bnrm = float(np.linalg.norm(b))

    def true_rel(x):
        return float(np.linalg.norm(
            b - aniso_csr @ np.asarray(x, np.float64))) / bnrm

    ungated = _jax_solver(aniso_csr, dtype=jnp.float32, pipelined=True)
    x0 = ungated.solve(b, criteria=crit, raise_on_divergence=False)

    gated = _jax_solver(
        aniso_csr, dtype=jnp.float32, pipelined=True,
        recovery=RecoveryPolicy(max_restarts=25, fallback_host=False),
        health=health.make_spec(every=10, threshold=1e-4,
                                action="replace"))
    x1 = gated.solve(b, criteria=crit, raise_on_divergence=False)
    assert gated.stats.converged
    assert gated.stats.nrestarts >= 1
    assert any(ev["kind"] == "accuracy_degraded"
               for ev in gated.stats.events)
    # the health summary MERGES across restart attempts: the recovered
    # solve still shows the worst gap of the whole solve (a converged
    # final attempt by itself could never exceed the threshold -- it
    # would have tripped), and naudits accumulates
    assert gated.stats.health["gap_max"] > 1e-4
    assert gated.stats.health["naudits"] >= gated.stats.nrestarts
    # recovered: the TRUE residual lands within the requested tolerance
    # plus the gap threshold's drift allowance...
    assert true_rel(x1) <= rtol + 2e-4
    # ...and beats the ungated solve decisively
    assert true_rel(x1) < 0.2 * true_rel(x0), (true_rel(x1),
                                               true_rel(x0))


def test_on_gap_abort_raises(aniso_csr):
    import jax.numpy as jnp

    from acg_tpu.errors import BreakdownError

    s = _jax_solver(aniso_csr, dtype=jnp.float32, pipelined=True,
                    health=health.make_spec(every=10, threshold=1e-4,
                                            action="abort"))
    # the raise names the REAL cause (the accuracy gate), not the
    # generic arithmetic-breakdown diagnosis -- host-tier parity
    with pytest.raises(BreakdownError, match="true-residual gap"):
        s.solve(np.ones(aniso_csr.shape[0]),
                criteria=StoppingCriteria(maxits=4000,
                                          residual_rtol=1e-6))
    assert any(ev["kind"] == "accuracy_degraded"
               for ev in s.stats.events)


def test_on_gap_abort_ignores_restart_budget(aniso_csr):
    """abort must stay a hard stop even when a recovery policy is
    armed: the restart budget belongs to replace, and silently
    restarting would turn the abort gate the user asked for into
    replace (host-tier parity -- host_cg aborts unconditionally)."""
    import jax.numpy as jnp

    from acg_tpu.errors import BreakdownError
    from acg_tpu.solvers.resilience import RecoveryPolicy

    s = _jax_solver(aniso_csr, dtype=jnp.float32, pipelined=True,
                    recovery=RecoveryPolicy(max_restarts=25,
                                            fallback_host=False),
                    health=health.make_spec(every=10, threshold=1e-4,
                                            action="abort"))
    with pytest.raises(BreakdownError, match=r"--on-gap abort"):
        s.solve(np.ones(aniso_csr.shape[0]),
                criteria=StoppingCriteria(maxits=4000,
                                          residual_rtol=1e-6))
    assert s.stats.nrestarts == 0, s.stats.recovery_log
    assert s.stats.converged is False


# -- Lanczos spectrum estimation ------------------------------------------

def test_kappa_estimate_vs_eigsh(aniso_csr):
    """kappa from the Lanczos tridiagonal of a traced f64 solve lands
    within a documented band of scipy's exact extremal eigenvalues on
    the generated SPD family.  Ritz values converge from INSIDE the
    spectrum, so the estimate is a lower bound that tightens with the
    iteration count -- the band pins [0.5x, 1.05x]."""
    from scipy.sparse.linalg import eigsh

    n = aniso_csr.shape[0]
    s = _jax_solver(aniso_csr, trace=4096)
    s.solve(np.ones(n), criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-12),
            raise_on_divergence=False)
    est = health.spectrum_estimate(s.last_trace)
    assert est is not None and est["kappa"] is not None
    lmax_true = float(eigsh(aniso_csr, k=1, which="LA",
                            return_eigenvectors=False)[0])
    lmin_true = float(eigsh(aniso_csr, k=1, which="SA",
                            return_eigenvectors=False)[0])
    kappa_true = lmax_true / lmin_true
    assert 0.5 * kappa_true <= est["kappa"] <= 1.05 * kappa_true, (
        est, kappa_true)
    assert est["lambda_max"] <= 1.05 * lmax_true
    assert est["lambda_min"] >= 0.95 * lmin_true


def test_kappa_pipelined_trace_aligns_with_classic(poisson_csr):
    """The pipelined trace records beta shifted by one iteration (the
    GV recurrence computes it at the top of the loop); the re-aligned
    Lanczos build must land on the same kappa as the classic trace."""
    ests = {}
    for pipelined in (False, True):
        s = _jax_solver(poisson_csr, pipelined=pipelined, trace=2048)
        s.solve(np.ones(poisson_csr.shape[0]),
                criteria=StoppingCriteria(maxits=500,
                                          residual_rtol=1e-11))
        ests[pipelined] = health.spectrum_estimate(s.last_trace)
    k0, k1 = ests[False]["kappa"], ests[True]["kappa"]
    assert k0 and k1
    assert abs(k1 - k0) / k0 < 0.2, (k0, k1)


def test_predicted_iterations_bound(poisson_csr):
    """The CG bound is an upper bound on a worst-case spectrum: the
    measured f64 iteration count must come in at or under it."""
    rtol = 1e-10
    s = _jax_solver(poisson_csr, trace=2048)
    s.solve(np.ones(poisson_csr.shape[0]),
            criteria=StoppingCriteria(maxits=2000, residual_rtol=rtol))
    rep = health.convergence_report(s.last_trace, s.stats.niterations,
                                    rtol)
    assert rep["predicted_iterations"] >= rep["measured_iterations"]
    # monotonicity sanity of the bound itself
    assert (health.predicted_iterations(1e6, 1e-9)
            > health.predicted_iterations(1e3, 1e-9)
            > health.predicted_iterations(1e3, 1e-3))
    assert health.predicted_iterations(0, 1e-9) is None
    assert health.predicted_iterations(100.0, 0.0) is None


def test_lanczos_wrapped_window_and_poisoned_tail():
    """A wrapped ring (window_start > 0) drops the boundary row whose
    beta_{k-1}/alpha_{k-1} predates the window; a poisoned tail (NaN
    alpha, the breakdown evidence) is trimmed, not propagated."""
    alphas = np.full(20, 0.5)
    betas = np.full(20, 0.25)
    d, e = health.lanczos_tridiagonal(alphas, betas, window_start=7)
    assert d is not None and d.size == 19  # leading row dropped
    alphas[-3:] = np.nan
    d2, e2 = health.lanczos_tridiagonal(alphas, betas, window_start=0)
    assert d2.size >= 16 and np.isfinite(d2).all()
    assert np.isfinite(e2).all()
    # too-short windows refuse
    assert health.lanczos_tridiagonal([0.5], [0.1]) == (None, None)


# -- dist parity: single vs 8-part audit records --------------------------

def test_dist_audit_parity_single_vs_8part(poisson_csr):
    """The audited dist solve over the 8-device CPU mesh produces the
    SAME audit record as the single-device program: same audit count,
    same audited iterations in the gap column, f64 gaps at rounding
    level on both."""
    import jax.numpy as jnp

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    n = poisson_csr.shape[0]
    b = np.ones(n)
    crit = StoppingCriteria(maxits=300, residual_rtol=1e-10)
    spec = health.make_spec(every=8)

    s1 = _jax_solver(poisson_csr, health=spec, trace=128)
    s1.solve(b, criteria=crit)

    part = partition_rows(poisson_csr, 8, seed=0, method="band")
    prob = DistributedProblem.build(poisson_csr, part, 8,
                                    dtype=jnp.float64)
    s8 = DistCGSolver(prob, health=spec, trace=128)
    s8.solve(b, criteria=crit)

    h1, h8 = s1.stats.health, s8.stats.health
    assert s1.stats.niterations == s8.stats.niterations
    assert h1["naudits"] == h8["naudits"] > 0
    assert h1["gap_max"] < 1e-11 and h8["gap_max"] < 1e-11
    gi = s1.last_trace.fields.index("gap")
    audited1 = s1.last_trace.iterations[
        np.isfinite(s1.last_trace.records[:, gi])]
    audited8 = s8.last_trace.iterations[
        np.isfinite(s8.last_trace.records[:, gi])]
    np.testing.assert_array_equal(audited1, audited8)


def test_sharded_gen_direct_audit():
    """The fourth tier: the sharded gen-direct solver inherits the
    audited programs unchanged -- the audit's roll SpMV partitions
    into the usual boundary collective-permutes and the gap psums
    through sharding propagation like the CG scalars."""
    import jax.numpy as jnp

    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    s = build_sharded_poisson_solver(
        16, 2, nparts=8, dtype=jnp.float64, pipelined=True,
        health=health.make_spec(every=8))
    s.solve(s.ones_b(), criteria=StoppingCriteria(maxits=300,
                                                  residual_rtol=1e-9),
            host_result=False)
    h = s.stats.health
    assert h["naudits"] > 0 and h["gap_max"] < 1e-11


# -- telemetry audit column: meta + round trip (the small-fix satellite) --

def test_audit_column_roundtrip_and_tail_note(poisson_csr, tmp_path):
    s = _jax_solver(poisson_csr, health=health.make_spec(every=6),
                    trace=64)
    s.solve(np.ones(poisson_csr.shape[0]),
            criteria=StoppingCriteria(maxits=200, residual_rtol=1e-9))
    t = s.last_trace
    assert t.fields == ("rnrm2", "alpha", "beta", "pAp", "gap")
    # the meta line declares the audit column so mixed windows never
    # misalign; NaN (unaudited) survives as a "nan" string
    path = tmp_path / "c.jsonl"
    t.write_jsonl(path)
    meta, records = telemetry.read_convergence_log(path)
    assert meta["fields"] == ["rnrm2", "alpha", "beta", "pAp", "gap"]
    assert t.to_dict()["records"] == records
    audited = [r for r in records if isinstance(r["gap"], float)]
    unaudited = [r for r in records if isinstance(r["gap"], str)]
    assert audited and unaudited  # a genuinely mixed window
    assert all((r["it"] + 1) % 6 == 0 for r in audited)
    # tail_summary flags the column and quotes the gap inline
    tail = t.tail_summary(8)
    assert "[audit gap column present]" in tail
    assert "(gap " in tail
    # an unaudited trace keeps the pre-/5 4-field layout exactly
    s2 = _jax_solver(poisson_csr, trace=16)
    s2.solve(np.ones(poisson_csr.shape[0]),
             criteria=StoppingCriteria(maxits=50),
             raise_on_divergence=False)
    assert s2.last_trace.fields == ("rnrm2", "alpha", "beta", "pAp")
    assert "audit" not in s2.last_trace.tail_summary()


def test_host_oracle_audit_and_replace(poisson_csr):
    """The eager f64 twin: audits fire on the device schedule, the gap
    column rides the recorder, replacement applies literally, abort
    raises."""
    from acg_tpu.errors import BreakdownError
    from acg_tpu.solvers.host_cg import HostCGSolver

    n = poisson_csr.shape[0]
    hs = HostCGSolver(poisson_csr, trace=128,
                      health=health.make_spec(every=5))
    hs.solve(np.ones(n), criteria=StoppingCriteria(maxits=300,
                                                   residual_rtol=1e-10))
    h = hs.stats.health
    assert h["naudits"] > 0 and h["gap_max"] < 1e-12
    gi = hs.last_trace.fields.index("gap")
    assert np.isfinite(hs.last_trace.records[:, gi]).sum() > 0

    # an (artificially) hair-trigger threshold: every audit replaces --
    # bounded by the SAME restart budget the compiled tiers consume,
    # and counted on the same resilience counters
    from acg_tpu.solvers.resilience import RecoveryPolicy

    hr = HostCGSolver(poisson_csr,
                      recovery=RecoveryPolicy(max_restarts=100,
                                              fallback_host=False),
                      health=health.make_spec(every=5, threshold=1e-300,
                                              action="replace"))
    hr.solve(np.ones(n), criteria=StoppingCriteria(maxits=300,
                                                   residual_rtol=1e-10))
    assert hr.stats.converged
    assert hr.stats.nrestarts >= 1  # each replacement consumes budget
    assert any("residual replacement" in ev
               for ev in hr.stats.recovery_log)
    assert any(ev["kind"] == "accuracy_degraded"
               for ev in hr.stats.events)

    # without a policy the replacement budget is zero: the first trip
    # raises with the gap named (never an unbounded replacement loop)
    hz = HostCGSolver(poisson_csr,
                      health=health.make_spec(every=5, threshold=1e-300,
                                              action="replace"))
    with pytest.raises(BreakdownError, match="gap"):
        hz.solve(np.ones(n), criteria=StoppingCriteria(
            maxits=300, residual_rtol=1e-10))
    assert hz.stats.health["naudits"] >= 1  # audit evidence survives

    ha = HostCGSolver(poisson_csr,
                      health=health.make_spec(every=5, threshold=1e-300,
                                              action="abort"))
    with pytest.raises(BreakdownError, match="gap"):
        ha.solve(np.ones(n), criteria=StoppingCriteria(
            maxits=300, residual_rtol=1e-10))


# -- metrics / soak / stats surfaces --------------------------------------

def test_health_metrics_and_section(poisson_csr):
    from acg_tpu import metrics

    was = metrics.armed()
    try:
        metrics.arm()
        g0 = metrics.HEALTH_AUDITS.value
        s = _jax_solver(poisson_csr, health=health.make_spec(every=5),
                        trace=64)
        s.solve(np.ones(poisson_csr.shape[0]),
                criteria=StoppingCriteria(maxits=200,
                                          residual_rtol=1e-9))
        assert metrics.HEALTH_AUDITS.value > g0
        assert math.isfinite(metrics.HEALTH_GAP.value)
        txt = metrics.expose()
        for fam in ("acg_health_residual_gap", "acg_health_audits_total",
                    "acg_health_kappa_estimate",
                    "acg_health_gap_trips_total"):
            assert fam in txt
    finally:
        if not was:
            metrics.disarm()


def test_health_section_appends_only():
    """Like soak:/precond:, health: appends strictly after the
    reference-format block -- a report without it is a byte-prefix of
    one with it, and the /5 twin carries the full structure."""
    st = SolverStats(unknowns=7)
    st.precond.update({"kind": "jacobi"})
    base = st.fwrite()
    st.health.update({"audit_every": 10, "gap_last": 1e-6,
                      "spectrum": {"kappa": 123.4}})
    txt = st.fwrite()
    assert txt.startswith(base)
    assert "health:" in txt[len(base):]
    d = st.to_dict()
    assert d["health"]["spectrum"]["kappa"] == 123.4
    assert telemetry.STATS_SCHEMA == "acg-tpu-stats/12"
    json.dumps(telemetry.stats_document(st))


def test_soak_tracks_gap(poisson_csr):
    from acg_tpu.soak import run_soak

    s = _jax_solver(poisson_csr, health=health.make_spec(every=5))
    _x, report = run_soak(
        s, np.ones(poisson_csr.shape[0]), nsolves=3,
        criteria=StoppingCriteria(maxits=200, residual_rtol=1e-9))
    gap = report["gap"]
    assert math.isfinite(gap["first"]) and math.isfinite(gap["last"])
    assert gap["max"] >= gap["last"] > 0


# -- explain convergence verdict ------------------------------------------

def test_explain_convergence_verdict(aniso_csr, capsys):
    import io
    import types

    from acg_tpu.perfmodel import _explain_convergence
    from acg_tpu.precond import parse_precond

    args = types.SimpleNamespace(residual_rtol=1e-8, max_iterations=400,
                                 _precond=parse_precond("jacobi"))
    err = io.StringIO()
    rep = _explain_convergence(args, aniso_csr, [], err)
    out = err.getvalue()
    assert rep is not None and rep["kappa"] > 1
    assert rep["precond_effectiveness"] > 1  # jacobi compresses here
    assert "explain: convergence" in out
    assert "preconditioner effectiveness" in out
    assert "predicted" in out


# -- satellites: bench_diff backend-unavailable capture -------------------

def test_bench_diff_unavailable_capture(tmp_path):
    """A capture recording only bench_backend_unavailable (BENCH_r05:
    the tunnel was down) exits 2 with the re-baseline message instead
    of attempting a comparison."""
    script = os.path.join(REPO, "scripts", "bench_diff.py")
    r04 = os.path.join(REPO, "BENCH_r04.json")
    r05 = os.path.join(REPO, "BENCH_r05.json")
    r = subprocess.run([sys.executable, script, r04, r05],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "re-baseline before trusting --fail-on-regress" in r.stderr
    assert "bench_backend_unavailable" in r.stderr
    # the sentinel as BASELINE refuses the same way
    r = subprocess.run([sys.executable, script, r05, r04],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "re-baseline" in r.stderr
    # real captures still compare (no false refusals)
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"metric": "case_a", "value": 10.0})
                    + "\n")
    r = subprocess.run([sys.executable, script, str(good), str(good)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_split_unavailable_keeps_real_cases():
    from acg_tpu.perfmodel import split_unavailable

    cases, had = split_unavailable({"bench_backend_unavailable": 0.0,
                                    "cg_iters": 100.0})
    assert had and cases == {"cg_iters": 100.0}
    cases, had = split_unavailable({"cg_iters": 100.0})
    assert not had and cases == {"cg_iters": 100.0}


# -- CLI end-to-end -------------------------------------------------------

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


def test_cli_health_end_to_end(tmp_path):
    """--audit-every on a dist solve over the 8-device mesh: health:
    section + /5 stats doc with a spectrum estimate + gap column in
    the convergence log + acg_health_* families in the textfile."""
    stats = tmp_path / "s.json"
    conv = tmp_path / "c.jsonl"
    prom = tmp_path / "m.prom"
    r = run_cli(["gen:poisson2d:24", "--nparts", "8",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--audit-every", "10",
                 "--convergence-log", str(conv),
                 "--metrics-file", str(prom),
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    assert "health:" in r.stderr
    doc = json.loads(stats.read_text())
    assert doc["schema"] == "acg-tpu-stats/12"
    h = doc["stats"]["health"]
    assert h["naudits"] > 0 and isinstance(h["gap_last"], float)
    assert h["spectrum"]["kappa"] > 1
    assert h["spectrum"]["predicted_iterations"] >= 1
    meta, records = telemetry.read_convergence_log(conv)
    assert "gap" in meta["fields"]
    assert any(isinstance(rec.get("gap"), float) for rec in records)
    txt = prom.read_text()
    assert "acg_health_residual_gap" in txt
    assert "acg_health_kappa_estimate" in txt


def test_cli_health_flag_validation():
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--quiet",
                 "--gap-threshold", "1e-4"])
    assert r.returncode != 0
    assert "--gap-threshold needs --audit-every" in r.stderr
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--quiet",
                 "--audit-every", "5", "--on-gap", "replace"])
    assert r.returncode != 0
    assert "gap threshold" in r.stderr
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--quiet",
                 "--solver", "host-native", "--audit-every", "5"])
    assert r.returncode != 0
    assert "no audit hooks" in r.stderr


def test_cli_buildinfo_advertises_health():
    r = run_cli(["--buildinfo"])
    assert r.returncode == 0, r.stderr
    assert "--audit-every" in r.stdout
    assert "--on-gap" in r.stdout
    assert "acg-tpu-stats/12" in r.stdout


def test_plot_convergence_renders_gap(tmp_path):
    """The plotting satellite: a gap-bearing log renders the audit
    trail in the text fallback."""
    t = telemetry.ConvergenceTrace(
        capacity=8, niterations=8,
        records=np.column_stack([
            np.logspace(0, -7, 8), np.ones(8), np.ones(8), np.ones(8),
            [math.nan, 1e-7, math.nan, 1e-6, math.nan, 1e-5,
             math.nan, 1e-4]]),
        iterations=np.arange(8), wrapped=False,
        fields=("rnrm2", "alpha", "beta", "pAp", "gap"))
    path = tmp_path / "c.jsonl"
    t.write_jsonl(path)
    script = os.path.join(REPO, "scripts", "plot_convergence.py")
    r = subprocess.run([sys.executable, script, str(path), "--ascii"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "audit gap max 1.000e-04" in r.stdout
    assert "gap:" in r.stdout
