"""Native C++ core (libacg_core) vs the pure-Python fallbacks.

Every binding in acg_tpu._native has a numpy twin; these tests pin the two
implementations to each other and to scipy oracles, the same
cross-implementation strategy the reference uses between its host and GPU
solvers (SURVEY.md section 4).
"""

import numpy as np
import pytest
import scipy.sparse as sp

from acg_tpu import _native as nat

pytestmark = pytest.mark.skipif(not nat.available(),
                                reason="native library not built")


# ---- sort / scan ---------------------------------------------------------

def test_radixsort_matches_numpy():
    rng = np.random.default_rng(0)
    for n in (0, 1, 2, 1000, 65537):
        k = rng.integers(-2**62, 2**62, n)
        sk, perm = nat.radixsort(k)
        assert (sk == np.sort(k)).all()
        assert (k[perm] == sk).all()


def test_radixsort_stable():
    rng = np.random.default_rng(1)
    k = rng.integers(0, 7, 5000)
    assert (nat.argsort(k) == np.argsort(k, kind="stable")).all()


def test_radixsort_extremes():
    k = np.array([2**62, -2**62, 0, -1, 1, np.iinfo(np.int64).max,
                  np.iinfo(np.int64).min])
    sk, _ = nat.radixsort(k)
    assert (sk == np.sort(k)).all()


def test_prefixsum():
    a = np.array([3, 0, 5, 1])
    assert (nat.prefixsum_exclusive(a) == [0, 3, 3, 8, 9]).all()
    assert (nat.prefixsum_exclusive(np.array([], dtype=np.int64)) == [0]).all()


# ---- Matrix Market parse / format ---------------------------------------

def test_parse_coord_basic():
    buf = b"1 1 2.5\n2 1 -3e-2\n\n   3 2 1e10  \n"
    r, c, v = nat.parse_coord(buf, 3, 3, 3, True)
    assert (r == [0, 1, 2]).all() and (c == [0, 0, 1]).all()
    assert np.allclose(v, [2.5, -0.03, 1e10])


def test_parse_coord_pattern():
    r, c, v = nat.parse_coord(b"1 2\n2 3\n", 2, 3, 3, False)
    assert v is None and (r == [0, 1]).all() and (c == [1, 2]).all()


def test_parse_coord_errors():
    with pytest.raises(nat.NativeParseError):  # truncated
        nat.parse_coord(b"1 1 2.5\n", 2, 3, 3, True)
    with pytest.raises(nat.NativeParseError):  # out of bounds
        nat.parse_coord(b"4 1 2.5\n", 1, 3, 3, True)
    with pytest.raises(nat.NativeParseError):  # garbage
        nat.parse_coord(b"a b c\n", 1, 3, 3, True)
    with pytest.raises(nat.NativeParseError):  # trailing garbage on value
        nat.parse_coord(b"1 1 3junk\n", 1, 3, 3, True)
    with pytest.raises(nat.NativeParseError):  # extra token
        nat.parse_coord(b"1 1 3.0 4.0\n", 1, 3, 3, True)


def test_parse_format_roundtrip_random():
    rng = np.random.default_rng(2)
    n = 10000
    r = rng.integers(0, 4096, n)
    c = rng.integers(0, 4096, n)
    v = rng.standard_normal(n) * 10.0 ** rng.integers(-300, 300, n)
    buf = nat.format_coord(r, c, v)
    r2, c2, v2 = nat.parse_coord(buf, n, 4096, 4096, True)
    assert (r2 == r).all() and (c2 == c).all()
    assert (v2 == v).all(), "%.17g round-trip must be exact"


def test_format_array_roundtrip():
    v = np.array([0.1, -1e308, 2.5e-308, 0.0, -0.0])
    assert (nat.parse_array(nat.format_array(v), v.size) == v).all()


def test_format_rejects_int_conversion():
    with pytest.raises(nat.NativeParseError):
        nat.format_array(np.ones(3), "%d")


def test_parse_array_multiple_per_line():
    assert (nat.parse_array(b"1.0 2.0 3.0\n4.0\n", 4) == [1, 2, 3, 4]).all()


# ---- symmetric CSR assembly ---------------------------------------------

def _random_spd_coo(n, seed, full):
    rng = np.random.default_rng(seed)
    A = sp.random(n, n, density=0.08, random_state=seed)
    A = (A + A.T).tocsr()
    A.setdiag(np.arange(1, n + 1).astype(float))
    A = A.tocsr()
    A.sum_duplicates()
    M = A if full else sp.triu(A).tocsr()
    coo = M.tocoo()
    return A, coo


@pytest.mark.parametrize("full", [True, False])
def test_sym_csr_from_coo(full):
    A, coo = _random_spd_coo(64, 3, full)
    pr, pc, pa = nat.sym_csr_from_coo(64, coo.row, coo.col, coo.data)
    U = sp.triu(A).tocsr()
    U.sort_indices()
    assert (pr == U.indptr).all()
    assert (pc == U.indices).all()
    assert np.allclose(pa, U.data)


def test_sym_csr_duplicates_summed():
    # same entry twice in the same triangle sums (not halved)
    r = np.array([0, 0, 1])
    c = np.array([1, 1, 1])
    v = np.array([2.0, 3.0, 1.0])
    pr, pc, pa = nat.sym_csr_from_coo(2, r, c, v)
    assert np.allclose(pa, [5.0, 1.0])


@pytest.mark.parametrize("epsilon", [0.0, 0.25])
def test_sym_csr_expand(epsilon):
    A, coo = _random_spd_coo(50, 4, full=False)
    # drop some diagonal entries so epsilon has missing rows to create
    keep = ~((coo.row == coo.col) & (coo.row % 7 == 0))
    pr, pc, pa = nat.sym_csr_from_coo(50, coo.row[keep], coo.col[keep],
                                      coo.data[keep])
    fr, fc, fa = nat.sym_csr_expand(50, pr, pc, pa, epsilon)
    up = sp.csr_matrix((pa, pc, pr), shape=(50, 50))
    ref = (up + sp.triu(up, k=1).T).tocsr()
    if epsilon:
        ref = (ref + epsilon * sp.eye(50, format="csr")).tocsr()
    ref.sort_indices()
    assert (fr == ref.indptr).all()
    assert (fc == ref.indices).all()
    assert np.allclose(fa, ref.data)


# ---- one-pass graph partitioner -----------------------------------------

def test_graph_partition_matches_numpy():
    from acg_tpu.graph import (_partition_graph_nodes_native,
                               _partition_graph_nodes_numpy)
    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix

    r, c, v, N = poisson2d_coo(24)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    rng = np.random.default_rng(5)
    for nparts in (1, 2, 5, 8):
        part = rng.integers(0, nparts, N).astype(np.int32)
        subs_n = _partition_graph_nodes_native(csr, part, nparts)
        subs_p = _partition_graph_nodes_numpy(csr, part, nparts)
        for sn, sp_ in zip(subs_n, subs_p):
            assert sn.ninterior == sp_.ninterior
            assert sn.nborder == sp_.nborder
            assert sn.nghost == sp_.nghost
            assert (sn.global_ids == sp_.global_ids).all()
            assert (sn.ghost_owner == sp_.ghost_owner).all()
            hn, hp = sn.halo, sp_.halo
            assert (hn.send_parts == hp.send_parts).all()
            assert (hn.send_counts == hp.send_counts).all()
            assert (hn.send_idx == hp.send_idx).all()
            assert (hn.recv_parts == hp.recv_parts).all()
            assert (hn.recv_idx == hp.recv_idx).all()


def test_mtxfile_native_vs_python_read(tmp_path):
    """End-to-end file read must be identical with and without native."""
    import subprocess
    import sys
    import os
    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.io.mtxfile import MtxFile, read_mtx, write_mtx

    r, c, v, N = poisson2d_coo(12)
    path = tmp_path / "p.mtx"
    write_mtx(path, MtxFile(object="matrix", format="coordinate",
                            field="real", symmetry="general", nrows=N,
                            ncols=N, nnz=r.size, rowidx=r, colidx=c, vals=v))
    m1 = read_mtx(path)
    env = dict(os.environ, ACG_TPU_DISABLE_NATIVE="1")
    out = subprocess.run(
        [sys.executable, "-c",
         "import numpy as np;from acg_tpu.io.mtxfile import read_mtx;"
         f"m=read_mtx({str(path)!r});"
         "print(int(m.rowidx.sum()), int(m.colidx.sum()), float(m.vals.sum()))"],
        capture_output=True, text=True, env=env, check=True)
    rs, cs, vs = out.stdout.split()
    assert int(rs) == int(m1.rowidx.sum())
    assert int(cs) == int(m1.colidx.sum())
    assert float(vs) == float(m1.vals.sum())


# ---- host CG solver (native/src/cg.cpp) ----------------------------------

def _poisson_csr(n=24):
    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix

    r, c, v, N = poisson2d_coo(n)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_native_cg_matches_python_host():
    from acg_tpu.solvers.host_cg import HostCGSolver, NativeHostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    csr = _poisson_csr()
    n = csr.shape[0]
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(n)
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=5000, residual_rtol=1e-11)
    py = HostCGSolver(csr)
    nt = NativeHostCGSolver(csr)
    xp = py.solve(b, criteria=crit)
    xn = nt.solve(b, criteria=crit)
    # identical recurrences in f64: same iteration count, same solution
    assert nt.stats.niterations == py.stats.niterations
    np.testing.assert_allclose(xn, xp, rtol=0, atol=1e-12)
    assert np.linalg.norm(xn - xsol) < 1e-9
    assert nt.stats.rnrm2 == pytest.approx(py.stats.rnrm2, rel=1e-6)


def test_native_cg_unbounded_and_divergence():
    from acg_tpu.errors import NotConvergedError
    from acg_tpu.solvers.host_cg import NativeHostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    csr = _poisson_csr(12)
    b = np.ones(csr.shape[0])
    s = NativeHostCGSolver(csr)
    s.solve(b, criteria=StoppingCriteria(maxits=7))  # unbounded: exact count
    assert s.stats.niterations == 7 and s.stats.converged
    with pytest.raises(NotConvergedError):
        NativeHostCGSolver(csr).solve(
            b, criteria=StoppingCriteria(maxits=3, residual_rtol=1e-14))


def test_native_cg_diff_criterion_and_x0():
    from acg_tpu.solvers.host_cg import HostCGSolver, NativeHostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    csr = _poisson_csr(16)
    n = csr.shape[0]
    b = np.ones(n)
    x0 = np.full(n, 0.1)
    crit = StoppingCriteria(maxits=5000, diff_atol=1e-10)
    py = HostCGSolver(csr).solve(b, x0=x0, criteria=crit)
    nt = NativeHostCGSolver(csr).solve(b, x0=x0, criteria=crit)
    np.testing.assert_allclose(nt, py, rtol=0, atol=1e-10)
