"""Two-phase fused CG iteration (ops.pallas_kernels.cg_phase_a/b,
solvers.jax_cg._cg_fused_program, kernels="fused").

The reference's monolithic device-kernel CG
(``acgsolvercuda_cg_kernel``, ``cg-kernels-cuda.cu:627-970``) done the
TPU way: each iteration is exactly two streamed Pallas kernels with the
CG scalars riding SMEM -- the p-update folded into the SpMV's halo
windows, both dots accumulated in-kernel -- so no XLA fusion is
forfeited (round 2's single-fused-kernel failure mode) and the
iteration runs in ~15 HBM passes instead of ~20.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson_dia
from acg_tpu.ops.pallas_kernels import cg_phase_a, cg_phase_b, fused_cg_route
from acg_tpu.ops.spmv import DiaMatrix, dia_mv
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


def _dia(n=128, dim=2, dtype=jnp.float32):
    planes, offsets, N = poisson_dia(n, dim, dtype=np.float64)
    return DiaMatrix(data=tuple(jnp.asarray(p, dtype) for p in planes),
                     offsets=offsets, nrows=N, ncols_padded=N)


def test_phase_a_matches_reference():
    """p = r + beta p_old, t = A p, (p, t) -- all exact vs the XLA
    formulation (the kernel computes the same f32 sums)."""
    A = _dia()
    N = A.nrows
    rng = np.random.default_rng(0)
    r = jnp.asarray(rng.standard_normal(N), jnp.float32)
    p_old = jnp.asarray(rng.standard_normal(N), jnp.float32)
    p, t, pdott = cg_phase_a(A.data, A.offsets, r, p_old,
                             jnp.float32(2.0), jnp.float32(4.0),
                             interpret=True)
    p_ref = np.asarray(r) + 0.5 * np.asarray(p_old)
    t_ref = np.asarray(dia_mv(A.data, A.offsets, N, jnp.asarray(p_ref)))
    np.testing.assert_array_equal(np.asarray(p), p_ref)
    np.testing.assert_array_equal(np.asarray(t), t_ref)
    assert float(pdott) == pytest.approx(float(p_ref @ t_ref), rel=1e-6)


def test_phase_a_first_iteration_beta_zero():
    """gamma_prev = inf must give beta = 0 exactly (p = r)."""
    A = _dia()
    N = A.nrows
    r = jnp.asarray(np.random.default_rng(1).standard_normal(N),
                    jnp.float32)
    junk = jnp.full((N,), 1e30, jnp.float32)  # must not leak into p
    p, t, _ = cg_phase_a(A.data, A.offsets, r, junk,
                         jnp.float32(3.0), jnp.float32(np.inf),
                         interpret=True)
    np.testing.assert_array_equal(np.asarray(p), np.asarray(r))


def test_phase_b_matches_reference():
    N = 16384
    rng = np.random.default_rng(2)
    x, p, r, t = (jnp.asarray(rng.standard_normal(N), jnp.float32)
                  for _ in range(4))
    xn, rn, g = cg_phase_b(x, p, r, t, jnp.float32(3.0), jnp.float32(1.5),
                           interpret=True)
    np.testing.assert_array_equal(np.asarray(xn),
                                  np.asarray(x) + 2.0 * np.asarray(p))
    r_ref = np.asarray(r) - 2.0 * np.asarray(t)
    np.testing.assert_array_equal(np.asarray(rn), r_ref)
    assert float(g) == pytest.approx(float(r_ref @ r_ref), rel=1e-6)


def test_phase_a_multi_tile_grid():
    """N = 16 tiles exercises the cross-step double-buffered window
    machinery (slot parity, prefetch of step i+1, per-slot semaphores,
    edge fills at both grid ends) that a single-tile grid never runs."""
    A = _dia(n=512, dim=2)  # N = 262144 = 16 tiles
    N = A.nrows
    rng = np.random.default_rng(7)
    r = jnp.asarray(rng.standard_normal(N), jnp.float32)
    p_old = jnp.asarray(rng.standard_normal(N), jnp.float32)
    p, t, pdott = cg_phase_a(A.data, A.offsets, r, p_old,
                             jnp.float32(1.0), jnp.float32(2.0),
                             interpret=True)
    p_ref = np.asarray(r) + 0.5 * np.asarray(p_old)
    t_ref = np.asarray(dia_mv(A.data, A.offsets, N, jnp.asarray(p_ref)))
    np.testing.assert_array_equal(np.asarray(p), p_ref)
    np.testing.assert_array_equal(np.asarray(t), t_ref)
    assert float(pdott) == pytest.approx(float(p_ref @ t_ref), rel=1e-5)


def test_fused_solver_matches_xla_multi_tile():
    """Whole fused solve on a multi-tile grid agrees with XLA.

    On a WELL-CONDITIONED matrix (diagonal shift -> kappa ~ 9): at the
    flagship's kappa ~ 1e5, any two f32 CG implementations legitimately
    diverge by percents at fixed iteration counts (dot summation order
    alone; measured: the fused tier tracks an f64 reference to 5e-7
    where the XLA tier sits at 2.6% after 8 iterations), so unshifted
    mid-convergence iterates are not comparable.  Shifted, both
    converge and the solutions must agree tightly; the per-kernel
    multi-tile test above pins the kernels bitwise."""
    base = _dia(n=512, dim=2)
    d = base.offsets.index(0)
    planes = list(base.data)
    planes[d] = planes[d] + jnp.float32(2.0)   # A + 2I: kappa ~ 9/2
    A = DiaMatrix(data=tuple(planes), offsets=base.offsets,
                  nrows=base.nrows, ncols_padded=base.ncols_padded)
    b = np.ones(A.nrows, np.float32)
    crit = StoppingCriteria(maxits=500, residual_rtol=1e-6)
    sf = JaxCGSolver(A, kernels="fused")
    xf = np.asarray(sf.solve(b, criteria=crit))
    sx = JaxCGSolver(A, kernels="xla")
    xx = np.asarray(sx.solve(b, criteria=crit))
    assert sf.stats.converged and sx.stats.converged
    assert abs(sf.stats.niterations - sx.stats.niterations) <= 2
    assert np.linalg.norm(xf - xx) <= 1e-5 * np.linalg.norm(xx)


def test_fused_solver_matches_xla():
    A = _dia()
    b = np.ones(A.nrows, np.float32)
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-6)
    sf = JaxCGSolver(A, kernels="fused")
    assert sf.kernels == "fused-interpret"  # off-TPU resolution
    xf = np.asarray(sf.solve(b, criteria=crit))
    sx = JaxCGSolver(A, kernels="xla")
    xx = np.asarray(sx.solve(b, criteria=crit))
    assert sf.stats.converged and sx.stats.converged
    # near-stall crossing wobble: counts agree loosely, solutions tightly
    assert abs(sf.stats.niterations - sx.stats.niterations) \
        <= 0.3 * sx.stats.niterations
    assert np.linalg.norm(xf - xx) <= 1e-5 * np.linalg.norm(xx)


def test_fused_mixed_bitwise_equals_fused_f32():
    A32 = _dia(dtype=jnp.float32)
    A16 = _dia(dtype=jnp.bfloat16)
    b = np.ones(A32.nrows, np.float32)
    crit = StoppingCriteria(maxits=300)
    x32 = np.asarray(JaxCGSolver(A32, kernels="fused")
                     .solve(b, criteria=crit))
    xm = np.asarray(JaxCGSolver(A16, kernels="fused",
                                vector_dtype=jnp.float32)
                    .solve(b, criteria=crit))
    assert np.array_equal(x32, xm)


def test_fused_rejects_unsupported_shapes():
    # ragged N (not a multiple of the kernel tile) has no fast route;
    # the solver must say so instead of miscompiling
    planes, offsets, N = poisson_dia(90, 2, dtype=np.float64)
    A = DiaMatrix(data=tuple(jnp.asarray(p, jnp.float32) for p in planes),
                  offsets=offsets, nrows=N, ncols_padded=N)
    assert fused_cg_route(offsets, N, jnp.float32) is None
    with pytest.raises(ValueError, match="fused"):
        JaxCGSolver(A, kernels="fused")
    with pytest.raises(ValueError, match="fused"):
        JaxCGSolver(_dia(), kernels="fused", pipelined=True)


def test_fused_rejects_diff_criteria():
    A = _dia()
    s = JaxCGSolver(A, kernels="fused")
    with pytest.raises(ValueError, match="residual"):
        s.solve(np.ones(A.nrows, np.float32),
                criteria=StoppingCriteria(maxits=10, diff_atol=1e-3))
