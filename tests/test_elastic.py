"""Elastic-mesh recovery: shape-portable checkpoints, the
survivor-mesh supervisor, and the chaos campaign (ISSUE 10).

The acceptance contract:
  * an N-part snapshot resumed with repartition onto M parts (or the
    single-device / host tiers) converges to the ORIGINAL tolerance
    with total (pre + post) iterations within a small band of the
    uninterrupted count;
  * a corrupted row-permutation sidecar REFUSES instead of resuming a
    scrambled Krylov state;
  * crash:exit mid-solve on the 8-part mesh -> the supervisor
    relaunches with --resume --resume-repartition on fewer parts ->
    the final true relative residual meets the original rtol;
  * a seeded chaos campaign ends every schedule converged or
    agreed-abort -- zero wrong-answer-green;
  * the exit-code contract is one registry (errors.ExitCode) and
    --buildinfo renders it.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import faults, observatory
from acg_tpu.checkpoint import (CheckpointConfig, SolverSnapshot,
                                load_snapshot, reassemble_global,
                                save_snapshot, validate_resume)
from acg_tpu.errors import (AcgError, ExitCode, PEER_LOST_CODES,
                            RELAUNCHABLE_CODES, exit_code_table)
from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import is_permutation, partition_rows
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu import supervisor as sup

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, extra_env=None, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    if extra_env:
        env.update(extra_env)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


@pytest.fixture(scope="module")
def system():
    csr = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2)).to_csr()
    b = csr @ (np.ones(csr.shape[0]) / np.sqrt(csr.shape[0]))
    return csr, b


@pytest.fixture(scope="module")
def prob8(system):
    csr, _ = system
    return DistributedProblem.build(csr, partition_rows(csr, 8, seed=0),
                                    8, dtype=jnp.float64)


@pytest.fixture(scope="module")
def prob4(system):
    csr, _ = system
    return DistributedProblem.build(csr, partition_rows(csr, 4, seed=1),
                                    4, dtype=jnp.float64)


CRIT = StoppingCriteria(residual_rtol=1e-8, maxits=2000)


@pytest.fixture(scope="module")
def snap8(system, prob8, tmp_path_factory):
    """A mid-solve 8-part snapshot (the last one committed before
    convergence) plus the uninterrupted iteration count."""
    csr, b = system
    ref = DistCGSolver(prob8)
    ref.solve(b, criteria=CRIT)
    p = str(tmp_path_factory.mktemp("snap") / "ck8")
    s = DistCGSolver(prob8, ckpt=CheckpointConfig(path=p, every=16))
    s.solve(b, criteria=CRIT)
    return load_snapshot(p), ref.stats.niterations


# -- the exit-code contract (satellite 3) --------------------------------

def test_exit_code_registry_is_the_single_source():
    """The scattered rc constants all resolve to the registry."""
    from acg_tpu.checkpoint import CRASH_EXIT_CODE
    from acg_tpu.observatory import SLO_EXIT_CODE
    from acg_tpu.parallel.erragree import PEER_LOST_EXIT
    from acg_tpu.soak import DRIFT_EXIT_CODE

    assert CRASH_EXIT_CODE == int(ExitCode.CRASH_INJECTED) == 94
    assert PEER_LOST_EXIT == int(ExitCode.PEER_LOST) == 97
    assert DRIFT_EXIT_CODE == int(ExitCode.DRIFT) == 7
    assert SLO_EXIT_CODE == int(ExitCode.SLO_BREACH) == 8
    assert int(ExitCode.PEER_DEAD_INJECTED) == 86
    assert int(ExitCode.RELAUNCH_BUDGET) == 95
    assert int(ExitCode.WRONG_ANSWER) == 96
    codes = [c for c, _, _ in exit_code_table()]
    assert codes == sorted(codes)
    assert set(RELAUNCHABLE_CODES) >= {86, 94, 97}
    assert PEER_LOST_CODES == {86, 97}
    # every registry row names an origin and a meaning
    assert all(origin and meaning
               for _, origin, meaning in exit_code_table())


def test_buildinfo_renders_exit_table_and_elastic_row():
    import io

    from acg_tpu.cli import _buildinfo
    out = io.StringIO()
    assert _buildinfo(out) == 0
    text = out.getvalue()
    assert "exit codes:" in text
    assert "\n   94  [faults/checkpoint]" in text
    assert "elastic recovery: --supervise" in text
    assert "--resume-repartition" in text


# -- cadence: --ckpt-secs (satellite 2) ----------------------------------

def test_ckpt_config_refuses_double_cadence():
    with pytest.raises(ValueError, match="EITHER"):
        CheckpointConfig(path="x", every=8, secs=1.0)
    with pytest.raises(ValueError, match="cadence"):
        CheckpointConfig(path="x")
    # secs alone is a valid cadence; chunk sizing adapts to the
    # measured rate (probe chunk first, then secs / s_per_iter)
    c = CheckpointConfig(path="x", secs=2.0)
    assert c.chunk_for(None) == CheckpointConfig.PROBE_CHUNK
    assert c.chunk_for(0.01) == 200
    assert CheckpointConfig(path="x", every=8).chunk_for(0.01) == 8
    with pytest.raises(ValueError, match="resume"):
        CheckpointConfig(path="x", every=8, repartition=True)


def test_ckpt_secs_commits_and_keeps_trajectory(system, tmp_path):
    csr, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    ref = JaxCGSolver(A)
    x_ref = ref.solve(b, criteria=CRIT)
    s = JaxCGSolver(A, ckpt=CheckpointConfig(path=str(tmp_path / "c"),
                                             secs=1e-4))
    x = s.solve(b, criteria=CRIT)
    assert s.stats.ckpt["snapshots"] >= 1
    assert s.stats.ckpt["secs"] == 1e-4
    # chunking never changes the trajectory, whatever the cadence
    assert s.stats.niterations == ref.stats.niterations
    assert np.array_equal(np.asarray(x), np.asarray(x_ref))


def test_cli_refuses_both_cadences():
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--quiet",
                 "--ckpt", "/tmp/nope", "--ckpt-every", "8",
                 "--ckpt-secs", "1"])
    assert r.returncode != 0
    assert "mutually exclusive" in r.stderr


# -- shape-portable snapshots (tentpole leg 1) ---------------------------

def test_fault_spec_str_roundtrips():
    for text in ("crash:exit@20", "sdc:flip@7:seed=99",
                 "spmv:nan@3:part=2", "peer:dead:proc=1",
                 "solve:slow@10:secs=0.05", "backend:hang:secs=12"):
        spec = faults.parse_fault_spec(text)
        assert faults.parse_fault_spec(str(spec)) == spec


def test_is_permutation():
    assert is_permutation(np.arange(5), 5)
    assert is_permutation(np.array([3, 0, 2, 1]), 4)
    assert not is_permutation(np.array([0, 0, 2, 1]), 4)
    assert not is_permutation(np.arange(4), 5)
    assert not is_permutation(np.array([0.0, 1.0]), 2)
    assert not is_permutation(np.array([0, 1, 4]), 3)


def test_validate_resume_repartition_relaxes_only_shape():
    snap = SolverSnapshot(
        meta={"tier": "dist-cg", "pipelined": False, "precond": None,
              "n": 64, "dtype": "float64", "b_crc": 7, "nparts": 8,
              "iteration": 5},
        arrays={})
    ok = dict(tier="jax-cg", pipelined=False, precond=None, n=64,
              dtype=np.float64, b_crc=7)
    # tier + nparts mismatch: refused plain, allowed with repartition
    with pytest.raises(AcgError):
        validate_resume(snap, **ok)
    validate_resume(snap, repartition=True, **ok)
    validate_resume(snap, repartition=True, nparts=4, **{**ok,
                    "tier": "dist-cg"})
    # everything else still refuses under repartition
    for key, bad in (("pipelined", True), ("precond", "jacobi"),
                     ("n", 65), ("dtype", np.float32), ("b_crc", 8)):
        with pytest.raises(AcgError):
            validate_resume(snap, repartition=True, **{**ok, key: bad})
    # tiers outside the repartition set refuse even with the opt-in
    sh = SolverSnapshot(meta={**snap.meta, "tier": "sharded-dia"},
                        arrays={})
    with pytest.raises(AcgError, match="repartition resume supports"):
        validate_resume(sh, repartition=True, **ok)


def test_reassemble_global_identity_and_stacked():
    # single-part snapshots pass through untouched
    s1 = SolverSnapshot(meta={"tier": "jax-cg", "n": 4},
                        arrays={"x": np.arange(4.0)})
    assert reassemble_global(s1) is s1
    # a 2-part stacked snapshot reassembles through the sidecar
    perm = np.array([2, 0, 3, 1], dtype=np.int64)  # slots -> rows
    stacked = np.array([[10.0, 11.0, -1.0], [12.0, 13.0, -1.0]])
    s2 = SolverSnapshot(
        meta={"tier": "dist-cg", "n": 4, "nparts": 2,
              "part_rows": [2, 2]},
        arrays={"x": stacked, "gamma": np.float64(2.5),
                "_rowperm": perm})
    g = reassemble_global(s2)
    assert np.array_equal(g.arrays["x"],
                          np.array([11.0, 13.0, 10.0, 12.0]))
    assert float(g.arrays["gamma"]) == 2.5
    assert "_rowperm" not in g.arrays
    assert g.meta["repartitioned_from"] == {"tier": "dist-cg",
                                            "nparts": 2}


def test_reassemble_refuses_corruption():
    perm = np.array([2, 0, 3, 1], dtype=np.int64)
    stacked = np.zeros((2, 2))
    base = {"tier": "dist-cg", "n": 4, "nparts": 2,
            "part_rows": [2, 2]}

    def snap(meta=None, arrays=None):
        a = {"x": stacked, "_rowperm": perm}
        a.update(arrays or {})
        return SolverSnapshot(meta={**base, **(meta or {})}, arrays=a)

    bad_perm = perm.copy()
    bad_perm[0] = bad_perm[1]                       # duplicate row
    with pytest.raises(AcgError, match="not a permutation"):
        reassemble_global(snap(arrays={"_rowperm": bad_perm}))
    with pytest.raises(AcgError, match="part_rows"):
        reassemble_global(snap(meta={"part_rows": [3, 2]}))
    with pytest.raises(AcgError, match="sidecar"):
        reassemble_global(SolverSnapshot(meta=dict(base),
                                         arrays={"x": stacked}))
    with pytest.raises(AcgError, match="stacked layout"):
        reassemble_global(snap(arrays={"x": np.zeros(4)}))


def test_corrupted_sidecar_refuses_through_save_load(system, prob4,
                                                     snap8, tmp_path):
    """The satellite-5 refusal end-to-end: a snapshot whose permutation
    sidecar was corrupted ON DISK (valid checksums, wrong content)
    refuses at resume instead of scrambling the carry."""
    csr, b = system
    snap, _ = snap8
    arrays = dict(snap.arrays)
    rp = arrays["_rowperm"].copy()
    rp[:2] = rp[0]                                   # now a repeat
    arrays["_rowperm"] = rp
    p = str(tmp_path / "bad")
    save_snapshot(p, dict(snap.meta), arrays)
    bad = load_snapshot(p)
    s = DistCGSolver(prob4, ckpt=CheckpointConfig(resume=bad,
                                                  repartition=True))
    with pytest.raises(AcgError, match="not a permutation"):
        s.solve(b, criteria=CRIT)


def test_repartition_parity_8_to_4_and_single_and_host(system, prob4,
                                                       snap8):
    """The satellite-5 parity bar: an 8-part snapshot resumed at
    4 parts, on the single-device tier, and on the host oracle all
    converge to the original tolerance with total (pre + post)
    iterations within a small band of uninterrupted (measured: exactly
    equal -- the global Krylov state continues; only dot-product
    re-association can move the count)."""
    csr, b = system
    snap, it_ref = snap8
    assert snap.meta["nparts"] == 8 and snap.iteration < it_ref
    band = (it_ref, int(it_ref * 1.15) + 3)

    s4 = DistCGSolver(prob4, ckpt=CheckpointConfig(resume=snap,
                                                   repartition=True))
    x4 = s4.solve(b, criteria=CRIT)
    total = snap.iteration + s4.stats.niterations
    assert band[0] - 3 <= total <= band[1]
    assert s4.stats.ckpt["repartitioned_from"] == {"tier": "dist-cg",
                                                   "nparts": 8}
    assert np.linalg.norm(b - csr @ np.asarray(x4)) \
        / np.linalg.norm(b) < 1e-7

    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s1 = JaxCGSolver(A, ckpt=CheckpointConfig(resume=snap,
                                              repartition=True))
    x1 = s1.solve(b, criteria=CRIT)
    assert band[0] - 3 <= snap.iteration + s1.stats.niterations \
        <= band[1]
    assert np.linalg.norm(b - csr @ np.asarray(x1)) \
        / np.linalg.norm(b) < 1e-7

    sh = HostCGSolver(csr, ckpt=CheckpointConfig(resume=snap,
                                                 repartition=True))
    xh = sh.solve(b, criteria=CRIT)
    assert band[0] - 3 <= snap.iteration + sh.stats.niterations \
        <= band[1]
    assert any(e["kind"] == "repartition" for e in sh.stats.events)
    assert np.linalg.norm(b - csr @ xh) / np.linalg.norm(b) < 1e-7

    # and WITHOUT the opt-in the same mismatch still refuses
    with pytest.raises(AcgError, match="does not match this solve"):
        DistCGSolver(prob4, ckpt=CheckpointConfig(resume=snap)).solve(
            b, criteria=CRIT)


def test_repartition_single_to_dist(system, prob4, tmp_path):
    """The reverse direction: a single-device snapshot (global vectors,
    no sidecar needed) re-slices onto the mesh."""
    csr, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    ref = JaxCGSolver(A)
    ref.solve(b, criteria=CRIT)
    p = str(tmp_path / "ck1")
    JaxCGSolver(A, ckpt=CheckpointConfig(path=p, every=16)).solve(
        b, criteria=CRIT)
    snap = load_snapshot(p)
    s = DistCGSolver(prob4, ckpt=CheckpointConfig(resume=snap,
                                                  repartition=True))
    s.solve(b, criteria=CRIT)
    total = snap.iteration + s.stats.niterations
    assert abs(total - ref.stats.niterations) <= 3


# -- env provenance (satellite 1) ----------------------------------------

def test_snapshot_records_env_and_resume_mismatch_warns(
        system, tmp_path, capsys):
    csr, b = system
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    p = str(tmp_path / "ck")
    JaxCGSolver(A, ckpt=CheckpointConfig(path=p, every=16)).solve(
        b, criteria=CRIT)
    snap = load_snapshot(p)
    import jax
    assert snap.meta["env"]["jax"] == jax.__version__
    assert snap.meta["env"]["backend"] == "cpu"

    meta = dict(snap.meta)
    meta["env"] = {"jax": "0.0.1", "jaxlib": "0.0.1", "backend": "tpu"}
    doctored = SolverSnapshot(meta=meta, arrays=snap.arrays)
    s = JaxCGSolver(A, ckpt=CheckpointConfig(resume=doctored))
    s.solve(b, criteria=CRIT)
    assert any(e["kind"] == "resume-env-mismatch"
               for e in s.stats.events)
    err = capsys.readouterr().err
    assert "environment change" in err and "'tpu' -> 'cpu'" in err
    # a matching environment stays silent
    s2 = JaxCGSolver(A, ckpt=CheckpointConfig(resume=snap))
    s2.solve(b, criteria=CRIT)
    assert not any(e["kind"] == "resume-env-mismatch"
                   for e in s2.stats.events)


# -- live-status peers + degraded (satellite 4) --------------------------

def test_status_document_peers_and_degraded_blocks():
    class StubHeartbeat:
        deadline = 30.0

        def peer_ages(self):
            return {1: 2.5, 2: 0.4}

    observatory.arm()
    try:
        observatory.set_heartbeat(StubHeartbeat())
        observatory.STATUS.note_degraded(8, 4, "peer-lost")
        doc = observatory.status_document()
        assert doc["peers"]["deadline_seconds"] == 30.0
        assert doc["peers"]["last_beat_age_seconds"] == {"1": 2.5,
                                                         "2": 0.4}
        assert doc["degraded"] == {"from": 8, "to": 4,
                                   "reason": "peer-lost"}
    finally:
        observatory.shutdown()
    # shutdown clears both planes
    assert "peers" not in observatory.status_document()


def test_degraded_env_pickup(monkeypatch):
    monkeypatch.setenv(observatory.DEGRADED_ENV, "8:4:crash")
    observatory.arm()
    try:
        doc = observatory.status_document()
        assert doc["degraded"] == {"from": 8, "to": 4,
                                   "reason": "crash"}
    finally:
        observatory.shutdown()


def test_heartbeat_peer_ages_from_watch_thread():
    """peer_ages() reflects the watcher's bookkeeping (a fake KV
    client, the DeadlineHeartbeat test convention)."""
    import time as _time

    from acg_tpu.parallel.erragree import DeadlineHeartbeat

    class FakeClient:
        def __init__(self):
            self.store = {}

        def key_value_set(self, k, v):
            self.store[k] = v

        def key_value_delete(self, k):
            self.store.pop(k, None)

        def key_value_dir_get(self, prefix):
            return [(k, v) for k, v in self.store.items()
                    if k.startswith(prefix)]

    hb = DeadlineHeartbeat(period=0.05, deadline=10.0,
                           client=FakeClient(), nprocs=2, me=0,
                           on_lost=lambda q, age: None)
    hb.start()
    try:
        _time.sleep(0.3)
        ages = hb.peer_ages()
        assert set(ages) == {1}
        assert ages[1] >= 0.0
    finally:
        hb.stop()


# -- the supervisor (tentpole leg 2) -------------------------------------

def test_supervisor_argv_surgery():
    argv = ["gen:poisson2d:16", "--supervise", "--relaunch-budget",
            "2", "--metrics-file", "m.prom", "--ckpt", "ck",
            "--ckpt-every", "8", "--nparts", "8"]
    child = sup.strip_flags(argv, sup.SUPERVISOR_FLAGS)
    assert "--supervise" not in child
    assert "--metrics-file" not in child and "m.prom" not in child
    assert "--ckpt" in child
    assert sup.flag_value(child, "--nparts") == "8"
    re = sup.set_flag(child, "--nparts", 4)
    assert sup.flag_value(re, "--nparts") == "4"
    re = sup.set_flag(re, "--resume", "ck")
    assert sup.flag_value(re, "--resume") == "ck"
    # fault hygiene: device faults are stripped on relaunch, the
    # crossing-safe crash:exit is kept
    a, e = sup._strip_fault(["--fault-inject", "spmv:nan@3"],
                            {"ACG_TPU_FAULT_INJECT": "spmv:nan@3"})
    assert "--fault-inject" not in a and "ACG_TPU_FAULT_INJECT" not in e
    a, e = sup._strip_fault(["--fault-inject", "crash:exit@9"], {})
    assert sup.flag_value(a, "--fault-inject") == "crash:exit@9"


def test_supervisor_reason_classification():
    assert sup._reason(int(ExitCode.CRASH_INJECTED)) == "crash"
    assert sup._reason(int(ExitCode.PEER_LOST)) == "peer-lost"
    assert sup._reason(int(ExitCode.PEER_DEAD_INJECTED)) == "peer-lost"
    assert sup._reason(-9) == "signal"
    assert sup._reason(1) == "failure"
    assert sup._reason(3) == "backend"


def test_chaos_schedules_are_deterministic_and_config_aware():
    class A:
        nparts = 8
        abft = True
        audit_every = 5
        multihost = False
        coordinator = None
        soak = 0
        max_iterations = 300
        num_processes = None

    specs = [sup.chaos_schedule(i, 77, A) for i in range(40)]
    assert specs == [sup.chaos_schedule(i, 77, A) for i in range(40)]
    sites = {s.split(":", 1)[0] for s in specs if s}
    assert "crash" in sites
    # every spec parses back through the fault grammar; sdc flips land
    # on AUDITED iterations ((k+1) % every == 0) -- the ABFT contract
    # protects the checksummed product, an off-cadence flip is the
    # documented negative control, not a campaign schedule
    for s in specs:
        if s is None:
            continue
        spec = faults.parse_fault_spec(s)
        if spec.site == "sdc":
            assert (spec.iteration + 1) % A.audit_every == 0, s
    A.nparts = 0
    A.abft = False
    sites0 = {s.split(":", 1)[0]
              for i in range(40)
              if (s := sup.chaos_schedule(i, 77, A)) is not None}
    assert "halo" not in sites0 and "sdc" not in sites0


def test_verify_solution_detects_wrong_answer(system, tmp_path):
    from acg_tpu.io.mtxfile import vector_mtx, write_mtx

    csr, _ = system
    b = np.ones(csr.shape[0])
    import scipy.sparse.linalg as spla
    x = spla.spsolve(csr.tocsc(), b)
    good = str(tmp_path / "good.mtx")
    write_mtx(good, vector_mtx(x), binary=True)
    ok, rel = sup.verify_solution(csr, b, good, 1e-8)
    assert ok and rel < 1e-8
    bad = str(tmp_path / "bad.mtx")
    xw = x.copy()
    xw[7] *= -1.0                     # the sdc wrong-answer shape
    write_mtx(bad, vector_mtx(xw), binary=True)
    ok, rel = sup.verify_solution(csr, b, bad, 1e-8)
    assert not ok and rel > 1e-4


def test_supervise_cli_validation():
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--supervise"])
    assert r.returncode != 0 and "--ckpt" in r.stderr
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--supervise",
                 "--ckpt", "/tmp/x", "--ckpt-every", "8",
                 "--resume", "/tmp/x"])
    assert r.returncode != 0 and "--resume" in r.stderr
    r = run_cli(["gen:poisson2d:8", "--comm", "none", "--chaos",
                 "boom"])
    assert r.returncode != 0


def test_supervisor_crash_relaunch_single_device(tmp_path):
    """crash:exit kills the child (rc 94); the supervisor relaunches
    with --resume and the solve converges -- with the acg_recovery_*
    families on the supervisor's metrics textfile."""
    ck = str(tmp_path / "ck")
    prom = str(tmp_path / "sup.prom")
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--ckpt", ck, "--ckpt-every", "8",
                 "--fault-inject", "crash:exit@20",
                 "--supervise", "--relaunch-backoff", "0",
                 "--metrics-file", prom])
    assert r.returncode == 0, r.stderr
    assert "relaunch 1/3 with --resume" in r.stderr
    assert "recovery:" in r.stderr
    assert "outcome: converged (rc 0)" in r.stderr
    text = open(prom).read()
    assert 'acg_recovery_relaunches_total{reason="crash"} 1' in text
    assert "acg_recovery_mttr_seconds_count 1" in text


@pytest.mark.slow
def test_supervisor_budget_exhaustion(tmp_path):
    """A child that keeps failing (unresolvable config failure after
    the first crash consumed the snapshot) spends the budget and exits
    95."""
    ck = str(tmp_path / "ck")
    # a fault-free child that cannot converge in 1 iteration: rc 1
    # every time; budget 1 -> rc 95 after one relaunch
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "1", "--residual-rtol", "1e-12",
                 "--warmup", "0", "--quiet",
                 "--ckpt", ck, "--ckpt-secs", "30",
                 "--supervise", "--relaunch-budget", "1",
                 "--relaunch-backoff", "0"])
    # no snapshot is ever committed in 1 iteration -> not relaunchable
    # via resume; the supervisor passes the failure through
    assert r.returncode in (1, int(ExitCode.RELAUNCH_BUDGET))


def test_supervisor_shrink_elastic_e2e(tmp_path):
    """THE acceptance e2e: crash mid-solve on the 8-part mesh -> the
    supervisor relaunches with --resume --resume-repartition on 4
    parts -> the final true relative residual meets the original
    rtol, and the relaunched child's status document says degraded."""
    ck = str(tmp_path / "ck")
    out = str(tmp_path / "x.mtx")
    status = str(tmp_path / "status.json")
    r = run_cli(["gen:poisson2d:20", "--nparts", "8",
                 "--max-iterations", "400", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--ckpt", ck, "--ckpt-every", "8",
                 "--fault-inject", "crash:exit@20",
                 "--supervise", "--shrink", "any",
                 "--relaunch-backoff", "0",
                 "--status-file", status, "-o", out])
    assert r.returncode == 0, r.stderr
    assert "shrinking 8 -> 4 parts" in r.stderr
    assert "degraded: 8 -> 4 parts (crash)" in r.stderr
    # independent verification: the answer meets the ORIGINAL rtol
    csr = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2)).to_csr()
    b = np.ones(csr.shape[0])
    ok, rel = sup.verify_solution(csr, b, out, 1e-8)
    assert ok, rel
    assert rel < 1e-7
    doc = json.load(open(status))
    assert doc["degraded"] == {"from": 8, "to": 4, "reason": "crash"}


def test_chaos_campaign_small(tmp_path):
    """A seeded 4-schedule campaign (abft + ckpt armed) ends every
    run converged or agreed-abort, records acg-tpu-chaos/1 ledger
    rows, and exits 0 -- zero wrong-answer-green."""
    hist = str(tmp_path / "hist")
    r = run_cli(["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--ckpt", str(tmp_path / "ck"), "--ckpt-every", "8",
                 "--audit-every", "5", "--abft",
                 "--chaos", "2026:4", "--relaunch-backoff", "0",
                 "--history", hist])
    assert r.returncode == 0, r.stderr
    assert "wrong-answer: 0" in r.stderr
    rows = []
    for name in os.listdir(hist):
        with open(os.path.join(hist, name)) as f:
            for line in f:
                obj = json.loads(line)
                if obj.get("schema") == "acg-tpu-chaos/1":
                    rows.append(obj)
    assert len(rows) == 4
    outcomes = {r_["doc"]["chaos"]["outcome"] for r_ in rows}
    assert outcomes <= {"converged", "agreed-abort"}
    # the schedules are re-runnable: each records its fault spec
    for r_ in rows:
        spec = r_["doc"]["chaos"]["fault"]
        if spec is not None:
            faults.parse_fault_spec(spec)


@pytest.mark.slow
def test_chaos_campaign_acceptance_20_schedules(tmp_path):
    """The full ISSUE-10 acceptance bar: >= 20 seeded schedules on the
    8-part mesh through the supervisor (shrink armed), every run
    converged or agreed-abort, ZERO wrong-answer-green."""
    hist = str(tmp_path / "hist")
    r = run_cli(["gen:poisson2d:20", "--nparts", "8",
                 "--max-iterations", "400", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--ckpt", str(tmp_path / "ck"), "--ckpt-every", "8",
                 "--audit-every", "5", "--abft", "--shrink", "any",
                 "--chaos", "4242:20", "--relaunch-backoff", "0",
                 "--history", hist],
                timeout=3000)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "schedules: 20" in r.stderr
    assert "wrong-answer: 0" in r.stderr
