"""Replay-based per-op profiling tier (solvers/profile.py)."""

import numpy as np
import pytest

from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.profile import profile_ops
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(16)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_profile_single_device(csr):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(A)
    b = np.ones(csr.shape[0])
    solver.solve(b, criteria=StoppingCriteria(maxits=20))
    per_call = profile_ops(solver, b, reps=3)
    # nrm2/copy joined the replay when the compiled solvers' counters
    # for them stopped being permanently zero (PR 2 satellite);
    # chain_overhead is the scalar-chain correction term reported as an
    # explicit key (PR 3 satellite) -- one axpy-equivalent per call
    assert set(per_call) == {"gemv", "dot", "nrm2", "axpy", "copy",
                             "dispatch", "chain_overhead"}
    assert all(t >= 0 for t in per_call.values())
    assert per_call["dispatch"] > 0
    assert per_call["chain_overhead"] == per_call["axpy"]
    st = solver.stats
    for op in ("gemv", "dot", "nrm2", "axpy", "copy"):
        assert st.ops[op].n > 0
        assert st.ops[op].t == pytest.approx(per_call[op] * st.ops[op].n)


def test_profile_distributed(csr):
    import jax.numpy as jnp

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    part = partition_rows(csr, 4, seed=0)
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    solver = DistCGSolver(prob)
    b = np.ones(csr.shape[0])
    solver.solve(b, criteria=StoppingCriteria(maxits=20))
    per_call = profile_ops(solver, b, reps=3)
    assert {"gemv", "dot", "axpy", "allreduce", "dispatch"} <= set(per_call)
    assert "halo" in per_call  # 4-way Poisson partition has ghosts
    assert all(t >= 0 for t in per_call.values())
    st = solver.stats
    # stats scale consistently from per_call (values may clamp to 0
    # under host contention -- the estimator is a lower-bounded diff)
    assert st.ops["gemv"].t == pytest.approx(
        per_call["gemv"] * st.ops["gemv"].n)


def test_profile_unwraps_refined(csr):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.refine import RefinedSolver

    A = device_matrix_from_csr(csr, dtype=jnp.float32)
    inner = JaxCGSolver(A)
    solver = RefinedSolver(inner, csr)
    b = np.ones(csr.shape[0])
    solver.solve(b, criteria=StoppingCriteria(maxits=50, residual_rtol=1e-6))
    per_call = profile_ops(solver, b, reps=2)
    assert per_call and inner.stats.ops["gemv"].t >= 0
    assert per_call["dispatch"] > 0
