"""Live solve observatory (acg_tpu.observatory): in-flight status
endpoint/file, run-history ledger, and SLO burn tracking.

Covers the PR-9 acceptance criteria: a poller observes iteration and
residual ADVANCING across >= 2 polls mid-solve (with iterations/sec and
ETA populated), disarmed programs lower byte-identical on the single
and dist tiers, the history ledger round-trips through
history_report/bench_diff/plot_convergence (including /7 documents and
the all-unavailable exit-2 refusal), concurrent /status + /metrics
scrapes never see torn output, the --progress heartbeat carries the
it/s + ETA fields on every tier including the host oracle, and
--fail-on-slo gates with exit 8.
"""

import importlib.util
import json
import math
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import metrics, observatory, telemetry
from acg_tpu.checkpoint import CheckpointConfig
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import SolverStats, StoppingCriteria

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


def run_script(name, argv, **kw):
    kw.setdefault("timeout", 300)
    return subprocess.run([sys.executable,
                           os.path.join(SCRIPTS, name), *argv],
                          capture_output=True, text=True, **kw)


@pytest.fixture(autouse=True)
def _clean_observatory():
    """Every test leaves the process-wide recorder and SLO state the
    way it found it (the metrics/tracing discipline)."""
    yield
    observatory.shutdown()
    metrics.disarm()


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(12)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def _doc(schema="acg-tpu-stats/12", metric=None, matrix="m", solver="acg",
         tsolve=0.1, niter=20, soak=None, unix_time=None):
    """A minimal synthetic stats document (the shape history_append
    indexes)."""
    man = {"schema": schema, "matrix": matrix, "solver": solver,
           "dtype": "f64", "nparts": 1,
           "unix_time": unix_time if unix_time is not None
           else time.time()}
    if metric is not None:
        man["metric"] = metric
    st = {"tsolve": tsolve, "niterations": niter, "converged": True}
    if soak is not None:
        st["soak"] = soak
    return {"schema": schema, "manifest": man, "stats": st}


# -- the SolveStatus recorder --------------------------------------------

def test_status_document_schema_and_rates():
    observatory.arm()
    observatory.begin_solve("cg", maxits=100, rtol=1e-8,
                            matrix="gen:test", nparts=4)
    t = [time.time()]
    observatory.STATUS.trail.append((t[0] - 1.0, 10, 1e-2))
    observatory.STATUS.sample("cg", 60, 1e-4)
    observatory.STATUS.note_target(1e-8)
    doc = observatory.status_document()
    assert doc["schema"] == "acg-tpu-status/1"
    assert doc["phase"] is None or isinstance(doc["phase"], str)
    s = doc["solve"]
    assert s["what"] == "cg" and s["active"] is True
    assert s["iteration"] == 60 and s["matrix"] == "gen:test"
    # two trail samples 1 s apart, 50 iterations -> ~50 it/s
    assert s["iterations_per_second"] == pytest.approx(50.0, rel=0.5)
    # decreasing residual + absolute target -> the measured-rate ETA
    assert s["eta_seconds"] is not None and s["eta_seconds"] > 0
    assert s["eta_source"] == "measured-rate"
    assert doc["residual_trail"][-1] == [60, 1e-4]


def test_eta_prefers_kappa_bound():
    observatory.arm()
    observatory.begin_solve("cg", maxits=1000, rtol=1e-8)
    observatory.STATUS.trail.append((time.time() - 1.0, 10, 1e-2))
    observatory.STATUS.sample("cg", 60, 1e-3)
    observatory.note_kappa(100.0, predicted_total=200)
    ips, eta, source = observatory.STATUS.rates()
    assert source == "kappa-bound"
    # ~140 remaining at ~50 it/s
    assert eta == pytest.approx(140.0 / ips, rel=1e-6)


def test_disarmed_hooks_are_noops():
    assert not observatory.armed()
    observatory.note_chunk("cg", 5, 1e-3)
    observatory.note_event("x", "y")
    observatory.note_kappa(10.0, 50)
    observatory.note_imbalance({"count": 2})
    doc = observatory.status_document()
    assert not doc["residual_trail"] and "events" not in doc
    assert "kappa" not in doc and "imbalance" not in doc
    # begin/end and the heartbeat tracker stay live even disarmed:
    # they are what gives --progress lines the it/s + ETA fields
    observatory.begin_solve("cg", maxits=10)
    assert observatory.status_document()["solve"]["maxits"] == 10


def test_trail_resets_when_iteration_goes_backwards():
    observatory.arm()
    observatory.STATUS.sample("cg", 50, 1e-3)
    observatory.STATUS.sample("cg", 60, 1e-4)
    observatory.STATUS.sample("cg", 5, 1e-1)   # new solve / rollback
    assert [k for _, k, _ in observatory.STATUS.trail] == [5]


def test_status_file_atomic_json(tmp_path):
    path = tmp_path / "status.json"
    observatory.arm()
    observatory.set_status_file(path)
    observatory.begin_solve("cg", maxits=10)
    observatory.flush_status(force=True)
    doc = json.loads(path.read_text())
    assert doc["schema"] == "acg-tpu-status/1"
    assert not [p for p in os.listdir(tmp_path)
                if p.startswith("status.json.tmp")]


def test_heartbeat_line_carries_rate_and_eta():
    observatory.STATUS.reset()
    line0 = observatory.heartbeat_line("cg", 10, 1.0)
    assert line0.startswith("acg-tpu: cg: iteration 10: "
                            "residual 2-norm")
    assert "it/s" not in line0          # one sample: no rate yet
    observatory.STATUS.trail.appendleft((time.time() - 1.0, 0, 10.0))
    line1 = observatory.heartbeat_line("cg", 20, 1e-2)
    assert "it/s" in line1


def test_host_oracle_progress_emits_rate_fields(csr, capfd):
    from acg_tpu.solvers.host_cg import HostCGSolver

    s = HostCGSolver(csr, progress=5)
    s.solve(np.ones(csr.shape[0]),
            criteria=StoppingCriteria(maxits=60, residual_rtol=1e-10))
    err = capfd.readouterr().err
    assert "host-cg: iteration 5: residual 2-norm" in err
    # by the second heartbeat two samples exist -> rate + ETA fields
    later = [ln for ln in err.splitlines()
             if "iteration 10:" in ln or "iteration 15:" in ln]
    assert later and any("it/s" in ln for ln in later)


# -- acceptance: polling the endpoint DURING a chunked solve -------------

def test_status_endpoint_advances_during_chunked_solve(tmp_path):
    """The headline acceptance: a chunked single-tier solve is watched
    over the HTTP endpoint; iteration and residual must ADVANCE across
    >= 2 polls with iterations/sec and ETA populated mid-flight."""
    r, c, v, N = poisson2d_coo(40)
    csr40 = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr40, dtype=jnp.float64)
    s = JaxCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=4))
    observatory.arm()
    observatory.begin_solve("cg", maxits=300, rtol=1e-10,
                            matrix="gen:poisson2d:40")
    server = observatory.serve_status(0)
    port = server.server_address[1]
    b = np.ones(N)
    crit = StoppingCriteria(maxits=300, residual_rtol=1e-10)
    done = threading.Event()
    err: list = []

    def solve():
        try:
            s.solve(b, criteria=crit)
        except Exception as e:  # noqa: BLE001 -- surfaced below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=solve, daemon=True)
    t.start()
    seen: list[dict] = []
    deadline = time.time() + 120
    try:
        while not done.is_set() and time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=10) as r_:
                doc = json.loads(r_.read())
            sv = doc.get("solve") or {}
            if sv.get("iteration") and sv.get("residual") is not None:
                if not seen or sv["iteration"] != \
                        seen[-1]["iteration"]:
                    seen.append(sv)
            time.sleep(0.002)
        t.join(timeout=120)
    finally:
        server.shutdown()
        server.server_close()
    assert not err, err
    mid = [sv for sv in seen if sv.get("active")]
    assert len(mid) >= 2, f"only {len(mid)} mid-flight polls: {seen}"
    its = [sv["iteration"] for sv in mid]
    res = [sv["residual"] for sv in mid]
    assert its == sorted(its) and its[-1] > its[0]
    assert res[-1] < res[0]
    # rate + ETA populated once two chunk samples existed
    rated = [sv for sv in mid if sv.get("iterations_per_second")]
    assert rated and any(sv.get("eta_seconds") for sv in rated)
    assert any(sv.get("eta_source") in ("measured-rate", "kappa-bound",
                                        "iteration-cap")
               for sv in rated)


# -- disarmed byte-identity (single + dist tiers) ------------------------

def test_disarmed_programs_byte_identical(csr):
    """Arming the observatory cannot touch the compiled programs: all
    recording is host-side.  Pinned at the HLO level on both tiers (the
    telemetry/faults convention)."""
    b1 = np.ones(csr.shape[0])
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    plain = JaxCGSolver(A, kernels="xla").lower_solve(b1).as_text()
    observatory.arm()
    observatory.begin_solve("cg", maxits=100, rtol=1e-8)
    armed = JaxCGSolver(A, kernels="xla").lower_solve(b1).as_text()
    assert armed == plain

    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    b2 = np.ones(prob.n)
    observatory.shutdown()
    d_plain = DistCGSolver(prob).lower_solve(b2).as_text()
    observatory.arm()
    d_armed = DistCGSolver(prob).lower_solve(b2).as_text()
    assert d_armed == d_plain


# -- SLO tracking ---------------------------------------------------------

def test_parse_slo():
    spec = observatory.parse_slo("latency=1.5,iters=100,gap=1e-4")
    assert spec.latency_s == 1.5 and spec.iters == 100
    assert spec.gap == pytest.approx(1e-4)
    assert observatory.parse_slo("latency=2").iters is None
    for bad in ("", "latency", "latency=-1", "iters=0", "foo=3",
                "latency=abc"):
        with pytest.raises(ValueError):
            observatory.parse_slo(bad)


def test_slo_observe_breach_metrics_and_events():
    metrics.arm()
    observatory.install_slo(observatory.parse_slo("latency=0.5,iters=10"))
    st = SolverStats()
    # first solve: healthy
    assert not observatory.slo_observe(st, latency=0.1, iterations=5)
    # second: both objectives breached
    assert observatory.slo_observe(st, latency=1.0, iterations=50)
    assert [e["kind"] for e in st.events] == ["slo-breach",
                                              "slo-breach"]
    rep = observatory.slo_report()
    assert rep["breached"] is True
    assert rep["breaches"] == {"latency": 1, "iters": 1}
    assert rep["burn"]["latency"] == pytest.approx(0.5)
    txt = metrics.expose()
    assert 'acg_slo_target{objective="latency"} 0.5' in txt
    assert 'acg_slo_breaches_total{objective="iters"} 1' in txt
    assert 'acg_slo_burn_ratio{objective="latency"} 0.5' in txt
    assert observatory.slo_exit_code(True) == 8
    assert observatory.slo_exit_code(False) == 0
    observatory.attach_slo(st)
    assert st.slo["targets"]["latency"] == 0.5


def test_cli_slo_gate_exit_8(tmp_path):
    status = tmp_path / "status.json"
    r = run_cli(["gen:poisson2d:12", "--comm", "none",
                 "--max-iterations", "100", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--slo", "latency=0.000001", "--fail-on-slo",
                 "--status-file", str(status),
                 "--stats-json", str(tmp_path / "s.json")])
    assert r.returncode == 8, (r.returncode, r.stderr)
    assert "SLO breach: latency" in r.stderr
    doc = json.loads(status.read_text())
    assert doc["schema"] == "acg-tpu-status/1"
    assert doc["phase"] == "exited"
    assert doc["solve"]["active"] is False
    assert doc["slo"]["breached"] is True
    sj = json.loads((tmp_path / "s.json").read_text())
    assert sj["schema"] == "acg-tpu-stats/12"
    assert sj["stats"]["slo"]["breaches"]["latency"] == 1
    assert any(e["kind"] == "slo-breach"
               for e in sj["stats"]["events"])


def test_cli_flag_validation():
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--fail-on-slo"])
    assert r.returncode != 0 and "--fail-on-slo needs --slo" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--slo", "bogus=3"])
    assert r.returncode != 0 and "--slo" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--slo", "gap=1e-3"])
    assert r.returncode != 0 and "--audit-every" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--status-port", "99999"])
    assert r.returncode != 0 and "--status-port" in r.stderr


def test_cli_history_refuses_file_path(tmp_path):
    f = tmp_path / "ledger"
    f.write_text("x")
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--history", str(f)])
    assert r.returncode != 0 and "needs a directory" in r.stderr


# -- run-history ledger ---------------------------------------------------

def test_history_append_scan_roundtrip(tmp_path):
    d = tmp_path / "hist"
    p1 = observatory.history_append(d, _doc(tsolve=0.1, niter=10))
    p2 = observatory.history_append(d, _doc(tsolve=0.2, niter=12))
    assert p1 == p2 and p1.endswith(".jsonl")
    entries = observatory.history_scan(d)
    assert len(entries) == 2
    idx = entries[0]
    assert idx["ledger"] == "acg-tpu-history/1"
    assert idx["schema"] == "acg-tpu-stats/12"
    assert idx["matrix"] == "m" and idx["dtype"] == "f64"
    assert idx["iterations"] == 10
    assert idx["latency_s"] == pytest.approx(0.1)
    assert idx["case"] == "acg:m"
    assert idx["doc"]["stats"]["niterations"] == 10
    # a torn trailing append yields the usable prefix, not an error
    with open(p1, "a") as f:
        f.write('{"ledger": "acg-tpu-history/1", "trunc')
    assert len(observatory.history_scan(d)) == 2


def test_history_baseline_picks_best_usable_and_skips_unavailable(
        tmp_path):
    d = tmp_path / "hist"
    observatory.history_append(d, _doc(tsolve=0.2, niter=20))   # 100/s
    observatory.history_append(d, _doc(tsolve=0.1, niter=20))   # 200/s
    observatory.history_append(
        d, _doc(metric="bench_backend_unavailable", tsolve=1.0,
                niter=1))
    cases, all_unavail, n = observatory.load_history_baseline(d)
    assert n == 3 and not all_unavail
    assert cases == {"acg:m": pytest.approx(200.0)}


def test_history_all_unavailable_refuses_exit_2(tmp_path):
    d = tmp_path / "hist"
    for _ in range(2):
        observatory.history_append(
            d, _doc(metric="bench_backend_unavailable", tsolve=1.0,
                    niter=1))
    cases, all_unavail, _ = observatory.load_history_baseline(d)
    assert all_unavail and not cases
    # the library gate
    from acg_tpu.perfmodel import check_regression
    rows = [{"metric": "solve", "value": 100.0}]
    assert check_regression(rows, str(d), 10.0) == 2
    # the script gate, with the re-baseline message
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(_doc()) + "\n")
    r = run_script("bench_diff.py",
                   ["--baseline-from-history", str(d), str(cand)])
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "re-baseline" in r.stderr
    # a ledger of FAILED runs (no usable value, but not the sentinel)
    # still refuses -- with the generic message, never the
    # backend-was-down diagnosis
    d2 = tmp_path / "hist-failed"
    observatory.history_append(d2, _doc(tsolve=0.0, niter=0))
    cases, all_unavail, _ = observatory.load_history_baseline(d2)
    assert not cases and not all_unavail
    r = run_script("bench_diff.py",
                   ["--baseline-from-history", str(d2), str(cand)])
    assert r.returncode == 2
    assert "re-baseline" not in r.stderr
    assert "no usable ledger entries" in r.stderr


def test_bench_diff_from_history_and_regression(tmp_path):
    d = tmp_path / "hist"
    observatory.history_append(d, _doc(tsolve=0.1, niter=20))   # 200/s
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_doc(tsolve=0.11, niter=20)) + "\n")
    r = run_script("bench_diff.py",
                   ["--baseline-from-history", str(d), str(good),
                    "--fail-on-regress", "20"])
    assert r.returncode == 0, (r.stdout, r.stderr)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_doc(tsolve=0.4, niter=20)) + "\n")
    r = run_script("bench_diff.py",
                   ["--baseline-from-history", str(d), str(bad),
                    "--fail-on-regress", "20"])
    assert r.returncode == 1 and "REGRESSION" in r.stdout
    # exactly one baseline source
    r = run_script("bench_diff.py", [str(good)])
    assert r.returncode == 2
    r = run_script("bench_diff.py",
                   ["--baseline-from-history", str(d), str(good),
                    str(bad)])
    assert r.returncode == 2


def test_history_report_flags_dilated_drift(tmp_path):
    """The acceptance: a ledger whose trailing run was dilated (the
    solve:slow@K soak shape: same case, inflated latency) gets the
    DRIFT flag, and --fail-on-drift exits 7 (the soak gate's code)."""
    d = tmp_path / "hist"
    t0 = time.time()
    for i, lat in enumerate([0.1, 0.1, 0.1, 0.1, 1.1]):
        observatory.history_append(
            d, _doc(tsolve=lat, niter=20, unix_time=t0 + i))
    r = run_script("history_report.py", [str(d)])
    assert r.returncode == 0, r.stderr
    assert "DRIFT" in r.stdout
    assert "5 run(s)" in r.stdout
    r = run_script("history_report.py", [str(d), "--fail-on-drift"])
    assert r.returncode == 7
    # a stable ledger never flags
    d2 = tmp_path / "hist2"
    for i in range(5):
        observatory.history_append(
            d2, _doc(tsolve=0.1, niter=20, unix_time=t0 + i))
    r = run_script("history_report.py", [str(d2), "--fail-on-drift"])
    assert r.returncode == 0 and "DRIFT" not in r.stdout


def test_plot_convergence_renders_history_trend(tmp_path):
    d = tmp_path / "hist"
    for i, lat in enumerate([0.1, 0.2, 0.15]):
        observatory.history_append(
            d, _doc(tsolve=lat, niter=20, unix_time=time.time() + i))
    ledger = os.path.join(str(d), sorted(os.listdir(d))[0])
    r = run_script("plot_convergence.py", ["--ascii", ledger])
    assert r.returncode == 0, r.stderr
    assert "run-history ledger, 3 entries" in r.stdout
    assert "acg:m" in r.stdout and "latency first" in r.stdout


def test_v7_documents_still_load(tmp_path):
    """The additive-schema acceptance: /7 documents (no slo key) still
    flow through the ledger, bench_diff and plot_convergence."""
    doc7 = _doc(schema="acg-tpu-stats/7", tsolve=0.1, niter=20,
                soak={"nsolves": 3,
                      "latency": {"p50": 0.1, "p95": 0.12, "p99": 0.2},
                      "iterations": {"p50": 20},
                      "drift": {"ratio": 1.0, "tripped": False}})
    del doc7["stats"]["soak"]["drift"]["tripped"]  # keep it minimal
    f7 = tmp_path / "v7.json"
    f7.write_text(json.dumps(doc7))
    # bench_diff: a /7 capture diffs against itself cleanly
    r = run_script("bench_diff.py", [str(f7), str(f7)])
    assert r.returncode == 0, (r.stdout, r.stderr)
    # plot_convergence classifies the /7 soak capture
    r = run_script("plot_convergence.py", ["--ascii", str(f7)])
    assert r.returncode == 0 and "latency" in r.stdout
    # the ledger indexes it (p50 latency preferred) and baselines it
    d = tmp_path / "hist"
    observatory.history_append(d, doc7)
    e = observatory.history_scan(d)[0]
    assert e["schema"] == "acg-tpu-stats/7"
    assert e["latency_s"] == pytest.approx(0.1)
    cases, all_unavail, _ = observatory.load_history_baseline(d)
    assert not all_unavail and cases  # p50 its / p50 latency
    r = run_script("plot_convergence.py",
                   ["--ascii", os.path.join(str(d),
                                            sorted(os.listdir(d))[0])])
    assert r.returncode == 0 and "run-history ledger" in r.stdout


# -- concurrent scrapes (satellite): no torn documents -------------------

def test_concurrent_scrapes_mid_soak(csr, tmp_path):
    """/status and /metrics polled from threads mid-soak must return a
    valid document on EVERY poll -- no torn JSON, no half-written
    exposition."""
    from acg_tpu.soak import run_soak

    spec = importlib.util.spec_from_file_location(
        "check_metrics_textfile",
        os.path.join(SCRIPTS, "check_metrics_textfile.py"))
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)

    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A)
    observatory.arm()
    metrics.arm()
    server = observatory.serve_status(0)
    port = server.server_address[1]
    done = threading.Event()
    problems: list = []

    def poll():
        n = 0
        while True:
            n += 1
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/status",
                        timeout=10) as r:
                    doc = json.loads(r.read())
                if doc.get("schema") != "acg-tpu-status/1":
                    problems.append(f"bad schema: {doc}")
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=10) as r:
                    text = r.read().decode()
                prom = tmp_path / f"scrape-{threading.get_ident()}.prom"
                prom.write_text(text)
                # format validity on every poll; the solve counters
                # only EXIST after the first solve, so presence is
                # asserted once at the end, not mid-poll
                problems.extend(checker.check(str(prom)))
            except Exception as e:  # noqa: BLE001 -- a failed poll IS
                problems.append(repr(e))  # the failure being tested
            if done.is_set() and n >= 3:
                break

    threads = [threading.Thread(target=poll, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        run_soak(s, np.ones(csr.shape[0]), nsolves=6,
                 criteria=StoppingCriteria(maxits=100,
                                           residual_rtol=1e-8))
    finally:
        done.set()
        for t in threads:
            t.join(timeout=60)
        server.shutdown()
        server.server_close()
    assert not problems, problems[:5]
    # the soak progress reached the status plane, and the final
    # exposition carries the solve counters
    doc = observatory.status_document()
    assert doc["soak"] == {"solve": 6, "nsolves": 6}
    assert doc["solves_completed"] == 6
    final = tmp_path / "final.prom"
    final.write_text(metrics.expose())
    assert not checker.check(str(final), require=["acg_solves_total"])


# -- CLI end-to-end: chunked dist solve with the full plane armed --------

def test_cli_status_file_history_dist_chunked(tmp_path):
    """The T1_STATUS smoke in miniature: a chunked 8-part CPU-mesh
    solve with --status-file + --history + --slo; the status document
    validates, the ledger row lands, and the acg_slo_* families are
    exposed."""
    status = tmp_path / "status.json"
    hist = tmp_path / "hist"
    prom = tmp_path / "m.prom"
    r = run_cli(["gen:poisson2d:24", "--nparts", "8",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet",
                 "--ckpt", str(tmp_path / "ck"), "--ckpt-every", "16",
                 "--status-file", str(status),
                 "--history", str(hist),
                 "--slo", "latency=30,iters=250",
                 "--metrics-file", str(prom),
                 "--stats-json", str(tmp_path / "s.json")])
    assert r.returncode == 0, r.stderr
    doc = json.loads(status.read_text())
    assert doc["schema"] == "acg-tpu-status/1"
    assert doc["solve"]["converged"] is True
    assert doc["solve"]["iteration"] > 0
    assert doc["residual_trail"]  # chunk samples landed
    assert "snapshot" in {e["kind"] for e in doc.get("events", [])}
    entries = observatory.history_scan(hist)
    assert len(entries) == 1
    assert entries[0]["nparts"] == 8
    assert entries[0]["doc"]["stats"]["slo"]["targets"]["iters"] == 250
    txt = prom.read_text()
    assert "acg_slo_target" in txt and "acg_slo_burn_ratio" in txt
