"""Communication-avoiding recurrences (acg_tpu.recurrence): s-step CG
and deep-pipelined p(l)-CG across the solver tiers, plus the builder's
spec/schedule surfaces.

The HLO-level pins (builder byte-identity, collective counts) live in
tests/test_hlo_structure.py; this file covers the numerics -- host-
oracle trajectory parity, single<->dist parity, the aniso-family
convergence acceptance, the p(l) Lanczos-recovery identity -- and the
integration surfaces (telemetry ring alignment, kappa estimation,
health gates, comm ledger, CLI, refusals)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import scipy.sparse as sp

import jax.numpy as jnp

from acg_tpu import recurrence as rec
from acg_tpu.io.generators import aniso_poisson2d_coo, poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria

RTOL = 1e-8


def _aniso(n=32, eps=0.1):
    r, c, v, N = aniso_poisson2d_coo(n, eps)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    Asp = sp.coo_matrix((v, (r, c)), shape=(N, N)).tocsr()
    return csr, Asp, N


@pytest.fixture(scope="module")
def aniso():
    csr, Asp, N = _aniso()
    rng = np.random.default_rng(7)
    return {
        "csr": csr, "Asp": Asp, "N": N,
        "A": device_matrix_from_csr(csr, dtype=jnp.float64),
        "b": rng.standard_normal(N),
    }


@pytest.fixture(scope="module")
def classic_iters(aniso):
    s = JaxCGSolver(aniso["A"], kernels="xla")
    s.solve(aniso["b"], criteria=StoppingCriteria(residual_rtol=RTOL,
                                                  maxits=5000))
    return s.stats.niterations


# -- spec parsing ----------------------------------------------------------

def test_parse_algorithm():
    assert rec.parse_algorithm(None) is None
    assert rec.parse_algorithm("auto") is None
    assert rec.parse_algorithm("classic").kind == "classic"
    assert rec.parse_algorithm("pipelined").kind == "pipelined"
    s4 = rec.parse_algorithm("sstep:4")
    assert (s4.kind, s4.param) == ("sstep", 4)
    assert s4.basis == "chebyshev" and s4.needs_lam
    s2 = rec.parse_algorithm("sstep:2")
    assert s2.basis == "monomial" and not s2.needs_lam
    p2 = rec.parse_algorithm("pipelined:2")
    assert (p2.kind, p2.param) == ("pl", 2) and p2.needs_lam
    assert str(s4) == "sstep:4" and str(p2) == "pipelined:2"
    # the solver names deliberately avoid the "pipelined" substring
    # (health.spectrum_estimate keys its re-alignment on it)
    assert "pipelined" not in s4.solver_name("cg")
    assert "pipelined" not in p2.solver_name("dist-cg")
    for bad in ("sstep:1", "sstep:99", "pipelined:0", "pipelined:9",
                "nope"):
        with pytest.raises(ValueError):
            rec.parse_algorithm(bad)


def test_reduction_schedule():
    s8 = rec.reduction_schedule(rec.RecurrenceSpec("sstep", 8), False)
    assert s8["allreduce_per_iteration"] == pytest.approx(1 / 8)
    assert s8["allreduce_scalars"] == 17 * 17
    assert s8["spmv_per_iteration"] == pytest.approx(15 / 8)
    p3 = rec.reduction_schedule(rec.RecurrenceSpec("pl", 3), False)
    assert p3["allreduce_per_iteration"] == 1.0
    assert p3["allreduce_scalars"] == 8
    assert p3["reduction_latency_hidden"] == 3
    assert rec.reduction_schedule(None, False)[
        "allreduce_per_iteration"] == 2.0
    assert rec.reduction_schedule(None, True)[
        "allreduce_per_iteration"] == 1.0


# -- s-step: host-oracle trajectory parity + convergence acceptance --------

def test_sstep_host_oracle_trajectory_parity(aniso):
    """The compiled s-step program's telemetry ring records the SAME
    (gamma, alpha, beta) trajectory as the eager f64 host oracle --
    per-scalar, not just the iteration count."""
    s = 4
    lam = rec.estimate_lam(aniso["A"], aniso["N"], jnp.float64)
    _, k_h, _, traj = rec.host_sstep_cg(
        aniso["Asp"], aniso["b"], rtol=RTOL, maxits=5000, s=s, lam=lam)
    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm=f"sstep:{s}", trace=4096)
    solver.solve(aniso["b"],
                 criteria=StoppingCriteria(residual_rtol=RTOL,
                                           maxits=5000))
    assert solver.stats.niterations == k_h
    recs = np.asarray(solver.last_trace.records, dtype=np.float64)
    th = np.asarray(traj, dtype=np.float64)
    m = min(len(th), recs.shape[0])
    assert m > 50
    # same recurrence, same arithmetic order: tight relative agreement
    # (from_ring converts the stored ||r||^2 to norms -- sqrt here too)
    np.testing.assert_allclose(recs[:m, 0], np.sqrt(th[:m, 0]),
                               rtol=1e-6)
    np.testing.assert_allclose(recs[:m, 1], th[:m, 1], rtol=1e-6)


@pytest.mark.parametrize("s", [2, 4, 8])
def test_sstep_convergence_acceptance(aniso, classic_iters, s):
    """The aniso-family acceptance: s-step converges to the standard
    rtol with an iteration count inside the CA-CG stability band
    (measured: EXACT parity with classic in f64 for all three S)."""
    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm=f"sstep:{s}")
    x = solver.solve(aniso["b"],
                     criteria=StoppingCriteria(residual_rtol=RTOL,
                                               maxits=5000))
    assert solver.stats.converged
    # true-residual check, not just the recurrence's word
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL
    # the CA-CG stability band: within one block of classic
    assert abs(solver.stats.niterations - classic_iters) <= s


def test_sstep_dist_matches_single(aniso, classic_iters):
    """8-part mesh parity: the dist s-step program (same recurrence
    code, dist TierOps) converges with the same iteration count."""
    part = partition_rows(aniso["csr"], 8, seed=0, method="band")
    prob = DistributedProblem.build(aniso["csr"], part, 8,
                                    dtype=jnp.float64)
    solver = DistCGSolver(prob, algorithm="sstep:4")
    x = solver.solve(aniso["b"],
                     criteria=StoppingCriteria(residual_rtol=RTOL,
                                               maxits=5000))
    assert solver.stats.converged
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL
    assert abs(solver.stats.niterations - classic_iters) <= 4


def test_sstep_unbounded_runs_exactly_maxits(aniso):
    solver = JaxCGSolver(aniso["A"], kernels="xla", algorithm="sstep:4")
    solver.solve(aniso["b"], criteria=StoppingCriteria(maxits=37))
    assert solver.stats.niterations == 37
    assert solver.stats.converged  # unbounded semantics


# -- p(l): convergence via restarts + the Lanczos-recovery identity --------

@pytest.mark.parametrize("l", [1, 2, 3])
def test_pl_convergence_acceptance(aniso, classic_iters, l):
    """Restarted p(l)-CG reaches the standard rtol on the aniso family.
    The sqrt breakdown of the deep pipeline restarts from the current
    iterate through the standard recovery ladder (armed by default for
    p(l)); the measured band is <= ~1.9x classic, pinned at 3x."""
    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm=f"pipelined:{l}")
    x = solver.solve(aniso["b"],
                     criteria=StoppingCriteria(residual_rtol=RTOL,
                                               maxits=5000))
    assert solver.stats.converged
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL
    assert solver.stats.niterations <= 3 * classic_iters


def test_pl_dist_converges(aniso, classic_iters):
    part = partition_rows(aniso["csr"], 8, seed=0, method="band")
    prob = DistributedProblem.build(aniso["csr"], part, 8,
                                    dtype=jnp.float64)
    solver = DistCGSolver(prob, algorithm="pipelined:2")
    x = solver.solve(aniso["b"],
                     criteria=StoppingCriteria(residual_rtol=RTOL,
                                               maxits=5000))
    assert solver.stats.converged
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL
    assert solver.stats.niterations <= 3 * classic_iters


def test_pl_recovers_reference_lanczos(aniso):
    """The deep pipeline's WHOLE correctness argument: the T entries it
    recovers with lag l from the z-window Gram are the true Lanczos
    coefficients.  The telemetry ring records (q^2, 1/d, l^2, d) at
    solution-advance time; d_k (the LDL pivot of T_k) recomputed from a
    reference f64 Lanczos must match the ring's pAp column."""
    l = 2
    N = aniso["N"]
    b = aniso["b"]
    Asp = aniso["Asp"]
    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm=f"pipelined:{l}", trace=4096)
    # fixed 30 advances: well inside the first attempt (the aniso
    # sqrt breakdown arrives ~iteration 50+), so the ring is the
    # UNrestarted trajectory the reference Lanczos reproduces
    solver.solve(b, criteria=StoppingCriteria(maxits=30))
    recs = np.asarray(solver.last_trace.records, dtype=np.float64)
    # reference Lanczos + LDL pivots from the same start
    r0 = b.astype(np.float64)
    eta = np.linalg.norm(r0)
    v_prev = np.zeros(N)
    v_cur = r0 / eta
    beta_prev = 0.0
    deltas, gammas = [], []
    for _ in range(40):
        w = Asp @ v_cur - beta_prev * v_prev
        a = w @ v_cur
        w = w - a * v_cur
        g = np.linalg.norm(w)
        deltas.append(a)
        gammas.append(g)
        v_prev, v_cur, beta_prev = v_cur, w / g, g
    ds = [deltas[0]]
    for k in range(1, 40):
        ds.append(deltas[k] - gammas[k - 1] ** 2 / ds[k - 1])
    m = min(30, recs.shape[0])
    # ring pAp column = d_k: exact recurrence parity, with only the
    # finite-precision drift of the lag-l recovery (measured ~1e-6
    # relative by iteration 30 in f64) as the tolerance
    np.testing.assert_allclose(recs[:m, 3], ds[:m], rtol=1e-4)


def test_pl_restart_budget_and_events(aniso):
    """p(l) arms the restart ladder by default (no recovery passed):
    sqrt breakdowns surface as recorded restarts, not raises."""
    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm="pipelined:1")
    solver.solve(aniso["b"],
                 criteria=StoppingCriteria(residual_rtol=RTOL,
                                           maxits=5000))
    assert solver.stats.converged
    assert solver.stats.nrestarts >= 1
    assert solver.recovery.max_restarts == rec.PL_RESTART_BUDGET


# -- telemetry / health alignment ------------------------------------------

def test_sstep_kappa_estimate(aniso):
    """The Lanczos (alpha, beta) re-alignment learns the s-step layout:
    classic-aligned rows, so spectrum_estimate's kappa lands in the
    PR-6 acceptance band against eigsh."""
    from scipy.sparse.linalg import eigsh

    from acg_tpu.health import spectrum_estimate

    solver = JaxCGSolver(aniso["A"], kernels="xla", algorithm="sstep:4",
                         trace=4096)
    solver.solve(aniso["b"],
                 criteria=StoppingCriteria(residual_rtol=RTOL,
                                           maxits=5000))
    est = spectrum_estimate(solver.last_trace)
    assert est is not None and est["kappa"] is not None
    lmax = float(eigsh(aniso["Asp"], k=1,
                       return_eigenvectors=False)[0])
    lmin = float(eigsh(aniso["Asp"], k=1, which="SA",
                       return_eigenvectors=False)[0])
    kappa_true = lmax / lmin
    assert 0.5 * kappa_true <= est["kappa"] <= 1.05 * kappa_true


def test_pl_kappa_estimate(aniso):
    """Same for p(l): the ring's (1/d, l^2) columns satisfy the classic
    identity by construction, so the estimator needs no shift."""
    from scipy.sparse.linalg import eigsh

    from acg_tpu.health import spectrum_estimate

    solver = JaxCGSolver(aniso["A"], kernels="xla",
                         algorithm="pipelined:2", trace=4096)
    # fixed 40 advances: inside the first attempt (no restart window
    # truncation), long enough for the Ritz lower bound to close
    solver.solve(aniso["b"], criteria=StoppingCriteria(maxits=40))
    est = spectrum_estimate(solver.last_trace)
    assert est is not None and est["kappa"] is not None
    lmax = float(eigsh(aniso["Asp"], k=1,
                       return_eigenvectors=False)[0])
    lmin = float(eigsh(aniso["Asp"], k=1, which="SA",
                       return_eigenvectors=False)[0])
    kappa_true = lmax / lmin
    assert 0.5 * kappa_true <= est["kappa"] <= 1.1 * kappa_true


def test_sstep_health_audit_fires(aniso):
    """The health tier reaches s-step: the block-granular audit
    (audit_update_crossing) recomputes b - A x through the tier's own
    SpMV whenever the cadence boundary falls inside a block."""
    from acg_tpu.health import make_spec

    solver = JaxCGSolver(aniso["A"], kernels="xla", algorithm="sstep:4",
                         health=make_spec(every=10))
    solver.solve(aniso["b"],
                 criteria=StoppingCriteria(residual_rtol=RTOL,
                                           maxits=5000))
    assert solver.stats.converged
    assert solver.stats.health.get("naudits", 0) > 0
    # converged cleanly: the recorded gap is tiny in f64
    assert solver.stats.health["gap_max"] < 1e-8


def test_sstep_gap_replace_hook(aniso):
    """The residual-replacement hook into the PR-6 gates: an armed
    --on-gap replace whose threshold any finite gap exceeds trips the
    breakdown path and restarts from the recomputed true residual --
    and the solve still converges."""
    from acg_tpu.health import make_spec
    from acg_tpu.solvers.resilience import RecoveryPolicy

    solver = JaxCGSolver(aniso["A"], kernels="xla", algorithm="sstep:4",
                         health=make_spec(every=20, threshold=1e-300,
                                          action="replace"),
                         recovery=RecoveryPolicy(max_restarts=64,
                                                 fallback_host=False))
    solver.solve(aniso["b"],
                 criteria=StoppingCriteria(residual_rtol=RTOL,
                                           maxits=8000))
    assert solver.stats.converged
    assert solver.stats.nrestarts >= 1


# -- comm ledger -----------------------------------------------------------

def test_comm_ledger_reduction_drop(aniso):
    part = partition_rows(aniso["csr"], 8, seed=0, method="band")
    prob = DistributedProblem.build(aniso["csr"], part, 8,
                                    dtype=jnp.float64)
    base = DistCGSolver(prob).comm_profile()
    led_s = DistCGSolver(prob, algorithm="sstep:8").comm_profile()
    led_p = DistCGSolver(prob, algorithm="pipelined:2").comm_profile()
    assert base["allreduce_per_iteration"] == 2
    assert led_s["allreduce_per_iteration"] == pytest.approx(1 / 8)
    assert led_s["iterations_per_reduction"] == 8
    assert led_s["algorithm"] == "sstep:8"
    assert led_s["halo_exchanges_per_iteration"] == pytest.approx(15 / 8)
    assert led_p["allreduce_per_iteration"] == 1.0
    assert led_p["allreduce_scalars"] == 6
    assert led_p["reduction_latency_hidden"] == 2


def test_sharded_gen_direct_rides_builder():
    """The sharded gen-direct tier (ShardedDiaCGSolver) inherits the
    CA recurrences through the callable-SpMV hook, ledger included."""
    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    solver = build_sharded_poisson_solver(24, 2, nparts=8,
                                          dtype=jnp.float64,
                                          algorithm="sstep:2")
    b = np.ones(solver.A.nrows)
    x = solver.solve(b, criteria=StoppingCriteria(residual_rtol=1e-6,
                                                  maxits=2000))
    assert solver.stats.converged
    led = solver.comm_profile()
    assert led["algorithm"] == "sstep:2"
    assert led["allreduce_per_iteration"] == pytest.approx(0.5)


# -- refusals (the could-never-fire discipline) ----------------------------

def test_refusals(aniso):
    A = aniso["A"]
    with pytest.raises(ValueError, match="unpreconditioned"):
        JaxCGSolver(A, algorithm="sstep:4", precond="jacobi")
    with pytest.raises(ValueError, match="precise_dots"):
        JaxCGSolver(A, algorithm="sstep:4", precise_dots=True)
    with pytest.raises(ValueError, match="pipelined flag"):
        JaxCGSolver(A, algorithm="sstep:4", pipelined=True)
    with pytest.raises(ValueError, match="replace_every"):
        JaxCGSolver(A, algorithm="sstep:4", replace_every=10,
                    vector_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="bf16"):
        JaxCGSolver(A, algorithm="pipelined:2",
                    vector_dtype=jnp.bfloat16)
    from acg_tpu.checkpoint import CheckpointConfig
    # checkpointing now composes with CA recurrences (the ISSUE-16
    # carry); the narrowed refusal matrix (repartition carry, p(l) +
    # trace) lives in tests/test_checkpoint.py
    JaxCGSolver(A, algorithm="sstep:4",
                ckpt=CheckpointConfig(path="/tmp/x.ckpt", every=10))
    with pytest.raises(ValueError, match="trace"):
        JaxCGSolver(A, algorithm="pipelined:2", trace=8,
                    ckpt=CheckpointConfig(path="/tmp/x.ckpt", every=10))
    from acg_tpu.health import make_spec
    with pytest.raises(ValueError, match="audit"):
        JaxCGSolver(A, algorithm="pipelined:2",
                    health=make_spec(every=10))
    with pytest.raises(ValueError, match="abft"):
        JaxCGSolver(A, algorithm="sstep:4",
                    health=make_spec(every=10, abft=True))
    # diff criteria refuse at dispatch
    s = JaxCGSolver(A, algorithm="sstep:4")
    with pytest.raises(ValueError, match="residual criteria"):
        s.solve(aniso["b"],
                criteria=StoppingCriteria(diff_rtol=1e-6, maxits=10))
    # classic/pipelined aliases resolve onto the hand-built programs
    s = JaxCGSolver(A, algorithm="pipelined")
    assert s.algo is None and s.pipelined


def test_fault_refusals(aniso):
    from acg_tpu import faults
    from acg_tpu.errors import AcgError

    s = JaxCGSolver(aniso["A"], algorithm="sstep:4")
    with faults.injected("spmv:nan@3"):
        with pytest.raises(AcgError, match="block boundaries"):
            s.solve(aniso["b"],
                    criteria=StoppingCriteria(residual_rtol=RTOL,
                                              maxits=100))
    p = JaxCGSolver(aniso["A"], algorithm="pipelined:2")
    with faults.injected("dot:nan@3"):
        with pytest.raises(AcgError, match="no site"):
            p.solve(aniso["b"],
                    criteria=StoppingCriteria(residual_rtol=RTOL,
                                              maxits=100))


def test_sstep_fault_detected_and_recovered(aniso):
    """A block-aligned SpMV fault fires, is caught by the breakdown
    guard, and the recovery ladder restarts past it."""
    from acg_tpu import faults
    from acg_tpu.solvers.resilience import RecoveryPolicy

    s = JaxCGSolver(aniso["A"], algorithm="sstep:4",
                    recovery=RecoveryPolicy(max_restarts=3,
                                            fallback_host=False))
    with faults.injected("spmv:nan@8"):
        x = s.solve(aniso["b"],
                    criteria=StoppingCriteria(residual_rtol=RTOL,
                                              maxits=5000))
    assert s.stats.converged
    assert s.stats.nbreakdowns >= 1
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL


def test_pl_fault_detected_and_recovered(aniso):
    """A p(l) SpMV fault (keyed on the auxiliary-basis counter) fires,
    breaks the pipeline, and the restart ladder retires it in the
    z-counter frame -- the fault must NOT deterministically re-trigger
    across restarts (the FaultSpec.shift contract)."""
    from acg_tpu import faults

    s = JaxCGSolver(aniso["A"], kernels="xla", algorithm="pipelined:2")
    with faults.injected("spmv:nan@10"):
        x = s.solve(aniso["b"],
                    criteria=StoppingCriteria(residual_rtol=RTOL,
                                              maxits=5000))
    assert s.stats.converged
    assert s.stats.nrestarts >= 1
    rel = (np.linalg.norm(aniso["b"] - aniso["Asp"] @ np.asarray(x))
           / np.linalg.norm(aniso["b"]))
    assert rel < 10 * RTOL


def test_dist_census_matches_schedule(aniso):
    """The dist tier's op census bills the SAME SpMV-equivalents per
    iteration as the ledger/schedule declares (and as the single-device
    census does) -- the two tiers' stats for one algorithm must agree."""
    part = partition_rows(aniso["csr"], 8, seed=0, method="band")
    prob = DistributedProblem.build(aniso["csr"], part, 8,
                                    dtype=jnp.float64)
    solver = DistCGSolver(prob, algorithm="sstep:8")
    solver.solve(aniso["b"], criteria=StoppingCriteria(maxits=80))
    niter = solver.stats.niterations
    sched = rec.reduction_schedule(rec.RecurrenceSpec("sstep", 8), False)
    gemv = solver.stats.ops["gemv"].n
    assert gemv == int(niter * sched["spmv_per_iteration"]) + 1
    ar = solver.stats.ops["allreduce"].n
    assert ar == max(int(round(niter / 8)), 1)


# -- CLI -------------------------------------------------------------------

def _cli(args, env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "acg_tpu"] + args,
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_cli_sstep_end_to_end(tmp_path):
    sj = tmp_path / "stats.json"
    p = _cli(["gen:poisson2d:24", "--aniso", "0.5", "--algorithm",
              "sstep:4", "--max-iterations", "2000",
              "--residual-rtol", "1e-6", "--warmup", "0",
              "--stats-json", str(sj)])
    assert p.returncode == 0, p.stderr[-2000:]
    doc = json.loads(sj.read_text())
    assert doc["stats"]["converged"] is True


def test_cli_pl_end_to_end():
    p = _cli(["gen:poisson2d:24", "--aniso", "0.5", "--algorithm",
              "pipelined:2", "--max-iterations", "2000",
              "--residual-rtol", "1e-6", "--warmup", "0"])
    assert p.returncode == 0, p.stderr[-2000:]


def test_cli_dist_sstep_end_to_end():
    p = _cli(["gen:poisson2d:24", "--nparts", "8", "--algorithm",
              "sstep:2", "--max-iterations", "2000",
              "--residual-rtol", "1e-5", "--warmup", "0"])
    assert p.returncode == 0, p.stderr[-2000:]


def test_cli_refusals():
    p = _cli(["gen:poisson2d:24", "--algorithm", "sstep:4",
              "--precond", "jacobi", "--warmup", "0"])
    assert p.returncode != 0
    assert "does not support" in p.stderr
    p = _cli(["gen:poisson2d:24", "--algorithm", "sstep:33",
              "--warmup", "0"])
    assert p.returncode != 0
    p = _cli(["gen:poisson2d:24", "--algorithm", "pipelined:2",
              "--explain", "--warmup", "0"])
    assert p.returncode != 0
    assert "does not support" in p.stderr


def test_cli_algorithm_aliases():
    """--algorithm pipelined is the Ghysels-Vanroose solver (the
    existing name), not p(l)."""
    p = _cli(["gen:poisson2d:16", "--algorithm", "pipelined",
              "--max-iterations", "800", "--residual-rtol", "1e-6",
              "--warmup", "0"])
    assert p.returncode == 0, p.stderr[-2000:]


def test_buildinfo_row():
    p = _cli(["--buildinfo", "x"])
    assert "communication-avoiding recurrences" in p.stdout
    assert "sstep:S" in p.stdout
