"""Pod-scale local ingest: range reads + per-controller subdomain
construction (io.mtxfile.read_mtx_row_range, graph.subdomain_from_row_
slice, DistributedProblem.build_local_read).

The reference scales file ingest by root-read + MPI scatter of
subgraphs (``graph.c:1529-1897``, ``mtxfile.h:997-1087``); the TPU
build removes the root instead: every controller bisects a row-sorted
full-storage binary file (``mtx2bin --expand``) for exactly its rows
and derives its halo locally from structural symmetry.  Tests pin
range-read equivalence, subdomain equivalence against the full-graph
partitioner, solve agreement, and the 2-process CLI flow.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson_mtx, poisson2d_coo
from acg_tpu.io.mtxfile import (expand_to_rowsorted_full, read_mtx,
                                read_mtx_row_range, read_mtx_sizes,
                                write_mtx)
from acg_tpu.graph import partition_graph_nodes, subdomain_from_row_slice
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def binfile(tmp_path_factory):
    """24x24 2D Poisson as an expanded row-sorted binary file."""
    path = tmp_path_factory.mktemp("lr") / "p24.bin.mtx"
    mtx = expand_to_rowsorted_full(poisson_mtx(24, dim=2))
    write_mtx(path, mtx, binary=True)
    return path


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(24)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_read_sizes(binfile):
    assert read_mtx_sizes(binfile) == (576, 576, 5*576 - 4*24)


def test_row_range_matches_full_read(binfile, csr):
    full = read_mtx(binfile, binary=True)
    for lo, hi in ((0, 100), (100, 400), (400, 576), (0, 576), (50, 50)):
        sl = read_mtx_row_range(binfile, lo, hi)
        keep = (np.asarray(full.rowidx) >= lo) & (np.asarray(full.rowidx) < hi)
        np.testing.assert_array_equal(sl.rowidx, np.asarray(full.rowidx)[keep])
        np.testing.assert_array_equal(sl.colidx, np.asarray(full.colidx)[keep])
        np.testing.assert_array_equal(sl.vals, np.asarray(full.vals)[keep])
        assert sl.nrows == 576 and sl.nnz == int(keep.sum())


def test_row_range_rejects_unsorted(tmp_path):
    mtx = poisson_mtx(8, dim=2)  # one-triangle, row-sorted, but NOT full
    # scramble entry order to break row sorting
    rng = np.random.default_rng(0)
    perm = rng.permutation(mtx.nnz)
    mtx.rowidx = np.asarray(mtx.rowidx)[perm]
    mtx.colidx = np.asarray(mtx.colidx)[perm]
    mtx.vals = np.asarray(mtx.vals)[perm]
    p = tmp_path / "scrambled.bin.mtx"
    write_mtx(p, mtx, binary=True)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError):
        read_mtx_row_range(p, 10, 40)


def test_row_range_rejects_text_file(tmp_path):
    """A TEXT coordinate file must be diagnosed as such (ADVICE round 3:
    frombuffer over an ASCII data section used to surface as a
    misleading 'not row-sorted' error)."""
    p = tmp_path / "text.mtx"
    write_mtx(p, expand_to_rowsorted_full(poisson_mtx(8, dim=2)),
              binary=False)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError, match="binary"):
        read_mtx_row_range(p, 0, 10)


def test_subdomain_matches_full_partitioner(binfile, csr):
    """The locally-built subdomain equals what the full-graph path
    (partition_graph_nodes + natural reorder + block build) produces for
    the same band partition: global ids, halo windows, matrix blocks."""
    from acg_tpu.graph import partition_matrix, reorder_owned_natural

    N = csr.shape[0]
    bounds = np.array([0, 200, 390, N])
    part = np.zeros(N, dtype=np.int32)
    for p in range(3):
        part[bounds[p]:bounds[p + 1]] = p
    ref_subs = reorder_owned_natural(partition_matrix(csr, part, 3))
    for p in range(3):
        sl = read_mtx_row_range(binfile, int(bounds[p]), int(bounds[p + 1]))
        r, c, v = sl.to_coo()
        s = subdomain_from_row_slice(r, c, v, bounds, p)
        ref = ref_subs[p]
        assert s.nowned == ref.nowned and s.nghost == ref.nghost
        assert s.nborder == ref.nborder
        np.testing.assert_array_equal(s.global_ids, ref.global_ids)
        np.testing.assert_array_equal(s.ghost_owner, ref.ghost_owner)
        np.testing.assert_array_equal(s.halo.send_parts, ref.halo.send_parts)
        np.testing.assert_array_equal(s.halo.send_idx, ref.halo.send_idx)
        np.testing.assert_array_equal(s.halo.recv_counts,
                                      ref.halo.recv_counts)
        assert (s.A_local != ref.A_local).nnz == 0
        assert (s.A_ghost != ref.A_ghost).nnz == 0


def test_build_local_read_solves(binfile, csr):
    """Single-process build_local_read (owns every part) solves to the
    same answer as the replicated-read build."""
    prob = DistributedProblem.build_local_read(binfile, 4,
                                               dtype=jnp.float64)
    assert prob.local.format == "dia"  # band partition keeps DIA
    solver = DistCGSolver(prob)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    x = solver.solve(b, criteria=crit)
    assert np.linalg.norm(b - csr @ x) <= 1e-8 * np.linalg.norm(b)


def test_build_local_read_rejects_one_triangle(tmp_path):
    """A plain mtx2bin file (symmetric one-triangle, no --expand) must be
    rejected -- silently solving half the matrix would be worse."""
    p = tmp_path / "tri.bin.mtx"
    write_mtx(p, poisson_mtx(8, dim=2), binary=True)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError, match="expand"):
        DistributedProblem.build_local_read(p, 2)


def test_expand_rejects_unsupported_symmetry():
    from acg_tpu.io.mtxfile import MtxFile
    from acg_tpu.errors import AcgError
    m = MtxFile(symmetry="skew-symmetric", nrows=2, ncols=2, nnz=1,
                rowidx=np.array([1]), colidx=np.array([0]),
                vals=np.array([1.0]))
    with pytest.raises(AcgError, match="expand"):
        expand_to_rowsorted_full(m)


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.mark.two_process_collectives
def test_cli_two_process_distributed_read(binfile):
    """The full 2-process flow: both controllers range-read only their
    rows (--distributed-read), solve, and process 0 reports the
    manufactured error."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(binfile),
                "--binary", "--distributed-read", "--nparts", "4",
                "--manufactured-solution", "--max-iterations", "2000",
                "--residual-rtol", "1e-8", "--dtype", "f64",
                "--warmup", "0", "--quiet",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    assert "total solver time" in se0 and "total solver time" not in se1
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6, se0


def test_cli_two_process_one_sided_read_failure(binfile, tmp_path):
    """One controller's file is missing; the ingest checkpoint (run
    BEFORE the uniform-shape allgather) must bring both down in
    agreement instead of wedging the healthy peer in a mismatched
    collective."""
    import time as _time

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid, path):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(path),
                "--binary", "--distributed-read", "--nparts", "4",
                "--max-iterations", "100", "--residual-rtol", "1e-6",
                "--dtype", "f64", "--warmup", "0", "--quiet",
                "--err-timeout", "20",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    t0 = _time.monotonic()
    p0 = launch(0, binfile)
    p1 = launch(1, tmp_path / "nope.bin.mtx")
    outs = [p.communicate(timeout=180) for p in (p0, p1)]
    elapsed = _time.monotonic() - t0
    assert p0.returncode != 0 and p1.returncode != 0
    assert elapsed < 150
    assert "peer controller failed during ingest" in outs[0][1]


# -- arbitrary (METIS/graph) partitions via offline permutation ----------
# (round-3 verdict item 2: the band-only limitation removed)

@pytest.fixture(scope="module")
def irregular():
    """An irregular SPD matrix a band partition would serve poorly."""
    from acg_tpu.io.generators import irregular_spd_coo
    r, c, v, N = irregular_spd_coo(400, avg_degree=6.0, seed=3)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


@pytest.fixture(scope="module")
def part_binfile(tmp_path_factory, irregular):
    """The offline pipeline: graph partition -> mtx2bin --expand
    --partition -> permuted binary + sidecars."""
    from acg_tpu.io.mtxfile import vector_mtx
    from acg_tpu.partition import partition_rows
    from acg_tpu.tools.mtx2bin import main as mtx2bin_main

    d = tmp_path_factory.mktemp("mp")
    src = d / "irr.mtx"
    coo = irregular.tocoo()
    up = coo.row <= coo.col  # one-triangle symmetric input, like mtxpartition
    from acg_tpu.io.mtxfile import MtxFile
    write_mtx(src, MtxFile(object="matrix", format="coordinate",
                           field="real", symmetry="symmetric",
                           nrows=irregular.shape[0],
                           ncols=irregular.shape[0], nnz=int(up.sum()),
                           rowidx=coo.row[up], colidx=coo.col[up],
                           vals=coo.data[up]))
    part = partition_rows(irregular, 3, seed=0, method="graph")
    pf = d / "part.mtx"
    write_mtx(pf, vector_mtx(part.astype(np.int64), field="integer"),
              numfmt="%d")
    out = d / "irr.bin.mtx"
    rc = mtx2bin_main([str(src), str(out), "--expand",
                       "--partition", str(pf)])
    assert rc == 0
    return out, part


def test_partitioned_sidecars(part_binfile, irregular):
    from acg_tpu.io.mtxfile import read_mtx
    out, part = part_binfile
    bounds = np.asarray(read_mtx(str(out) + ".bounds.mtx").vals).reshape(-1)
    counts = np.bincount(part, minlength=3)
    np.testing.assert_array_equal(bounds,
                                  np.concatenate([[0], np.cumsum(counts)]))
    perm = np.asarray(read_mtx(str(out) + ".perm.mtx",
                               binary=True).vals).reshape(-1) - 1
    # perm groups rows by part, stable
    np.testing.assert_array_equal(part[perm], np.sort(part, kind="stable"))


def test_partitioned_subdomains_match_full_partitioner(part_binfile,
                                                       irregular):
    """Range-read subdomains of the permuted file == the full-graph
    partitioner run on the permuted matrix with the same (now grouped)
    partition -- the METIS generalization of the band exactness test."""
    from acg_tpu.graph import partition_matrix, reorder_owned_natural
    from acg_tpu.io.mtxfile import read_mtx

    out, part = part_binfile
    bounds = np.asarray(read_mtx(str(out) + ".bounds.mtx").vals
                        ).reshape(-1).astype(np.int64)
    perm = np.asarray(read_mtx(str(out) + ".perm.mtx", binary=True).vals
                      ).reshape(-1).astype(np.int64) - 1
    perm_csr = irregular[perm][:, perm].tocsr()
    gpart = (np.searchsorted(bounds, np.arange(irregular.shape[0]),
                             side="right") - 1).astype(np.int32)
    ref_subs = reorder_owned_natural(partition_matrix(perm_csr, gpart, 3))
    for p in range(3):
        sl = read_mtx_row_range(out, int(bounds[p]), int(bounds[p + 1]))
        r, c, v = sl.to_coo()
        s = subdomain_from_row_slice(r, c, v, bounds, p)
        ref = ref_subs[p]
        assert s.nowned == ref.nowned and s.nghost == ref.nghost
        np.testing.assert_array_equal(s.global_ids, ref.global_ids)
        np.testing.assert_array_equal(s.ghost_owner, ref.ghost_owner)
        np.testing.assert_array_equal(s.halo.send_parts,
                                      ref.halo.send_parts)
        np.testing.assert_array_equal(s.halo.send_idx, ref.halo.send_idx)
        assert (s.A_local != ref.A_local).nnz == 0
        assert (s.A_ghost != ref.A_ghost).nnz == 0


def test_partitioned_local_read_solves_to_original(part_binfile, irregular):
    """build_local_read over the permuted file solves the ORIGINAL
    system: un-permuting the solution must satisfy the original matrix."""
    from acg_tpu.io.mtxfile import read_mtx

    out, part = part_binfile
    bounds = np.asarray(read_mtx(str(out) + ".bounds.mtx").vals
                        ).reshape(-1).astype(np.int64)
    perm = np.asarray(read_mtx(str(out) + ".perm.mtx", binary=True).vals
                      ).reshape(-1).astype(np.int64) - 1
    prob = DistributedProblem.build_local_read(out, 3, dtype=jnp.float64,
                                               bounds=bounds)
    # irregular: no DIA structure; skewed row lengths select the
    # length-binned layout via the agreed uniform shapes (round 5)
    assert prob.local.format == "binnedell"
    solver = DistCGSolver(prob)
    n = irregular.shape[0]
    b_orig = np.ones(n)
    x_perm = solver.solve(b_orig[perm],  # b in permuted ordering
                          criteria=StoppingCriteria(maxits=3000,
                                                    residual_rtol=1e-10))
    x = np.empty(n)
    x[perm] = x_perm
    rel = np.linalg.norm(b_orig - irregular @ x) / np.linalg.norm(b_orig)
    assert rel < 1e-8


@pytest.mark.two_process_collectives
def test_cli_two_process_partitioned_distributed_read(part_binfile):
    """2-process METIS-partitioned ingest: each controller range-reads
    only its permuted rows (O(local nnz)), bounds sidecar auto-detected."""
    out, part = part_binfile
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(out),
                "--binary", "--distributed-read",
                "--manufactured-solution", "--max-iterations", "3000",
                "--residual-rtol", "1e-8", "--dtype", "f64",
                "--warmup", "0", "--quiet",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6, se0


def test_read_vector_rows_gather(tmp_path):
    """Scattered-row gather of a binary vector file: any order,
    duplicates, coalesced runs -- the permuted-b/x0 primitive."""
    from acg_tpu.io.mtxfile import read_vector_rows, vector_mtx

    n = 500
    x = np.linspace(0, 1, n)
    p = tmp_path / "v.bin.mtx"
    write_mtx(p, vector_mtx(x), binary=True)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, n, size=137)
    rows[10] = rows[20]  # duplicate
    got = read_vector_rows(p, rows, expect_nrows=n)
    np.testing.assert_array_equal(got, x[rows])
    assert read_vector_rows(p, np.zeros(0, np.int64)).size == 0
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError):
        read_vector_rows(p, np.asarray([n]), expect_nrows=n)


@pytest.mark.two_process_collectives
def test_cli_two_process_permuted_b_x0_files(part_binfile, irregular,
                                             tmp_path_factory):
    """b/x0 FILES with a METIS-permuted matrix under --distributed-read
    (round-4 verdict item 6): each controller window-reads the perm
    sidecar for its owned rows and gathers the original-ordering b/x0
    entries; the emitted solution (original ordering) must satisfy the
    ORIGINAL system."""
    from acg_tpu.io.mtxfile import vector_mtx

    out, part = part_binfile
    d = tmp_path_factory.mktemp("pbx")
    n = irregular.shape[0]
    rng = np.random.default_rng(5)
    b_orig = rng.standard_normal(n)
    x0_orig = 0.1 * rng.standard_normal(n)
    bf, xf = d / "b.bin.mtx", d / "x0.bin.mtx"
    write_mtx(bf, vector_mtx(b_orig), binary=True)
    write_mtx(xf, vector_mtx(x0_orig), binary=True)

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(out),
                str(bf), str(xf),
                "--binary", "--distributed-read",
                "--max-iterations", "3000", "--residual-rtol", "1e-10",
                "--dtype", "f64", "--warmup", "0",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    from io import BytesIO
    so = outs[0][0]
    mtx_text = so[so.index("%%MatrixMarket"):]  # Gloo may log to stdout
    x = np.asarray(read_mtx(BytesIO(mtx_text.encode())).vals).reshape(-1)
    rel = (np.linalg.norm(b_orig - irregular @ x)
           / np.linalg.norm(b_orig))
    assert rel < 1e-8


def test_cli_singledevice_permuted_output_original_order(part_binfile,
                                                         irregular):
    """The replicated single-device path must honor the perm sidecar
    too: solving the permuted binary prints the solution in ORIGINAL
    row ordering (consistent with --distributed-read)."""
    out, part = part_binfile
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(out), "--binary",
         "--nparts", "1", "--dtype", "f64", "--max-iterations", "3000",
         "--residual-rtol", "1e-10", "--warmup", "0"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    from io import BytesIO
    from acg_tpu.io.mtxfile import read_mtx
    x = np.asarray(read_mtx(BytesIO(r.stdout.encode())).vals).reshape(-1)
    b = np.ones(irregular.shape[0])
    rel = np.linalg.norm(b - irregular @ x) / np.linalg.norm(b)
    assert rel < 1e-8


# -- distributed solution output (round-4: the fwrite_mpi_double role) ---

def test_write_vector_window_roundtrip(tmp_path):
    from acg_tpu.io.mtxfile import (finalize_vector_file, read_mtx,
                                    vector_mtx, write_vector_window)
    n = 37
    x = np.linspace(-1, 1, n)
    p = tmp_path / "x.bin.mtx"
    # windows written out of order, by "different controllers"
    write_vector_window(p, n, 20, x[20:])
    write_vector_window(p, n, 0, x[:9])
    write_vector_window(p, n, 9, x[9:20])
    finalize_vector_file(p, n)
    got = np.asarray(read_mtx(p, binary=True).vals).reshape(-1)
    np.testing.assert_array_equal(got, x)
    # byte-identical to the ordinary single-writer path
    ref = tmp_path / "ref.bin.mtx"
    write_mtx(ref, vector_mtx(x), binary=True)
    assert p.read_bytes() == ref.read_bytes()


@pytest.mark.two_process_collectives
def test_cli_two_process_distributed_write(binfile, tmp_path_factory):
    """2-process --distributed-read --output: both controllers range-
    write their owned windows; the assembled file is byte-identical to
    the single-process run's output of the same solve."""
    d = tmp_path_factory.mktemp("dw")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    base = [sys.executable, "-m", "acg_tpu.cli", str(binfile),
            "--binary", "--distributed-read", "--nparts", "4",
            "--manufactured-solution", "--max-iterations", "2000",
            "--residual-rtol", "1e-8", "--dtype", "f64",
            "--warmup", "0", "--quiet"]

    # single-process reference (owns all parts; same program)
    ref = d / "ref.bin.mtx"
    env1 = dict(env)
    env1["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    r = subprocess.run(base + ["--output", str(ref)], env=env1,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "error 2-norm:" in r.stderr

    port = _free_port()
    out = d / "two.bin.mtx"

    def launch(pid):
        argv = base + ["--output", str(out),
                       "--coordinator", f"localhost:{port}",
                       "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    err = float(outs[0][1].split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6
    # identical structure (header + exact size); values agree to solve
    # tolerance (bitwise equality would be too strict: the two process
    # topologies reduce psums in different orders).  Byte-identity of
    # the assembly mechanism itself is pinned by
    # test_write_vector_window_roundtrip.
    rb, ob = ref.read_bytes(), out.read_bytes()
    from acg_tpu.io.mtxfile import vector_binary_header
    hdr = vector_binary_header(576)
    assert ob[:len(hdr)] == rb[:len(hdr)] == hdr
    assert len(ob) == len(rb) == len(hdr) + 8 * 576
    from acg_tpu.io.mtxfile import read_mtx
    x2 = np.asarray(read_mtx(out, binary=True).vals).reshape(-1)
    x1 = np.asarray(read_mtx(ref, binary=True).vals).reshape(-1)
    np.testing.assert_allclose(x2, x1, atol=1e-7)


def test_distributed_read_b_and_x0_files(binfile, csr, tmp_path):
    """--b/--x0 under --distributed-read: per-controller window reads of
    binary array vectors (the input mirror of the distributed write);
    the solve matches the in-memory right-hand side."""
    from acg_tpu.io.mtxfile import read_mtx, vector_mtx
    import scipy.sparse.linalg as spla
    rng = np.random.default_rng(5)
    b = rng.standard_normal(csr.shape[0])
    bfile = tmp_path / "b.bin.mtx"
    write_mtx(bfile, vector_mtx(b), binary=True)
    # x0 = the exact solution: the solver must see it (near-zero
    # iterations), which pins that the x0 file actually reaches the
    # solve rather than being silently dropped
    x0 = spla.spsolve(csr.tocsc(), b)
    xfile = tmp_path / "x0.bin.mtx"
    write_mtx(xfile, vector_mtx(x0), binary=True)

    out = tmp_path / "x.bin.mtx"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(binfile),
         str(bfile), str(xfile), "--binary",
         "--distributed-read", "--nparts", "4", "--dtype", "f64",
         # ABSOLUTE tolerance: x0 = exact solution makes r0 ~ 1e-13, so
         # a relative-to-r0 tolerance would keep iterating; with atol
         # the solve must stop immediately iff x0 actually arrived
         "--max-iterations", "3000", "--residual-atol", "1e-8",
         "--residual-rtol", "0",
         "--warmup", "0", "--quiet", "-o", str(out)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert r.returncode == 0, r.stderr
    x = np.asarray(read_mtx(out, binary=True).vals).reshape(-1)
    rel = np.linalg.norm(b - csr @ x) / np.linalg.norm(b)
    assert rel < 1e-8
    its = int([ln for ln in r.stderr.splitlines()
               if ln.strip().startswith("iterations:")][0]
              .split(":")[1].replace(",", ""))
    assert its <= 2  # started AT the solution: x0 demonstrably used

    # a wrong-length b is rejected loudly (window reads would otherwise
    # silently accept any file the windows fit inside)
    bad = tmp_path / "bad.bin.mtx"
    write_mtx(bad, vector_mtx(np.ones(2 * csr.shape[0])), binary=True)
    r2 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(binfile), str(bad),
         "--binary", "--distributed-read", "--nparts", "4",
         "--dtype", "f64", "--max-iterations", "10", "--warmup", "0",
         "--quiet"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert r2.returncode != 0
    assert "need" in r2.stderr


def test_read_vector_window_validates(tmp_path):
    from acg_tpu.errors import AcgError
    from acg_tpu.io.mtxfile import read_vector_window, vector_mtx
    p = tmp_path / "v.mtx"
    write_mtx(p, vector_mtx(np.arange(5.0)), binary=False)  # TEXT
    with pytest.raises(AcgError, match="binary"):
        read_vector_window(p, 0, 3)
    pb = tmp_path / "v.bin.mtx"
    write_mtx(pb, vector_mtx(np.arange(5.0)), binary=True)
    np.testing.assert_array_equal(read_vector_window(pb, 1, 4),
                                  [1.0, 2.0, 3.0])
    with pytest.raises(AcgError, match="outside"):
        read_vector_window(pb, 2, 9)


def test_distributed_read_refine_f64_class(binfile, csr, tmp_path):
    """--refine under --distributed-read: f64 outer residuals from the
    per-part host blocks (no full matrix on any controller) reach
    residuals far beyond the f32 inner tier, and the refined solution
    round-trips through the distributed write."""
    from acg_tpu.io.mtxfile import read_mtx
    out = tmp_path / "x.bin.mtx"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(binfile), "--binary",
         "--distributed-read", "--nparts", "4", "--dtype", "f32",
         "--refine", "--manufactured-solution",
         "--max-iterations", "20000", "--residual-rtol", "1e-11",
         "--warmup", "0", "--quiet", "-o", str(out)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert r.returncode == 0, r.stderr
    err = float(r.stderr.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-9
    # the WRITTEN file must itself carry the refined accuracy: rebuild
    # its b (same seed/protocol as the CLI) and check the f64 residual
    x = np.asarray(read_mtx(out, binary=True).vals).reshape(-1)
    rng = np.random.default_rng(42)  # the CLI default --seed
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    rel = np.linalg.norm(b - csr @ x) / np.linalg.norm(b)
    assert rel < 1e-9


@pytest.mark.two_process_collectives
def test_cli_two_process_distributed_read_refine(binfile):
    """2-process --distributed-read --refine: the outer matvec combines
    per-controller owned windows across processes."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(binfile),
                "--binary", "--distributed-read", "--nparts", "4",
                "--dtype", "f32", "--refine", "--manufactured-solution",
                "--max-iterations", "20000", "--residual-rtol", "1e-11",
                "--warmup", "0", "--quiet",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    err = float(outs[0][1].split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-9


def test_distributed_read_comm_matrix(binfile):
    """--output-comm-matrix under --distributed-read: the volume matrix
    assembled from owned halo plans matches the replicated path's."""
    from io import BytesIO
    from acg_tpu.io.mtxfile import read_mtx
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    base = ["--nparts", "4", "--dtype", "f64", "--max-iterations", "50",
            "--residual-rtol", "1e-6", "--warmup", "0", "--quiet",
            "--output-comm-matrix"]
    r1 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(binfile), "--binary",
         "--distributed-read"] + base,
        capture_output=True, text=True, env=env)
    assert r1.returncode == 0, r1.stderr
    m1 = read_mtx(BytesIO(r1.stdout.encode()))
    # replicated path on the same matrix with the same band partition
    r2 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", str(binfile), "--binary",
         "--partition-method", "band"] + base,
        capture_output=True, text=True, env=env)
    assert r2.returncode == 0, r2.stderr
    m2 = read_mtx(BytesIO(r2.stdout.encode()))
    assert m1.nrows == m2.nrows == 4
    np.testing.assert_array_equal(np.asarray(m1.rowidx),
                                  np.asarray(m2.rowidx))
    np.testing.assert_array_equal(np.asarray(m1.colidx),
                                  np.asarray(m2.colidx))
    np.testing.assert_array_equal(np.asarray(m1.vals),
                                  np.asarray(m2.vals))
