"""Pod-scale local ingest: range reads + per-controller subdomain
construction (io.mtxfile.read_mtx_row_range, graph.subdomain_from_row_
slice, DistributedProblem.build_local_read).

The reference scales file ingest by root-read + MPI scatter of
subgraphs (``graph.c:1529-1897``, ``mtxfile.h:997-1087``); the TPU
build removes the root instead: every controller bisects a row-sorted
full-storage binary file (``mtx2bin --expand``) for exactly its rows
and derives its halo locally from structural symmetry.  Tests pin
range-read equivalence, subdomain equivalence against the full-graph
partitioner, solve agreement, and the 2-process CLI flow.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson_mtx, poisson2d_coo
from acg_tpu.io.mtxfile import (expand_to_rowsorted_full, read_mtx,
                                read_mtx_row_range, read_mtx_sizes,
                                write_mtx)
from acg_tpu.graph import partition_graph_nodes, subdomain_from_row_slice
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def binfile(tmp_path_factory):
    """24x24 2D Poisson as an expanded row-sorted binary file."""
    path = tmp_path_factory.mktemp("lr") / "p24.bin.mtx"
    mtx = expand_to_rowsorted_full(poisson_mtx(24, dim=2))
    write_mtx(path, mtx, binary=True)
    return path


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(24)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_read_sizes(binfile):
    assert read_mtx_sizes(binfile) == (576, 576, 5*576 - 4*24)


def test_row_range_matches_full_read(binfile, csr):
    full = read_mtx(binfile, binary=True)
    for lo, hi in ((0, 100), (100, 400), (400, 576), (0, 576), (50, 50)):
        sl = read_mtx_row_range(binfile, lo, hi)
        keep = (np.asarray(full.rowidx) >= lo) & (np.asarray(full.rowidx) < hi)
        np.testing.assert_array_equal(sl.rowidx, np.asarray(full.rowidx)[keep])
        np.testing.assert_array_equal(sl.colidx, np.asarray(full.colidx)[keep])
        np.testing.assert_array_equal(sl.vals, np.asarray(full.vals)[keep])
        assert sl.nrows == 576 and sl.nnz == int(keep.sum())


def test_row_range_rejects_unsorted(tmp_path):
    mtx = poisson_mtx(8, dim=2)  # one-triangle, row-sorted, but NOT full
    # scramble entry order to break row sorting
    rng = np.random.default_rng(0)
    perm = rng.permutation(mtx.nnz)
    mtx.rowidx = np.asarray(mtx.rowidx)[perm]
    mtx.colidx = np.asarray(mtx.colidx)[perm]
    mtx.vals = np.asarray(mtx.vals)[perm]
    p = tmp_path / "scrambled.bin.mtx"
    write_mtx(p, mtx, binary=True)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError):
        read_mtx_row_range(p, 10, 40)


def test_row_range_rejects_text_file(tmp_path):
    """A TEXT coordinate file must be diagnosed as such (ADVICE round 3:
    frombuffer over an ASCII data section used to surface as a
    misleading 'not row-sorted' error)."""
    p = tmp_path / "text.mtx"
    write_mtx(p, expand_to_rowsorted_full(poisson_mtx(8, dim=2)),
              binary=False)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError, match="binary"):
        read_mtx_row_range(p, 0, 10)


def test_subdomain_matches_full_partitioner(binfile, csr):
    """The locally-built subdomain equals what the full-graph path
    (partition_graph_nodes + natural reorder + block build) produces for
    the same band partition: global ids, halo windows, matrix blocks."""
    from acg_tpu.graph import partition_matrix, reorder_owned_natural

    N = csr.shape[0]
    bounds = np.array([0, 200, 390, N])
    part = np.zeros(N, dtype=np.int32)
    for p in range(3):
        part[bounds[p]:bounds[p + 1]] = p
    ref_subs = reorder_owned_natural(partition_matrix(csr, part, 3))
    for p in range(3):
        sl = read_mtx_row_range(binfile, int(bounds[p]), int(bounds[p + 1]))
        r, c, v = sl.to_coo()
        s = subdomain_from_row_slice(r, c, v, bounds, p)
        ref = ref_subs[p]
        assert s.nowned == ref.nowned and s.nghost == ref.nghost
        assert s.nborder == ref.nborder
        np.testing.assert_array_equal(s.global_ids, ref.global_ids)
        np.testing.assert_array_equal(s.ghost_owner, ref.ghost_owner)
        np.testing.assert_array_equal(s.halo.send_parts, ref.halo.send_parts)
        np.testing.assert_array_equal(s.halo.send_idx, ref.halo.send_idx)
        np.testing.assert_array_equal(s.halo.recv_counts,
                                      ref.halo.recv_counts)
        assert (s.A_local != ref.A_local).nnz == 0
        assert (s.A_ghost != ref.A_ghost).nnz == 0


def test_build_local_read_solves(binfile, csr):
    """Single-process build_local_read (owns every part) solves to the
    same answer as the replicated-read build."""
    prob = DistributedProblem.build_local_read(binfile, 4,
                                               dtype=jnp.float64)
    assert prob.local.format == "dia"  # band partition keeps DIA
    solver = DistCGSolver(prob)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    x = solver.solve(b, criteria=crit)
    assert np.linalg.norm(b - csr @ x) <= 1e-8 * np.linalg.norm(b)


def test_build_local_read_rejects_one_triangle(tmp_path):
    """A plain mtx2bin file (symmetric one-triangle, no --expand) must be
    rejected -- silently solving half the matrix would be worse."""
    p = tmp_path / "tri.bin.mtx"
    write_mtx(p, poisson_mtx(8, dim=2), binary=True)
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError, match="expand"):
        DistributedProblem.build_local_read(p, 2)


def test_expand_rejects_unsupported_symmetry():
    from acg_tpu.io.mtxfile import MtxFile
    from acg_tpu.errors import AcgError
    m = MtxFile(symmetry="skew-symmetric", nrows=2, ncols=2, nnz=1,
                rowidx=np.array([1]), colidx=np.array([0]),
                vals=np.array([1.0]))
    with pytest.raises(AcgError, match="expand"):
        expand_to_rowsorted_full(m)


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_cli_two_process_distributed_read(binfile):
    """The full 2-process flow: both controllers range-read only their
    rows (--distributed-read), solve, and process 0 reports the
    manufactured error."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(binfile),
                "--binary", "--distributed-read", "--nparts", "4",
                "--manufactured-solution", "--max-iterations", "2000",
                "--residual-rtol", "1e-8", "--dtype", "f64",
                "--warmup", "0", "--quiet",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    procs = [launch(i) for i in range(2)]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se
    (so0, se0), (so1, se1) = outs
    assert "total solver time" in se0 and "total solver time" not in se1
    err = float(se0.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6, se0


def test_cli_two_process_one_sided_read_failure(binfile, tmp_path):
    """One controller's file is missing; the ingest checkpoint (run
    BEFORE the uniform-shape allgather) must bring both down in
    agreement instead of wedging the healthy peer in a mismatched
    collective."""
    import time as _time

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    def launch(pid, path):
        argv = [sys.executable, "-m", "acg_tpu.cli", str(path),
                "--binary", "--distributed-read", "--nparts", "4",
                "--max-iterations", "100", "--residual-rtol", "1e-6",
                "--dtype", "f64", "--warmup", "0", "--quiet",
                "--err-timeout", "20",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid)]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env)

    t0 = _time.monotonic()
    p0 = launch(0, binfile)
    p1 = launch(1, tmp_path / "nope.bin.mtx")
    outs = [p.communicate(timeout=180) for p in (p0, p1)]
    elapsed = _time.monotonic() - t0
    assert p0.returncode != 0 and p1.returncode != 0
    assert elapsed < 150
    assert "peer controller failed during ingest" in outs[0][1]
