"""Distributed CG over the virtual 8-device CPU mesh vs serial oracles.

The analog of the reference's np=1,2,4,8 operational testing (SURVEY.md
section 4): the same partitioned solve runs over a real (simulated) mesh
with communication exercised, checked against the host solver.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from acg_tpu.graph import partition_matrix
from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.parallel.halo import build_device_halo, halo_exchange
from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh
from acg_tpu.partition import partition_rows
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from jax.sharding import PartitionSpec as P
from acg_tpu._platform import shard_map as _shard_map


@pytest.fixture(scope="module")
def problem2d():
    A = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2))
    return A.to_csr()


@pytest.fixture(scope="module")
def problem3d():
    A = SymCsrMatrix.from_mtx(poisson_mtx(7, dim=3))
    return A.to_csr()


def manufactured(csr, seed=0):
    rng = np.random.default_rng(seed)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    return xsol, csr @ xsol


def test_device_count():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"


@pytest.mark.parametrize("nparts", [2, 8])
def test_device_halo_exchange(problem2d, nparts):
    """Device halo exchange must deliver exactly the host-plan ghosts."""
    part = partition_rows(problem2d, nparts, seed=0)
    subs = partition_matrix(problem2d, part, nparts)
    halo = build_device_halo(subs)
    nmax = max(s.nowned for s in subs)
    xg = np.random.default_rng(1).standard_normal(problem2d.shape[0])
    stacked = np.zeros((nparts, nmax))
    for p, s in enumerate(subs):
        stacked[p, : s.nowned] = xg[s.global_ids[: s.nowned]]

    mesh = solve_mesh(nparts)
    ghost = jax.jit(_shard_map(
        lambda x, si, gs: halo_exchange(x[0], si[0], gs[0])[None],
        mesh=mesh,
        in_specs=(P(PARTS_AXIS),) * 3,
        out_specs=P(PARTS_AXIS)))(
            jnp.asarray(stacked), halo.send_idx, halo.ghost_src)
    ghost = np.asarray(ghost)
    for p, s in enumerate(subs):
        np.testing.assert_array_equal(ghost[p, : s.nghost],
                                      xg[s.global_ids[s.nowned:]])


@pytest.mark.parametrize("pipelined", [False, True])
@pytest.mark.parametrize("nparts", [1, 2, 8])
def test_dist_cg_matches_host(problem2d, nparts, pipelined):
    xsol, b = manufactured(problem2d, 2)
    part = partition_rows(problem2d, nparts, seed=1)
    prob = DistributedProblem.build(problem2d, part, nparts, dtype=jnp.float64)
    solver = DistCGSolver(prob, pipelined=pipelined)
    crit = StoppingCriteria(maxits=3000, residual_rtol=1e-10)
    x = solver.solve(b, criteria=crit)
    assert solver.stats.converged
    assert np.linalg.norm(x - xsol) < 1e-7

    host = HostCGSolver(SymCsrMatrix.from_coo(
        problem2d.shape[0], *_coo(problem2d)))
    host.solve(b, criteria=crit)
    assert abs(solver.stats.niterations - host.stats.niterations) <= 5


def _coo(csr):
    coo = csr.tocoo()
    return coo.row, coo.col, coo.data


def test_dist_cg_3d(problem3d):
    xsol, b = manufactured(problem3d, 3)
    part = partition_rows(problem3d, 8, seed=2)
    prob = DistributedProblem.build(problem3d, part, 8, dtype=jnp.float64)
    solver = DistCGSolver(prob)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000, residual_rtol=1e-9))
    assert np.linalg.norm(x - xsol) < 1e-6


def test_dist_cg_irregular_partition_sizes(problem2d):
    """Parts of very different sizes exercise the padding invariants."""
    n = problem2d.shape[0]
    part = np.zeros(n, dtype=np.int32)
    part[n // 8:] = 1
    part[n // 2:] = 2
    prob = DistributedProblem.build(problem2d, part, 3, dtype=jnp.float64)
    xsol, b = manufactured(problem2d, 4)
    solver = DistCGSolver(prob)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=3000, residual_rtol=1e-9))
    assert np.linalg.norm(x - xsol) < 1e-6


def test_dist_cg_maxits_only(problem2d):
    part = partition_rows(problem2d, 4, seed=3)
    prob = DistributedProblem.build(problem2d, part, 4, dtype=jnp.float64)
    solver = DistCGSolver(prob)
    solver.solve(np.ones(problem2d.shape[0]),
                 criteria=StoppingCriteria(maxits=17))
    assert solver.stats.niterations == 17
    assert solver.stats.converged
    assert solver.stats.ops["halo"].n == 18
    assert solver.stats.ops["allreduce"].n == 34


def test_dist_cg_stats_report(problem2d):
    part = partition_rows(problem2d, 2, seed=4)
    prob = DistributedProblem.build(problem2d, part, 2, dtype=jnp.float64)
    solver = DistCGSolver(prob, pipelined=True)
    solver.solve(np.ones(problem2d.shape[0]),
                 criteria=StoppingCriteria(maxits=500, residual_rtol=1e-8))
    text = solver.stats.fwrite()
    assert "total solver time: " in text
    assert solver.stats.ops["allreduce"].n == solver.stats.niterations


# -- stacked block formats (DIA local / compact ghost) ----------------------

@pytest.mark.parametrize("pipelined", [False, True])
def test_dist_cg_band_partition_dia(problem2d, pipelined):
    """A contiguous band partition of a banded matrix must select the
    gather-free DIA local format (the fast TPU path, ops/spmv.py) and
    still match the host solver."""
    nparts = 4
    part = partition_rows(problem2d, nparts, seed=0, method="band")
    prob = DistributedProblem.build(problem2d, part, nparts,
                                    dtype=jnp.float64)
    assert prob.local.format == "dia"
    assert len(prob.local.offsets) <= 5  # 5-point stencil
    xsol, b = manufactured(problem2d)
    solver = DistCGSolver(prob, pipelined=pipelined)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-6


def test_dist_cg_scattered_partition_falls_back_to_ell(problem2d):
    """A partition with non-contiguous parts cannot stay banded; the
    builder must fall back to ELL and still solve correctly."""
    n = problem2d.shape[0]
    # pathological random scatter (round-robin would still be banded:
    # stride-4 owned sets keep the +-n stencil neighbours on diagonals)
    part = np.random.default_rng(0).integers(0, 4, n).astype(np.int32)
    prob = DistributedProblem.build(problem2d, part, 4, dtype=jnp.float64)
    assert prob.local.format == "ell"
    xsol, b = manufactured(problem2d)
    solver = DistCGSolver(prob)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-6


def test_dist_ghost_block_is_compact(problem2d):
    """The ghost block must cover only coupled (border) rows, not all
    owned rows (the reference's border-rows-only o* block)."""
    part = partition_rows(problem2d, 4, seed=0, method="band")
    prob = DistributedProblem.build(problem2d, part, 4, dtype=jnp.float64)
    nmax_owned = prob.nmax_owned
    assert prob.ghost.bmax < nmax_owned / 2
    # padding row indices are out of bounds -> dropped by scatter-add
    rows = np.asarray(prob.ghost.rows)
    assert rows.max() <= nmax_owned


def test_dist_cg_pallas_kernel_tier(problem2d):
    """kernels="pallas" (interpret off-TPU) on a band partition (DIA
    local blocks) agrees with the XLA tier -- the distributed analog of
    the single-device Pallas SpMV tests."""
    csr = problem2d
    xsol, b = manufactured(csr)
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    assert prob.local.format == "dia"
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    x_xla = DistCGSolver(prob, kernels="xla").solve(b, criteria=crit)
    sp = DistCGSolver(prob, kernels="pallas")
    assert sp.kernels == "pallas-interpret"  # CPU mesh resolves interpret
    x_pal = sp.solve(b, criteria=crit)
    assert np.linalg.norm(x_pal - xsol) < 1e-8
    np.testing.assert_allclose(x_pal, x_xla, rtol=0, atol=1e-9)


def test_dist_cg_pallas_falls_back_on_ell(problem2d):
    """Graph partitions give ELL local blocks; the pallas tier must fall
    back to the XLA path (same contract as the single-device solver for
    non-DIA matrices)."""
    csr = problem2d
    xsol, b = manufactured(csr, seed=2)
    part = partition_rows(csr, 4, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    if prob.local.format == "dia":
        pytest.skip("graph partition unexpectedly banded")
    x = DistCGSolver(prob, kernels="pallas").solve(
        b, criteria=StoppingCriteria(maxits=2000, residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-8


def test_dist_binned_ell_local_blocks():
    """Power-law (SuiteSparse-class) workloads trigger the length-binned
    local-block layout on the mesh (round-4 verdict item 3): plain-ELL
    hub-row padding would blow the waste limit.  Solve must match the
    serial oracle, and the format must report binnedell."""
    from acg_tpu.io.generators import irregular_spd_coo
    from acg_tpu.matrix import SymCsrMatrix

    r, c, v, N = irregular_spd_coo(3000, avg_degree=8.0, seed=0)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    Kmax = int(np.diff(csr.indptr).max())
    assert Kmax * N > 3.0 * csr.nnz  # the workload really is skewed
    xsol, b = manufactured(csr, seed=1)
    iters = []
    for nparts in (1, 4, 8):
        part = partition_rows(csr, nparts, seed=0, method="graph")
        prob = DistributedProblem.build(csr, part, nparts,
                                        dtype=jnp.float64)
        assert prob.local.format == "binnedell"
        # mesh-uniform: every bin array's leading axis is nparts and
        # every part's padding rows are out-of-bounds sentinels
        bin_rows = prob.local.arrays[0]
        assert all(a.shape[0] == nparts for a in bin_rows)
        solver = DistCGSolver(prob)
        x = solver.solve(b, criteria=StoppingCriteria(
            maxits=4000, residual_rtol=1e-10))
        assert np.linalg.norm(x - xsol) < 1e-6
        iters.append(solver.stats.niterations)
    # partition-invariant iteration counts (up to rounding)
    assert max(iters) - min(iters) <= max(2, int(0.02 * max(iters)))


def test_dist_binned_ell_matches_ell_spmv():
    """The binned stacked SpMV is numerically the same operator as the
    plain-ELL stacked SpMV on the same problem (format is a layout
    choice, not an arithmetic one)."""
    from acg_tpu.io.generators import irregular_spd_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.parallel.dist import _stack_local_blocks

    r, c, v, N = irregular_spd_coo(1000, avg_degree=6.0, seed=3)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 4, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    assert prob.local.format == "binnedell"
    # force the plain-ELL stacking of the same subdomains
    ell = _stack_local_blocks(prob.subs, prob.nmax_owned, jnp.float64,
                              ell_waste_limit=1e9)
    assert ell.format == "ell"
    rng = np.random.default_rng(0)
    for p in range(4):
        x = rng.standard_normal(prob.nmax_owned)
        y_bell = prob.local.shard_mv(
            jax.tree.map(lambda a: jnp.asarray(a[p]), prob.local.arrays),
            jnp.asarray(x))
        y_ell = ell.shard_mv(
            jax.tree.map(lambda a: jnp.asarray(a[p]), ell.arrays),
            jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(y_bell), np.asarray(y_ell),
                                   rtol=0, atol=1e-12)


def test_refined_distributed_solver(problem2d):
    """Mixed-precision refinement over the DISTRIBUTED solver (the CLI's
    --refine --nparts N path): f32 device CG + f64 host residual reaches
    f64-class accuracy."""
    from acg_tpu.solvers.refine import RefinedSolver

    csr = problem2d
    xsol, b = manufactured(csr, seed=4)
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float32)
    inner = DistCGSolver(prob)
    solver = RefinedSolver(inner, csr, inner_rtol=1e-5)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=4000,
                                                  residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-7  # beyond f32's ~1e-6 stall
