"""Irregular (power-law) SPD workload -- the SuiteSparse stand-in
(BASELINE.json configs 4-5).  Exercises the non-banded SpMV formats and
graph partitioning on matrices where DIA/band layouts don't apply."""

import numpy as np
import pytest

from acg_tpu.io.generators import irregular_mtx, irregular_spd_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.host_cg import HostCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = irregular_spd_coo(1500, avg_degree=12, seed=3)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_generator_properties(csr):
    n = csr.shape[0]
    deg = np.diff(csr.indptr)
    assert 8 <= csr.nnz / n <= 20            # near requested density
    assert deg.max() >= 5 * deg.mean()       # genuinely heavy-tailed
    # strict diagonal dominance with positive diagonal -> SPD
    A = csr.toarray()
    d = np.diag(A)
    assert (d > 0).all()
    assert (d >= np.abs(A - np.diag(d)).sum(axis=1) + 0.999).all()
    # not a banded matrix: the DIA heuristic must decline it
    from acg_tpu.ops.spmv import prefers_dia
    assert not prefers_dia(csr)


def test_host_and_device_agree(csr):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    n = csr.shape[0]
    rng = np.random.default_rng(0)
    xsol = rng.standard_normal(n)
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    xh = HostCGSolver(csr).solve(b, criteria=crit)
    xd = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64)).solve(
        b, criteria=crit)
    assert np.linalg.norm(xh - xsol) < 1e-8
    assert np.linalg.norm(xd - xsol) < 1e-8


def test_distributed_solve(csr):
    import jax.numpy as jnp

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    n = csr.shape[0]
    rng = np.random.default_rng(1)
    xsol = rng.standard_normal(n)
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    part = partition_rows(csr, 4, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    solver = DistCGSolver(prob, pipelined=True)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-8


def test_mtx_roundtrip(tmp_path):
    from acg_tpu.io.mtxfile import read_mtx, write_mtx

    mtx = irregular_mtx(300, avg_degree=10, seed=7)
    assert mtx.symmetry == "symmetric"
    path = tmp_path / "irr.mtx"
    write_mtx(path, mtx)
    back = read_mtx(path)
    np.testing.assert_array_equal(back.rowidx, mtx.rowidx)
    np.testing.assert_allclose(back.vals, mtx.vals)


def test_genmatrix_cli(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "irr.mtx"
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.tools.genmatrix", "-n", "400",
         "--kind", "irregular", "--avg-degree", "8", "-o", str(out), "-v"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    from acg_tpu.io.mtxfile import read_mtx

    m = read_mtx(out)
    assert m.nrows == 400 and m.symmetry == "symmetric"
