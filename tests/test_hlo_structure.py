"""Structural (HLO-level) properties of the compiled distributed solve.

The pipelined variant's entire reason to exist is communication
avoidance: both CG scalars ride ONE allreduce per iteration where
classic CG needs two (``cgcuda.c:1730-1737``; our ``pdot2_fused``).
These tests pin that property at the compiler-artifact level -- if a
refactor accidentally splits the fused psum or adds a collective to the
loop body, the lowered program's collective counts change and this
fails, no timing required.
"""

import re

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows


@pytest.fixture(scope="module")
def prob():
    r, c, v, N = poisson2d_coo(16)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 4, seed=0, method="band")
    return DistributedProblem.build(csr, part, 4, dtype=jnp.float64)


def _lowered_text(prob, pipelined):
    s = DistCGSolver(prob, pipelined=pipelined)
    b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = s.device_args(
        np.ones(prob.n))
    tols = jnp.zeros(4)
    args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols, jnp.int32(5))
    return s._program.lower(*args, unbounded=True,
                            needs_diff=False).as_text()


def _counts(txt):
    return (len(re.findall(r"all_reduce", txt)),
            len(re.findall(r"all_to_all", txt)),
            len(re.findall(r"stablehlo\.while|\bwhile\b", txt)))


def test_collective_counts(prob):
    """Static collective inventory of the whole-solve programs.

    The loop body appears once in the program text, so whole-program
    counts decompose as setup + body:
      classic:   3 setup psums (||b||, ||x0||, gamma0) + 2 in-loop
                 ((p,t) and (r,r))                         -> 5 ARs
                 1 setup SpMV (r0) + 1 in-loop SpMV        -> 2 A2As
      pipelined: 4 setup psums (+ final fresh ||r||)
                 + 1 in-loop FUSED psum                    -> 5 ARs
                 2 setup SpMVs (r0, w=Ar) + 1 in-loop      -> 3 A2As
    """
    ar_c, ata_c, wl_c = _counts(_lowered_text(prob, pipelined=False))
    ar_p, ata_p, wl_p = _counts(_lowered_text(prob, pipelined=True))
    assert wl_c >= 1 and wl_p >= 1, "solve loop not compiled as while"
    assert ar_c == 5, f"classic program has {ar_c} all_reduces, expected 5"
    assert ata_c == 2, f"classic program has {ata_c} all_to_alls, expected 2"
    assert ar_p == 5, f"pipelined program has {ar_p} all_reduces, expected 5"
    assert ata_p == 3, f"pipelined program has {ata_p} all_to_alls, expected 3"
    # the communication-avoiding property, stated relatively: same AR
    # total despite one extra setup psum => one FEWER in-loop allreduce
    assert ar_p - 4 == 1 and ar_c - 3 == 2


def test_precise_dots_keep_fusion(prob):
    """Compensated dots widen each psum payload (hi+lo pairs) but must
    not add collectives: the pipelined loop still has ONE allreduce."""
    s = DistCGSolver(prob, pipelined=True, precise_dots=True)
    b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = s.device_args(
        np.ones(prob.n))
    tols = jnp.zeros(4)
    args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols, jnp.int32(5))
    txt = s._program.lower(*args, unbounded=True, needs_diff=False).as_text()
    ar, ata, _ = _counts(txt)
    assert ar == 5, f"precise-dots pipelined program has {ar} all_reduces"
    assert ata == 3


# -- preconditioning tier: none = byte-identical; PCG keeps the
# communication-avoiding structure --------------------------------------

def test_precond_none_is_byte_identical(prob):
    """--precond none must lower BYTE-IDENTICAL programs to a build
    that never mentions the preconditioner -- single-chip and
    distributed (the telemetry/faults/perfmodel disarmament contract,
    extended to the PCG tier)."""
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.matrix import SymCsrMatrix as _S
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    r, c, v, N = _p2(12)
    csr = _S.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    plain = JaxCGSolver(A, kernels="xla").lower_solve(b1).as_text()
    none = JaxCGSolver(A, kernels="xla",
                       precond="none").lower_solve(b1).as_text()
    armed = JaxCGSolver(A, kernels="xla",
                        precond="jacobi").lower_solve(b1).as_text()
    assert none == plain
    assert armed != plain

    b2 = np.ones(prob.n)
    d_plain = DistCGSolver(prob).lower_solve(b2).as_text()
    d_none = DistCGSolver(prob, precond="none").lower_solve(b2).as_text()
    assert d_none == d_plain


def test_pcg_collective_counts(prob):
    """PCG keeps the tiers' communication structure: the classic loop
    still runs 2 in-loop allreduces (the second FUSES (r, z) with
    (r, r)), the pipelined loop keeps its SINGLE fused in-loop
    allreduce (now 3 scalars), and cheby:K adds exactly K halo'd SpMVs
    per apply site (setup + loop = 2K extra all_to_alls)."""
    b = np.ones(prob.n)

    def counts(pipelined, pc):
        s = DistCGSolver(prob, pipelined=pipelined, precond=pc)
        return _counts(s.lower_solve(b).as_text())[:2]

    # jacobi/bjacobi: zero extra collectives anywhere
    assert counts(False, "jacobi") == (5, 2)
    assert counts(True, "jacobi") == (5, 3)
    assert counts(False, "bjacobi:16") == (5, 2)
    assert counts(True, "bjacobi:16") == (5, 3)
    # cheby:2 -> 2 apply sites x 2 SpMVs, allreduce count unchanged
    assert counts(False, "cheby:2") == (5, 2 + 4)
    assert counts(True, "cheby:2") == (5, 3 + 4)


# -- numerical-health tier: disarmed audit = byte-identical; armed adds
# exactly the conditional audit collectives ------------------------------

def test_health_disarmed_is_byte_identical(prob):
    """--audit-every 0 (default) must lower BYTE-IDENTICAL programs to
    a build that never mentions the health tier -- single-chip and
    distributed (the telemetry/faults/precond/perfmodel disarmament
    contract, extended to the audit)."""
    from acg_tpu.health import make_spec
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    for pipelined in (False, True):
        plain = JaxCGSolver(A, pipelined=pipelined,
                            kernels="xla").lower_solve(b1).as_text()
        none = JaxCGSolver(A, pipelined=pipelined, kernels="xla",
                           health=None).lower_solve(b1).as_text()
        armed = JaxCGSolver(
            A, pipelined=pipelined, kernels="xla",
            health=make_spec(every=4)).lower_solve(b1).as_text()
        assert none == plain
        assert armed != plain

    b2 = np.ones(prob.n)
    for pipelined in (False, True):
        d_plain = DistCGSolver(prob,
                               pipelined=pipelined).lower_solve(
                                   b2).as_text()
        d_none = DistCGSolver(prob, pipelined=pipelined,
                              health=None).lower_solve(b2).as_text()
        assert d_none == d_plain


def test_health_armed_collective_counts(prob):
    """The armed audit adds EXACTLY one conditional halo'd SpMV (one
    all_to_all region) and one psum (one all_reduce region) to the
    distributed program text -- the audit reuses the tier's own
    machinery, nothing else moves."""
    from acg_tpu.health import make_spec

    b = np.ones(prob.n)

    def counts(pipelined, hs):
        s = DistCGSolver(prob, pipelined=pipelined, health=hs)
        return _counts(s.lower_solve(b).as_text())[:2]

    assert counts(False, None) == (5, 2)
    assert counts(False, make_spec(every=4)) == (6, 3)
    assert counts(True, None) == (5, 3)
    assert counts(True, make_spec(every=4)) == (6, 4)


# -- perfmodel tier: disarmed observability changes NOTHING ---------------

def test_lower_solve_is_the_dispatched_program(prob):
    """The perfmodel hook (DistCGSolver.lower_solve) must hand out the
    program solve() dispatches -- byte-identical StableHLO to lowering
    the cached program by hand with solve()'s own argument
    construction.  A hook that rebuilt or re-parameterised the program
    could silently analyse something the solve never runs."""
    s = DistCGSolver(prob)
    b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = s.device_args(
        np.ones(prob.n))
    tols = jnp.zeros(4)
    args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
            jnp.int32(100))
    direct = s._program.lower(*args, unbounded=True, needs_diff=False,
                              detect=False).as_text()
    hook = s.lower_solve(np.ones(prob.n)).as_text()
    assert hook == direct


def test_perfmodel_analysis_leaves_programs_byte_identical(prob):
    """Disarmed perfmodel (like disarmed telemetry): running a full
    analysis pass -- lower, compile, cost/memory extraction, comm
    ledger -- must leave the solver's lowered solve program
    byte-identical, single-chip and distributed."""
    from acg_tpu import perfmodel
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    s1 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                     kernels="xla")
    before = s1.lower_solve(b1).as_text()
    perfmodel.analyze_solver(s1, b1)
    perfmodel.per_iteration_cost(s1, b1)
    assert s1.lower_solve(b1).as_text() == before

    s2 = DistCGSolver(prob)
    b2 = np.ones(prob.n)
    before2 = s2.lower_solve(b2).as_text()
    perfmodel.analyze_solver(s2, b2)
    perfmodel.comm_ledger(s2)
    assert s2.lower_solve(b2).as_text() == before2


def test_metrics_layer_leaves_programs_byte_identical(prob):
    """The service-metrics tier is host-side bookkeeping only: arming
    the registry, recording solves/phases/events, and a full soak pass
    must leave the lowered solve programs byte-identical, single-chip
    and distributed (the telemetry/faults/perfmodel disarmament
    contract, extended to PR 4's layer)."""
    from acg_tpu import metrics, soak
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    s1 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                     kernels="xla")
    s2 = DistCGSolver(prob)
    b2 = np.ones(prob.n)
    before1 = s1.lower_solve(b1).as_text()
    before2 = s2.lower_solve(b2).as_text()
    was = metrics.armed()
    try:
        metrics.arm()
        soak.run_soak(s1, b1, nsolves=3,
                      criteria=StoppingCriteria(maxits=20),
                      solve_kwargs={"raise_on_divergence": False})
        s2.solve(b2, criteria=StoppingCriteria(maxits=10),
                 raise_on_divergence=False)
        assert s1.lower_solve(b1).as_text() == before1
        assert s2.lower_solve(b2).as_text() == before2
    finally:
        if not was:
            metrics.disarm()


def test_tracing_layer_leaves_programs_byte_identical(prob):
    """The timeline-tracing tier is host-side bookkeeping only: arming
    the span recorder, recording phase spans / instants, and solving
    under it must leave the lowered solve programs byte-identical,
    single-chip and distributed (the metrics-layer disarmament
    contract, extended to PR 8's layer)."""
    from acg_tpu import tracing
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    s1 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                     kernels="xla")
    s2 = DistCGSolver(prob)
    b2 = np.ones(prob.n)
    before1 = s1.lower_solve(b1).as_text()
    before2 = s2.lower_solve(b2).as_text()
    try:
        tracing.arm()
        s1.solve(b1, criteria=StoppingCriteria(maxits=10),
                 raise_on_divergence=False)
        s2.solve(b2, criteria=StoppingCriteria(maxits=10),
                 raise_on_divergence=False)
        assert tracing.nspans() > 0  # the hooks DID record
        assert s1.lower_solve(b1).as_text() == before1
        assert s2.lower_solve(b2).as_text() == before2
    finally:
        tracing.disarm()


def test_reqtrace_layer_leaves_programs_byte_identical(prob, tmp_path):
    """The request observatory is host-side stdlib bookkeeping only:
    serving requests with the access ledger armed and request spans
    riding the tracing recorder must leave the lowered solve programs
    byte-identical, single-chip and distributed (the
    metrics/tracing/planner disarmament contract, extended to the
    per-request layer)."""
    from acg_tpu import observatory, tracing
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.serve import ServeConfig, ServeDaemon
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    s1 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                     kernels="xla")
    s2 = DistCGSolver(prob)
    b2 = np.ones(prob.n)
    before1 = s1.lower_solve(b1).as_text()
    before2 = s2.lower_solve(b2).as_text()
    d = ServeDaemon(ServeConfig(
        port=0, default_timeout=60.0,
        access_log=str(tmp_path / "access.jsonl")))
    d.start()
    try:
        tracing.arm()
        status, body = d.submit({"matrix": "gen:poisson2d:12",
                                 "rtol": 1e-8, "maxits": 300,
                                 "request_id": "pin-1"})
        assert status == 200 and body["request_id"] == "pin-1"
        assert tracing.nspans() > 0  # the request lanes DID record
        s1.solve(b1, criteria=StoppingCriteria(maxits=10),
                 raise_on_divergence=False)
        assert s1.lower_solve(b1).as_text() == before1
        assert s2.lower_solve(b2).as_text() == before2
    finally:
        tracing.disarm()
        d.stop()
        observatory._clear_slo()


def test_planner_leaves_programs_byte_identical(prob):
    """The decision observatory is host arithmetic only: building a
    full ranked plan (kappa oracle, candidate pricing, rendering) must
    leave the lowered solve programs byte-identical, single-chip and
    distributed -- disarmed (no --autotune/--plan), the planner never
    touches program emission (the perfmodel/metrics/tracing
    disarmament contract, extended to the planner's layer)."""
    from acg_tpu import planner
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    s1 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64),
                     kernels="xla")
    s2 = DistCGSolver(prob)
    b2 = np.ones(prob.n)
    before1 = s1.lower_solve(b1).as_text()
    before2 = s2.lower_solve(b2).as_text()
    kappa, src = planner.kappa_estimate(csr, 1e-6, 200)
    doc = planner.build_plan(
        csr, matrix_id="gen:poisson2d:12", nparts=4,
        dtype_name="float64", rtol=1e-6, maxits=200,
        mat_itemsize=8, vec_itemsize=8, kappa=kappa,
        kappa_source=src)
    assert doc["ranked"]
    planner.render_plan(doc)
    assert s1.lower_solve(b1).as_text() == before1
    assert s2.lower_solve(b2).as_text() == before2


def test_tracing_section_appends_only():
    """Like costmodel:/soak:/ckpt:, the tracing: section appends
    strictly after every existing section -- a report without it is a
    byte-prefix of one with it, so pre-/7 consumers see the exact
    historical block."""
    from acg_tpu.solvers.stats import SolverStats

    st = SolverStats(unknowns=7)
    st.timings["solve"] = 0.25
    st.ckpt.update({"every": 8})
    base = st.fwrite()
    st.tracing.update({"available": True,
                       "op_seconds": {"dot": 0.01},
                       "overlap_efficiency": 0.75,
                       "timeline": {"nspans": 5, "nparts": 2}})
    txt = st.fwrite()
    assert txt.startswith(base)
    tail = txt[len(base):]
    assert tail.index("tracing:") >= 0
    assert base.index("ckpt:") < len(base)  # tracing: renders after it
    d = st.to_dict()
    assert d["tracing"]["timeline"]["nparts"] == 2


def test_soak_section_appends_only():
    """Like costmodel:/memory:, the soak: section appends strictly
    after the reference-format block -- a report without it is a
    byte-prefix of one with it."""
    from acg_tpu.solvers.stats import SolverStats

    st = SolverStats(unknowns=7)
    st.timings["solve"] = 0.25
    st.costmodel.update({"flops": 1.0})
    base = st.fwrite()
    st.soak.update({"nsolves": 3,
                    "latency": {"p50": 0.001, "p95": 0.002},
                    "drift": {"tripped": False}})
    txt = st.fwrite()
    assert txt.startswith(base)
    assert "soak:" in txt[len(base):]
    assert st.to_dict()["soak"]["latency"]["p50"] == 0.001


def test_explain_sections_append_only():
    """--explain never mutates the reference-format stats block: the
    costmodel:/memory: sections (like timings:) append strictly AFTER
    it, so the report with them set starts byte-for-byte with the
    report without them."""
    from acg_tpu.solvers.stats import SolverStats

    st = SolverStats(unknowns=7)
    st.timings["solve"] = 0.25  # an existing optional section, for order
    base = st.fwrite()
    st.costmodel.update({"flops": 123.0,
                         "comm": {"halo_bytes_per_iteration": 64,
                                  "neighbors": [{"src": 0, "dst": 1}]}})
    st.memory.update({"argument_bytes": 10, "total_hbm_bytes": 10})
    txt = st.fwrite()
    assert txt.startswith(base)
    tail = txt[len(base):]
    assert tail.index("costmodel:") < tail.index("memory:")
    # lists render summarised in text (full form lives in the JSON twin)
    assert "[1 entries -- see --stats-json]" in tail
    # and the JSON twin round-trips the full structure
    d = st.to_dict()
    assert d["costmodel"]["comm"]["neighbors"] == [{"src": 0, "dst": 1}]


# -- recurrence builder (acg_tpu.recurrence): byte-identity + the
# communication-avoiding collective pins --------------------------------

def _norm_module(txt):
    """Normalise the ONE permitted difference between builder-emitted
    and hand-built programs: the module symbol, which StableHLO derives
    from the jitted wrapper's Python name (`module @jit_<fn>`), not
    from the traced computation.  Everything after it must match
    byte-for-byte."""
    return re.sub(r"module @jit_\w+", "module @jit_PROGRAM", txt,
                  count=1)


def test_builder_emission_byte_identical_single():
    """The builder's classic/GV-pipelined emission (recurrence.
    _builder_cg_program, composed from classic_recurrence /
    pipelined_recurrence over TierOps) lowers BYTE-IDENTICAL StableHLO
    to the hand-built jax_cg programs -- the proof the recurrence
    refactor is a no-op for current users (ISSUE 12 acceptance)."""
    import jax.numpy as jnp

    from acg_tpu import recurrence as rec
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.matrix import SymCsrMatrix as _S
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers import jax_cg as jc

    r, c, v, N = _p2(12)
    csr = _S.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    b = jnp.ones(N)
    x0 = jnp.zeros(N)
    z = jnp.float64(0.0)
    a = (A, b, x0, z, jnp.float64(1e-8), z, z, jnp.int32(50))
    for pipelined in (False, True):
        hand = (jc._cg_pipelined_program if pipelined
                else jc._cg_program).lower(
            *a, unbounded=False, needs_diff=False).as_text()
        built = rec._builder_cg_program.lower(
            *a, unbounded=False, needs_diff=False,
            pipelined=pipelined).as_text()
        assert _norm_module(built) == _norm_module(hand), \
            f"builder emission diverged (pipelined={pipelined})"


def test_builder_emission_byte_identical_dist(prob):
    """Dist-tier twin: recurrence.build_dist_program composes the SAME
    recurrence bodies with DistCGSolver's halo'd SpMV / fused-psum
    machinery and lowers byte-identical StableHLO to the hand-built
    shard_map program."""
    from acg_tpu import recurrence as rec

    for pipelined in (False, True):
        s = DistCGSolver(prob, pipelined=pipelined)
        b, x0, la, ga, sidx, gsrc, gval, scnt, rcnt = s.device_args(
            np.ones(prob.n))
        tols = jnp.zeros(4)
        args = (la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                jnp.int32(5))
        hand = s._program.lower(*args, unbounded=True,
                                needs_diff=False).as_text()
        built = rec.build_dist_program(s).lower(
            *args, unbounded=True, needs_diff=False).as_text()
        assert _norm_module(built) == _norm_module(hand), \
            f"dist builder emission diverged (pipelined={pipelined})"


def _ca_counts(prob, algorithm):
    s = DistCGSolver(prob, algorithm=algorithm)
    txt = s.lower_solve(np.ones(prob.n)).as_text()
    return _counts(txt)


def test_sstep_collective_counts(prob):
    """s-step CG's communication-avoiding property at the HLO level:
    exactly ONE in-loop allreduce per s-iteration block, for every S --
    whole-program decomposition: 3 setup psums (||b||, ||x0||, gamma0)
    + 1 in-loop Gram -> 4 allreduces REGARDLESS of S (classic: 5, with
    2 in-loop); all_to_alls = 1 setup SpMV + the 2S-1 in-loop basis
    products."""
    for S in (2, 4, 8):
        ar, ata, wl = _ca_counts(prob, f"sstep:{S}")
        assert wl >= 1
        assert ar == 4, f"sstep:{S} lowered {ar} all_reduces, expected 4"
        assert ata == 2 * S, (f"sstep:{S} lowered {ata} all_to_alls, "
                              f"expected {2 * S} (1 setup + 2S-1 basis)")
    # the comparison the tier exists for: classic carries 2 in-loop
    # allreduces (5 total), s-step carries 1 per BLOCK (4 total)
    ar_c, _, _ = _counts(_lowered_text(prob, pipelined=False))
    assert ar_c == 5


def test_pl_collective_counts(prob):
    """p(l)-CG keeps ONE fused allreduce per iteration (the 2l+2-scalar
    z-window reduction) for every depth: 3 setup psums + 1 in-loop ->
    4 allreduces, 1 setup + 1 in-loop SpMV -> 2 all_to_alls."""
    for L in (2, 3):
        ar, ata, wl = _ca_counts(prob, f"pipelined:{L}")
        assert wl >= 1
        assert ar == 4, (f"pipelined:{L} lowered {ar} all_reduces, "
                         f"expected 4")
        assert ata == 2, (f"pipelined:{L} lowered {ata} all_to_alls, "
                          f"expected 2")


# -- matrix-free operator tier (acg_tpu.ops.operator): disarmed =
# byte-identical; armed keeps the assembled collective pins ---------------

def _armed_matfree_prob():
    from acg_tpu.ops.operator import poisson_stencil
    from acg_tpu.parallel.dist import arm_matfree

    r, c, v, N = poisson2d_coo(16)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    part = partition_rows(csr, 4, seed=0, method="band")
    p = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    arm_matfree(p, poisson_stencil(16, 2, dtype=jnp.float64))
    return p


def test_matfree_dist_collective_counts(prob):
    """Matrix-free dist programs keep the assembled collective pins
    EXACTLY -- classic 5 AR / 2 A2A, pipelined 5/3: only the local
    plane reads vanished, the halo/reduction machinery is untouched --
    and comm='dma' drops the all_to_alls entirely (the one-sided
    transport, unchanged under the operator)."""
    for pipelined, want in ((False, (5, 2)), (True, (5, 3))):
        ar, ata, wl = _counts(_lowered_text(_armed_matfree_prob(),
                                            pipelined))
        assert wl >= 1
        assert (ar, ata) == want, \
            f"matfree pipelined={pipelined}: {(ar, ata)} != {want}"
    s = DistCGSolver(_armed_matfree_prob(), comm="dma")
    ar, ata, _ = _counts(s.lower_solve(np.ones(16 * 16)).as_text())
    assert ata == 0, f"comm='dma' matfree kept {ata} all_to_alls"
    assert ar == 5


def test_operator_disarmed_is_byte_identical(prob):
    """--operator absent lowers byte-identical programs on every tier
    (the precond/health/telemetry disarmament contract, extended to
    the operator): arming a matfree TWIN problem leaves the plain
    build's lowered text unchanged, and the armed program itself
    differs (its local planes are gone)."""
    from acg_tpu.io.generators import poisson2d_coo as _p2
    from acg_tpu.ops.operator import poisson_stencil
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    b = np.ones(prob.n)
    plain_before = DistCGSolver(prob).lower_solve(b).as_text()
    armed_txt = DistCGSolver(_armed_matfree_prob()).lower_solve(
        b).as_text()
    plain_after = DistCGSolver(prob).lower_solve(b).as_text()
    assert plain_after == plain_before
    assert armed_txt != plain_before

    r, c, v, N = _p2(12)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    b1 = np.ones(N)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    assembled_before = JaxCGSolver(A, kernels="xla").lower_solve(
        b1).as_text()
    op_txt = JaxCGSolver(poisson_stencil(12, 2, dtype=jnp.float64),
                         kernels="xla").lower_solve(b1).as_text()
    assembled_after = JaxCGSolver(A, kernels="xla").lower_solve(
        b1).as_text()
    assert assembled_after == assembled_before
    assert op_txt != assembled_before
