"""Cross-controller failure agreement (parallel/erragree).

The ``acgerrmpi`` analog (``acg/error.c``, used at
``cuda/acg-cuda.c:2410``): one controller failing a host-local stage
must bring the whole pod down promptly and in agreement, instead of one
process dying alone while the peer wedges in the next collective until
a scheduler timeout.  Both failure shapes are tested on the real
2-process CPU pod: (a) a one-sided ingest error agreed at the
checkpoint, (b) a peer that dies before ever reaching a checkpoint,
detected by the watchdog.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.io.mtxfile import write_mtx
from acg_tpu.parallel.erragree import PEER_LOST_EXIT


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def matrix_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("ea") / "p12.mtx"
    write_mtx(path, poisson_mtx(12, dim=2))
    return path


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


def _cli(matrix, port, pid, timeout_s="20"):
    return subprocess.Popen(
        [sys.executable, "-m", "acg_tpu.cli", str(matrix),
         "--nparts", "4", "--max-iterations", "200",
         "--residual-rtol", "1e-6", "--dtype", "f64", "--warmup", "0",
         "--quiet", "--err-timeout", timeout_s,
         "--coordinator", f"localhost:{port}",
         "--num-processes", "2", "--process-id", str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_env())


def test_one_sided_ingest_error_agreed(matrix_file, tmp_path):
    """Process 1 reads a nonexistent matrix; process 0 is healthy.  The
    ingest checkpoint must bring BOTH down nonzero within seconds --
    process 0 reporting the peer failure, not hanging into the solve."""
    port = _free_port()
    t0 = time.monotonic()
    p0 = _cli(matrix_file, port, 0)
    p1 = _cli(tmp_path / "missing.mtx", port, 1)
    outs = [p.communicate(timeout=120) for p in (p0, p1)]
    elapsed = time.monotonic() - t0
    assert p0.returncode != 0 and p1.returncode != 0
    assert elapsed < 100
    assert "missing.mtx" in outs[1][1]
    assert "peer controller failed during ingest" in outs[0][1]


def test_dead_peer_trips_watchdog(matrix_file):
    """Process 1 joins the pod (coordinator + backend device exchange)
    then dies WITHOUT reaching any checkpoint; process 0's ingest
    agreement must abort promptly (watchdog or failed collective), not
    hang until a cluster timeout.

    Teardown tiers, by failure window: a peer dying before the backend
    device exchange parks the survivor inside jax.devices(), where JAX's
    own coordination-service heartbeat kills it (~100 s, measured); a
    peer dying any time after that is caught by OUR checkpoint watchdog
    in --err-timeout seconds.  This test pins the second tier."""
    port = _free_port()
    p0 = _cli(matrix_file, port, 0, timeout_s="8")
    # jax.config, not just the env var: the axon TPU plugin overrides
    # JAX_PLATFORMS in raw subprocesses, and with the tunnel down the
    # backend init HANGS instead of failing (observed round 5)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from acg_tpu.parallel.multihost import initialize; "
            f"initialize('localhost:{port}', 2, 1); "
            "jax.devices(); "   # complete the device exchange
            "import os; os._exit(42)")
    p1 = subprocess.Popen([sys.executable, "-c", code], env=_env())
    t0 = time.monotonic()
    out, err = p0.communicate(timeout=120)
    elapsed = time.monotonic() - t0
    p1.wait(timeout=30)
    assert p0.returncode != 0
    # watchdog exit is the designed path; a fast-failing collective or
    # the heartbeat tier are acceptable -- either way, well under the
    # 600 s CI timeout the round-2 verdict flagged
    assert elapsed < 90, err
    if p0.returncode == PEER_LOST_EXIT:
        assert "peer controller died" in err or "timed out" in err
