"""The solver service (``--serve``, ISSUE 16): admission control,
request isolation, caches, coalescing, and the self-healing loop.

The acceptance contract:
  * a second identical request pays ZERO ingest and ZERO compile --
    asserted via the ``acg_serve_cache_*`` families AND the untouched
    ``acg_compiles_total`` counter;
  * a coalesced batch answers each member BITWISE equal to serving it
    singly (the batched-classic column-identity, re-pinned here);
  * the bounded queue sheds with a typed 429, an expired request is
    answered with a typed 504 -- never a hang;
  * SLO error-budget burn drives degrade-before-refuse: past
    ``degrade_burn`` requests are served on the cheap profile and
    marked ``degraded``; past ``shed_burn`` they are refused typed;
  * a crashed daemon relaunches under the supervisor and WARM-RESTORES
    its operator cache from the persisted serve state;
  * the chaos campaign against the LIVE daemon (schedule 1 forced
    crash-mid-request) ends serving with zero wrong-answer-green.
"""

import argparse
import contextlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from acg_tpu import metrics, observatory
from acg_tpu import supervisor as sup_mod
from acg_tpu.cli import synthesize_host_matrix
from acg_tpu.serve import (COALESCE_WINDOW_SECS, RequestRefused,
                           SCHEMA, STATE_SCHEMA, ServeConfig,
                           ServeDaemon, _Request, _serve_validate,
                           config_from_args, serve_chaos_schedule)

MATRIX = "gen:poisson2d:12"
_CSR = synthesize_host_matrix(MATRIX).to_csr()
N = int(_CSR.shape[0])

ENV = {"JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "1",
       "PYTHONPATH": os.path.dirname(os.path.dirname(
           os.path.abspath(__file__)))}


def _counter(name: str) -> float:
    """Sum every sample of a counter family in the exposition (labeled
    or not) -- tests assert DELTAS, the registry is process-global."""
    total = 0.0
    for line in metrics.expose().splitlines():
        if line.startswith("#"):
            continue
        head, _, val = line.rpartition(" ")
        if head == name or head.startswith(name + "{"):
            total += float(val)
    return total


@contextlib.contextmanager
def _daemon(**kw):
    kw.setdefault("port", 0)
    kw.setdefault("default_timeout", 60.0)
    d = ServeDaemon(ServeConfig(**kw))
    d.start()
    try:
        yield d
    finally:
        d.stop()
        observatory._clear_slo()


def _doc(**kw):
    doc = {"matrix": MATRIX, "rtol": 1e-8, "maxits": 300}
    doc.update(kw)
    return doc


def _true_rel(x, b) -> float:
    r = b - _CSR @ np.asarray(x, dtype=np.float64)
    return float(np.linalg.norm(r) / np.linalg.norm(b))


# -- validation & refusal matrix ------------------------------------------

def _serve_args(**kw):
    ns = argparse.Namespace(
        A=MATRIX, soak=0, resume=None, b=None, x0=None, output=None,
        explain=False, bench=False, nrhs=0, block_cg=False,
        fault_inject=None, manufactured_solution=False,
        distributed_read=False, output_comm_matrix=False,
        profile_ops=None, ckpt=None, serve_port=0,
        serve_queue_depth=16, serve_coalesce=8, serve_deadline=60.0,
        nparts=0, comm="xla", dtype="f64", serve_faults=False)
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_serve_validate_refusal_matrix():
    _serve_validate(_serve_args())  # the clean profile passes
    for kw, frag in [
            ({"soak": 5}, "--soak"),
            ({"resume": "snap"}, "--resume"),
            ({"b": "b.npy"}, "b/x0"),
            ({"output": "x.npy"}, "-o/--output"),
            ({"explain": True}, "--explain"),
            ({"bench": True}, "--bench"),
            ({"nrhs": 4}, "--nrhs"),
            ({"block_cg": True}, "--nrhs"),
            ({"fault_inject": "spmv:nan@3"}, "--fault-inject"),
            ({"manufactured_solution": True}, "--manufactured"),
            ({"plan": "p.json"}, "--plan"),
            ({"A": "matrix.mtx"}, "gen:")]:
        with pytest.raises(SystemExit, match=frag):
            _serve_validate(_serve_args(**kw))


def test_config_from_args_state_suffix():
    cfg = config_from_args(_serve_args(ckpt="/tmp/ck"))
    assert cfg.state_path == "/tmp/ck.serve.json"
    assert cfg.preload == MATRIX
    assert config_from_args(_serve_args()).state_path is None


def test_request_validation_refusals():
    cfg = ServeConfig()
    for doc, kind, status in [
            ({}, "invalid-request", 400),
            ({"matrix": "file.mtx"}, "invalid-request", 400),
            ({"matrix": MATRIX, "dtype": "f16"}, "invalid-request",
             400),
            ({"matrix": MATRIX, "algorithm": "sstep:zz"},
             "invalid-request", 400),
            ({"matrix": MATRIX, "maxits": 0}, "invalid-request", 400),
            ({"matrix": MATRIX, "timeout": -1}, "invalid-request",
             400),
            ({"matrix": MATRIX, "rtol": "soon"}, "invalid-request",
             400),
            ({"matrix": MATRIX, "b": ["x", "y"]}, "invalid-request",
             400),
            ({"matrix": MATRIX, "fault": "crash"}, "faults-disabled",
             403)]:
        with pytest.raises(RequestRefused) as ei:
            _Request(doc, cfg)
        assert ei.value.kind == kind
        assert ei.value.status == status
    # faults pass once the daemon was armed for them
    armed = ServeConfig(allow_faults=True)
    assert _Request({"matrix": MATRIX, "fault": "crash"},
                    armed).fault == "crash"


def test_coalesce_key_compatibility():
    cfg = ServeConfig(allow_faults=True)
    a = _Request(_doc(b_seed=1), cfg)
    b = _Request(_doc(b_seed=2), cfg)
    assert a.coalesce_key(cfg) is not None
    assert a.coalesce_key(cfg) == b.coalesce_key(cfg)
    # every incompatibility opts out of the bitwise-equal merge
    for doc in [_doc(coalesce=False), _doc(fault="slow:0.1"),
                _doc(precond="jacobi"),
                _doc(algorithm="pipelined:2")]:
        assert _Request(doc, cfg).coalesce_key(cfg) is None
    assert _Request(_doc(rtol=1e-6),
                    cfg).coalesce_key(cfg) != a.coalesce_key(cfg)
    assert _Request(_doc(algorithm="classic"),
                    cfg).coalesce_key(cfg) == a.coalesce_key(cfg)


# -- caches: steady state is zero ingest, zero compile --------------------

def test_repeat_request_zero_ingest_zero_compile():
    with _daemon() as d:
        c0 = _counter("acg_compiles_total")
        s1, b1 = d.submit(_doc(b_seed=7))
        assert s1 == 200 and b1["ok"] and b1["converged"]
        assert b1["cache"] == {"operator": "miss", "program": "miss"}
        c1 = _counter("acg_compiles_total")
        assert c1 > c0  # the miss absorbed AND counted its compile
        hits0 = _counter("acg_serve_cache_hits_total")
        s2, b2 = d.submit(_doc(b_seed=8))
        assert s2 == 200 and b2["ok"]
        assert b2["cache"] == {"operator": "hit", "program": "hit"}
        # THE acceptance assertion: a repeated request pays zero
        # ingest and zero compile
        assert _counter("acg_compiles_total") == c1
        assert _counter("acg_serve_cache_hits_total") >= hits0 + 2
        b = np.random.default_rng(8).standard_normal(N)
        assert _true_rel(b2["x"], b) <= 1e-8
        assert d.requests_served == 2
        doc = d.status_doc()
        assert doc["schema"] == SCHEMA and doc["serving"]
        assert doc["operator_cache"]["entries"] == 1
        assert doc["program_cache"]["entries"] == 1


def test_program_cache_keyed_by_shape():
    with _daemon() as d:
        d.submit(_doc(b_seed=1))
        # a different recurrence is a different program: operator hit,
        # program miss
        s, body = d.submit(_doc(b_seed=1, algorithm="pipelined:2",
                                coalesce=False))
        assert s == 200
        assert body["cache"] == {"operator": "hit", "program": "miss"}
        assert len(d.programs) == 2


# -- coalescing: bitwise equal to single service --------------------------

def test_coalesced_batch_bitwise_equals_single():
    seeds = [11, 22, 33]
    with _daemon(allow_faults=True, coalesce=4) as d:
        # pin the singles first (fresh program cache, nrhs=1)
        singles = {}
        for s in seeds:
            st, body = d.submit(_doc(b_seed=s, coalesce=False))
            assert st == 200 and body["coalesced"] == 1
            singles[s] = (body["x"], body["iterations"])
        # block the worker with a slow fault request (itself
        # uncoalescible), queue the three compatible followers behind
        # it, and let the drain merge them into ONE batched solve
        results = {}
        threads = [threading.Thread(
            target=lambda: d.submit(_doc(fault="slow:0.6",
                                         b_seed=99)))]
        threads[0].start()
        deadline = time.monotonic() + 5.0
        while len(d.queue) > 0 and time.monotonic() < deadline:
            time.sleep(0.01)

        def _go(seed):
            results[seed] = d.submit(_doc(b_seed=seed))

        for s in seeds:
            t = threading.Thread(target=_go, args=(s,))
            threads.append(t)
            t.start()
        coal0 = _counter("acg_serve_coalesced_total")
        for t in threads:
            t.join(timeout=120.0)
        for s in seeds:
            st, body = results[s]
            assert st == 200 and body["ok"]
            assert body["coalesced"] == len(seeds)
            # the bitwise pin: same bits, same per-RHS iteration count
            assert body["x"] == singles[s][0]
            assert body["iterations"] == singles[s][1]
        assert _counter("acg_serve_coalesced_total") == \
            coal0 + len(seeds)


# -- admission control: queue, deadline, SLO ladder -----------------------

def test_queue_full_sheds_typed_429():
    with _daemon(allow_faults=True, queue_depth=1) as d:
        d.submit(_doc(b_seed=1))  # warm the caches
        shed0 = _counter("acg_serve_shed_total")
        t = threading.Thread(
            target=lambda: d.submit(_doc(fault="slow:0.8", b_seed=2)))
        t.start()
        deadline = time.monotonic() + 5.0
        while len(d.queue) > 0 and time.monotonic() < deadline:
            time.sleep(0.01)  # the worker holds the slow lead
        filler = threading.Thread(
            target=lambda: d.submit(_doc(b_seed=3)))
        filler.start()
        deadline = time.monotonic() + 5.0
        while len(d.queue) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        status, body = d.submit(_doc(b_seed=4))
        assert status == 429
        assert body["error"]["type"] == "shed-queue-full"
        assert body["error"]["retryable"]
        t.join(timeout=60.0)
        filler.join(timeout=60.0)
        assert _counter("acg_serve_shed_total") > shed0


def test_expired_request_answers_typed_504():
    with _daemon(allow_faults=True, queue_depth=4) as d:
        d.submit(_doc(b_seed=1))  # warm the caches
        t = threading.Thread(
            target=lambda: d.submit(_doc(fault="slow:0.8", b_seed=2)))
        t.start()
        deadline = time.monotonic() + 5.0
        while len(d.queue) > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        # queued behind 0.8s of service with a 0.2s budget: the worker
        # answers it typed the moment it pops -- never a hang
        status, body = d.submit(_doc(b_seed=3, timeout=0.2))
        assert status == 504
        assert body["error"]["type"] == "deadline-expired"
        assert body["error"]["retryable"]
        t.join(timeout=60.0)


def test_slo_burn_degrades_then_sheds():
    with _daemon(degrade_burn=0.4, shed_burn=0.75) as d:
        d.submit(_doc(b_seed=1))  # warm (and observe nothing: no SLO)
        observatory.install_slo(observatory.parse_slo("iters=1"))
        observatory.slo_observe(iterations=100)  # breach
        observatory.slo_observe(iterations=1)    # ok -> burn 0.5
        deg0 = _counter("acg_serve_degraded_total")
        status, body = d.submit(_doc(b_seed=2,
                                     algorithm="pipelined:2",
                                     coalesce=False))
        assert status == 200 and body["ok"]
        assert body["degraded"] is True
        assert _counter("acg_serve_degraded_total") == deg0 + 1
        b = np.random.default_rng(2).standard_normal(N)
        assert _true_rel(body["x"], b) <= 1e-8  # degraded, not wrong
        # burn past the shed rung -> typed refusal, not service
        observatory.slo_observe(iterations=100)
        observatory.slo_observe(iterations=100)
        status, body = d.submit(_doc(b_seed=3))
        assert status == 503
        assert body["error"]["type"] == "shed-slo-burn"
        assert body["error"]["retryable"]


def test_stopped_daemon_sheds_typed():
    d = ServeDaemon(ServeConfig(port=0))
    d.start()
    d.stop()
    status, body = d.submit(_doc(b_seed=1))
    assert status == 503
    assert body["error"]["type"] == "shed-shutdown"
    observatory._clear_slo()


# -- request isolation ----------------------------------------------------

def test_fault_request_is_isolated_and_retried():
    with _daemon(allow_faults=True, retries=1,
                 retry_backoff=0.01) as d:
        inval0 = _counter("acg_serve_cache_invalidations_total")
        # dot:nan trips the solve; the retry (fault dropped: it
        # modelled a transient) must answer green from a fresh program
        status, body = d.submit(_doc(b_seed=5, fault="dot:nan@2",
                                     coalesce=False))
        assert status == 200 and body["ok"]
        b = np.random.default_rng(5).standard_normal(N)
        assert _true_rel(body["x"], b) <= 1e-8
        # the daemon survived and still serves
        status, body = d.submit(_doc(b_seed=6))
        assert status == 200 and body["ok"]
        assert _counter("acg_serve_cache_invalidations_total") \
            >= inval0


def test_unconverged_request_answers_typed_500():
    with _daemon() as d:
        status, body = d.submit(_doc(b_seed=1, maxits=2))
        assert status == 500 and not body["ok"]
        assert body["error"]["type"] == "NotConvergedError"
        assert body["error"]["retryable"]
        # isolation: the daemon still answers the next request
        status, body = d.submit(_doc(b_seed=1))
        assert status == 200 and body["ok"]


# -- self-healing: state sidecar + warm restore ---------------------------

def test_state_sidecar_and_warm_restore(tmp_path):
    state = str(tmp_path / "serve.json")
    with _daemon(state_path=state) as d:
        st, _ = d.submit(_doc(b_seed=1))
        assert st == 200
    with open(state) as f:
        doc = json.load(f)
    assert doc["schema"] == STATE_SCHEMA
    assert doc["requests_served"] == 1
    assert doc["operators"] == [[MATRIX, "f64", 0]]
    warm0 = _counter("acg_serve_warm_restores_total")
    with _daemon(state_path=state) as d2:
        assert d2.warm_restored == 1
        assert _counter("acg_serve_warm_restores_total") == warm0 + 1
        # the first request of the new incarnation already hits the
        # re-ingested operator (only the program must rebuild)
        st, body = d2.submit(_doc(b_seed=2))
        assert st == 200
        assert body["cache"]["operator"] == "hit"
        assert body["cache"]["program"] == "miss"


def test_unreadable_state_is_cold_start(tmp_path):
    state = str(tmp_path / "serve.json")
    with open(state, "w") as f:
        f.write("{not json")
    with _daemon(state_path=state) as d:
        assert d.warm_restored == 0
        st, body = d.submit(_doc(b_seed=1))
        assert st == 200 and body["cache"]["operator"] == "miss"


# -- the HTTP surface -----------------------------------------------------

def test_http_endpoints_end_to_end():
    import urllib.error
    import urllib.request

    with _daemon() as d:
        base = f"http://127.0.0.1:{d.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=30.0) \
                    as resp:
                return resp.status, resp.read().decode()

        status, body = get("/healthz")
        assert status == 200 and json.loads(body)["ok"]
        status, body = get("/status")
        assert status == 200
        assert json.loads(body)["schema"] == SCHEMA
        status, body = get("/metrics")
        assert status == 200 and "acg_serve_requests_total" in body
        status, body = get("/requests")
        assert status == 200
        reqdoc = json.loads(body)
        assert reqdoc["schema"] == "acg-serve-requests/1"
        assert reqdoc["inflight"] == [] and reqdoc["completed"] == []
        req = urllib.request.Request(
            base + "/solve",
            data=json.dumps(_doc(b_seed=9,
                                 return_x=False)).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120.0) as resp:
            out = json.loads(resp.read().decode())
        assert out["ok"] and out["converged"] and "x" not in out
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/solve", data=b"{not json"), timeout=30.0)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/nope")
        assert ei.value.code == 404


# -- grow-on-recovery (the supervisor's other ratchet half) ---------------

def test_supervisor_regrow_relaunch_argv_surgery(tmp_path):
    from acg_tpu.observatory import DEGRADED_ENV
    metrics.arm()
    sup = sup_mod.DaemonSupervisor(
        [MATRIX, "--serve", "--nparts", "8"],
        state_path=str(tmp_path / "s.json"), nparts=8, grow_after=3,
        backoff=0.0)
    launches = []
    sup._launch = lambda: launches.append(list(sup.argv))
    # a crash-class death shrinks and marks the fleet degraded
    sup._relaunch(parts=4, reason="crash-injected", grow=False)
    assert sup.cur_parts == 4
    assert sup.report["degraded"] == {"from": 8, "to": 4,
                                      "reason": "crash-injected"}
    assert sup.env[DEGRADED_ENV] == "8:4:crash-injected"
    assert "--nparts" in launches[0] \
        and launches[0][launches[0].index("--nparts") + 1] == "4"
    # healthy for grow_after requests -> deliberate regrow relaunch
    re0 = _counter("acg_recovery_regrows_total")
    sup._relaunch(parts=8, reason="regrow", grow=True)
    assert sup.cur_parts == 8
    assert sup.report["regrows"] == 1
    assert sup.report["degraded"] is None  # back at full width
    assert DEGRADED_ENV not in sup.env
    assert "--resume-repartition" in launches[1]
    assert launches[1][launches[1].index("--nparts") + 1] == "8"
    assert _counter("acg_recovery_regrows_total") == re0 + 1
    assert len(sup.report["relaunches"]) == 1  # regrow is not a death


def test_serve_chaos_schedule_deterministic_and_crashful():
    a = [serve_chaos_schedule(i, 1234, 0) for i in range(8)]
    b = [serve_chaos_schedule(i, 1234, 0) for i in range(8)]
    assert a == b  # seeded: the campaign is replayable
    assert a[1] == {"fault": "crash"}  # schedule 1 is ALWAYS a crash
    for sched in a:
        f = sched.get("fault")
        assert f is None or f == "crash" or f.startswith("slow:") \
            or f.startswith(("spmv:", "dot:"))
    # halo faults only enter the menu when there IS a mesh
    singles = [serve_chaos_schedule(i, 99, 0).get("fault")
               for i in range(40)]
    assert all(f is None or not f.startswith("halo:")
               for f in singles)


# -- the live campaign (subprocess; the t1.yml smoke twin) ----------------

@pytest.mark.slow
def test_crash_relaunch_warm_cache_live(tmp_path):
    """Kill the daemon mid-request via the chaos campaign (schedule 1
    is a forced crash): the supervisor must relaunch it, the relaunch
    must warm-restore, and every response must verify."""
    env = dict(os.environ, **ENV)
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", MATRIX, "--comm",
         "none", "--serve", "--serve-faults", "--chaos", "77:2",
         "--ckpt", str(tmp_path / "ck"), "--relaunch-backoff", "0",
         "--max-iterations", "400", "--residual-rtol", "1e-8",
         "--quiet", "--history", str(tmp_path / "history")],
        env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stderr[-4000:]
    assert "warm-restored" in r.stderr
    rows = [e["doc"] for e in
            observatory.history_scan(tmp_path / "history")
            if e["doc"].get("schema") == "acg-tpu-chaos-serve/1"]
    assert len(rows) == 2
    verdicts = {row["chaos"]["verdict"] for row in rows}
    assert "crash-relaunched" in verdicts
    assert "WRONG-ANSWER" not in verdicts
    assert "HANG" not in verdicts


# -- decision observatory (--serve --autotune) ----------------------------

def _serve_cal(**over):
    from acg_tpu import commbench as cb
    doc = {"schema": cb.COMMBENCH_SCHEMA, "backend": "cpu", "nparts": 8,
           "collectives": {
               "all_reduce": {"alpha_s": 1e-5, "beta_s_per_byte": 1e-10,
                              "npoints": 3, "r2": None},
               "all_to_all": {"alpha_s": 2e-5,
                              "beta_s_per_byte": 1e-9,
                              "npoints": 3, "r2": None}}}
    doc.update(over)
    doc["calibration_id"] = cb.calibration_id(doc)
    return doc


def test_serve_autotune_plans_and_stamps_provenance():
    """--serve --autotune: the daemon plans on operator-cache miss,
    stamps every response with plan id + decision provenance, surfaces
    the cached decisions under /status plans:, and replans when the
    calibration id changes (the serve satellite of ISSUE 17)."""
    cal = _serve_cal()
    with _daemon(autotune=True, calibration=cal) as d:
        s1, b1 = d.submit(_doc(b_seed=1))
        assert s1 == 200 and b1["ok"]
        assert b1["plan"]["source"] == "planned", b1["plan"]
        assert str(b1["plan"]["id"]).startswith("plan-"), b1["plan"]
        doc = d.status_doc()
        plans = doc["plans"]
        assert plans["autotune"] is True
        assert plans["calibration"] == cal["calibration_id"]
        assert plans["decisions"] and \
            plans["decisions"][0]["plan_id"] == b1["plan"]["id"]
        assert plans["last_misprediction_ratio"] > 0
        # an explicit per-request algorithm overrides the plan: the
        # provenance says so instead of silently re-labelling
        s2, b2 = d.submit(_doc(b_seed=2, algorithm="classic"))
        assert s2 == 200 and b2["ok"]
        assert b2["plan"]["source"] == "flag-forced", b2["plan"]
        # calibration swap -> the next planned request replans
        cal2 = _serve_cal(nparts=8, backend="cpu",
                          note="recalibrated")
        assert cal2["calibration_id"] != cal["calibration_id"]
        d.set_calibration(cal2)
        s3, b3 = d.submit(_doc(b_seed=3))
        assert s3 == 200 and b3["ok"]
        assert b3["plan"]["source"] == "planned", b3["plan"]
        doc2 = d.status_doc()
        assert doc2["plans"]["calibration"] == cal2["calibration_id"]
        assert all(dec["calibration"] == cal2["calibration_id"]
                   for dec in doc2["plans"]["decisions"])


# -- the request observatory (ISSUE 18) -----------------------------------

TRACEPARENT = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"


def test_request_identity_echo_and_access_ledger(tmp_path):
    """Every request resolves to a request_id (client id > traceparent
    trace-id > generated), echoed on the 200 AND the 400 path, and
    --access-log lands exactly one acg-tpu-access/1 row per request
    that scripts/check_access_log.py accepts."""
    ledger = str(tmp_path / "access.jsonl")
    with _daemon(access_log=ledger) as d:
        s1, b1 = d.submit(_doc(b_seed=1, request_id="client-1"))
        assert s1 == 200 and b1["request_id"] == "client-1"
        s2, b2 = d.submit(_doc(b_seed=2, traceparent=TRACEPARENT))
        assert s2 == 200
        assert b2["request_id"] == "4bf92f3577b34da6a3ce929d0e0e4736"
        s3, b3 = d.submit(_doc(b_seed=3))
        assert s3 == 200 and b3["request_id"].startswith("req-")
        # the refusal path carries the identity too
        s4, b4 = d.submit(_doc(maxits=0, request_id="bad-1"))
        assert s4 == 400 and b4["request_id"] == "bad-1"
        assert b4["error"]["type"] == "invalid-request"
        # the response contract is the PR 17 body plus ONE additive
        # field -- the id; nothing else moved
        assert set(b1) == {"ok", "schema", "id", "request_id",
                           "converged", "iterations",
                           "latency_seconds", "cache", "coalesced",
                           "degraded", "plan", "x"}
        # per-stage seconds reached the histogram surface
        expo = metrics.expose()
        assert 'acg_serve_stage_seconds_bucket{stage="solve"' in expo
        assert "acg_serve_inflight" in expo
        doc = d.status_doc()
        assert doc["requests"]["completed"] == 4
        assert doc["requests"]["outcomes"] == {"ok": 3,
                                               "invalid-request": 1}
        assert doc["requests"]["access_log"] == ledger
    with open(ledger) as f:
        rows = [json.loads(line) for line in f]
    assert [r["request_id"] for r in rows] == \
        ["client-1", "4bf92f3577b34da6a3ce929d0e0e4736",
         b3["request_id"], "bad-1"]
    for r in rows[:3]:
        assert r["outcome"] == "ok"
        assert sum(r["stages"].values()) <= r["wall_seconds"] + 5e-3
        for stage in ("admit", "queue-wait", "cache", "solve",
                      "demux", "respond"):
            assert stage in r["stages"], (r["request_id"], stage)
    assert rows[3]["outcome"] == "invalid-request"
    res = subprocess.run(
        [sys.executable, "scripts/check_access_log.py", ledger,
         "--min-rows", "4", "--require-outcome", "ok",
         "--require-outcome", "invalid-request"],
        capture_output=True, text=True, cwd=ENV["PYTHONPATH"])
    assert res.returncode == 0, res.stderr


def test_concurrent_coalesced_requests_trace_one_solve(tmp_path):
    """N parallel POST /solve coalescing into one batch: /requests
    never tears under fire, each member lands its own ledger row, the
    rows share ONE batch block whose per-RHS attribution sums back to
    the batch solve time, and the armed timeline carries a single
    worker solve-batch span linked to ALL member request ids."""
    from acg_tpu import tracing

    ledger = str(tmp_path / "access.jsonl")
    seeds = [11, 22, 33]
    ids = {s: f"member-{s}" for s in seeds}
    try:
        tracing.arm()
        with _daemon(allow_faults=True, coalesce=4,
                     access_log=ledger) as d:
            d.submit(_doc(b_seed=1))  # warm the caches
            # block the worker with an uncoalescible slow lead, queue
            # the members behind it so the drain merges them
            threads = [threading.Thread(
                target=lambda: d.submit(_doc(fault="slow:0.6",
                                             b_seed=99,
                                             request_id="slow-lead")))]
            threads[0].start()
            deadline = time.monotonic() + 5.0
            while len(d.queue) > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            results = {}

            def _go(seed):
                results[seed] = d.submit(
                    _doc(b_seed=seed, request_id=ids[seed]))

            for s in seeds:
                t = threading.Thread(target=_go, args=(s,))
                threads.append(t)
                t.start()
            # the non-torn read under fire: in-flight + completed
            # documents are never half-written
            for _ in range(20):
                snap = d.reqlog.snapshot()
                assert snap["schema"] == "acg-serve-requests/1"
                for doc in snap["inflight"] + snap["completed"]:
                    assert doc["request_id"]
                    assert isinstance(doc["stages"], dict)
                time.sleep(0.01)
            for t in threads:
                t.join(timeout=120.0)
            for s in seeds:
                st, body = results[s]
                assert st == 200 and body["ok"]
                assert body["coalesced"] == len(seeds)
                assert body["request_id"] == ids[s]
        spans = tracing.local_payload()["spans"]
    finally:
        tracing.disarm()

    with open(ledger) as f:
        rows = {r["request_id"]: r
                for r in map(json.loads, f)
                if r["request_id"] in ids.values()}
    assert set(rows) == set(ids.values())
    batches = {r["batch"]["id"] for r in rows.values()}
    assert len(batches) == 1  # ONE solve, three attributions
    blk = next(iter(rows.values()))["batch"]
    assert blk["width"] == len(seeds)
    assert sorted(blk["members"]) == sorted(ids.values())
    assert abs(blk["rhs_solve_seconds"] * blk["width"]
               - blk["solve_seconds"]) <= 1e-3
    for r in rows.values():
        assert r["batch"] == blk  # every member links the same block
        assert r["stages"]["queue-wait"] > 0  # they waited on the lead
        assert abs(r["stages"]["solve"]
                   - blk["rhs_solve_seconds"]) <= 1e-3
    # the worker track: one solve-batch span naming every member
    worker = [s for s in spans if s["cat"] == "worker"
              and s["name"].startswith("solve-batch")
              and set((s.get("args") or {}).get("requests", []))
              >= set(ids.values())]
    assert len(worker) == 1
    # and each member's lane carries its own request-scoped spans
    for rid in ids.values():
        mine = [s for s in spans if s["cat"] == "request"
                and (s.get("args") or {}).get("request") == rid]
        assert {s["name"] for s in mine} >= {"queue-wait", "solve",
                                             "demux"}


def test_serve_without_autotune_has_no_plan_section():
    """Disarmed (no --autotune) the daemon neither plans nor stamps:
    responses carry no plan id and the decision is flag-forced --
    byte-compatible with the PR 16 response contract plus the one
    additive plan field."""
    with _daemon() as d:
        s, b = d.submit(_doc(b_seed=4))
        assert s == 200 and b["ok"]
        assert b["plan"]["id"] is None
        assert b["plan"]["source"] == "flag-forced"
        assert d.status_doc()["plans"]["autotune"] is False
        assert d.status_doc()["plans"]["decisions"] == []
