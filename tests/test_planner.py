"""Decision observatory (acg_tpu.planner): ranked-plan determinism,
pricing within band of measured-best on the 8-part mesh, the typed
refusal matrix, plan-vs-actual ledger round-trip through
history_report, and old-document tolerance."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu import commbench as cb
from acg_tpu import planner
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix

_ENV = {"JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")


def _run_cli(argv, timeout=600):
    env = dict(os.environ)
    env.update(_ENV)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli"] + argv,
                          capture_output=True, text=True, env=env,
                          timeout=timeout)


def _run_script(name, argv, **kw):
    kw.setdefault("timeout", 300)
    return subprocess.run([sys.executable,
                           os.path.join(SCRIPTS, name), *argv],
                          capture_output=True, text=True, **kw)


def _csr(side=24):
    r, c, v, n = poisson2d_coo(side)
    return SymCsrMatrix.from_coo(n, r, c, v).to_csr()


def _cal(**over):
    """A synthetic but well-formed calibration document (the
    test_commbench _minimal_doc shape)."""
    doc = {"schema": cb.COMMBENCH_SCHEMA, "backend": "cpu", "nparts": 8,
           "collectives": {
               "all_reduce": {"alpha_s": 1e-5, "beta_s_per_byte": 1e-10,
                              "npoints": 1, "r2": None,
                              "points": [{"bytes": 8,
                                          "seconds": 1e-5}]},
               "all_to_all": {"alpha_s": 2e-5,
                              "beta_s_per_byte": 1e-9,
                              "npoints": 1, "r2": None,
                              "points": [{"bytes": 1024,
                                          "seconds": 2.1e-5}]}}}
    doc.update(over)
    doc["calibration_id"] = cb.calibration_id(doc)
    return doc


def _plan_kwargs(**over):
    kw = dict(matrix_id="gen:poisson2d:24", nparts=8,
              dtype_name="float64", rtol=1e-6, maxits=400,
              mat_itemsize=8, vec_itemsize=8, kappa=950.0,
              kappa_source="lanczos-oracle", bw_gbs=40.0,
              dispatch_s=5e-5)
    kw.update(over)
    return kw


# -- determinism ---------------------------------------------------------

def test_plan_determinism_and_id_integrity():
    """Same inputs + same calibration => byte-identical ranked document
    (the planner's determinism contract: no timestamps, stable
    tie-breaks), and the content-hash plan id detects tampering."""
    csr = _csr()
    cal = _cal()
    a = planner.build_plan(csr, cal=cal, **_plan_kwargs())
    b = planner.build_plan(csr, cal=cal, **_plan_kwargs())
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert planner.validate_plan(a) == []
    assert a["calibration"] == cal["calibration_id"]
    assert a["plan_id"].startswith("plan-cpu-8p-")
    # ranked strictly sorted by predicted seconds
    preds = [r["predicted_s_per_solve"] for r in a["ranked"]]
    assert preds == sorted(preds) and all(p > 0 for p in preds)
    # tamper: the id no longer matches the content
    tampered = json.loads(json.dumps(a))
    tampered["ranked"][0]["predicted_s_per_solve"] *= 0.5
    assert any("plan_id" in p for p in planner.validate_plan(tampered))


def test_plan_render_and_write(tmp_path):
    csr = _csr()
    doc = planner.build_plan(csr, cal=_cal(), **_plan_kwargs())
    txt = planner.render_plan(doc)
    assert doc["plan_id"] in txt
    assert doc["calibration"] in txt
    assert "UNCALIBRATED" not in txt
    dest = tmp_path / "plan.json"
    planner.write_plan(doc, dest)
    assert json.loads(dest.read_text())["plan_id"] == doc["plan_id"]


# -- refusal matrix ------------------------------------------------------

def test_refusal_matrix_uncalibrated_and_pruned_reasons():
    """No calibration => the ranking is clearly marked uncalibrated;
    incompatible cells are pruned with TYPED reasons mirroring the CLI
    refusal matrices, never silently ranked."""
    csr = _csr()
    doc = planner.build_plan(csr, cal=None, **_plan_kwargs())
    assert doc["uncalibrated"] is True
    assert doc["calibration"] == cb.UNCALIBRATED
    assert "UNCALIBRATED" in planner.render_plan(doc)
    reasons = {p["reason"] for p in doc["pruned"]}
    # CA x fused refused; dma unpriceable without a dma fit
    assert "ca-fused" in reasons
    assert "dma-unbenchmarked" in reasons
    known = {"ca-precond", "ca-fused", "fused-precond",
             "dma-single-part", "dma-unbenchmarked",
             "assembled-bypassed"}
    assert reasons <= known, reasons
    # no pruned combination ever appears in the ranking
    pruned_labels = {planner.candidate_label(p) for p in doc["pruned"]}
    assert not pruned_labels & {r["label"] for r in doc["ranked"]}


def test_refusal_matrix_precond_and_operator_cells():
    csr = _csr()
    doc = planner.build_plan(csr, cal=_cal(), precond="cheby:4",
                             **_plan_kwargs())
    reasons = {p["reason"] for p in doc["pruned"]}
    assert "ca-precond" in reasons
    assert "fused-precond" in reasons
    # preconditioned cells survive on the non-CA recurrences
    assert any(r["precond"].startswith("cheby")
               for r in doc["ranked"])
    # --operator armed: assembled cells are pruned, ranked cells are
    # all matrix-free
    doc2 = planner.build_plan(csr, cal=_cal(), operator_armed=True,
                              **_plan_kwargs())
    assert "assembled-bypassed" in {p["reason"] for p in doc2["pruned"]}
    assert all(r["matrix_free"] for r in doc2["ranked"])
    # single-part mesh: dma is structurally unavailable
    doc3 = planner.build_plan(csr, cal=_cal(), **_plan_kwargs(nparts=1))
    assert "dma-single-part" in {p["reason"] for p in doc3["pruned"]}


def test_iteration_model_tracks_recurrence():
    """The predicted-iterations adjustment follows the recurrence: an
    s-step cell predicts more iterations than classic on the same
    kappa (basis-conditioning penalty), and a cheby preconditioner
    compresses kappa so its cell predicts fewer."""
    csr = _csr()
    doc = planner.build_plan(csr, cal=_cal(), precond="cheby:4",
                             **_plan_kwargs())
    by_label = {r["label"]: r for r in doc["ranked"]}
    classic = by_label["classic/auto/xla/none/assembled"]
    sstep = by_label["sstep:4/auto/xla/none/assembled"]
    cheby = by_label["classic/auto/xla/cheby:4/assembled"]
    assert sstep["predicted_iterations"] > classic["predicted_iterations"]
    assert cheby["predicted_iterations"] < classic["predicted_iterations"]


# -- pricing within band (the acceptance) --------------------------------

def test_top_plan_within_band_of_measured_best():
    """On the 8-part CPU mesh with a LIVE collective calibration, the
    planner's preferred cell among {classic, sstep:4, pipelined} must
    be within 2x of the measured-best of those three (the ISSUE's
    pricing-within-band acceptance)."""
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.parallel.mesh import solve_mesh
    from acg_tpu.partition import partition_rows
    from acg_tpu.recurrence import parse_algorithm
    from acg_tpu.solvers.stats import StoppingCriteria

    side, nparts, rtol, maxits = 48, 8, 1e-6, 400
    csr = _csr(side)
    # live alpha-beta calibration over the in-process mesh
    colls = cb.bench_collectives(solve_mesh(nparts), cb.CPU_SWEEP,
                                 reps=4, repeats=2)
    cal = {"schema": cb.COMMBENCH_SCHEMA, "backend": "cpu",
           "nparts": nparts, "collectives": colls}
    cal["calibration_id"] = cb.calibration_id(cal)

    part = partition_rows(csr, nparts, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, nparts,
                                    dtype=jnp.float64)
    b = np.ones(prob.n)
    crit = StoppingCriteria(maxits=maxits, residual_rtol=rtol)
    measured = {}
    for name in ("classic", "sstep:4", "pipelined"):
        if name == "classic":
            s = DistCGSolver(prob)
        elif name == "pipelined":
            s = DistCGSolver(prob, pipelined=True)
        else:
            s = DistCGSolver(prob, algorithm=parse_algorithm(name))
        s.solve(b, criteria=crit, raise_on_divergence=False, warmup=1)
        best = min(_timed_solve(s, b, crit) for _ in range(3))
        measured[name] = best
    kappa, src = planner.kappa_estimate(csr, rtol, maxits)
    doc = planner.build_plan(
        csr, matrix_id=f"gen:poisson2d:{side}", nparts=nparts,
        dtype_name="float64", rtol=rtol, maxits=maxits,
        mat_itemsize=8, vec_itemsize=8, cal=cal, kappa=kappa,
        kappa_source=src, kernels=("auto",), comms=("xla",))
    wanted = {f"{name}/auto/xla/none/assembled": name
              for name in measured}
    ranked3 = [wanted[r["label"]] for r in doc["ranked"]
               if r["label"] in wanted]
    top = ranked3[0]
    floor = min(measured.values())
    assert measured[top] <= 2.0 * floor, (measured, top)


def _timed_solve(s, b, crit):
    t0 = time.perf_counter()
    s.solve(b, criteria=crit, raise_on_divergence=False, warmup=0)
    return time.perf_counter() - t0


# -- plan-vs-actual ledger round-trip ------------------------------------

@pytest.fixture(scope="module")
def planned_run(tmp_path_factory):
    """One subprocess --commbench + one --autotune solve with a
    --history ledger, shared by the round-trip tests."""
    root = tmp_path_factory.mktemp("plan")
    cal = root / "cal.json"
    r = _run_cli(["gen:poisson2d:16", "--commbench", str(cal),
                  "--nparts", "8", "--dtype", "f32",
                  "--max-iterations", "20", "--warmup", "0", "-q"])
    assert r.returncode == 0, r.stderr
    hist = root / "hist"
    plan = root / "plan.json"
    sj = root / "stats.json"
    r = _run_cli(["gen:poisson2d:32", "--autotune", "--calibration",
                  str(cal), "--history", str(hist), "--plan", str(plan),
                  "--stats-json", str(sj), "--nparts", "8",
                  "--residual-rtol", "1e-6", "--max-iterations", "300",
                  "--warmup", "0", "-q"])
    assert r.returncode == 0, r.stderr
    assert "autotune: dispatching" in r.stderr
    return {"cal": cal, "hist": hist, "plan": plan, "stats": sj}


def test_autotune_records_plan_vs_actual(planned_run):
    doc = json.loads(planned_run["plan"].read_text())
    assert planner.validate_plan(doc) == []
    cal_id = json.loads(planned_run["cal"].read_text())["calibration_id"]
    assert doc["calibration"] == cal_id
    sj = json.loads(planned_run["stats"].read_text())
    plan = sj["stats"]["plan"]
    assert plan["plan_id"] == doc["plan_id"]
    assert plan["source"] == "planned"
    assert plan["calibration"] == cal_id
    assert plan["measured_s_per_solve"] > 0
    assert plan["misprediction_ratio"] > 0
    # the ledger carries the same row
    from acg_tpu.observatory import history_scan
    entries = history_scan(planned_run["hist"])
    rows = [e["doc"]["stats"]["plan"] for e in entries
            if (e.get("doc") or {}).get("stats", {}).get("plan")]
    assert rows and rows[-1]["plan_id"] == doc["plan_id"]


def test_history_report_plan_column_and_gate(planned_run):
    r = _run_script("history_report.py", [str(planned_run["hist"])])
    assert r.returncode == 0, r.stderr
    assert "plan x" in r.stdout
    # a tolerance no real model meets trips the drift gate (exit 7)
    r = _run_script("history_report.py",
                    [str(planned_run["hist"]),
                     "--fail-on-misprediction", "1e-9"])
    assert r.returncode == 7
    assert "MISPREDICTION" in r.stdout
    # an infinitely loose gate passes
    r = _run_script("history_report.py",
                    [str(planned_run["hist"]),
                     "--fail-on-misprediction", "1e9"])
    assert r.returncode == 0


def test_second_planned_run_self_corrects(planned_run):
    """The self-correction acceptance: a second planned solve for the
    same (matrix, mesh, calibration) key consults the first run's
    plan-vs-actual row and rescales -- the emitted document records a
    non-unit correction with nsamples >= 1."""
    plan2 = planned_run["hist"].parent / "plan2.json"
    r = _run_cli(["gen:poisson2d:32", "--autotune", "--calibration",
                  str(planned_run["cal"]), "--history",
                  str(planned_run["hist"]), "--plan", str(plan2),
                  "--nparts", "8", "--residual-rtol", "1e-6",
                  "--max-iterations", "300", "--warmup", "0", "-q"])
    assert r.returncode == 0, r.stderr
    doc = json.loads(plan2.read_text())
    assert doc["correction"]["nsamples"] >= 1
    assert doc["correction"]["scale"] != 1.0
    assert "correction" in planner.render_plan(doc)


def test_explain_plan_prints_table_without_solving(tmp_path):
    out = tmp_path / "plan.json"
    r = _run_cli(["gen:poisson2d:16", "--explain", "--plan", str(out),
                  "--nparts", "8", "--max-iterations", "50", "-q"])
    assert r.returncode == 0, r.stderr
    assert "ranked" in r.stderr or "plan" in r.stderr
    doc = json.loads(out.read_text())
    assert planner.validate_plan(doc) == []
    assert doc["uncalibrated"] is True
    # --explain --plan never dispatches a solve
    assert "converged" not in r.stdout


def test_autotune_refusal_matrix():
    r = _run_cli(["gen:poisson2d:16", "--autotune", "--explain"])
    assert r.returncode != 0
    assert "--explain --plan" in r.stderr
    r = _run_cli(["gen:poisson2d:16", "--autotune", "--kernels",
                  "fused"])
    assert r.returncode != 0
    r = _run_cli(["gen:poisson2d:16", "--plan", "p.json"])
    assert r.returncode != 0


def test_explain_calibration_mismatch_warns_structured(tmp_path):
    """--explain --calibration with a doc recorded on a DIFFERENT mesh
    warns with a structured calibration-mismatch event (stderr line +
    stats events) instead of silently pricing with the wrong fit."""
    cal = _cal(nparts=4)  # recorded on 4 parts, priced on 8
    p = tmp_path / "cal.json"
    p.write_text(json.dumps(cal))
    sj = tmp_path / "explain.jsonl"
    r = _run_cli(["gen:poisson2d:16", "--explain", "--calibration",
                  str(p), "--nparts", "8", "--dtype", "f32",
                  "--max-iterations", "20", "--warmup", "0",
                  "--stats-json", str(sj), "-q"])
    assert r.returncode == 0, r.stderr
    assert "WARNING" in r.stderr and "4 parts" in r.stderr
    docs = [json.loads(ln) for ln in sj.read_text().splitlines()
            if ln.strip()]
    kinds = [e.get("kind") for d in docs
             for e in d["stats"].get("events", [])]
    assert "calibration-mismatch" in kinds, kinds
    # a MATCHING calibration never fires the event
    cal8 = _cal(nparts=8)
    p8 = tmp_path / "cal8.json"
    p8.write_text(json.dumps(cal8))
    r2 = _run_cli(["gen:poisson2d:16", "--explain", "--calibration",
                   str(p8), "--nparts", "8", "--dtype", "f32",
                   "--max-iterations", "20", "--warmup", "0", "-q"])
    assert r2.returncode == 0, r2.stderr
    assert "calibration-mismatch" not in r2.stderr


# -- old-document tolerance ----------------------------------------------

def test_old_ledger_docs_render_without_plan_column(tmp_path):
    """A pre-/12 ledger row (no stats.plan key) renders with a '-'
    plan column and never trips the misprediction gate (the additive
    schema-bump contract)."""
    d = tmp_path / "hist"
    d.mkdir()
    row = {"ledger": "acg-tpu-history/1", "unix_time": 1e9,
           "case": "legacy", "latency_s": 0.1, "iterations": 9,
           "doc": {"schema": "acg-tpu-stats/11",
                   "manifest": {"metric": "legacy"},
                   "stats": {"tsolve": 0.1, "niterations": 9}}}
    (d / "2001-09-09.jsonl").write_text(json.dumps(row) + "\n")
    r = _run_script("history_report.py",
                    [str(d), "--fail-on-misprediction", "1e-9"])
    assert r.returncode == 0, r.stderr + r.stdout
    assert "plan -" in r.stdout


def test_old_stats_doc_loads_additively():
    """stats.plan is strictly additive: a /11 document without it
    still round-trips through the observatory index path."""
    from acg_tpu import observatory
    doc = {"schema": "acg-tpu-stats/11",
           "manifest": {"metric": "m", "matrix": "m", "solver": "acg"},
           "stats": {"tsolve": 0.5, "niterations": 7,
                     "converged": True}}
    idx = observatory._index_of(doc)
    assert idx["iterations"] == 7
    # and a fresh Stats carries an EMPTY plan section that serializes
    # to {} (absent from fwrite output until a planner stamps it)
    from acg_tpu.solvers.stats import SolverStats
    st = SolverStats()
    assert st.to_dict()["plan"] == {}
