"""End-to-end CLI tests: the reference's operational verification flow
(generate -> partition -> solve -> manufactured-solution check)."""

import subprocess
import sys

import numpy as np
import pytest

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.io.mtxfile import read_mtx, write_mtx

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(module, argv, **kw):
    import os
    env = dict(os.environ)
    env.update(ENV_KEYS)
    # a hard timeout so a wedged accelerator tunnel fails ONE test
    # instead of hanging the whole suite (observed round 5)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", module, *argv],
                          capture_output=True, text=True, env=env, **kw)


@pytest.fixture(scope="module")
def matrix_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("mtx") / "poisson2d_n12.mtx"
    write_mtx(path, poisson_mtx(12, dim=2))
    return path


def test_genmatrix_tool(tmp_path):
    out = tmp_path / "p.mtx"
    r = run_cli("acg_tpu.tools.genmatrix", ["-n", "6", "--dim", "3", "-o", str(out), "-v"])
    assert r.returncode == 0, r.stderr
    m = read_mtx(out)
    assert m.nrows == 216 and m.symmetry == "symmetric"


def test_mtx2bin_roundtrip(matrix_file, tmp_path):
    out = tmp_path / "p.bin.mtx"
    r = run_cli("acg_tpu.tools.mtx2bin", [str(matrix_file), str(out), "-v"])
    assert r.returncode == 0, r.stderr
    orig = read_mtx(matrix_file)
    binm = read_mtx(out, binary=True)
    np.testing.assert_array_equal(binm.rowidx, orig.rowidx)
    np.testing.assert_allclose(binm.vals, orig.vals)


def test_mtx2bin_one_based_partition(matrix_file, tmp_path):
    """--one-based shifts a Fortran/METIS-style partition vector; a
    vector whose min part is 1 is AMBIGUOUS and must be disambiguated
    explicitly (--one-based / --zero-based) -- the round-4 silent
    renumbering became a warning, the round-5 advice a hard error."""
    from acg_tpu.io.mtxfile import vector_mtx

    n = 144
    rng = np.random.default_rng(0)
    part1 = rng.integers(1, 4, size=n)  # 1-based: parts 1..3
    pf = tmp_path / "part.mtx"
    write_mtx(pf, vector_mtx(part1.astype(np.int64), field="integer"),
              numfmt="%d")

    out = tmp_path / "ob.bin.mtx"
    r = run_cli("acg_tpu.tools.mtx2bin",
                [str(matrix_file), str(out), "--expand",
                 "--partition", str(pf), "--one-based"])
    assert r.returncode == 0, r.stderr
    bounds = np.asarray(read_mtx(str(out) + ".bounds.mtx").vals).reshape(-1)
    counts = np.bincount(part1 - 1, minlength=3)
    np.testing.assert_array_equal(bounds,
                                  np.concatenate([[0], np.cumsum(counts)]))

    # ambiguous (min part == 1) without a flag: hard error naming both
    # disambiguation flags
    out2 = tmp_path / "amb.bin.mtx"
    r2 = run_cli("acg_tpu.tools.mtx2bin",
                 [str(matrix_file), str(out2), "--expand",
                  "--partition", str(pf)])
    assert r2.returncode != 0
    assert "--one-based" in r2.stderr and "--zero-based" in r2.stderr

    # the same vector with --zero-based: accepted, numbering untouched
    # (part 0 empty -> 4 parts with a zero-width first window)
    r2b = run_cli("acg_tpu.tools.mtx2bin",
                  [str(matrix_file), str(out2), "--expand",
                   "--partition", str(pf), "--zero-based"])
    assert r2b.returncode == 0, r2b.stderr
    b2 = np.asarray(read_mtx(str(out2) + ".bounds.mtx").vals).reshape(-1)
    np.testing.assert_array_equal(
        b2, np.concatenate([[0, 0], np.cumsum(counts)]))

    # --one-based on a vector containing part 0 is an error
    part0 = part1 - 1
    pf0 = tmp_path / "part0.mtx"
    write_mtx(pf0, vector_mtx(part0.astype(np.int64), field="integer"),
              numfmt="%d")
    r3 = run_cli("acg_tpu.tools.mtx2bin",
                 [str(matrix_file), str(tmp_path / "z.bin.mtx"),
                  "--expand", "--partition", str(pf0), "--one-based"])
    assert r3.returncode != 0


def test_mtxpartition_tool(matrix_file, tmp_path):
    r = run_cli("acg_tpu.tools.mtxpartition",
                [str(matrix_file), "--parts", "4", "-v"])
    assert r.returncode == 0, r.stderr
    pfile = tmp_path / "part.mtx"
    pfile.write_text(r.stdout)
    pm = read_mtx(pfile)
    assert pm.object == "vector" and pm.field == "integer"
    part = np.asarray(pm.vals).reshape(-1)
    assert part.size == 144
    assert set(np.unique(part)) == {0, 1, 2, 3}
    assert "edge cut" in r.stderr


def test_mtxpartition_tool_variant_and_band(matrix_file, tmp_path):
    """--variant recursive and --method band both produce valid covers
    (metis.h:39-43 variants; band = TPU DIA-friendly contiguous ranges)."""
    for extra in (["--variant", "recursive"], ["--method", "band"]):
        r = run_cli("acg_tpu.tools.mtxpartition",
                    [str(matrix_file), "--parts", "3"] + extra)
        assert r.returncode == 0, r.stderr
        pfile = tmp_path / "part.mtx"
        pfile.write_text(r.stdout)
        part = np.asarray(read_mtx(pfile).vals).reshape(-1)
        assert part.size == 144
        assert set(np.unique(part)) == {0, 1, 2}


def test_cli_solve_single(matrix_file):
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "none", "--solver", "acg",
                 "--max-iterations", "500", "--residual-rtol", "1e-8",
                 "--manufactured-solution", "--warmup", "1", "--quiet"])
    assert r.returncode == 0, r.stderr
    assert "total solver time: " in r.stderr
    err = float([l for l in r.stderr.splitlines()
                 if l.startswith("error 2-norm:")][0].split(":")[1])
    assert err < 1e-5


def test_cli_solve_distributed_with_partition_file(matrix_file, tmp_path):
    part = run_cli("acg_tpu.tools.mtxpartition", [str(matrix_file), "--parts", "4"])
    pfile = tmp_path / "part.mtx"
    pfile.write_text(part.stdout)
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--nparts", "4", "--partition", str(pfile),
                 "--solver", "acg-pipelined", "--max-iterations", "500",
                 "--residual-rtol", "1e-8", "--manufactured-solution",
                 "--warmup", "0", "--output-comm-matrix", "--quiet"])
    assert r.returncode == 0, r.stderr
    assert "total solver time: " in r.stderr
    err = float([l for l in r.stderr.splitlines()
                 if l.startswith("error 2-norm:")][0].split(":")[1])
    assert err < 1e-5
    # comm matrix on stdout
    assert "%%MatrixMarket matrix coordinate integer general" in r.stdout


def test_cli_solution_output(matrix_file, tmp_path):
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "none", "--solver", "host",
                 "--max-iterations", "500", "--residual-rtol", "1e-10"])
    assert r.returncode == 0, r.stderr
    sol = tmp_path / "x.mtx"
    sol.write_text(r.stdout)
    x = np.asarray(read_mtx(sol).vals)
    assert x.shape == (144,)
    # verify: A x ~= ones
    from acg_tpu.matrix import SymCsrMatrix
    A = SymCsrMatrix.from_mtx(read_mtx(matrix_file))
    np.testing.assert_allclose(A.dsymv(x), np.ones(144), atol=1e-7)


def test_cli_not_converged_exit_code(matrix_file):
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "none", "--max-iterations", "2",
                 "--residual-rtol", "1e-14", "--warmup", "0", "--quiet"])
    assert r.returncode == 1
    assert "did not converge" in r.stderr


def test_cli_comm_aliases(matrix_file):
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "nccl", "--nparts", "2",
                 "--max-iterations", "300", "--residual-rtol", "1e-6",
                 "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr


def test_numfmt_rejects_non_float_conversions():
    from acg_tpu.cli import _validate_numfmt
    import pytest as _pytest
    # note: "%.g" is VALID C (bare '.' = precision 0, fmtspec.h:120-122)
    for bad in ("%d", "%s", "%i", "%x", "%.17g %g", "g", "%", "%q"):
        with _pytest.raises(SystemExit):
            _validate_numfmt(bad)
    for good in ("%.17g", "%e", "%12.6f", "%+G", "%#.3E", "%-8.2f"):
        assert _validate_numfmt(good) == good


def test_cli_bf16_smoke(matrix_file):
    """--dtype bf16 must run end-to-end; accuracy is limited (~2-3
    digits) so only loose convergence is asserted."""
    r = run_cli("acg_tpu.cli", [str(matrix_file), "--dtype", "bf16",
                                "--comm", "none",
                                "--max-iterations", "800",
                                "--residual-rtol", "1e-2",
                                "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    assert "total solver time" in r.stderr


def test_cli_rejects_integer_numfmt(matrix_file):
    r = run_cli("acg_tpu.cli", [str(matrix_file), "--numfmt", "%d",
                                "--comm", "none", "--max-iterations", "5"])
    assert r.returncode != 0
    assert "numfmt" in r.stderr


@pytest.mark.parametrize("fmt", ["dia", "ell", "coo"])
def test_cli_spmv_format_forced(matrix_file, fmt):
    """--spmv-format forces the device sparse format (the reference's
    --cusparse-spmv-alg role); every format solves to the same answer."""
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "none", "--spmv-format", fmt,
                 "--max-iterations", "500", "--residual-rtol", "1e-8",
                 "--manufactured-solution", "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    err = float(r.stderr.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6, r.stderr


def test_cli_compat_flags(matrix_file, tmp_path):
    """Reference drop-in flags: --gzip/--gunzip/--ungzip (no-ops; gzip is
    magic-byte autodetected), --binary-partition alias, and the --no-*
    negations (cuda/acg-cuda.c option list)."""
    import gzip as _gzip
    gz = tmp_path / "p.mtx.gz"
    gz.write_bytes(_gzip.compress(matrix_file.read_bytes()))
    r = run_cli("acg_tpu.cli",
                [str(gz), "--gzip", "--comm", "none",
                 "--max-iterations", "300", "--residual-rtol", "1e-8",
                 "--manufactured-solution", "--no-manufactured-solution",
                 "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    assert "error 2-norm" not in r.stderr  # negation disabled the check
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--binary-partition", "--ungzip",
                 "--comm", "none", "--max-iterations", "10",
                 "--residual-rtol", "0", "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr


def test_cli_trace_writes_profile(matrix_file, tmp_path):
    """--trace DIR produces a jax.profiler trace (the nsys-trace tier,
    scripts/trace_nvshmem.sh:57-63)."""
    tdir = tmp_path / "trace"
    r = run_cli("acg_tpu.cli",
                [str(matrix_file), "--comm", "none", "--max-iterations",
                 "50", "--residual-rtol", "0", "--warmup", "0",
                 "--trace", str(tdir), "--quiet"])
    assert r.returncode == 0, r.stderr
    produced = list(tdir.rglob("*"))
    assert any(p.is_file() for p in produced), "no trace files written"


def test_cli_gen_spec_standard_pipeline():
    """gen:poisson2d:N synthesizes the matrix in-process and runs the
    FULL pipeline (partition, manufactured solution, distributed)."""
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:24", "--nparts", "4",
                 "--max-iterations", "500", "--residual-rtol", "1e-8",
                 "--manufactured-solution", "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    err = float(r.stderr.split("\nerror 2-norm: ")[1].split()[0])
    assert err < 1e-6


def test_cli_kernels_fused():
    """--kernels fused runs the two-phase iteration end-to-end from the
    CLI on a single-window DIA shape (gen 2D Poisson n=128 -> N=16384 =
    one kernel tile)."""
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:128", "--comm", "none", "--kernels",
                 "fused", "--dtype", "f32", "--max-iterations", "2000",
                 "--residual-rtol", "1e-6", "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    assert "total solver time" in r.stderr


def test_cli_gen_direct_profile_ops():
    """--profile-ops now works on the single-chip gen-direct path
    (round-2 verdict weak #4: it was on the unsupported list)."""
    import os
    env_extra = {"ACG_TPU_GEN_DIRECT_MIN": "100"}
    env = dict(os.environ); env.update(ENV_KEYS); env.update(env_extra)
    import subprocess
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson2d:64",
         "--comm", "none", "--profile-ops", "2", "--max-iterations", "200",
         "--residual-rtol", "1e-6", "--dtype", "f32", "--warmup", "0",
         "--quiet"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    # the per-op block carries replayed (nonzero) times
    gemv = [l for l in r.stderr.splitlines() if l.strip().startswith("gemv:")]
    assert gemv and not gemv[0].strip().startswith("gemv: 0.000000")


def test_cli_gen_spec_direct_device_path():
    """Above the size threshold, gen:poisson specs assemble DIA planes
    on device with no host matrix at all (the 512^3 route; threshold
    lowered via env to keep CI tiny)."""
    import os
    env_extra = {"ACG_TPU_GEN_DIRECT_MIN": "100"}
    env = dict(os.environ); env.update(ENV_KEYS); env.update(env_extra)
    import subprocess
    r = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:8",
         "--comm", "none", "--max-iterations", "500",
         "--residual-rtol", "1e-8", "--warmup", "0", "-v"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "assemble DIA planes on device" in r.stderr
    assert "total solver time" in r.stderr
    # solution written and solves A x = ones
    assert "%%MatrixMarket matrix array" in r.stdout
    # --manufactured-solution routes to the SHARDED direct path (round 3)
    # and verifies end-to-end
    r2 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:8",
         "--manufactured-solution", "--max-iterations", "500",
         "--residual-rtol", "1e-6", "--warmup", "0", "--quiet"],
        capture_output=True, text=True, env=env)
    assert r2.returncode == 0, r2.stderr
    assert float(r2.stderr.split("\nerror 2-norm: ")[1].split()[0]) < 1e-4
    # remaining restrictions still produce a clear error
    r3 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:8",
         "--output-comm-matrix"],
        capture_output=True, text=True, env=env)
    assert r3.returncode != 0
    assert "does not support" in r3.stderr
    # --refine is supported here since round 4 (sharded df64 route) but
    # requires an f32-family storage dtype
    r4 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson3d:8",
         "--refine", "--dtype", "f64"],
        capture_output=True, text=True, env=env)
    assert r4.returncode != 0
    assert "df64" in r4.stderr


def test_cli_gen_spec_invalid():
    r = run_cli("acg_tpu.cli", ["gen:bogus:3"])
    assert r.returncode != 0
    assert "invalid generator spec" in r.stderr


def test_cli_buildinfo():
    r = run_cli("acg_tpu.cli", ["--buildinfo"])
    assert r.returncode == 0, r.stderr
    for key in ("acg-tpu:", "jax:", "backend:", "native core", "libmetis:"):
        assert key in r.stdout, r.stdout


def test_cli_replace_every_bf16(tmp_path):
    """--dtype bf16 --replace-every: the sound-bf16 tier end-to-end --
    converges to a residual tolerance plain bf16 cannot reach, with the
    manufactured-solution error reported."""
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:64", "--dtype", "bf16", "--nparts", "1",
                 "--replace-every", "25", "--solver", "acg",
                 "--max-iterations", "4000", "--residual-rtol", "1e-4",
                 "--manufactured-solution", "--warmup", "0", "--quiet"])
    assert r.returncode == 0, r.stderr
    err = float([ln for ln in r.stderr.splitlines()
                 if ln.startswith("error 2-norm:")][0].split(":")[1])
    assert err < 2e-2
    assert "total solver time:" in r.stderr


def test_cli_replace_every_rejects_f32():
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:16", "--dtype", "f32",
                 "--replace-every", "25", "--warmup", "0", "--quiet"])
    assert r.returncode != 0
    assert "bf16" in r.stderr


def test_cli_output_file_all_paths(tmp_path, matrix_file):
    """-o/--output writes a binary array vector on every path (not just
    --distributed-read), regardless of --quiet."""
    from acg_tpu.io.mtxfile import read_mtx

    # replicated single-device path
    out = tmp_path / "x1.bin.mtx"
    r = run_cli("acg_tpu.cli", [str(matrix_file), "--nparts", "1",
                                "--dtype", "f64", "--max-iterations", "500",
                                "--residual-rtol", "1e-10", "--warmup", "0",
                                "--quiet", "-o", str(out)])
    assert r.returncode == 0, r.stderr
    x = np.asarray(read_mtx(out, binary=True).vals).reshape(-1)
    m = read_mtx(matrix_file)
    import scipy.sparse as sp
    rr, cc, vv = m.to_coo()
    from acg_tpu.io.mtxfile import expand_symmetry
    rr, cc, vv = expand_symmetry(rr, cc, vv, m.nrows)
    A = sp.coo_matrix((vv, (rr, cc))).tocsr()
    b = np.ones(m.nrows)
    assert np.linalg.norm(b - A @ x) < 1e-8 * np.linalg.norm(b)

    # gen-direct on-device path
    out2 = tmp_path / "x2.bin.mtx"
    import os, subprocess
    env = dict(os.environ); env.update(ENV_KEYS)
    env["ACG_TPU_GEN_DIRECT_MIN"] = "100"
    r2 = subprocess.run(
        [sys.executable, "-m", "acg_tpu.cli", "gen:poisson2d:16",
         "--comm", "none", "--max-iterations", "400",
         "--residual-rtol", "1e-6", "--warmup", "0", "--quiet",
         "-o", str(out2)],
        capture_output=True, text=True, env=env)
    assert r2.returncode == 0, r2.stderr
    assert read_mtx(out2, binary=True).nrows == 256
