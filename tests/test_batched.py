"""Batched multi-RHS CG + block-CG tier (acg_tpu.solvers.batched,
acg_tpu.parallel.dist_batched).

The acceptance surface of ISSUE 11: per-column parity with the
single-RHS tiers (bitwise where the recurrences are identical),
mask-freeze correctness, block-CG's iteration-count win on the aniso
family, B-INVARIANT collective counts at the HLO level, B=1
byte-identity (the disarmed-identity discipline), batched
checkpoint/resume parity, and the per-RHS soak percentiles."""

import re

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import (aniso_poisson2d_coo, batched_rhs,
                                   poisson2d_coo)
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr
from acg_tpu.partition import partition_rows
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.parallel.dist_batched import BatchedDistCGSolver
from acg_tpu.solvers.batched import BatchedCGSolver, spmv_multi
from acg_tpu.solvers.host_cg import host_batched_cg, host_block_cg
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def sys16():
    r, c, v, N = poisson2d_coo(16)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    B = batched_rhs(N, 3, seed=0)
    return csr, A, B


@pytest.fixture(scope="module")
def dist_prob(sys16):
    csr, _, _ = sys16
    part = partition_rows(csr, 4, seed=0, method="band")
    return DistributedProblem.build(csr, part, 4, dtype=jnp.float64)


CRIT = StoppingCriteria(maxits=500, residual_rtol=1e-10)


# -- multi-vector SpMV ----------------------------------------------------

@pytest.mark.parametrize("fmt", ["dia", "ell", "coo", "bell"])
def test_spmv_multi_matches_columns(sys16, fmt):
    csr, _, B = sys16
    A = device_matrix_from_csr(csr, dtype=jnp.float64, format=fmt)
    Y = np.asarray(spmv_multi(A, jnp.asarray(B)))
    assert np.allclose(Y, csr @ B, atol=1e-12)


# -- batched parity: per-column trajectories ARE the single-RHS ones ------

def test_batched_classic_matches_independent_bitwise(sys16):
    _, A, B = sys16
    s = BatchedCGSolver(A)
    X = s.solve(B, criteria=CRIT)
    assert s.stats.batch["nrhs"] == 3
    for j in range(3):
        s1 = JaxCGSolver(A, kernels="xla")
        x1 = s1.solve(B[:, j], criteria=CRIT)
        assert s.stats.batch["iterations"][j] == s1.stats.niterations
        assert np.array_equal(X[:, j], x1)   # bitwise


def test_batched_pipelined_matches_independent_bitwise(sys16):
    _, A, B = sys16
    s = BatchedCGSolver(A, mode="pipelined")
    X = s.solve(B, criteria=CRIT)
    for j in range(3):
        s1 = JaxCGSolver(A, kernels="xla", pipelined=True)
        x1 = s1.solve(B[:, j], criteria=CRIT)
        assert s.stats.batch["iterations"][j] == s1.stats.niterations
        assert np.array_equal(X[:, j], x1)


def test_batched_precond_matches_independent(sys16):
    _, A, B = sys16
    s = BatchedCGSolver(A, precond="jacobi")
    X = s.solve(B, criteria=CRIT)
    for j in range(3):
        s1 = JaxCGSolver(A, kernels="xla", precond="jacobi")
        x1 = s1.solve(B[:, j], criteria=CRIT)
        assert s.stats.batch["iterations"][j] == s1.stats.niterations
        assert np.allclose(X[:, j], x1, atol=1e-12)


def test_batched_matches_host_oracle(sys16):
    csr, A, B = sys16
    s = BatchedCGSolver(A)
    X = s.solve(B, criteria=CRIT)
    Xh, iters_h, _ = host_batched_cg(csr, B, criteria=CRIT)
    assert np.allclose(X, Xh, atol=1e-8)
    assert s.stats.batch["iterations"] == [int(v) for v in iters_h]


# -- mask freeze ----------------------------------------------------------

def test_converged_column_freezes(sys16):
    """A column converged at ENTRY (x0 = its solution, absolute
    tolerance) must stay bitwise frozen at 0 iterations while the rest
    of the batch runs to convergence."""
    csr, A, B = sys16
    x0 = np.zeros_like(B)
    x0[:, 0] = np.linalg.solve(csr.toarray(), B[:, 0])
    s = BatchedCGSolver(A)
    X = s.solve(B, x0=x0,
                criteria=StoppingCriteria(maxits=300,
                                          residual_atol=1e-8))
    batch = s.stats.batch
    assert batch["iterations"][0] == 0
    assert np.array_equal(X[:, 0], x0[:, 0])   # frozen bitwise
    assert all(batch["converged"])
    assert batch["iterations"][1] > 0 and batch["iterations"][2] > 0


def test_early_converged_column_stays_frozen(sys16):
    """A column that converges mid-run freezes: its final value equals
    an independent solve that STOPPED at the same tolerance, while the
    batch ran on to its slowest column."""
    csr, A, B = sys16
    # column 1 gets a much looser effective target via a larger b
    # norm: scale so its relative tolerance is met many iterations
    # before the others'
    crit = StoppingCriteria(maxits=500, residual_atol=1e-3)
    Bs = B.copy()
    Bs[:, 1] *= 1e-3   # tiny b -> absolute target met early
    s = BatchedCGSolver(A)
    X = s.solve(Bs, criteria=crit)
    its = s.stats.batch["iterations"]
    assert its[1] < its[0] and its[1] < its[2]
    s1 = JaxCGSolver(A, kernels="xla")
    x1 = s1.solve(Bs[:, 1], criteria=crit)
    assert s1.stats.niterations == its[1]
    assert np.array_equal(X[:, 1], x1)


# -- block CG -------------------------------------------------------------

def test_block_cg_solves_and_matches_oracle(sys16):
    csr, A, B = sys16
    s = BatchedCGSolver(A, mode="block")
    X = s.solve(B, criteria=CRIT)
    Xd = np.linalg.solve(csr.toarray(), B)
    assert np.allclose(X, Xd, atol=1e-8)
    Xh, _, _, trips_h = host_block_cg(csr, B, criteria=CRIT)
    assert np.allclose(X, Xh, atol=1e-8)
    # device and host block recurrences take the same trip count
    assert abs(s.stats.batch["block_iterations"] - trips_h) <= 2


def test_block_cg_beats_independent_on_aniso():
    """The ISSUE-11 acceptance: block-CG total iterations (trips x B)
    <= 0.7x the summed iterations of B independent solves on the
    anisotropic family."""
    r, c, v, N = aniso_poisson2d_coo(48, 0.05)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    B = batched_rhs(N, 8, seed=0)
    crit = StoppingCriteria(maxits=20000, residual_rtol=1e-8)
    s = BatchedCGSolver(A, mode="block")
    s.solve(B, criteria=crit)
    trips = s.stats.batch["block_iterations"]
    indep = 0
    for j in range(8):
        s1 = JaxCGSolver(A, kernels="xla")
        s1.solve(B[:, j], criteria=crit)
        indep += s1.stats.niterations
    assert trips * 8 <= 0.7 * indep, (trips, indep)


def test_block_cg_deflates_parallel_rhs(sys16):
    """Exactly parallel RHS columns collapse the block to rank 1 --
    the deflated Gram solves must converge anyway (rank deflation on
    breakdown), to the same answer."""
    csr, A, B = sys16
    Bp = np.column_stack([B[:, 0], 2.0 * B[:, 0], B[:, 1]])
    s = BatchedCGSolver(A, mode="block")
    X = s.solve(Bp, criteria=StoppingCriteria(maxits=500,
                                              residual_rtol=1e-8))
    Xd = np.linalg.solve(csr.toarray(), Bp)
    assert np.allclose(X, Xd, atol=1e-6)
    assert all(s.stats.batch["converged"])


# -- dist tier ------------------------------------------------------------

def test_dist_batched_matches_independent_bitwise(dist_prob, sys16):
    _, _, B = sys16
    s = BatchedDistCGSolver(dist_prob)
    X = s.solve(B, criteria=CRIT)
    for j in range(3):
        s1 = DistCGSolver(dist_prob)
        x1 = s1.solve(B[:, j], criteria=CRIT)
        assert s.stats.batch["iterations"][j] == s1.stats.niterations
        assert np.array_equal(X[:, j], x1)


def test_dist_batched_pipelined_matches_independent(dist_prob, sys16):
    _, _, B = sys16
    s = BatchedDistCGSolver(dist_prob, pipelined=True)
    X = s.solve(B, criteria=CRIT)
    for j in range(3):
        s1 = DistCGSolver(dist_prob, pipelined=True)
        x1 = s1.solve(B[:, j], criteria=CRIT)
        assert s.stats.batch["iterations"][j] == s1.stats.niterations
        assert np.array_equal(X[:, j], x1)


# -- HLO pins: collective count invariant in B ----------------------------

def _counts(txt):
    return (len(re.findall(r"all_reduce", txt)),
            len(re.findall(r"all_to_all", txt)))


def test_dist_batched_collectives_invariant_in_B(dist_prob):
    """The tentpole's communication contract, pinned at the compiler
    artifact: the batched programs' allreduce/all_to_all counts do not
    change with B, and they EQUAL the single-RHS tier's pinned counts
    (classic 5 ARs / 2 A2As, pipelined 5 ARs / 3 A2As -- the 2-psum /
    1-fused-psum in-loop structure of tests/test_hlo_structure.py)."""
    n = dist_prob.n
    crit = StoppingCriteria(maxits=5)
    for pipelined, want in ((False, (5, 2)), (True, (5, 3))):
        got = []
        for nb in (2, 4, 8):
            s = BatchedDistCGSolver(dist_prob, pipelined=pipelined)
            txt = s.lower_solve(batched_rhs(n, nb, seed=0),
                                criteria=crit).as_text()
            got.append(_counts(txt))
        assert got[0] == got[1] == got[2] == want, (pipelined, got)


def test_precise_dots_keep_fused_counts(dist_prob):
    """Compensated column dots widen the psum payloads (hi+lo pairs)
    but must not add collectives."""
    n = dist_prob.n
    crit = StoppingCriteria(maxits=5)
    s = BatchedDistCGSolver(dist_prob, pipelined=True,
                            precise_dots=True)
    txt = s.lower_solve(batched_rhs(n, 4, seed=0),
                        criteria=crit).as_text()
    assert _counts(txt) == (5, 3)


# -- B=1 byte-identity (the disarmed-identity discipline) -----------------

def test_single_column_is_byte_identical(sys16, dist_prob):
    _, A, B = sys16
    b1 = B[:, :1]
    batched = BatchedCGSolver(A).lower_solve(b1, criteria=CRIT).as_text()
    plain = JaxCGSolver(A, kernels="xla").lower_solve(
        B[:, 0], criteria=CRIT).as_text()
    assert batched == plain
    d_b = BatchedDistCGSolver(dist_prob).lower_solve(
        b1, criteria=CRIT).as_text()
    d_p = DistCGSolver(dist_prob).lower_solve(
        B[:, 0], criteria=CRIT).as_text()
    assert d_b == d_p


def test_cli_flag_absent_routes_unbatched():
    """--nrhs absent (or 1) never arms the batched dispatch."""
    from acg_tpu.cli import make_parser
    args = make_parser().parse_args(["gen:poisson2d:8"])
    assert args.nrhs == 0 and not args.block_cg


# -- checkpoint: a batch survives preemption ------------------------------

def test_batched_ckpt_chunked_is_bitwise(sys16, tmp_path):
    from acg_tpu.checkpoint import CheckpointConfig
    _, A, B = sys16
    Xp = BatchedCGSolver(A).solve(B, criteria=CRIT)
    ck = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=10))
    Xc = ck.solve(B, criteria=CRIT)
    assert np.array_equal(Xp, Xc)
    assert ck.stats.ckpt["snapshots"] > 0
    assert ck.stats.batch["nrhs"] == 3


def test_batched_resume_continues_exactly(sys16, tmp_path):
    from acg_tpu.checkpoint import CheckpointConfig, load_snapshot
    _, A, B = sys16
    Xp = BatchedCGSolver(A).solve(B, criteria=CRIT)
    t = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=10))
    t.solve(B, criteria=StoppingCriteria(maxits=25,
                                         residual_rtol=1e-10),
            raise_on_divergence=False)
    snap = load_snapshot(str(tmp_path / "ck"))
    assert snap.meta["nrhs"] == 3
    assert "done" in snap.arrays and "iters" in snap.arrays
    res = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck2"), every=10, resume=snap))
    Xr = res.solve(B, criteria=CRIT)
    assert np.array_equal(Xp, Xr)
    assert res.stats.ckpt["resumed_from"] == snap.iteration


def test_batched_ckpt_unbounded_chunks_continue(sys16, tmp_path):
    """Unbounded (fixed-work) chunked solves must CONTINUE across
    chunk boundaries -- the result's converged=ran-the-budget flag
    must not leak into the carry and freeze later chunks -- and the
    per-RHS iteration counts must report trajectory totals, not the
    last chunk's length."""
    from acg_tpu.checkpoint import CheckpointConfig
    _, A, B = sys16
    crit = StoppingCriteria(maxits=100)   # no tolerance: unbounded
    Xp = BatchedCGSolver(A).solve(B, criteria=crit,
                                  raise_on_divergence=False)
    ck = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=20))
    Xc = ck.solve(B, criteria=crit, raise_on_divergence=False)
    assert ck.stats.batch["iterations"] == [100, 100, 100]
    assert np.array_equal(Xp, Xc)


def test_batched_resume_refuses_wrong_nrhs(sys16, tmp_path):
    from acg_tpu.checkpoint import CheckpointConfig, load_snapshot
    from acg_tpu.errors import AcgError
    _, A, B = sys16
    t = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=10))
    t.solve(B, criteria=StoppingCriteria(maxits=25,
                                         residual_rtol=1e-10),
            raise_on_divergence=False)
    snap = load_snapshot(str(tmp_path / "ck"))
    res = BatchedCGSolver(A, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck2"), every=10, resume=snap))
    with pytest.raises(AcgError, match="right-hand-side count"):
        res.solve(batched_rhs(A.nrows, 5, seed=1), criteria=CRIT)


def test_dist_batched_ckpt_and_repartition(dist_prob, sys16, tmp_path):
    """A 4-part batched snapshot resumes bitwise on the same mesh AND
    restores onto a 2-part mesh via --resume-repartition (the per-RHS
    leaves reassemble through the row-permutation sidecar)."""
    from acg_tpu.checkpoint import CheckpointConfig, load_snapshot
    csr, _, B = sys16
    Xp = BatchedDistCGSolver(dist_prob).solve(B, criteria=CRIT)
    t = BatchedDistCGSolver(dist_prob, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck"), every=10))
    t.solve(B, criteria=StoppingCriteria(maxits=25,
                                         residual_rtol=1e-10),
            raise_on_divergence=False)
    snap = load_snapshot(str(tmp_path / "ck"))
    res = BatchedDistCGSolver(dist_prob, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck2"), every=10, resume=snap))
    Xr = res.solve(B, criteria=CRIT)
    assert np.array_equal(Xp, Xr)
    snap2 = load_snapshot(str(tmp_path / "ck"))
    part2 = partition_rows(csr, 2, seed=0, method="band")
    prob2 = DistributedProblem.build(csr, part2, 2, dtype=jnp.float64)
    rep = BatchedDistCGSolver(prob2, ckpt=CheckpointConfig(
        path=str(tmp_path / "ck3"), every=10, resume=snap2,
        repartition=True))
    Xrep = rep.solve(B, criteria=CRIT)
    assert np.abs(Xrep - Xp).max() < 1e-10
    assert rep.stats.ckpt["repartitioned_from"]["nparts"] == 4
    assert all(rep.stats.batch["converged"])


# -- telemetry / soak / status --------------------------------------------

def test_batched_trace_per_rhs_columns(sys16, tmp_path):
    from acg_tpu.telemetry import read_convergence_log
    _, A, B = sys16
    s = BatchedCGSolver(A, trace=64)
    s.solve(B, criteria=CRIT)
    tr = s.last_trace
    assert tr.nrhs == 3
    assert tr.records.shape[1] == 3
    # per-column residual histories are monotone-ish and end at the
    # per-RHS final residuals
    assert np.allclose(tr.records[-1], s.stats.batch["rnrm2"],
                       rtol=1e-6)
    path = tmp_path / "fan.jsonl"
    tr.write_jsonl(str(path))
    meta, recs = read_convergence_log(str(path))
    assert meta["nrhs"] == 3
    assert len(recs[0]["rnrm2"]) == 3
    assert "worst" in recs[0]


def test_batched_soak_per_rhs_percentiles(sys16):
    from acg_tpu import soak
    _, A, B = sys16
    s = BatchedCGSolver(A)
    _, report = soak.run_soak(
        s, B, nsolves=3, criteria=CRIT,
        solve_kwargs={"raise_on_divergence": False})
    pr = report["per_rhs"]
    assert pr["nrhs"] == 3
    assert pr["iterations"]["p50"] > 0
    assert pr["latency"]["p99"] >= pr["latency"]["p50"] > 0


def test_observatory_batch_block(sys16):
    from acg_tpu import observatory
    _, A, B = sys16
    was = observatory.armed()
    try:
        observatory.arm()
        s = BatchedCGSolver(A)
        s.solve(B, criteria=CRIT)
        doc = observatory.STATUS.document()
        batch = doc["solve"]["batch"]
        assert batch["nrhs"] == 3
        assert batch["unconverged"] == 0
        assert 0 <= batch["slowest_rhs"] < 3
        assert len(batch["residuals"]) == 3
    finally:
        if not was:
            observatory.disarm()


# -- case keys ------------------------------------------------------------

def test_batch_joins_bench_diff_case_key():
    from acg_tpu.perfmodel import _batch_keyed, _row_case
    assert _batch_keyed("m", None) == "m"
    assert _batch_keyed("m", 1) == "m"
    assert _batch_keyed("m", 8) == "m|nrhs=8"
    assert _batch_keyed("m", 8, True) == "m|nrhs=8|block"
    key, val = _row_case({"metric": "m", "value": 2.0, "nrhs": 4})
    assert key == "m|nrhs=4" and val == 2.0


# -- refusals -------------------------------------------------------------

def test_batched_refusals(sys16):
    _, A, B = sys16
    with pytest.raises(ValueError, match="multi-vector"):
        BatchedCGSolver(A, kernels="pallas")
    with pytest.raises(ValueError, match="unknown batched mode"):
        BatchedCGSolver(A, mode="what")
    from acg_tpu.errors import AcgError
    s = BatchedCGSolver(A)
    with pytest.raises(AcgError, match="residual criteria only"):
        s.solve(B, criteria=StoppingCriteria(maxits=5, diff_rtol=1e-3))


def test_dist_batched_refuses_precond(dist_prob):
    with pytest.raises(ValueError, match="unpreconditioned"):
        BatchedDistCGSolver(dist_prob, precond="jacobi")
