"""SymCsrMatrix invariants and host reference CG vs scipy/numpy oracles."""

import numpy as np
import pytest
import scipy.sparse as sp

from acg_tpu.io.generators import poisson2d_coo, poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.errors import NotConvergedError
from acg_tpu.solvers import HostCGSolver, StoppingCriteria


def rand_spd(n, seed=0, density=0.2):
    rng = np.random.default_rng(seed)
    B = sp.random(n, n, density=density, random_state=rng).toarray()
    A = B @ B.T + n * np.eye(n)
    return A


def test_from_coo_full_vs_triangle():
    A = rand_spd(12, 1)
    Asp = sp.coo_matrix(A)
    full = SymCsrMatrix.from_coo(12, Asp.row, Asp.col, Asp.data)
    up = sp.triu(sp.coo_matrix(A)).tocoo()
    tri = SymCsrMatrix.from_coo(12, up.row, up.col, up.data)
    np.testing.assert_allclose(full.to_csr().toarray(), A, rtol=1e-14)
    np.testing.assert_allclose(tri.to_csr().toarray(), A, rtol=1e-14)
    # packed storage stores upper triangle only
    assert (full.pcolidx >= np.repeat(np.arange(12), np.diff(full.prowptr))).all()
    assert full.pnnz == tri.pnnz


def test_packed_nnz_full():
    m = poisson_mtx(5, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    assert A.nnz_full == A.to_csr().nnz


def test_epsilon_shift():
    m = poisson_mtx(4, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    d0 = A.to_csr().diagonal()
    d1 = A.to_csr(epsilon=0.5).diagonal()
    np.testing.assert_allclose(d1 - d0, 0.5)


def test_dsymv_matches_dense():
    m = poisson_mtx(6, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    x = np.random.default_rng(2).standard_normal(36)
    np.testing.assert_allclose(A.dsymv(x), A.to_csr().toarray() @ x, rtol=1e-14)


def test_host_cg_small_dense():
    A = rand_spd(20, 3)
    xsol = np.random.default_rng(4).standard_normal(20)
    b = A @ xsol
    solver = HostCGSolver(sp.csr_matrix(A))
    x = solver.solve(b, criteria=StoppingCriteria(maxits=200, residual_rtol=1e-12))
    np.testing.assert_allclose(x, xsol, rtol=1e-8)
    st = solver.stats
    assert st.converged and st.niterations > 0
    assert st.rnrm2 < 1e-12 * st.r0nrm2 * 1.0000001
    assert st.nflops > 0 and st.tsolve > 0


def test_host_cg_poisson_manufactured():
    """The reference's primary verification: random unit-norm xsol,
    b = A xsol, check final error norm (cuda/acg-cuda.c:1969-2385)."""
    m = poisson_mtx(16, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(A.nrows)
    xsol /= np.linalg.norm(xsol)
    b = A.dsymv(xsol)
    solver = HostCGSolver(A)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000, residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-7


def test_host_cg_not_converged():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    with pytest.raises(NotConvergedError):
        solver.solve(b, criteria=StoppingCriteria(maxits=2, residual_rtol=1e-14))


def test_host_cg_maxits_only():
    """With all tolerances zero the solver runs exactly maxits iterations
    and reports success (the reference's benchmark mode)."""
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    solver.solve(b, criteria=StoppingCriteria(maxits=7))
    assert solver.stats.niterations == 7
    assert solver.stats.converged


def test_stats_report_format():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    solver = HostCGSolver(A)
    solver.solve(np.ones(A.nrows), criteria=StoppingCriteria(maxits=500, residual_rtol=1e-8))
    text = solver.stats.fwrite()
    # the reference's analysis scripts grep for this exact phrase
    assert "total solver time: " in text
    assert "performance breakdown:" in text
    for label in ("gemv:", "dot:", "nrm2:", "axpy:", "copy:",
                  "MPI_Allreduce:", "MPI_HaloExchange:"):
        assert label in text
    assert "floating-point exceptions: none" in text


def test_diff_stopping_criteria():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    solver.solve(b, criteria=StoppingCriteria(maxits=1000, diff_atol=1e-10))
    assert solver.stats.converged
    assert solver.stats.dxnrm2 < 1e-10


# -- external oracle: scipy-backed PETSc-baseline slot ----------------------

def test_petsc_baseline_matches_host():
    """The external CG (scipy, the KSPCG analog) must agree with our host
    solver on solution and (approximately) iteration count."""
    from acg_tpu.solvers.petsc_cg import PetscBaselineSolver
    A = SymCsrMatrix.from_mtx(poisson_mtx(16, dim=2))
    csr = A.to_csr()
    rng = np.random.default_rng(3)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    xh = HostCGSolver(csr).solve(b, criteria=crit)
    sp_solver = PetscBaselineSolver(csr)
    xp = sp_solver.solve(b, criteria=crit)
    hp = HostCGSolver(csr)
    hp.solve(b, criteria=crit)
    assert np.linalg.norm(xp - xh) < 1e-8
    assert np.linalg.norm(xp - xsol) < 1e-7
    # iteration counts agree within a few iterations (identical algorithm,
    # independent implementation)
    assert abs(sp_solver.stats.niterations - hp.stats.niterations) <= 3
    assert sp_solver.stats.converged


def test_petsc_baseline_divergence_raises():
    from acg_tpu.errors import NotConvergedError
    from acg_tpu.solvers.petsc_cg import PetscBaselineSolver
    A = SymCsrMatrix.from_mtx(poisson_mtx(16, dim=2))
    solver = PetscBaselineSolver(A.to_csr())
    b = np.ones(A.nrows)
    with pytest.raises(NotConvergedError):
        solver.solve(b, criteria=StoppingCriteria(maxits=3,
                                                  residual_rtol=1e-12))


def test_petsc_baseline_rejects_diff_criteria():
    from acg_tpu.errors import AcgError
    from acg_tpu.solvers.petsc_cg import PetscBaselineSolver
    A = SymCsrMatrix.from_mtx(poisson_mtx(8, dim=2))
    solver = PetscBaselineSolver(A.to_csr())
    with pytest.raises(AcgError):
        solver.solve(np.ones(A.nrows),
                     criteria=StoppingCriteria(maxits=10, diff_atol=1e-8))


# -- distributed host CG (solvempi analog) over PVector subdomains ----------

@pytest.mark.parametrize("nparts", [2, 4, 8])
def test_host_dist_cg_matches_serial(nparts):
    """HostDistCGSolver (cg.c:408 solvempi analog, PVector + host halo)
    must match the serial host solver bit-for-bit in iteration count and
    closely in solution."""
    from acg_tpu.graph import partition_matrix
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.host_cg import HostDistCGSolver
    A = SymCsrMatrix.from_mtx(poisson_mtx(16, dim=2))
    csr = A.to_csr()
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    serial = HostCGSolver(csr)
    xs = serial.solve(b, criteria=crit)
    part = partition_rows(csr, nparts, seed=1)
    subs = partition_matrix(csr, part, nparts)
    dist = HostDistCGSolver(subs)
    xd = dist.solve(b, criteria=crit)
    assert abs(dist.stats.niterations - serial.stats.niterations) <= 2
    assert np.linalg.norm(xd - xs) < 1e-8
    assert np.linalg.norm(xd - xsol) < 1e-7
    assert dist.stats.converged


def test_indefinite_matrix_abort():
    """CG on a matrix with (p, Ap) == 0 must raise the reference's
    indefinite-matrix error (ACG_ERR_NOT_CONVERGED_INDEFINITE_MATRIX,
    cg.c:304) from BOTH host oracles, not divide by zero."""
    import pytest
    import scipy.sparse as sp

    from acg_tpu.errors import IndefiniteMatrixError
    from acg_tpu.solvers.host_cg import HostCGSolver, NativeHostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria
    from acg_tpu import _native

    n = 16
    Z = sp.csr_matrix((n, n))  # Ap = 0 for every p
    b = np.ones(n)
    crit = StoppingCriteria(maxits=10, residual_rtol=1e-10)
    solvers = [HostCGSolver(Z)]
    if _native.available():
        solvers.append(NativeHostCGSolver(Z))
    for s in solvers:
        with pytest.raises(IndefiniteMatrixError):
            s.solve(b, criteria=crit)


def test_exact_convergence_is_not_indefinite():
    """Fixed-iteration CG past exact convergence reaches r = p = 0, where
    (p, Ap) == 0 means "done", not "indefinite": both host oracles must
    return the exact solution instead of raising (SPD identity matrix,
    maxits far beyond the 1 iteration needed)."""
    import scipy.sparse as sp

    from acg_tpu.solvers.host_cg import HostCGSolver, NativeHostCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria
    from acg_tpu import _native

    n = 16
    I = sp.identity(n, format="csr")
    b = np.ones(n)
    crit = StoppingCriteria(maxits=10)  # unbounded fixed-iteration mode
    solvers = [HostCGSolver(I)]
    if _native.available():
        solvers.append(NativeHostCGSolver(I))
    for s in solvers:
        x = s.solve(b, criteria=crit)
        np.testing.assert_allclose(x, b, rtol=1e-14)
