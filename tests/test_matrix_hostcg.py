"""SymCsrMatrix invariants and host reference CG vs scipy/numpy oracles."""

import numpy as np
import pytest
import scipy.sparse as sp

from acg_tpu.io.generators import poisson2d_coo, poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.errors import NotConvergedError
from acg_tpu.solvers import HostCGSolver, StoppingCriteria


def rand_spd(n, seed=0, density=0.2):
    rng = np.random.default_rng(seed)
    B = sp.random(n, n, density=density, random_state=rng).toarray()
    A = B @ B.T + n * np.eye(n)
    return A


def test_from_coo_full_vs_triangle():
    A = rand_spd(12, 1)
    Asp = sp.coo_matrix(A)
    full = SymCsrMatrix.from_coo(12, Asp.row, Asp.col, Asp.data)
    up = sp.triu(sp.coo_matrix(A)).tocoo()
    tri = SymCsrMatrix.from_coo(12, up.row, up.col, up.data)
    np.testing.assert_allclose(full.to_csr().toarray(), A, rtol=1e-14)
    np.testing.assert_allclose(tri.to_csr().toarray(), A, rtol=1e-14)
    # packed storage stores upper triangle only
    assert (full.pcolidx >= np.repeat(np.arange(12), np.diff(full.prowptr))).all()
    assert full.pnnz == tri.pnnz


def test_packed_nnz_full():
    m = poisson_mtx(5, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    assert A.nnz_full == A.to_csr().nnz


def test_epsilon_shift():
    m = poisson_mtx(4, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    d0 = A.to_csr().diagonal()
    d1 = A.to_csr(epsilon=0.5).diagonal()
    np.testing.assert_allclose(d1 - d0, 0.5)


def test_dsymv_matches_dense():
    m = poisson_mtx(6, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    x = np.random.default_rng(2).standard_normal(36)
    np.testing.assert_allclose(A.dsymv(x), A.to_csr().toarray() @ x, rtol=1e-14)


def test_host_cg_small_dense():
    A = rand_spd(20, 3)
    xsol = np.random.default_rng(4).standard_normal(20)
    b = A @ xsol
    solver = HostCGSolver(sp.csr_matrix(A))
    x = solver.solve(b, criteria=StoppingCriteria(maxits=200, residual_rtol=1e-12))
    np.testing.assert_allclose(x, xsol, rtol=1e-8)
    st = solver.stats
    assert st.converged and st.niterations > 0
    assert st.rnrm2 < 1e-12 * st.r0nrm2 * 1.0000001
    assert st.nflops > 0 and st.tsolve > 0


def test_host_cg_poisson_manufactured():
    """The reference's primary verification: random unit-norm xsol,
    b = A xsol, check final error norm (cuda/acg-cuda.c:1969-2385)."""
    m = poisson_mtx(16, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(A.nrows)
    xsol /= np.linalg.norm(xsol)
    b = A.dsymv(xsol)
    solver = HostCGSolver(A)
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000, residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-7


def test_host_cg_not_converged():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    with pytest.raises(NotConvergedError):
        solver.solve(b, criteria=StoppingCriteria(maxits=2, residual_rtol=1e-14))


def test_host_cg_maxits_only():
    """With all tolerances zero the solver runs exactly maxits iterations
    and reports success (the reference's benchmark mode)."""
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    solver.solve(b, criteria=StoppingCriteria(maxits=7))
    assert solver.stats.niterations == 7
    assert solver.stats.converged


def test_stats_report_format():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    solver = HostCGSolver(A)
    solver.solve(np.ones(A.nrows), criteria=StoppingCriteria(maxits=500, residual_rtol=1e-8))
    text = solver.stats.fwrite()
    # the reference's analysis scripts grep for this exact phrase
    assert "total solver time: " in text
    assert "performance breakdown:" in text
    for label in ("gemv:", "dot:", "nrm2:", "axpy:", "copy:",
                  "MPI_Allreduce:", "MPI_HaloExchange:"):
        assert label in text
    assert "floating-point exceptions: none" in text


def test_diff_stopping_criteria():
    m = poisson_mtx(8, dim=2)
    A = SymCsrMatrix.from_mtx(m)
    b = np.ones(A.nrows)
    solver = HostCGSolver(A)
    solver.solve(b, criteria=StoppingCriteria(maxits=1000, diff_atol=1e-10))
    assert solver.stats.converged
    assert solver.stats.dxnrm2 < 1e-10
