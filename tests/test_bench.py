"""Unit tests for the benchmark harness's timing logic (bench.py).

The harness defends the one number the driver records against three
shared-chip failure modes: bursty contention (best-of-N), long-program
watchdog kills (trip-count reduction), and per-case crashes (isolation).
These tests pin that logic with a fake solver -- no device needed.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class FakeStats:
    tsolve = 0.0


class FakeSolver:
    """Solver whose per-iteration cost is scripted."""

    def __init__(self, seconds_per_iter):
        self.per_iter = seconds_per_iter
        self.stats = FakeStats()
        self.calls = []

    def solve(self, b, criteria=None, **kw):
        self.calls.append(criteria.maxits)
        self.stats.tsolve += self.per_iter * criteria.maxits


class FakeCriteria:
    def __init__(self, maxits):
        self.maxits = maxits


def test_time_solver_full_trip_count_when_fast():
    s = FakeSolver(1e-4)  # 1000 iters = 0.1s, far under the watchdog
    tsolve, maxits = bench._time_solver(s, None, FakeCriteria, repeats=3)
    assert maxits == bench.MAXITS
    assert tsolve == pytest.approx(1e-4 * bench.MAXITS)
    # compile warmup, then the TWO-POINT rate estimate (2x short + 2x
    # long -- cancels any constant dispatch overhead), then 3 timed runs
    assert s.calls == ([bench.WARMUP_ITS] * 3
                       + [4 * bench.WARMUP_ITS] * 2 + [bench.MAXITS] * 3)


def test_time_solver_reduces_trip_count_for_slow_configs():
    s = FakeSolver(0.13)  # 1000 iters = 130s >> MAX_PROGRAM_SECONDS
    tsolve, maxits = bench._time_solver(s, None, FakeCriteria, repeats=2)
    assert maxits < bench.MAXITS
    assert maxits >= 100
    # the timed program stays under the budget OR at the 100-iteration
    # floor (very slow configs keep 100 its so iters/s stays meaningful,
    # accepting the watchdog risk for that one class)
    budget_its = max(100, int(bench.MAX_PROGRAM_SECONDS / 0.13))
    assert maxits == budget_its
    # iters/s is trip-count-invariant
    assert maxits / tsolve == pytest.approx(1 / 0.13)


def test_time_solver_passes_solve_kwargs():
    seen = {}

    class KwSolver(FakeSolver):
        def solve(self, b, criteria=None, **kw):
            seen.update(kw)
            super().solve(b, criteria=criteria)

    s = KwSolver(1e-5)
    bench._time_solver(s, None, FakeCriteria, repeats=1, host_result=False)
    assert seen == {"host_result": False}
