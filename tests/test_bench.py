"""Unit tests for the benchmark harness's timing logic (bench.py).

The harness defends the one number the driver records against three
shared-chip failure modes: bursty contention (best-of-N), long-program
watchdog kills (trip-count reduction), and per-case crashes (isolation).
These tests pin that logic with a fake solver -- no device needed.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


class FakeStats:
    tsolve = 0.0


class FakeSolver:
    """Solver whose per-iteration cost is scripted."""

    def __init__(self, seconds_per_iter):
        self.per_iter = seconds_per_iter
        self.stats = FakeStats()
        self.calls = []

    def solve(self, b, criteria=None, **kw):
        self.calls.append(criteria.maxits)
        self.stats.tsolve += self.per_iter * criteria.maxits


class FakeCriteria:
    def __init__(self, maxits):
        self.maxits = maxits


def test_time_solver_full_trip_count_when_fast():
    s = FakeSolver(1e-4)  # 1000 iters = 0.1s, far under the watchdog
    tsolve, maxits, info = bench._time_solver(s, None, FakeCriteria,
                                              repeats=3)
    assert maxits == bench.MAXITS
    assert tsolve == pytest.approx(1e-4 * bench.MAXITS)
    assert info["raw"] == pytest.approx(tsolve)
    assert info["budget_exhausted"] is False
    # compile warmup, then the TWO-POINT rate estimate (2x short + 2x
    # long -- cancels any constant dispatch overhead), then 3 timed runs
    assert s.calls == ([bench.WARMUP_ITS] * 3
                       + [4 * bench.WARMUP_ITS] * 2 + [bench.MAXITS] * 3)


def test_time_solver_reduces_trip_count_for_slow_configs():
    s = FakeSolver(0.13)  # 1000 iters = 130s >> MAX_PROGRAM_SECONDS
    tsolve, maxits, _ = bench._time_solver(s, None, FakeCriteria, repeats=2)
    assert maxits < bench.MAXITS
    assert maxits >= 100
    # the timed program stays under the budget OR at the 100-iteration
    # floor (very slow configs keep 100 its so iters/s stays meaningful,
    # accepting the watchdog risk for that one class)
    budget_its = max(100, int(bench.MAX_PROGRAM_SECONDS / 0.13))
    assert maxits == budget_its
    # iters/s is trip-count-invariant
    assert maxits / tsolve == pytest.approx(1 / 0.13)


def test_time_solver_wall_clock_budget_stops_repeats():
    """A slow config under a wall-clock budget keeps its first timed run
    and skips the rest (round-4 verdict item 8: fewer repeats on a slow
    row beats a dead row)."""
    import time as _time

    class SlowSolver(FakeSolver):
        def solve(self, b, criteria=None, **kw):
            super().solve(b, criteria=criteria)
            _time.sleep(0.05)  # real wall clock, what the budget sees

    s = SlowSolver(1e-4)
    tsolve, maxits, info = bench._time_solver(
        s, None, FakeCriteria, repeats=50, time_budget_s=0.01)
    assert info["budget_exhausted"] is True
    # warmup x3 + two-point x2 always run; then exactly ONE timed run
    assert len(s.calls) == 6
    assert maxits / tsolve == pytest.approx(1e4)


def test_roofline_clamp_discards_impossible_correction(monkeypatch):
    """A corrected value implying traffic far above the paired probe on
    a working set too large for VMEM residency reverts to the raw time
    (round-4 verdict item 2)."""
    monkeypatch.setattr(bench, "bandwidth_probe_gbs", lambda refresh: 800.0)
    # corrected 10,000 iters/s at 0.4 GB/iter -> 4 TB/s implied (5x probe)
    bpi = 0.4e9
    row = {"metric": "m", "value": 10_000.0, "vs_baseline": 2.0}
    info = {"raw": 1000 / 4000.0, "corrected": True,
            "budget_exhausted": False}  # raw = 4,000 iters/s
    out = bench._roofline_context(
        dict(row), bpi, info=info,
        working_set_bytes=6e9, maxits=1000)
    assert out["correction_discarded"] is True
    assert out["value"] == pytest.approx(4000.0)
    assert out["vs_baseline"] == pytest.approx(0.8)
    assert out["roofline_frac"] == pytest.approx(
        4000.0 * bpi / 800e9, rel=1e-3)

    # same correction on a VMEM-scale working set is EXEMPT (the HBM
    # traffic model does not bind there)
    out2 = bench._roofline_context(
        dict(row), bpi, info=info,
        working_set_bytes=100e6, maxits=1000)
    assert "correction_discarded" not in out2
    assert out2["value"] == pytest.approx(10_000.0)

    # an uncorrected row is never clamped, only annotated by its frac
    info_raw = {"raw": 1000 / 10_000.0, "corrected": False,
                "budget_exhausted": False}
    out3 = bench._roofline_context(
        dict(row), bpi, info=info_raw,
        working_set_bytes=6e9, maxits=1000)
    assert "correction_discarded" not in out3


def test_time_solver_passes_solve_kwargs():
    seen = {}

    class KwSolver(FakeSolver):
        def solve(self, b, criteria=None, **kw):
            seen.update(kw)
            super().solve(b, criteria=criteria)

    s = KwSolver(1e-5)
    bench._time_solver(s, None, FakeCriteria, repeats=1, host_result=False)
    assert seen == {"host_result": False}


def test_roofline_clamp_keeps_raw_only_when_slower(monkeypatch):
    """The clamp only ever moves a row DOWN to the raw time -- a raw
    value even faster than the corrected one (can't happen from the
    estimator, but belt-and-braces) is not adopted."""
    monkeypatch.setattr(bench, "bandwidth_probe_gbs", lambda refresh: 800.0)
    row = {"metric": "m", "value": 10_000.0, "vs_baseline": 2.0}
    info = {"raw": 1000 / 20_000.0, "corrected": True,
            "budget_exhausted": False}
    out = bench._roofline_context(dict(row), 0.4e9, info=info,
                                  working_set_bytes=6e9, maxits=1000)
    assert "correction_discarded" not in out
    assert out["value"] == pytest.approx(10_000.0)
