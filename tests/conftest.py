"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU build's analog of the reference's np=1,2,4,8 single-node
testing (SURVEY.md section 4): the same partitioned solve paths run over
XLA's host-platform device simulation so distributed code is exercised in
CI without TPU hardware.  float64 is enabled to match the reference's
strictly-FP64 semantics for correctness tests.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_ENABLE_X64", "1")

from acg_tpu._platform import provision_host_mesh  # noqa: E402

jax = provision_host_mesh(8)
jax.config.update("jax_enable_x64", True)
