"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU build's analog of the reference's np=1,2,4,8 single-node
testing (SURVEY.md section 4): the same partitioned solve paths run over
XLA's host-platform device simulation so distributed code is exercised in
CI without TPU hardware.  float64 is enabled to match the reference's
strictly-FP64 semantics for correctness tests.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_ENABLE_X64", "1")

from acg_tpu._platform import provision_host_mesh  # noqa: E402

jax = provision_host_mesh(8)
jax.config.update("jax_enable_x64", True)

# -- two-process collective capability probe ----------------------------
#
# The two-process CLI tests need the CPU backend to RUN cross-process
# XLA computations, not just to initialise a coordinator: some jaxlib
# CPU builds raise "Multiprocess computations aren't implemented on the
# CPU backend" at dispatch.  Probing that with a real two-process psum
# once per session lets those tests SKIP with the true reason instead
# of failing in containers whose backend lacks the capability.
# ACG_TPU_MULTIPROC_TESTS=1/0 overrides the probe either way.

_PROBE_CODE = """
import sys
import numpy as np
from acg_tpu.parallel.multihost import initialize
initialize("localhost:%d", 2, int(sys.argv[1]))
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from acg_tpu._platform import shard_map
assert jax.process_count() == 2
devs = np.asarray(jax.devices()[:2])
mesh = Mesh(devs, ("x",))
f = jax.jit(shard_map(lambda a: jax.lax.psum(a, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P()))
a = jax.device_put(jnp.arange(2.0),
                   NamedSharding(mesh, P("x")))
out = np.asarray(f(a))
assert out == 1.0, out
print("MULTIPROC-OK")
"""

_mp_status = None


def _multiprocess_collectives_status():
    """Cached ``(available, reason)`` for cross-process XLA
    collectives on this backend."""
    global _mp_status
    if _mp_status is not None:
        return _mp_status
    forced = os.environ.get("ACG_TPU_MULTIPROC_TESTS")
    if forced is not None:
        _mp_status = (forced not in ("0", "false", ""),
                      "forced by ACG_TPU_MULTIPROC_TESTS")
        return _mp_status
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_CODE % port, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))) for i in range(2)]
    try:
        outs = [p.communicate(timeout=180) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        _mp_status = (False, "two-process collective probe timed out")
        return _mp_status
    if all(p.returncode == 0 and "MULTIPROC-OK" in so
           for p, (so, _) in zip(procs, outs)):
        _mp_status = (True, "")
    else:
        reason = "two-process XLA computation failed"
        for _, (_, se) in zip(procs, outs):
            for line in se.splitlines():
                if "Multiprocess computations" in line:
                    reason = line.strip().split("INVALID_ARGUMENT: ")[-1]
                    break
        _mp_status = (False, reason)
    return _mp_status


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "two_process_collectives: needs cross-process XLA collectives "
        "(skipped when the CPU backend lacks them; probe in conftest)")
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'); full "
        "campaigns and long soak scenarios")


def pytest_collection_modifyitems(config, items):
    import pytest

    marked = [it for it in items
              if it.get_closest_marker("two_process_collectives")]
    if not marked:
        return
    ok, reason = _multiprocess_collectives_status()
    if ok:
        return
    skip = pytest.mark.skip(
        reason=f"CPU backend lacks multiprocess collectives in this "
               f"environment: {reason}")
    for it in marked:
        it.add_marker(skip)
