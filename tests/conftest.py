"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU build's analog of the reference's np=1,2,4,8 single-node
testing (SURVEY.md section 4): the same partitioned solve paths run over
XLA's host-platform device simulation so distributed code is exercised in
CI without TPU hardware.  float64 is enabled to match the reference's
strictly-FP64 semantics for correctness tests.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
