"""Partition-layer invariant tests (reference: graph.c:813-1452, halo.c:61-241).

These are the invariants SURVEY.md section 4 calls out as the test model:
interior/border/ghost counts sum correctly, halo plan send<->recv symmetry,
and distributed SpMV equals the serial SpMV.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from acg_tpu.graph import (comm_matrix, dsymv_dist_host, gather_vector,
                           halo_exchange_host, partition_graph_nodes,
                           partition_matrix, scatter_vector)
from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.partition import edgecut, partition_rows


@pytest.fixture(scope="module", params=[2, 3])
def problem(request):
    dim = request.param
    n = 12 if dim == 2 else 6
    A = SymCsrMatrix.from_mtx(poisson_mtx(n, dim=dim))
    return A.to_csr()


@pytest.mark.parametrize("nparts", [1, 2, 4, 7])
def test_partition_balance_and_cover(problem, nparts):
    part = partition_rows(problem, nparts, seed=1)
    n = problem.shape[0]
    assert part.size == n
    counts = np.bincount(part, minlength=nparts)
    assert counts.sum() == n
    assert counts.min() > 0
    # balance within 15% of ideal
    assert counts.max() <= 1.15 * np.ceil(n / nparts) + 1


def test_partition_quality_vs_random(problem):
    """Graph-growing bisection must beat a random partition's edge cut by a
    wide margin (the reason METIS exists)."""
    part = partition_rows(problem, 4, seed=0)
    rng = np.random.default_rng(0)
    rand = rng.integers(0, 4, problem.shape[0]).astype(np.int32)
    assert edgecut(problem, part) < 0.4 * edgecut(problem, rand)


@pytest.mark.parametrize("nparts", [2, 4, 8])
def test_subdomain_invariants(problem, nparts):
    part = partition_rows(problem, nparts, seed=2)
    subs = partition_graph_nodes(problem, part, nparts)
    n = problem.shape[0]

    # owned nodes tile the graph
    assert sum(s.nowned for s in subs) == n
    all_owned = np.concatenate([s.global_ids[:s.nowned] for s in subs])
    assert np.array_equal(np.sort(all_owned), np.arange(n))

    for s in subs:
        assert s.ninterior + s.nborder == s.nowned
        # ghosts are owned by other parts
        assert (part[s.global_ids[s.nowned:]] != s.part).all()
        assert (s.ghost_owner == part[s.global_ids[s.nowned:]]).all()
        # interior nodes have no neighbours outside the part
        indptr, indices = problem.indptr, problem.indices
        for u in s.global_ids[:s.ninterior]:
            nbr = indices[indptr[u]:indptr[u + 1]]
            assert (part[nbr] == s.part).all()
        # border nodes each have at least one external neighbour
        for u in s.global_ids[s.ninterior:s.nowned]:
            nbr = indices[indptr[u]:indptr[u + 1]]
            assert (part[nbr] != s.part).any()
        # send indices point at border region, recv at ghost region
        h = s.halo
        if h.send_idx.size:
            assert h.send_idx.min() >= s.ninterior
            assert h.send_idx.max() < s.nowned
        if h.recv_idx.size:
            assert h.recv_idx.min() >= s.nowned
        assert h.send_ptr[-1] == h.send_idx.size
        assert h.recv_ptr[-1] == h.recv_idx.size == s.nghost


@pytest.mark.parametrize("nparts", [2, 4, 8])
def test_halo_plan_symmetry(problem, nparts):
    """Send windows p->q must pair exactly with recv windows q<-p, in both
    count and global-id content (the halo plan agreement invariant)."""
    part = partition_rows(problem, nparts, seed=3)
    subs = partition_graph_nodes(problem, part, nparts)
    for s in subs:
        h = s.halo
        for j, q in enumerate(h.send_parts):
            sq = subs[int(q)]
            hq = sq.halo
            jq = list(hq.recv_parts).index(s.part)
            assert h.send_counts[j] == hq.recv_counts[jq]
            sent_globals = s.global_ids[h.send_idx[h.send_ptr[j]:h.send_ptr[j + 1]]]
            recv_globals = sq.global_ids[hq.recv_idx[hq.recv_ptr[jq]:hq.recv_ptr[jq + 1]]]
            np.testing.assert_array_equal(sent_globals, recv_globals)


def test_halo_exchange_delivers_ghosts(problem):
    nparts = 4
    part = partition_rows(problem, nparts, seed=4)
    subs = partition_graph_nodes(problem, part, nparts)
    n = problem.shape[0]
    xg = np.random.default_rng(5).standard_normal(n)
    xs = scatter_vector(subs, xg)
    halo_exchange_host(subs, xs)
    for s, x in zip(subs, xs):
        np.testing.assert_array_equal(x[s.nowned:], xg[s.global_ids[s.nowned:]])


@pytest.mark.parametrize("nparts", [1, 3, 8])
def test_distributed_spmv_matches_serial(problem, nparts):
    """The end-to-end oracle: partitioned halo+SpMV == serial SpMV
    (the acgsymcsrmatrix_dsymvmpi vs dsymv equivalence)."""
    part = partition_rows(problem, nparts, seed=6)
    subs = partition_matrix(problem, part, nparts)
    n = problem.shape[0]
    xg = np.random.default_rng(7).standard_normal(n)
    want = problem @ xg
    xs = scatter_vector(subs, xg)
    ys = dsymv_dist_host(subs, xs)
    got = gather_vector(subs, [np.concatenate([y, np.zeros(s.nghost)])
                               for s, y in zip(subs, ys)], n)
    np.testing.assert_allclose(got, want, rtol=1e-13, atol=1e-13)


def test_matrix_blocks_cover_all_entries(problem):
    nparts = 4
    part = partition_rows(problem, nparts, seed=8)
    subs = partition_matrix(problem, part, nparts)
    total = sum(s.A_local.nnz + s.A_ghost.nnz for s in subs)
    assert total == problem.nnz
    # off-diagonal blocks only touch border rows
    for s in subs:
        rows_with_ghost = np.flatnonzero(np.diff(s.A_ghost.indptr))
        if rows_with_ghost.size:
            assert rows_with_ghost.min() >= s.ninterior


def test_comm_matrix_symmetry(problem):
    nparts = 4
    part = partition_rows(problem, nparts, seed=9)
    subs = partition_graph_nodes(problem, part, nparts)
    M = comm_matrix(subs, nparts)
    # structure is symmetric (p sends to q iff q sends to p) though volumes
    # need not be: counts depend on each side's border width
    np.testing.assert_array_equal(M > 0, (M > 0).T)
    assert (np.diag(M) == 0).all()
    # total volume matches the halo plans
    assert M.sum() == sum(s.halo.total_send for s in subs)
    assert M.sum() == sum(s.halo.total_recv for s in subs)


def test_scatter_gather_roundtrip(problem):
    nparts = 5
    part = partition_rows(problem, nparts, seed=10)
    subs = partition_graph_nodes(problem, part, nparts)
    n = problem.shape[0]
    xg = np.random.default_rng(11).standard_normal(n)
    xs = scatter_vector(subs, xg)
    back = gather_vector(subs, xs, n)
    np.testing.assert_array_equal(back, xg)


def test_partition_vector_deterministic(problem):
    p1 = partition_rows(problem, 4, seed=42)
    p2 = partition_rows(problem, 4, seed=42)
    np.testing.assert_array_equal(p1, p2)


def test_disconnected_graph():
    """Two disjoint chains: partitioner must still cover every node."""
    n = 20
    diags = np.ones(n - 1)
    diags[n // 2 - 1] = 0  # break the chain in the middle
    A = sp.diags([diags, np.full(n, 4.0), diags], [-1, 0, 1]).tocsr()
    part = partition_rows(A, 4, seed=0)
    counts = np.bincount(part, minlength=4)
    assert counts.sum() == n and counts.min() > 0
    subs = partition_matrix(A, part, 4)
    xg = np.arange(n, dtype=float)
    ys = dsymv_dist_host(subs, scatter_vector(subs, xg))
    got = gather_vector(subs, [np.concatenate([y, np.zeros(s.nghost)])
                               for s, y in zip(subs, ys)], n)
    np.testing.assert_allclose(got, A @ xg)


# -- band partition + natural owned order (TPU DIA-friendly layout) ---------

@pytest.mark.parametrize("nparts", [2, 4, 8])
def test_band_partition_contiguous_balanced(problem, nparts):
    from acg_tpu.partition import partition_rows_band
    part = partition_rows_band(problem, nparts)
    n = problem.shape[0]
    counts = np.bincount(part, minlength=nparts)
    assert counts.sum() == n and counts.min() > 0
    # contiguity: part ids are non-decreasing over rows
    assert (np.diff(part) >= 0).all()
    # nnz balance within 30% of ideal (quantile cuts on cumulative nnz)
    nnz_per = np.bincount(part, weights=np.diff(problem.indptr),
                          minlength=nparts)
    assert nnz_per.max() <= 1.3 * problem.nnz / nparts + problem.nnz / n + 1


def test_band_partition_more_parts_than_rows():
    from acg_tpu.errors import AcgError
    from acg_tpu.partition import partition_rows_band
    A = SymCsrMatrix.from_mtx(poisson_mtx(2, dim=2))
    with pytest.raises(AcgError):
        partition_rows_band(A.to_csr(), 10)


@pytest.mark.parametrize("method", ["graph", "band"])
@pytest.mark.parametrize("nparts", [2, 4])
def test_reorder_owned_natural_preserves_semantics(problem, nparts, method):
    """After the natural reorder, owned global ids are ascending and the
    distributed host SpMV still equals the serial SpMV (halo plan, matrix
    blocks and scatter/gather all stay mutually consistent)."""
    from acg_tpu.graph import reorder_owned_natural
    part = partition_rows(problem, nparts, seed=3, method=method)
    subs = partition_matrix(problem, part, nparts)
    reorder_owned_natural(subs)
    n = problem.shape[0]
    for s in subs:
        owned = s.global_ids[: s.nowned]
        assert (np.diff(owned) > 0).all()
        assert s.owned_order == "natural"
    rng = np.random.default_rng(7)
    xg = rng.standard_normal(n)
    xs = scatter_vector(subs, xg)
    ys = dsymv_dist_host(subs, xs)
    y = gather_vector(subs, ys, n)
    assert np.allclose(y, problem @ xg, rtol=1e-12, atol=1e-12)


def test_reorder_owned_natural_idempotent(problem):
    from acg_tpu.graph import reorder_owned_natural
    part = partition_rows(problem, 4, seed=3)
    subs = partition_matrix(problem, part, 4)
    reorder_owned_natural(subs)
    ids = [s.global_ids.copy() for s in subs]
    sidx = [s.halo.send_idx.copy() for s in subs]
    reorder_owned_natural(subs)
    for s, i0, x0 in zip(subs, ids, sidx):
        assert (s.global_ids == i0).all()
        assert (s.halo.send_idx == x0).all()


def test_band_partition_concentrated_nnz_keeps_parts_nonempty():
    """Equal quantile cuts (nnz concentrated in one row) must not collapse
    into an empty part."""
    from acg_tpu.partition import partition_rows_band
    rows = [4] * 50 + list(range(10))
    cols = list(np.random.default_rng(0).integers(0, 10, 50)) + list(range(10))
    A = sp.coo_matrix((np.ones(len(rows)), (rows, cols)),
                      shape=(10, 10)).tocsr()
    for nparts in (2, 3, 5, 10):
        part = partition_rows_band(A, nparts)
        counts = np.bincount(part, minlength=nparts)
        assert counts.min() > 0
        assert (np.diff(part) >= 0).all()


# -- nested dissection (metis.h:249-263 role) -------------------------------

def _fill_nnz(csr, perm=None):
    """nnz(L+U) of an LU factorisation with a fixed (given) ordering."""
    import scipy.sparse.linalg as spla
    A = csr if perm is None else csr[perm][:, perm]
    lu = spla.splu(A.tocsc(), permc_spec="NATURAL",
                   options={"SymmetricMode": True})
    return lu.L.nnz + lu.U.nnz


def test_nested_dissection_valid_permutation():
    from acg_tpu.partition import nested_dissection
    A = SymCsrMatrix.from_mtx(poisson_mtx(16, dim=2)).to_csr()
    perm, iperm = nested_dissection(A, seed=0, use_metis="never")
    n = A.shape[0]
    assert np.array_equal(np.sort(perm), np.arange(n))
    assert np.array_equal(perm[iperm], np.arange(n))
    assert np.array_equal(iperm[perm], np.arange(n))


def test_nested_dissection_reduces_fill():
    """The point of the ordering: Cholesky/LU fill on a 2D grid should be
    well below natural (banded) ordering fill."""
    from acg_tpu.partition import nested_dissection
    A = SymCsrMatrix.from_mtx(poisson_mtx(24, dim=2)).to_csr()
    perm, _ = nested_dissection(A, seed=0, use_metis="never")
    assert _fill_nnz(A, perm) < 0.8 * _fill_nnz(A)


def test_nested_dissection_leaf_only():
    """Graphs at or below leaf_size come back as one identity-like leaf."""
    from acg_tpu.partition import nested_dissection
    A = SymCsrMatrix.from_mtx(poisson_mtx(4, dim=2)).to_csr()
    perm, iperm = nested_dissection(A, use_metis="never", leaf_size=100)
    assert np.array_equal(np.sort(perm), np.arange(A.shape[0]))


def test_nested_dissection_require_metis_errors_without_lib():
    from acg_tpu.errors import AcgError
    from acg_tpu.partition import metis_available, nested_dissection
    A = SymCsrMatrix.from_mtx(poisson_mtx(4, dim=2)).to_csr()
    if metis_available():
        perm, iperm = nested_dissection(A, use_metis="require")
        assert np.array_equal(np.sort(perm), np.arange(A.shape[0]))
    else:
        with pytest.raises(AcgError):
            nested_dissection(A, use_metis="require")


@pytest.mark.parametrize("variant", ["kway", "recursive"])
def test_partition_rows_variant_plumbing(problem, variant):
    """Both METIS variants are accepted; without libmetis they share the
    built-in recursive-bisection fallback and must agree."""
    part = partition_rows(problem, 4, seed=1, variant=variant)
    counts = np.bincount(part, minlength=4)
    assert counts.sum() == problem.shape[0] and counts.min() > 0


def test_metis_partgraphsym_rejects_bad_variant():
    from acg_tpu.errors import AcgError
    with pytest.raises(AcgError):
        from acg_tpu.partition import metis_partgraphsym
        metis_partgraphsym(np.array([0, 0]), np.array([], dtype=np.int64),
                           1, variant="bogus")
