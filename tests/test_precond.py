"""Preconditioning subsystem (acg_tpu.precond): apply-level unit tests
against scipy references, SPD preservation, Chebyshev spectral-estimate
bounds, single-device <-> 8-part dist parity, the anisotropic-Poisson
acceptance criterion (>= 2x iteration reduction for jacobi and cheby:4),
and restart-after-breakdown with preconditioner state rebuild."""

import numpy as np
import pytest
import scipy.linalg as sla
import scipy.sparse as sp

import jax.numpy as jnp

from acg_tpu import faults, precond
from acg_tpu.io.generators import aniso_poisson2d_coo, poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import device_matrix_from_csr, matrix_diagonal, spmv
from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
from acg_tpu.partition import partition_rows
from acg_tpu.solvers.host_cg import HostCGSolver
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.resilience import RecoveryPolicy
from acg_tpu.solvers.stats import StoppingCriteria


def _csr(n=12, aniso=None):
    if aniso is None:
        r, c, v, N = poisson2d_coo(n)
    else:
        r, c, v, N = aniso_poisson2d_coo(n, aniso)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


# -- spec parsing ---------------------------------------------------------

def test_parse_precond():
    assert precond.parse_precond(None) is None
    assert precond.parse_precond("none") is None
    assert precond.parse_precond("jacobi").kind == "jacobi"
    s = precond.parse_precond("bjacobi:8")
    assert (s.kind, s.block) == ("bjacobi", 8)
    assert precond.parse_precond("bjacobi").block == precond.DEFAULT_BLOCK
    s = precond.parse_precond("cheby:4")
    assert (s.kind, s.degree) == ("cheby", 4)
    assert str(s) == "cheby:4"
    for bad in ("chebyshev", "cheby", "cheby:x", "cheby:0", "jacobi:3",
                "bjacobi:0", "bjacobi:9999", "nope"):
        with pytest.raises(ValueError):
            precond.parse_precond(bad)


# -- apply-level unit tests vs the scipy reference ------------------------

def test_matrix_diagonal_all_formats():
    csr = _csr(7, aniso=0.1)
    want = csr.diagonal()
    for fmt in ("dia", "ell", "coo", "bell"):
        A = device_matrix_from_csr(csr, dtype=jnp.float64, format=fmt)
        got = np.asarray(matrix_diagonal(A))
        np.testing.assert_allclose(got, want, rtol=1e-14,
                                   err_msg=fmt)


@pytest.mark.parametrize("kind", ["jacobi", "bjacobi:8", "cheby:3"])
@pytest.mark.parametrize("fmt", ["dia", "ell"])
def test_device_apply_matches_host_reference(kind, fmt):
    """The traced device apply must agree with the eager numpy/scipy
    twin (HostPrecond) on the same matrix and vector."""
    csr = _csr(9, aniso=0.2)
    n = csr.shape[0]
    spec = precond.parse_precond(kind)
    A = device_matrix_from_csr(csr, dtype=jnp.float64, format=fmt)
    mstate = precond.setup_single(spec, A, spmv, jnp.float64)
    apply_fn = precond.make_apply(spec, spmv)
    rng = np.random.default_rng(3)
    r = rng.standard_normal(n)
    z_dev = np.asarray(apply_fn(mstate, A, jnp.asarray(r)))

    host = precond.HostPrecond(spec, csr)
    if spec.kind == "cheby":
        # pin the host twin to the device interval so the polynomials
        # are identical (their lambda estimates differ by rng stream)
        host.state = (float(mstate[0]), float(mstate[1]))
    z_host = host.apply(r)
    np.testing.assert_allclose(z_dev, z_host, rtol=1e-10, atol=1e-12)


def test_bjacobi_apply_vs_scipy_cho_solve():
    """Block solves agree with an explicit scipy cho_solve over the
    dense diagonal blocks (including the ragged final block)."""
    csr = _csr(5, aniso=0.3)      # n = 25, bs = 8 -> ragged last block
    n = csr.shape[0]
    bs = 8
    spec = precond.parse_precond(f"bjacobi:{bs}")
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    mstate = precond.setup_single(spec, A, spmv, jnp.float64)
    apply_fn = precond.make_apply(spec, spmv)
    rng = np.random.default_rng(0)
    r = rng.standard_normal(n)
    z = np.asarray(apply_fn(mstate, A, jnp.asarray(r)))
    dense = csr.toarray()
    want = np.zeros(n)
    for lo in range(0, n, bs):
        hi = min(lo + bs, n)
        blk = dense[lo:hi, lo:hi]
        want[lo:hi] = sla.cho_solve((sla.cholesky(blk, lower=True), True),
                                    r[lo:hi])
    np.testing.assert_allclose(z, want, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("kind", ["jacobi", "bjacobi:4", "cheby:3"])
def test_spd_preservation(kind):
    """M^-1 (the operator the applies implement) must be symmetric
    positive definite -- PCG's correctness precondition."""
    csr = _csr(4, aniso=0.2)      # n = 16: dense operator is cheap
    n = csr.shape[0]
    spec = precond.parse_precond(kind)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    mstate = precond.setup_single(spec, A, spmv, jnp.float64)
    apply_fn = precond.make_apply(spec, spmv)
    M = np.column_stack([
        np.asarray(apply_fn(mstate, A, jnp.asarray(e)))
        for e in np.eye(n)])
    np.testing.assert_allclose(M, M.T, rtol=1e-10, atol=1e-12)
    assert np.linalg.eigvalsh(M).min() > 0


def test_cheby_lambda_estimate_bounds():
    """The power-iteration lambda_max lands inside a known band: it can
    only UNDERestimate the true largest eigenvalue, and 24 iterations
    from a random start get well past 70% of it; the state builder's
    interval then pads by CHEBY_SAFETY."""
    csr = _csr(24)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    est = float(precond.estimate_lmax(spmv, A, A.nrows, jnp.float64))
    true = float(sp.linalg.eigsh(csr, k=1, which="LA",
                                 return_eigenvectors=False)[0])
    assert 0.7 * true <= est <= true * (1 + 1e-9)
    lmin, lmax = precond.cheby_state(est, jnp.float64)
    assert float(lmax) == pytest.approx(est * precond.CHEBY_SAFETY)
    assert float(lmin) == pytest.approx(float(lmax) / precond.CHEBY_RATIO)


# -- the anisotropic generator -------------------------------------------

def test_aniso_generator_spd_and_limits():
    r, c, v, N = aniso_poisson2d_coo(10, 0.05)
    A = sp.csr_matrix((v, (r, c)), shape=(N, N))
    assert abs(A - A.T).max() < 1e-14
    assert float(sp.linalg.eigsh(A, k=1, which="SA",
                                 return_eigenvectors=False)[0]) > 0
    # the diagonal VARIES (the property that makes Jacobi non-trivial
    # here, unlike the constant-diagonal uniform stencil)
    d = A.diagonal()
    assert d.max() / d.min() > 5.0
    # eps = 1 degenerates to the uniform 5-point Poisson matrix
    r1, c1, v1, _ = aniso_poisson2d_coo(10, 1.0)
    r0, c0, v0, _ = poisson2d_coo(10)
    A1 = sp.csr_matrix((v1, (r1, c1)), shape=(N, N))
    A0 = sp.csr_matrix((v0, (r0, c0)), shape=(N, N))
    assert abs(A1 - A0).max() < 1e-12
    with pytest.raises(ValueError):
        aniso_poisson2d_coo(10, 0.0)


# -- solver integration: parity and acceptance ---------------------------

@pytest.fixture(scope="module")
def aniso256():
    return _csr(256, aniso=0.01)


@pytest.mark.parametrize("kind", ["jacobi", "cheby:4"])
def test_acceptance_2x_single_device(aniso256, kind):
    """The PR's acceptance bullet, single-device half: on the
    anisotropic generator (eps = 0.01, n = 256^2), jacobi and cheby:4
    each cut iterations-to-tolerance by >= 2x vs unpreconditioned CG."""
    csr = aniso256
    b = np.ones(csr.shape[0])
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A, precond=kind)
    s.solve(b, criteria=StoppingCriteria(maxits=2500, residual_rtol=1e-6))
    it_pc = s.stats.niterations
    assert s.stats.converged
    # the >= 2x claim without paying for the full unpreconditioned
    # solve: at TWICE the preconditioned count, plain CG is still short
    cap = 2 * it_pc + 1
    s0 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64))
    s0.solve(b, criteria=StoppingCriteria(maxits=cap, residual_rtol=1e-6),
             raise_on_divergence=False)
    assert not s0.stats.converged, (it_pc, s0.stats.niterations)


def test_acceptance_2x_dist_8(aniso256):
    """The acceptance bullet's 8-device half (jacobi; cheby covered by
    the parity test below): >= 2x on the dist tier too."""
    csr = aniso256
    b = np.ones(csr.shape[0])
    part = partition_rows(csr, 8, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s = DistCGSolver(prob, precond="jacobi")
    s.solve(b, criteria=StoppingCriteria(maxits=2500, residual_rtol=1e-6))
    it_pc = s.stats.niterations
    assert s.stats.converged
    prob0 = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s0 = DistCGSolver(prob0)
    s0.solve(b, criteria=StoppingCriteria(maxits=2 * it_pc + 1,
                                          residual_rtol=1e-6),
             raise_on_divergence=False)
    assert not s0.stats.converged


@pytest.mark.parametrize("kind", ["jacobi", "cheby:4"])
@pytest.mark.parametrize("pipelined", [False, True])
def test_dist_parity_with_single_device(kind, pipelined):
    """8-part mesh PCG matches the single-device tier: same iteration
    count (+- a rounding iteration) and the same solution."""
    csr = _csr(48, aniso=0.05)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-7)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s1 = JaxCGSolver(A, pipelined=pipelined, precond=kind)
    x1 = s1.solve(b, criteria=crit)
    part = partition_rows(csr, 8, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s8 = DistCGSolver(prob, pipelined=pipelined, precond=kind)
    x8 = s8.solve(b, criteria=crit)
    assert abs(s1.stats.niterations - s8.stats.niterations) <= 2
    np.testing.assert_allclose(x8, x1, rtol=1e-5, atol=1e-8)


def test_bjacobi_dist_blocks_are_local(monkeypatch):
    """Dist block-Jacobi factors each part's LOCAL diagonal block:
    converges to the right answer on the 8-part mesh (block content
    differs from the single-device factorization by construction)."""
    csr = _csr(32, aniso=0.05)
    rng = np.random.default_rng(1)
    xsol = rng.standard_normal(csr.shape[0])
    b = csr @ xsol
    part = partition_rows(csr, 8, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 8, dtype=jnp.float64)
    s = DistCGSolver(prob, precond="bjacobi:16")
    x = s.solve(b, criteria=StoppingCriteria(maxits=4000,
                                             residual_rtol=1e-9))
    np.testing.assert_allclose(x, xsol, rtol=1e-6, atol=1e-7)


def test_host_pcg_matches_device_iterations():
    """The eager host PCG is the device loop's oracle: identical
    update order -> identical iteration counts on f64."""
    csr = _csr(24, aniso=0.05)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-8)
    for kind in ("jacobi", "bjacobi:16", "cheby:3"):
        hs = HostCGSolver(csr, precond=kind)
        hs.solve(b, criteria=crit)
        A = device_matrix_from_csr(csr, dtype=jnp.float64)
        ds = JaxCGSolver(A, precond=kind)
        ds.solve(b, criteria=crit)
        assert abs(hs.stats.niterations - ds.stats.niterations) <= 1, kind
        assert hs.stats.ops["precond"].n > 0
        if hs.stats.niterations == ds.stats.niterations:
            # host and device bill the SAME op census (cheby counts
            # its degree-many SpMVs per apply on both)
            assert hs.stats.ops["precond"].n == \
                ds.stats.ops["precond"].n, kind


# -- stats / accounting ---------------------------------------------------

def test_precond_op_counter_and_section():
    csr = _csr(16, aniso=0.1)
    b = np.ones(csr.shape[0])
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A, precond="cheby:2")
    s.solve(b, criteria=StoppingCriteria(maxits=500, residual_rtol=1e-7))
    st = s.stats
    nappl = st.niterations + 1
    # cheby bills degree-many SpMVs per apply (the satellite's contract)
    assert st.ops["precond"].n == 2 * nappl
    assert st.ops["precond"].bytes > 0
    assert st.precond["kind"] == "cheby:2"
    assert st.precond["applies"] == nappl
    assert st.precond["lambda_max"] > st.precond["lambda_min"] > 0
    # the section renders (append-only) and round-trips the JSON twin
    txt = st.fwrite()
    assert "precond:" in txt and "  precond:" in txt
    assert st.to_dict()["precond"]["applies"] == nappl
    # ... and an UNpreconditioned report still has no precond row at all
    s0 = JaxCGSolver(device_matrix_from_csr(csr, dtype=jnp.float64))
    s0.solve(b, criteria=StoppingCriteria(maxits=500,
                                          residual_rtol=1e-7))
    assert "precond" not in s0.stats.fwrite()


def test_comm_profile_reclassifies_for_precond():
    csr = _csr(16)
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    base = DistCGSolver(prob).comm_profile()
    led = DistCGSolver(prob, precond="cheby:3").comm_profile()
    assert led["halo_exchanges_per_iteration"] == 4   # 1 + degree
    assert led["halo_bytes_per_iteration"] == \
        4 * base["halo_bytes_per_iteration"]
    assert led["precond"]["kind"] == "cheby:3"
    ledj = DistCGSolver(prob, precond="jacobi").comm_profile()
    # jacobi moves NOTHING extra -- the whole point
    assert ledj["halo_bytes_per_iteration"] == \
        base["halo_bytes_per_iteration"]
    assert ledj["allreduce_per_iteration"] == 2
    # classic PCG moves 3 scalars per iteration total (1 + the fused
    # 2): bytes bill the TOTAL, not reductions x widest payload
    assert ledj["allreduce_bytes_per_iteration"] == 3 * 8
    ledp = DistCGSolver(prob, pipelined=True,
                        precond="jacobi").comm_profile()
    assert ledp["allreduce_per_iteration"] == 1
    assert ledp["allreduce_bytes_per_iteration"] == 3 * 8


def test_host_device_precond_trace_parity():
    """The eager recorder's rnrm2 slot carries the PRECONDITIONED norm
    under precond, exactly like the compiled rings (the eager-twin
    contract the telemetry tier documents)."""
    csr = _csr(16, aniso=0.1)
    b = np.ones(csr.shape[0])
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-7)
    hs = HostCGSolver(csr, precond="jacobi", trace=64)
    hs.solve(b, criteria=crit)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    ds = JaxCGSolver(A, precond="jacobi", trace=64)
    ds.solve(b, criteria=crit)
    m = min(hs.last_trace.records.shape[0], ds.last_trace.records.shape[0])
    np.testing.assert_allclose(hs.last_trace.records[:m, 0],
                               ds.last_trace.records[:m, 0],
                               rtol=1e-6)


# -- faults + resilience --------------------------------------------------

def test_precond_fault_refused_without_precond():
    csr = _csr(8)
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A)
    with faults.injected("precond:nan@2"):
        with pytest.raises(Exception, match="armed preconditioner"):
            s.solve(np.ones(csr.shape[0]),
                    criteria=StoppingCriteria(maxits=50,
                                              residual_rtol=1e-6))


@pytest.mark.parametrize("pipelined", [False, True])
def test_precond_fault_triggers_recovery(pipelined):
    """A poisoned z = M^-1 r drives (r, z) non-finite: the breakdown
    path fires, the restart (fault consumed) converges, and the state
    is PRESERVED (it was never corrupted)."""
    csr = _csr(16, aniso=0.1)
    b = np.ones(csr.shape[0])
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A, pipelined=pipelined, precond="jacobi",
                    recovery=RecoveryPolicy(max_restarts=2))
    with faults.injected("precond:nan@3"):
        s.solve(b, criteria=StoppingCriteria(maxits=1000,
                                             residual_rtol=1e-7))
    st = s.stats
    assert st.converged
    assert st.nbreakdowns >= 1 and st.nrestarts >= 1
    assert any("preserved across restart" in ev for ev in st.recovery_log)


def test_restart_rebuilds_poisoned_state():
    """The state-rebuild rung: a non-finite preconditioner state (here
    poisoned by hand) breaks the first attempt down at setup; recovery
    detects the non-finite state, rebuilds it from the matrix, and the
    restart converges."""
    csr = _csr(16, aniso=0.1)
    b = np.ones(csr.shape[0])
    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    s = JaxCGSolver(A, precond="jacobi",
                    recovery=RecoveryPolicy(max_restarts=2))
    s._ensure_precond_state()
    s._mstate = (s._mstate[0].at[0].set(jnp.nan),)
    s.solve(b, criteria=StoppingCriteria(maxits=1000,
                                         residual_rtol=1e-7))
    st = s.stats
    assert st.converged
    assert st.nrestarts >= 1
    assert any("rebuilt from the matrix" in ev for ev in st.recovery_log)
    assert precond.state_finite(s._mstate)


def test_dist_precond_fault_and_recovery():
    csr = _csr(16, aniso=0.1)
    b = np.ones(csr.shape[0])
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    s = DistCGSolver(prob, precond="jacobi",
                     recovery=RecoveryPolicy(max_restarts=2))
    with faults.injected("precond:inf@2"):
        s.solve(b, criteria=StoppingCriteria(maxits=1000,
                                             residual_rtol=1e-7))
    assert s.stats.converged
    assert s.stats.nbreakdowns >= 1 and s.stats.nrestarts >= 1


def test_host_pcg_restart_rebuild():
    csr = _csr(12, aniso=0.1)
    b = np.ones(csr.shape[0])
    s = HostCGSolver(csr, precond="jacobi",
                     recovery=RecoveryPolicy(max_restarts=2))
    with faults.injected("precond:nan@2"):
        s.solve(b, criteria=StoppingCriteria(maxits=1000,
                                             residual_rtol=1e-7))
    assert s.stats.converged
    assert s.stats.nrestarts >= 1


# -- configuration refusals ----------------------------------------------

def test_precond_config_refusals():
    csr = _csr(8)
    A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="replace_every"):
        JaxCGSolver(A, precond="jacobi", replace_every=10)
    part = partition_rows(csr, 2, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 2, dtype=jnp.bfloat16,
                                    vector_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="replace_every"):
        DistCGSolver(prob, precond="jacobi", replace_every=10)


# -- bench-diff case keys -------------------------------------------------

def test_precond_joins_the_case_key():
    from acg_tpu.perfmodel import _doc_case, _row_case

    row = {"metric": "m", "value": 5.0}
    assert _row_case(row)[0] == "m"
    assert _row_case({**row, "precond": "cheby:4"})[0] == \
        "m|precond=cheby:4"
    doc = {"manifest": {"metric": "m", "precond": "jacobi"},
           "stats": {"tsolve": 1.0, "niterations": 10}}
    assert _doc_case(doc)[0] == "m|precond=jacobi"
    doc["manifest"].pop("precond")
    assert _doc_case(doc)[0] == "m"
