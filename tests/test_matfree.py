"""Matrix-free operator tier (ISSUE 15, acg_tpu.ops.operator).

The contract under test: ``A`` as a jitted apply rides EVERY solver
tier through the ops.spmv dispatch with trajectories BITWISE-equal to
the assembled-DIA tier of the same system -- classic/pipelined, the CA
recurrences, precond (jacobi via the analytic diagonal, cheby via
applies), ABFT (checksum through the apply), the batched multi-RHS
tier, and the distributed mesh (generated local planes behind the
existing halo/ghost machinery, incl. the fused interior|border split
and the one-sided DMA transport).  Refusals are typed and
self-describing (the could-never-fire discipline).
"""

import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.errors import AcgError
from acg_tpu.io.generators import (aniso_poisson2d_coo, poisson2d_coo,
                                   poisson3d_coo)
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.operator import (aniso2d_stencil, build_operator,
                                  parse_operator_spec, poisson_stencil,
                                  register_operator, user_operator)
from acg_tpu.ops.spmv import dia_from_csr, matrix_diagonal, spmv
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


def _poisson1d_csr(n):
    idx = np.arange(n)
    r = np.concatenate([idx, idx[1:], idx[:-1]])
    c = np.concatenate([idx, idx[:-1], idx[1:]])
    v = np.concatenate([np.full(n, 2.0), np.full(2 * (n - 1), -1.0)])
    return SymCsrMatrix.from_coo(n, r, c, v).to_csr()


@pytest.fixture(scope="module")
def aniso_pair():
    """(csr, assembled DIA, operator) of the variable-coefficient
    family -- the stencil whose tables exercise the pre-rounding
    contract."""
    n, eps = 16, 0.1
    r, c, v, N = aniso_poisson2d_coo(n, eps)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    return csr, dia_from_csr(csr, dtype=jnp.float64), \
        aniso2d_stencil(n, eps, dtype=jnp.float64)


# -- apply / diagonal parity ----------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_apply_bitwise_parity_per_stencil(dtype):
    """Every built-in stencil's generated apply equals the assembled
    DIA SpMV BITWISE (same values, same dia_mv accumulation), and the
    analytic diagonal/nnz match the assembled extraction exactly."""
    cases = []
    for dim, n in ((1, 15), (2, 11), (3, 5)):
        if dim == 1:
            csr = _poisson1d_csr(n)
        else:
            gen = poisson2d_coo if dim == 2 else poisson3d_coo
            r, c, v, N = gen(n)
            csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
        cases.append((csr, poisson_stencil(n, dim, dtype=dtype)))
    r, c, v, N = aniso_poisson2d_coo(11, 0.07)
    cases.append((SymCsrMatrix.from_coo(N, r, c, v).to_csr(),
                  aniso2d_stencil(11, 0.07, dtype=dtype)))
    rng = np.random.default_rng(0)
    for csr, op in cases:
        A = dia_from_csr(csr, dtype=dtype)
        assert op.offsets == A.offsets
        x = jnp.asarray(rng.standard_normal(csr.shape[0]), dtype)
        assert np.array_equal(np.asarray(spmv(A, x)),
                              np.asarray(spmv(op, x)))
        assert np.array_equal(np.asarray(matrix_diagonal(A)),
                              np.asarray(matrix_diagonal(op)))
        assert int(op.matfree_nnz()) == csr.nnz


# -- single-device solver tiers -------------------------------------------

@pytest.mark.parametrize("kw", [dict(), dict(algorithm="sstep:4"),
                                dict(precond="jacobi")])
def test_solver_trajectory_parity_bitwise(aniso_pair, kw):
    """Tiers whose applies consume/produce LOOP-CARRIED state --
    classic (the headline bench protocol), s-step, jacobi PCG --
    produce BITWISE-identical iterates matrix-free vs assembled: the
    structured apply's per-element products equal the assembled
    plane products, and nothing fuses across the apply boundary."""
    _, A, op = aniso_pair
    b = np.random.default_rng(0).standard_normal(A.nrows)
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-9)
    sa = JaxCGSolver(A, kernels="xla", **kw)
    sm = JaxCGSolver(op, kernels="xla", **kw)
    xa = sa.solve(b, criteria=crit)
    xm = sm.solve(b, criteria=crit)
    assert sa.stats.niterations == sm.stats.niterations
    assert np.array_equal(np.asarray(xa), np.asarray(xm))


@pytest.mark.parametrize("kw", [dict(pipelined=True),
                                dict(algorithm="pipelined:2"),
                                dict(precond="cheby:2")])
def test_solver_trajectory_parity_chained(aniso_pair, kw):
    """Tiers that CHAIN applies inside one fused region (the pipelined
    setup's w = A(b - A x0), cheby's K-apply polynomial) let XLA
    contract the fused multiply-adds differently than the assembled
    build: per apply the structured form is bitwise-equal (pinned in
    test_apply_bitwise_parity_per_stencil), in-program the
    trajectories agree to FMA reassociation -- solutions match to
    ~1e-8 relative and iteration counts within the rounding jitter
    any ulp perturbation produces near the tolerance."""
    _, A, op = aniso_pair
    b = np.random.default_rng(0).standard_normal(A.nrows)
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-9)
    sa = JaxCGSolver(A, kernels="xla", **kw)
    sm = JaxCGSolver(op, kernels="xla", **kw)
    xa = sa.solve(b, criteria=crit)
    xm = sm.solve(b, criteria=crit)
    assert abs(sa.stats.niterations - sm.stats.niterations) <= 3
    np.testing.assert_allclose(np.asarray(xm), np.asarray(xa),
                               rtol=1e-7, atol=1e-9)


def test_abft_and_health_through_apply(aniso_pair):
    """The health tier's true-residual audit AND the Huang-Abraham ABFT
    checksum (c = A^T 1 computed through the apply at setup) run
    matrix-free: audits fire, checks count, the solve converges to the
    assembled answer (the setup checksum chains an apply into the
    fused setup region, so this is the FMA-equivalence contract)."""
    from acg_tpu.health import make_spec
    _, A, op = aniso_pair
    b = np.random.default_rng(1).standard_normal(A.nrows)
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-9)
    hs = make_spec(every=7, abft=True)
    sa = JaxCGSolver(A, kernels="xla", health=hs)
    sm = JaxCGSolver(op, kernels="xla", health=hs)
    xa = sa.solve(b, criteria=crit)
    xm = sm.solve(b, criteria=crit)
    np.testing.assert_allclose(np.asarray(xm), np.asarray(xa),
                               rtol=1e-7, atol=1e-9)
    assert sm.stats.health["naudits"] > 0
    assert sm.stats.health["abft"]["nchecks"] > 0


def test_bjacobi_refuses_matfree(aniso_pair):
    """bjacobi factors stored blocks; an armed spec over an operator
    refuses self-describingly at state setup."""
    _, _, op = aniso_pair
    s = JaxCGSolver(op, kernels="xla", precond="bjacobi:8")
    with pytest.raises(AcgError, match="bjacobi"):
        s.solve(np.ones(op.nrows),
                criteria=StoppingCriteria(maxits=5),
                raise_on_divergence=False)


def test_bf16_vectors_refuse_matfree(aniso_pair):
    _, _, op = aniso_pair
    with pytest.raises(ValueError, match="bf16"):
        JaxCGSolver(op, kernels="xla", vector_dtype=jnp.bfloat16)


def test_batched_matfree_parity(aniso_pair):
    """The batched multi-RHS tier rides the operator's multi-column
    apply: per-column results bitwise-equal to the assembled batched
    solve."""
    from acg_tpu.solvers.batched import BatchedCGSolver
    _, A, op = aniso_pair
    n = A.nrows
    B = np.random.default_rng(2).standard_normal((n, 3))
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-9)
    sa = BatchedCGSolver(A)
    sm = BatchedCGSolver(op)
    xa = sa.solve(B, criteria=crit)
    xm = sm.solve(B, criteria=crit)
    assert np.array_equal(np.asarray(xa), np.asarray(xm))


# -- user-operator registration hook --------------------------------------

def test_user_operator_registration():
    """A registered jitted operator solves through every hook: apply in
    the loop, diagonal_fn arming jacobi; registration is validated."""
    n = 64
    d = np.linspace(1.0, 4.0, n)

    register_operator(
        "testdiag",
        lambda caps, x: caps[0] * x,
        diagonal_fn=lambda caps: caps[0],
        nnz=n)
    op = user_operator("testdiag", n, dtype=jnp.float64,
                       captures=(jnp.asarray(d),))
    b = np.random.default_rng(3).standard_normal(n)
    s = JaxCGSolver(op, kernels="xla", precond="jacobi")
    x = s.solve(b, criteria=StoppingCriteria(maxits=200,
                                             residual_rtol=1e-12))
    assert np.allclose(np.asarray(x), b / d, rtol=1e-10)

    register_operator("testdiag_nodiag", lambda caps, x: caps[0] * x)
    op2 = user_operator("testdiag_nodiag", n, dtype=jnp.float64,
                        captures=(jnp.asarray(d),))
    s2 = JaxCGSolver(op2, kernels="xla", precond="jacobi")
    with pytest.raises(AcgError, match="diagonal_fn"):
        s2.solve(b, criteria=StoppingCriteria(maxits=5),
                 raise_on_divergence=False)

    with pytest.raises(AcgError, match="not registered"):
        user_operator("no_such_operator", n)
    with pytest.raises(ValueError, match="callable"):
        register_operator("bad", "not-a-function")


# -- the Pallas stencil path ----------------------------------------------

def test_pallas_stencil_kernel_interpret():
    """The in-kernel-generated stencil SpMV (interpret mode) matches
    the XLA matfree apply -- bitwise on the 1D/2D shapes, to FMA
    reassociation (1 ulp) on 3D -- and degrades to the XLA apply off
    the supported route."""
    from acg_tpu.ops.pallas_kernels import stencil_spmv
    rng = np.random.default_rng(0)
    for dim, n, tile in ((1, 512, 128), (2, 32, 256)):
        op = poisson_stencil(n, dim, dtype=jnp.float32)
        x = jnp.asarray(rng.standard_normal(n ** dim), jnp.float32)
        y = stencil_spmv(op, x, interpret=True, tile=tile, align=8)
        assert np.array_equal(np.asarray(y),
                              np.asarray(op.matfree_apply(x)))
    op = poisson_stencil(8, 3, dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal(512), jnp.float32)
    y = stencil_spmv(op, x, interpret=True, tile=128, align=8)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(op.matfree_apply(x)),
                               rtol=2e-6, atol=2e-6)
    # ragged shape: no route -> the operator's own XLA apply
    op = poisson_stencil(10, 2, dtype=jnp.float32)
    x = jnp.ones(100, jnp.float32)
    assert np.array_equal(np.asarray(stencil_spmv(op, x, interpret=True)),
                          np.asarray(op.matfree_apply(x)))


def test_pallas_kernels_solver_route(aniso_pair):
    """kernels='pallas-interpret' over an operator dispatches the
    stencil kernel for const-Poisson and falls back to the XLA apply
    for kinds without one -- both converge to the assembled answer."""
    n = 16
    r, c, v, N = poisson2d_coo(n)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    op = poisson_stencil(n, 2, dtype=jnp.float64)
    b = np.random.default_rng(4).standard_normal(N)
    crit = StoppingCriteria(maxits=400, residual_rtol=1e-10)
    x_ref = JaxCGSolver(dia_from_csr(csr, dtype=jnp.float64),
                        kernels="xla").solve(b, criteria=crit)
    x_pal = JaxCGSolver(op, kernels="pallas").solve(b, criteria=crit)
    np.testing.assert_allclose(np.asarray(x_pal), np.asarray(x_ref),
                               rtol=1e-8, atol=1e-8)


# -- distributed tier ------------------------------------------------------

def _dist_pair(csr, op, nparts=4, **kw):
    from acg_tpu.parallel.dist import (DistCGSolver, DistributedProblem,
                                       arm_matfree)
    from acg_tpu.partition import partition_rows
    part = partition_rows(csr, nparts, seed=0, method="band")
    pa = DistributedProblem.build(csr, part, nparts, dtype=jnp.float64)
    pm = DistributedProblem.build(csr, part, nparts, dtype=jnp.float64)
    arm_matfree(pm, op)
    return DistCGSolver(pa, **kw), DistCGSolver(pm, **kw)


@pytest.mark.parametrize("kw", [dict(), dict(pipelined=True),
                                dict(kernels="fused"),
                                dict(kernels="fused", pipelined=True),
                                dict(comm="dma"),
                                dict(precond="jacobi")])
def test_dist_matfree_parity(aniso_pair, kw):
    """The armed matfree local block is bitwise-equal to the assembled
    stacked DIA planes across the dist tiers: classic/pipelined, the
    fused interior|border OVERLAPPED split applied to the stencil
    apply, the one-sided DMA transport, and stacked-jacobi PCG."""
    csr, _, op = aniso_pair
    b = np.random.default_rng(5).standard_normal(csr.shape[0])
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-9)
    sa, sm = _dist_pair(csr, op, **kw)
    xa = sa.solve(b, criteria=crit)
    xm = sm.solve(b, criteria=crit)
    assert sa.stats.niterations == sm.stats.niterations
    assert np.array_equal(np.asarray(xa), np.asarray(xm))


def test_dist_matfree_matches_single(aniso_pair):
    """Single-device matfree and 4-part matfree agree (the dist solve
    reassembles to the same answer at tolerance)."""
    csr, _, op = aniso_pair
    b = np.random.default_rng(6).standard_normal(csr.shape[0])
    crit = StoppingCriteria(maxits=600, residual_rtol=1e-10)
    x1 = JaxCGSolver(op, kernels="xla").solve(b, criteria=crit)
    _, sm = _dist_pair(csr, op)
    xm = sm.solve(b, criteria=crit)
    np.testing.assert_allclose(np.asarray(xm), np.asarray(x1),
                               rtol=1e-8, atol=1e-8)


def test_dist_matfree_ledger(aniso_pair):
    """The comm ledger declares the operator: identity, the matrix-free
    marker, and the table-bytes matrix term (the --explain input)."""
    csr, _, op = aniso_pair
    _, sm = _dist_pair(csr, op)
    led = sm.comm_profile()
    assert led["matrix_free"] is True
    assert led["operator"] == op.identity()
    # three f64 tables of n, n+1, n rows
    n = op.grid[0]
    assert led["matrix_bytes_per_spmv"] == 8 * (3 * n + 1)
    # the fused tier's overlap stanza prices ZERO interior matrix bytes
    _, sf = _dist_pair(csr, op, kernels="fused")
    ov = sf.comm_profile()["overlap"]
    dbl = 8
    assert ov["interior_matrix_bytes"] == 2 * ov["interior_rows"] * dbl


def test_dist_matfree_refusals(aniso_pair):
    """Typed refusals: scattered partitions, wrong sizes, user
    operators, restricted builds."""
    from acg_tpu.parallel.dist import DistributedProblem, arm_matfree
    from acg_tpu.partition import partition_rows
    csr, _, op = aniso_pair
    part = partition_rows(csr, 4, seed=0, method="graph")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    with pytest.raises(AcgError, match="band partition"):
        arm_matfree(prob, op)
    part = partition_rows(csr, 4, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, 4, dtype=jnp.float64)
    with pytest.raises(AcgError, match="rows"):
        arm_matfree(prob, poisson_stencil(8, 2, dtype=jnp.float64))
    with pytest.raises(AcgError, match="dtype"):
        arm_matfree(prob, aniso2d_stencil(16, 0.1, dtype=jnp.float32))
    register_operator("dist_refusal_probe", lambda caps, x: x)
    with pytest.raises(AcgError, match="single-device"):
        arm_matfree(prob, user_operator("dist_refusal_probe",
                                        csr.shape[0]))


# -- spec parsing / case keys ---------------------------------------------

def test_operator_spec_parsing():
    assert parse_operator_spec(None) is None
    assert parse_operator_spec("none") is None
    assert parse_operator_spec("stencil") == ("auto",)
    assert parse_operator_spec("stencil:poisson2d:64") == \
        ("poisson", 2, 64)
    assert parse_operator_spec("stencil:aniso2d:32:0.05") == \
        ("aniso2d", 32, 0.05)
    assert parse_operator_spec("user:myop") == ("user", "myop")
    for bad in ("stencil:poisson4d:8", "stencil:poisson2d",
                "stencil:aniso2d:8", "wat", "user:"):
        with pytest.raises(ValueError):
            parse_operator_spec(bad)
    # explicit spec validated against the gen: matrix being solved --
    # the match must be AFFIRMATIVE: a non-matching kind or a missing
    # --aniso must refuse, never silently solve a different system
    gen = ("poisson", 2, 16, 256, None)
    with pytest.raises(ValueError, match="does not compute"):
        build_operator(("poisson", 2, 32), jnp.float64, gen=gen)
    with pytest.raises(ValueError, match="does not compute"):
        build_operator(("poisson", 2, 16), jnp.float64,
                       gen=("irregular", 0, 256, 256, 16.0))
    with pytest.raises(ValueError, match="constant-coefficient"):
        # aniso stencil against the PLAIN poisson matrix (no --aniso)
        build_operator(("aniso2d", 16, 0.01), jnp.float64, gen=gen,
                       aniso=None)
    with pytest.raises(ValueError, match="disagrees"):
        build_operator(("aniso2d", 16, 0.01), jnp.float64, gen=gen,
                       aniso=0.5)
    with pytest.raises(ValueError, match="gen:poisson"):
        build_operator(("auto",), jnp.float64, gen=None)
    # and the affirmative matches still build
    assert build_operator(("poisson", 2, 16), jnp.float64,
                          gen=gen).identity() == "stencil:poisson2d:16"
    assert build_operator(("aniso2d", 16, 0.25), jnp.float64, gen=gen,
                          aniso=0.25).identity() \
        == "stencil:aniso2d:16:0.25"


def test_operator_case_key():
    """The bench/bench_diff case key grows the operator selection (the
    _precond_keyed pattern): matrix-free and assembled captures never
    alias."""
    from acg_tpu.perfmodel import _operator_keyed, _row_case, _doc_case
    assert _operator_keyed("m", None) == "m"
    assert _operator_keyed("m", "none") == "m"
    assert _operator_keyed("m", "stencil:poisson2d:64") == \
        "m|operator=stencil:poisson2d:64"
    k, v = _row_case({"metric": "m", "value": 2.0,
                      "operator": "stencil:poisson2d:64"})
    assert k == "m|operator=stencil:poisson2d:64" and v == 2.0
    doc = {"manifest": {"metric": "m",
                        "operator": "stencil:poisson2d:64"},
           "stats": {"tsolve": 1.0, "niterations": 10}}
    k, v = _doc_case(doc)
    assert k == "m|operator=stencil:poisson2d:64" and v == 10.0


# -- CLI -------------------------------------------------------------------

def test_cli_operator_refusals_fast():
    """Refusal matrix, in-process (these fire before jax init)."""
    from acg_tpu.cli import main
    base = ["gen:poisson2d:12", "--operator", "stencil", "--comm",
            "none", "--quiet"]
    for extra in (["--dtype", "bf16"], ["--solver", "host"],
                  ["--replace-every", "8"], ["--refine"],
                  ["--spmv-format", "ell"], ["--epsilon", "0.5"],
                  ["--distributed-read"],
                  ["--nrhs", "2", "--block-cg"]):
        with pytest.raises(SystemExit):
            main(base + extra)
    # a file matrix cannot pair with a stencil spec
    with pytest.raises(SystemExit):
        main(["some_file.mtx", "--operator", "stencil:poisson2d:12",
              "--comm", "none", "--quiet"])


def test_cli_operator_e2e(tmp_path):
    """End-to-end: an 8-part matrix-free stencil solve converges, the
    manifest carries the operator identity, and the solution equals the
    assembled run's BYTE-identically (the trajectory bitwise
    contract, observed through the printed vector)."""
    import json
    env_args = ["gen:poisson2d:20", "--nparts", "8",
                "--max-iterations", "300", "--residual-rtol", "1e-8",
                "--warmup", "0"]
    out_a = tmp_path / "xa.mtx"
    out_m = tmp_path / "xm.mtx"
    sj = tmp_path / "mf.json"
    ra = run_cli(env_args + ["-o", str(out_a), "--quiet"])
    rm = run_cli(env_args + ["--operator", "stencil", "-o", str(out_m),
                             "--quiet", "--stats-json", str(sj)])
    assert ra.returncode == 0, ra.stderr
    assert rm.returncode == 0, rm.stderr
    assert out_a.read_bytes() == out_m.read_bytes()
    doc = json.loads(sj.read_text())
    assert doc["manifest"]["operator"] == "stencil:poisson2d:20"
    assert doc["stats"]["converged"] is True


def run_cli(argv, **kw):
    import os
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)
