"""Timeline-tracing subsystem tests (acg_tpu.tracing): the span
recorder, the hoisted profiler context manager, capture analysis with
graceful degradation, the Chrome trace-event exporter + clock
alignment, the --trace/--timeline CLI paths end-to-end on the CPU
backend, and the validator/report/plot tooling."""

import gzip
import json
import os
import subprocess
import sys

import pytest

from acg_tpu import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(module, argv, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", module, *argv],
                          capture_output=True, text=True, env=env, **kw)


def run_script(name, argv, **kw):
    kw.setdefault("timeout", 300)
    env = dict(os.environ)
    env.update(ENV_KEYS)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", name), *argv],
        capture_output=True, text=True, env=env, **kw)


@pytest.fixture
def recorder():
    """Armed span recorder, disarmed (and cleared) afterwards."""
    tracing.arm()
    yield tracing
    tracing.disarm()


# -- span recorder -------------------------------------------------------

def test_recorder_disarmed_is_noop():
    assert not tracing.armed()
    tracing.record_span("x", 0.0, 1.0)
    tracing.record_phase_span("solve", 0.5)
    tracing.record_instant("breakdown", detail="d")
    assert tracing.nspans() == 0


def test_recorder_records_and_clears(recorder):
    tracing.record_span("solve", 10.0, 11.0, cat="phase")
    tracing.record_span("chunk k0..8", 10.2, 10.4, cat="chunk",
                        k_offset=0, iterations=8)
    tracing.record_instant("restart", detail="it 5")
    assert tracing.nspans() == 3
    p = tracing.local_payload(parts=[0, 1])
    assert p["parts"] == [0, 1]
    assert [s["name"] for s in p["spans"]] == ["solve", "chunk k0..8"]
    assert p["spans"][1]["args"] == {"k_offset": 0, "iterations": 8}
    assert p["instants"][0]["name"] == "restart"
    tracing.disarm()
    assert tracing.nspans() == 0  # disarm clears


def test_phase_span_end_is_now(recorder):
    import time

    t_before = time.time()
    tracing.record_phase_span("ingest", 2.0)
    s = tracing.local_payload()["spans"][0]
    assert s["t1"] >= t_before
    assert s["t1"] - s["t0"] == pytest.approx(2.0, abs=0.1)


# -- clock alignment -----------------------------------------------------

def test_align_payloads_removes_negative_skew():
    """Two ranks whose clocks disagree by 3 s: after the barrier-stamp
    alignment both barrier stamps are EQUAL (no negative inter-rank
    skew) and the laggard's spans shifted forward, never backward."""
    mk = lambda rank, tb, t0: {
        "process": rank, "parts": [rank], "t_barrier": tb,
        "spans": [{"name": "solve", "t0": t0, "t1": t0 + 1.0,
                   "cat": "phase"}],
        "instants": [{"name": "e", "t": t0 + 0.5}]}
    fast = mk(0, 103.0, 100.0)   # clock runs 3 s ahead
    slow = mk(1, 100.0, 97.0)
    info = tracing.align_payloads([fast, slow])
    assert info["aligned"] and info["max_skew_s"] == pytest.approx(3.0)
    assert fast["t_barrier"] == slow["t_barrier"] == 103.0
    # the slow clock's spans moved FORWARD by the offset
    assert slow["spans"][0]["t0"] == pytest.approx(100.0)
    assert slow["instants"][0]["t"] == pytest.approx(100.5)
    assert slow["clock_offset_s"] == pytest.approx(3.0)
    # the reference rank is untouched
    assert fast["spans"][0]["t0"] == pytest.approx(100.0)


def test_gather_timeline_single_process(recorder):
    tracing.record_span("solve", 1.0, 2.0)
    got = tracing.gather_timeline(parts=[0, 1, 2])
    assert got is not None
    payloads, clock = got
    assert len(payloads) == 1 and clock["ranks"] == 1
    assert payloads[0]["parts"] == [0, 1, 2]


# -- Chrome trace export + validator ------------------------------------

def _payload(rank, parts, spans, instants=()):
    return {"process": rank, "parts": parts, "t_barrier": 0.0,
            "spans": list(spans), "instants": list(instants)}


def test_export_one_pid_per_part(tmp_path):
    out = tmp_path / "tl.json"
    spans = [{"name": "ingest", "t0": 1.0, "t1": 1.5, "cat": "phase"},
             {"name": "solve", "t0": 1.5, "t1": 3.0, "cat": "phase"},
             {"name": "chunk k0..4", "t0": 1.6, "t1": 2.0,
              "cat": "chunk"}]
    summary = tracing.export_chrome_trace(
        out, [_payload(0, [0, 1, 2, 3], spans,
                       [{"name": "restart", "t": 2.5,
                         "detail": "it 3"}])], nparts=4)
    assert summary["nparts"] == 4
    assert summary["nspans"] == 3 * 4  # controller spans replicated
    doc = json.loads(out.read_text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {1, 2, 3, 4}
    # chunk spans land on their own track, instants on the events one
    assert {e["tid"] for e in xs if e["cat"] == "chunk"} == {2}
    pins = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert pins and pins[0]["args"]["detail"] == "it 3"
    # per-track monotone ts (the exporter sorts)
    per_track = {}
    for e in xs:
        key = (e["pid"], e["tid"])
        assert e["ts"] >= per_track.get(key, -1.0)
        per_track[key] = e["ts"]


def test_export_part_scoped_span_stays_on_its_pid(tmp_path):
    out = tmp_path / "tl.json"
    spans = [{"name": "solve", "t0": 0.0, "t1": 1.0, "cat": "phase"},
             {"name": "hot", "t0": 0.2, "t1": 0.4, "cat": "phase",
              "part": 1}]
    tracing.export_chrome_trace(out, [_payload(0, [0, 1], spans)],
                                nparts=2)
    doc = json.loads(out.read_text())
    hot = [e for e in doc["traceEvents"]
           if e.get("ph") == "X" and e["name"] == "hot"]
    assert len(hot) == 1 and hot[0]["pid"] == 2


def test_check_timeline_validator(tmp_path):
    out = tmp_path / "tl.json"
    spans = [{"name": n, "t0": float(i), "t1": i + 1.0, "cat": "phase"}
             for i, n in enumerate(("ingest", "partition", "compile",
                                    "solve"))]
    tracing.export_chrome_trace(out, [_payload(0, [0, 1], spans)],
                                nparts=2)
    r = run_script("check_timeline.py",
                   [str(out), "--parts", "2", "--require-span", "solve"])
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    # wrong part count refuses
    r = run_script("check_timeline.py", [str(out), "--parts", "3"])
    assert r.returncode == 1
    assert "expected spans on exactly 3 pids" in r.stderr
    # missing required span refuses
    r = run_script("check_timeline.py",
                   [str(out), "--require-span", "ckpt"])
    assert r.returncode == 1
    # corrupt ts refuses (non-monotone injected by hand)
    doc = json.loads(out.read_text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    xs[-1]["ts"] = -5.0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    r = run_script("check_timeline.py", [str(bad)])
    assert r.returncode == 1
    assert "negative ts" in r.stderr or "non-monotone" in r.stderr


# -- capture analysis ----------------------------------------------------

def _write_capture(root, events, host="vm"):
    d = root / "plugins" / "profile" / "2026_01_01_00_00_00"
    d.mkdir(parents=True, exist_ok=True)
    doc = {"displayTimeUnit": "ns", "metadata": {},
           "traceEvents": events}
    with gzip.open(d / f"{host}.trace.json.gz", "wt") as f:
        json.dump(doc, f)
    return d


def test_analyze_trace_missing_dir(tmp_path):
    an = tracing.analyze_trace(tmp_path / "nope")
    assert an["available"] is False
    assert "no profiler capture" in an["why"]


def test_analyze_trace_xplane_only_degrades(tmp_path):
    """An xplane-only capture (the schema we deliberately do not parse)
    degrades to a self-describing record instead of raising."""
    d = tmp_path / "plugins" / "profile" / "r"
    d.mkdir(parents=True)
    (d / "vm.xplane.pb").write_bytes(b"\x00proto")
    an = tracing.analyze_trace(tmp_path)
    assert an["available"] is False
    assert "xplane" in an["why"]
    assert an["xplane_files"] == 1


def test_analyze_trace_corrupt_json_degrades(tmp_path):
    d = tmp_path / "plugins" / "profile" / "r"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        f.write("{torn")
    an = tracing.analyze_trace(tmp_path)
    assert an["available"] is False


def test_analyze_trace_classifies_and_scores_overlap(tmp_path):
    """Synthetic TPU-shaped capture: HLO op instances classify into
    op classes, compile-pass names do NOT, and the overlap score is
    exposed/total over the interval algebra (here: 2 s of all-reduce,
    1 s of it under fusion compute -> efficiency 0.5)."""
    us = 1e6
    events = [
        {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.3",
         "ts": 0.0, "dur": 1.0 * us},
        {"ph": "X", "pid": 1, "tid": 3, "name": "all-reduce.1",
         "ts": 0.5 * us, "dur": 2.0 * us},
        {"ph": "X", "pid": 1, "tid": 2, "name": "dot.7",
         "ts": 4.0 * us, "dur": 0.25 * us},
        {"ph": "X", "pid": 1, "tid": 2, "name": "collective-permute.2",
         "ts": 4.0 * us, "dur": 0.25 * us},
        # the traps: pass names and python frames must NOT classify
        {"ph": "X", "pid": 1, "tid": 9,
         "name": "batch-dot-simplification", "ts": 0.0, "dur": 9 * us},
        {"ph": "X", "pid": 1, "tid": 9, "name": "fusion",
         "ts": 0.0, "dur": 9 * us},
        {"ph": "X", "pid": 1, "tid": 9, "name": "$builtins isinstance",
         "ts": 0.0, "dur": 9 * us},
        # a phase bracket (the acg:* annotation, prefix stripped by the
        # profiler on some backends)
        {"ph": "X", "pid": 1, "tid": 1, "name": "solve",
         "ts": 0.0, "dur": 5.0 * us},
    ]
    _write_capture(tmp_path, events)
    an = tracing.analyze_trace(tmp_path)
    assert an["available"] is True
    ops = an["op_seconds"]
    assert ops["fusion"] == pytest.approx(1.0)
    assert ops["allreduce"] == pytest.approx(2.0)
    assert ops["dot"] == pytest.approx(0.25)
    assert ops["halo"] == pytest.approx(0.25)
    assert "program" not in ops  # no pjit wrappers in this capture
    assert an["collective_seconds"] == pytest.approx(2.25)
    # all-reduce [0.5, 2.5] overlaps fusion [0, 1] for 0.5 s; the
    # permute [4, 4.25] is fully under dot [4, 4.25] -> exposed 1.5
    assert an["exposed_collective_seconds"] == pytest.approx(1.5)
    assert an["overlap_efficiency"] == pytest.approx(1 - 1.5 / 2.25,
                                                     abs=1e-5)
    assert an["phase_seconds"]["solve"] == pytest.approx(5.0)
    # the solve bracket [0, 5] windows the per-solve attribution: the
    # all-reduce/fusion midpoints fall inside, the dot/permute at
    # t=4..4.25 too -- everything here ran inside the one timed solve
    assert an["solve_windows"] == 1
    assert an["op_seconds_in_solve"]["allreduce"] == pytest.approx(2.0)
    assert an["collective_seconds_in_solve"] == pytest.approx(2.25)


def test_analyze_trace_overlap_is_per_file(tmp_path):
    """Interval algebra must stay within one capture file: each host
    has its own profiler timebase, and host1's compute must not "hide"
    host0's fully-exposed allreduce at the same nominal ts."""
    us = 1e6
    _write_capture(tmp_path, [
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce.1",
         "ts": 0.0, "dur": 1.0 * us}], host="h0")
    _write_capture(tmp_path, [
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
         "ts": 0.0, "dur": 1.0 * us}], host="h1")
    an = tracing.analyze_trace(tmp_path)
    assert an["available"]
    assert an["exposed_collective_seconds"] == pytest.approx(1.0)
    assert an["overlap_efficiency"] == pytest.approx(0.0)


def test_analyze_trace_straggler_two_ranks(tmp_path):
    """The TRUE median (np.median convention): across exactly 2 hosts
    at 1.0 s / 2.0 s the median is 1.5 and the slow host IS a
    straggler -- the same verdict telemetry.aggregate_ranks gives."""
    us = 1e6
    for host, secs in (("h0", 1.0), ("h1", 2.0)):
        _write_capture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "solve",
             "ts": 0.0, "dur": secs * us}], host=host)
    an = tracing.analyze_trace(tmp_path)
    strag = an["straggler"]
    assert strag is not None and strag["rank"] == "h1"
    assert strag["ratio_to_median"] == pytest.approx(2.0 / 1.5,
                                                     rel=1e-3)


def test_analyze_trace_straggler_across_ranks(tmp_path):
    """Per-host trace files = ranks; a rank whose solve bracket exceeds
    STRAGGLER_RATIO x median gets the callout."""
    us = 1e6
    for host, secs in (("h0", 1.0), ("h1", 1.1), ("h2", 2.0)):
        _write_capture(tmp_path, [
            {"ph": "X", "pid": 1, "tid": 1, "name": "acg:solve",
             "ts": 0.0, "dur": secs * us}], host=host)
    an = tracing.analyze_trace(tmp_path)
    assert an["available"] and len(an["per_rank"]) == 3
    strag = an["straggler"]
    assert strag is not None and strag["rank"] == "h2"
    assert strag["ratio_to_median"] == pytest.approx(2.0 / 1.1,
                                                     rel=1e-3)


def test_apply_measured_ops_overrides_replay():
    from acg_tpu.solvers.stats import SolverStats

    st = SolverStats()
    st.ops["dot"].add(n=10, t=99.0)
    st.ops["gemv"].add(n=5, t=99.0)
    an = {"available": True, "solve_windows": 2,
          "op_seconds": {"dot": 9.0, "gemv": 9.0, "allreduce": 9.0},
          "op_seconds_in_solve": {"dot": 1.0, "gemv": 0.0,
                                  "allreduce": 1.0}}
    filled = tracing.apply_measured_ops(st, an)
    assert filled == ["dot"]           # gemv 0 s and allreduce n=0 skip
    # solve-windowed seconds SUMMED over windows (the rows' n/bytes
    # accumulate across soak repeats too -- the replay tier's
    # cumulative t = per_call * n convention), never the capture
    # totals (those include the warmup executions)
    assert st.ops["dot"].t == 1.0
    assert st.ops["gemv"].t == 99.0
    # a capture without solve brackets overwrites nothing
    st2 = SolverStats()
    st2.ops["dot"].add(n=10, t=99.0)
    assert tracing.apply_measured_ops(
        st2, {"available": True, "solve_windows": 0,
              "op_seconds": {"dot": 1.0},
              "op_seconds_in_solve": {}}) == []
    assert st2.ops["dot"].t == 99.0


def test_measured_comm_line_verdicts():
    line = tracing.measured_comm_line(
        {"collective_seconds": 1.0}, predicted_comm_s=0.9)
    assert "ledger consistent" in line
    line = tracing.measured_comm_line(
        {"collective_seconds": 10.0}, predicted_comm_s=1.0)
    assert "underestimates" in line
    line = tracing.measured_comm_line(
        {"collective_seconds": 0.0}, predicted_comm_s=1.0)
    assert "no collective device events" in line
    # with solve brackets, the verdict uses the WINDOWED collectives:
    # the capture total includes the warmup solves' (here 2x) which
    # would spuriously flip an accurate ledger to "underestimates"
    line = tracing.measured_comm_line(
        {"solve_windows": 1, "collective_seconds": 2.1,
         "collective_seconds_in_solve": 1.0}, predicted_comm_s=1.0)
    assert "ledger consistent" in line and "(solve windows)" in line


# -- profiler context manager -------------------------------------------

def test_profiler_trace_none_is_noop():
    with tracing.profiler_trace(None):
        pass
    with tracing.profiler_trace(""):
        pass


def test_profiler_trace_captures(tmp_path):
    import jax.numpy as jnp

    d = tmp_path / "cap"
    with tracing.profiler_trace(d):
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    cap = tracing.find_capture(d)
    assert cap["trace_json"], "profiler wrote no trace.json capture"
    an = tracing.analyze_trace(d)
    assert an["available"] is True


def test_profiler_trace_failed_start_warns_not_raises(tmp_path, capsys):
    """A second start while a trace runs raises inside jax; the context
    manager must degrade to an unprofiled body, and the OUTER capture
    must still stop cleanly."""
    ran = False
    with tracing.profiler_trace(tmp_path / "outer"):
        with tracing.profiler_trace(tmp_path / "inner"):
            ran = True
    assert ran
    err = capsys.readouterr().err
    assert "profiler start failed" in err
    assert tracing.find_capture(tmp_path / "outer")["trace_json"]
    assert not tracing.find_capture(tmp_path / "inner")["trace_json"]


# -- CLI end-to-end ------------------------------------------------------

def test_cli_trace_capture_and_analysis(tmp_path):
    """--trace end-to-end on the CPU backend: capture dir created,
    tracing: section lands in the report and the /7 stats twin, and
    the ops source is marked when a class was measured."""
    cap = tmp_path / "cap"
    stats = tmp_path / "st.json"
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:16", "--comm", "none",
                 "--max-iterations", "50", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--trace", str(cap),
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    assert tracing.find_capture(cap)["trace_json"]
    assert "tracing:" in r.stderr
    doc = json.loads(stats.read_text())
    assert doc["schema"] == "acg-tpu-stats/12"
    tr = doc["stats"]["tracing"]
    assert tr["available"] is True
    assert tr["capture_files"] >= 1


def test_cli_trace_analysis_degrades_without_capture(tmp_path,
                                                     monkeypatch):
    """When the profiler start fails (here: a second trace already
    running in-process), the solve must still succeed and the section
    must say why the analysis is unavailable."""
    import jax

    from acg_tpu import cli

    cap = tmp_path / "cap"
    jax.profiler.start_trace(str(tmp_path / "hog"))
    try:
        rc = cli.main(["gen:poisson2d:12", "--comm", "none",
                       "--max-iterations", "20", "--residual-rtol",
                       "1e-6", "--warmup", "0", "--quiet",
                       "--trace", str(cap)])
    finally:
        jax.profiler.stop_trace()
    assert rc == 0
    assert not tracing.find_capture(cap)["trace_json"]


def test_cli_timeline_8part(tmp_path):
    """The acceptance path: an 8-part CPU-mesh solve under --timeline
    emits a validating Chrome trace-event file with one pid per part
    and spans for ingest/partition/compile/solve."""
    tl = tmp_path / "tl.json"
    stats = tmp_path / "st.json"
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:24", "--nparts", "8",
                 "--max-iterations", "100", "--residual-rtol", "1e-8",
                 "--warmup", "1", "--quiet", "--timeline", str(tl),
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    assert "timeline:" in r.stderr
    doc = json.loads(tl.read_text())
    assert doc["metadata"]["schema"] == tracing.TIMELINE_SCHEMA
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in xs} == set(range(1, 9))
    names = {e["name"] for e in xs}
    assert {"ingest", "partition", "compile", "solve"} <= names
    r = run_script("check_timeline.py",
                   [str(tl), "--parts", "8", "--require-span", "ingest",
                    "--require-span", "partition", "--require-span",
                    "compile", "--require-span", "solve"])
    assert r.returncode == 0, r.stderr
    twin = json.loads(stats.read_text())
    assert twin["stats"]["tracing"]["timeline"]["nparts"] == 8


def test_cli_timeline_ckpt_chunks(tmp_path):
    """Checkpoint-armed solves put their chunked-dispatch boundaries on
    the timeline (cat=chunk, k_offset args)."""
    tl = tmp_path / "tl.json"
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:12", "--comm", "none", "--dtype", "f32",
                 "--max-iterations", "60", "--residual-rtol", "1e-6",
                 "--warmup", "0", "--quiet", "--ckpt",
                 str(tmp_path / "ck"), "--ckpt-every", "10",
                 "--timeline", str(tl)])
    assert r.returncode == 0, r.stderr
    doc = json.loads(tl.read_text())
    chunks = [e for e in doc["traceEvents"]
              if e.get("ph") == "X" and e.get("cat") == "chunk"
              and e["name"].startswith("chunk k")]
    assert chunks, "no chunk spans on the timeline"
    assert chunks[0]["args"]["k_offset"] == 0


def test_cli_timeline_refused_under_explain():
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:12", "--explain", "--timeline",
                 "/tmp/never.json"])
    assert r.returncode != 0
    assert "--timeline" in r.stderr


def test_cli_explain_measured_section(tmp_path):
    """--explain --trace prints the measured section (per-op-class
    seconds, overlap score, measured-vs-predicted comm line); without
    --trace the section is absent and the static verdict unchanged."""
    r = run_cli("acg_tpu.cli",
                ["gen:poisson2d:12", "--explain", "--max-iterations",
                 "16", "--warmup", "0", "--quiet", "--trace",
                 str(tmp_path / "cap")])
    assert r.returncode == 0, r.stderr
    assert "== explain: measured (profiler trace) ==" in r.stderr
    assert ("overlap efficiency" in r.stderr
            or "no usable capture" in r.stderr)
    assert "comm: predicted" in r.stderr or "no usable" in r.stderr
    r2 = run_cli("acg_tpu.cli",
                 ["gen:poisson2d:12", "--explain", "--max-iterations",
                  "16", "--warmup", "0", "--quiet"])
    assert r2.returncode == 0, r2.stderr
    assert "measured (profiler trace)" not in r2.stderr


def test_cli_buildinfo_advertises_tracing():
    r = run_cli("acg_tpu.cli", ["--buildinfo"])
    assert r.returncode == 0
    for token in ("timeline tracing", "--timeline", "acg_trace_",
                  "acg-tpu-stats/12"):
        assert token in r.stdout, token


# -- tooling -------------------------------------------------------------

def test_trace_report_on_capture_and_timeline(tmp_path):
    us = 1e6
    _write_capture(tmp_path / "cap", [
        {"ph": "X", "pid": 1, "tid": 1, "name": "dot.1", "ts": 0.0,
         "dur": 1.0 * us}])
    r = run_script("trace_report.py", [str(tmp_path / "cap")])
    assert r.returncode == 0, r.stderr
    assert "dot" in r.stdout
    tl = tmp_path / "tl.json"
    tracing.export_chrome_trace(
        tl, [_payload(0, [0, 1],
                      [{"name": "solve", "t0": 0.0, "t1": 1.0,
                        "cat": "phase"}])], nparts=2)
    r = run_script("trace_report.py", [str(tl)])
    assert r.returncode == 0, r.stderr
    assert "2 part(s)" in r.stdout and "solve" in r.stdout
    r = run_script("trace_report.py", [str(tmp_path / "missing.json")])
    assert r.returncode == 1


def test_plot_convergence_timeline_gantt(tmp_path):
    tl = tmp_path / "tl.json"
    spans = [{"name": "ingest", "t0": 0.0, "t1": 0.2, "cat": "phase"},
             {"name": "solve", "t0": 0.2, "t1": 1.0, "cat": "phase"}]
    tracing.export_chrome_trace(tl, [_payload(0, [0], spans)], nparts=1)
    r = run_script("plot_convergence.py", [str(tl), "--ascii"])
    assert r.returncode == 0, r.stderr
    assert "ingest" in r.stdout and "#" in r.stdout
    # and next to a residual plot (mixed inputs classify independently)
    from acg_tpu.telemetry import EagerTraceRecorder

    rec = EagerTraceRecorder(16)
    for k in range(8):
        rec.record(10.0 ** -k, 1.0, 0.5, 2.0)
    conv = tmp_path / "conv.jsonl"
    rec.finish().write_jsonl(str(conv))
    r = run_script("plot_convergence.py",
                   [str(conv), str(tl), "--ascii"])
    assert r.returncode == 0, r.stderr
    assert "rnrm2" in r.stdout and "spans" in r.stdout


def test_old_schema_docs_still_accepted(tmp_path):
    """Append-only contract: a /6 document (no tracing key) still loads
    through bench_diff's case reader and plot_convergence."""
    doc = {"schema": "acg-tpu-stats/6",
           "manifest": {"schema": "acg-tpu-stats/6", "metric": "case-a",
                        "matrix": "gen:poisson2d:16", "dtype": "f64"},
           "stats": {"unknowns": 256, "niterations": 10,
                     "tsolve": 0.5, "converged": True,
                     "soak": {"nsolves": 3,
                              "latency": {"p50": 0.1, "p95": 0.2,
                                          "p99": 0.3},
                              "iterations": {"p50": 10},
                              "drift": {"tripped": False}},
                     "events": []}}
    p = tmp_path / "old.json"
    p.write_text(json.dumps(doc))
    from acg_tpu.perfmodel import load_cases

    cases = load_cases(str(p))
    assert cases, "a /6 capture must still produce comparable cases"
    r = run_script("plot_convergence.py", [str(p), "--ascii"])
    assert r.returncode == 0, r.stderr


def test_metrics_trace_families(recorder):
    from acg_tpu import metrics

    was = metrics.armed()
    try:
        metrics.arm()
        # the registry is process-wide and other tests feed it too:
        # assert the DELTA, not an absolute count
        v0 = metrics.TRACE_SPANS.labels(cat="phase").value
        tracing.record_span("solve", 0.0, 1.0)
        tracing.record_instant("drift")
        metrics.record_trace_analysis(
            {"available": True, "op_seconds": {"dot": 0.25},
             "overlap_efficiency": 0.8,
             "exposed_collective_seconds": 0.1})
        assert metrics.TRACE_SPANS.labels(cat="phase").value == v0 + 1
        text = metrics.expose()
    finally:
        if not was:
            metrics.disarm()
    assert 'acg_trace_spans_total{cat="phase"}' in text
    assert 'acg_trace_op_seconds{op="dot"} 0.25' in text
    assert "acg_trace_overlap_efficiency 0.8" in text
