"""Length-binned ELL format (ops.spmv.BinnedEllMatrix).

The TPU rebuild of the reference's merge-CSR load-balancing goal
(``cg-kernels-cuda.cu:340-441``) for power-law row-length matrices:
near-tight per-bin widths (padding < 1.33x), no per-nnz segment_sum,
hub rows in a sorted-COO tail.  Measured ~2x over pure COO on v5e
(BASELINE.md round 3).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from acg_tpu.io.generators import irregular_spd_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.spmv import (BELL_WIDTHS, BinnedEllMatrix,
                              binned_ell_from_csr, device_matrix_from_csr,
                              spmv, spmv_flops)
from acg_tpu.solvers.jax_cg import JaxCGSolver
from acg_tpu.solvers.stats import StoppingCriteria


@pytest.fixture(scope="module")
def irregular():
    r, c, v, N = irregular_spd_coo(8_000, avg_degree=12.0, seed=3)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def test_auto_picks_bell_for_powerlaw(irregular):
    A = device_matrix_from_csr(irregular, dtype=jnp.float64)
    assert isinstance(A, BinnedEllMatrix)


def test_spmv_matches_scipy(irregular):
    A = binned_ell_from_csr(irregular, dtype=jnp.float64)
    x = np.random.default_rng(0).standard_normal(irregular.shape[0])
    y = np.asarray(spmv(A, jnp.asarray(x)))
    np.testing.assert_allclose(y, irregular @ x, rtol=1e-12)


def test_rows_partition_exactly(irregular):
    """Every row appears in exactly one bin (or the hub tail), padding
    is bounded by the geometric widths, and nnz is conserved."""
    A = binned_ell_from_csr(irregular, dtype=jnp.float64)
    seen = np.concatenate([np.asarray(r) for r in A.bin_rows]
                          + [np.unique(np.asarray(A.tail_rows))])
    row_nnz = np.diff(irregular.indptr)
    # rows with zero nnz may be binned or absent; all NONZERO rows once
    nz_rows = np.flatnonzero(row_nnz)
    assert np.isin(nz_rows, seen).all()
    assert len(seen) == len(np.unique(seen))
    total = (sum(int(np.count_nonzero(np.asarray(d))) for d in A.bin_data)
             + int(A.tail_vals.size))
    # explicit stored zeros (none in this generator) aside, nnz conserved
    assert total == irregular.nnz
    assert spmv_flops(A) == pytest.approx(3.0 * irregular.nnz)
    for d, K in zip(A.bin_data, A.bin_ks):
        assert d.shape[1] == K and K in BELL_WIDTHS


def test_hub_tail_engages():
    """A graph with rows wider than the largest bin exercises the COO
    tail path."""
    n = 4_000
    r, c, v, N = irregular_spd_coo(n, avg_degree=8.0, seed=0)
    # add a dense hub row/col: row 0 coupled to everyone
    hub_c = np.arange(1, n, dtype=r.dtype)
    hub_r = np.zeros(n - 1, dtype=r.dtype)
    w = np.full(n - 1, -0.01)
    rows = np.concatenate([r, hub_r, hub_c])
    cols = np.concatenate([c, hub_c, hub_r])
    vals = np.concatenate([v, w, w])
    # restore diagonal dominance
    diag_fix = np.zeros(n); diag_fix[0] = 0.01 * (n - 1) + 1
    diag_fix[1:] += 0.011
    rows = np.concatenate([rows, np.arange(n, dtype=r.dtype)])
    cols = np.concatenate([cols, np.arange(n, dtype=r.dtype)])
    vals = np.concatenate([vals, diag_fix])
    csr = SymCsrMatrix.from_coo(n, rows, cols, vals).to_csr()
    A = binned_ell_from_csr(csr, dtype=jnp.float64)
    assert A.tail_rows.size >= n - 1  # the hub row overflows every bin
    x = np.random.default_rng(1).standard_normal(n)
    np.testing.assert_allclose(np.asarray(spmv(A, jnp.asarray(x))),
                               csr @ x, rtol=1e-12)


def test_cg_solves_on_bell(irregular):
    rng = np.random.default_rng(5)
    xsol = rng.standard_normal(irregular.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = irregular @ xsol
    A = device_matrix_from_csr(irregular, dtype=jnp.float64)
    s = JaxCGSolver(A)
    x = s.solve(b, criteria=StoppingCriteria(maxits=3000,
                                             residual_rtol=1e-10))
    assert np.linalg.norm(np.asarray(x) - xsol) < 1e-8
