"""The request observatory (ISSUE 18): request identity resolution,
per-stage accounting, the access ledger, and its validators.

The contracts pinned here:
  * identity never costs a request its answer -- a malformed client id
    or traceparent falls back (client id > traceparent trace-id >
    generated), it does not refuse;
  * stage accounting is accumulating, lock-protected, and frozen at
    completion -- a worker racing the deadline boundary cannot mutate a
    sealed row;
  * ``RequestLog.complete`` is idempotent (first outcome wins), stamps
    strictly-increasing ``t_done``, and appends ONE atomic JSONL line
    per request that ``scripts/check_access_log.py`` accepts;
  * the /requests ring is bounded and never torn under concurrent
    completion.
"""

import json
import os
import subprocess
import sys
import threading

from acg_tpu import reqtrace
from acg_tpu.reqtrace import (ACCESS_SCHEMA, OUTCOMES, REQUESTS_SCHEMA,
                              STAGES, RequestLog, RequestRecord,
                              generate_request_id, outcome_of,
                              parse_traceparent, request_id_from_doc)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACEPARENT = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
TRACE_ID = "4bf92f3577b34da6a3ce929d0e0e4736"


# -- identity resolution --------------------------------------------------

def test_parse_traceparent():
    assert parse_traceparent(TRACEPARENT) == TRACE_ID
    # case and surrounding whitespace are normalised away
    assert parse_traceparent("  " + TRACEPARENT.upper() + " ") == TRACE_ID
    for bad in (None, "", "not-a-traceparent", TRACE_ID,
                "00-" + "g" * 32 + "-00f067aa0ba902b7-01",
                "00-" + "a" * 31 + "-00f067aa0ba902b7-01", 17):
        assert parse_traceparent(bad) is None


def test_request_id_resolution_order():
    # a well-formed client id wins over everything
    assert request_id_from_doc({"request_id": "client-7",
                                "traceparent": TRACEPARENT}) == "client-7"
    # no client id -> the traceparent's trace-id
    assert request_id_from_doc({"traceparent": TRACEPARENT}) == TRACE_ID
    # malformed client ids are IGNORED (never refused): fall through
    for bad in ("", "has space", "x" * 129, 42, ["list"]):
        assert request_id_from_doc(
            {"request_id": bad, "traceparent": TRACEPARENT}) == TRACE_ID
    # nothing usable -> generated, with the recognisable prefix
    for doc in ({}, None, {"request_id": "bad id",
                           "traceparent": "junk"}):
        rid = request_id_from_doc(doc)
        assert rid.startswith("req-") and len(rid) == 4 + 16
    # generated ids are unique
    assert generate_request_id() != generate_request_id()


def test_outcome_mapping():
    assert outcome_of({"ok": True}) == "ok"
    for kind in ("shed-queue-full", "shed-slo-burn", "shed-shutdown",
                 "deadline-expired"):
        body = {"ok": False, "error": {"type": kind}}
        assert outcome_of(body) == kind
        assert outcome_of(body) in OUTCOMES
    for kind in ("invalid-request", "faults-disabled"):
        assert outcome_of({"ok": False, "error": {"type": kind}}) == \
            "invalid-request"
    # breakdowns, non-convergence, isolation deaths: request-failed
    assert outcome_of({"ok": False,
                       "error": {"type": "not-converged"}}) == \
        "request-failed"
    assert outcome_of(None) == "request-failed"
    assert outcome_of({}) == "request-failed"


# -- per-request records --------------------------------------------------

def test_record_accumulates_and_freezes():
    rec = RequestRecord("r-1", matrix="gen:poisson2d:12")
    rec.stage("queue-wait", 0.25)
    rec.stage("queue-wait", 0.25)  # accumulating, not overwriting
    rec.stage("solve", 0.5, batch="batch-1")
    rec.note("coalesced", 3)
    assert rec.stages() == {"queue-wait": 0.5, "solve": 0.5}
    d = rec.doc()
    assert d["inflight"] and d["request_id"] == "r-1"
    assert d["coalesced"] == 3
    # negative durations clamp to zero (clock jitter must not produce
    # time-travelling rows)
    rec.stage("demux", -1.0)
    assert rec.stages()["demux"] == 0.0

    log = RequestLog()
    rec2 = log.begin("r-2")
    rec2.stage("admit", 0.01)
    row = log.complete(rec2, "ok")
    assert row["outcome"] == "ok"
    # sealed: further stage()/note() calls are no-ops
    rec2.stage("solve", 99.0)
    rec2.note("cache", {"operator": "hit"})
    assert "solve" not in rec2.doc()["stages"]
    assert "cache" not in rec2.doc()


def test_log_lane_assignment_and_idempotent_complete():
    log = RequestLog(ring=4)
    a, b, c = log.begin("a"), log.begin("b"), log.begin("c")
    assert (a.lane, b.lane, c.lane) == (0, 1, 2)
    log.complete(b, "ok")
    assert log.begin("d").lane == 1  # lowest free lane is reused
    # first completion wins; the loser sees None and the outcome holds
    assert log.complete(a, "deadline-expired") is not None
    assert log.complete(a, "ok") is None
    assert a.outcome == "deadline-expired"
    assert log.summary()["outcomes"]["deadline-expired"] == 1


def test_log_ring_bound_and_monotone_t_done():
    log = RequestLog(ring=3)
    rows = [log.complete(log.begin(f"r-{i}"), "ok") for i in range(8)]
    snap = log.snapshot()
    assert snap["schema"] == REQUESTS_SCHEMA
    assert [d["request_id"] for d in snap["completed"]] == \
        ["r-5", "r-6", "r-7"]  # bounded ring keeps the last K
    assert snap["outcomes"] == {"ok": 8}
    dones = [r["t_done"] for r in rows]
    assert all(b > a for a, b in zip(dones, dones[1:]))
    for r in rows:
        assert r["t_arrival"] <= r["t_done"]
    s = log.summary()
    assert s["completed"] == 8 and s["inflight"] == 0 and s["ring"] == 3


def test_ledger_rows_pass_the_validator(tmp_path):
    """Round-trip: rows written by RequestLog -- including a batched
    row with per-RHS attribution and a shed row -- satisfy
    scripts/check_access_log.py, and torn/invalid rows are rejected."""
    path = str(tmp_path / "access.jsonl")
    log = RequestLog(path, ring=8)
    members = [f"m-{i}" for i in range(3)]
    batch = {"id": "batch-1", "width": 3, "members": members,
             "solve_seconds": 0.3, "rhs_solve_seconds": 0.1}
    for rid in members:
        rec = log.begin(rid, matrix="gen:poisson2d:12")
        rec.arrival -= 0.5  # backdate: wall must cover the stages
        rec.stage("admit", 0.001)
        rec.stage("queue-wait", 0.02)
        rec.stage("solve", 0.1, batch="batch-1")
        rec.note("batch", batch)
        rec.note("cache", {"operator": "hit", "program": "hit"})
        log.complete(rec, "ok")
    shed = log.begin("shed-1")
    shed.stage("admit", 0.0005)
    log.complete(shed, "shed-queue-full")
    log.close()

    with open(path) as f:
        rows = [json.loads(line) for line in f]
    assert len(rows) == 4
    assert all(r["schema"] == ACCESS_SCHEMA for r in rows)
    assert set(rows[0]["stages"]) <= set(STAGES)

    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts",
                                      "check_access_log.py"),
         path, "--min-rows", "4", "--require-outcome", "ok",
         "--require-outcome", "shed-queue-full"],
        capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    # and the reporter reads the same ledger: a per-stage table with
    # p50/p95/p99 columns plus the tail decomposition
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts",
                                      "access_report.py"), path],
        capture_output=True, text=True)
    assert rep.returncode == 0, rep.stderr
    assert "p99" in rep.stdout and "queue-wait" in rep.stdout
    assert "tail decomposition" in rep.stdout
    # a stage-sum > wall forgery is caught
    forged = dict(rows[0])
    forged["stages"] = {"solve": forged["wall_seconds"] + 1.0}
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write(json.dumps(forged) + "\n")
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts",
                                      "check_access_log.py"), bad],
        capture_output=True, text=True)
    assert res.returncode == 1
    assert "exceeds wall" in res.stderr


def test_concurrent_completions_never_tear(tmp_path):
    """Many threads completing against one log: every ledger line
    parses (the single-os.write atomic-append contract), t_done stays
    strictly monotone, and snapshot() under fire never tears."""
    path = str(tmp_path / "access.jsonl")
    log = RequestLog(path, ring=16)
    nthreads, per = 8, 25
    stop = threading.Event()

    def _writer(k):
        for i in range(per):
            rec = log.begin(f"w{k}-{i}")
            rec.stage("admit", 0.0001)
            rec.stage("solve", 0.0002)
            log.complete(rec, "ok")

    def _reader():
        while not stop.is_set():
            snap = log.snapshot()
            for d in snap["completed"] + snap["inflight"]:
                assert d["request_id"]  # a torn doc would KeyError

    threads = [threading.Thread(target=_writer, args=(k,))
               for k in range(nthreads)]
    rt = threading.Thread(target=_reader)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    stop.set()
    rt.join(timeout=60.0)
    log.close()
    with open(path) as f:
        rows = [json.loads(line) for line in f]  # every line parses
    assert len(rows) == nthreads * per
    dones = [r["t_done"] for r in rows]
    assert all(b > a for a, b in zip(dones, dones[1:]))
    assert log.summary()["outcomes"] == {"ok": nthreads * per}
