"""Pallas kernel correctness (interpret mode on the CPU mesh) against the
XLA formulations, and the kernels="pallas" solver path end to end.

The reference validates its device-kernel tier operationally through the
manufactured-solution flow (SURVEY.md section 4); here each kernel also
gets a direct unit oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from acg_tpu.io.generators import poisson_mtx
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.ops.pallas_kernels import dia_spmv, fused_pipelined_update
from acg_tpu.ops.spmv import device_matrix_from_csr, dia_mv
from acg_tpu.solvers import HostCGSolver, StoppingCriteria
from acg_tpu.solvers.jax_cg import JaxCGSolver


from acg_tpu.ops.pallas_kernels import TILE


@pytest.mark.parametrize("n,offsets", [
    (1000, (-32, -1, 0, 1, 32)),          # ragged: padded fallback
    (20000, (-141, -1, 0, 1, 141)),       # ragged: padded fallback
    (500, (0,)),
    (700, (-3, 2)),                        # asymmetric offsets
    (2 * TILE, (-128, -1, 0, 1, 128)),     # fast path, 2 tiles
    (TILE, (-64, 0, 64)),                  # fast path, single tile
    (4 * TILE, (-TILE, -1, 0, 1, TILE)),   # fast path, band == tile
])
def test_dia_spmv_matches_xla(n, offsets):
    rng = np.random.default_rng(0)
    planes = tuple(jnp.asarray(rng.standard_normal(n), jnp.float32)
                   for _ in offsets)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    want = dia_mv(planes, offsets, n, x)
    got = dia_spmv(planes, offsets, x, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_pipelined_update_matches_xla():
    rng = np.random.default_rng(1)
    n = 20000
    x, r, w, p, t, z, q = (jnp.asarray(rng.standard_normal(n), jnp.float32)
                           for _ in range(7))
    a, b = jnp.float32(0.37), jnp.float32(0.81)
    zn = q + b * z
    tn = w + b * t
    pn = r + b * p
    want = (x + a * pn, r - a * tn, w - a * zn, pn, tn, zn)
    got = fused_pipelined_update(x, r, w, p, t, z, q, a, b, interpret=True)
    for g, wv in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pipelined", [False, True])
def test_solver_pallas_kernels_match_host(pipelined):
    """kernels="pallas" (interpret mode off-TPU) must solve to the same
    answer as the host oracle."""
    A = SymCsrMatrix.from_mtx(poisson_mtx(20, dim=2))
    csr = A.to_csr()
    rng = np.random.default_rng(2)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    dev = device_matrix_from_csr(csr, dtype=jnp.float64)
    solver = JaxCGSolver(dev, pipelined=pipelined, kernels="pallas")
    assert solver.kernels == "pallas-interpret"  # CPU in CI
    x = solver.solve(b, criteria=StoppingCriteria(maxits=2000,
                                                  residual_rtol=1e-10))
    assert np.linalg.norm(x - xsol) < 1e-6


def test_solver_auto_kernels_off_tpu_is_xla():
    A = SymCsrMatrix.from_mtx(poisson_mtx(8, dim=2))
    dev = device_matrix_from_csr(A.to_csr(), dtype=jnp.float64)
    assert jax.default_backend() != "tpu"  # CPU mesh in CI
    assert JaxCGSolver(dev, kernels="auto").kernels == "xla"


def test_dia_spmv_clustered_route_and_numerics():
    """Clustered-offset stencils (3D Poisson shape: far +-n^2 diagonals)
    take the multi-window kernel; numerics must match dia_mv exactly,
    including the whole-tile-shift zero-fill at both edges."""
    import numpy as np

    from acg_tpu.ops.pallas_kernels import TILE, dia_spmv, dia_spmv_route
    from acg_tpu.ops.spmv import dia_mv

    # the real 512^3 shape routes clustered
    r = dia_spmv_route((-262144, -512, -1, 0, 1, 512, 262144),
                       512 ** 3, np.float32)
    assert r[0] == "clustered"
    assert r[1] == (-512, -1, 0, 1, 512) and r[2] == (-262144, 262144)

    # band too wide for one VMEM window, far offsets on tile boundaries
    n = 64 * TILE
    offsets = (-32 * TILE, -3, 0, 3, 32 * TILE)
    assert dia_spmv_route(offsets, n, np.float32)[0] == "clustered"
    rng = np.random.default_rng(0)
    planes = tuple(jnp.asarray(rng.random(n), jnp.float32)
                   for _ in offsets)
    x = jnp.asarray(rng.random(n), jnp.float32)
    y = dia_spmv(planes, offsets, x, interpret=True)
    yref = dia_mv(planes, offsets, n, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yref), rtol=2e-6)

    # off-tile far offsets cannot cluster -> xla fallback
    assert dia_spmv_route((-32 * TILE + 7, 0, 1), n,
                          np.float32)[0] == "xla"


def test_dia_spmv_dot_fused():
    """Fused (y, dot(x,y)) matches separate ops on every route, and the
    classic solver using the pallas tier (which routes through it)
    still matches the host oracle."""
    import numpy as np

    from acg_tpu.ops.pallas_kernels import TILE, dia_spmv_dot
    from acg_tpu.ops.spmv import dia_mv

    rng = np.random.default_rng(1)
    for n, offsets in [(3 * TILE, (-3, -1, 0, 1, 3)),
                       (64 * TILE, (-32 * TILE, -3, 0, 3, 32 * TILE)),
                       (1000, (-3, 0, 3))]:
        planes = tuple(jnp.asarray(rng.random(n), jnp.float32)
                       for _ in offsets)
        x = jnp.asarray(rng.random(n), jnp.float32)
        y, d = dia_spmv_dot(planes, offsets, x, interpret=True)
        yref = dia_mv(planes, offsets, n, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yref),
                                   rtol=2e-6)
        # f64 ground truth: both the fused f32 accumulation and XLA's
        # pairwise f32 dot carry ~sqrt(n)*eps error in different
        # directions; compare each to the exact value instead
        dref = float(np.asarray(x, np.float64)
                     @ np.asarray(yref, np.float64))
        assert float(d) == pytest.approx(dref, rel=3e-4)


def test_classic_solver_pallas_tier_matches_xla():
    """End-to-end: JaxCGSolver(kernels=pallas) on a DIA matrix solves
    to the same answer as the xla tier."""
    import numpy as np

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    r, c, v, N = poisson2d_coo(24)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float64, format="dia")
    b = np.ones(N)
    crit = StoppingCriteria(maxits=2000, residual_rtol=1e-10)
    x_xla = JaxCGSolver(A, kernels="xla").solve(b, criteria=crit)
    x_pal = JaxCGSolver(A, kernels="pallas").solve(b, criteria=crit)
    np.testing.assert_allclose(x_pal, x_xla, atol=1e-9)
