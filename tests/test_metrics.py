"""Service-metrics tier (acg_tpu.metrics + acg_tpu.soak): registry
semantics, Prometheus exposition golden, the soak driver, the drift
detector + injected-slowdown trip path, and the ``acg-tpu-stats/3``
round-trip through ``scripts/bench_diff.py``.

Covers the PR-4 satellite checklist: counter monotonicity, histogram
bucket boundaries, label dedup, an exposition-format golden, a
3-solve soak smoke with the drift detector armed, and the /3 schema
diffing through the bench gate."""

import json
import math
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from acg_tpu import metrics, soak
from acg_tpu.io.generators import poisson2d_coo
from acg_tpu.matrix import SymCsrMatrix
from acg_tpu.solvers.stats import StoppingCriteria

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")

ENV_KEYS = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def run_cli(argv, **kw):
    env = dict(os.environ)
    env.update(ENV_KEYS)
    kw.setdefault("timeout", 600)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli", *argv],
                          capture_output=True, text=True, env=env, **kw)


@pytest.fixture(scope="module")
def csr():
    r, c, v, N = poisson2d_coo(12)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


def _jax_solver(csr, **kw):
    import jax.numpy as jnp

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    A = device_matrix_from_csr(csr, dtype=jnp.float64)
    return JaxCGSolver(A, **kw)


@pytest.fixture(autouse=True)
def _disarm_after():
    """Every test leaves the process-wide layer the way it found it."""
    was = metrics.armed()
    yield
    if not was:
        metrics.disarm()


# -- registry semantics --------------------------------------------------

def test_counter_monotonic():
    reg = metrics.Registry()
    c = reg.counter("t_total", "x")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.dec()  # counters cannot go down, by any route


def test_gauge_set_dec():
    reg = metrics.Registry()
    g = reg.gauge("t_g", "x")
    g.set(10)
    g.dec(3)
    g.inc(0.5)
    assert g.value == 7.5


def test_histogram_bucket_boundaries():
    """A value EQUAL to an upper bound lands in that bucket (le =
    less-or-equal, the Prometheus contract); above the ladder it lands
    only in +Inf."""
    reg = metrics.Registry()
    h = reg.histogram("t_h", "x", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 2.0, 2.00001, 100.0):
        h.observe(v)
    cum = h._children[()].cumulative_buckets()
    assert cum == [(1.0, 2), (2.0, 3), (4.0, 4), (math.inf, 5)]
    assert h.count == 5


def test_histogram_quantile_interpolation():
    reg = metrics.Registry()
    h = reg.histogram("t_q", "x", buckets=(1.0, 2.0, 4.0))
    assert math.isnan(h.quantile(0.5))  # empty
    for _ in range(10):
        h.observe(1.5)  # all land in (1, 2]
    # rank 5 of 10 inside [1, 2] -> linear midpoint
    assert h.quantile(0.5) == pytest.approx(1.5)
    assert h.quantile(1.0) == pytest.approx(2.0)
    h.observe(1000.0)  # beyond the ladder: +Inf bucket
    assert h.quantile(0.999) == pytest.approx(4.0)  # last finite edge


def test_label_dedup_and_validation():
    reg = metrics.Registry()
    c = reg.counter("t_l", "x", labelnames=("a", "b"))
    c1 = c.labels(a="1", b="2")
    c2 = c.labels(b="2", a="1")
    assert c1 is c2  # one child per distinct value tuple, ever
    c1.inc()
    assert c.labels("1", "2").value == 1
    with pytest.raises(ValueError):
        c.labels(a="1")  # missing label
    with pytest.raises(ValueError):
        c.labels(a="1", b="2", z="3")  # unknown label
    with pytest.raises(ValueError):
        c.inc()  # labelled family needs .labels()


def test_reregistration_returns_same_family_or_raises():
    reg = metrics.Registry()
    a = reg.counter("t_r", "x")
    assert reg.counter("t_r", "x") is a
    with pytest.raises(ValueError):
        reg.gauge("t_r", "x")
    with pytest.raises(ValueError):
        reg.counter("t_r", "x", labelnames=("l",))
    with pytest.raises(ValueError):
        reg.counter("bad name", "x")


# -- exposition golden ---------------------------------------------------

def test_prometheus_exposition_golden():
    """The full text format, pinned: HELP/TYPE comments, label
    escaping, cumulative histogram buckets with +Inf, _sum/_count, and
    deterministic family/series ordering."""
    reg = metrics.Registry()
    h = reg.histogram("t_lat_seconds", "Latency.",
                      buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)
    h.observe(5.0)
    c = reg.counter("t_requests_total", "Total requests.",
                    labelnames=("code",))
    c.labels(code="200").inc(3)
    c.labels(code='5"00').inc()  # a quote that must escape
    g = reg.gauge("t_temp_celsius", "Temp.")
    g.set(21.5)
    expected = "\n".join([
        "# HELP t_lat_seconds Latency.",
        "# TYPE t_lat_seconds histogram",
        't_lat_seconds_bucket{le="0.1"} 1',
        't_lat_seconds_bucket{le="1"} 1',
        't_lat_seconds_bucket{le="10"} 2',
        't_lat_seconds_bucket{le="+Inf"} 2',
        "t_lat_seconds_sum 5.05",
        "t_lat_seconds_count 2",
        "# HELP t_requests_total Total requests.",
        "# TYPE t_requests_total counter",
        't_requests_total{code="200"} 3',
        't_requests_total{code="5\\"00"} 1',
        "# HELP t_temp_celsius Temp.",
        "# TYPE t_temp_celsius gauge",
        "t_temp_celsius 21.5",
    ]) + "\n"
    assert reg.expose() == expected


def test_exposition_validates_and_snapshot_roundtrips(tmp_path):
    """The process-wide registry's exposition passes the CI validator,
    and the JSON snapshot agrees with the text counters."""
    metrics.arm()
    metrics.record_solve(0.01, 25, True, solver="unit-test")
    path = tmp_path / "m.prom"
    metrics.write_textfile(path)
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS,
                                      "check_metrics_textfile.py"),
         str(path), "--require", "acg_solves_total",
         "--require", "acg_solve_seconds"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    snap = metrics.snapshot_dict()
    total = sum(s["value"]
                for s in snap["acg_solves_total"]["samples"]
                if s["labels"].get("solver") == "unit-test")
    assert total >= 1
    assert snap["acg_solve_seconds"]["type"] == "histogram"


def test_textfile_flush_is_atomic_rename(tmp_path):
    """write_textfile leaves no temp droppings and replaces in place."""
    path = tmp_path / "out.prom"
    metrics.write_textfile(path)
    first = path.read_text()
    metrics.write_textfile(path)
    assert path.read_text().startswith("# HELP")
    assert first.startswith("# HELP")
    assert [p for p in os.listdir(tmp_path)
            if p.startswith("out.prom.tmp")] == []


def test_http_endpoint_serves_metrics():
    metrics.arm()
    server = metrics.serve(0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read()
        assert b"acg_solves_total" in body
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=30)
    finally:
        server.shutdown()
        server.server_close()


def test_disarmed_hooks_are_noops():
    metrics.disarm()
    before = metrics.SOLVE_SECONDS.count
    metrics.record_solve(1.0, 10, True)
    metrics.record_phase("solve", 1.0)
    metrics.record_event_kind("breakdown")
    assert metrics.SOLVE_SECONDS.count == before


# -- drift detector ------------------------------------------------------

def test_drift_detector_trips_deterministically():
    det = soak.DriftDetector(nsolves=10, threshold_pct=20.0)
    for i in range(det.nbaseline):
        assert det.update(i, 0.010) is False
    assert det.baseline == pytest.approx(0.010)
    tripped = []
    for i in range(det.nbaseline, 10):
        if det.update(i, 0.030):  # 3x the baseline
            tripped.append(i)
    assert len(tripped) == 1  # structured event fires ONCE
    assert det.to_dict()["tripped"] is True
    assert det.ratio > 1.2


def test_drift_detector_stable_latency_never_trips():
    det = soak.DriftDetector(nsolves=20, threshold_pct=20.0)
    assert not any(det.update(i, 0.010 + (i % 3) * 1e-4)
                   for i in range(20))
    assert det.to_dict()["tripped"] is False


# -- soak driver ---------------------------------------------------------

def test_soak_smoke_three_solves(csr):
    """The satellite's 3-solve smoke: report shape, registry feed, and
    the stats section landing on the solver."""
    s = _jax_solver(csr)
    b = np.ones(csr.shape[0])
    before = metrics.SOLVE_SECONDS.count
    x, report = soak.run_soak(
        s, b, nsolves=3,
        criteria=StoppingCriteria(maxits=60, residual_rtol=1e-8))
    assert np.linalg.norm(b - csr @ np.asarray(x, np.float64)) \
        <= 1e-6 * np.linalg.norm(b)
    assert report["nsolves"] == 3
    assert report["latency"]["p50"] > 0
    assert report["iterations"]["p50"] > 0
    assert report["drift"]["tripped"] is False
    assert report["drift"]["baseline_solves"] == 3
    assert s.stats.soak is report and s.stats.nsolves == 3
    # the solvers fed the process-wide histograms too (metrics armed
    # by the driver)
    assert metrics.SOLVE_SECONDS.count >= before + 3


def test_soak_slow_fault_trips_detector(csr):
    """solve:slow@K dilates solves from index K inside the timed
    window; the EWMA detector must trip and record ONE drift event."""
    from acg_tpu import faults

    s = _jax_solver(csr)
    b = np.ones(csr.shape[0])
    drift_ctr = metrics.EVENTS.labels(kind="drift")
    before = drift_ctr.value
    with faults.injected("solve:slow@4:secs=0.05"):
        x, report = soak.run_soak(
            s, b, nsolves=10, fail_on_drift=20.0,
            criteria=StoppingCriteria(maxits=30,
                                      residual_rtol=1e-8),
            solve_kwargs={"raise_on_divergence": False})
    assert report["drift"]["tripped"] is True
    # the by-kind counter and stats.events must AGREE: one trip, one
    # increment (record_event routes to the counter; no double count)
    assert drift_ctr.value == before + 1
    assert report["drift"]["tripped_at_solve"] >= 4
    drift_events = [e for e in s.stats.events if e["kind"] == "drift"]
    assert len(drift_events) == 1
    assert soak.gate_exit_code(report, 20.0) == soak.DRIFT_EXIT_CODE
    assert soak.gate_exit_code(report, None) == 0  # gate needs the flag


def test_solve_slow_spec_parsing():
    from acg_tpu import faults

    spec = faults.parse_fault_spec("solve:slow@10:secs=0.25")
    assert (spec.site, spec.mode, spec.iteration, spec.secs) == \
        ("solve", "slow", 10, 0.25)
    assert not spec.device_site
    with pytest.raises(ValueError):
        faults.parse_fault_spec("solve:slow@10")  # secs is mandatory
    with pytest.raises(ValueError):
        faults.parse_fault_spec("solve:nan@10:secs=1")


# -- CLI end-to-end ------------------------------------------------------

def test_cli_soak_acceptance(tmp_path):
    """The acceptance criterion: one --soak run produces a textfile the
    format validator accepts, p50/p95/p99 latency + iteration
    histograms in the /3 stats document, and the soak: stats section."""
    prom = tmp_path / "m.prom"
    stats = tmp_path / "s.json"
    r = run_cli(["gen:poisson2d:12", "--comm", "none",
                 "--max-iterations", "200", "--residual-rtol", "1e-8",
                 "--warmup", "1", "--quiet", "--soak", "6",
                 "--metrics-file", str(prom),
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    assert "soak:" in r.stderr  # the stats section rendered
    v = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS,
                                      "check_metrics_textfile.py"),
         str(prom), "--require", "acg_solves_total",
         "--require", "acg_solve_seconds",
         "--require", "acg_solve_iterations",
         "--require", "acg_process_resident_bytes"],
        capture_output=True, text=True, timeout=120)
    assert v.returncode == 0, v.stderr + v.stdout
    doc = json.loads(stats.read_text())
    assert doc["schema"] == "acg-tpu-stats/12"
    sk = doc["stats"]["soak"]
    assert sk["nsolves"] == 6
    for k in ("p50", "p95", "p99"):
        assert sk["latency"][k] > 0
        assert sk["iterations"][k] > 0
    assert doc["metrics"]["acg_solve_seconds"]["samples"][0]["count"] \
        >= 6
    # RSS gauge carries a real value
    rss = doc["metrics"]["acg_process_resident_bytes"]["samples"][0]
    assert rss["value"] > 1e6


def test_cli_soak_drift_gate_exit_code(tmp_path):
    """The injected slowdown trips --fail-on-drift: exit 7, a drift
    event in the stats document."""
    stats = tmp_path / "s.json"
    r = run_cli(["gen:poisson2d:12", "--comm", "none",
                 "--max-iterations", "100", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--soak", "12",
                 "--fail-on-drift", "20",
                 "--fault-inject", "solve:slow@6:secs=0.05",
                 "--stats-json", str(stats)])
    assert r.returncode == soak.DRIFT_EXIT_CODE, r.stderr
    assert "latency drift" in r.stderr
    doc = json.loads(stats.read_text())
    assert any(e["kind"] == "drift" for e in doc["stats"]["events"])
    assert doc["stats"]["soak"]["drift"]["tripped"] is True


def test_cli_soak_flag_validation():
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--fail-on-drift", "10"])
    assert r.returncode != 0 and "--fail-on-drift needs --soak" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--soak", "2", "--refine"])
    assert r.returncode != 0 and "--soak does not support" in r.stderr
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--soak", "3", "--fail-on-drift", "-5"])
    assert r.returncode != 0 and "must be positive" in r.stderr
    # a gate whose baseline window consumes the whole run could never
    # trip -- it must refuse, not green CI silently
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--soak", "3", "--fail-on-drift", "20"])
    assert r.returncode != 0 and "vacuous" in r.stderr


def test_failed_validation_never_clobbers_textfile(tmp_path):
    """A run that dies in flag validation ran nothing: it must not
    replace the last healthy run's textfile with an all-zeros scrape."""
    prom = tmp_path / "m.prom"
    prom.write_text("# last healthy capture\n")
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--soak", "3", "--fail-on-drift", "20",
                 "--metrics-file", str(prom)])
    assert r.returncode != 0
    assert prom.read_text() == "# last healthy capture\n"


def test_gate_is_vacuous_boundary():
    assert soak.gate_is_vacuous(3)
    assert not soak.gate_is_vacuous(4)  # one evaluated solve
    assert not soak.gate_is_vacuous(50)
    with pytest.raises(ValueError):
        # library route refuses the same way the CLI does
        soak.run_soak(object(), None, nsolves=3, fail_on_drift=10.0)
    r = run_cli(["gen:poisson2d:12", "--comm", "none", "--quiet",
                 "--fault-inject", "solve:slow@2:secs=0.01"])
    assert r.returncode != 0 and "--soak N" in r.stderr


def test_cli_soak_dist_solver(tmp_path):
    """Soak over the distributed solver on the 8-device mesh: the comm
    ledger feeds the halo/psum byte counters."""
    prom = tmp_path / "m.prom"
    r = run_cli(["gen:poisson2d:16", "--nparts", "4",
                 "--max-iterations", "200", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--soak", "3",
                 "--metrics-file", str(prom)])
    assert r.returncode == 0, r.stderr
    text = prom.read_text()
    halo = [ln for ln in text.splitlines()
            if ln.startswith("acg_halo_bytes_total")][0]
    psum = [ln for ln in text.splitlines()
            if ln.startswith("acg_allreduce_bytes_total")][0]
    assert float(halo.split()[-1]) > 0
    assert float(psum.split()[-1]) > 0


# -- /3 round-trip through bench_diff ------------------------------------

def _soak_doc(metric: str, p50_lat: float, p50_its: float) -> dict:
    return {"schema": "acg-tpu-stats/3",
            "manifest": {"schema": "acg-tpu-stats/3", "metric": metric},
            "stats": {"niterations": 0, "tsolve": 0.0,
                      "soak": {"nsolves": 5,
                               "latency": {"p50": p50_lat},
                               "iterations": {"p50": p50_its}}}}


def test_bench_diff_soak_captures(tmp_path):
    """Two /3 soak documents diff case-by-case on the p50 figure: a
    slower candidate regresses, an equal one passes."""
    base = tmp_path / "base.jsonl"
    good = tmp_path / "good.jsonl"
    bad = tmp_path / "bad.jsonl"
    base.write_text(json.dumps(_soak_doc("soak_case", 0.010, 100)) + "\n")
    good.write_text(json.dumps(_soak_doc("soak_case", 0.010, 100)) + "\n")
    bad.write_text(json.dumps(_soak_doc("soak_case", 0.020, 100)) + "\n")
    script = os.path.join(SCRIPTS, "bench_diff.py")

    def diff(a, b):
        return subprocess.run(
            [sys.executable, script, str(a), str(b),
             "--fail-on-regress", "10"],
            capture_output=True, text=True, timeout=120)

    r = diff(base, good)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 case(s) compared" in r.stdout
    r = diff(base, bad)
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout


def test_cli_soak_stats_json_diffs_itself(tmp_path):
    """A REAL soak capture diffs cleanly against itself through the
    bench gate (the /3 reader path end-to-end)."""
    stats = tmp_path / "s.json"
    r = run_cli(["gen:poisson2d:12", "--comm", "none",
                 "--max-iterations", "100", "--residual-rtol", "1e-8",
                 "--warmup", "0", "--quiet", "--soak", "3",
                 "--stats-json", str(stats)])
    assert r.returncode == 0, r.stderr
    d = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "bench_diff.py"),
         str(stats), str(stats)],
        capture_output=True, text=True, timeout=120)
    assert d.returncode == 0, d.stdout + d.stderr
    assert "1 case(s) compared" in d.stdout


# -- tooling: plot_convergence latency inputs ----------------------------

def test_plot_convergence_accepts_metrics_and_stats(tmp_path):
    metrics.arm()
    for v in (0.001, 0.002, 0.002, 0.004):
        metrics.SOLVE_SECONDS.observe(v)
    prom = tmp_path / "m.prom"
    metrics.write_textfile(prom)
    doc = _soak_doc("x", 0.002, 50)
    sj = tmp_path / "s.json"
    sj.write_text(json.dumps(doc))
    script = os.path.join(SCRIPTS, "plot_convergence.py")
    r = subprocess.run(
        [sys.executable, script, str(prom), str(sj), "--ascii"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "p50" in r.stdout and "latency" in r.stdout


def test_buildinfo_advertises_service_metrics():
    r = run_cli(["--buildinfo"])
    assert r.returncode == 0, r.stderr
    assert "--metrics-file" in r.stdout
    assert "--soak" in r.stdout
    assert "--fail-on-drift" in r.stdout
    assert "acg-tpu-stats/12" in r.stdout
