"""Communication observatory (acg_tpu.commbench): the alpha-beta fit,
the 8-part mesh collective sweeps, per-edge one-sided DMA timing in
interpret mode, measured segment decomposition, document validation +
bench_diff keying, disarmed byte-identity pins, and the CLI
``--commbench`` / ``--calibration`` acceptance path."""

import gzip
import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from acg_tpu import commbench as cb

_ENV = {"JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}


def _mesh(nparts=8):
    from acg_tpu.parallel.mesh import solve_mesh
    return solve_mesh(nparts)


def _dist_solver(side=16, nparts=8, pipelined=False, comm="xla"):
    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows

    r, c, v, n = poisson2d_coo(side)
    csr = SymCsrMatrix.from_coo(n, r, c, v).to_csr()
    part = partition_rows(csr, nparts, seed=42, method="band")
    prob = DistributedProblem.build(csr, part, nparts)
    return DistCGSolver(prob, pipelined=pipelined, comm=comm), csr


# -- the alpha-beta fit --------------------------------------------------

def test_fit_recovers_known_alpha_beta():
    """Synthetic timings t = alpha + beta*bytes (+ 2% noise) recover
    alpha and beta within a band."""
    alpha, beta = 5e-5, 2e-9
    rng = np.random.default_rng(7)
    pts = []
    for b in (64, 1024, 16384, 262144, 4194304):
        t = (alpha + beta * b) * (1.0 + 0.02 * rng.standard_normal())
        pts.append((b, t))
    fit = cb.fit_alpha_beta(pts)
    assert fit["npoints"] == 5
    assert abs(fit["alpha_s"] - alpha) / alpha < 0.25
    assert abs(fit["beta_s_per_byte"] - beta) / beta < 0.25
    assert fit["r2"] > 0.99
    assert cb.predict_seconds(fit, 0) == pytest.approx(fit["alpha_s"])


def test_fit_clamps_nonnegative_and_degrades():
    # decreasing times (noise): beta clamps to 0, alpha = mean
    fit = cb.fit_alpha_beta([(64, 3e-5), (65536, 1e-5)])
    assert fit["beta_s_per_byte"] == 0.0
    assert fit["alpha_s"] == pytest.approx(2e-5)
    # nothing usable
    assert cb.fit_alpha_beta([]) is None
    assert cb.fit_alpha_beta([(64, -1.0)]) is None
    # one point: pure-bandwidth attribution
    one = cb.fit_alpha_beta([(1024, 1e-6)])
    assert one["alpha_s"] == 0.0
    assert one["beta_s_per_byte"] == pytest.approx(1e-6 / 1024)
    assert cb.predict_seconds(None, 10) is None


# -- mesh microbenchmarks ------------------------------------------------

def test_collective_sweep_on_8part_mesh():
    """The message-size sweep runs every XLA collective kind over the
    8-part CPU mesh and yields usable nonnegative fits with per-point
    provenance."""
    colls = cb.bench_collectives(_mesh(), (256, 8192), reps=6,
                                 repeats=2)
    for kind in ("all_reduce", "all_to_all", "collective_permute"):
        entry = colls[kind]
        assert entry["alpha_s"] >= 0.0, kind
        assert entry["beta_s_per_byte"] >= 0.0, kind
        assert len(entry["points"]) == 2
        for p in entry["points"]:
            assert p["seconds"] > 0 and p["bytes"] > 0
    # the all_to_all plane realises the requested per-shard payload
    assert colls["all_to_all"]["points"][1]["bytes"] == 8192


def test_dma_per_edge_timing_interpret_mode():
    """Per-edge put/wait rows by ring distance on the 8-part interpret
    mesh: one row per distance 1..4, positive seconds, and the
    antipodal distance has a single peer per shard."""
    rows = cb.bench_dma_edges(_mesh(), 2048, reps=6, repeats=2)
    assert [r["distance"] for r in rows] == [1, 2, 3, 4]
    for r in rows:
        assert r["put_wait_seconds"] > 0
        assert r["window_bytes"] == 2048
    assert rows[-1]["peers_per_shard"] == 1
    assert all(r["peers_per_shard"] == 2 for r in rows[:-1])
    # the dense sweep fits too
    dense = cb.bench_dma(_mesh(), (512, 4096), reps=6, repeats=2)
    assert dense["alpha_s"] >= 0 and len(dense["points"]) == 2


# -- segment decomposition ----------------------------------------------

def test_segment_decomposition_sums_to_measured_band():
    """The measured SpMV/halo/reduction split approximates the measured
    s/iter of the same dist solve: every segment positive, halo
    strictly inside the SpMV segment's scope, and explained/measured
    within a CI-noise-tolerant band."""
    from acg_tpu.solvers.stats import StoppingCriteria

    solver, _csr = _dist_solver()
    b = np.ones(solver.problem.n)
    segs = cb.segment_decomposition(solver, b, reps=12, repeats=3)
    assert segs["available"], segs
    names = set(segs["segments"])
    assert names == {"spmv", "halo", "reduction"}
    for seg in segs["segments"].values():
        assert seg["s_per_iteration"] > 0
    # classic CG: two reductions, one halo'd SpMV per iteration
    assert segs["segments"]["reduction"]["calls_per_iteration"] == 2.0
    assert segs["segments"]["spmv"]["calls_per_iteration"] == 1.0
    K = 25
    best = math.inf
    for _ in range(3):
        solver.stats.tsolve = 0.0
        solver.solve(b, criteria=StoppingCriteria(maxits=K), warmup=1,
                     host_result=False, raise_on_divergence=False)
        best = min(best, solver.stats.tsolve / K)
    ratio = segs["explained_s_per_iteration"] / best
    assert 0.15 <= ratio <= 3.5, (segs, best)


def test_pipelined_reduction_probe_is_fused():
    """The pipelined tier's reduction probe reproduces the ONE fused
    2-scalar ladder (calls/iter = 1), not two classic pdots."""
    solver, _ = _dist_solver(pipelined=True)
    segs = cb.segment_decomposition(solver, np.ones(solver.problem.n),
                                    reps=6, repeats=2)
    assert segs["available"], segs
    assert segs["segments"]["reduction"]["calls_per_iteration"] == 1.0


def test_probes_leave_solve_programs_byte_identical():
    """The disarmed pin: building + running segment probes and the
    collective microbenchmarks must leave every dispatched solve
    program byte-identical (StableHLO), dist AND single-chip."""
    import jax.numpy as jnp
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver

    solver, csr = _dist_solver()
    b = np.ones(solver.problem.n)
    before = solver.lower_solve(b).as_text()
    A = device_matrix_from_csr(csr, dtype=jnp.float64, format="auto")
    s1 = JaxCGSolver(A, kernels="xla")
    b1 = jnp.asarray(b, s1._solve_dtype())
    before1 = s1.lower_solve(b1).as_text()
    assert cb.segment_decomposition(solver, b, reps=4,
                                    repeats=1)["available"]
    assert cb.segment_decomposition(s1, b1, reps=4,
                                    repeats=1)["available"]
    cb.bench_collectives(_mesh(), (256,), reps=2, repeats=1)
    cb.bench_dma_edges(_mesh(), 256, reps=2, repeats=1)
    assert solver.lower_solve(b).as_text() == before
    assert s1.lower_solve(b1).as_text() == before1


# -- document validation + calibrated pricing ----------------------------

def _minimal_doc(**over):
    doc = {"schema": cb.COMMBENCH_SCHEMA, "nparts": 8,
           "collectives": {
               "all_reduce": {"alpha_s": 1e-5, "beta_s_per_byte": 0.0,
                              "npoints": 1, "r2": None,
                              "points": [{"bytes": 8,
                                          "seconds": 1e-5}]},
               "all_to_all": {"alpha_s": 2e-5,
                              "beta_s_per_byte": 1e-9,
                              "npoints": 1, "r2": None,
                              "points": [{"bytes": 1024,
                                          "seconds": 2.1e-5}]}}}
    doc.update(over)
    doc["calibration_id"] = cb.calibration_id(doc)
    return doc


def test_validator_roundtrip_and_tamper_detection(tmp_path):
    doc = _minimal_doc()
    assert cb.validate_commbench(doc) == []
    p = tmp_path / "cal.json"
    p.write_text(json.dumps(doc))
    assert cb.load_calibration(p)["calibration_id"] == \
        doc["calibration_id"]
    # tamper: content no longer matches the id
    tampered = dict(doc, nparts=4)
    assert any("calibration_id" in w
               for w in cb.validate_commbench(tampered))
    # wrong schema / not json
    assert cb.validate_commbench({"schema": "nope"})
    (tmp_path / "garbage.json").write_text("{torn")
    with pytest.raises(ValueError):
        cb.load_calibration(tmp_path / "garbage.json")
    # malformed VALUES become named problems, never exceptions --
    # rejecting such docs gracefully is the validator's whole job
    mangled = _minimal_doc()
    mangled["collectives"]["all_reduce"]["points"] = [
        {"bytes": "oops", "seconds": 1}]
    mangled["calibration_id"] = cb.calibration_id(mangled)
    assert any("bad point" in w for w in cb.validate_commbench(mangled))
    for bad_alpha in ("abc", None, True):
        m2 = _minimal_doc()
        m2["collectives"]["all_reduce"]["alpha_s"] = bad_alpha
        m2["calibration_id"] = cb.calibration_id(m2)
        assert any("alpha/beta" in w
                   for w in cb.validate_commbench(m2)), bad_alpha
    m3 = _minimal_doc(edges=[{"distance": "x"}])
    assert any("edge" in w for w in cb.validate_commbench(m3))


def test_calibrated_comm_pricing_math():
    cal = _minimal_doc()
    led = {"transport": "xla", "nparts": 8,
           "allreduce_per_iteration": 2, "allreduce_scalars": 1,
           "allreduce_bytes_per_iteration": 16,
           "halo_bytes_per_iteration": 800,
           "halo_exchanges_per_iteration": 1,
           "halo_plane_bytes_per_exchange": 1000}
    cs = cb.comm_seconds(cal, led)
    assert cs["allreduce_s"] == pytest.approx(2 * 1e-5)
    assert cs["halo_s"] == pytest.approx(2e-5 + 1e-9 * 1000)
    assert cs["halo_kind"] == "all_to_all"
    assert cs["calibration_id"] == cal["calibration_id"]
    # the dma transport falls back to the all_to_all fit when no dma
    # kind was benchmarked -- and the reported kind names the fit
    # actually used, not the transport's wish
    led_dma = dict(led, transport="dma")
    assert cb.comm_seconds(cal, led_dma)["halo_kind"] == "all_to_all"
    with_dma = _minimal_doc(collectives={
        **cal["collectives"],
        "dma": {"alpha_s": 4e-5, "beta_s_per_byte": 2e-9,
                "npoints": 1, "r2": None,
                "points": [{"bytes": 512, "seconds": 4.1e-5}]}})
    cs_dma = cb.comm_seconds(with_dma, led_dma)
    assert cs_dma["halo_kind"] == "dma"
    assert cs_dma["halo_s"] == pytest.approx(4e-5 + 2e-9 * 1000)
    # errored/absent ledgers refuse
    assert cb.comm_seconds(cal, {"error": "x"}) is None


def test_ledger_carries_plane_bytes_and_ring_distances():
    """The dist comm ledger declares the padded plane bytes the
    transport actually moves and the ring distances its edges span --
    the keys calibrated pricing and the per-edge rows match on."""
    solver, _ = _dist_solver()
    led = solver.comm_profile()
    maxcnt = solver.problem.halo.maxcnt
    dbl = np.dtype(solver.problem.vdtype).itemsize
    assert led["halo_plane_bytes_per_exchange"] == 8 * maxcnt * dbl
    assert led["ring_distances"] == [1]
    sd = cb.halo_exchange_seconds(_minimal_doc(), led)
    assert sd == pytest.approx(
        2e-5 + 1e-9 * led["halo_plane_bytes_per_exchange"])


def test_bench_diff_keys_calibrations_apart(tmp_path):
    """Differently-calibrated captures become distinct, not-silently-
    comparable cases; uncalibrated captures keep their old keys."""
    from acg_tpu import perfmodel

    def doc(cal, val):
        return {"schema": "acg-tpu-stats/11",
                "manifest": {"metric": "m1", "calibration": cal},
                "stats": {"tsolve": 1.0, "niterations": val}}

    a = tmp_path / "a.jsonl"
    a.write_text(json.dumps(doc("cb-cpu-8p-aaaa", 100)) + "\n")
    b = tmp_path / "b.jsonl"
    b.write_text(json.dumps(doc("cb-cpu-8p-bbbb", 50)) + "\n")
    u = tmp_path / "u.jsonl"
    u.write_text(json.dumps(doc("uncalibrated", 75)) + "\n")
    ca, cbb, cu = (perfmodel.load_cases(p) for p in (a, b, u))
    assert list(ca) == ["m1|cal=cb-cpu-8p-aaaa"]
    assert list(cbb) == ["m1|cal=cb-cpu-8p-bbbb"]
    assert list(cu) == ["m1"]  # the sentinel adds nothing
    lines, nreg, ncmp = perfmodel.compare_cases(ca, cbb, 10.0)
    assert ncmp == 0 and nreg == 0  # keyed apart, never gated
    # bench rows key the same way
    key, _ = perfmodel._row_case({"metric": "m1", "value": 1.0,
                                  "calibration": "cb-x-2p-cc"})
    assert key == "m1|cal=cb-x-2p-cc"


# -- the probe-cache sidecar ---------------------------------------------

def test_triad_probe_cache_sidecar(tmp_path, monkeypatch):
    """Backend-keyed on-disk cache: the second call reads the sidecar,
    use_cache=False and refresh=True re-measure (refresh still updates
    the sidecar)."""
    from acg_tpu import perfmodel

    calls = {"n": 0}

    def fake_probe(nelems, **kw):
        calls["n"] += 1
        return 123.0 + calls["n"]

    monkeypatch.setattr(perfmodel, "triad_probe_gbs", fake_probe)
    monkeypatch.setenv("ACG_TPU_PROBE_CACHE",
                       str(tmp_path / "probe.json"))
    bw1 = perfmodel.cached_triad_probe_gbs(999)
    assert bw1 == 124.0 and calls["n"] == 1
    assert perfmodel.cached_triad_probe_gbs(999) == 124.0
    assert calls["n"] == 1  # sidecar hit, no re-probe
    cache = json.loads((tmp_path / "probe.json").read_text())
    (key,) = cache.keys()
    assert key.endswith(":n999") and cache[key]["gbs"] == 124.0
    # a different size is a different key
    perfmodel.cached_triad_probe_gbs(1000)
    assert calls["n"] == 2
    # --no-probe-cache: re-measure (3rd probe call), sidecar untouched
    assert perfmodel.cached_triad_probe_gbs(999,
                                            use_cache=False) == 126.0
    assert json.loads((tmp_path
                       / "probe.json").read_text())[key]["gbs"] == 124.0
    # refresh: re-measure (4th call) AND update the sidecar
    assert perfmodel.cached_triad_probe_gbs(999, refresh=True) == 127.0
    assert json.loads((tmp_path
                       / "probe.json").read_text())[key]["gbs"] == 127.0


# -- tracing per-kind breakdown ------------------------------------------

def test_trace_analysis_breaks_collectives_out_by_kind(tmp_path):
    """analyze_trace reports per-kind collective seconds (all_reduce /
    all_to_all / collective_permute) instead of one pooled figure --
    the row the commbench fit is confronted with."""
    from acg_tpu import tracing

    us = 1e6
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "name": "solve",
         "ts": 0.0, "dur": 10.0 * us},
        {"ph": "X", "pid": 1, "tid": 3, "name": "all-reduce.1",
         "ts": 1.0 * us, "dur": 2.0 * us},
        {"ph": "X", "pid": 1, "tid": 3, "name": "all-to-all.4",
         "ts": 4.0 * us, "dur": 1.0 * us},
        {"ph": "X", "pid": 1, "tid": 3, "name": "collective-permute.2",
         "ts": 6.0 * us, "dur": 0.5 * us},
    ]
    d = tmp_path / "plugins" / "profile" / "r"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"displayTimeUnit": "ns", "metadata": {},
                   "traceEvents": events}, f)
    an = tracing.analyze_trace(tmp_path)
    assert an["available"]
    kinds = an["collective_kind_seconds"]
    assert kinds["all_reduce"] == pytest.approx(2.0)
    assert kinds["all_to_all"] == pytest.approx(1.0)
    assert kinds["collective_permute"] == pytest.approx(0.5)
    assert an["collective_kind_seconds_in_solve"]["all_reduce"] == \
        pytest.approx(2.0)
    assert sum(kinds.values()) == pytest.approx(
        an["collective_seconds"])
    assert any("collectives by kind" in ln
               for ln in tracing.format_analysis(an))


# -- CLI: --commbench / --calibration ------------------------------------

def _run_cli(argv, timeout=600):
    env = dict(os.environ)
    env.update(_ENV)
    return subprocess.run([sys.executable, "-m", "acg_tpu.cli"] + argv,
                          capture_output=True, text=True, env=env,
                          timeout=timeout)


@pytest.fixture(scope="module")
def commbench_doc(tmp_path_factory):
    """One subprocess --commbench run shared by the CLI tests."""
    out = tmp_path_factory.mktemp("cb") / "cal.json"
    r = _run_cli(["gen:poisson2d:16", "--commbench", str(out),
                  "--nparts", "8", "--dtype", "f32",
                  "--max-iterations", "20", "--warmup", "0", "-q"])
    assert r.returncode == 0, r.stderr
    assert "calibration id: cb-cpu-8p-" in r.stderr
    return out


def test_cli_commbench_document_validates(commbench_doc):
    doc = json.loads(commbench_doc.read_text())
    assert cb.validate_commbench(doc) == []
    assert doc["schema"] == cb.COMMBENCH_SCHEMA
    for kind in ("all_reduce", "all_to_all", "collective_permute",
                 "dma"):
        assert "alpha_s" in doc["collectives"][kind], kind
    assert [e["distance"] for e in doc["edges"]] == [1, 2, 3, 4]
    assert doc["segments"]["available"] is True
    assert doc["case"]["measured_s_per_iteration"] > 0


def test_cli_calibrated_explain_beats_uncalibrated(commbench_doc,
                                                   tmp_path):
    """THE acceptance criterion: on the 8-part CPU mesh,
    ``--explain --calibration <doc>`` reports a predicted-vs-measured
    s/iter ratio strictly closer to 1.0 than the uncalibrated verdict
    on the same case, with calibration provenance printed and recorded
    in the stats manifest."""
    sj = tmp_path / "explain.jsonl"
    r = _run_cli(["gen:poisson2d:16", "--explain", "--calibration",
                  str(commbench_doc), "--nparts", "8", "--dtype",
                  "f32", "--max-iterations", "20", "--warmup", "0",
                  "--stats-json", str(sj), "-q"])
    assert r.returncode == 0, r.stderr
    cal_id = json.loads(commbench_doc.read_text())["calibration_id"]
    assert "== explain: calibration ==" in r.stderr
    assert cal_id in r.stderr
    docs = [json.loads(ln) for ln in sj.read_text().splitlines()
            if ln.strip()]
    dist = [d for d in docs
            if "dist-cg" in d["manifest"]["metric"]]
    assert dist, [d["manifest"]["metric"] for d in docs]
    row = dist[0]["manifest"]["explain"]
    meas = row["measured_s_per_iter"]
    ratio = row["predicted_s_per_iter"] / meas
    ratio_uncal = row["uncalibrated_predicted_s_per_iter"] / meas
    assert abs(math.log(ratio)) < abs(math.log(ratio_uncal)), row
    assert row["calibration"] == cal_id
    assert dist[0]["manifest"]["calibration"] == cal_id
    assert row["segments"]["available"] is True
    assert "segments" in dist[0]["stats"]["costmodel"]
    assert dist[0]["stats"]["costmodel"]["calibration"] == cal_id


def test_cli_solve_records_calibration_provenance(commbench_doc,
                                                  tmp_path):
    """A NORMAL solve under --calibration stamps the id on the stats
    manifest and the convergence-log meta line; without one both say
    'uncalibrated'."""
    sj = tmp_path / "solve.jsonl"
    cl = tmp_path / "conv.jsonl"
    r = _run_cli(["gen:poisson2d:16", "--comm", "none",
                  "--max-iterations", "100", "--residual-rtol", "1e-8",
                  "--warmup", "0", "-q", "--calibration",
                  str(commbench_doc), "--stats-json", str(sj),
                  "--convergence-log", str(cl)])
    assert r.returncode == 0, r.stderr
    cal_id = json.loads(commbench_doc.read_text())["calibration_id"]
    doc = json.loads(sj.read_text())
    assert doc["schema"] == "acg-tpu-stats/12"
    assert doc["manifest"]["calibration"] == cal_id
    meta = json.loads(cl.read_text().splitlines()[0])
    assert meta["meta"] is True and meta["calibration"] == cal_id
    # uncalibrated twin
    r2 = _run_cli(["gen:poisson2d:16", "--comm", "none",
                   "--max-iterations", "100", "--residual-rtol",
                   "1e-8", "--warmup", "0", "-q", "--stats-json",
                   str(tmp_path / "u.jsonl"), "--convergence-log",
                   str(tmp_path / "uc.jsonl")])
    assert r2.returncode == 0, r2.stderr
    u = json.loads((tmp_path / "u.jsonl").read_text())
    assert u["manifest"]["calibration"] == "uncalibrated"
    umeta = json.loads((tmp_path
                        / "uc.jsonl").read_text().splitlines()[0])
    assert umeta["calibration"] == "uncalibrated"


def test_cli_commbench_and_calibration_refusals(tmp_path):
    """Validation: two calibration sources refuse, a garbage/missing
    --calibration file refuses self-describingly, --commbench refuses
    fault injection and solve-output flags."""
    r = _run_cli(["gen:poisson2d:8", "--commbench", "--calibration",
                  "x.json"], timeout=120)
    assert r.returncode != 0
    assert "two calibration sources" in r.stderr
    r = _run_cli(["gen:poisson2d:8", "--explain", "--calibration",
                  str(tmp_path / "missing.json")], timeout=120)
    assert r.returncode != 0 and "--calibration" in r.stderr
    bad = tmp_path / "bad.json"
    bad.write_text('{"schema": "nope"}')
    r = _run_cli(["gen:poisson2d:8", "--explain", "--calibration",
                  str(bad)], timeout=120)
    assert r.returncode != 0
    assert "not a valid acg-tpu-commbench/1" in r.stderr
    r = _run_cli(["gen:poisson2d:8", "--commbench", "--fault-inject",
                  "spmv:nan@3"], timeout=120)
    assert r.returncode != 0 and "PRISTINE" in r.stderr
    r = _run_cli(["gen:poisson2d:8", "--commbench", "--soak", "3"],
                 timeout=120)
    assert r.returncode != 0 and "measurement pass" in r.stderr
    r = _run_cli(["gen:poisson2d:8", "--commbench", "/tmp/x.json",
                  "--stats-json", "/tmp/s.jsonl"], timeout=120)
    assert r.returncode != 0 and "--stats-json" in r.stderr
    r = _run_cli(["gen:poisson2d:8", "--commbench", "--multihost"],
                 timeout=120)
    assert r.returncode != 0 and "single-controller" in r.stderr
