/* Matrix Market data-section parsing and formatting.
 *
 * The role of the reference's per-line parse loops (acg/mtxfile.c:706-728
 * parse_acgidx_t / parse_double) and text writers (mtxfile.c fwrite
 * paths), rebuilt as an OpenMP two-phase parser: phase 1 counts entry
 * lines per chunk (memchr newline scan), phase 2 parses each chunk into
 * its prefix-summed output offset with std::from_chars. */

#include "acg_core.h"

#include <cctype>
#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline const char *skip_ws(const char *p, const char *end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    return p;
}

inline const char *skip_to_eol(const char *p, const char *end) {
    const char *nl = static_cast<const char *>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    return nl ? nl + 1 : end;
}

/* A line counts as an entry if it contains any non-whitespace. */
inline bool line_has_entry(const char *p, const char *end) {
    for (; p < end && *p != '\n'; p++)
        if (*p != ' ' && *p != '\t' && *p != '\r') return true;
    return false;
}

inline const char *parse_i64(const char *p, const char *end, int64_t *out) {
    auto [ptr, ec] = std::from_chars(p, end, *out);
    return ec == std::errc() ? ptr : nullptr;
}

inline const char *parse_f64(const char *p, const char *end, double *out) {
    auto [ptr, ec] = std::from_chars(p, end, *out);
    if (ec == std::errc()) return ptr;
    /* from_chars rejects leading '+' and some exotic spellings; fall back */
    char *e = nullptr;
    *out = strtod(p, &e);
    return (e && e != p && e <= end) ? e : nullptr;
}

/* %.17g formatting via std::to_chars (same output, ~5x faster than
 * snprintf); returns chars written or -1 if the buffer is full. */
inline int format_g17(char *p, char *end, double v) {
    auto [ptr, ec] = std::to_chars(p, end, v, std::chars_format::general, 17);
    return ec == std::errc() ? static_cast<int>(ptr - p) : -1;
}

/* Fast unsigned int formatting; returns chars written or -1. */
inline int format_u64(char *p, char *end, uint64_t v) {
    char tmp[20];
    int n = 0;
    do {
        tmp[n++] = static_cast<char>('0' + v % 10);
        v /= 10;
    } while (v);
    if (end - p < n) return -1;
    for (int i = 0; i < n; i++) p[i] = tmp[n - 1 - i];
    return n;
}

struct Chunk {
    const char *begin;
    const char *end;
    int64_t nentries;
};

/* Split buf into per-thread chunks aligned to line starts and count entry
 * lines in each. */
std::vector<Chunk> scan_chunks(const char *buf, int64_t len) {
#ifdef _OPENMP
    int nthreads = omp_get_max_threads();
#else
    int nthreads = 1;
#endif
    int64_t target = len / nthreads + 1;
    std::vector<Chunk> chunks;
    const char *end = buf + len;
    const char *p = buf;
    while (p < end) {
        const char *cend = p + target < end ? p + target : end;
        if (cend < end) cend = skip_to_eol(cend, end);
        chunks.push_back({p, cend, 0});
        p = cend;
    }
#pragma omp parallel for schedule(static)
    for (size_t c = 0; c < chunks.size(); c++) {
        int64_t n = 0;
        const char *q = chunks[c].begin;
        while (q < chunks[c].end) {
            if (line_has_entry(q, chunks[c].end)) n++;
            q = skip_to_eol(q, chunks[c].end);
        }
        chunks[c].nentries = n;
    }
    return chunks;
}

}  // namespace

extern "C" {

int64_t acg_mtx_parse_coord(const char *buf, int64_t len, int64_t nnz,
                            int64_t nrows, int64_t ncols, int32_t with_vals,
                            int64_t *rowidx, int64_t *colidx, double *vals) {
    auto chunks = scan_chunks(buf, len);
    int64_t total = 0;
    std::vector<int64_t> offs(chunks.size());
    for (size_t c = 0; c < chunks.size(); c++) {
        offs[c] = total;
        total += chunks[c].nentries;
    }
    if (total < nnz) return ACG_NATIVE_ERR_EOF;

    int64_t err = 0;
#pragma omp parallel for schedule(static) reduction(min : err)
    for (size_t c = 0; c < chunks.size(); c++) {
        const char *p = chunks[c].begin;
        const char *cend = chunks[c].end;
        int64_t i = offs[c];
        while (p < cend && i < nnz) {
            const char *line_end = skip_to_eol(p, cend);
            p = skip_ws(p, line_end);
            if (p >= line_end || *p == '\n') {  /* blank line */
                p = line_end;
                continue;
            }
            int64_t r, col;
            double v = 0.0;
            const char *q = parse_i64(p, line_end, &r);
            if (!q) { err = ACG_NATIVE_ERR_INVALID_FORMAT; break; }
            q = skip_ws(q, line_end);
            q = parse_i64(q, line_end, &col);
            if (!q) { err = ACG_NATIVE_ERR_INVALID_FORMAT; break; }
            if (with_vals) {
                q = skip_ws(q, line_end);
                q = parse_f64(q, line_end, &v);
                if (!q) { err = ACG_NATIVE_ERR_INVALID_FORMAT; break; }
            }
            /* reject trailing garbage ("5 7 3junk", extra tokens) */
            q = skip_ws(q, line_end);
            if (q < line_end && *q != '\n') {
                err = ACG_NATIVE_ERR_INVALID_FORMAT;
                break;
            }
            if (r < 1 || r > nrows || col < 1 || col > ncols) {
                err = ACG_NATIVE_ERR_OUT_OF_BOUNDS;
                break;
            }
            rowidx[i] = r - 1;
            colidx[i] = col - 1;
            if (with_vals) vals[i] = v;
            i++;
            p = line_end;
        }
    }
    if (err < 0) return err;
    return nnz;
}

int64_t acg_mtx_parse_array(const char *buf, int64_t len, int64_t n,
                            double *vals) {
    auto chunks = scan_chunks(buf, len);
    /* entry count per chunk = token count; MTX array sections are written
     * one value per line, but accept several per line by re-counting
     * tokens in a sequential pass when the line counts don't match. */
    int64_t total = 0;
    std::vector<int64_t> offs(chunks.size());
    for (size_t c = 0; c < chunks.size(); c++) {
        offs[c] = total;
        total += chunks[c].nentries;
    }
    if (total >= n) {
        int64_t err = 0;
#pragma omp parallel for schedule(static) reduction(min : err)
        for (size_t c = 0; c < chunks.size(); c++) {
            const char *p = chunks[c].begin;
            const char *cend = chunks[c].end;
            int64_t i = offs[c];
            while (p < cend && i < n) {
                const char *line_end = skip_to_eol(p, cend);
                p = skip_ws(p, line_end);
                if (p >= line_end || *p == '\n') { p = line_end; continue; }
                double v;
                const char *q = parse_f64(p, line_end, &v);
                /* multiple tokens on one line: fall back to sequential */
                if (!q || skip_ws(q, line_end) < line_end) {
                    err = ACG_NATIVE_ERR_INVALID_FORMAT;
                    break;
                }
                vals[i++] = v;
                p = line_end;
            }
        }
        if (err == 0) return n;
    }
    /* sequential whitespace-token parse (values not one-per-line) */
    const char *p = buf;
    const char *end = buf + len;
    int64_t i = 0;
    while (i < n) {
        while (p < end && isspace(static_cast<unsigned char>(*p))) p++;
        if (p >= end) return ACG_NATIVE_ERR_EOF;
        const char *q = parse_f64(p, end, &vals[i]);
        if (!q) return ACG_NATIVE_ERR_INVALID_FORMAT;
        i++;
        p = q;
    }
    return n;
}

int64_t acg_mtx_format_coord(int64_t nnz, const int64_t *rowidx,
                             const int64_t *colidx, const double *vals,
                             const char *fmt, char *out, int64_t cap) {
    bool g17 = strcmp(fmt, "%.17g") == 0;
    char *p = out;
    char *end = out + cap;
    for (int64_t i = 0; i < nnz; i++) {
        int k = format_u64(p, end, static_cast<uint64_t>(rowidx[i] + 1));
        if (k < 0) return ACG_NATIVE_ERR_OVERFLOW;
        p += k;
        if (end - p < 2) return ACG_NATIVE_ERR_OVERFLOW;
        *p++ = ' ';
        k = format_u64(p, end, static_cast<uint64_t>(colidx[i] + 1));
        if (k < 0) return ACG_NATIVE_ERR_OVERFLOW;
        p += k;
        if (vals) {
            if (end - p < 2) return ACG_NATIVE_ERR_OVERFLOW;
            *p++ = ' ';
            k = g17 ? format_g17(p, end, vals[i])
                    : snprintf(p, static_cast<size_t>(end - p), fmt, vals[i]);
            if (k < 0 || k >= end - p) return ACG_NATIVE_ERR_OVERFLOW;
            p += k;
        }
        if (end - p < 1) return ACG_NATIVE_ERR_OVERFLOW;
        *p++ = '\n';
    }
    return p - out;
}

int64_t acg_mtx_format_array(int64_t n, const double *vals, const char *fmt,
                             char *out, int64_t cap) {
    bool g17 = strcmp(fmt, "%.17g") == 0;
    char *p = out;
    char *end = out + cap;
    for (int64_t i = 0; i < n; i++) {
        int k = g17 ? format_g17(p, end, vals[i])
                    : snprintf(p, static_cast<size_t>(end - p), fmt, vals[i]);
        if (k < 0 || k >= end - p - 1) return ACG_NATIVE_ERR_OVERFLOW;
        p += k;
        *p++ = '\n';
    }
    return p - out;
}

}  // extern "C"
