/* Symmetric CSR assembly: COO -> packed upper triangle -> full storage.
 *
 * The role of the reference's acgsymcsrmatrix_init_* (COO to packed-upper
 * CSR with radix-sort dedupe, acg/symcsrmatrix.c) and
 * acgsymcsrmatrix_dsymv_init (full-storage expansion with the --epsilon
 * diagonal shift, symcsrmatrix.c:760-862).  Semantics match
 * acg_tpu.matrix.SymCsrMatrix.from_coo / to_csr exactly: entries are
 * mapped to (min,max), duplicates summed, and when both strict triangles
 * were present in the input every off-diagonal sum is halved (full-storage
 * input stores each symmetric entry twice). */

#include "acg_core.h"

#include <vector>

namespace {
/* nrows*nrows must stay below 2^63 for the sort key packing */
const int64_t kMaxKeyRows = 3037000499LL;
}

extern "C" {

int64_t acg_sym_csr_count(int64_t nrows, int64_t nnz, const int64_t *rowidx,
                          const int64_t *colidx, int64_t *workkeys,
                          int64_t *workperm, int32_t *mirrored) {
    if (nrows > kMaxKeyRows) return ACG_NATIVE_ERR_OVERFLOW;
    bool has_lower = false, has_upper = false;
    for (int64_t i = 0; i < nnz; i++) {
        int64_t r = rowidx[i], c = colidx[i];
        if (r < 0 || r >= nrows || c < 0 || c >= nrows)
            return ACG_NATIVE_ERR_OUT_OF_BOUNDS;
        has_lower |= r > c;
        has_upper |= r < c;
        int64_t lo = r < c ? r : c, hi = r < c ? c : r;
        workkeys[i] = lo * nrows + hi;
    }
    *mirrored = (has_lower && has_upper) ? 1 : 0;
    acg_radixsort_i64(nnz, workkeys, workperm);
    int64_t pnnz = 0;
    for (int64_t i = 0; i < nnz; i++)
        if (i == 0 || workkeys[i] != workkeys[i - 1]) pnnz++;
    return pnnz;
}

int64_t acg_sym_csr_fill(int64_t nrows, int64_t nnz, int64_t pnnz,
                         const int64_t *workkeys, const int64_t *workperm,
                         const double *vals, int32_t mirrored,
                         int64_t *prowptr, int64_t *pcolidx, double *pa) {
    for (int64_t r = 0; r <= nrows; r++) prowptr[r] = 0;
    int64_t k = -1;
    for (int64_t i = 0; i < nnz; i++) {
        double v = vals ? vals[workperm[i]] : 1.0;
        if (i == 0 || workkeys[i] != workkeys[i - 1]) {
            k++;
            int64_t r = workkeys[i] / nrows, c = workkeys[i] % nrows;
            pcolidx[k] = c;
            pa[k] = v;
            prowptr[r + 1]++;
        } else {
            pa[k] += v;
        }
    }
    if (k + 1 != pnnz) return ACG_NATIVE_ERR_INVALID_FORMAT;
    if (mirrored) {
        /* full-storage input: off-diagonal sums were counted twice */
        int64_t j = 0;
        for (int64_t r = 0; r < nrows; r++) {
            int64_t cnt = prowptr[r + 1];
            for (int64_t i = 0; i < cnt; i++, j++)
                if (pcolidx[j] != r) pa[j] *= 0.5;
        }
    }
    /* counts sit at prowptr[1..nrows]; the inclusive scan turns them into
     * row pointers (prowptr[r] = entries in rows < r) */
    int64_t sum = 0;
    for (int64_t r = 0; r <= nrows; r++) {
        sum += prowptr[r];
        prowptr[r] = sum;
    }
    return pnnz;
}

int64_t acg_sym_csr_expand(int64_t nrows, const int64_t *prowptr,
                           const int64_t *pcolidx, const double *pa,
                           double epsilon, int64_t *frowptr, int64_t *fcolidx,
                           double *fa, int64_t cap) {
    /* count per-row lengths of the full matrix */
    std::vector<int64_t> len(nrows, 0);
    std::vector<uint8_t> hasdiag(nrows, 0);
    for (int64_t r = 0; r < nrows; r++) {
        for (int64_t j = prowptr[r]; j < prowptr[r + 1]; j++) {
            int64_t c = pcolidx[j];
            if (c < r || c >= nrows) return ACG_NATIVE_ERR_INVALID_FORMAT;
            len[r]++;
            if (c == r) hasdiag[r] = 1;
            else len[c]++;  /* mirror */
        }
    }
    if (epsilon != 0.0)
        for (int64_t r = 0; r < nrows; r++)
            if (!hasdiag[r]) len[r]++;
    int64_t total = 0;
    for (int64_t r = 0; r < nrows; r++) {
        frowptr[r] = total;
        total += len[r];
    }
    frowptr[nrows] = total;
    if (total > cap) return ACG_NATIVE_ERR_OVERFLOW;

    /* fill with sorted columns: processing rows in ascending order, row
     * i's strictly-lower entries (mirrors from rows < i) land before its
     * diagonal, which lands before its strictly-upper entries. */
    std::vector<int64_t> cursor(frowptr, frowptr + nrows);
    for (int64_t r = 0; r < nrows; r++) {
        int64_t j = prowptr[r];
        int64_t rowend = prowptr[r + 1];
        /* diagonal (packed rows are sorted, so it is first if present) */
        if (j < rowend && pcolidx[j] == r) {
            fcolidx[cursor[r]] = r;
            fa[cursor[r]++] = pa[j] + epsilon;
            j++;
        } else if (epsilon != 0.0) {
            fcolidx[cursor[r]] = r;
            fa[cursor[r]++] = epsilon;
        }
        for (; j < rowend; j++) {
            int64_t c = pcolidx[j];
            fcolidx[cursor[r]] = c;
            fa[cursor[r]++] = pa[j];
            fcolidx[cursor[c]] = r;   /* mirror into row c (c > r) */
            fa[cursor[c]++] = pa[j];
        }
    }
    return total;
}

}  // extern "C"
