/* acg_core: native host core for the acg-tpu framework.
 *
 * C ABI mirror of the reference's native host layers (SURVEY.md section 2):
 * Matrix Market data-section parsing/formatting (acg/mtxfile.c, component
 * #1), LSD radix sort (acg/sort.c, #2), prefix sums (acg/prefixsum.c, #3),
 * symmetric CSR assembly (acg/symcsrmatrix.c, #8), and the one-pass graph
 * partitioner (acg/graph.c, #6).  All functions are exported with C linkage
 * so Python binds them through ctypes; arrays are caller-allocated numpy
 * buffers.  Index type is int64 throughout (reference acgidx_t at
 * IDXSIZE=64, config.h:59-95).
 *
 * Error protocol: functions returning int64 return a nonnegative count on
 * success and a negative ACG_NATIVE_ERR_* code on failure.
 */

#ifndef ACG_CORE_H
#define ACG_CORE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define ACG_NATIVE_ERR_INVALID_FORMAT (-1)
#define ACG_NATIVE_ERR_EOF (-2)
#define ACG_NATIVE_ERR_OUT_OF_BOUNDS (-3)
#define ACG_NATIVE_ERR_OVERFLOW (-4)

/* ---- version / capability probe ---- */
int32_t acg_core_abi_version(void);

/* ---- sort.cpp: LSD radix sort (reference acg/sort.c) ---- */

/* Sort keys ascending in place; if perm != NULL it receives the applied
 * permutation (perm[i] = original position of the i-th smallest key),
 * starting from identity.  Stable. */
void acg_radixsort_i64(int64_t n, int64_t *keys, int64_t *perm);

/* Stable counting/radix argsort without modifying keys. */
void acg_radixargsort_i64(int64_t n, const int64_t *keys, int64_t *perm);

/* ---- prefixsum.cpp (reference acg/prefixsum.c) ---- */

/* In-place exclusive scan: a[i] <- sum of original a[0..i-1]; a has n+1
 * entries, a[n] receives the total. */
void acg_prefixsum_exclusive_i64(int64_t n, int64_t *a);

/* ---- mtxparse.cpp: Matrix Market data sections (reference acg/mtxfile.c,
 *      parse_acgidx_t/parse_double loops at mtxfile.c:706-728) ---- */

/* Parse nnz "row col [val]" coordinate lines from buf[0..len).  Indices are
 * converted from 1-based to 0-based and bounds-checked against
 * nrows/ncols.  vals may be NULL for pattern fields.  OpenMP-parallel
 * (newline pre-scan then per-chunk parse).  Returns entries parsed. */
int64_t acg_mtx_parse_coord(const char *buf, int64_t len, int64_t nnz,
                            int64_t nrows, int64_t ncols, int32_t with_vals,
                            int64_t *rowidx, int64_t *colidx, double *vals);

/* Parse n whitespace-separated real numbers (array format data section). */
int64_t acg_mtx_parse_array(const char *buf, int64_t len, int64_t n,
                            double *vals);

/* Format nnz coordinate lines "r+1 c+1 fmt(v)\n" into out (capacity cap
 * bytes).  fmt is a single printf double conversion, pre-validated by the
 * caller.  vals may be NULL (pattern).  Returns bytes written, or
 * ACG_NATIVE_ERR_OVERFLOW if cap is too small. */
int64_t acg_mtx_format_coord(int64_t nnz, const int64_t *rowidx,
                             const int64_t *colidx, const double *vals,
                             const char *fmt, char *out, int64_t cap);

/* Format n "fmt(v)\n" array lines. */
int64_t acg_mtx_format_array(int64_t n, const double *vals, const char *fmt,
                             char *out, int64_t cap);

/* ---- csr.cpp: symmetric CSR assembly (reference acg/symcsrmatrix.c,
 *      acgsymcsrmatrix_init_* + dsymv_init) ---- */

/* Pass 1 of packed-upper assembly: given COO triplets of a symmetric
 * matrix (either one triangle or both), compute the packed-upper nonzero
 * count after mapping every entry to (min,max) and deduplicating.
 * Fills work[nnz] with the sort keys (r*nrows+c, sorted) for reuse by
 * pass 2.  Also reports whether both strict triangles were present
 * (*mirrored = 1) -- then off-diagonal duplicate sums are halved in pass 2,
 * matching SymCsrMatrix.from_coo.  Returns pnnz. */
int64_t acg_sym_csr_count(int64_t nrows, int64_t nnz, const int64_t *rowidx,
                          const int64_t *colidx, int64_t *workkeys,
                          int64_t *workperm, int32_t *mirrored);

/* Pass 2: fill prowptr (nrows+1), pcolidx (pnnz), pa (pnnz) from the
 * workkeys/workperm produced by pass 1 and the original vals. */
int64_t acg_sym_csr_fill(int64_t nrows, int64_t nnz, int64_t pnnz,
                         const int64_t *workkeys, const int64_t *workperm,
                         const double *vals, int32_t mirrored,
                         int64_t *prowptr, int64_t *pcolidx, double *pa);

/* Expand packed-upper CSR to full-storage CSR with optional diagonal shift
 * (A + epsilon*I).  Caller sizes frowptr to nrows+1 and fcolidx/fa to
 * 2*pnnz - ndiag + (nrows if epsilon adds missing diagonals; passing
 * cap lets the function verify).  Rows come out with sorted columns.
 * Returns full nnz. */
int64_t acg_sym_csr_expand(int64_t nrows, const int64_t *prowptr,
                           const int64_t *pcolidx, const double *pa,
                           double epsilon, int64_t *frowptr, int64_t *fcolidx,
                           double *fa, int64_t cap);

/* ---- graph.cpp: one-pass subdomain construction (reference acg/graph.c
 *      acggraph_partition, graph.c:813-1452).  Opaque-handle protocol:
 *      run once, query counts, copy out ragged arrays, free. ---- */

typedef struct acg_partition_result acg_partition_result;

/* Partition the sparsity pattern (full-storage CSR) by the given part
 * vector.  Returns NULL on invalid input (part ids outside [0, nparts)). */
acg_partition_result *acg_graph_partition_run(int64_t nrows,
                                              const int64_t *frowptr,
                                              const int64_t *fcolidx,
                                              const int32_t *part,
                                              int32_t nparts);

/* Per-part counts; each output array has nparts entries. */
void acg_pr_counts(const acg_partition_result *res, int64_t *nowned,
                   int64_t *ninterior, int64_t *nghost, int64_t *nsend);

/* Copy out the ragged per-part arrays.  Layout (offsets are the prefix
 * sums of the counts above, computed by the caller):
 *   global_ids: per part [interior | border | ghost] global node ids,
 *     interior and border ascending, ghosts grouped by owner part then id;
 *   ghost_owner: owning part per ghost slot;
 *   send_part/send_gid/send_lidx: halo send list sorted by (destination,
 *     global id) -- the reference's (recipient, node-tag) radix order
 *     (halo.c:61-241); send_lidx is each node's local (subdomain) index.
 */
void acg_pr_fill(const acg_partition_result *res, int64_t *global_ids,
                 int32_t *ghost_owner, int32_t *send_part, int64_t *send_gid,
                 int64_t *send_lidx);

void acg_pr_free(acg_partition_result *res);

/* ---- cg.cpp: host reference CG solver (reference acg/cg.c, #16) ----
 *
 * Classic CG over full-storage CSR.  x holds x0 on entry and the
 * solution on return.  r_out (size n, may be NULL) receives the final
 * residual vector, so callers can scan it for FP exceptions the way the
 * reference's stats stage does.  Tolerances of 0 disable their
 * criterion; all zero means run exactly maxits iterations.  Returns 0
 * on convergence (or unbounded completion), 1 if tolerances were not
 * met, 2 if (p, Ap) hit exactly zero -- the reference's
 * ACG_ERR_NOT_CONVERGED_INDEFINITE_MATRIX (cg.c:304) -- and negative on
 * invalid input. */
#define ACG_NATIVE_CG_NOT_CONVERGED 1
#define ACG_NATIVE_CG_INDEFINITE 2
int32_t acg_cg_solve(int64_t n, const int64_t *rowptr, const int64_t *colidx,
                     const double *a, const double *b, double *x,
                     int32_t maxits, double res_atol, double res_rtol,
                     double diff_atol, double diff_rtol, int32_t *niter,
                     double *rnrm2_out, double *r0nrm2_out,
                     double *dxnrm2_out, double *r_out);

#ifdef __cplusplus
}
#endif

#endif /* ACG_CORE_H */
