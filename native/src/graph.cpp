/* One-pass distributed-graph partitioning.
 *
 * The role of the reference's acggraph_partition (acg/graph.c:813-1452):
 * given the full-storage sparsity pattern and a partition vector, build
 * every part's subdomain layout -- nodes reordered interior -> border ->
 * ghost, ghosts grouped by owner, and halo send lists sorted by
 * (recipient, node id), the reference's (recipient, node-tag) radix order
 * (halo.c:61-241).  Unlike the reference's per-rank construction, all
 * parts are built in one whole-graph pass over the edges plus two radix
 * sorts of the cut-edge set: O(nnz + ncut log-radix) total, independent of
 * nparts (the numpy fallback in acg_tpu.graph is O(n * nparts)). */

#include "acg_core.h"

#include <cstring>
#include <vector>

struct acg_partition_result {
    int32_t nparts;
    std::vector<int64_t> nowned, ninterior, nghost, nsend;
    std::vector<int64_t> global_ids;   /* ragged: per part [int|bord|ghost] */
    std::vector<int32_t> ghost_owner;  /* ragged: per part, per ghost slot */
    std::vector<int32_t> send_part;    /* ragged: per part send list dest */
    std::vector<int64_t> send_gid;     /* ragged: per part send list node */
    std::vector<int64_t> send_lidx;    /* ragged: send node local index */
};

namespace {

int64_t dedup_sorted(std::vector<int64_t> &keys) {
    int64_t m = 0;
    for (size_t i = 0; i < keys.size(); i++)
        if (i == 0 || keys[i] != keys[i - 1]) keys[m++] = keys[i];
    keys.resize(m);
    return m;
}

}  // namespace

extern "C" {

acg_partition_result *acg_graph_partition_run(int64_t nrows,
                                              const int64_t *frowptr,
                                              const int64_t *fcolidx,
                                              const int32_t *part,
                                              int32_t nparts) {
    if (nparts <= 0) return nullptr;
    /* key packing ((p*nparts)+q)*nrows + node must fit in int64 */
    if (nrows > 0 &&
        static_cast<int64_t>(nparts) * nparts >
            (INT64_MAX / (nrows + 1)))
        return nullptr;
    for (int64_t u = 0; u < nrows; u++)
        if (part[u] < 0 || part[u] >= nparts) return nullptr;

    auto *res = new acg_partition_result;
    res->nparts = nparts;
    res->nowned.assign(nparts, 0);
    res->ninterior.assign(nparts, 0);
    res->nghost.assign(nparts, 0);
    res->nsend.assign(nparts, 0);

    /* pass 1: border flags + cut-edge keys */
    std::vector<uint8_t> is_border(nrows, 0);
    std::vector<int64_t> ghost_keys;  /* (p, q, v): v ghost of p, owner q */
    std::vector<int64_t> send_keys;   /* (p, q, u): p sends u to q */
    for (int64_t u = 0; u < nrows; u++) {
        int64_t p = part[u];
        for (int64_t j = frowptr[u]; j < frowptr[u + 1]; j++) {
            int64_t v = fcolidx[j];
            if (v < 0 || v >= nrows) { delete res; return nullptr; }
            int64_t q = part[v];
            if (p != q) {
                is_border[u] = 1;
                ghost_keys.push_back((p * nparts + q) * nrows + v);
                send_keys.push_back((p * nparts + q) * nrows + u);
            }
        }
    }
    acg_radixsort_i64(static_cast<int64_t>(ghost_keys.size()),
                      ghost_keys.data(), nullptr);
    acg_radixsort_i64(static_cast<int64_t>(send_keys.size()),
                      send_keys.data(), nullptr);
    dedup_sorted(ghost_keys);
    dedup_sorted(send_keys);

    /* counts */
    std::vector<int64_t> nborder(nparts, 0);
    for (int64_t u = 0; u < nrows; u++) {
        res->nowned[part[u]]++;
        if (is_border[u]) nborder[part[u]]++;
    }
    for (int32_t p = 0; p < nparts; p++)
        res->ninterior[p] = res->nowned[p] - nborder[p];
    for (int64_t key : ghost_keys)
        res->nghost[key / (nrows * nparts)]++;
    for (int64_t key : send_keys)
        res->nsend[key / (nrows * nparts)]++;

    /* offsets for the ragged outputs */
    std::vector<int64_t> gid_off(nparts + 1, 0), ghost_off(nparts + 1, 0),
        send_off(nparts + 1, 0);
    for (int32_t p = 0; p < nparts; p++) {
        gid_off[p + 1] = gid_off[p] + res->nowned[p] + res->nghost[p];
        ghost_off[p + 1] = ghost_off[p] + res->nghost[p];
        send_off[p + 1] = send_off[p] + res->nsend[p];
    }
    res->global_ids.resize(gid_off[nparts]);
    res->ghost_owner.resize(ghost_off[nparts]);
    res->send_part.resize(send_off[nparts]);
    res->send_gid.resize(send_off[nparts]);
    res->send_lidx.resize(send_off[nparts]);

    /* owned nodes: one ascending sweep fills interior and border sections
     * of every part in ascending-global-id order */
    std::vector<int64_t> int_cur(nparts), bord_cur(nparts);
    for (int32_t p = 0; p < nparts; p++) {
        int_cur[p] = gid_off[p];
        bord_cur[p] = gid_off[p] + res->ninterior[p];
    }
    std::vector<int64_t> local_of(nrows);
    for (int64_t u = 0; u < nrows; u++) {
        int32_t p = part[u];
        int64_t slot = is_border[u] ? bord_cur[p]++ : int_cur[p]++;
        res->global_ids[slot] = u;
        local_of[u] = slot - gid_off[p];
    }
    /* ghosts: already sorted by (p, owner q, global id) */
    {
        std::vector<int64_t> cur(nparts);
        for (int32_t p = 0; p < nparts; p++) cur[p] = 0;
        for (int64_t key : ghost_keys) {
            int64_t p = key / (nrows * nparts);
            int64_t q = (key / nrows) % nparts;
            int64_t v = key % nrows;
            int64_t slot = cur[p]++;
            res->global_ids[gid_off[p] + res->nowned[p] + slot] = v;
            res->ghost_owner[ghost_off[p] + slot] = static_cast<int32_t>(q);
        }
    }
    /* send lists: sorted by (p, recipient q, global id) */
    {
        std::vector<int64_t> cur(nparts);
        for (int32_t p = 0; p < nparts; p++) cur[p] = 0;
        for (int64_t key : send_keys) {
            int64_t p = key / (nrows * nparts);
            int64_t q = (key / nrows) % nparts;
            int64_t u = key % nrows;
            int64_t slot = send_off[p] + cur[p]++;
            res->send_part[slot] = static_cast<int32_t>(q);
            res->send_gid[slot] = u;
            res->send_lidx[slot] = local_of[u];
        }
    }
    return res;
}

void acg_pr_counts(const acg_partition_result *res, int64_t *nowned,
                   int64_t *ninterior, int64_t *nghost, int64_t *nsend) {
    size_t n = static_cast<size_t>(res->nparts);
    std::memcpy(nowned, res->nowned.data(), n * sizeof(int64_t));
    std::memcpy(ninterior, res->ninterior.data(), n * sizeof(int64_t));
    std::memcpy(nghost, res->nghost.data(), n * sizeof(int64_t));
    std::memcpy(nsend, res->nsend.data(), n * sizeof(int64_t));
}

void acg_pr_fill(const acg_partition_result *res, int64_t *global_ids,
                 int32_t *ghost_owner, int32_t *send_part, int64_t *send_gid,
                 int64_t *send_lidx) {
    std::memcpy(global_ids, res->global_ids.data(),
                res->global_ids.size() * sizeof(int64_t));
    std::memcpy(ghost_owner, res->ghost_owner.data(),
                res->ghost_owner.size() * sizeof(int32_t));
    std::memcpy(send_part, res->send_part.data(),
                res->send_part.size() * sizeof(int32_t));
    std::memcpy(send_gid, res->send_gid.data(),
                res->send_gid.size() * sizeof(int64_t));
    std::memcpy(send_lidx, res->send_lidx.data(),
                res->send_lidx.size() * sizeof(int64_t));
}

void acg_pr_free(acg_partition_result *res) { delete res; }

}  // extern "C"
