/* Host reference CG solver in native code.
 *
 * The role of the reference's acg/cg.c (SURVEY.md component #16): a
 * textbook classic-CG correctness oracle over full-storage CSR, with the
 * same recurrences as acgsolver_solve (cg.c:198-407) and all four
 * stopping criteria (cg.h:136-149).  The SpMV is the OpenMP row loop
 * idiom of acgsymcsrmatrix_dsymv (symcsrmatrix.c:863-1005); dots use
 * OpenMP reductions.  Semantics (tolerance derivation, diff-in-iterates
 * via |alpha|*||p||) match acg_tpu.solvers.host_cg exactly, so the two
 * oracles cross-check each other.
 */

#include "acg_core.h"

#include <cmath>
#include <vector>

namespace {

void spmv(int64_t n, const int64_t *rowptr, const int64_t *colidx,
          const double *a, const double *x, double *y) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n; i++) {
        double acc = 0.0;
        int64_t k = rowptr[i], end = rowptr[i + 1];
        /* 4-way unroll (the reference's dsymv loop shape) */
        for (; k + 3 < end; k += 4)
            acc += a[k] * x[colidx[k]] + a[k + 1] * x[colidx[k + 1]] +
                   a[k + 2] * x[colidx[k + 2]] + a[k + 3] * x[colidx[k + 3]];
        for (; k < end; k++) acc += a[k] * x[colidx[k]];
        y[i] = acc;
    }
}

double dot(int64_t n, const double *a, const double *b) {
    double acc = 0.0;
#ifdef _OPENMP
#pragma omp parallel for reduction(+ : acc) schedule(static)
#endif
    for (int64_t i = 0; i < n; i++) acc += a[i] * b[i];
    return acc;
}

}  // namespace

extern "C" {

int32_t acg_cg_solve(int64_t n, const int64_t *rowptr, const int64_t *colidx,
                     const double *a, const double *b, double *x,
                     int32_t maxits, double res_atol, double res_rtol,
                     double diff_atol, double diff_rtol, int32_t *niter,
                     double *rnrm2_out, double *r0nrm2_out,
                     double *dxnrm2_out, double *r_out) {
    if (n < 0 || maxits < 0) return ACG_NATIVE_ERR_INVALID_FORMAT;
    std::vector<double> r(n), p(n), t(n);
    const bool unbounded = res_atol == 0.0 && res_rtol == 0.0 &&
                           diff_atol == 0.0 && diff_rtol == 0.0;
    const bool needs_diff = diff_atol > 0.0 || diff_rtol > 0.0;

    double x0nrm2 = std::sqrt(dot(n, x, x));
    spmv(n, rowptr, colidx, a, x, t.data());
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n; i++) {
        r[i] = b[i] - t[i];
        p[i] = r[i];
    }
    double gamma = dot(n, r.data(), r.data());
    double rnrm2 = std::sqrt(gamma);
    double r0nrm2 = rnrm2;
    double dxnrm2 = HUGE_VAL;
    *r0nrm2_out = r0nrm2;
    double res_tol = res_atol > res_rtol * r0nrm2 ? res_atol
                                                  : res_rtol * r0nrm2;
    auto test = [&]() {
        if (res_tol > 0.0 && rnrm2 < res_tol) return true;
        if (diff_atol > 0.0 && dxnrm2 < diff_atol) return true;
        if (diff_rtol > 0.0 &&
            dxnrm2 < diff_rtol * (x0nrm2 > 1e-300 ? x0nrm2 : 1e-300))
            return true;
        return false;
    };

    int32_t k = 0;
    bool indefinite = false;
    bool converged = !unbounded && test();
    while (!converged && k < maxits) {
        spmv(n, rowptr, colidx, a, p.data(), t.data());
        double pdott = dot(n, p.data(), t.data());
        /* (p, Ap) == 0 for p != 0 means A is not positive definite; the
         * reference aborts here (cg.c:304) rather than dividing.  With
         * gamma == 0 it instead means r = p = 0: exact convergence
         * (reachable in fixed-iteration mode), not indefiniteness. */
        if (pdott == 0.0) {
            if (gamma != 0.0) indefinite = true;
            break;
        }
        double alpha = gamma / pdott;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
        for (int64_t i = 0; i < n; i++) {
            x[i] += alpha * p[i];
            r[i] -= alpha * t[i];
        }
        double gamma_next = dot(n, r.data(), r.data());
        double beta = gamma_next / gamma;
        gamma = gamma_next;
        if (needs_diff)
            dxnrm2 = std::fabs(alpha) * std::sqrt(dot(n, p.data(), p.data()));
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
        for (int64_t i = 0; i < n; i++) p[i] = r[i] + beta * p[i];
        k++;
        rnrm2 = std::sqrt(gamma);
        if (!unbounded) converged = test();
    }
    *niter = k;
    *rnrm2_out = rnrm2;
    *dxnrm2_out = dxnrm2;
    if (r_out)
        for (int64_t i = 0; i < n; i++) r_out[i] = r[i];
    if (indefinite) return ACG_NATIVE_CG_INDEFINITE;
    return (converged || unbounded) ? 0 : ACG_NATIVE_CG_NOT_CONVERGED;
}

}  // extern "C"
