/* LSD radix sort, 8-bit digits (reference: acg/sort.c acgradixsort*_int64_t
 * and the pair variants returning permutations, sort.h:82-432). */

#include "acg_core.h"

#include <cstring>
#include <vector>

namespace {

/* One radix pass over 8-bit digit `shift`; returns false if the pass is a
 * no-op (all keys share the digit), letting callers skip the copy. */
template <typename K>
bool radix_pass(int64_t n, const K *keys_in, K *keys_out,
                const int64_t *perm_in, int64_t *perm_out, int shift) {
    int64_t count[256] = {0};
    for (int64_t i = 0; i < n; i++)
        count[(keys_in[i] >> shift) & 0xff]++;
    for (int d = 0; d < 256; d++)
        if (count[d] == n) return false;
    int64_t offset = 0;
    int64_t start[256];
    for (int d = 0; d < 256; d++) {
        start[d] = offset;
        offset += count[d];
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t pos = start[(keys_in[i] >> shift) & 0xff]++;
        keys_out[pos] = keys_in[i];
        if (perm_in) perm_out[pos] = perm_in[i];
    }
    return true;
}

void radixsort_u64(int64_t n, uint64_t *keys, int64_t *perm) {
    std::vector<uint64_t> kbuf(n);
    std::vector<int64_t> pbuf(perm ? n : 0);
    uint64_t *ka = keys, *kb = kbuf.data();
    int64_t *pa = perm, *pb = perm ? pbuf.data() : nullptr;
    for (int shift = 0; shift < 64; shift += 8) {
        if (radix_pass(n, ka, kb, pa, pb, shift)) {
            std::swap(ka, kb);
            std::swap(pa, pb);
        }
    }
    if (ka != keys) {
        std::memcpy(keys, ka, sizeof(uint64_t) * n);
        if (perm) std::memcpy(perm, pa, sizeof(int64_t) * n);
    } else if (perm && pa != perm) {
        std::memcpy(perm, pa, sizeof(int64_t) * n);
    }
}

}  // namespace

extern "C" {

int32_t acg_core_abi_version(void) { return 3; }

void acg_radixsort_i64(int64_t n, int64_t *keys, int64_t *perm) {
    if (n <= 0) return;
    if (perm)
        for (int64_t i = 0; i < n; i++) perm[i] = i;
    /* flip the sign bit so signed order matches unsigned radix order */
    uint64_t *u = reinterpret_cast<uint64_t *>(keys);
    for (int64_t i = 0; i < n; i++) u[i] ^= 0x8000000000000000ull;
    radixsort_u64(n, u, perm);
    for (int64_t i = 0; i < n; i++) u[i] ^= 0x8000000000000000ull;
}

void acg_radixargsort_i64(int64_t n, const int64_t *keys, int64_t *perm) {
    if (n <= 0) return;
    std::vector<int64_t> copy(keys, keys + n);
    acg_radixsort_i64(n, copy.data(), perm);
}

void acg_prefixsum_exclusive_i64(int64_t n, int64_t *a) {
    int64_t sum = 0;
    for (int64_t i = 0; i < n; i++) {
        int64_t v = a[i];
        a[i] = sum;
        sum += v;
    }
    a[n] = sum;
}

}  // extern "C"
