"""Benchmark harness: CG iterations/second on the reference workload.

Protocol (BASELINE.md, from the reference's scripts): 2D Poisson 5-point,
n=2048 (N=4,194,304 unknowns, ~2.09e7 stored nonzeros), classic CG,
1000 iterations, warmup before timing, metric = iterations/second
("total solver time" for a fixed iteration count).  Runs on whatever
accelerator JAX exposes (one TPU chip under the driver).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": N}

``vs_baseline`` divides by an analytic roofline for one H100 running the
reference's CUDA solver on the same workload (HBM-bound: ~600 MB of
traffic per iteration at 3.35 TB/s with ~80% efficiency -> ~4500 iters/s).
The reference repo publishes no measured numbers (BASELINE.md); this
analytic stand-in is documented there and replaced when measured numbers
exist.
"""

from __future__ import annotations

import json
import sys
import time

N_SIDE = 2048
MAXITS = 1000
WARMUP_ITS = 50

# Analytic H100 baseline for vs_baseline (see module docstring / BASELINE.md)
H100_BASELINE_ITERS_PER_SEC = 4500.0


def main() -> int:
    import jax
    import jax.numpy as jnp

    from acg_tpu.io.generators import poisson2d_coo
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    t0 = time.perf_counter()
    r, c, v, N = poisson2d_coo(N_SIDE)
    csr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    A = device_matrix_from_csr(csr, dtype=jnp.float32)  # DIA for stencils
    print(f"# setup: N={N} nnz={csr.nnz} in {time.perf_counter() - t0:.1f}s "
          f"on {jax.devices()[0].platform}", file=sys.stderr)

    solver = JaxCGSolver(A)
    b = jnp.ones(N, dtype=jnp.float32)
    # warmup: compile + a short run (the reference warms up every op class)
    solver.solve(b, criteria=StoppingCriteria(maxits=WARMUP_ITS))
    solver.stats.tsolve = 0.0

    solver.solve(b, criteria=StoppingCriteria(maxits=MAXITS))
    tsolve = solver.stats.tsolve
    iters_per_sec = MAXITS / tsolve
    print(f"# total solver time: {tsolve:.6f} seconds "
          f"({solver.stats.nflops * 1e-9 / tsolve:.1f} Gflop/s)",
          file=sys.stderr)

    print(json.dumps({
        "metric": "cg_iters_per_sec_poisson2d_n2048_f32",
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        "vs_baseline": round(iters_per_sec / H100_BASELINE_ITERS_PER_SEC, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
