"""Benchmark harness: CG iterations/second on the reference workloads.

Protocol (BASELINE.md, from the reference's scripts): Poisson stencil
matrices, fixed 1000-iteration CG solves, warmup before timing, metric =
iterations/second ("total solver time" for a fixed iteration count).
Runs on whatever accelerator JAX exposes (one TPU chip under the driver).

Default mode prints ONE JSON line for the flagship config (2D Poisson
n=2048, N=4,194,304, classic CG), the best of {f32, bf16} x {pallas,
xla} measured in the same contention window:
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": N,
   "dtype": ..., "kernels": ..., "bw_gbs": N, "roofline_frac": N}
``bw_gbs`` is a ~1 s triad bandwidth probe (quiet v5e: ~800 GB/s) and
``roofline_frac`` the fraction of that bandwidth the solve achieved --
together they distinguish a contended capture from a regression.  A
bf16 winner also reports its measured accuracy cost
(``rel_residual_1000it``; recovery via --refine is documented in
BASELINE.md).

``--full`` runs the BASELINE ladder (classic + pipelined x 2D n=2048 /
3D 128^3 / 3D 256^3, plus the distributed program at nparts=1 to bound
sharding overhead) and prints one JSON line per row.

``--sweep-np`` runs the multi-chip CPU-mesh correctness sweep
(np=1,2,4,8, the reference's single-node scaling protocol,
``scripts/nccl_combined.sh:41-176``): iterations-to-rtol must stay
nearly flat across mesh sizes.  Re-executes itself on a provisioned
virtual CPU mesh, so it works from any platform.

``vs_baseline`` divides by an analytic roofline for one H100 running the
reference's CUDA solver on the same workload (HBM-bound: ~587 MB of f64
traffic per iteration at 3.35 TB/s with ~80% efficiency -> ~4500 iters/s
for the flagship; other configs scale by the reference's own f64
bytes/iter, not ours).  The reference repo publishes no measured numbers
(BASELINE.md); this analytic stand-in is documented there and replaced
when measured numbers exist.  Timed solves repeat ``TIMED_REPEATS``
times and report the best -- the benchmark chip is shared and
contention is bursty (BASELINE.md round-2 caveat).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

MAXITS = 1000
WARMUP_ITS = 50

# Analytic H100 baseline, flagship config (see module docstring/BASELINE.md)
H100_BASELINE_ITERS_PER_SEC = 4500.0
# The reference's bytes/iteration on the flagship config, in ITS dtype
# (strictly f64 values + int32 column indices, ``comm.h:180-183``):
# nnz*(8+4) + 10 vector passes * 8 B = ~587 MB for 2D n=2048.  The
# stand-in for other configs scales 4500 iters/s by the reference's own
# traffic ratio -- NOT by our f32 traffic, which would wrongly credit
# the H100 with our halved-precision bandwidth advantage.
_FLAGSHIP_REF_BYTES_PER_ITER = (5 * 2048**2 - 4 * 2048) * 12.0 + 80.0 * 2048**2
# timed repeats; the tunneled benchmark chip is shared and contention is
# bursty (BASELINE.md round-2 caveat), so report the best of N
TIMED_REPEATS = 5


# --stats-json sink: the telemetry tier's structured writer
# (acg_tpu.telemetry.write_stats_json, JSONL-appended one document per
# measured case) -- the same schema-versioned twin of the fwrite block
# the CLI writes, so bench captures and CLI solves feed one consumer
_STATS_SINK: str | None = None


_CALIBRATION_ID: str | None = None


def _sink_stats(row: dict, solver) -> None:
    """Append the timed solver's full stats document for this row."""
    if _STATS_SINK is None or solver is None:
        return
    try:
        from acg_tpu import telemetry

        man = telemetry.run_manifest(
            metric=row.get("metric"), dtype=row.get("dtype"),
            kernels=row.get("kernels"), format=row.get("format"),
            # rides into the bench-diff case key (perfmodel._doc_case):
            # preconditioned captures never diff against plain ones,
            # and differently-calibrated captures key apart too
            precond=row.get("precond"),
            calibration=_CALIBRATION_ID)
        telemetry.write_stats_json(_STATS_SINK, solver.stats,
                                   manifest=man, append=True)
    except Exception as e:  # noqa: BLE001 -- the sink must never sink a row
        print(f"# stats-json sink failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _ref_bytes_per_iter(csr) -> float:
    """The reference's analytic HBM traffic per classic-CG iteration
    (f64 values, int32 indices -- same accounting as its GB/s printout,
    ``cgcuda.c:1942-1957``)."""
    return csr.nnz * 12.0 + 80.0 * csr.shape[0]


def _our_bytes_per_iter(nnz: int, n: int, idx_bytes: float,
                        mat_itemsize: int, vec_itemsize: int,
                        pipelined: bool) -> float:
    """OUR analytic HBM traffic per CG iteration: matrix reads in the
    matrix storage dtype (+``idx_bytes`` index bytes per nonzero --
    ops.spmv.matrix_index_bytes) plus the vector passes of the loop
    (15 classic / 21 pipelined, the pass count implied by the measured
    335 MB/iter f32 flagship -- BASELINE.md) in the vector storage
    dtype (they differ under --dtype mixed).  Delegates to the perfmodel
    tier's shared model, which the --explain roofline and the
    cost_analysis cross-check test also consume -- one model, no drift."""
    from acg_tpu.perfmodel import analytic_bytes_per_iteration

    return analytic_bytes_per_iteration(nnz, n, idx_bytes, mat_itemsize,
                                        vec_itemsize, pipelined)


# storage tiers: (matrix dtype, vector dtype) by bench dtype name;
# "mixed" = bf16 matrix + f32 vectors (lossless for Poisson stencil
# values -> arithmetic-identical to f32 at half the matrix traffic);
# "bf16" = half traffic everywhere but kappa-limited (~500) vector
# storage -- diverges at flagship conditioning, measured and reported;
# "bf16rr" = bf16 with periodic f32 residual replacement every
# REPLACE_EVERY iterations (solvers.jax_cg._cg_replaced_program): the
# SOUND half-traffic tier -- f32-class residuals at flagship
# conditioning for ~2% replacement overhead (round 4)
REPLACE_EVERY = 50


def _dtypes_of(dtype_name: str):
    import jax.numpy as jnp

    return {"f32": (jnp.float32, jnp.float32),
            "mixed": (jnp.bfloat16, jnp.float32),
            "bf16": (jnp.bfloat16, jnp.bfloat16),
            "bf16rr": (jnp.bfloat16, jnp.bfloat16)}[dtype_name]


_probe_cache: float | None = None
_USE_PROBE_CACHE = True


def bandwidth_probe_gbs(refresh: bool = False) -> float:
    """~1 s saxpy-triad HBM bandwidth probe on the current device.

    Reported as ``bw_gbs`` in every JSON row so a contended capture is
    distinguishable from a regression (VERDICT round 2): the v5e quiet-
    window figure is ~800 GB/s; a probe far below that marks the whole
    window as contended.  Uses the two-point chained-program estimator
    (solvers/profile.py rationale) so the ~100 ms tunnel dispatch
    latency cancels.
    """
    global _probe_cache
    if _probe_cache is not None and not refresh:
        return _probe_cache
    # the chained two-point estimator (device_sync'd, dispatch latency
    # cancelled, 20-4000 GB/s plausibility bounds) lives in the
    # perfmodel tier now, shared with the --explain roofline verdict;
    # raises RuntimeError("bandwidth probe unstable ...") as before.
    # Behind the backend-keyed on-disk sidecar so repeated bench runs
    # skip the ~1 s re-probe; refresh (the contention-detection call
    # sites) re-measures but still refreshes the sidecar, and
    # --no-probe-cache bypasses the disk entirely
    from acg_tpu.perfmodel import cached_triad_probe_gbs

    _probe_cache = cached_triad_probe_gbs(
        1 << 26, use_cache=_USE_PROBE_CACHE,
        refresh=refresh)  # 256 MB per f32 vector
    return _probe_cache


def _h100_standin(ref_bytes_per_iter: float) -> float:
    """HBM-roofline iters/s estimate for the reference on one H100."""
    return (H100_BASELINE_ITERS_PER_SEC
            * _FLAGSHIP_REF_BYTES_PER_ITER / ref_bytes_per_iter)


def _build(side: int, dim: int):
    """dim 2/3 = Poisson stencils; dim 0 = irregular power-law SPD with
    ``side`` rows (the SuiteSparse-workload stand-in, configs 4-5)."""
    from acg_tpu.io.generators import (irregular_spd_coo, poisson2d_coo,
                                       poisson3d_coo)
    from acg_tpu.matrix import SymCsrMatrix

    gen = {2: poisson2d_coo, 3: poisson3d_coo,
           0: lambda n: irregular_spd_coo(n, avg_degree=16.0, seed=0)}[dim]
    r, c, v, N = gen(side)
    return SymCsrMatrix.from_coo(N, r, c, v).to_csr()


# longest single device program we let the timing loop launch: the
# tunneled chip kills long-running programs (observed round 2: a ~50s
# COO solve dies with "UNAVAILABLE: TPU device error"; round 3: a
# program SIZED to 25s from its warmup estimate died when contention
# stretched it further -- so budget half of the observed kill threshold
# to leave contention headroom)
MAX_PROGRAM_SECONDS = 12.0

# wall-clock cap for one row's TIMING loop (round-4 verdict item 8: the
# slowest ladder rows landed only at the edge of a raised 1500 s per-row
# harness budget; with setup/probe overhead on top, a 420 s timing loop
# keeps every row inside 900 s with headroom.  Fewer repeats on a slow
# config under a bad window beats a dead row.)
ROW_TIME_BUDGET = 420.0


def _time_solver(solver, b, criteria_cls, repeats: int = TIMED_REPEATS,
                 time_budget_s: float | None = None, **solve_kwargs):
    """Best-of-``repeats`` solve time, as ``(tsolve, maxits, info)``
    (shared-chip contention is bursty; min is the least-noisy estimator
    of uncontended speed).  Slow configs time fewer iterations so the
    device program stays under the execution watchdog -- iters/s is
    trip-count-invariant.

    ``info`` carries the estimator's provenance for the plausibility
    clamp downstream: ``raw`` (the uncorrected best time), ``corrected``
    (whether the two-point round-trip subtraction was applied), and
    ``budget_exhausted``.  ``time_budget_s`` caps the WALL CLOCK of the
    whole timing loop (round-4 verdict item 8: the slowest ladder rows
    must land inside a 900 s per-row harness budget with headroom;
    fewer repeats on a slow config beats a dead row)."""
    from acg_tpu._platform import block_until_ready_works
    broken_sync = not block_until_ready_works()
    if broken_sync:
        # fetch-sync timing carries per-dispatch round-trip jitter;
        # more repeats tighten the min estimator
        repeats = max(repeats, 2 * TIMED_REPEATS)
    t_start = time.monotonic()

    def over_budget() -> bool:
        return (time_budget_s is not None
                and time.monotonic() - t_start > time_budget_s)

    def timed(its: int) -> float:
        solver.stats.tsolve = 0.0
        solver.solve(b, criteria=criteria_cls(maxits=its), **solve_kwargs)
        return solver.stats.tsolve

    timed(WARMUP_ITS)  # compile + warm
    # per-iteration estimate by TWO-POINT difference: a lying
    # block_until_ready pushes a dispatch round-trip (seconds, on a
    # degraded tunnel) into every measurement, which a single-shot
    # estimate would bill per-iteration and wrongly trip the
    # long-program guard (measured: 25 ms/iter "estimates" for a
    # 0.2 ms/iter solve)
    t_lo = min(timed(WARMUP_ITS) for _ in range(2))
    t_hi = min(timed(4 * WARMUP_ITS) for _ in range(2))
    if t_hi > t_lo:
        per_iter = (t_hi - t_lo) / (3 * WARMUP_ITS)
    else:
        # jitter swamped the two-point difference; fall back to the
        # round-trip-inflated single-shot estimate, which errs toward
        # TRIPPING the long-program guard (the safe direction: a short
        # program never meets the execution watchdog)
        per_iter = t_hi / (4 * WARMUP_ITS)
        print(f"# two-point per-iter estimate failed (t_lo {t_lo:.3f} >= "
              f"t_hi {t_hi:.3f}); using conservative {per_iter * 1e3:.1f} "
              f"ms/iter", file=sys.stderr)
    maxits = MAXITS
    if per_iter * MAXITS > MAX_PROGRAM_SECONDS:
        maxits = max(100, int(MAX_PROGRAM_SECONDS / per_iter))
        print(f"# long-program guard: timing {maxits} iterations "
              f"(~{per_iter * 1e3:.1f} ms/iter)", file=sys.stderr)
    times = [timed(maxits)]
    for _ in range(repeats - 1):
        if over_budget():
            break
        times.append(timed(maxits))
    if max(times) > 1.5 * min(times):
        print(f"# contention: solve times ranged "
              f"{min(times):.3f}-{max(times):.3f}s over {len(times)} runs",
              file=sys.stderr)
    tsolve = min(times)
    info = {"raw": tsolve, "corrected": False,
            "budget_exhausted": over_budget()}
    if broken_sync:
        # the raw times include the round-trip the fetch-sync adds; a
        # second point at a shorter trip count subtracts it (same
        # chained-difference rationale as the bandwidth probe).  The
        # short run is taken IMMEDIATELY AFTER each long run so both
        # points share a contention window (a batch of shorts after all
        # longs measured 5x scatter in the corrected figure), and the
        # estimator is the MEDIAN of per-pair differences -- min would
        # keep the jitter tail's most optimistic pairing.
        short_its = max(maxits // 4, 1)
        its_dt = maxits - short_its
        dts = []
        for _ in range(repeats):
            if over_budget() and dts:
                info["budget_exhausted"] = True
                break
            t_long = timed(maxits)
            t_short = timed(short_its)
            if t_long > t_short:
                dts.append(t_long - t_short)
        if dts:
            import statistics
            corrected = statistics.median(dts) / its_dt * maxits
            if tsolve / corrected < 20:
                print(f"# two-point correction: raw {tsolve:.3f}s -> "
                      f"{corrected:.3f}s for {maxits} its (median of "
                      f"{len(dts)} adjacent pairs)", file=sys.stderr)
                info["corrected"] = True
                tsolve = corrected
    return tsolve, maxits, info


# v5e VMEM is 128 MiB; a working set within a small multiple of it can
# be substantially on-chip-resident, making HBM-roofline arithmetic
# non-binding (the 2D flagship family: ~84-184 MB working sets measure
# 2-6x the HBM probe on the per-pass traffic model, honestly)
VMEM_BYTES = 128 * 2**20
CLAMP_MIN_WORKING_SET = 4 * VMEM_BYTES
# ceiling for the correction clamp, as a multiple of the paired fresh
# probe -- the same plausibility-gate idea the bandwidth probe itself
# carries (bench.bandwidth_probe_gbs bounds)
CLAMP_ROOFLINE_FRAC = 1.25


def _roofline_context(row: dict, bytes_per_iter: float,
                      info: dict | None = None,
                      working_set_bytes: float | None = None,
                      maxits: int | None = None) -> dict:
    """Attach ``bw_gbs`` (probe) and ``roofline_frac`` (achieved traffic
    over probe bandwidth) so a contended capture reads as such.

    The probe runs FRESH for every row (round-3 verdict: a cached probe
    minutes stale under different contention produced roofline_frac >
    1.0 -- a context key that cannot distinguish a contended probe from
    a wrong traffic model).  ``roofline_frac`` can still legitimately
    exceed 1.0 for configs whose working set is partly on-chip-resident
    (the bf16 flagship family: measured up to ~6.8k iters/s against a
    ~700 GB/s probe); the paired fresh probe makes that reading
    interpretable instead of inconsistent.

    PLAUSIBILITY CLAMP (round-4 verdict item 2): when the two-point
    correction produced a rate whose implied HBM traffic exceeds
    ``CLAMP_ROOFLINE_FRAC`` x the paired probe for a working set far too
    large to be VMEM-resident (``working_set_bytes`` >
    ``CLAMP_MIN_WORKING_SET``), the correction is physically impossible
    -- a contention burst landed inside the pair difference.  Discard
    it: revert to the raw (round-trip-inflated, biased-LOW) time and
    mark the row ``correction_discarded``.  Rows whose working set can
    ride VMEM are exempt -- their HBM traffic model does not bind."""
    try:
        bw = bandwidth_probe_gbs(refresh=True)
    except Exception as e:  # noqa: BLE001 -- the probe must not sink rows
        print(f"# bandwidth probe failed: {e}", file=sys.stderr)
        return row
    row["bw_gbs"] = round(bw, 1)
    row["roofline_frac"] = round(
        row["value"] * bytes_per_iter / (bw * 1e9), 3)
    if (info is not None and info.get("corrected")
            and working_set_bytes is not None and maxits
            and working_set_bytes > CLAMP_MIN_WORKING_SET
            and row["roofline_frac"] > CLAMP_ROOFLINE_FRAC):
        raw_value = maxits / info["raw"]
        if raw_value < row["value"]:
            print(f"# correction clamp: {row['value']:.1f} iters/s "
                  f"implies {row['roofline_frac']:.2f}x the paired "
                  f"{bw:.0f} GB/s probe on a {working_set_bytes / 2**30:.2f}"
                  f" GiB working set -- physically impossible; keeping "
                  f"the raw {raw_value:.1f} iters/s", file=sys.stderr)
            row["vs_baseline"] = round(
                row["vs_baseline"] * raw_value / row["value"], 4)
            row["value"] = round(raw_value, 2)
            row["roofline_frac"] = round(
                raw_value * bytes_per_iter / (bw * 1e9), 3)
            row["correction_discarded"] = True
    if info is not None and info.get("budget_exhausted"):
        row["budget_exhausted"] = True
    from acg_tpu._platform import block_until_ready_works
    if not block_until_ready_works():
        # timing had to fall back to scalar-fetch sync (the backend's
        # block_until_ready does not wait -- _platform); dispatch
        # round-trip jitter then biases every row LOW.  Mark the
        # capture so the number is read as a lower bound.
        row["block_sync_broken"] = True
    return row


# a window counts as quiet when the triad probe reaches this fraction of
# the chip's quiet-window bandwidth (v5e: ~800-915 GB/s measured)
QUIET_GBS = 600.0


def wait_for_quiet(budget_s: float = 240.0, min_bw: float = QUIET_GBS):
    """Probe-gate for the headline capture (round-3 verdict item 1):
    retry the bandwidth probe until it reports a quiet window or the
    time budget runs out.  Returns ``(bw_gbs, quiet)``; the caller
    records both so a contended capture self-describes."""
    deadline = time.monotonic() + budget_s
    while True:
        try:
            bw = bandwidth_probe_gbs(refresh=True)
        except RuntimeError:
            bw = 0.0
        if bw >= min_bw:
            return bw, True
        left = deadline - time.monotonic()
        if left <= 0:
            return bw, False
        wait = min(20.0, left)
        print(f"# window contended (probe {bw:.0f} GB/s < {min_bw:.0f}); "
              f"retrying in {wait:.0f}s", file=sys.stderr)
        time.sleep(wait)


def run_case(csr, name: str, pipelined: bool, dist: bool = False,
             kernels: str = "xla", dtype_name: str = "f32",
             spmv_format: str = "auto") -> dict:
    import jax.numpy as jnp
    import numpy as np

    from acg_tpu.solvers.stats import StoppingCriteria

    mat_dtype, vec_dtype = _dtypes_of(dtype_name)
    b = np.ones(csr.shape[0], dtype=np.float32)
    if dist:
        from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
        from acg_tpu.partition import partition_rows

        part = partition_rows(csr, 1, seed=0)
        prob = DistributedProblem.build(csr, part, 1, dtype=mat_dtype,
                                        vector_dtype=vec_dtype)
        solver = DistCGSolver(prob, pipelined=pipelined)
        fmt = prob.local.format
        idx_bytes = 0.0 if fmt == "dia" else 4.0
    else:
        from acg_tpu.ops.spmv import device_matrix_from_csr
        from acg_tpu.solvers.jax_cg import JaxCGSolver

        from acg_tpu.ops.spmv import matrix_index_bytes

        A = device_matrix_from_csr(csr, dtype=mat_dtype, format=spmv_format)
        solver = JaxCGSolver(
            A, pipelined=pipelined, kernels=kernels,
            vector_dtype=vec_dtype,
            replace_every=REPLACE_EVERY if dtype_name == "bf16rr" else 0)
        fmt = type(A).__name__.replace("Matrix", "").lower()
        idx_bytes = matrix_index_bytes(A)
    tsolve, maxits, info = _time_solver(solver, b, StoppingCriteria,
                                        time_budget_s=ROW_TIME_BUDGET)
    iters_per_sec = maxits / tsolve
    standin = _h100_standin(_ref_bytes_per_iter(csr))
    print(f"# {name}: total solver time: {tsolve:.6f} seconds "
          f"({solver.stats.nflops * 1e-9 / tsolve:.1f} Gflop/s)",
          file=sys.stderr)
    row = {
        "metric": name,
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        "vs_baseline": round(iters_per_sec / standin, 4),
        "dtype": dtype_name,
        "format": fmt,
    }
    if hasattr(solver, "kernels"):
        # record the *resolved* tier so an off-TPU run of the pallas-named
        # case cannot masquerade as a Pallas measurement
        row["kernels"] = solver.kernels
    mvb = np.dtype(mat_dtype).itemsize
    vvb = np.dtype(vec_dtype).itemsize
    ws = csr.nnz * (mvb + idx_bytes) + 6.0 * csr.shape[0] * vvb
    row = _roofline_context(
        row, _our_bytes_per_iter(csr.nnz, csr.shape[0], idx_bytes, mvb,
                                 vvb, pipelined),
        info=info, working_set_bytes=ws, maxits=maxits)
    _sink_stats(row, solver)
    return row


def run_host_baseline(csr, name: str, kind: str) -> dict:
    """Host/external baseline row (f64 on the host CPU): ``petsc`` =
    the scipy-CG external oracle, ``native`` = the C++ core solver."""
    import numpy as np

    from acg_tpu.solvers.stats import StoppingCriteria

    if kind == "petsc":
        from acg_tpu.solvers.petsc_cg import PetscBaselineSolver
        solver = PetscBaselineSolver(csr)
    else:
        from acg_tpu.solvers.host_cg import NativeHostCGSolver
        solver = NativeHostCGSolver(csr)
    b = np.ones(csr.shape[0])
    tsolve, maxits, _ = _time_solver(solver, b, StoppingCriteria, repeats=2,
                                     time_budget_s=ROW_TIME_BUDGET)
    iters_per_sec = maxits / tsolve
    standin = _h100_standin(_ref_bytes_per_iter(csr))
    print(f"# {name}: total solver time: {tsolve:.6f} seconds",
          file=sys.stderr)
    row = {"metric": name, "value": round(iters_per_sec, 2),
           "unit": "iters/s",
           "vs_baseline": round(iters_per_sec / standin, 4),
           "dtype": "f64", "host": True}
    _sink_stats(row, solver)
    return row


def _enable_compile_cache():
    from acg_tpu._platform import enable_compile_cache

    enable_compile_cache()


# headline-eligibility threshold for the bf16-family tiers: the true
# relative residual after the protocol's 1000 iterations under the
# manufactured-solution setup must be f32-class.  Measured flagship
# values: f32 8.0e-7, bf16rr 1.0e-6, plain bf16 2.2e-1 (stall), so the
# gate cleanly separates sound from stalled tiers with margin
SOUND_REL_RESIDUAL = 1e-4


def _accuracy_context(csr, row: dict, dtype_name: str) -> dict:
    """Measure a bf16-family tier's accuracy next to its speed: the TRUE
    f64 relative residual after the protocol's fixed iteration count,
    under the reference's own verification setup (random unit-norm
    manufactured xsol, b = A xsol -- ``cuda/acg-cuda.c:1969-1984``; the
    benchmark scripts always run with --manufactured-solution,
    ``scripts/nccl_combined.sh:55-60``).  b = ones is NOT used here: at
    flagship conditioning its solution norm is ~1e8, putting even exact
    f32 arithmetic at an O(10) relative-residual floor -- a scale
    artifact that would mask the actual soundness difference between
    tiers (plain bf16 stalls at 2e-1, replacement reaches 1e-6)."""
    import jax.numpy as jnp
    import numpy as np

    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    try:
        rng = np.random.default_rng(0)
        xsol = rng.standard_normal(csr.shape[0])
        xsol /= np.linalg.norm(xsol)
        b = (csr @ xsol).astype(np.float32)
        A = device_matrix_from_csr(csr, dtype=jnp.bfloat16)
        s = JaxCGSolver(
            A, kernels="xla",
            replace_every=REPLACE_EVERY if dtype_name == "bf16rr" else 0)
        x = np.asarray(s.solve(b, criteria=StoppingCriteria(maxits=MAXITS),
                               raise_on_divergence=False), dtype=np.float64)
        rel = float(np.linalg.norm(b - csr @ x) / np.linalg.norm(b))
        row["rel_residual_1000it"] = float(f"{rel:.3g}")
        row["error_2norm_1000it"] = float(
            f"{np.linalg.norm(x - xsol):.3g}")
    except Exception as e:  # noqa: BLE001 -- context must not sink the row
        print(f"# accuracy context failed: {e}", file=sys.stderr)
    return row


def _accuracy_context_dia(A, row: dict, replace_every: int,
                          chunk_its: int = 250) -> dict:
    """Soundness gate for the bf16-family tiers at DIRECT-DIA sizes,
    fully device-resident (no host CSR exists at 512^3): manufactured
    f32 unit-norm xsol, ``b = A xsol`` in f32 arithmetic (lossless for
    bf16-exact stencil values), then the tier's own solve for the
    protocol's ``MAXITS`` iterations, and the TRUE df64 relative
    residual -- ``dia_mv_roll_df`` carries ~48 mantissa bits, so the
    reported residual is not capped by f32 roundoff.

    The solve runs as ``MAXITS / chunk_its`` chained programs (each a
    multiple of the replacement period K): for the replacement tier a
    chunk boundary IS a segment boundary -- solve(x0=x) recomputes
    r = b - A x in f32 exactly like the in-loop replacement does -- so
    chunking changes nothing semantically while keeping each device
    program far under the tunnel's execution watchdog
    (MAX_PROGRAM_SECONDS notes)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from acg_tpu.parallel.sharded_dia import dia_mv_roll_df
    from acg_tpu.ops.spmv import dia_mv_roll
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    assert replace_every and chunk_its % replace_every == 0
    N, offsets = A.nrows, A.offsets

    try:
        @jax.jit
        def build(key, planes):
            x = jax.random.normal(key, (N,), jnp.float32)
            x = x / jnp.linalg.norm(x)
            return x, dia_mv_roll(planes, offsets, x)

        xsol, b = build(jax.random.key(0), A.data)
        s = JaxCGSolver(A, kernels="auto", vector_dtype=jnp.bfloat16,
                        replace_every=replace_every)
        x = jnp.zeros_like(b)
        for _ in range(MAXITS // chunk_its):
            x = s.solve(b, x0=x,
                        criteria=StoppingCriteria(maxits=chunk_its),
                        raise_on_divergence=False, host_result=False)

        @jax.jit
        def norms(planes, b, x, xsol):
            ah, al = dia_mv_roll_df(planes, offsets, x,
                                    jnp.zeros_like(x))
            r = (b - ah) - al
            return (jnp.linalg.norm(r), jnp.linalg.norm(b),
                    jnp.linalg.norm(x - xsol))

        rn, bn, en = norms(A.data, b, x, xsol)
        row["rel_residual_1000it"] = float(f"{float(rn) / float(bn):.3g}")
        row["error_2norm_1000it"] = float(f"{float(en):.3g}")
    except Exception as e:  # noqa: BLE001 -- context must not sink the row
        print(f"# accuracy context failed: {e}", file=sys.stderr)
    return row


def run_case_dia(side: int, dim: int, name: str,
                 dtype_name: str = "f32") -> dict:
    """Stencil configs assembled DIRECTLY as DIA planes (no COO/CSR/sort
    preprocessing) -- the only practical route to the north-star 512^3
    problem (N=134M, ~0.9G nnz) on one chip: ~4 GB of f32 planes built
    in seconds instead of tens of GB of COO intermediates.

    ``bf16rr`` runs the sound half-traffic tier (periodic f32 residual
    replacement, solvers.jax_cg._cg_replaced_program) and measures its
    soundness at 3D conditioning next to the speed (round-4 verdict
    item 1: the tier that makes the 2D flagship green must run -- and
    be accuracy-gated -- at the problem size the project is named for)."""
    import jax.numpy as jnp
    import numpy as np

    _enable_compile_cache()

    from acg_tpu.io.generators import poisson_dia_device
    from acg_tpu.ops.spmv import DiaMatrix
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    mat_dtype, vec_dtype = _dtypes_of(dtype_name)
    replace_every = REPLACE_EVERY if dtype_name == "bf16rr" else 0
    planes, offsets, N = poisson_dia_device(side, dim, dtype=mat_dtype)
    A = DiaMatrix(data=tuple(planes), offsets=offsets,
                  nrows=N, ncols_padded=N)
    n_axis = N // side
    nnz = N + 2 * dim * (N - n_axis)  # full-storage stencil nonzeros
    solver = JaxCGSolver(A, kernels="auto", vector_dtype=vec_dtype,
                         replace_every=replace_every)
    # b lives on device from birth, and results stay device-resident
    # (host_result=False): at this size every 537 MB host<->device copy
    # costs minutes over a tunneled chip and none of them are part of
    # the measured solve; 2 repeats keep the row inside a bench budget
    b = jnp.ones(N, dtype=jnp.float32 if replace_every else vec_dtype)
    tsolve, maxits, info = _time_solver(solver, b, StoppingCriteria,
                                        repeats=2, host_result=False,
                                        time_budget_s=ROW_TIME_BUDGET)
    iters_per_sec = maxits / tsolve
    standin = _h100_standin(nnz * 12.0 + 80.0 * N)
    print(f"# {name}: total solver time: {tsolve:.6f} seconds",
          file=sys.stderr)
    # report what actually RAN: the pallas tier routes wide-band DIA
    # (512^3's +-n^2 diagonals) back to XLA's shifted-views SpMV
    kernels = solver.kernels
    if kernels.startswith("pallas"):
        from acg_tpu.ops.pallas_kernels import dia_spmv_route

        if dia_spmv_route(offsets, N, vec_dtype)[0] == "xla":
            kernels = "xla"
    row = {"metric": name, "value": round(iters_per_sec, 2),
           "unit": "iters/s",
           "vs_baseline": round(iters_per_sec / standin, 4),
           "dtype": dtype_name, "kernels": kernels}
    if replace_every:
        row = _accuracy_context_dia(A, row, replace_every)
        if row.get("rel_residual_1000it",
                   float("inf")) >= SOUND_REL_RESIDUAL:
            row["sound"] = False  # speed without the accuracy contract
    mvb = np.dtype(mat_dtype).itemsize
    vvb = 2 if replace_every else np.dtype(vec_dtype).itemsize
    ws = nnz * float(mvb) + 6.0 * N * vvb
    row = _roofline_context(
        row, _our_bytes_per_iter(nnz, N, 0.0, mvb, vvb, False),
        info=info, working_set_bytes=ws, maxits=maxits)
    _sink_stats(row, solver)
    return row


def sweep_np(out=sys.stdout) -> int:
    """Multi-chip correctness sweep on the virtual CPU mesh: iterations to
    residual_rtol=1e-6 at np=1,2,4,8 (should be nearly flat -- CG
    iteration count is partition-invariant up to rounding)."""
    from acg_tpu._platform import provision_host_mesh

    jax = provision_host_mesh(8)
    jax.config.update("jax_enable_x64", True)
    if len(jax.devices()) < 8:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import subprocess
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sweep-np"],
            env=env).returncode

    import jax.numpy as jnp
    import numpy as np

    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.stats import StoppingCriteria

    csr = _build(256, 2)  # N=65,536: big enough to partition meaningfully
    rng = np.random.default_rng(0)
    xsol = rng.standard_normal(csr.shape[0])
    xsol /= np.linalg.norm(xsol)
    b = csr @ xsol
    rows = []
    for nparts in (1, 2, 4, 8):
        part = partition_rows(csr, nparts, seed=0, method="band")
        prob = DistributedProblem.build(csr, part, nparts, dtype=jnp.float64)
        solver = DistCGSolver(prob, pipelined=False)
        x = solver.solve(b, criteria=StoppingCriteria(
            maxits=5000, residual_rtol=1e-6))
        err = float(np.linalg.norm(x - xsol))
        rows.append({"np": nparts, "iterations": solver.stats.niterations,
                     "error_2norm": err, "local_format": prob.local.format})
        print(f"# np={nparts}: {solver.stats.niterations} iterations, "
              f"error {err:.3e} ({prob.local.format})", file=sys.stderr)
    iters = [r["iterations"] for r in rows]
    flat = max(iters) - min(iters) <= max(2, int(0.02 * max(iters)))
    print(json.dumps({"metric": "dist_cg_iters_to_rtol1e-6_np_sweep",
                      "rows": rows, "flat": flat}), file=out)

    # the DIRECT-ASSEMBLY route (sharded on-device planes + derived
    # halo, parallel/sharded_dia -- the north-star path) swept the same
    # way: manufactured solution, iterations to rtol must stay flat
    from acg_tpu.parallel.sharded_dia import build_sharded_poisson_solver

    n3 = 32
    rows2 = []
    for nparts in (1, 2, 4, 8):
        s = build_sharded_poisson_solver(n3, 3, nparts=nparts)
        xsol, b = s.manufactured(seed=0)
        x = s.solve(b, criteria=StoppingCriteria(maxits=5000,
                                                 residual_rtol=1e-6),
                    host_result=False)
        err = float(np.linalg.norm(np.asarray(x, np.float64)
                                   - np.asarray(xsol, np.float64)))
        rows2.append({"np": nparts, "iterations": s.stats.niterations,
                      "error_2norm": err})
        print(f"# direct np={nparts}: {s.stats.niterations} iterations, "
              f"error {err:.3e}", file=sys.stderr)
    iters2 = [r["iterations"] for r in rows2]
    flat2 = max(iters2) - min(iters2) <= max(2, int(0.02 * max(iters2)))
    print(json.dumps({"metric": "direct_dia_iters_to_rtol1e-6_np_sweep",
                      "rows": rows2, "flat": flat2}), file=out)

    # IRREGULAR workload over the mesh (VERDICT r2 item 6): graph
    # partition -> ELL local blocks; iterations to rtol must stay flat
    csr_i = _build(20_000, 0)
    xsol_i = rng.standard_normal(csr_i.shape[0])
    xsol_i /= np.linalg.norm(xsol_i)
    b_i = csr_i @ xsol_i
    rows3 = []
    for nparts in (1, 2, 4, 8):
        part = partition_rows(csr_i, nparts, seed=0, method="graph")
        prob = DistributedProblem.build(csr_i, part, nparts,
                                        dtype=jnp.float64)
        solver = DistCGSolver(prob)
        x = solver.solve(b_i, criteria=StoppingCriteria(
            maxits=5000, residual_rtol=1e-6))
        err = float(np.linalg.norm(x - xsol_i))
        rows3.append({"np": nparts, "iterations": solver.stats.niterations,
                      "error_2norm": err,
                      "local_format": prob.local.format})
        print(f"# irregular np={nparts}: {solver.stats.niterations} "
              f"iterations, error {err:.3e} ({prob.local.format})",
              file=sys.stderr)
    iters3 = [r["iterations"] for r in rows3]
    flat3 = max(iters3) - min(iters3) <= max(2, int(0.02 * max(iters3)))
    print(json.dumps({"metric": "irregular_iters_to_rtol1e-6_np_sweep",
                      "rows": rows3, "flat": flat3}), file=out)
    return 0 if (flat and flat2 and flat3) else 1


def run_soak_mode(args) -> int:
    """``bench.py --soak N``: the service-soak harness over one Poisson
    matrix (``--soak-side``/``--soak-dim``) -- N repeated fixed-work
    solves through :func:`acg_tpu.soak.run_soak`, one JSON summary row
    (p50/p95/p99 latency, drift verdict) on stdout, the full
    ``acg-tpu-stats/3`` document on ``--stats-json``, a Prometheus
    textfile on ``--metrics-file``, and the ``--fail-on-drift`` exit
    gate (exit 7) shared with the CLI."""
    import numpy as np

    from acg_tpu import metrics, soak
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    metrics.arm()
    if args.metrics_file:
        metrics.install_flush_handlers(args.metrics_file)
    from acg_tpu.precond import parse_precond
    pc = parse_precond(args.precond)
    name = (f"soak_poisson{args.soak_dim}d_n{args.soak_side}"
            f"_{args.soak_dtype}_x{args.soak}")
    csr = _build(args.soak_side, args.soak_dim)
    mat_dtype, vec_dtype = _dtypes_of(args.soak_dtype)
    A = device_matrix_from_csr(csr, dtype=mat_dtype)
    solver = JaxCGSolver(A, kernels="auto", vector_dtype=vec_dtype,
                         precond=pc)
    b = np.ones(csr.shape[0], dtype=np.float32)
    # fixed-iteration protocol (the bench convention): every solve does
    # identical work, so the latency distribution measures the SYSTEM,
    # not the convergence path
    crit = StoppingCriteria(maxits=args.soak_its)
    t0 = time.perf_counter()
    x, report = soak.run_soak(
        solver, b, nsolves=args.soak, criteria=crit,
        fail_on_drift=args.fail_on_drift,
        first_solve_kwargs={"warmup": 1},
        solve_kwargs={"raise_on_divergence": False,
                      "host_result": False},
        progress_every=max(1, args.soak // 10), what="bench-soak")
    print(f"# {name}: {args.soak} solves in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
    lat, its = report["latency"], report["iterations"]
    row = {
        "metric": name,
        # iters/s at the medians: the longitudinally comparable figure
        "value": (round(its["p50"] / lat["p50"], 2)
                  if lat.get("p50") and its.get("p50") else 0.0),
        "unit": "iters/s",
        "dtype": args.soak_dtype,
        "kernels": getattr(solver, "kernels", "auto"),
        "latency_p50_s": lat["p50"], "latency_p95_s": lat["p95"],
        "latency_p99_s": lat["p99"],
        "drift_ratio": report["drift"]["ratio"],
        "drift_tripped": report["drift"]["tripped"],
        "nsolves": args.soak,
    }
    if pc is not None:
        # folded into the diff case key by perfmodel._row_case
        row["precond"] = str(pc)
    print(json.dumps(row))
    _sink_stats(row, solver)
    if args.metrics_file:
        metrics.write_textfile(args.metrics_file)
    rc = _finish(args, [row], 0)
    return rc or soak.gate_exit_code(report, args.fail_on_drift)


def run_batched_mode(args) -> int:
    """``bench.py --batched``: the batched-vs-sequential throughput
    case (ISSUE 11 acceptance) -- solves/second at B in {1, 4, 8} for
    one Poisson matrix, each B measured as ONE batched multi-RHS solve
    against a sequential loop of B single-RHS solves of the SAME
    columns (fixed-iteration protocol, so every row does identical
    numerical work), plus the block-CG iteration-count case on the
    --aniso family (block total iterations vs the sum of B independent
    solves).  One JSON row per case.

    Measured over the 8-part mesh (the virtual CPU mesh off-TPU, the
    sweep_np provisioning): the per-iteration collectives are where
    the B-invariance pays -- a sequential loop moves B x the
    allreduces/halo exchanges of one batched solve.

    Re-baseline note: the nrhs/block keys join the bench-diff case key
    (perfmodel._batch_keyed), so the FIRST batched capture starts a
    fresh baseline series -- r05 was bench_backend_unavailable and no
    prior batched rows exist to diff against (ROADMAP Recent)."""
    import numpy as np

    from acg_tpu._platform import provision_host_mesh

    jax = provision_host_mesh(8)
    if len(jax.devices()) < 8:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
        import subprocess
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--batched",
             "--batched-side", str(args.batched_side),
             "--batched-its", str(args.batched_its),
             "--batched-aniso-side", str(args.batched_aniso_side)]
            + (["--stats-json", args.stats_json] if args.stats_json
               else [])
            + (["--baseline", args.baseline] if args.baseline else []),
            env=env).returncode

    import jax.numpy as jnp

    from acg_tpu._platform import device_sync
    from acg_tpu.io.generators import batched_rhs
    from acg_tpu.ops.spmv import device_matrix_from_csr
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.parallel.dist_batched import BatchedDistCGSolver
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.batched import BatchedCGSolver
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    side, its = args.batched_side, args.batched_its
    csr = _build(side, 2)
    n = csr.shape[0]
    nparts = 8
    part = partition_rows(csr, nparts, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, nparts,
                                    dtype=jnp.float32)
    Bcols = batched_rhs(n, 8, seed=0, dtype=np.float32)
    crit = StoppingCriteria(maxits=its)   # fixed-work protocol
    rows = []

    def best_of(fn, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    seq = DistCGSolver(prob)
    seq.solve(Bcols[:, 0], criteria=crit, host_result=False)  # compile
    for nb in (1, 4, 8):
        cols = Bcols[:, :nb]
        bs = BatchedDistCGSolver(prob)
        # compile outside timing (both sides)
        device_sync(bs.solve(cols, criteria=crit, host_result=False))

        def batched_once():
            device_sync(bs.solve(cols, criteria=crit,
                                 host_result=False))

        def sequential_once():
            for j in range(nb):
                device_sync(seq.solve(cols[:, j], criteria=crit,
                                      host_result=False))

        t_b = best_of(batched_once)
        t_s = best_of(sequential_once)
        row = {
            "metric": f"batched_cg_solves_per_sec_poisson2d_n{side}"
                      f"_np{nparts}_f32_its{its}",
            "nrhs": nb,
            "value": round(nb / t_b, 3),
            "unit": "solves/s",
            "dtype": "f32",
            "nparts": nparts,
            "sequential_solves_per_sec": round(nb / t_s, 3),
            "speedup_vs_sequential": round(t_s / t_b, 3),
        }
        print(f"# B={nb}: batched {t_b:.3f}s vs sequential {t_s:.3f}s "
              f"({t_s / t_b:.2f}x)", file=sys.stderr)
        print(json.dumps(row))
        rows.append(row)
        _sink_stats(row, bs)
        sys.stdout.flush()

    # block-CG iteration acceptance on the aniso family: total block
    # iterations (trips x B) vs the summed iterations of B independent
    # solves to the same tolerance
    from acg_tpu.io.generators import aniso_poisson2d_coo
    from acg_tpu.matrix import SymCsrMatrix
    jax.config.update("jax_enable_x64", True)
    r, c, v, N = aniso_poisson2d_coo(args.batched_aniso_side, 0.05)
    acsr = SymCsrMatrix.from_coo(N, r, c, v).to_csr()
    Aa = device_matrix_from_csr(acsr, dtype=jnp.float64)
    B8 = batched_rhs(N, 8, seed=0)
    tol = StoppingCriteria(maxits=50000, residual_rtol=1e-8)
    blk = BatchedCGSolver(Aa, mode="block")
    blk.solve(B8, criteria=tol)
    trips = blk.stats.batch["block_iterations"]
    indep = 0
    for j in range(8):
        s1 = JaxCGSolver(Aa, kernels="xla")
        s1.solve(B8[:, j], criteria=tol)
        indep += s1.stats.niterations
    ratio = trips * 8 / indep
    row = {
        "metric": f"block_cg_iters_ratio_aniso_n"
                  f"{args.batched_aniso_side}_eps0.05_rtol1e-8",
        "nrhs": 8,
        "block": True,
        "value": round(ratio, 4),
        "unit": "block_total/indep_sum",
        "block_iterations": int(trips),
        "block_total_iterations": int(trips * 8),
        "independent_iterations_sum": int(indep),
    }
    print(f"# block-CG: {trips} trips x 8 = {trips * 8} vs "
          f"{indep} independent ({ratio:.3f}x)", file=sys.stderr)
    print(json.dumps(row))
    rows.append(row)
    _sink_stats(row, blk)
    return _finish(args, rows, 0)


def run_algorithms_mode(args) -> int:
    """``bench.py --algorithms``: the communication-avoiding recurrence
    sweep (ISSUE 12 acceptance) -- s/iteration and the static comm
    ledger for classic, GV-pipelined, sstep:{2,4,8} and p(l):{2,3} over
    ONE Poisson matrix on the 8-part mesh (the virtual CPU mesh
    off-TPU, the sweep_np provisioning), fixed-iteration protocol so
    every row does comparable numerical work.  One JSON row per
    algorithm; the ledger columns show the reduction-count drop
    (classic 2 allreduce/iter -> sstep 1 per S iterations, p(l) 1
    fused) that is the whole point of the tier."""
    import numpy as np

    from acg_tpu._platform import provision_host_mesh

    jax = provision_host_mesh(8)
    if len(jax.devices()) < 8:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
        import subprocess
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--algorithms",
             "--algorithms-side", str(args.algorithms_side),
             "--algorithms-its", str(args.algorithms_its),
             "--fail-on-regress", str(args.fail_on_regress)]
            + (["--stats-json", args.stats_json] if args.stats_json
               else [])
            + (["--baseline", args.baseline] if args.baseline else []),
            env=env).returncode

    import jax.numpy as jnp

    from acg_tpu._platform import device_sync
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.stats import StoppingCriteria

    side, its = args.algorithms_side, args.algorithms_its
    csr = _build(side, 2)
    n = csr.shape[0]
    nparts = 8
    part = partition_rows(csr, nparts, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, nparts,
                                    dtype=jnp.float32)
    rng = np.random.default_rng(0)
    b = rng.standard_normal(n).astype(np.float32)
    crit = StoppingCriteria(maxits=its)   # fixed-work protocol
    rows = []

    def best_of(fn, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    algs = [("classic", dict()),
            ("pipelined", dict(pipelined=True)),
            ("sstep:2", dict(algorithm="sstep:2")),
            ("sstep:4", dict(algorithm="sstep:4")),
            ("sstep:8", dict(algorithm="sstep:8")),
            ("pipelined:2", dict(algorithm="pipelined:2")),
            ("pipelined:3", dict(algorithm="pipelined:3"))]
    for name, kw in algs:
        s = DistCGSolver(prob, **kw)
        device_sync(s.solve(b, criteria=crit, host_result=False,
                            raise_on_divergence=False))  # compile

        def once():
            device_sync(s.solve(b, criteria=crit, host_result=False,
                                raise_on_divergence=False))

        t = best_of(once)
        led = s.comm_profile()
        tag = name.replace(":", "")
        row = {
            "metric": f"ca_cg_iters_per_sec_poisson2d_n{side}"
                      f"_np{nparts}_f32_its{its}_{tag}",
            "algorithm": name,
            "value": round(its / t, 2),
            "unit": "iters/s",
            "s_per_iter": round(t / its, 6),
            "dtype": "f32",
            "nparts": nparts,
            "iterations": int(s.stats.niterations),
            "allreduce_per_iteration":
                led["allreduce_per_iteration"],
            "allreduce_scalars": led["allreduce_scalars"],
            "halo_exchanges_per_iteration":
                led["halo_exchanges_per_iteration"],
        }
        print(f"# {name}: {t:.3f}s for {its} its "
              f"({its / t:.1f} iters/s, "
              f"{led['allreduce_per_iteration']:g} allreduce/iter)",
              file=sys.stderr)
        print(json.dumps(row))
        rows.append(row)
        _sink_stats(row, s)
        sys.stdout.flush()
    return _finish(args, rows, 0)


def run_overlap_mode(args) -> int:
    """``bench.py --overlap``: the fused-iteration overlap sweep (ISSUE
    13 acceptance) -- comm={xla,dma} x kernels={auto,fused} at small
    n/P on the 8-part mesh (the regime where BENCH_r03/r04 showed
    collective latency dominating), fixed-iteration protocol.  Each
    case is timed AND captured under the jax profiler so the row
    carries the measured solve-windowed overlap-efficiency score
    (acg_tpu.tracing -- the PR 8 protocol the ISSUE 13 acceptance
    gates on) next to s/iter and the ledger's interior/border split."""
    import shutil
    import tempfile

    import numpy as np

    from acg_tpu._platform import provision_host_mesh

    jax = provision_host_mesh(8)
    if len(jax.devices()) < 8:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
        import subprocess
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--overlap",
             "--overlap-side", str(args.overlap_side),
             "--overlap-its", str(args.overlap_its),
             "--fail-on-regress", str(args.fail_on_regress)]
            + (["--stats-json", args.stats_json] if args.stats_json
               else [])
            + (["--baseline", args.baseline] if args.baseline else []),
            env=env).returncode

    import jax.numpy as jnp

    from acg_tpu import tracing
    from acg_tpu._platform import device_sync
    from acg_tpu.parallel.dist import DistCGSolver, DistributedProblem
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.stats import StoppingCriteria

    side, its = args.overlap_side, args.overlap_its
    csr = _build(side, 2)
    n = csr.shape[0]
    nparts = 8
    part = partition_rows(csr, nparts, seed=0, method="band")
    prob = DistributedProblem.build(csr, part, nparts,
                                    dtype=jnp.float32)
    rng = np.random.default_rng(0)
    b = rng.standard_normal(n).astype(np.float32)
    crit = StoppingCriteria(maxits=its)   # fixed-work protocol
    rows = []

    def best_of(fn, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    for comm in ("xla", "dma"):
        for kern in ("auto", "fused"):
            s = DistCGSolver(prob, comm=comm, kernels=kern)
            device_sync(s.solve(b, criteria=crit, host_result=False,
                                raise_on_divergence=False))  # compile

            def once():
                device_sync(s.solve(b, criteria=crit,
                                    host_result=False,
                                    raise_on_divergence=False))

            t = best_of(once)
            # per-case profiler capture -> measured solve-windowed
            # overlap-efficiency (degrades to null where the capture
            # is unusable; the timing row stands either way)
            cap = tempfile.mkdtemp(prefix="acg_overlap_")
            try:
                with tracing.profiler_trace(cap):
                    once()
                analysis = tracing.analyze_trace(cap)
            finally:
                shutil.rmtree(cap, ignore_errors=True)
            eff = (analysis.get("overlap_efficiency")
                   if analysis.get("available") else None)
            led = s.comm_profile()
            row = {
                "metric": f"overlap_cg_iters_per_sec_poisson2d_n{side}"
                          f"_np{nparts}_f32_its{its}_{comm}_{kern}",
                "comm": comm,
                "kernels": s.kernels,
                "value": round(its / t, 2),
                "unit": "iters/s",
                "s_per_iter": round(t / its, 6),
                "dtype": "f32",
                "nparts": nparts,
                "iterations": int(s.stats.niterations),
                "overlap_efficiency": eff,
                "halo_bytes_per_iteration":
                    led["halo_bytes_per_iteration"],
            }
            if led.get("overlap"):
                row["interior_rows"] = led["overlap"]["interior_rows"]
                row["border_rows"] = led["overlap"]["border_rows"]
            print(f"# {comm}/{kern}: {t:.3f}s for {its} its "
                  f"({its / t:.1f} iters/s, overlap-efficiency "
                  f"{eff if eff is not None else 'n/a'})",
                  file=sys.stderr)
            print(json.dumps(row))
            rows.append(row)
            _sink_stats(row, s)
            sys.stdout.flush()
    return _finish(args, rows, 0)


def run_matfree_mode(args) -> int:
    """``bench.py --matfree``: the matrix-free operator sweep (ISSUE 15
    acceptance) -- s/iteration of the matrix-free stencil apply vs the
    assembled ``gen:`` DIA planes vs the general assembled gather
    format (ELL, the CSR-class fallback) at 2-3 sizes on the single
    device AND the assembled-vs-matfree pair on the 8-part mesh,
    fixed-iteration protocol.  Matrix-free rows carry the ``operator``
    identity so bench_diff keys them apart from assembled captures
    (perfmodel._operator_keyed), and the headline comparison is the
    matfree-vs-DIA s/iter at the largest (most HBM-bound) size."""
    import numpy as np

    from acg_tpu._platform import provision_host_mesh

    jax = provision_host_mesh(8)
    if len(jax.devices()) < 8:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
        import subprocess
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--matfree",
             "--matfree-sides", args.matfree_sides,
             "--matfree-its", str(args.matfree_its),
             "--fail-on-regress", str(args.fail_on_regress)]
            + (["--stats-json", args.stats_json] if args.stats_json
               else [])
            + (["--baseline", args.baseline] if args.baseline else []),
            env=env).returncode

    import jax.numpy as jnp

    from acg_tpu._platform import device_sync
    from acg_tpu.ops.operator import poisson_stencil
    from acg_tpu.ops.spmv import device_matrix_from_csr, dia_from_csr
    from acg_tpu.parallel.dist import (DistCGSolver, DistributedProblem,
                                       arm_matfree)
    from acg_tpu.partition import partition_rows
    from acg_tpu.solvers.jax_cg import JaxCGSolver
    from acg_tpu.solvers.stats import StoppingCriteria

    sides = [int(s) for s in args.matfree_sides.split(",") if s]
    its = args.matfree_its
    crit = StoppingCriteria(maxits=its)   # fixed-work protocol
    rows = []

    def best_of(fn, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    def emit(name, side, nparts, t, solver, op=None, extra=None):
        row = {
            "metric": f"matfree_cg_iters_per_sec_poisson2d_n{side}"
                      f"_np{nparts}_f32_its{its}_{name}",
            "case": name,
            "value": round(its / t, 2),
            "unit": "iters/s",
            "s_per_iter": round(t / its, 8),
            "dtype": "f32",
            "nparts": nparts,
            "iterations": int(solver.stats.niterations),
        }
        if op is not None:
            row["operator"] = op.identity()
        if extra:
            row.update(extra)
        print(f"# n={side} np={nparts} {name}: {t:.3f}s for {its} its "
              f"({its / t:.1f} iters/s)", file=sys.stderr)
        print(json.dumps(row))
        rows.append(row)
        _sink_stats(row, solver)
        sys.stdout.flush()

    for side in sides:
        csr = _build(side, 2)
        n = csr.shape[0]
        rng = np.random.default_rng(0)
        b = rng.standard_normal(n).astype(np.float32)
        op = poisson_stencil(side, 2, dtype=jnp.float32)
        single = [
            ("matfree", op, op),
            ("dia", dia_from_csr(csr, dtype=jnp.float32), None),
            ("ell", device_matrix_from_csr(csr, dtype=jnp.float32,
                                           format="ell"), None),
        ]
        for name, A, op_row in single:
            s = JaxCGSolver(A, kernels="xla")
            device_sync(s.solve(b, criteria=crit, host_result=False,
                                raise_on_divergence=False))  # compile

            def once(s=s):
                device_sync(s.solve(b, criteria=crit, host_result=False,
                                    raise_on_divergence=False))

            emit(name, side, 1, best_of(once), s, op=op_row)

        # 8-part mesh pair: assembled DIA vs armed matfree over the
        # SAME band partition / halo plan
        part = partition_rows(csr, 8, seed=0, method="band")
        for name, armed in (("dist_dia", False), ("dist_matfree", True)):
            prob = DistributedProblem.build(csr, part, 8,
                                            dtype=jnp.float32)
            if armed:
                arm_matfree(prob, op)
            s = DistCGSolver(prob)
            device_sync(s.solve(b, criteria=crit, host_result=False,
                                raise_on_divergence=False))  # compile

            def once(s=s):
                device_sync(s.solve(b, criteria=crit, host_result=False,
                                    raise_on_divergence=False))

            led = s.comm_profile()
            emit(name, side, 8, best_of(once), s,
                 op=op if armed else None,
                 extra={"matrix_free": bool(led.get("matrix_free"))})
    return _finish(args, rows, 0)


def _finish(args, rows, rc: int) -> int:
    """Apply the --baseline regression gate to this run's emitted rows
    (the perfmodel tier's case-by-case diff -- same engine as
    scripts/bench_diff.py): exit nonzero when any common case fell more
    than --fail-on-regress percent below the baseline capture, or when
    nothing was comparable at all (a renamed metric must not silently
    green the gate)."""
    if not args.baseline:
        return rc
    from acg_tpu.perfmodel import check_regression

    gate = check_regression(rows, args.baseline, args.fail_on_regress)
    return rc or gate


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="run the whole BASELINE ladder (one JSON line/row)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="compare this run's rows against a prior "
                         "capture (a --stats-json JSONL or a bench "
                         "row file like BENCH_*.json) and exit nonzero "
                         "on regression -- the enforced form of the "
                         "BENCH trajectory.  A DIRECTORY is a "
                         "--history run ledger: the best USABLE prior "
                         "capture per case baselines, with "
                         "bench_backend_unavailable entries skipped "
                         "(an all-unavailable ledger refuses, exit 2)")
    ap.add_argument("--fail-on-regress", type=float, default=10.0,
                    metavar="PCT",
                    help="with --baseline: regression threshold in "
                         "percent (default: 10)")
    ap.add_argument("--row", metavar="SUBSTR", default=None,
                    help="with --full: run only ladder rows whose metric "
                         "name contains SUBSTR (per-row driver "
                         "invocations -- scripts/ladder.sh -- so one "
                         "contention burst or tunnel drop cannot take "
                         "out subsequent rows; round-3 verdict item 8)")
    ap.add_argument("--sweep-np", action="store_true",
                    help="multi-chip CPU-mesh correctness sweep")
    ap.add_argument("--algorithms", action="store_true",
                    help="run the communication-avoiding recurrence "
                         "sweep (classic/pipelined/sstep:S/p(l)) on "
                         "the 8-part CPU mesh: s/iter + comm ledger "
                         "per algorithm")
    ap.add_argument("--algorithms-side", type=int, default=128,
                    metavar="N",
                    help="with --algorithms: Poisson grid side "
                         "(default 128 -> n=16384: small n/P, the "
                         "latency-dominated regime)")
    ap.add_argument("--algorithms-its", type=int, default=200,
                    metavar="K",
                    help="with --algorithms: fixed iterations per "
                         "solve (default 200)")
    ap.add_argument("--overlap", action="store_true",
                    help="run the fused-iteration overlap sweep "
                         "(comm={xla,dma} x kernels={auto,fused} on "
                         "the 8-part mesh at small n/P, each case "
                         "profiler-captured for its measured "
                         "overlap-efficiency; one JSON line per case)")
    ap.add_argument("--overlap-side", type=int, default=64,
                    metavar="N",
                    help="with --overlap: Poisson grid side (default "
                         "64 -- small n/P, the collective-latency-"
                         "dominated regime)")
    ap.add_argument("--overlap-its", type=int, default=200,
                    metavar="K",
                    help="with --overlap: fixed iterations per case "
                         "(default 200)")
    ap.add_argument("--matfree", action="store_true",
                    help="run the matrix-free operator sweep (matfree "
                         "vs assembled DIA vs assembled ELL on the "
                         "single device, assembled-vs-matfree on the "
                         "8-part mesh; fixed-iteration protocol, one "
                         "JSON line per case; matfree rows carry the "
                         "operator identity for bench_diff keying)")
    ap.add_argument("--matfree-sides", default="256,512,1024",
                    metavar="N,N",
                    help="with --matfree: comma-separated Poisson grid "
                         "sides (default 256,512,1024 -- the largest "
                         "is bandwidth-bound on every backend "
                         "measured, where deleting the plane reads "
                         "shows up)")
    ap.add_argument("--matfree-its", type=int, default=200, metavar="K",
                    help="with --matfree: fixed iterations per case "
                         "(default 200)")
    ap.add_argument("--batched", action="store_true",
                    help="batched multi-RHS throughput case: solves/s "
                         "at B in {1,4,8}, one batched solve vs a "
                         "sequential B-solve loop of the same columns, "
                         "plus the block-CG iteration-ratio case on "
                         "the --aniso family (ISSUE 11 acceptance).  "
                         "nrhs/block join the bench-diff case key; the "
                         "first batched capture starts a fresh "
                         "baseline series")
    ap.add_argument("--batched-side", type=int, default=128, metavar="N",
                    help="with --batched: Poisson grid side "
                         "(default: 128)")
    ap.add_argument("--batched-its", type=int, default=200, metavar="K",
                    help="with --batched: fixed iterations per solve "
                         "(default: 200)")
    ap.add_argument("--batched-aniso-side", type=int, default=48,
                    metavar="N",
                    help="with --batched: aniso grid side for the "
                         "block-CG iteration case (default: 48)")
    ap.add_argument("--stats-json", metavar="FILE", default=None,
                    help="JSONL-append each timed case's full solver "
                         "stats document (the CLI's --stats-json "
                         "schema, acg_tpu.telemetry) next to the "
                         "summary rows on stdout")
    ap.add_argument("--soak", type=int, default=0, metavar="N",
                    help="service-soak mode: N repeated fixed-work "
                         "solves of one Poisson system through "
                         "acg_tpu.soak (p50/p95/p99 latency row, EWMA "
                         "drift detector, --fail-on-drift exit 7)")
    ap.add_argument("--soak-side", type=int, default=256, metavar="N",
                    help="with --soak: Poisson grid side (default: 256)")
    ap.add_argument("--soak-dim", type=int, default=2, choices=(2, 3),
                    help="with --soak: Poisson dimension (default: 2)")
    ap.add_argument("--soak-its", type=int, default=200, metavar="K",
                    help="with --soak: fixed iterations per solve "
                         "(default: 200)")
    ap.add_argument("--soak-dtype", default="f32",
                    choices=("f32", "mixed", "bf16"),
                    help="with --soak: storage tier (default: f32)")
    ap.add_argument("--precond", default="none", metavar="KIND",
                    help="with --soak: preconditioner selection "
                         "(none | jacobi | bjacobi[:BS] | cheby:K, "
                         "acg_tpu.precond); joins the case metric so "
                         "preconditioned captures never diff against "
                         "plain ones")
    ap.add_argument("--fail-on-drift", type=float, default=None,
                    metavar="PCT",
                    help="with --soak: exit 7 when EWMA solve latency "
                         "drifts more than PCT percent over the "
                         "baseline window's median")
    ap.add_argument("--metrics-file", metavar="FILE", default=None,
                    help="with --soak: flush the service-metrics "
                         "registry to FILE in Prometheus text format "
                         "(atomic rename; also written on SIGTERM)")
    ap.add_argument("--calibration", metavar="FILE", default=None,
                    help="a saved acg-tpu-commbench/1 document "
                         "(acg-tpu --commbench): its calibration id is "
                         "stamped on every --stats-json case document, "
                         "so bench_diff keys differently-calibrated "
                         "captures apart instead of diffing them "
                         "silently")
    ap.add_argument("--no-probe-cache", action="store_true",
                    help="ignore the on-disk backend-keyed triad-probe "
                         "sidecar and re-measure HBM bandwidth")
    args = ap.parse_args(argv)
    global _STATS_SINK, _CALIBRATION_ID, _USE_PROBE_CACHE
    _STATS_SINK = args.stats_json
    _USE_PROBE_CACHE = not args.no_probe_cache
    if args.calibration:
        from acg_tpu.commbench import load_calibration
        try:
            _CALIBRATION_ID = load_calibration(
                args.calibration)["calibration_id"]
        except (OSError, ValueError) as e:
            ap.error(f"--calibration {args.calibration}: {e}")
    if not args.soak and (args.metrics_file
                          or args.fail_on_drift is not None
                          or args.precond != "none"):
        # only the soak harness reads these; silently ignoring them
        # would let an operator believe a gate/capture ran
        ap.error("--metrics-file/--fail-on-drift/--precond need "
                 "--soak N")
    if args.fail_on_drift is not None:
        from acg_tpu.soak import gate_is_vacuous
        if args.fail_on_drift <= 0:
            ap.error("--fail-on-drift must be positive percent")
        if gate_is_vacuous(args.soak):
            ap.error(f"--fail-on-drift is vacuous at --soak "
                     f"{args.soak} (the baseline window consumes the "
                     f"whole run); use --soak 4 or more")

    if args.sweep_np:
        return sweep_np()

    if args.algorithms:
        # like --sweep-np/--batched, provisions its own 8-part virtual
        # CPU mesh (re-executing itself when the flags must be set
        # before jax init), so it runs BEFORE the backend probe
        return run_algorithms_mode(args)

    if args.overlap:
        # like --algorithms: provisions its own 8-part virtual CPU
        # mesh, so it runs BEFORE the backend probe
        return run_overlap_mode(args)

    if args.matfree:
        # like --overlap: provisions its own 8-part virtual CPU mesh,
        # so it runs BEFORE the backend probe
        return run_matfree_mode(args)

    if args.batched:
        # like --sweep-np, provisions its own 8-part virtual CPU mesh
        # (re-executing itself when the flags must be set before jax
        # init), so it runs BEFORE the backend probe
        return run_batched_mode(args)

    # fail FAST when the tunneled backend is dead: its init has been
    # observed to hang ~15 minutes before raising UNAVAILABLE (round 5),
    # which would silently eat the driver's whole capture budget.  The
    # bounded child-process probe now lives in _platform.probe_backend
    # (shared with the CLI and dryrun_multichip); the emitted row
    # self-describes the failure.  ACG_TPU_SKIP_BACKEND_PROBE opts out
    # (drivers that just proved the backend alive themselves,
    # scripts/r5_capture.sh -- the probe child is a full backend init,
    # minutes of redundant wall-clock per ladder row over a tunnel).
    from acg_tpu._platform import honour_jax_platforms, probe_backend

    backend_ok, detail = probe_backend()
    if not backend_ok:
        print(json.dumps({"metric": "bench_backend_unavailable",
                          "value": 0, "unit": "iters/s",
                          "error": detail}))
        sys.stdout.flush()
        return 2
    # the PARENT must honour JAX_PLATFORMS too, or it initialises a
    # different backend than the one the probe just validated (the axon
    # plugin overrides the env var at import time)
    honour_jax_platforms()
    import jax

    _enable_compile_cache()

    if args.soak:
        return run_soak_mode(args)

    if not args.full:
        # flagship: wait for a quiet window (probe-gated, round-3
        # verdict item 1), then measure the kernel AND storage tiers in
        # that window and report the best SOUND config.  "f32"/"mixed"
        # are sound by construction ("mixed" is arithmetic-identical to
        # f32); the bf16-family tiers must DEMONSTRATE soundness -- the
        # measured true relative residual under the manufactured-
        # solution protocol must clear SOUND_REL_RESIDUAL.  Plain bf16
        # stalls at ~2e-1 at flagship kappa (context keys only);
        # "bf16rr" (periodic f32 residual replacement) measures ~1e-6
        # and competes for the headline at ~0.94x plain-bf16 speed.
        # one stable metric name across rounds/runs; the winning tier is
        # recorded in the "dtype"/"kernels" fields (a name that changed
        # with the winner would split the longitudinal series)
        name = "cg_iters_per_sec_poisson2d_n2048_f32"
        csr = _build(2048, 2)
        if jax.default_backend() == "tpu":
            bw0, quiet = wait_for_quiet()
        else:
            # CPU/debug runs: no quiet threshold exists to wait for
            bw0, quiet = 0.0, False
        print(f"# capture window: probe {bw0:.0f} GB/s "
              f"({'quiet' if quiet else 'CONTENDED -- budget exhausted'})",
              file=sys.stderr)
        rows = {}

        # a driver-side timeout must not cost the whole capture: on
        # SIGTERM/SIGINT, emit the best row measured so far (marked
        # partial) before dying
        import signal

        def _emit_partial(signum, frame):
            if rows:
                best = max(rows.values(), key=lambda r: r["value"])
                best = dict(best)
                best["partial_capture"] = True
                print(json.dumps(best))
                sys.stdout.flush()
                # the baseline gate runs on the partial row too: a
                # truncated capture is exactly when a silent regression
                # would otherwise green the gate
                sys.exit(_finish(args, [best], 0))
            sys.exit(124)

        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _emit_partial)
        for dtn in ("f32", "mixed", "bf16", "bf16rr"):
            # a tier that fails (compile flake, OOM) must not sink the
            # tiers already measured
            try:
                best = run_case(csr, name, False, False, "auto", dtn)
                if best.get("kernels") != "xla":
                    alt = run_case(csr, name, False, False, "xla", dtn)
                    if alt["value"] > best["value"]:
                        best = alt
                # the two-phase fused iteration beat the xla tier in
                # both prior same-window sweeps (QUIET_AB 1.27x/2.16x,
                # contended-grade); measuring it here lets the first
                # honest capture adjudicate the promotion (round-4
                # verdict item 2).  No fused hook for the replacement
                # program -> bf16rr keeps its tiers.
                if dtn != "bf16rr" and jax.default_backend() == "tpu":
                    # TPU only: off-TPU the tier resolves to interpret
                    # mode, which is unusable at flagship size
                    try:
                        alt = run_case(csr, name, False, False, "fused",
                                       dtn)
                        if alt["value"] > best["value"]:
                            best = alt
                    except Exception as e:  # noqa: BLE001 -- keep `best`
                        print(f"# {dtn} fused tier skipped: "
                              f"{type(e).__name__}: "
                              f"{str(e).splitlines()[0][:160]}",
                              file=sys.stderr)
                rows[dtn] = best
            except Exception as e:  # noqa: BLE001 -- report and continue
                print(f"# {dtn} tier skipped: {type(e).__name__}: "
                      f"{str(e).splitlines()[0][:200]}", file=sys.stderr)
        if not rows:
            return 1
        sound = [rows[k] for k in ("f32", "mixed") if k in rows]
        for dtn in ("bf16", "bf16rr"):
            row = rows.get(dtn)
            if row is None:
                continue
            row = _accuracy_context(csr, row, dtn)
            if row.get("rel_residual_1000it",
                       float("inf")) < SOUND_REL_RESIDUAL:
                sound.append(row)
        best = max(sound or rows.values(), key=lambda r: r["value"])
        for dtn in ("bf16", "bf16rr"):
            row = rows.get(dtn)
            if row is not None and best is not row:
                best[f"{dtn}_iters_per_sec"] = row["value"]
                if "rel_residual_1000it" in row:
                    best[f"{dtn}_rel_residual_1000it"] = \
                        row["rel_residual_1000it"]
        best["quiet_window"] = bool(quiet)
        print(json.dumps(best))
        return _finish(args, [best], 0)

    cases = [
            ("cg_iters_per_sec_poisson2d_n2048_f32",
             2048, 2, False, False, "auto", "f32"),
            ("cg_xla_iters_per_sec_poisson2d_n2048_f32",
             2048, 2, False, False, "xla", "f32"),
            ("cg_iters_per_sec_poisson2d_n2048_mixed",
             2048, 2, False, False, "auto", "mixed"),
            ("cg_iters_per_sec_poisson2d_n2048_bf16",
             2048, 2, False, False, "auto", "bf16"),
            ("cg_iters_per_sec_poisson2d_n2048_bf16rr",
             2048, 2, False, False, "auto", "bf16rr"),
            ("cg_pipelined_iters_per_sec_poisson2d_n2048_f32",
             2048, 2, True, False, "xla", "f32"),
            ("cg_iters_per_sec_poisson3d_n128_f32",
             128, 3, False, False, "xla", "f32"),
            ("cg_pipelined_iters_per_sec_poisson3d_n128_f32",
             128, 3, True, False, "xla", "f32"),
            ("cg_iters_per_sec_poisson3d_n256_f32",
             256, 3, False, False, "xla", "f32"),
            ("cg_iters_per_sec_poisson3d_n256_mixed",
             256, 3, False, False, "xla", "mixed"),
            ("cg_dist1_iters_per_sec_poisson2d_n2048_f32",
             2048, 2, False, True, "xla", "f32"),
            # auto -> binned ELL (the merge-CSR-goal format); the COO row
            # stays as the within-window A/B partner
            ("cg_iters_per_sec_irregular_n500k_d16_f32",
             500_000, 0, False, False, "xla", "f32"),
            ("cg_coo_iters_per_sec_irregular_n500k_d16_f32",
             500_000, 0, False, False, "xla", "f32"),
        ]

    built: dict[tuple, object] = {}
    emitted: list[dict] = []  # every row this run printed (baseline gate)

    def emit(row: dict) -> None:
        emitted.append(row)
        print(json.dumps(row))

    if args.row:
        # exact name match wins (several row names are substrings of
        # others, e.g. ..._bf16 / ..._bf16rr); substring is the
        # fallback for family selections
        exact = [c for c in cases if c[0] == args.row]
        cases = exact or [c for c in cases if args.row in c[0]]
    for name, side, dim, pipelined, dist, kernels, dtn in cases:
        # one failing case (device flake, OOM) must not sink the rest of
        # the ladder -- report it and keep going
        try:
            key = (side, dim)
            if key not in built:
                t0 = time.perf_counter()
                built[key] = _build(side, dim)
                csr = built[key]
                print(f"# setup: {dim}D n={side} N={csr.shape[0]} "
                      f"nnz={csr.nnz} in {time.perf_counter() - t0:.1f}s on "
                      f"{jax.devices()[0].platform}", file=sys.stderr)
            emit(run_case(
                built[key], name, pipelined, dist, kernels, dtn,
                spmv_format="coo" if "_coo_" in name else "auto"))
        except Exception as e:  # noqa: BLE001 -- report and continue
            print(f"# {name} skipped: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:200]}", file=sys.stderr)
        sys.stdout.flush()

    # external/host baselines on the SAME 128^3 matrix (the reference's
    # PETSc performance-baseline role, cgpetsc.c:335-378): scipy-CG
    # oracle and the native C++ core, timed under the same protocol so
    # the cross-implementation perf comparison is reproducible here
    for name, kind in (
            ("cg_iters_per_sec_poisson3d_n128_petsc_f64", "petsc"),
            ("cg_iters_per_sec_poisson3d_n128_hostnative_f64", "native")):
        if args.row and args.row not in name:
            continue
        try:
            if (128, 3) not in built:
                built[(128, 3)] = _build(128, 3)
            emit(run_host_baseline(built[(128, 3)], name, kind))
        except Exception as e:  # noqa: BLE001 -- report and continue
            print(f"# {name} skipped: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:200]}", file=sys.stderr)
        sys.stdout.flush()

    # the north-star problem size, single chip, direct-DIA assembly;
    # skipped gracefully where the device memory cannot hold it.  The
    # bf16rr rows (256^3 + 512^3) carry a measured soundness gate at 3D
    # conditioning (round-4 verdict item 1)
    built.clear()
    for side, dtn in ((512, "f32"), (512, "mixed"), (512, "bf16rr"),
                      (256, "bf16rr")):
        name = f"cg_iters_per_sec_poisson3d_n{side}_{dtn}_dia"
        if args.row and args.row not in name:
            continue
        try:
            emit(run_case_dia(side, 3, name, dtn))
        except Exception as e:  # noqa: BLE001 -- report and continue
            print(f"# {side}^3 {dtn} row skipped: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:200]}", file=sys.stderr)
        sys.stdout.flush()
    return _finish(args, emitted, 0)


if __name__ == "__main__":
    sys.exit(main())
