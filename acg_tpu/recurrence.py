"""Unified recurrence builder: one place where a CG recurrence is
defined, composed with any tier's SpMV/reduction machinery.

ROADMAP item 3.  Before this module every (recurrence x tier) cell of
the program matrix was hand-built -- classic and Ghysels-Vanroose
pipelined each copied into solvers/jax_cg.py, parallel/dist.py,
solvers/batched.py, parallel/dist_batched.py -- and PRs 5-11 threaded
each cross-cutting feature (precond, health, ABFT, checkpoint carry,
telemetry ring, batching) through every copy by hand.  Here a
recurrence contributes three things:

* its **carry layout** (what rides the loop),
* its **per-iteration update** (pure math over the tier's ops),
* its **reduction schedule** (what crosses the mesh, and how often --
  the ledger entry perfmodel's comm profile reports),

and the builder composes it with a :class:`TierOps` bundle -- the
tier's SpMV (halo'd or not), its global dot / fused k-dot family
(:mod:`acg_tpu.parallel.reductions`), its psum, its storage rounding.

Recurrences:

``classic`` / ``pipelined``
    The existing hand-built programs stay dispatched (zero risk), but
    the builder can emit both, and tests/test_hlo_structure.py pins the
    builder emission BYTE-IDENTICAL (StableHLO) to the hand-built
    programs on the single-device and dist tiers -- the proof that this
    refactor is a no-op for current users and that new features can
    land in the builder instead of per-copy.

``sstep:S`` -- communication-avoiding s-step CG (arXiv:2501.03743
    lineage; Chronopoulos-Gear / Carson formulation).  Per outer block:
    build the 2s+1-column Krylov basis ``[p, th_1(A)p, ..., th_s(A)p,
    r, ..., th_{s-1}(A)r]`` (2s-1 SpMVs), reduce its Gram matrix in ONE
    allreduce, then run s CG steps entirely in coefficient space --
    mesh reduction count drops from 2/iteration (classic) to 1 per s
    iterations.  Monomial basis below S = 4, scaled-Chebyshev basis
    (power-iteration lambda_max) at S >= 4 for conditioning -- measured
    in the prototype: monomial s=8 drifts (+12% iterations on 2D
    Poisson), Chebyshev s=8 matches classic's count exactly.

``pipelined:L`` -- deep-pipelined p(l)-CG (Cornelis-Cools-Vanroose,
    arXiv:1801.04728 lineage).  Lanczos-basis CG where the basis vector
    v_m is recovered with lag l from an auxiliary basis z_j = P_l(A)
    v_{j-l} (P_l = degree-l shifted polynomial, Chebyshev shifts):
    per iteration ONE SpMV and ONE fused allreduce of the 2l+2-scalar
    z-window dot vector whose result is only consumed l iterations
    later -- l reduction latencies hidden behind l SpMVs.  The z-Gram
    is stream-Cholesky-factored on the fly; the known square-root
    breakdown of the method (the Gram loses positivity as convergence
    proceeds) exits through the breakdown flag into the standard
    restart ladder (restart from the current iterate = the literature's
    remedy; measured total iterations stay within ~1.8x classic on the
    aniso family at rtol 1e-8).  ``pipelined:1`` is p(1)-CG, NOT the
    Ghysels-Vanroose variant (different recurrence family).

Both new recurrences ride the single-device tier (and its sharded-DIA
subclass -- the SpMV is a parameter) through the programs in this
module, and the dist tier through :func:`dist_flow` composed with the
mesh machinery in parallel/dist.py.  They currently run
unpreconditioned over f32/f64 vectors: precond / bf16 / replacement /
checkpoint-carry composition is refused explicitly at solver setup
(the could-never-fire discipline) rather than silently dropped.
"""

from __future__ import annotations

import dataclasses
import functools
import re

import jax
import jax.numpy as jnp
import numpy as np

from acg_tpu.ops.spmv import acc_dtype

# the classic/pipelined body + loop driver live in jax_cg; imported
# lazily inside functions to avoid a circular import at module load
# (jax_cg does not import recurrence at module level either -- the
# solver imports it inside _select_program)

POWER_ITERS = 24          # lambda_max power iteration length (setup)
LAM_SAFETY = 1.05         # spectral headroom on the estimated lambda_max
PL_RESTART_BUDGET = 64    # sqrt-breakdown restarts before giving up


# -- recurrence specs ------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RecurrenceSpec:
    """Hashable static-argument recurrence selector (the PrecondSpec /
    FaultSpec design): ``kind`` in {"classic", "pipelined", "sstep",
    "pl"}; ``param`` is s (block length) or l (pipeline depth)."""

    kind: str
    param: int = 0

    def __post_init__(self):
        if self.kind not in ("classic", "pipelined", "sstep", "pl"):
            raise ValueError(f"unknown recurrence kind {self.kind!r}")
        if self.kind == "sstep" and not 2 <= self.param <= 16:
            raise ValueError(
                f"sstep:S needs 2 <= S <= 16 (got {self.param}): S = 1 "
                f"is classic CG, and the 2S+1-column basis loses full "
                f"rank in floating point well before S = 16")
        if self.kind == "pl" and not 1 <= self.param <= 4:
            raise ValueError(
                f"pipelined:L needs 1 <= L <= 4 (got {self.param}): "
                f"the z-basis Gram conditioning degrades with the "
                f"polynomial degree")

    @property
    def communication_avoiding(self) -> bool:
        return self.kind in ("sstep", "pl")

    @property
    def basis(self) -> str:
        """s-step basis selection: monomial below the measured
        stability knee, scaled Chebyshev at s >= 4."""
        return "chebyshev" if self.kind == "sstep" and self.param >= 4 \
            else "monomial"

    @property
    def needs_lam(self) -> bool:
        """Whether the program consumes the (lmin, lmax) spectral
        estimate: the Chebyshev s-step basis and every p(l) shift."""
        return self.kind == "pl" or (self.kind == "sstep"
                                     and self.basis == "chebyshev")

    def __str__(self):
        if self.kind == "sstep":
            return f"sstep:{self.param}"
        if self.kind == "pl":
            return f"pipelined:{self.param}"
        return self.kind

    def solver_name(self, tier: str = "cg") -> str:
        """Telemetry/metrics solver label.  Deliberately does NOT
        contain the substring "pipelined": health.spectrum_estimate
        keys its Lanczos (alpha, beta) re-alignment on that substring,
        and BOTH new recurrences record classic-aligned rows (s-step
        records the plain CG scalars of each inner step; p(l) records
        (q^2, 1/d, l^2, d) at solution-advance time, which satisfies
        the classic identity by construction)."""
        if self.kind == "sstep":
            return f"{tier}-sstep{self.param}"
        if self.kind == "pl":
            return f"{tier}-pl{self.param}"
        return tier


def parse_algorithm(name) -> RecurrenceSpec | None:
    """``--algorithm`` parser: classic | pipelined | sstep:S |
    pipelined:L.  None/"auto" -> None (the --solver name decides)."""
    if name is None or isinstance(name, RecurrenceSpec):
        return name
    s = str(name).strip().lower()
    if s in ("", "auto"):
        return None
    if s == "classic":
        return RecurrenceSpec("classic")
    if s == "pipelined":
        return RecurrenceSpec("pipelined")
    m = re.fullmatch(r"sstep:(\d+)", s)
    if m:
        return RecurrenceSpec("sstep", int(m.group(1)))
    m = re.fullmatch(r"pipelined:(\d+)", s)
    if m:
        return RecurrenceSpec("pl", int(m.group(1)))
    raise ValueError(
        f"unknown --algorithm {name!r}: expected classic, pipelined, "
        f"sstep:S (2 <= S <= 16) or pipelined:L (1 <= L <= 4)")


def reduction_schedule(spec: RecurrenceSpec | None, pipelined: bool,
                       precond: bool = False) -> dict:
    """The recurrence's per-iteration mesh-reduction schedule -- the
    single source the comm ledger (perfmodel via DistCGSolver.
    comm_profile) reports.  Fractional values are exact per-iteration
    averages of per-block quantities (communication-avoiding
    recurrences amortize; an int would lie)."""
    if spec is not None and spec.kind == "sstep":
        s = spec.param
        w = 2 * s + 1
        return {
            "allreduce_per_iteration": 1.0 / s,
            "allreduce_scalars": w * w,
            "spmv_per_iteration": (2 * s - 1) / s,
            "iterations_per_reduction": s,
        }
    if spec is not None and spec.kind == "pl":
        return {
            "allreduce_per_iteration": 1.0,
            "allreduce_scalars": 2 * spec.param + 2,
            "spmv_per_iteration": 1.0,
            "reduction_latency_hidden": spec.param,
        }
    if pipelined:
        return {"allreduce_per_iteration": 1.0,
                "allreduce_scalars": 3 if precond else 2,
                "spmv_per_iteration": 1.0}
    return {"allreduce_per_iteration": 2.0,
            "allreduce_scalars": 2 if precond else 1,
            "spmv_per_iteration": 1.0}


# -- tier ops --------------------------------------------------------------

@dataclasses.dataclass
class TierOps:
    """What a tier contributes to the builder: its SpMV (halo machinery
    included), its global dot, its stacked-payload reduction (the ONE
    collective of the communication-avoiding recurrences), and its
    storage rounding.  ``spmv(v, k)`` takes the iteration index so the
    deterministic fault injector can key on it."""

    spmv: callable
    dot: callable            # (a, c) -> global scalar in sdt
    psum_stack: callable     # stacked local payload -> reduced payload
    store: callable
    sdt: object

    def gram(self, V):
        """Global Gram matrix of the stacked basis V ((m, n) rows):
        one local matmul, ONE reduction."""
        local = jnp.matmul(V, V.T, preferred_element_type=self.sdt)
        return self.psum_stack(local)

    def windots(self, Z, znew):
        """The p(l) fused window reduction: (2l+2,) dots of the rolled
        z-window against the new z -- one local matvec, ONE psum."""
        local = jnp.matmul(Z.astype(self.sdt),
                           znew.astype(self.sdt),
                           preferred_element_type=self.sdt)
        return self.psum_stack(local)


def single_ops(A, kernels, dot, sdt, store, fault=None):
    """TierOps for the single-device tier (and the sharded-DIA tier,
    whose mesh-aware SpMV arrives as a callable ``kernels``).

    ``A`` may be any DeviceMatrix OR a matrix-free operator
    (acg_tpu.ops.operator): ``_spmv_fn`` routes through the ops.spmv
    protocol dispatch, so this is the ONE SpMV source through which
    every builder recurrence -- classic, pipelined, sstep:S,
    pipelined:L -- inherits matrix-free operation (the s-step basis
    products and the p(l) auxiliary-basis SpMVs are all ``ops.spmv``
    calls; nothing below ever touches stored planes)."""
    from acg_tpu.solvers.jax_cg import _spmv_fn
    spmv_ = _spmv_fn(kernels)

    def spmv(v, k=None):
        y = spmv_(A, v)
        if fault is not None and k is not None:
            y = fault.apply_spmv(y, k)
        return y

    return TierOps(spmv=spmv, dot=dot, psum_stack=lambda s: s,
                   store=store, sdt=sdt)


# -- s-step CG -------------------------------------------------------------

def sstep_basis_matrix(s: int, basis: str, lam, sdt):
    """(s+1, s+1) change-of-basis B with A V[:, j] = V B[:, j] for
    j < s (the last column is never consumed: coefficient vectors keep
    total degree <= s inside a block)."""
    if basis == "monomial":
        B = np.zeros((s + 1, s + 1))
        for j in range(s):
            B[j + 1, j] = 1.0
        return jnp.asarray(B, sdt)
    lmin, lmax = lam
    d = (lmax + lmin) / 2.0
    c = (lmax - lmin) / 2.0
    B = jnp.zeros((s + 1, s + 1), sdt)
    for j in range(s):
        if j == 0:
            B = B.at[0, 0].set(d)
            B = B.at[1, 0].set(c)
        else:
            B = B.at[j - 1, j].set(c / 2.0)
            B = B.at[j, j].set(d)
            B = B.at[j + 1, j].set(c / 2.0)
    return B


def sstep_combined_bmat(s: int, basis: str, lam, sdt):
    """(2s+1, 2s+1) block-diagonal change-of-basis for the combined
    [P-basis | R-basis] stack, top-degree columns zeroed."""
    m = 2 * s + 1
    Bp = sstep_basis_matrix(s, basis, lam, sdt)
    B = jnp.zeros((m, m), sdt)
    B = B.at[:s + 1, :s + 1].set(Bp)
    if s > 1:
        Br = sstep_basis_matrix(s - 1, basis, lam, sdt)
        B = B.at[s + 1:, s + 1:].set(Br)
    B = B.at[:, s].set(0.0)
    B = B.at[:, m - 1].set(0.0)
    return B


def sstep_build_basis(ops: TierOps, v, deg: int, basis: str, lam, k):
    """The matrix-powers stack [v, th_1(A)v, ..., th_deg(A)v] as a
    (deg+1, n) array -- deg SpMVs through the tier's own machinery
    (halo exchanges and all), zero reductions."""
    rows = [v]
    if basis == "monomial":
        for j in range(deg):
            rows.append(ops.store(ops.spmv(rows[-1], k)))
        return jnp.stack(rows)
    lmin, lmax = lam
    d = (lmax + lmin) / 2.0
    c = (lmax - lmin) / 2.0
    for j in range(deg):
        w = ops.spmv(rows[-1], k) - d * rows[-1]
        if j == 0:
            rows.append(ops.store(w / c))
        else:
            rows.append(ops.store(2.0 * w / c - rows[-2]))
    return jnp.stack(rows)


def make_sstep_block(ops: TierOps, s: int, basis: str, lam, res_tol,
                     maxits, fault=None, trace: int = 0,
                     progress: int = 0, health=None, what: str = "cg",
                     leader=None, k_offset=None):
    """The s-step outer-block body, tier-agnostic.

    Carry: ``(x, r, p, gamma, k, bad)`` (+ audit vector, + telemetry
    ring -- the jax_cg tail discipline: feature leaves ride LAST).
    ``gamma`` is the coefficient-space ||r||^2 carried across blocks --
    the convergence test's scalar, one reduction-free byproduct of the
    Gram.  Returns ``(body, tails)`` where tails counts the armed
    feature leaves."""
    sdt = ops.sdt
    tol2 = res_tol * res_tol
    Bmat = sstep_combined_bmat(s, basis, lam, sdt)
    w = 2 * s + 1
    if trace or progress:
        from acg_tpu import telemetry
    if health is not None:
        from acg_tpu import health as _health

    def body(state):
        if trace:
            buf, state = state[-1], state[:-1]
        if health is not None:
            aud, state = state[-1], state[:-1]
        x, r, p, gamma, k, bad = state
        # -- basis: 2s-1 SpMVs, zero reductions ----------------------
        Vp = sstep_build_basis(ops, p, s, basis, lam, k)
        if s > 1:
            Vr = sstep_build_basis(ops, r, s - 1, basis, lam, k)
            V = jnp.concatenate([Vp, Vr], axis=0)
        else:
            V = jnp.concatenate([Vp, r[None]], axis=0)
        # -- the block's ONE reduction -------------------------------
        G = ops.gram(V)
        # -- s CG steps in coefficient space (unrolled: s is static) --
        pc = jnp.zeros((w,), sdt).at[0].set(1.0)
        rc = jnp.zeros((w,), sdt).at[s + 1].set(1.0)
        xc = jnp.zeros((w,), sdt)
        # the coefficient-space gamma of the FRESH basis: rc' G rc is
        # the (s+1, s+1) Gram entry -- re-anchors the carried scalar
        # against basis-change drift each block
        gamma_blk = G[s + 1, s + 1]
        nsteps = jnp.int32(0)
        for j in range(s):
            wc = Bmat @ pc
            Gw = G @ wc
            denom = pc @ Gw
            if fault is not None:
                denom = fault.apply_dot(denom, k + j)
            bad_j = ((~jnp.isfinite(denom)) | (~jnp.isfinite(gamma_blk))
                     | ((denom <= 0) & (gamma_blk > 0)))
            step = ((~bad) & (~bad_j) & (gamma_blk >= tol2)
                    & (k + jnp.int32(j) < maxits))
            bad = bad | (bad_j & (gamma_blk >= tol2)
                         & (k + jnp.int32(j) < maxits))
            alpha = jnp.where(step, gamma_blk
                              / jnp.where(denom == 0, 1.0, denom), 0.0)
            xc = xc + alpha * pc
            rc_new = rc - alpha * wc
            Gr = G @ rc_new
            gamma_next = rc_new @ Gr
            beta = jnp.where(step, gamma_next
                             / jnp.where(gamma_blk == 0, 1.0,
                                         gamma_blk), 0.0)
            pc = jnp.where(step, rc_new + beta * pc, pc)
            rc = jnp.where(step, rc_new, rc)
            if trace:
                buf = jnp.where(
                    step,
                    telemetry.ring_record(buf, k + jnp.int32(j),
                                          gamma_next, alpha, beta,
                                          denom),
                    buf)
            gamma_blk = jnp.where(step, gamma_next, gamma_blk)
            nsteps = nsteps + step.astype(jnp.int32)
        # -- map back: 3 small GEMVs, zero reductions ----------------
        x = ops.store(x + xc.astype(sdt) @ V.astype(sdt))
        r = ops.store(rc.astype(sdt) @ V.astype(sdt))
        p = ops.store(pc.astype(sdt) @ V.astype(sdt))
        k_new = k + nsteps
        out_gamma = gamma_blk
        if health is not None:
            k0 = k if k_offset is None else k + k_offset
            k1 = k_new if k_offset is None else k_new + k_offset

            def compute_gap():
                bb = health_ctx["b"]
                return _health.relative_gap(bb - ops.spmv(x, None), r,
                                            ops.dot, health_ctx["bnrm2"],
                                            sdt)

            aud, fire = audit_update_crossing(
                aud, health, k0, k1, compute_gap)
            aud = _health.stall_update(aud, health, out_gamma < gamma)
            bad = bad | _health.trip(aud, health)
        if progress:
            telemetry.heartbeat(k_new, out_gamma, progress,
                                leader=leader, what=what)
        out = (x, r, p, out_gamma, k_new, bad)
        if health is not None:
            out = out + (aud,)
        if trace:
            out = out + (buf,)
        return out

    # the audit closure needs b/bnrm2 which only the caller has; it
    # fills this context dict before running the loop
    health_ctx: dict = {}
    body.health_ctx = health_ctx
    ntails = (1 if trace else 0) + (1 if health is not None else 0)
    return body, ntails


def audit_update_crossing(aud, spec, k0, k1, compute_gap):
    """Block-granular twin of health.audit_update: fire the audit when
    the cadence boundary was crossed anywhere in [k0, k1) -- the s-step
    tier advances s trajectory iterations per block, so equality
    against the cadence would skip audits whenever ``every`` is not a
    multiple of s."""
    every = jnp.int32(spec.every if spec.every else 1)
    fire = (spec.every > 0) & ((k1 // every) > (k0 // every))

    def do(a):
        gap = compute_gap()
        worst = jnp.maximum(a[1], gap)
        return jnp.stack([gap, worst, a[2] + 1.0, a[3]]).astype(a.dtype)

    new = jax.lax.cond(fire, do, lambda a: a, aud)
    return new, fire


# -- p(l)-CG ---------------------------------------------------------------

def pl_shifts(l: int, lam, sdt):
    """Chebyshev points of [lmin, lmax] -- the polynomial shifts
    sigma_0..sigma_{l-1} of the auxiliary basis z = P_l(A) v."""
    lmin, lmax = lam
    d = (lmax + lmin) / 2.0
    c = (lmax - lmin) / 2.0
    cosv = np.cos((2 * np.arange(l) + 1) * np.pi / (2 * l))
    return (d + c * jnp.asarray(cosv, sdt)).astype(sdt)


def make_pl_step(ops: TierOps, l: int, sigma, res_tol, maxits,
                 fault=None, trace: int = 0, progress: int = 0,
                 what: str = "cg", leader=None):
    """The p(l)-CG iteration body, tier-agnostic.

    Carry (all window buffers rolled newest-last; static ``l`` makes
    every index a Python constant):

    ``j``        auxiliary-basis iteration counter
    ``adv``      trajectory iterations (solution advances) -- the
                 reported niterations
    ``x, q, dprev, ptilde``  the LDL^T solution recurrence (d = 1/alpha)
    ``Z (2l+2, n)``  auxiliary basis window  z_{j-2l-1}..z_j
    ``V (2l, n)``    recovered Lanczos window v_{m-2l}..v_{m-1}
    ``zzq (l, 2l+2)``  the reduction delay line: window dots initiated
                 at iteration t are consumed at t+l (the l hidden
                 reduction latencies)
    ``gb (2l+1, 2l+1)``  banded columns of the stream-Cholesky factor
                 of the z-Gram (g[c][rr] = (v_{col-2l+rr}, z_col))
    ``gammas (l+2,), deltas (l+1,)``  Lanczos T windows
    ``conv, bad``  convergence / square-root-breakdown flags
    (+ telemetry ring, LAST)."""
    sdt = ops.sdt
    tol2 = res_tol * res_tol
    W = 2 * l + 2
    if trace or progress:
        from acg_tpu import telemetry

    def safe(x):
        return jnp.where(x == 0, jnp.asarray(1.0, sdt), x)

    def body(state):
        if trace:
            buf, state = state[-1], state[:-1]
        (j, adv, x, q, dprev, ptilde, Z, V, zzq, gb, gammas, deltas,
         conv, bad) = state
        m = j + 1 - l
        have_m = m >= 0
        y = zzq[0]
        # -- stream-Cholesky: g column m from the delayed z-dots ------
        newcol = []
        for rr in range(2 * l):
            r_abs = m - 2 * l + rr
            valid = have_m & (r_abs >= 0)
            acc = y[rr + 1]
            for tt in range(rr):
                acc = acc - gb[rr + 1][tt - rr + 2 * l] * newcol[tt]
            den = gb[rr + 1][2 * l]
            newcol.append(jnp.where(valid, acc / safe(den), 0.0))
        diag2 = y[2 * l + 1]
        for rr in range(2 * l):
            diag2 = diag2 - newcol[rr] * newcol[rr]
        bad_sqrt = have_m & ((diag2 <= 0) | (~jnp.isfinite(diag2)))
        gmm = jnp.sqrt(jnp.where(diag2 > 0, diag2, 1.0))
        newcol.append(gmm)
        # -- Lanczos T entries at index m-1 ---------------------------
        # window invariants AT ITERATION START (rolled last iteration):
        #   gammas[i] = gamma_{m-3-l+i}  -> gamma_{m-2}   = gammas[l+1]
        #                                   gamma_{m-1-l} = gammas[2]
        #   deltas[i] = delta_{m-2-l+i}  -> delta_{m-1-l} = deltas[1]
        startup = (m - 1) < l
        gm1m1 = safe(gb[2 * l][2 * l])
        gm2m1 = gb[2 * l][2 * l - 1]
        gm1m = newcol[2 * l - 1]
        gamma_m1 = jnp.where(startup, gmm / gm1m1,
                             gammas[2] * gmm / gm1m1)
        sig_m1 = sigma[jnp.clip(m - 1, 0, l - 1)]
        delta_start = sig_m1 + (gm1m - gammas[l + 1] * gm2m1) / gm1m1
        delta_main = ((gammas[2] * gm1m + deltas[1] * gm1m1
                       - gammas[l + 1] * gm2m1) / gm1m1)
        delta_m1 = jnp.where(startup, delta_start, delta_main)
        # -- recover v_m ---------------------------------------------
        zm = Z[l + 2]
        acc_v = zm.astype(sdt)
        for rr in range(2 * l):
            acc_v = acc_v - newcol[rr] * V[rr].astype(sdt)
        vm = ops.store(acc_v / safe(gmm))
        vmm = V[2 * l - 1]
        # -- advance the solution to trajectory index mm = m-1 --------
        is0 = (m - 1) == 0
        lprev = gammas[l + 1] / safe(dprev)
        dd = jnp.where(is0, delta_m1, delta_m1 - gammas[l + 1] * lprev)
        pt_new = jnp.where(is0, vmm.astype(sdt),
                           vmm.astype(sdt) - lprev * ptilde)
        do_adv = (have_m & (m >= 1) & (~bad_sqrt) & (~conv) & (~bad)
                  & (adv < maxits))
        x = jnp.where(do_adv, x + (q / safe(dd)) * pt_new, x)
        q_next = -(gamma_m1 / safe(dd)) * q
        if trace:
            alpha_rec = 1.0 / safe(dd)
            beta_rec = (q_next / safe(q)) ** 2
            buf = jnp.where(
                do_adv,
                telemetry.ring_record(buf, adv, q_next * q_next,
                                      alpha_rec, beta_rec, dd),
                buf)
        conv = conv | (do_adv & (q_next * q_next < tol2))
        bad = bad | bad_sqrt
        adv = adv + do_adv.astype(jnp.int32)
        q = jnp.where(do_adv, q_next, q)
        dprev = jnp.where(do_adv, dd, dprev)
        ptilde = jnp.where(do_adv, pt_new, ptilde)
        if progress:
            telemetry.heartbeat(adv, q * q, progress, leader=leader,
                                what=what)
        # -- build z_{j+1} (the iteration's ONE SpMV) -----------------
        zj = Z[2 * l + 1]
        Az = ops.spmv(zj, j)
        if fault is not None:
            pass  # spmv fault applied inside ops.spmv via k=j
        sig_j = sigma[jnp.clip(j, 0, l - 1)]
        z_start = Az.astype(sdt) - sig_j * zj.astype(sdt)
        z_main = (Az.astype(sdt) - delta_m1 * zj.astype(sdt)
                  - gammas[l + 1] * Z[2 * l].astype(sdt)) / safe(gamma_m1)
        znew = ops.store(jnp.where(j < l, z_start, z_main))
        # -- initiate the fused window reduction (ONE allreduce) ------
        Zr = jnp.roll(Z, -1, axis=0).at[2 * l + 1].set(znew)
        y_new = ops.windots(Zr, znew)
        zzq = jnp.roll(zzq, -1, axis=0).at[l - 1].set(y_new)
        # -- roll the g/T/V windows (only when column m materialized) --
        gb_new = jnp.roll(gb, -1, axis=0).at[2 * l].set(
            jnp.stack(newcol))
        gb = jnp.where(have_m, gb_new, gb)
        V_new = jnp.roll(V, -1, axis=0).at[2 * l - 1].set(vm)
        V = jnp.where(have_m, V_new, V)
        roll_T = have_m & (m >= 1)
        gammas = jnp.where(roll_T,
                           jnp.roll(gammas, -1).at[l + 1].set(gamma_m1),
                           gammas)
        deltas = jnp.where(roll_T,
                           jnp.roll(deltas, -1).at[l].set(delta_m1),
                           deltas)
        out = (j + 1, adv, x, q, dprev, ptilde, Zr, V, zzq, gb, gammas,
               deltas, conv, bad)
        if trace:
            out = out + (buf,)
        return out

    return body


def pl_init(l: int, n: int, x0, eta, dtype, sdt, z0):
    """Initial p(l) carry (minus the ring tail): see make_pl_step."""
    W = 2 * l + 2
    Z = jnp.zeros((W, n), dtype).at[2 * l + 1].set(z0)
    V = jnp.zeros((2 * l, n), dtype)
    zzq = jnp.zeros((l, W), sdt).at[l - 1, 2 * l + 1].set(1.0)
    gb = jnp.zeros((2 * l + 1, 2 * l + 1), sdt)
    gammas = jnp.zeros((l + 2,), sdt)
    deltas = jnp.zeros((l + 1,), sdt)
    return (jnp.int32(0), jnp.int32(0), x0.astype(sdt), eta,
            jnp.asarray(1.0, sdt), jnp.zeros((n,), sdt), Z, V, zzq, gb,
            gammas, deltas)


# -- the recurrence loops, tier-agnostic ----------------------------------

def run_sstep_loop(ops: TierOps, s: int, basis: str, lam, b, x0, r,
                   gamma, res_tol, maxits, unbounded: bool, fault=None,
                   trace: int = 0, progress: int = 0, health=None,
                   what: str = "cg-sstep", leader=None, bnrm2=None,
                   k_offset=None, p=None, state_io: bool = False):
    """The s-step outer loop, shared verbatim by every tier: the tier
    contributes ``ops`` (its SpMV/halo machinery, its global dot, its
    ONE stacked reduction); the recurrence contributes everything else.
    Returns ``(x, k, gamma_f, bad, done, extras)`` with extras =
    (ring?, audit?) in the jax_cg tail order.

    ``p``/``state_io`` (the survivability tier): at a BLOCK BOUNDARY
    the s-step state is exactly classic-shaped -- the basis and Gram
    products are rebuilt from (r, p) at every block start -- so a
    checkpoint carry is just ``(r, p, gamma)``.  A non-None ``p``
    re-enters mid-trajectory (``r``/``gamma`` then come from the
    snapshot too; ``p = r`` is the fresh-start value), and
    ``state_io`` appends the final ``(r, p, gamma)`` to the return."""
    sdt = ops.sdt
    tol2 = res_tol * res_tol
    if health is not None:
        from acg_tpu import health as _health
    body, ntails = make_sstep_block(
        ops, s, basis, lam, res_tol, maxits, fault=fault, trace=trace,
        progress=progress, health=health, what=what, leader=leader,
        k_offset=k_offset)
    body.health_ctx.update({"b": b, "bnrm2": bnrm2})
    init = (x0, r, r if p is None else p, gamma, jnp.int32(0),
            jnp.asarray(False))
    if health is not None:
        init = init + (_health.audit_init(sdt, health),)
    if trace:
        from acg_tpu import telemetry
        init = init + (telemetry.ring_init(trace, sdt),)

    def cond(state):
        g, k, bad = state[3], state[4], state[5]
        go = (k < maxits) & (~bad)
        if not unbounded:
            go = go & (g >= tol2)
        return go

    state = jax.lax.while_loop(cond, lambda st: body(st), init)
    gamma_f, k, bad = state[3], state[4], state[5]
    done = (~bad) if unbounded else (gamma_f < tol2)
    extras = ()
    if trace:
        extras = extras + (state[-1],)
    if health is not None:
        extras = extras + (state[-2] if trace else state[-1],)
    if state_io:
        return state[0], k, gamma_f, bad, done, extras, \
            (state[1], state[2], gamma_f)
    return state[0], k, gamma_f, bad, done, extras


def run_pl_loop(ops: TierOps, l: int, lam, x0, z0, eta, eta2, res_tol,
                maxits, unbounded: bool, fault=None, trace: int = 0,
                progress: int = 0, what: str = "cg-pl", leader=None,
                carry=None, state_io: bool = False):
    """The p(l) iteration loop, shared verbatim by every tier.  Returns
    ``(x, adv, q, conv, bad, extras)``.

    ``carry``/``state_io`` (the survivability tier): the deep-pipeline
    recurrence has no classic-shaped boundary -- its whole working set
    (z-window ``Z``/``V``, Gram column ``zzq``/``gb``, scalar histories
    ``gammas``/``deltas``, pipeline counters ``j``/``adv``) must
    round-trip through a snapshot.  ``carry`` re-enters from the full
    11-leaf state with ABSOLUTE ``j``/``adv`` (``maxits`` must then be
    absolute too), ``state_io`` appends that state to the return."""
    sdt = ops.sdt
    tol2 = res_tol * res_tol
    n = x0.shape[0]
    sigma = pl_shifts(l, lam, sdt)
    body = make_pl_step(ops, l, sigma, res_tol, maxits, fault=fault,
                        trace=trace, progress=progress, what=what,
                        leader=leader)
    if carry is None:
        init = pl_init(l, n, x0, eta, x0.dtype, sdt, z0)
        init = init + (eta2 < tol2, jnp.asarray(False))
    else:
        (q, dprev, ptilde, Z, V, zzq, gb, gammas, deltas, j, adv) = carry
        init = (j.astype(jnp.int32), adv.astype(jnp.int32),
                x0.astype(sdt), q, dprev, ptilde, Z, V, zzq, gb,
                gammas, deltas, jnp.asarray(False), jnp.asarray(False))
    if trace:
        from acg_tpu import telemetry
        init = init + (telemetry.ring_init(trace, sdt),)
    jcap = maxits + jnp.int32(2 * l + 2)

    def cond(state):
        j, adv, conv, bad = state[0], state[1], state[12], state[13]
        go = (~bad) & (adv < maxits) & (j < jcap)
        if not unbounded:
            go = go & (~conv)
        return go

    state = jax.lax.while_loop(cond, lambda st: body(st), init)
    extras = (state[-1],) if trace else ()
    out = (state[2], state[1], state[3], state[12], state[13], extras)
    if state_io:
        out = out + ((state[3], state[4], state[5], state[6], state[7],
                      state[8], state[9], state[10], state[11],
                      state[0], state[1]),)
    return out


# -- single-device programs ------------------------------------------------

@functools.partial(jax.jit,
                   static_argnames=("s", "basis", "unbounded", "kernels",
                                    "fault", "trace", "progress",
                                    "health", "state_io"))
def _cg_sstep_program(A, b, x0, res_atol, res_rtol, lam, maxits,
                      s: int, basis: str, unbounded: bool,
                      kernels: str = "xla", fault=None, trace: int = 0,
                      progress: int = 0, health=None,
                      state_io: bool = False, carry=None, k_offset=None):
    """Whole s-step-CG solve as one XLA program (single-device tier;
    the sharded-DIA tier rides through the callable ``kernels`` SpMV
    exactly like _cg_program).

    ``carry``/``state_io``/``k_offset`` are the checkpoint hooks: a
    carry re-enters from a block-boundary ``(r, p, gamma)`` snapshot
    (the setup SpMV is skipped; ``r0nrm2`` from the carried gamma is
    only meaningful on the first chunk, which never carries), state_io
    appends the final ``(r, p, gamma)`` to the return, and k_offset
    keeps the health audit cadence in the ABSOLUTE iteration frame."""
    from acg_tpu.solvers.jax_cg import CGResult, _scalar_setup
    dtype = b.dtype
    dot, sdt = _scalar_setup(dtype, False)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    ops = single_ops(A, kernels, dot, sdt, store, fault=fault)
    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    if carry is not None:
        r, p, gamma = carry
    else:
        r = b - ops.spmv(x0, None)
        p = None
        gamma = dot(r, r)
    r0nrm2 = jnp.sqrt(gamma)
    res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)
    lam = (jnp.asarray(lam[0], sdt), jnp.asarray(lam[1], sdt))
    out = run_sstep_loop(
        ops, s, basis, lam, b, x0, r, gamma, res_tol, maxits,
        unbounded, fault=fault, trace=trace, progress=progress,
        health=health, bnrm2=bnrm2, k_offset=k_offset, p=p,
        state_io=state_io)
    x, k, gamma_f, bad, done, extras = out[:6]
    breakdown = bad & ~done
    res = CGResult(x=x, niterations=k,
                   rnrm2=jnp.sqrt(jnp.maximum(gamma_f, 0.0)),
                   r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                   dxnrm2=inf, converged=done, breakdown=breakdown)
    tail = extras + (out[6],) if state_io else extras
    return (res,) + tail if tail else res


@functools.partial(jax.jit,
                   static_argnames=("l", "unbounded", "kernels", "fault",
                                    "trace", "progress", "state_io"))
def _cg_pl_program(A, b, x0, res_atol, res_rtol, lam, maxits, l: int,
                   unbounded: bool, kernels: str = "xla", fault=None,
                   trace: int = 0, progress: int = 0,
                   state_io: bool = False, carry=None, k_offset=None):
    """Whole p(l)-CG solve as one XLA program (single-device tier).

    The checkpoint hooks carry the FULL deep-pipeline working set (see
    run_pl_loop): ``carry`` re-enters from a snapshot whose ``j``/
    ``adv`` counters are ABSOLUTE -- the caller must then pass an
    absolute ``maxits`` (consumed + chunk) and read ``niterations`` as
    an absolute count.  ``k_offset`` is accepted for signature parity
    with the s-step program and ignored (the pipeline's own ``j``
    counter is already absolute)."""
    from acg_tpu.solvers.jax_cg import CGResult, _scalar_setup
    dtype = b.dtype
    dot, sdt = _scalar_setup(dtype, False)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    ops = single_ops(A, kernels, dot, sdt, store, fault=fault)
    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    if carry is not None:
        # mid-pipeline re-entry: the recurrence residual lives in the
        # carried q; no setup SpMV, and r0nrm2 is only cosmetic here
        # (later chunks run with rtol=0 against the first chunk's
        # absolute target)
        eta = eta2 = z0 = None
        r0nrm2 = jnp.abs(carry[0])
    else:
        r0 = b - ops.spmv(x0, None)
        eta2 = dot(r0, r0)
        eta = jnp.sqrt(eta2)
        r0nrm2 = eta
    res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)
    lam = (jnp.asarray(lam[0], sdt), jnp.asarray(lam[1], sdt))
    if carry is None:
        z0 = store(r0 / jnp.where(eta == 0, 1.0, eta))
    out = run_pl_loop(
        ops, l, lam, x0, z0, eta, eta2, res_tol, maxits, unbounded,
        fault=fault, trace=trace, progress=progress, carry=carry,
        state_io=state_io)
    x, adv, q, conv, bad, extras = out[:6]
    done = (~bad) if unbounded else conv
    breakdown = bad & ~done
    res = CGResult(x=x.astype(dtype), niterations=adv,
                   rnrm2=jnp.abs(q), r0nrm2=r0nrm2, bnrm2=bnrm2,
                   x0nrm2=x0nrm2, dxnrm2=inf, converged=done,
                   breakdown=breakdown)
    tail = extras + (out[6],) if state_io else extras
    return (res,) + tail if tail else res


@functools.partial(jax.jit, static_argnames=("kernels", "iters"))
def _lmax_program(A, v0, kernels: str = "xla", iters: int = POWER_ITERS):
    """Power-iteration lambda_max through the tier's own SpMV -- the
    communication-avoiding recurrences' spectral estimate (one compile
    at setup; the dist tier reuses DistCGSolver._power_lmax)."""
    from acg_tpu.solvers.jax_cg import _spmv_fn
    spmv_ = _spmv_fn(kernels)
    sdt = acc_dtype(v0.dtype)

    def ldot(a, c):
        return jnp.dot(a, c, preferred_element_type=sdt)

    def it(_, v):
        w = spmv_(A, v)
        return (w.astype(sdt)
                / jnp.sqrt(ldot(w, w))).astype(v.dtype)

    v = jax.lax.fori_loop(0, iters, it, v0)
    w = spmv_(A, v)
    return ldot(v, w) / ldot(v, v)


def estimate_lam(A, n: int, dtype, kernels: str = "xla"):
    """(lmin, lmax) host floats for the basis/shift interval: power
    iteration with spectral headroom, lmin = 0 (SPD; the Chebyshev
    interval does not need the low end resolved)."""
    rng = np.random.default_rng(0)
    v0 = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    lmax = float(_lmax_program(A, v0, kernels=kernels)) * LAM_SAFETY
    return (0.0, lmax)


# -- builder emission of the existing recurrences (byte-identity) ----------
#
# The classic and Ghysels-Vanroose recurrences as BUILDER bodies over
# TierOps -- the same carry layout / update / reduction schedule the
# hand-built programs in solvers/jax_cg.py and parallel/dist.py trace.
# tests/test_hlo_structure.py pins the builder emission byte-identical
# (StableHLO) to the hand-built programs on both tiers: the proof that
# the builder is a faithful home for the recurrence matrix, and that
# flipping the dispatch (or landing a new cross-cutting feature in the
# builder instead of per-copy) is a no-op for current users.

def classic_recurrence(ops: TierOps):
    """Classic CG as a builder body: carry ``(x, r, p, gamma)``, two
    global dots per iteration ((p, t) and (r, r))."""
    def body(k, state):
        x, r, p, gamma = state
        t = ops.spmv(p, k)
        pdott = ops.dot(p, t)
        alpha = gamma / pdott
        x = ops.store(x + alpha * p)
        r = ops.store(r - alpha * t)
        gamma_next = ops.dot(r, r)
        beta = gamma_next / gamma
        p_next = ops.store(r + beta * p)
        return (x, r, p_next, gamma_next)
    return body


def pipelined_recurrence(ops: TierOps, dot2):
    """Ghysels-Vanroose pipelined CG as a builder body: carry
    ``(x, r, w, p, t, z, gamma_prev, alpha_prev)``, ONE fused 2-scalar
    reduction per iteration (``dot2`` -- two plain dots on a single
    device, pdot2_fused on the mesh)."""
    def body(k, state):
        x, r, w, p, t, z, gamma_prev, alpha_prev = state
        gamma, delta = dot2(r, r, w, r)
        q = ops.spmv(w, k)
        beta = gamma / gamma_prev
        denom = delta - beta * (gamma / alpha_prev)
        alpha = gamma / denom
        z = ops.store(q + beta * z)
        t = ops.store(w + beta * t)
        p = ops.store(r + beta * p)
        x = ops.store(x + alpha * p)
        r = ops.store(r - alpha * t)
        w = ops.store(w - alpha * z)
        return (x, r, w, p, t, z, gamma, alpha)
    return body


@functools.partial(jax.jit,
                   static_argnames=("unbounded", "needs_diff",
                                    "pipelined", "kernels"))
def _builder_cg_program(A, b, x0, res_atol, res_rtol, diff_atol,
                        diff_rtol, maxits, unbounded: bool,
                        needs_diff: bool, pipelined: bool = False,
                        kernels: str = "xla"):
    """The builder's single-device emission of classic/GV-pipelined CG
    (base configuration): byte-identity with jax_cg._cg_program /
    _cg_pipelined_program is pinned in tests/test_hlo_structure.py."""
    from acg_tpu.solvers.jax_cg import CGResult, _iterate, _scalar_setup
    assert not needs_diff
    dtype = b.dtype
    dot, sdt = _scalar_setup(dtype, False)
    store = (lambda v: v.astype(dtype)) if sdt != dtype else (lambda v: v)
    ops = single_ops(A, kernels, dot, sdt, store)

    def dot2(a1, c1, a2, c2):
        return dot(a1, c1), dot(a2, c2)

    bnrm2 = jnp.sqrt(dot(b, b))
    x0nrm2 = jnp.sqrt(dot(x0, x0))
    if pipelined:
        r = b - ops.spmv(x0, None)
        w = ops.spmv(r, None)
        r0nrm2 = jnp.sqrt(dot(r, r))
    else:
        r = b - ops.spmv(x0, None)
        p = r
        gamma = dot(r, r)
        r0nrm2 = jnp.sqrt(gamma)
    res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
    diff_tol = jnp.maximum(diff_atol, diff_rtol * x0nrm2)
    inf = jnp.asarray(jnp.inf, sdt)
    if pipelined:
        zeros = jnp.zeros_like(b)
        body = pipelined_recurrence(ops, dot2)
        init_state = (x0, r, w, zeros, zeros, zeros, inf, inf)
        init_gamma = r0nrm2 * r0nrm2
        k, state, done = _iterate(
            body, init_state, lambda s: s[6], maxits, res_tol,
            diff_tol, lambda s: inf, unbounded,
            init_gamma=init_gamma, bad_of=None)
        x, r = state[0], state[1]
        dxsqr = inf
        breakdown = jnp.asarray(False)
        rnrm2 = jnp.sqrt(dot(r, r))
        done = jnp.logical_or(done, rnrm2 <= res_tol)
        breakdown = breakdown & ~done
        return CGResult(x=x, niterations=k, rnrm2=rnrm2,
                        r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                        dxnrm2=jnp.sqrt(dxsqr), converged=done,
                        breakdown=breakdown)
    body = classic_recurrence(ops)
    init_state = (x0, r, p, gamma)
    k, state, done = _iterate(
        body, init_state, lambda s: s[3], maxits, res_tol, diff_tol,
        lambda s: inf, unbounded, bad_of=None)
    x, r, p, gamma = state[:4]
    rnrm2sqr = gamma
    dxsqr = inf
    breakdown = jnp.asarray(False)
    breakdown = breakdown & ~done
    return CGResult(x=x, niterations=k, rnrm2=jnp.sqrt(rnrm2sqr),
                    r0nrm2=r0nrm2, bnrm2=bnrm2, x0nrm2=x0nrm2,
                    dxnrm2=jnp.sqrt(dxsqr), converged=done,
                    breakdown=breakdown)


def build_dist_program(solver):
    """The builder's dist-tier emission of classic/GV-pipelined CG
    (base configuration), composed with the solver's OWN machinery
    (halo'd SpMV, psum, fused reductions, mesh specs): byte-identity
    with DistCGSolver._compile()'s hand-built program is pinned in
    tests/test_hlo_structure.py.

    ``kernels='fused'`` swaps in the interior|border OVERLAPPED SpMV
    (``make_dist_spmv_overlapped``: halo exchange issued first,
    interior rows computed while it is in flight, border rows finished
    after) -- this IS the dispatched program of the distributed fused
    tier, so the overlapped SpMV lands once here and every recurrence
    the builder emits inherits it.  Everything else (carry layout,
    reduction ladder, shard specs) is identical, keeping the non-fused
    emission byte-stable."""
    import jax.numpy as _jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from acg_tpu._platform import shard_map as _shard_map
    from acg_tpu.parallel.dist import (make_dist_spmv,
                                       make_dist_spmv_overlapped)
    from acg_tpu.parallel.mesh import PARTS_AXIS
    from acg_tpu.parallel.reductions import make_pdot, make_pdotk
    from acg_tpu.solvers.jax_cg import _iterate

    prob = solver.problem
    pipelined = solver.pipelined
    axis = PARTS_AXIS
    if isinstance(solver.kernels, str) and \
            solver.kernels.startswith("fused"):
        dist_spmv = make_dist_spmv_overlapped(prob, solver.comm,
                                              solver._interpret)
    else:
        dist_spmv = make_dist_spmv(prob, solver.comm, solver._interpret,
                                   kernels=solver.kernels, fault=None)
    single_shard = solver.mesh.devices.size == 1

    def psum(v):
        return v if single_shard else lax.psum(v, axis)

    def shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                   maxits, unbounded=False, needs_diff=False):
        la, ga = (jax.tree.map(lambda a: a[0], t) for t in (la, ga))
        sidx, gsrc, gval, scnt, rcnt, b, x0 = (
            a[0] for a in (sidx, gsrc, gval, scnt, rcnt, b, x0))
        maxits = maxits.astype(jnp.int32)
        dtype = b.dtype
        sdt = acc_dtype(dtype)
        store = ((lambda v: v.astype(dtype)) if sdt != dtype
                 else (lambda v: v))
        res_atol, res_rtol, diff_atol, diff_rtol = tols

        def spmv(x, k=None):
            return dist_spmv(x, la, ga, sidx, gsrc, gval, scnt, rcnt,
                             k=k, pidx=None)

        def ldot(a, c):
            return jnp.dot(a, c, preferred_element_type=sdt)

        pdot = make_pdot(psum, ldot, sdt, False)
        _pdotk = make_pdotk(psum, ldot, sdt, False)

        def pdot2_fused(a1, c1, a2, c2):
            return _pdotk((a1, c1), (a2, c2))

        ops = TierOps(spmv=spmv, dot=pdot, psum_stack=psum,
                      store=store, sdt=sdt)
        bnrm2 = jnp.sqrt(pdot(b, b))
        x0nrm2 = jnp.sqrt(pdot(x0, x0))
        r = b - spmv(x0)
        if not pipelined:
            gamma = pdot(r, r)
            r0nrm2 = jnp.sqrt(gamma)
        else:
            gamma = pdot(r, r)
            r0nrm2 = jnp.sqrt(gamma)
        res_tol = jnp.maximum(res_atol, res_rtol * r0nrm2)
        diff_tol = jnp.maximum(diff_atol, diff_rtol * x0nrm2)
        inf = jnp.asarray(jnp.inf, sdt)
        if not pipelined:
            body = classic_recurrence(ops)
            init_state = (x0, r, r, gamma)
            k, state, done = _iterate(
                body, init_state, lambda s: s[3], maxits, res_tol,
                diff_tol, lambda s: inf, unbounded, bad_of=None)
            x, r_fin, gamma_fin = state[0], state[1], state[3]
            dxsqr = inf
            breakdown = jnp.asarray(False)
            rnrm2 = jnp.sqrt(gamma_fin)
        else:
            w = spmv(r)
            zeros = jnp.zeros_like(b)
            body = pipelined_recurrence(ops, pdot2_fused)
            init_state = (x0, r, w, zeros, zeros, zeros, inf, inf)
            init_gamma = gamma
            k, state, done = _iterate(
                body, init_state, lambda s: s[6], maxits, res_tol,
                diff_tol, lambda s: inf, unbounded,
                init_gamma=init_gamma, bad_of=None)
            x, r_fin = state[0], state[1]
            dxsqr = inf
            breakdown = jnp.asarray(False)
            rnrm2 = jnp.sqrt(pdot(r_fin, r_fin))
            done = jnp.logical_or(done, rnrm2 <= res_tol)
        breakdown = breakdown & ~done
        dxnrm2 = jnp.sqrt(dxsqr)
        out = (x[None], k, rnrm2, r0nrm2, bnrm2, x0nrm2, dxnrm2,
               done, breakdown)
        return out

    if single_shard and not prob.halo.has_ghosts:
        @functools.partial(jax.jit,
                           static_argnames=("unbounded", "needs_diff",
                                            "detect"))
        def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                    tols, maxits, unbounded, needs_diff,
                    detect=False, mstate=None, carry=None,
                    k_offset=None):
            return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                              b, x0, tols, maxits,
                              unbounded=unbounded,
                              needs_diff=needs_diff)

        return program

    pspec = P(PARTS_AXIS)
    rspec = P()
    in_specs = (pspec, pspec,
                pspec, pspec, pspec, pspec, pspec,
                pspec, pspec,
                rspec, rspec)
    out_specs = (pspec,) + (rspec,) * 8

    @functools.partial(jax.jit,
                       static_argnames=("unbounded", "needs_diff",
                                        "detect"))
    def program(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0,
                tols, maxits, unbounded, needs_diff, detect=False,
                mstate=None, carry=None, k_offset=None):
        extra = ()
        specs = in_specs

        def smb(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols,
                maxits, *rest):
            return shard_body(la, ga, sidx, gsrc, gval, scnt, rcnt,
                              b, x0, tols, maxits,
                              unbounded=unbounded,
                              needs_diff=needs_diff)

        return _shard_map(
            smb,
            mesh=solver.mesh, in_specs=specs, out_specs=out_specs,
        )(la, ga, sidx, gsrc, gval, scnt, rcnt, b, x0, tols, maxits,
          *extra)

    return program


# -- measured segment probes (the communication observatory) ---------------
#
# SpMV-only / halo-only / reduction-only probe programs for
# acg_tpu.commbench.segment_decomposition, built from the SAME machinery
# the builder's dispatched emission composes -- the overlapped-or-not
# dist SpMV selection of build_dist_program, the make_pdot/make_pdotk
# reduction ladders, _spmv_fn on the single-device tier -- so a measured
# segment times the ops a real iteration runs, not a replay stand-in.
# Each probe chains `reps` rounds inside ONE dispatched fori_loop (data
# dependence between rounds, so XLA can neither elide nor batch them)
# and clamps the SpMV chain (repeated A.v grows as lambda_max^reps;
# the clamp keeps values finite and out of denormal range without a
# norm, which would smuggle a reduction into the SpMV segment).
# Building or running probes never mutates solver state: the dispatched
# solve programs stay byte-identical (pinned in tests/test_commbench.py).

def _probe_reduction_calls(pipelined: bool) -> tuple[str, float]:
    """(probe flavour, calls/iteration) of the reduction segment:
    classic runs TWO single-scalar pdots per iteration, the pipelined
    recurrence ONE fused 2-scalar pdotk -- the probe reproduces the
    exact ladder so the segment prices what the mesh actually moves."""
    return ("pdotk2", 1.0) if pipelined else ("pdot", 2.0)


def build_single_segment_probes(solver, b, reps: int) -> list[tuple]:
    """``[(name, runner, calls_per_iteration), ...]`` for the
    single-device tier: SpMV-only and reduction-only (no halo on one
    chip)."""
    from acg_tpu.solvers.jax_cg import _scalar_setup, _spmv_fn

    if getattr(solver, "algo", None) is not None:
        raise ValueError("segment probes cover the classic/pipelined "
                         "recurrences")
    spmv_ = _spmv_fn(solver.kernels)
    dtype = solver._solve_dtype()
    v0 = jnp.asarray(np.ones(int(np.asarray(b).shape[0])), dtype)
    dot, sdt = _scalar_setup(dtype, solver.precise_dots)
    A = solver._A_program
    flavour, red_calls = _probe_reduction_calls(solver.pipelined)

    @functools.partial(jax.jit, static_argnames="reps")
    def spmv_prog(A, v, reps):
        def rnd(_, v):
            return jnp.clip(spmv_(A, v), -1e3, 1e3)
        return jax.lax.fori_loop(0, reps, rnd, v)

    @functools.partial(jax.jit, static_argnames="reps")
    def red_prog(v, reps):
        tiny = jnp.asarray(1e-30, sdt)

        def rnd(_, v):
            if flavour == "pdotk2":
                g = dot(v, v) + dot(v, v)
            else:
                g = dot(v, v)
            return v + (g * tiny).astype(v.dtype)
        return jax.lax.fori_loop(0, reps, rnd, v)

    r = int(reps)
    return [("spmv", lambda: spmv_prog(A, v0, r), 1.0),
            ("reduction", lambda: red_prog(v0, r), red_calls)]


def build_dist_segment_probes(solver, b_global, reps: int) -> list[tuple]:
    """``[(name, runner, calls_per_iteration), ...]`` for the dist
    tier: the halo'd SpMV (overlapped when ``kernels='fused'`` -- the
    same selection :func:`build_dist_program` dispatches), the halo
    exchange alone (xla all_to_all or one-sided DMA, per the solver's
    armed transport), and the psum reduction ladder."""
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from acg_tpu._platform import shard_map as _shard_map
    from acg_tpu.parallel.dist import (make_dist_spmv,
                                       make_dist_spmv_overlapped)
    from acg_tpu.parallel.halo import halo_exchange
    from acg_tpu.parallel.halo_dma import halo_exchange_dma
    from acg_tpu.parallel.mesh import PARTS_AXIS
    from acg_tpu.parallel.reductions import make_pdot, make_pdotk

    if getattr(solver, "algo", None) is not None:
        raise ValueError("segment probes cover the classic/pipelined "
                         "recurrences")
    prob = solver.problem
    axis = PARTS_AXIS
    if isinstance(solver.kernels, str) and \
            solver.kernels.startswith("fused"):
        dist_spmv = make_dist_spmv_overlapped(prob, solver.comm,
                                              solver._interpret)
    else:
        dist_spmv = make_dist_spmv(prob, solver.comm, solver._interpret,
                                   kernels=solver.kernels, fault=None)
    single_shard = solver.mesh.devices.size == 1
    comm = solver.comm
    interpret = solver._interpret
    precise = solver.precise_dots
    flavour, red_calls = _probe_reduction_calls(solver.pipelined)

    def psum(v):
        return v if single_shard else lax.psum(v, axis)

    dev = solver.device_args(np.asarray(b_global), None)
    b, _x0, la, ga, sidx, gsrc, gval, scnt, rcnt = dev

    def make_probe(round_of):
        """One probe program over the solver's own stacked device args:
        shard body unstacks exactly like the emission's shard_body,
        builds the round from the tier ops, and chains ``reps``
        rounds."""
        def shard(la, ga, sidx, gsrc, gval, scnt, rcnt, b):
            la, ga = (jax.tree.map(lambda a: a[0], t)
                      for t in (la, ga))
            sidx, gsrc, gval, scnt, rcnt, b = (
                a[0] for a in (sidx, gsrc, gval, scnt, rcnt, b))
            sdt = acc_dtype(b.dtype)

            def spmv(x):
                return dist_spmv(x, la, ga, sidx, gsrc, gval, scnt,
                                 rcnt, k=None, pidx=None)

            def halo(x):
                if comm == "dma":
                    return halo_exchange_dma(x, sidx, gsrc, gval,
                                             scnt, rcnt, axis,
                                             interpret=interpret)
                return halo_exchange(x, sidx, gsrc, axis)

            def ldot(a, c):
                return jnp.dot(a, c, preferred_element_type=sdt)

            pdot = make_pdot(psum, ldot, sdt, precise)
            pdotk = make_pdotk(psum, ldot, sdt, precise)
            rnd = round_of(spmv, halo, pdot, pdotk, sdt)
            v = jax.lax.fori_loop(0, reps, lambda _, v: rnd(v), b)
            return v[None]

        if single_shard and not prob.halo.has_ghosts:
            prog = jax.jit(lambda *a: shard(*a))
        else:
            pspec = P(axis)
            prog = jax.jit(_shard_map(
                shard, mesh=solver.mesh, in_specs=(pspec,) * 8,
                out_specs=pspec))
        return lambda: prog(la, ga, sidx, gsrc, gval, scnt, rcnt, b)

    def spmv_round(spmv, halo, pdot, pdotk, sdt):
        return lambda v: jnp.clip(spmv(v), -1e3, 1e3)

    def halo_round(spmv, halo, pdot, pdotk, sdt):
        def rnd(v):
            g = halo(v)
            return v.at[0].add((g[0]
                                * jnp.asarray(1e-30, g.dtype)))
        return rnd

    def red_round(spmv, halo, pdot, pdotk, sdt):
        tiny = jnp.asarray(1e-30, sdt)

        def rnd(v):
            if flavour == "pdotk2":
                g1, g2 = pdotk((v, v), (v, v))
                g = g1 + g2
            else:
                g = pdot(v, v)
            return v + (g * tiny).astype(v.dtype)
        return rnd

    probes = [("spmv", make_probe(spmv_round), 1.0)]
    if prob.halo.has_ghosts:
        probes.append(("halo", make_probe(halo_round), 1.0))
    probes.append(("reduction", make_probe(red_round), red_calls))
    return probes


# -- the p(l) restart driver (shared by every tier) ------------------------

def pl_restart_policy():
    """The default recovery policy a p(l) solver arms when the caller
    provided none: sqrt breakdown is an EXPECTED algorithmic event of
    deep pipelines (the z-Gram loses positivity as convergence
    proceeds), and the literature's remedy -- restart from the current
    iterate -- is exactly the existing recovery ladder's
    restart-from-true-residual rung.  Budgeted generously; restarts
    keep the original absolute tolerance target (the ladder's
    convention), and the measured total iteration count stays within
    ~1.8x classic on the aniso family at rtol 1e-8."""
    from acg_tpu.solvers.resilience import RecoveryPolicy
    return RecoveryPolicy(max_restarts=PL_RESTART_BUDGET,
                          fallback_comm=False, fallback_host=False)


# -- host oracles ----------------------------------------------------------

def host_sstep_cg(A, b, x0=None, rtol=1e-8, maxits=1000, s=4,
                  basis=None, lam=None):
    """Eager f64 s-step CG oracle (scipy matvec) -- the trajectory-
    parity reference of tests/test_recurrence.py."""
    import scipy.sparse as sp
    A = sp.csr_matrix(A)
    n = A.shape[0]
    b = np.asarray(b, np.float64)
    x = np.zeros(n) if x0 is None else np.asarray(x0, np.float64).copy()
    basis = basis or ("chebyshev" if s >= 4 else "monomial")
    if lam is None and basis == "chebyshev":
        v = np.random.default_rng(0).standard_normal(n)
        for _ in range(POWER_ITERS):
            v = A @ v
            v /= np.linalg.norm(v)
        lam = (0.0, float(v @ (A @ v)) * LAM_SAFETY)
    lam = lam or (0.0, 0.0)
    r = b - A @ x
    p = r.copy()
    gamma = float(r @ r)
    r0 = np.sqrt(gamma)
    tol2 = (rtol * r0) ** 2
    w = 2 * s + 1
    Bm = np.asarray(sstep_combined_bmat(s, basis, lam, jnp.float64))
    traj = []
    k = 0
    while k < maxits and gamma >= tol2:
        rows = [p]
        if basis == "monomial":
            for _ in range(s):
                rows.append(A @ rows[-1])
        else:
            d = (lam[0] + lam[1]) / 2.0
            c = (lam[1] - lam[0]) / 2.0
            for j in range(s):
                wv = A @ rows[-1] - d * rows[-1]
                rows.append(wv / c if j == 0 else 2 * wv / c - rows[-2])
        rrows = [r]
        if basis == "monomial":
            for _ in range(s - 1):
                rrows.append(A @ rrows[-1])
        else:
            d = (lam[0] + lam[1]) / 2.0
            c = (lam[1] - lam[0]) / 2.0
            for j in range(s - 1):
                wv = A @ rrows[-1] - d * rrows[-1]
                rrows.append(wv / c if j == 0
                             else 2 * wv / c - rrows[-2])
        V = np.stack(rows + rrows)
        G = V @ V.T
        pc = np.zeros(w); pc[0] = 1.0
        rc = np.zeros(w); rc[s + 1] = 1.0
        xc = np.zeros(w)
        gamma = float(G[s + 1, s + 1])
        for j in range(s):
            if gamma < tol2 or k >= maxits:
                break
            wc = Bm @ pc
            denom = float(pc @ (G @ wc))
            alpha = gamma / denom
            xc += alpha * pc
            rc = rc - alpha * wc
            gamma_next = float(rc @ (G @ rc))
            beta = gamma_next / gamma
            pc = rc + beta * pc
            traj.append((gamma_next, alpha, beta, denom))
            gamma = gamma_next
            k += 1
        x = x + xc @ V
        r = rc @ V
        p = pc @ V
    return x, k, np.sqrt(max(gamma, 0.0)) / r0, traj
