"""Offline row-partitioning tool (the reference's ``mtxpartition``).

Reads a symmetric matrix, computes a balanced low-edge-cut row partition
(METIS if present, built-in otherwise), and writes the partition vector as
a ``vector array integer general`` Matrix Market file -- the same shape the
reference writes (``mtxpartition/mtxpartition.c:721``) and the driver's
``--partition`` flag consumes (``cuda/acg-cuda.c:1542-1677``).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="acg-tpu-mtxpartition",
        description="Partition the rows of a symmetric sparse matrix.")
    p.add_argument("A", help="matrix in Matrix Market format")
    p.add_argument("--parts", type=int, default=2, metavar="N",
                   help="number of parts (default: 2)")
    p.add_argument("--seed", type=int, default=0, help="random seed")
    p.add_argument("--binary", action="store_true",
                   help="matrix file is in binary Matrix Market format")
    p.add_argument("--output-binary", action="store_true",
                   help="write the partition vector in binary format")
    p.add_argument("--use-metis", default="auto",
                   choices=["auto", "never", "require"],
                   help="METIS usage policy (default: auto-detect)")
    p.add_argument("--method", default="graph", choices=["graph", "band"],
                   help="graph = edge-cut minimisation; band = contiguous "
                        "nnz-balanced row ranges (TPU DIA-friendly)")
    p.add_argument("--variant", default="kway",
                   choices=["kway", "recursive"],
                   help="METIS algorithm (METIS_PartGraphKway or "
                        "METIS_PartGraphRecursive, metis.h:39-43)")
    p.add_argument("--numfmt", default="%d", metavar="FMT",
                   help="output number format (reference flag; default "
                        "%%d)")
    from acg_tpu.tools import add_parity_flags, apply_quiet
    add_parity_flags(p, "acg-tpu-mtxpartition")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    apply_quiet(args)

    from acg_tpu.io.mtxfile import MtxFile, read_mtx, write_mtx
    from acg_tpu.matrix import SymCsrMatrix
    from acg_tpu.partition import edgecut, partition_rows

    t0 = time.perf_counter()
    mtx = read_mtx(args.A, binary=args.binary)
    A = SymCsrMatrix.from_mtx(mtx)
    csr = A.to_csr()
    if args.verbose:
        sys.stderr.write(f"read+assemble: {time.perf_counter() - t0:.6f} s\n")

    t0 = time.perf_counter()
    part = partition_rows(csr, args.parts, seed=args.seed,
                          use_metis=args.use_metis, method=args.method,
                          variant=args.variant)
    if args.verbose:
        sys.stderr.write(
            f"partition into {args.parts} parts: "
            f"{time.perf_counter() - t0:.6f} s, "
            f"edge cut {edgecut(csr, part):,}\n")

    out = MtxFile(object="vector", format="array", field="integer",
                  symmetry="general", nrows=part.size, ncols=1,
                  nnz=part.size, vals=part.astype(np.int32))
    write_mtx(sys.stdout.buffer, out, binary=args.output_binary,
              numfmt=args.numfmt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
