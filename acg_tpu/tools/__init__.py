"""Offline preprocessing tools (the reference's standalone binaries)."""

from __future__ import annotations


def add_parity_flags(parser, prog: str) -> None:
    """Register the reference CLIs' shared drop-in flags (gzip family,
    -q/--quiet, --version) on ``parser`` -- one definition for every
    tool so the compatibility surface cannot drift between them."""
    for flag in ("--gzip", "--gunzip", "--ungzip"):
        parser.add_argument(flag, action="store_true",
                            help="accepted for drop-in compatibility; "
                                 "gzip input is auto-detected")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress diagnostic output")
    parser.add_argument("--version", action="version",
                        version=f"{prog} (acg_tpu)")


def apply_quiet(args) -> None:
    """--quiet wins over --verbose (the reference tools' precedence)."""
    if getattr(args, "quiet", False):
        args.verbose = 0
