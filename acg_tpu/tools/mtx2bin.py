"""Text-to-binary Matrix Market converter (the reference's ``mtx2bin``).

Converts a text or gzipped ``.mtx`` file to the raw-binary form (same
header text; data section as consecutive rowidx/colidx/vals arrays,
``mtx2bin/mtx2bin.c:538-547``) for fast re-reading at scale -- the de facto
checkpoint of the preprocessing pipeline (SURVEY.md section 5).
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="acg-tpu-mtx2bin",
        description="Convert a Matrix Market file to binary form.")
    p.add_argument("input", help="text or gzipped .mtx file")
    p.add_argument("output", nargs="?", default=None,
                   help="output path (default: stdout)")
    p.add_argument("--expand", action="store_true",
                   help="expand symmetric one-triangle storage to full "
                        "storage and sort entries by row: the layout "
                        "required for per-controller RANGE reads "
                        "(read_mtx_row_range) at pod scale -- each "
                        "controller then reads only its rows")
    p.add_argument("--partition", metavar="FILE", default=None,
                   help="with --expand: apply a partition vector "
                        "(mtxpartition output) by symmetrically "
                        "permuting the matrix so each part's rows are "
                        "contiguous -- arbitrary METIS/graph partitions "
                        "then ride the band range-read ingest "
                        "(--distributed-read) unchanged.  Writes two "
                        "sidecars next to OUTPUT: OUTPUT.bounds.mtx "
                        "(nparts+1 part boundaries, read automatically "
                        "by --distributed-read) and OUTPUT.perm.mtx "
                        "(permuted-to-original row map, applied "
                        "automatically to solution output)")
    p.add_argument("--partition-binary", action="store_true",
                   help="the --partition file is binary")
    nb = p.add_mutually_exclusive_group()
    nb.add_argument("--one-based", action="store_true",
                    help="the --partition vector numbers parts from 1 "
                         "(Fortran/METIS one-based output); shifted to "
                         "0-based before applying")
    nb.add_argument("--zero-based", action="store_true",
                    help="the --partition vector numbers parts from 0; "
                         "only needed when its minimum part is 1 (an "
                         "empty part 0), which is otherwise ambiguous "
                         "with one-based numbering and a hard error")
    # reference-parity flags (mtx2bin/mtx2bin.c:367-387)
    dt = p.add_mutually_exclusive_group()
    dt.add_argument("--double", dest="datatype", action="store_const",
                    const="real", help="treat values as double (real)")
    dt.add_argument("--integer", dest="datatype", action="store_const",
                    const="integer", help="treat values as integers")
    from acg_tpu.tools import add_parity_flags, apply_quiet
    add_parity_flags(p, "acg-tpu-mtx2bin")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    apply_quiet(args)

    import numpy as np

    from acg_tpu.io.mtxfile import (apply_partition_rowsorted,
                                    expand_to_rowsorted_full, read_mtx,
                                    vector_mtx, write_mtx)

    if args.partition and not args.expand:
        p.error("--partition requires --expand (range reads need "
                "row-sorted full storage)")
    if args.partition and not args.output:
        p.error("--partition requires an OUTPUT path (the bounds/perm "
                "sidecars are named after it)")

    t0 = time.perf_counter()
    mtx = read_mtx(args.input)
    if args.datatype and args.datatype != mtx.field:
        # reference --double/--integer: force the value datatype.
        # Pattern matrices have implicit unit values -- materialise them
        # (flipping the field while leaving vals=None would write a
        # value-typed header with no value bytes: a malformed file)
        import dataclasses
        vdt = np.float64 if args.datatype == "real" else np.int32
        vals = (np.ones(mtx.nnz, dtype=vdt) if mtx.vals is None
                else np.asarray(mtx.vals).astype(vdt))
        mtx = dataclasses.replace(mtx, field=args.datatype, vals=vals)
    if args.verbose:
        sys.stderr.write(f"read: {time.perf_counter() - t0:.6f} s "
                         f"({mtx.nrows}x{mtx.ncols}, {mtx.nnz} nnz)\n")
    if args.expand:
        mtx = expand_to_rowsorted_full(mtx)
        if args.verbose:
            sys.stderr.write(f"expand: full storage, {mtx.nnz} nnz\n")
    if args.output and not args.partition:
        # remove stale sidecars from an earlier --partition run to the
        # same path: a leftover perm/bounds pair would silently reorder
        # solutions of the now-unpermuted matrix
        import os
        for ext in (".bounds.mtx", ".perm.mtx"):
            if os.path.exists(args.output + ext):
                os.remove(args.output + ext)
                if args.verbose:
                    sys.stderr.write(f"removed stale {args.output}{ext}\n")
    if args.partition:
        pmtx = read_mtx(args.partition, binary=args.partition_binary)
        part = np.asarray(pmtx.vals).reshape(-1).astype(np.int64)
        if args.one_based:
            if part.size and part.min() < 1:
                p.error(f"--one-based given but the partition vector "
                        f"contains part {part.min()}")
            part = part - 1
        elif part.size and part.min() == 1 and not args.zero_based:
            # ambiguous: could be a 1-based vector OR a 0-based one
            # whose part 0 happens to be empty.  Guessing silently
            # renumbered every part (round-4 advisor finding), and the
            # round-5 advice upgraded the easy-to-miss warning to a
            # hard error: the two readings permute the matrix
            # differently, so the user must say which they mean.
            p.error(
                "partition vector has min part 1: ambiguous between "
                "one-based numbering (Fortran/METIS) and 0-based with "
                "an empty part 0 -- rerun with --one-based or "
                "--zero-based")
        t0 = time.perf_counter()
        mtx, bounds, perm = apply_partition_rowsorted(mtx, part)
        write_mtx(args.output + ".bounds.mtx",
                  vector_mtx(bounds, field="integer"), numfmt="%d")
        write_mtx(args.output + ".perm.mtx",
                  vector_mtx(perm + 1, field="integer"), binary=True)
        if args.verbose:
            sys.stderr.write(
                f"partition: {bounds.size - 1} parts grouped contiguous "
                f"in {time.perf_counter() - t0:.6f} s; sidecars "
                f"{args.output}.bounds.mtx, {args.output}.perm.mtx\n")
    t0 = time.perf_counter()
    if args.output:
        write_mtx(args.output, mtx, binary=True)
    else:
        write_mtx(sys.stdout.buffer, mtx, binary=True)
    if args.verbose:
        sys.stderr.write(f"write: {time.perf_counter() - t0:.6f} s\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
