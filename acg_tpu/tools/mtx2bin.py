"""Text-to-binary Matrix Market converter (the reference's ``mtx2bin``).

Converts a text or gzipped ``.mtx`` file to the raw-binary form (same
header text; data section as consecutive rowidx/colidx/vals arrays,
``mtx2bin/mtx2bin.c:538-547``) for fast re-reading at scale -- the de facto
checkpoint of the preprocessing pipeline (SURVEY.md section 5).
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="acg-tpu-mtx2bin",
        description="Convert a Matrix Market file to binary form.")
    p.add_argument("input", help="text or gzipped .mtx file")
    p.add_argument("output", nargs="?", default=None,
                   help="output path (default: stdout)")
    p.add_argument("--expand", action="store_true",
                   help="expand symmetric one-triangle storage to full "
                        "storage and sort entries by row: the layout "
                        "required for per-controller RANGE reads "
                        "(read_mtx_row_range) at pod scale -- each "
                        "controller then reads only its rows")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)

    from acg_tpu.io.mtxfile import expand_to_rowsorted_full, read_mtx, write_mtx

    t0 = time.perf_counter()
    mtx = read_mtx(args.input)
    if args.verbose:
        sys.stderr.write(f"read: {time.perf_counter() - t0:.6f} s "
                         f"({mtx.nrows}x{mtx.ncols}, {mtx.nnz} nnz)\n")
    if args.expand:
        mtx = expand_to_rowsorted_full(mtx)
        if args.verbose:
            sys.stderr.write(f"expand: full storage, {mtx.nnz} nnz\n")
    t0 = time.perf_counter()
    if args.output:
        write_mtx(args.output, mtx, binary=True)
    else:
        write_mtx(sys.stdout.buffer, mtx, binary=True)
    if args.verbose:
        sys.stderr.write(f"write: {time.perf_counter() - t0:.6f} s\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
