"""Model-problem matrix generator (the reference's ``matrices_generator``).

Writes 2D (5-point) or 3D (7-point) Poisson matrices in Matrix Market
format, e.g. ``python -m acg_tpu.tools.genmatrix --dim 2 -n 2048 -o
poisson2d_n2048.mtx`` reproduces the reference benchmark matrix
(``matrices_generator/poisson.py``, N=4,194,304).
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="acg-tpu-genmatrix",
                                description="Generate Poisson test matrices.")
    p.add_argument("-n", type=int, required=True,
                   help="grid points per side (poisson) or rows (irregular)")
    p.add_argument("--kind", default="poisson",
                   choices=["poisson", "irregular"],
                   help="poisson = banded stencil; irregular = power-law "
                        "random SPD (the SuiteSparse-workload stand-in)")
    p.add_argument("--dim", type=int, default=2, choices=[2, 3])
    p.add_argument("--avg-degree", type=float, default=16.0,
                   help="mean row degree for --kind irregular")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-o", "--output", default=None,
                   help="output path (default: poisson{dim}d_n{n}.mtx)")
    p.add_argument("--binary", action="store_true", help="write binary format")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)

    from acg_tpu.io.generators import irregular_mtx, poisson_mtx
    from acg_tpu.io.mtxfile import write_mtx

    t0 = time.perf_counter()
    if args.kind == "irregular":
        mtx = irregular_mtx(args.n, avg_degree=args.avg_degree,
                            seed=args.seed)
        out = args.output or f"irregular_n{args.n}.mtx"
    else:
        mtx = poisson_mtx(args.n, dim=args.dim)
        out = args.output or f"poisson{args.dim}d_n{args.n}.mtx"
    write_mtx(out, mtx, binary=args.binary)
    if args.verbose:
        sys.stderr.write(
            f"generated {out}: {mtx.nrows}x{mtx.ncols} matrix, "
            f"{mtx.nnz} stored nonzeros in {time.perf_counter() - t0:.3f} s\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
