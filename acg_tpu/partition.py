"""Row partitioners: METIS when available, built-in fallback otherwise.

Rebuilds the role of ``acg/metis.c`` (SURVEY.md component #7) +
``acggraph_partition_nodes`` (``graph.c:510-529``): compute a balanced,
edge-cut-minimising partition vector over the matrix sparsity graph.  METIS
is an *optional* dependency in the reference (``cmake/FindMETIS.cmake``);
we keep that contract by probing for ``libmetis`` via ctypes and otherwise
using a built-in multilevel-free partitioner: recursive graph-growing
bisection from pseudo-peripheral seeds (Gibbs-Poole-Stockmeyer style) with
boundary Kernighan-Lin-flavoured refinement.  For mesh-like matrices
(Poisson stencils, FEM) this yields contiguous, low-cut subdomains -- the
property the downstream halo exchange actually needs.

The partition id <-> mesh coordinate mapping (rank assignment in the
reference, ``cuda/acg-cuda.c:1036``) is the identity: part p lives on
device p of the 1-D solve mesh.
"""

from __future__ import annotations

import ctypes
import ctypes.util

import numpy as np
import scipy.sparse as sp

from acg_tpu.errors import AcgError, ErrorCode
from acg_tpu.io.mtxfile import IDX_DTYPE


# ---------------------------------------------------------------------------
# METIS via ctypes (optional, like the reference's CMake-gated METIS)
# ---------------------------------------------------------------------------

_METIS = None
_METIS_CHECKED = False


def is_permutation(perm, n: int) -> bool:
    """True when ``perm`` is exactly a permutation of ``[0, n)`` --
    the integrity test for stored row-permutation sidecars (the
    checkpoint tier's repartition resume and the mtx2bin perm files):
    scattering vector rows through anything else silently scrambles
    them."""
    perm = np.asarray(perm).reshape(-1)
    if perm.size != n or n == 0:
        return perm.size == n
    if not np.issubdtype(perm.dtype, np.integer):
        return False
    if perm.min() < 0 or perm.max() >= n:
        return False
    return bool((np.bincount(perm, minlength=n) == 1).all())


def _load_metis():
    global _METIS, _METIS_CHECKED
    if _METIS_CHECKED:
        return _METIS
    _METIS_CHECKED = True
    path = ctypes.util.find_library("metis")
    if path:
        try:
            _METIS = ctypes.CDLL(path)
        except OSError:
            _METIS = None
    return _METIS


def metis_available() -> bool:
    return _load_metis() is not None


def _metis_kway(lib, np_idx, rowptr, colidx, nparts: int, seed: int,
                variant: str = "kway") -> np.ndarray:
    """Raw METIS_PartGraph{Kway,Recursive} call at a given index width
    (np_idx dtype).  The two entry points share one C signature
    (``metis.h:39-43``)."""
    idx_t = ctypes.c_int32 if np_idx == np.int32 else ctypes.c_int64
    n = len(rowptr) - 1
    xadj = np.ascontiguousarray(rowptr, dtype=np_idx)
    adjncy = np.ascontiguousarray(colidx, dtype=np_idx)
    part = np.zeros(n, dtype=np_idx)
    ncon = idx_t(1)
    objval = idx_t(0)
    options = np.zeros(40, dtype=np_idx)
    lib.METIS_SetDefaultOptions(options.ctypes.data_as(ctypes.POINTER(idx_t)))
    options[8] = seed  # METIS_OPTION_SEED
    nv = idx_t(n)
    npp = idx_t(nparts)
    fn = (lib.METIS_PartGraphRecursive if variant == "recursive"
          else lib.METIS_PartGraphKway)
    ret = fn(
        ctypes.byref(nv), ctypes.byref(ncon),
        xadj.ctypes.data_as(ctypes.POINTER(idx_t)),
        adjncy.ctypes.data_as(ctypes.POINTER(idx_t)),
        None, None, None, ctypes.byref(npp), None, None,
        options.ctypes.data_as(ctypes.POINTER(idx_t)),
        ctypes.byref(objval),
        part.ctypes.data_as(ctypes.POINTER(idx_t)))
    if ret != 1:  # METIS_OK
        raise AcgError(ErrorCode.METIS,
                       f"METIS_PartGraph{variant.capitalize()} returned {ret}")
    return part


_METIS_IDX = None


def _metis_idx_width(lib):
    """Probe libmetis's IDXTYPEWIDTH at runtime (the role of the reference's
    build-time width validation, ``cuda/CMakeLists.txt:143-150``): partition
    a tiny path graph at each width and accept the one whose result is a
    valid cover.  A wrong-width call misreads the buffers and produces an
    invalid partition (or an error), never a silently-plausible one here
    because we validate the output."""
    global _METIS_IDX
    if _METIS_IDX is not None:
        return _METIS_IDX
    rowptr = np.array([0, 1, 3, 5, 6])
    colidx = np.array([1, 0, 2, 1, 3, 2])
    for np_idx in (np.int32, np.int64):
        try:
            part = _metis_kway(lib, np_idx, rowptr, colidx, 2, 0)
        except (AcgError, OSError):
            continue
        if part.min() >= 0 and part.max() == 1 and np.unique(part).size == 2:
            _METIS_IDX = np_idx
            return np_idx
    raise AcgError(ErrorCode.METIS, "could not determine libmetis index width")


def _metis_check_width(np_idx, rowptr, colidx):
    if np_idx == np.int32 and (len(colidx) > np.iinfo(np.int32).max
                               or len(rowptr) - 1 > np.iinfo(np.int32).max):
        raise AcgError(ErrorCode.METIS,
                       "graph too large for 32-bit libmetis indices")


def metis_partgraphsym(rowptr, colidx, nparts: int, seed: int = 0,
                       variant: str = "kway") -> np.ndarray:
    """Call ``METIS_PartGraph{Kway,Recursive}`` on a symmetric adjacency
    (no self-loops).

    The ``metis_partgraphsym`` role (``metis.h:81``); ``variant=
    "recursive"`` selects ``METIS_PartGraphRecursive`` (the reference
    exposes both, ``metis.h:39-43``).  Raises if libmetis is not present;
    callers use :func:`partition_rows` for the fallback.
    """
    if variant not in ("kway", "recursive"):
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"unknown METIS variant {variant!r}")
    lib = _load_metis()
    if lib is None:
        raise AcgError(ErrorCode.METIS, "libmetis not found")
    np_idx = _metis_idx_width(lib)
    _metis_check_width(np_idx, rowptr, colidx)
    part = _metis_kway(lib, np_idx, rowptr, colidx, nparts, seed, variant)
    if part.min() < 0 or part.max() >= nparts:
        raise AcgError(ErrorCode.METIS, "METIS returned an invalid partition")
    return part.astype(np.int32)


def metis_nd(rowptr, colidx) -> tuple[np.ndarray, np.ndarray]:
    """Call ``METIS_NodeND`` on a symmetric adjacency (no self-loops):
    fill-reducing nested-dissection ordering.

    The ``metis_ndsym``/``metis_nd`` role (``metis.h:249-263``).  Returns
    ``(perm, iperm)`` with METIS's convention: ``iperm[old] = new`` and
    ``perm[new] = old``.  Raises if libmetis is not present; callers use
    :func:`nested_dissection` for the built-in fallback.
    """
    lib = _load_metis()
    if lib is None:
        raise AcgError(ErrorCode.METIS, "libmetis not found")
    np_idx = _metis_idx_width(lib)
    _metis_check_width(np_idx, rowptr, colidx)
    idx_t = ctypes.c_int32 if np_idx == np.int32 else ctypes.c_int64
    n = len(rowptr) - 1
    xadj = np.ascontiguousarray(rowptr, dtype=np_idx)
    adjncy = np.ascontiguousarray(colidx, dtype=np_idx)
    perm = np.zeros(n, dtype=np_idx)
    iperm = np.zeros(n, dtype=np_idx)
    options = np.zeros(40, dtype=np_idx)
    lib.METIS_SetDefaultOptions(options.ctypes.data_as(ctypes.POINTER(idx_t)))
    nv = idx_t(n)
    ret = lib.METIS_NodeND(
        ctypes.byref(nv),
        xadj.ctypes.data_as(ctypes.POINTER(idx_t)),
        adjncy.ctypes.data_as(ctypes.POINTER(idx_t)),
        None,
        options.ctypes.data_as(ctypes.POINTER(idx_t)),
        perm.ctypes.data_as(ctypes.POINTER(idx_t)),
        iperm.ctypes.data_as(ctypes.POINTER(idx_t)))
    if ret != 1:
        raise AcgError(ErrorCode.METIS, f"METIS_NodeND returned {ret}")
    p32, i32 = perm.astype(np.int32), iperm.astype(np.int32)
    if not (np.array_equal(np.sort(p32), np.arange(n))
            and np.array_equal(p32[i32], np.arange(n))):
        raise AcgError(ErrorCode.METIS, "METIS_NodeND returned an invalid "
                       "permutation (index-width mismatch?)")
    return p32, i32


# ---------------------------------------------------------------------------
# Built-in fallback partitioner
# ---------------------------------------------------------------------------

def _frontier_neighbors(graph: sp.csr_matrix, frontier: np.ndarray) -> np.ndarray:
    """All column indices of the given rows, vectorised (no per-node loop)."""
    indptr, indices = graph.indptr, graph.indices
    starts, ends = indptr[frontier], indptr[frontier + 1]
    lens = ends - starts
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=indices.dtype)
    # ranges [starts[i], ends[i]) flattened without Python-level looping
    offsets = np.repeat(starts, lens)
    within = np.arange(total) - np.repeat(np.concatenate([[0], np.cumsum(lens)[:-1]]), lens)
    return indices[offsets + within]


def _bfs_order(graph: sp.csr_matrix, seed_node: int, mask: np.ndarray) -> np.ndarray:
    """BFS traversal order of the masked subgraph from seed_node."""
    visited = ~mask  # treat out-of-subset as visited
    order = np.empty(int(mask.sum()), dtype=IDX_DTYPE)
    count = 0
    frontier = np.array([seed_node], dtype=IDX_DTYPE)
    visited[seed_node] = True
    while frontier.size:
        order[count:count + frontier.size] = frontier
        count += frontier.size
        nbr = np.unique(_frontier_neighbors(graph, frontier))
        nbr = nbr[~visited[nbr]]
        visited[nbr] = True
        frontier = nbr.astype(IDX_DTYPE)
    return order[:count]


def _pseudo_peripheral(graph: sp.csr_matrix, mask: np.ndarray, rng) -> int:
    """A node of (near-)maximal eccentricity in the masked subgraph."""
    nodes = np.flatnonzero(mask)
    u = int(nodes[rng.integers(nodes.size)])
    for _ in range(3):
        order = _bfs_order(graph, u, mask.copy())
        far = int(order[-1])
        if far == u:
            break
        u = far
    return u


def _refine_bisection(adj: sp.csr_matrix, side: np.ndarray, mask: np.ndarray,
                      target0: int, passes: int = 4) -> None:
    """Greedy boundary refinement, vectorised: per pass, one sparse matvec
    computes each node's same-side neighbour count; nodes with positive
    gain (external-edge count exceeds internal) migrate, best-gain first,
    subject to a 1% balance slack.  KL/FM-flavoured but whole-boundary."""
    nodes = np.flatnonzero(mask)
    size0 = int(np.sum(side[nodes] == 0))
    slack = max(1, nodes.size // 100)
    in_mask = mask.astype(np.float64)
    deg = adj @ in_mask  # within-subset degree
    for _ in range(passes):
        nbr1 = adj @ (in_mask * (side == 1))
        # gain of flipping = external - internal neighbour count
        gain = np.where(side == 0, 2 * nbr1 - deg, deg - 2 * nbr1)
        gain[~mask] = -np.inf
        cand = np.flatnonzero(gain > 0)
        if cand.size == 0:
            break
        cand = cand[np.argsort(-gain[cand], kind="stable")]
        c0 = cand[side[cand] == 0][: max(0, size0 - (target0 - slack))]
        c1 = cand[side[cand] == 1][: max(0, (target0 + slack) - size0)]
        # flip the smaller of the two flows fully, counter-balance the other
        k = min(c0.size, c1.size) or max(c0.size, c1.size)
        c0, c1 = c0[:k], c1[:k]
        if c0.size == 0 and c1.size == 0:
            break
        side[c0] = 1
        side[c1] = 0
        size0 += c1.size - c0.size


def partition_rows_band(full_csr: sp.csr_matrix, nparts: int) -> np.ndarray:
    """Contiguous row-range partition with ~equal nonzeros per part.

    For banded matrices (stencils in natural order, anything after RCM)
    this is the TPU-preferred partition: each part's diagonal block is a
    contiguous sub-band of the global matrix, so the local SpMV stays in
    gather-free DIA form (see ``parallel/dist.py``), which on TPU outweighs
    the slightly larger edge cut vs a METIS patch partition.  The analog
    trade in the reference is choosing the SpMV kernel to fit the hardware
    (``cg-kernels-cuda.cu:340-441``).
    """
    n = full_csr.shape[0]
    if nparts <= 0:
        raise AcgError(ErrorCode.INVALID_VALUE, "nparts must be positive")
    if nparts > n:
        raise AcgError(ErrorCode.INVALID_PARTITION, "more parts than rows")
    indptr = np.asarray(full_csr.indptr, dtype=np.int64)
    total = int(indptr[-1])
    # row index where each part should start, by cumulative-nnz quantile
    cuts = np.searchsorted(indptr, total * np.arange(1, nparts) / nparts)
    # every part must own at least one row: lower-bound each cut, make the
    # sequence strictly increasing (equal quantiles collapse when nnz is
    # concentrated), then upper-bound so trailing parts stay nonempty
    cuts = np.maximum(cuts, np.arange(1, nparts))
    steps = np.arange(nparts - 1)
    cuts = np.maximum.accumulate(cuts - steps) + steps
    cuts = np.minimum(cuts, n - nparts + np.arange(1, nparts))
    part = np.zeros(n, dtype=np.int32)
    part[cuts] = 1
    return np.cumsum(part).astype(np.int32)


def _pattern_graph(graph: sp.csr_matrix) -> sp.csr_matrix:
    """0/1 adjacency with the diagonal removed (refinement and BFS must
    not see matrix values: negative off-diagonals would invert flip
    gains, and METIS forbids self-loops)."""
    coo = graph.tocoo()
    off = coo.row != coo.col
    return sp.coo_matrix((np.ones(int(off.sum())),
                          (coo.row[off], coo.col[off])),
                         shape=graph.shape).tocsr()


def _bisect(graph: sp.csr_matrix, mask: np.ndarray, target0: int,
            rng, refine: bool) -> np.ndarray:
    """One graph-growing bisection of the masked subgraph: returns the
    side array (0/1 per node; only masked entries meaningful)."""
    n = graph.shape[0]
    nnodes = int(mask.sum())
    seed_node = _pseudo_peripheral(graph, mask, rng)
    order = _bfs_order(graph, seed_node, mask.copy())
    side = np.zeros(n, dtype=np.int8)
    side[order[target0:]] = 1
    # disconnected leftovers go to the smaller side
    leftover = mask.copy()
    leftover[order] = False
    if leftover.any():
        side[leftover] = 1 if target0 > nnodes - target0 else 0
    if refine:
        _refine_bisection(graph, side, mask, target0)
    return side


def nested_dissection(full_csr: sp.csr_matrix, seed: int = 0,
                      use_metis: str = "auto",
                      leaf_size: int = 32) -> tuple[np.ndarray, np.ndarray]:
    """Fill-reducing nested-dissection ordering of the sparsity graph.

    The ``metis_nd`` role (``metis.h:249-263``) with the same optional-METIS
    contract as :func:`partition_rows`: ``METIS_NodeND`` when libmetis is
    present, otherwise a built-in recursion -- bisect with the graph-growing
    partitioner, extract the vertex separator (side-0 nodes adjacent to
    side 1), order both halves recursively, separator last.  Returns
    ``(perm, iperm)``: ``perm[new] = old``, ``iperm[old] = new``.
    """
    n = full_csr.shape[0]
    graph = _pattern_graph(full_csr)
    if use_metis in ("auto", "require") and metis_available():
        return metis_nd(graph.indptr.astype(np.int64),
                        graph.indices.astype(np.int64))
    if use_metis == "require":
        raise AcgError(ErrorCode.METIS, "libmetis required but not found")

    rng = np.random.default_rng(seed)

    def recurse(mask: np.ndarray) -> np.ndarray:
        nodes = np.flatnonzero(mask)
        if nodes.size <= leaf_size:
            return nodes.astype(np.int32)
        side = _bisect(graph, mask, nodes.size // 2, rng, refine=True)
        m0 = mask & (side == 0)
        m1 = mask & (side == 1)
        if not m0.any() or not m1.any():
            return nodes.astype(np.int32)
        # vertex separator: side-0 nodes with a neighbour in side 1
        nbr1 = (graph @ m1.astype(np.float64)) > 0
        sep = m0 & nbr1
        m0 = m0 & ~sep
        left = recurse(m0) if m0.any() else np.empty(0, dtype=np.int32)
        right = recurse(m1)
        return np.concatenate([left, right, np.flatnonzero(sep).astype(np.int32)])

    perm = recurse(np.ones(n, dtype=bool))
    iperm = np.empty(n, dtype=np.int32)
    iperm[perm] = np.arange(n, dtype=np.int32)
    return perm, iperm


def partition_rows(full_csr: sp.csr_matrix, nparts: int, seed: int = 0,
                   refine: bool = True, use_metis: str = "auto",
                   method: str = "graph", variant: str = "kway") -> np.ndarray:
    """Partition matrix rows into ``nparts`` balanced, low-cut parts.

    The ``acgsymcsrmatrix_partition_rows`` role (``symcsrmatrix.c`` ->
    ``graph.c:510`` -> METIS).  ``use_metis``: "auto" probes for libmetis,
    "never" forces the built-in partitioner, "require" errors without it.
    ``method``: "graph" = edge-cut minimisation (METIS or built-in
    bisection); "band" = contiguous nnz-balanced row ranges
    (:func:`partition_rows_band`).  ``variant``: "kway" (default) or
    "recursive" selects the METIS algorithm (``metis.h:39-43``); the
    built-in partitioner is recursive bisection either way.
    """
    n = full_csr.shape[0]
    if nparts <= 0:
        raise AcgError(ErrorCode.INVALID_VALUE, "nparts must be positive")
    if nparts == 1:
        return np.zeros(n, dtype=np.int32)
    if nparts > n:
        raise AcgError(ErrorCode.INVALID_PARTITION, "more parts than rows")
    if method == "band":
        return partition_rows_band(full_csr, nparts)
    if method != "graph":
        raise AcgError(ErrorCode.INVALID_VALUE,
                       f"unknown partition method {method!r}")

    if use_metis in ("auto", "require") and metis_available():
        adj = _pattern_graph(full_csr)
        return metis_partgraphsym(adj.indptr.astype(np.int64),
                                  adj.indices.astype(np.int64), nparts, seed,
                                  variant=variant)
    if use_metis == "require":
        raise AcgError(ErrorCode.METIS, "libmetis required but not found")

    graph = _pattern_graph(full_csr)

    rng = np.random.default_rng(seed)
    part = np.zeros(n, dtype=np.int32)
    # recursive bisection: split [lo, hi) part-id range
    stack = [(np.ones(n, dtype=bool), 0, nparts)]
    while stack:
        mask, lo, hi = stack.pop()
        if hi - lo == 1:
            part[mask] = lo
            continue
        nleft_parts = (hi - lo) // 2
        nnodes = int(mask.sum())
        target0 = int(round(nnodes * nleft_parts / (hi - lo)))
        side = _bisect(graph, mask, target0, rng, refine)
        m0 = mask & (side == 0)
        m1 = mask & (side == 1)
        if not m0.any() or not m1.any():
            # degenerate split: fall back to even index split
            nodes = np.flatnonzero(mask)
            m0 = np.zeros(n, dtype=bool)
            m0[nodes[:target0]] = True
            m1 = mask & ~m0
        stack.append((m0, lo, lo + nleft_parts))
        stack.append((m1, lo + nleft_parts, hi))
    return part


def edgecut(full_csr: sp.csr_matrix, part: np.ndarray) -> int:
    """Number of cut edges (each undirected edge counted once)."""
    coo = full_csr.tocoo()
    off = coo.row < coo.col
    return int(np.sum(part[coo.row[off]] != part[coo.col[off]]))
