"""Solve telemetry: convergence traces, structured stats export, phase
timing, and cross-rank aggregation.

The reference's only window into a running solve is the post-hoc stats
block (``acgsolvercuda_fwrite``, SURVEY.md section 5).  Under XLA the
whole CG loop is ONE fused program, so a 10k-iteration solve is a black
box between dispatch and result -- the resilience tier (PR 1) can say
*that* a breakdown happened but not show the residual trajectory that
led there.  Communication-reduced and deep-pipelined CG variants make
per-iteration residual drift and per-rank time imbalance the primary
evidence for choosing a variant (Cornelis & Vanroose, arXiv:1801.04728);
this module makes that evidence machine-readable, per rank, and cheap
enough to leave on.

Four tiers (lowest overhead first):

1. **Always-on counters** -- :class:`~acg_tpu.solvers.stats.SolverStats`
   (unchanged) plus the phase timer (:class:`PhaseTimer`) whose
   ingest/partition/transfer/compile/solve/writeback seconds appear in a
   new ``timings:`` stats section; each phase is also bracketed with a
   ``jax.profiler.TraceAnnotation`` so ``--trace`` Perfetto output is
   navigable.
2. **In-loop convergence telemetry** (``--convergence-log``): the jitted
   classic and pipelined loops carry a fixed-size device-side ring
   buffer recording per-iteration ``(||r||^2, alpha, beta, pAp)``.  The
   buffer rides the loop carry and is fetched ONCE with the result --
   zero additional host transfers per iteration.  Surfaced as JSONL
   (:meth:`ConvergenceTrace.write_jsonl`) and consumed by the recovery
   driver so breakdown/restart events log the trailing residual window.
3. **Progress heartbeat** (``--progress K``): a ``jax.debug.callback``
   fired every K iterations from inside the compiled loop -- the only
   liveness signal a multi-hour pod solve has.
4. **Structured stats sink** (``--stats-json``): a schema-versioned
   machine-readable twin of ``fwrite`` -- run manifest, per-op
   counters, timestamped resilience/fault events, phase timings, the
   convergence trace, and (multi-controller) the cross-rank aggregation
   gathered over the erragree coordination-service KV plumbing.

Everything here is OFF by default and compiles to the byte-identical
pristine programs when disarmed (``trace``/``progress`` are static jit
arguments, the same design as the fault injector's).
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import math
import sys
import time

import numpy as np

from acg_tpu.solvers.stats import PHASE_ORDER

# /2: the stats twin grew the perfmodel tier's "costmodel" (compiler
# cost analysis + per-iteration derivation + comm ledger) and "memory"
# (compiled HBM footprint) keys -- additive, so /1 consumers keep working
# /3: the service-metrics tier adds a top-level "metrics" key (the
# process-wide registry snapshot, acg_tpu.metrics, present when the
# metrics layer is armed) and a "soak" key inside the stats twin (the
# soak driver's latency/iteration percentiles + drift verdict) --
# additive again, so /1 and /2 consumers keep working
# /4: the preconditioning tier (acg_tpu.precond) adds a "precond" key
# inside the stats twin (kind/applies/spectral estimates), a "precond"
# op-class row under "ops", and a manifest "precond" key that joins the
# bench-diff case key -- additive, so /1../3 consumers keep working
# /5: the numerical-health tier (acg_tpu.health) adds a "health" key
# inside the stats twin (true-residual audit summary + Lanczos spectrum
# estimate) and an optional "gap" column in trace records (the audit
# column; its presence is declared by the trace/meta "fields" list so
# mixed audited/unaudited windows round-trip) -- additive, so /1../4
# consumers keep working
# /6: the survivability tier (acg_tpu.checkpoint) adds a "ckpt" key
# inside the stats twin (armed snapshot configuration, snapshots
# written, resume provenance), an "nrollbacks" counter inside
# "resilience", and an "abft" sub-dict inside "health" (checksum-SpMV
# verification summary) -- additive, so /1../5 consumers keep working
# /7: the timeline-tracing tier (acg_tpu.tracing) adds a "tracing" key
# inside the stats twin (profiler-capture analysis: measured per-op-
# class seconds, overlap efficiency, straggler attribution; plus the
# --timeline export summary) -- additive, so /1../6 consumers keep
# working
# /8: the live-observatory tier (acg_tpu.observatory) adds an "slo" key
# inside the stats twin (declared --slo objectives, per-objective
# observation/breach counts, cumulative burn fractions) and the
# slo-breach event kind -- additive, so /1../7 consumers keep working
# (the run-history ledger wraps whole /N documents, any N, under its
# own acg-tpu-history/1 index lines)
# /9: the batched multi-RHS tier (acg_tpu.solvers.batched) adds a
# "batch" key inside the stats twin (nrhs, per-RHS iteration/residual/
# converged columns, block-CG iteration totals), a "per_rhs" key inside
# "soak" (per-RHS latency/iteration percentiles), and an "nrhs" manifest
# key that joins the bench-diff case key -- additive, so /1../8
# consumers keep working
# /10: the communication observatory (acg_tpu.commbench) adds a
# "calibration" manifest key (the active acg-tpu-commbench/1
# calibration id, or "uncalibrated") that joins the bench-diff case
# key, "segments"/"calibration" keys inside the costmodel: stats
# section (measured SpMV/halo/reduction decomposition), and a
# "calibration" key on the convergence-log meta line -- additive, so
# /1../9 consumers keep working
# /11: the matrix-free operator tier (acg_tpu.ops.operator) adds an
# "operator" manifest key (the operator identity string, e.g.
# "stencil:poisson2d:2048", present only when --operator is armed) that
# joins the bench-diff case key (perfmodel._operator_keyed), and
# "operator"/"matrix_free"/"matrix_bytes_per_spmv" keys inside the comm
# ledger of matrix-free dist solves -- additive, so /1../10 consumers
# keep working
# /12: the decision observatory (acg_tpu.planner) adds a "plan" key
# inside the stats twin (plan id, decision provenance planned/
# flag-forced/fallback, the plan-vs-actual row: predicted vs measured
# s/solve + iterations, misprediction ratio, and the (matrix, mesh,
# calibration) self-correction key) and the calibration-mismatch event
# kind -- additive, so /1../11 consumers keep working
STATS_SCHEMA = "acg-tpu-stats/12"
CONVERGENCE_SCHEMA = "acg-tpu-convergence/1"
# default ring capacity (--telemetry-window): 512 iterations x 4 scalars
# is 8 KiB of f32 carry -- negligible against any solve's vectors, and
# deep enough to show the drift window leading into a breakdown
DEFAULT_WINDOW = 512
TRACE_FIELDS = ("rnrm2", "alpha", "beta", "pAp")
# the optional 5th ring column (the numerical-health tier's in-loop
# true-residual audit, acg_tpu.health): relative gap on audited
# iterations, NaN elsewhere.  Declared through the trace's "fields"
# list so readers never misalign mixed audited/unaudited windows
AUDIT_FIELD = "gap"
# a rank whose solve time exceeds this multiple of the median gets the
# straggler callout in the cross-rank report
STRAGGLER_RATIO = 1.2


# -- device-side ring buffer (inside jit; capacity is static) -----------

def ring_init(capacity: int, dtype, audit: bool = False):
    """The carried ring buffer: ``(capacity, 4)`` slots of
    ``(rnrm2sqr, alpha, beta, pAp)``, NaN-initialised so unwritten
    slots are detectable host-side.  ``audit`` (the numerical-health
    tier) grows a 5th ``gap`` column for the in-loop true-residual
    audit; without it the layout is byte-identical to every pre-/5
    ring."""
    import jax.numpy as jnp

    width = len(TRACE_FIELDS) + (1 if audit else 0)
    return jnp.full((max(int(capacity), 1), width), jnp.nan, dtype=dtype)


def ring_record(buf, k, rnrm2sqr, alpha, beta, pAp, audit=None):
    """Write iteration ``k``'s scalars into slot ``k % capacity``.
    One dynamic_update_slice per iteration -- the documented price of
    telemetry-on (every extra loop-carried array costs; see the
    jax_cg._cg_program carry notes); disarmed programs compile without
    any of this.  ``audit`` fills the optional gap column (a ring built
    with ``audit=True`` only)."""
    import jax
    import jax.numpy as jnp

    vals = (rnrm2sqr, alpha, beta, pAp)
    if audit is not None:
        vals = vals + (audit,)
    row = jnp.stack([jnp.asarray(v, buf.dtype).reshape(())
                     for v in vals])[None]
    slot = jnp.asarray(k, jnp.int32) % buf.shape[0]
    return jax.lax.dynamic_update_slice(buf, row, (slot, jnp.int32(0)))


def ring_init_batched(capacity: int, nrhs: int, dtype):
    """The batched tier's carried ring: ``(capacity, nrhs)`` slots of
    per-RHS ``||r_j||^2`` columns, NaN-initialised like the classic
    ring.  Scalars (alpha/beta/pAp) are per-RHS vectors in the batched
    recurrences, so the ring records the one column every consumer
    needs -- the residual fan -- instead of 4*nrhs columns nobody
    reads."""
    import jax.numpy as jnp

    return jnp.full((max(int(capacity), 1), max(int(nrhs), 1)), jnp.nan,
                    dtype=dtype)


def ring_record_batched(buf, k, rnrm2sqr_cols):
    """Write iteration ``k``'s per-RHS squared residuals into slot
    ``k % capacity`` (the batched twin of :func:`ring_record`)."""
    import jax
    import jax.numpy as jnp

    row = jnp.asarray(rnrm2sqr_cols, buf.dtype).reshape(1, -1)
    slot = jnp.asarray(k, jnp.int32) % buf.shape[0]
    return jax.lax.dynamic_update_slice(buf, row, (slot, jnp.int32(0)))


def heartbeat(k, rnrm2sqr, every: int, leader=None, what: str = "cg"):
    """In-loop progress heartbeat: every ``every`` iterations, a host
    callback writes the residual to STDERR (stdout belongs to the
    solution vector).  ``leader`` (a traced bool) gates the emit to one
    shard under shard_map so a mesh prints once, not once per part."""
    if not every:
        return
    import jax
    import jax.numpy as jnp

    def emit(kk, g):
        # the live-observatory tier derives iterations/sec and the ETA
        # from the same samples the status endpoint serves -- one line
        # shape for every tier (observatory.heartbeat_line)
        from acg_tpu import observatory
        sys.stderr.write(observatory.heartbeat_line(
            what, int(kk) + 1,
            math.sqrt(max(float(g), 0.0))) + "\n")
        sys.stderr.flush()

    fire = (jnp.asarray(k, jnp.int32) + 1) % jnp.int32(every) == 0
    if leader is not None:
        fire = fire & leader
    jax.lax.cond(fire,
                 lambda kk, g: jax.debug.callback(emit, kk, g),
                 lambda kk, g: None, k, rnrm2sqr)


# -- host-side trace representation -------------------------------------

@dataclasses.dataclass
class ConvergenceTrace:
    """The host view of one solve attempt's in-loop telemetry.

    ``records`` is ``(m, 4)`` float64 ``(rnrm2, alpha, beta, pAp)`` --
    note rnrm2 is the NORM (the square root is applied here, once,
    instead of per-iteration on device) -- and ``iterations`` the
    0-based iteration index of each row, contiguous and ascending.
    ``wrapped`` marks a ring that overwrote its oldest rows: only the
    trailing ``capacity`` iterations survive (truncation, marked in the
    JSONL meta record).  ``fields`` names the record columns -- rings
    carrying the numerical-health audit column append ``"gap"``
    (relative true-residual gap on audited iterations, NaN elsewhere),
    and the JSONL meta line carries the same list so mixed
    audited/unaudited windows round-trip without misaligned fields."""

    capacity: int
    niterations: int
    records: np.ndarray
    iterations: np.ndarray
    wrapped: bool
    solver: str = "cg"
    fields: tuple = TRACE_FIELDS
    # extra meta-line keys (additive; e.g. the active commbench
    # calibration id the CLI stamps on the JSONL meta record)
    meta_extra: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_ring(cls, buf, niterations: int, solver: str = "cg",
                  already_norm: bool = False,
                  offset: int = 0) -> "ConvergenceTrace":
        """Un-rotate a fetched ring buffer: slot ``k % capacity`` holds
        iteration ``k``, so the surviving window is iterations
        ``[max(0, n - capacity), n)``.  The column names come from the
        ring's width (4 = the classic tuple, 5 = + the audit column).
        ``offset`` (the checkpoint chunk drivers) renumbers the window
        to TRAJECTORY iterations: the ring held chunk-local indices,
        and iterations before the chunk are marked truncated exactly
        like a wrapped ring's."""
        buf = np.asarray(buf, dtype=np.float64)
        cap = int(buf.shape[0])
        fields = tuple(TRACE_FIELDS) + (
            (AUDIT_FIELD,) if buf.shape[1] > len(TRACE_FIELDS) else ())
        n = int(niterations)
        off = int(offset)
        m = min(n, cap)
        its = np.arange(n - m, n, dtype=np.int64)
        rows = buf[its % cap] if m else buf[:0]
        rows = np.array(rows, copy=True)
        if m and not already_norm:
            # stored squared (saves the per-iteration device sqrt);
            # NaN/Inf propagate through sqrt unchanged, and a poisoned
            # negative "norm" must stay visibly wrong, not become NaN
            g = rows[:, 0]
            rows[:, 0] = np.where(g >= 0, np.sqrt(np.abs(g)), g)
        return cls(capacity=cap, niterations=n + off, records=rows,
                   iterations=its + off, wrapped=n > cap or off > 0,
                   solver=solver, fields=fields)

    @property
    def first_iteration(self) -> int:
        return int(self.iterations[0]) if self.iterations.size else 0

    def to_dict(self) -> dict:
        """JSON-able form (the ``trace`` key of
        :meth:`SolverStats.to_dict`); record dicts are identical to the
        JSONL data lines, so the two sinks round-trip."""
        return {
            "schema": CONVERGENCE_SCHEMA,
            "solver": self.solver,
            "capacity": self.capacity,
            "niterations": self.niterations,
            "first_iteration": self.first_iteration,
            "wrapped": self.wrapped,
            "fields": list(self.fields),
            **dict(self.meta_extra),
            "records": [self.record_dict(i)
                        for i in range(self.iterations.size)],
        }

    def record_dict(self, i: int) -> dict:
        rec = {"it": int(self.iterations[i])}
        for j, f in enumerate(self.fields):
            rec[f] = _json_float(self.records[i, j])
        return rec

    def write_jsonl(self, f) -> None:
        """One meta line (wrap/truncation marked), then one record per
        surviving iteration."""
        own = isinstance(f, (str, bytes)) or hasattr(f, "__fspath__")
        out = open(f, "w") if own else f
        try:
            meta = self.to_dict()
            records = meta.pop("records")
            meta = {"meta": True, **meta}
            if self.wrapped:
                meta["truncated_before"] = self.first_iteration
            out.write(json.dumps(meta) + "\n")
            for rec in records:
                out.write(json.dumps(rec) + "\n")
        finally:
            if own:
                out.close()

    def tail_summary(self, n: int = 5) -> str:
        """The trailing residual window as one human line -- what the
        recovery driver logs next to a breakdown/restart event.  When
        the audit column is present each audited entry carries its gap
        inline, and the line says so -- a reader of a mixed window must
        never mistake audit gaps for residuals."""
        m = min(int(n), self.iterations.size)
        if not m:
            return "trailing residual window: (empty)"
        audited = AUDIT_FIELD in self.fields
        gi = self.fields.index(AUDIT_FIELD) if audited else None
        parts = []
        for i in range(m):
            row = self.records[-m + i]
            s = f"it {int(self.iterations[-m + i])}: {row[0]:.3e}"
            if audited and math.isfinite(row[gi]):
                s += f" (gap {row[gi]:.3e})"
            parts.append(s)
        line = "trailing residual window: " + ", ".join(parts)
        if audited:
            line += " [audit gap column present]"
        return line


@dataclasses.dataclass
class BatchedConvergenceTrace:
    """Host view of a batched solve's per-RHS residual ring.

    ``records`` is ``(m, nrhs)`` float64 of per-RHS residual NORMS
    (sqrt applied here, once); ``iterations`` the 0-based iteration of
    each row.  The JSONL form declares ``nrhs`` in its meta line and
    each data record carries the full residual column plus the
    worst-RHS value, so :mod:`scripts/plot_convergence` can render the
    residual fan and ascii consumers can fall back to the worst RHS."""

    capacity: int
    niterations: int
    nrhs: int
    records: np.ndarray
    iterations: np.ndarray
    wrapped: bool
    solver: str = "cg-batched"
    # extra meta-line keys (the ConvergenceTrace convention)
    meta_extra: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_ring(cls, buf, niterations: int,
                  solver: str = "cg-batched",
                  offset: int = 0) -> "BatchedConvergenceTrace":
        buf = np.asarray(buf, dtype=np.float64)
        cap, nrhs = int(buf.shape[0]), int(buf.shape[1])
        n = int(niterations)
        off = int(offset)
        m = min(n, cap)
        its = np.arange(n - m, n, dtype=np.int64)
        rows = np.array(buf[its % cap] if m else buf[:0], copy=True)
        if m:
            rows = np.where(rows >= 0, np.sqrt(np.abs(rows)), rows)
        return cls(capacity=cap, niterations=n + off, nrhs=nrhs,
                   records=rows, iterations=its + off,
                   wrapped=n > cap or off > 0, solver=solver)

    @property
    def first_iteration(self) -> int:
        return int(self.iterations[0]) if self.iterations.size else 0

    def worst_per_iteration(self) -> np.ndarray:
        """(m,) worst-RHS residual per recorded iteration -- what the
        ascii sparkline and the status-trail consumers fall back to."""
        if not self.records.size:
            return self.records.reshape(0)
        return np.nanmax(self.records, axis=1)

    def to_dict(self) -> dict:
        return {
            "schema": CONVERGENCE_SCHEMA,
            "solver": self.solver,
            "capacity": self.capacity,
            "niterations": self.niterations,
            "first_iteration": self.first_iteration,
            "wrapped": self.wrapped,
            "nrhs": self.nrhs,
            "fields": ["rnrm2"],
            **dict(self.meta_extra),
            "records": [self.record_dict(i)
                        for i in range(self.iterations.size)],
        }

    def record_dict(self, i: int) -> dict:
        cols = [_json_float(v) for v in self.records[i]]
        finite = [v for v in self.records[i] if math.isfinite(v)]
        return {"it": int(self.iterations[i]), "rnrm2": cols,
                "worst": _json_float(max(finite) if finite
                                     else float("nan"))}

    def write_jsonl(self, f) -> None:
        own = isinstance(f, (str, bytes)) or hasattr(f, "__fspath__")
        out = open(f, "w") if own else f
        try:
            meta = self.to_dict()
            records = meta.pop("records")
            meta = {"meta": True, **meta}
            if self.wrapped:
                meta["truncated_before"] = self.first_iteration
            out.write(json.dumps(meta) + "\n")
            for rec in records:
                out.write(json.dumps(rec) + "\n")
        finally:
            if own:
                out.close()

    def tail_summary(self, n: int = 5) -> str:
        worst = self.worst_per_iteration()
        m = min(int(n), self.iterations.size)
        if not m:
            return "trailing residual window: (empty)"
        parts = [f"it {int(self.iterations[-m + i])}: "
                 f"{worst[-m + i]:.3e} (worst of {self.nrhs})"
                 for i in range(m)]
        return "trailing residual window: " + ", ".join(parts)


class EagerTraceRecorder:
    """The eager twin of the device ring for the host solver: same
    capacity/wrap semantics, recorded per iteration in plain Python.
    ``audit=True`` mirrors the health tier's 5-column ring (gap column,
    NaN on unaudited iterations)."""

    def __init__(self, capacity: int, solver: str = "host-cg",
                 audit: bool = False):
        self.capacity = max(int(capacity), 1)
        self.solver = solver
        self.audit = bool(audit)
        self._rows: list = [None] * self.capacity
        self._n = 0

    def record(self, rnrm2: float, alpha: float, beta: float,
               pAp: float, gap: float = math.nan) -> None:
        row = (float(rnrm2), float(alpha), float(beta), float(pAp))
        if self.audit:
            row = row + (float(gap),)
        self._rows[self._n % self.capacity] = row
        self._n += 1

    def finish(self) -> ConvergenceTrace:
        n, cap = self._n, self.capacity
        width = len(TRACE_FIELDS) + (1 if self.audit else 0)
        fields = tuple(TRACE_FIELDS) + ((AUDIT_FIELD,) if self.audit
                                        else ())
        m = min(n, cap)
        its = np.arange(n - m, n, dtype=np.int64)
        rows = np.asarray([self._rows[k % cap] for k in its],
                          dtype=np.float64).reshape(m, width)
        return ConvergenceTrace(capacity=cap, niterations=n, records=rows,
                                iterations=its, wrapped=n > cap,
                                solver=self.solver, fields=fields)


def read_convergence_log(path) -> tuple[dict, list[dict]]:
    """Parse a ``--convergence-log`` JSONL file back into
    ``(meta, records)`` -- the inverse of :meth:`write_jsonl`, shared by
    the tests and ``scripts/plot_convergence.py``.

    A TRUNCATED TRAILING line (a SIGTERM/OOM-kill landing mid-write --
    exactly the runs whose telemetry matters most) yields the parseable
    prefix with ``meta["truncated"] = True`` instead of raising; a
    malformed line with valid JSON after it is still an error (that is
    corruption, not truncation)."""
    meta: dict = {}
    records: list[dict] = []
    with open(path) as f:
        lines = f.read().split("\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            if any(later.strip() for later in lines[i + 1:]):
                raise
            meta["truncated"] = True
            break
        if obj.get("meta"):
            meta = obj
        else:
            records.append(obj)
    return meta, records


def _json_float(v) -> float | str:
    """JSON has no NaN/Inf literal; poisoned telemetry values must
    survive the round trip as strings, not crash the writer."""
    v = float(v)
    if math.isfinite(v):
        return v
    return repr(v)


# -- phase timing + trace annotations -----------------------------------

class PhaseTimer:
    """Wall-clock seconds per pipeline phase (ingest -> partition ->
    transfer -> compile -> solve -> writeback), accumulated across
    retries.  :meth:`phase` also opens a ``jax.profiler.
    TraceAnnotation`` bracket so the same names navigate ``--trace``
    Perfetto output."""

    def __init__(self):
        self.phases: dict[str, float] = {}

    def add(self, name: str, seconds: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + float(seconds)
        # service-metrics tier: phase-time histogram (no-op disarmed)
        from acg_tpu import metrics, tracing
        metrics.record_phase(name, seconds)
        # timeline tier: the same phase as a wall-clock span (--timeline;
        # no-op disarmed)
        tracing.record_phase_span(name, seconds)

    @contextlib.contextmanager
    def phase(self, name: str):
        with annotate(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                self.add(name, time.perf_counter() - t0)

    def merge_into(self, timings: dict) -> dict:
        """Fold these phases into a stats ``timings`` dict, re-ordered
        so the canonical pipeline order survives whichever side recorded
        first.  CONSUMES the timer's phases (repeated folds -- e.g. a
        late writeback phase after the stats block printed -- accumulate
        instead of double-counting)."""
        merged = dict(timings)
        for k, v in self.phases.items():
            merged[k] = merged.get(k, 0.0) + v
        self.phases.clear()
        ordered = {k: merged[k] for k in PHASE_ORDER if k in merged}
        ordered.update({k: v for k, v in merged.items()
                        if k not in ordered})
        timings.clear()
        timings.update(ordered)
        return timings


@contextlib.contextmanager
def annotate(name: str):
    """``jax.profiler.TraceAnnotation("acg:<name>")`` bracket; a cheap
    no-op when no trace is being collected, and tolerant of backends
    without profiler support.  Also feeds the live-observatory status
    document's current-phase field (no-op disarmed) -- every pipeline
    phase passes through here."""
    from acg_tpu import observatory
    observatory.note_phase(name)
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(f"acg:{name}")
    except Exception:  # noqa: BLE001 -- telemetry must never sink a solve
        cm = contextlib.nullcontext()
    with cm:
        yield


def add_timing(stats, name: str, seconds: float) -> None:
    """Accumulate one phase's seconds onto ``stats.timings``."""
    stats.timings[name] = stats.timings.get(name, 0.0) + float(seconds)
    from acg_tpu import metrics, tracing
    metrics.record_phase(name, seconds)
    tracing.record_phase_span(name, seconds)


def record_event(stats, kind: str, detail: str) -> None:
    """Append one timestamped event (resilience, fault injection) for
    the structured sink; the human-readable ``recovery_log`` is separate
    and unchanged.  Every event also bumps the service-metrics
    by-kind counter (``acg_events_total``; no-op disarmed) and lands as
    an instant on the ``--timeline`` span timeline (no-op disarmed)."""
    stats.events.append({"t": time.time(), "kind": kind,
                         "detail": str(detail)})
    from acg_tpu import metrics, observatory, tracing
    metrics.record_event_kind(kind)
    tracing.record_instant(kind, detail=str(detail))
    # live-observatory tier: the status document serves the last K
    # structured events (no-op disarmed)
    observatory.note_event(kind, str(detail))


# -- structured stats sink ----------------------------------------------

def run_manifest(**extra) -> dict:
    """The run manifest of a ``--stats-json`` document: everything
    needed to interpret the numbers without the launching shell --
    backend, device/mesh shape, jax/jaxlib versions, process layout --
    plus caller-supplied keys (matrix id, solver/kernel/comm choices,
    partition and halo sizes)."""
    man: dict = {"schema": STATS_SCHEMA,
                 "unix_time": time.time()}
    try:
        import jax
        import jaxlib

        man["jax"] = jax.__version__
        man["jaxlib"] = jaxlib.__version__
        man["process_index"] = jax.process_index()
        man["process_count"] = jax.process_count()
        devs = jax.devices()
        man["backend"] = {"platform": devs[0].platform,
                          "device_kind": devs[0].device_kind,
                          "ndevices": len(devs)}
    except Exception as e:  # noqa: BLE001 -- manifest must not sink output
        man["backend"] = f"unavailable ({type(e).__name__})"
    try:
        from acg_tpu import __version__

        man["acg_tpu"] = __version__
    except Exception:  # noqa: BLE001
        pass
    man.update({k: v for k, v in extra.items() if v is not None})
    return man


def stats_document(stats, manifest: dict | None = None,
                   ranks: dict | None = None) -> dict:
    """The full ``--stats-json`` document: schema + manifest + the
    machine-readable twin of ``fwrite`` (+ cross-rank aggregation when
    gathered; + the service-metrics registry snapshot when that layer
    is armed -- the /3 additive key)."""
    doc = {"schema": STATS_SCHEMA,
           "manifest": manifest or run_manifest(),
           "stats": stats.to_dict()}
    if ranks is not None:
        doc["ranks"] = ranks
    from acg_tpu import metrics
    if metrics.armed():
        doc["metrics"] = metrics.snapshot_dict()
    return doc


def write_stats_json(path, stats, manifest: dict | None = None,
                     ranks: dict | None = None,
                     append: bool = False) -> dict:
    """Write (or with ``append``, JSONL-append -- the bench writer) the
    structured stats document.  Returns the document."""
    doc = stats_document(stats, manifest=manifest, ranks=ranks)
    own = isinstance(path, (str, bytes)) or hasattr(path, "__fspath__")
    f = open(path, "a" if append else "w") if own else path
    try:
        json.dump(doc, f, indent=None if append else 2, sort_keys=False,
                  default=str)
        f.write("\n")
    finally:
        if own:
            f.close()
    return doc


# -- cross-rank aggregation ---------------------------------------------

def rank_payload(solver) -> dict:
    """This controller's contribution to the cross-rank report: solve
    time, iteration count, and per-OWNED-part size/imbalance inputs
    (rows, nnz, halo send bytes) where a partitioned problem exists."""
    import jax

    st = solver.stats
    payload = {"process": int(jax.process_index()),
               "tsolve": float(st.tsolve),
               "niterations": int(st.niterations)}
    prob = getattr(solver, "problem", None)
    if prob is not None:
        dbl = int(np.dtype(prob.vdtype).itemsize)
        parts = []
        owned = (range(prob.nparts) if prob.owned_parts is None
                 else prob.owned_parts)
        for p in owned:
            s = prob.subs[p]
            if s is None or getattr(s, "A_local", None) is None:
                continue
            halo = getattr(s, "halo", None)
            parts.append({
                "part": int(p),
                "rows": int(s.nowned),
                "nnz": int(s.A_local.nnz
                           + (s.A_ghost.nnz if s.A_ghost is not None
                              else 0)),
                "halo_send_bytes": int(halo.total_send * dbl
                                       if halo is not None else 0),
            })
        payload["parts"] = parts
    return payload


def gather_rank_stats(payload: dict, timeout: float = 120.0
                      ) -> list[dict] | None:
    """Allgather each controller's payload dict (erragree KV plumbing;
    see :func:`acg_tpu.parallel.erragree.allgather_blobs`).  Every
    controller must call this at the same point.  Returns one dict per
    process, or None when the gather is unavailable."""
    import jax

    if jax.process_count() == 1:
        return [payload]
    from acg_tpu.parallel.erragree import allgather_blobs

    try:
        blobs = allgather_blobs(json.dumps(payload, default=str),
                                tag="telemetry", timeout=timeout)
    except Exception as e:  # noqa: BLE001 -- aggregation is best-effort:
        # a failed gather must not take down a solve that succeeded
        sys.stderr.write(f"acg-tpu: cross-rank stats gather failed "
                         f"({type(e).__name__}); skipping aggregation\n")
        return None
    return [json.loads(b) for b in blobs]


def aggregate_ranks(payloads: list[dict]) -> dict:
    """min/median/max solve time, per-part rows/nnz/halo-bytes imbalance
    (max over mean), and the straggler callout -- the evidence the
    communication-reduced-variant literature asks for, per pod."""
    ts = sorted((float(p.get("tsolve", 0.0)), int(p.get("process", i)))
                for i, p in enumerate(payloads))
    times = [t for t, _ in ts]
    med = float(np.median(times)) if times else 0.0
    agg: dict = {
        "processes": len(payloads),
        "solve_time": {"min": times[0] if times else 0.0,
                       "median": med,
                       "max": times[-1] if times else 0.0},
    }
    parts = [pt for p in payloads for pt in p.get("parts", [])]
    if parts:
        imb = {}
        for key in ("rows", "nnz", "halo_send_bytes"):
            vals = np.asarray([pt.get(key, 0) for pt in parts],
                              dtype=np.float64)
            mean = float(vals.mean()) if vals.size else 0.0
            imb[key] = {"max": float(vals.max(initial=0.0)),
                        "mean": mean,
                        "imbalance": (float(vals.max(initial=0.0) / mean)
                                      if mean > 0 else 1.0)}
        agg["parts"] = {"count": len(parts), "imbalance": imb}
    straggler = None
    if times and med > 0 and times[-1] > STRAGGLER_RATIO * med:
        straggler = {"process": ts[-1][1], "tsolve": times[-1],
                     "ratio_to_median": times[-1] / med}
    agg["straggler"] = straggler
    return agg


def format_rank_report(agg: dict) -> str:
    """One stderr line from the primary summarising the aggregation."""
    st = agg["solve_time"]
    line = (f"cross-rank: {agg['processes']} processes, solve time "
            f"min/median/max {st['min']:.6f}/{st['median']:.6f}/"
            f"{st['max']:.6f} s")
    parts = agg.get("parts")
    if parts:
        imb = parts["imbalance"]
        line += (f"; imbalance (max/mean) rows {imb['rows']['imbalance']:.2f}"
                 f" nnz {imb['nnz']['imbalance']:.2f}"
                 f" halo-bytes {imb['halo_send_bytes']['imbalance']:.2f}")
    s = agg.get("straggler")
    if s:
        line += (f"; straggler: process {s['process']} "
                 f"({s['ratio_to_median']:.2f}x median)")
    return line
