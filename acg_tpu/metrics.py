"""Process-lifetime service metrics: registry, Prometheus exposition,
and the instrumentation hooks the solver layers feed.

Everything observability-shaped so far describes ONE solve and exits:
the stats block (PR 0), convergence telemetry (PR 2), and the compiled
cost/memory introspection (PR 3) are all per-solve documents.  A solver
FLEET needs process-lifetime evidence instead -- cumulative counters,
latency histograms, drift across thousands of solves -- the same way
the aCG paper treats per-iteration cost as the quantity that must stay
flat at scale, and the reduction-pipelining line of work
(arXiv:1905.06850) treats latency JITTER, not mean cost, as the scaling
killer.  Jitter and drift are invisible to any single-solve document by
construction; they live here.

Three metric kinds, Prometheus-shaped (text exposition format 0.0.4):

* :class:`Counter` -- monotone totals (solves, iterations, breakdowns,
  restarts, halo bytes);
* :class:`Gauge` -- point-in-time values (process RSS, device memory,
  the soak driver's drift ratio);
* :class:`Histogram` -- fixed exponential buckets with cumulative
  counts (solve latency, iterations-to-converge, phase seconds);
  :meth:`Histogram.quantile` interpolates p50/p95/p99 the same way
  ``histogram_quantile`` does, so the soak report and a Grafana panel
  over the scraped data agree.

One process-wide :data:`REGISTRY`, thread-safe (one lock; the HTTP
exposition thread and the solving thread share it).  The layer is
DISARMED by default and every hook is a cheap early-return -- and since
all recording is host-side bookkeeping, the compiled solver programs
are byte-identical armed or disarmed (pinned in
tests/test_hlo_structure.py, the telemetry/faults convention).

Sinks:
* :func:`write_textfile` -- atomic-rename Prometheus textfile (the
  node-exporter textfile-collector contract); the CLI flushes it on
  exit and on SIGTERM (:func:`install_flush_handlers`);
* :func:`serve` -- a stdlib ``/metrics`` HTTP endpoint on a daemon
  thread (``--metrics-port``);
* :func:`snapshot_dict` -- the JSON twin embedded in ``--stats-json``
  documents (schema ``acg-tpu-stats/3``, additive).
"""

from __future__ import annotations

import atexit
import math
import os
import signal
import sys
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "arm", "disarm", "armed", "exponential_buckets",
    "write_textfile", "install_flush_handlers", "serve",
    "snapshot_dict", "expose",
]


def exponential_buckets(start: float, factor: float,
                        count: int) -> tuple[float, ...]:
    """``count`` bucket upper bounds ``start * factor**i`` -- the fixed
    exponential ladder every histogram here uses (a latency that can
    span 1e5x needs log-spaced resolution, not linear)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("exponential_buckets needs start > 0, "
                         "factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# solve latency: 100 us .. ~1.7 h in x2 steps -- wide enough for a tiny
# CPU debug solve and a pod-filling 512^3 one in the same ladder
SOLVE_SECONDS_BUCKETS = exponential_buckets(1e-4, 2.0, 26)
# iterations-to-converge: 1 .. ~8.4M
ITERATION_BUCKETS = exponential_buckets(1.0, 2.0, 24)
# pipeline phases: 10 us .. ~10 min
PHASE_SECONDS_BUCKETS = exponential_buckets(1e-5, 2.0, 26)

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _fmt(v: float) -> str:
    """Prometheus sample-value formatting: integers without a trailing
    ``.0``, ``+Inf``/``-Inf``/``NaN`` spelled the exposition-format way."""
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.12g}"


def _label_str(names, values) -> str:
    if not names:
        return ""
    esc = [str(v).replace("\\", r"\\").replace('"', r'\"')
           .replace("\n", r"\n") for v in values]
    return "{" + ",".join(f'{n}="{e}"' for n, e in zip(names, esc)) + "}"


class _Child:
    """One labelled time series of a metric family."""

    __slots__ = ("_family", "_values", "_sum", "_count", "labelvalues")

    def __init__(self, family, labelvalues):
        self._family = family
        self.labelvalues = labelvalues
        nb = len(family.buckets) if family.kind == "histogram" else 0
        self._values = [0.0] * nb if nb else 0.0
        self._sum = 0.0
        self._count = 0

    # counter/gauge -----------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        if self._family.kind == "histogram":
            raise ValueError(f"{self._family.name}: histograms "
                             f"observe(), they do not inc()")
        if self._family.kind == "counter" and amount < 0:
            raise ValueError(f"{self._family.name}: counters are "
                             f"monotone (inc by {amount})")
        with self._family._lock:
            self._values += float(amount)

    def dec(self, amount: float = 1.0) -> None:
        if self._family.kind != "gauge":
            raise ValueError(f"{self._family.name}: only gauges dec")
        with self._family._lock:
            self._values -= float(amount)

    def set(self, value: float) -> None:
        if self._family.kind != "gauge":
            raise ValueError(f"{self._family.name}: only gauges set")
        with self._family._lock:
            self._values = float(value)

    @property
    def value(self) -> float:
        return self._values if not isinstance(self._values, list) \
            else float(self._count)

    # histogram ---------------------------------------------------------
    def observe(self, value: float) -> None:
        if self._family.kind != "histogram":
            raise ValueError(f"{self._family.name}: only histograms "
                             f"observe")
        value = float(value)
        with self._family._lock:
            for i, ub in enumerate(self._family.buckets):
                if value <= ub:
                    self._values[i] += 1
                    break
            self._sum += value
            self._count += 1

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """``(upper_bound, cumulative_count)`` pairs ending with
        ``(+Inf, count)`` -- the exposition's ``_bucket`` series."""
        with self._family._lock:
            out, acc = [], 0
            for ub, c in zip(self._family.buckets, self._values):
                acc += int(c)
                out.append((ub, acc))
            out.append((math.inf, self._count))
            return out

    def quantile(self, q: float) -> float:
        """Histogram-interpolated quantile (the ``histogram_quantile``
        estimator: linear within the landing bucket, lower edge 0 for
        the first).  Returns NaN on an empty histogram."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        cum = self.cumulative_buckets()
        total = cum[-1][1]
        if total == 0:
            return math.nan
        rank = q * total
        prev_ub, prev_c = 0.0, 0
        for ub, c in cum:
            if c >= rank:
                if math.isinf(ub):
                    # landed past the ladder: the last finite edge is
                    # the honest answer (no width to interpolate in)
                    return prev_ub if prev_ub else math.nan
                if c == prev_c:
                    return ub
                return prev_ub + (ub - prev_ub) * (rank - prev_c) / (
                    c - prev_c)
            prev_ub, prev_c = ub, c
        return prev_ub


class _Family:
    """One named metric family; unlabelled families proxy straight to
    their single child, so ``REGISTRY.counter("x", "...").inc()`` works
    without a ``.labels()`` hop."""

    def __init__(self, name: str, help: str, kind: str, registry,
                 labelnames=(), buckets=()):
        bad = set(name) - _NAME_OK
        if bad or not name or name[0].isdigit():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._lock = registry._lock
        self._children: dict[tuple, _Child] = {}
        if not self.labelnames:
            self._children[()] = _Child(self, ())

    def labels(self, *values, **kwargs) -> _Child:
        if kwargs:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "name, not both")
            try:
                values = tuple(kwargs[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"{self.name}: missing label {e}")
            if len(kwargs) != len(self.labelnames):
                extra = set(kwargs) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {extra}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(f"{self.name}: expected labels "
                             f"{self.labelnames}, got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                # label dedup: one child per distinct value tuple, ever
                child = self._children[values] = _Child(self, values)
            return child

    def _only(self) -> _Child:
        if self.labelnames:
            raise ValueError(f"{self.name} is labelled "
                             f"{self.labelnames}; use .labels()")
        return self._children[()]

    # unlabelled proxies
    def inc(self, amount: float = 1.0) -> None:
        self._only().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._only().dec(amount)

    def set(self, value: float) -> None:
        self._only().set(value)

    def observe(self, value: float) -> None:
        self._only().observe(value)

    @property
    def value(self) -> float:
        return self._only().value

    def quantile(self, q: float) -> float:
        """Quantile over ALL children merged (the soak driver's view:
        one latency distribution regardless of solver labels)."""
        with self._lock:
            kids = list(self._children.values())
        if len(kids) == 1:
            return kids[0].quantile(q)
        merged = _Child(self, ())
        for k in kids:
            with self._lock:
                merged._values = [a + b for a, b in
                                  zip(merged._values, k._values)]
                merged._sum += k._sum
                merged._count += k._count
        return merged.quantile(q)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(k._count for k in self._children.values())


# aliases so isinstance-ish naming reads naturally in callers/tests
Counter = Gauge = Histogram = _Family


class Registry:
    """Thread-safe metric registry with Prometheus text exposition."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        self._collect_callbacks: list = []

    def _register(self, name, help, kind, labelnames, buckets=()):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if (fam.kind != kind
                        or fam.labelnames != tuple(labelnames)
                        or (kind == "histogram" and fam.buckets !=
                            tuple(sorted(float(b) for b in buckets)))):
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{tuple(labelnames)} (was {fam.kind}"
                        f"{fam.labelnames}; histograms must also keep "
                        f"their bucket ladder)")
                return fam
            fam = _Family(name, help, kind, self, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()) -> _Family:
        return self._register(name, help, "counter", labelnames)

    def gauge(self, name, help="", labelnames=()) -> _Family:
        return self._register(name, help, "gauge", labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=SOLVE_SECONDS_BUCKETS) -> _Family:
        if not buckets:
            raise ValueError(f"{name}: histogram needs buckets")
        return self._register(name, help, "histogram", labelnames,
                              buckets)

    def on_collect(self, fn) -> None:
        """Register a pre-exposition callback (resource gauges refresh
        at scrape/flush time, the Prometheus collector convention)."""
        with self._lock:
            if fn not in self._collect_callbacks:
                self._collect_callbacks.append(fn)

    def expose(self) -> str:
        """The Prometheus text exposition (format 0.0.4): families in
        name order, children in label order -- deterministic, so a
        golden test can pin it."""
        for fn in list(self._collect_callbacks):
            try:
                fn()
            except Exception:  # noqa: BLE001 -- a failed resource
                pass           # refresh must never sink a scrape
        out = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                out.append(f"# HELP {name} {fam.help}")
                out.append(f"# TYPE {name} {fam.kind}")
                for lv in sorted(fam._children):
                    child = fam._children[lv]
                    if fam.kind == "histogram":
                        for ub, c in child.cumulative_buckets():
                            ls = _label_str(fam.labelnames + ("le",),
                                            lv + (_fmt(ub),))
                            out.append(f"{name}_bucket{ls} {c}")
                        ls = _label_str(fam.labelnames, lv)
                        out.append(f"{name}_sum{ls} "
                                   f"{_fmt(child._sum)}")
                        out.append(f"{name}_count{ls} {child._count}")
                    else:
                        ls = _label_str(fam.labelnames, lv)
                        out.append(f"{name}{ls} "
                                   f"{_fmt(child._values)}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able registry snapshot (the ``metrics`` key of an
        ``acg-tpu-stats/3`` document)."""
        for fn in list(self._collect_callbacks):
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
        doc: dict = {}
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                entry: dict = {"type": fam.kind, "help": fam.help,
                               "samples": []}
                for lv in sorted(fam._children):
                    child = fam._children[lv]
                    labels = dict(zip(fam.labelnames, lv))
                    if fam.kind == "histogram":
                        entry["samples"].append({
                            "labels": labels,
                            "buckets": [[(None if math.isinf(ub)
                                          else ub), c]
                                        for ub, c in
                                        child.cumulative_buckets()],
                            "sum": child._sum,
                            "count": child._count,
                        })
                    else:
                        entry["samples"].append(
                            {"labels": labels, "value": child._values})
                doc[name] = entry
        return doc

    def reset(self) -> None:
        """Drop every family (tests only -- a service registry is
        append-only for life)."""
        with self._lock:
            self._families.clear()
            self._collect_callbacks.clear()


REGISTRY = Registry()

# -- the instrument set the solver layers feed ---------------------------

SOLVES = REGISTRY.counter(
    "acg_solves_total", "Completed solve() calls by solver and outcome.",
    labelnames=("solver", "converged"))
ITERATIONS = REGISTRY.counter(
    "acg_iterations_total", "CG iterations executed across all solves.")
SOLVE_SECONDS = REGISTRY.histogram(
    "acg_solve_seconds", "Wall-clock seconds per solve.",
    buckets=SOLVE_SECONDS_BUCKETS)
SOLVE_ITERATIONS = REGISTRY.histogram(
    "acg_solve_iterations", "Iterations-to-converge per solve.",
    buckets=ITERATION_BUCKETS)
PHASE_SECONDS = REGISTRY.histogram(
    "acg_phase_seconds", "Pipeline-phase seconds "
    "(ingest/partition/transfer/compile/solve/writeback).",
    labelnames=("phase",), buckets=PHASE_SECONDS_BUCKETS)
COMPILES = REGISTRY.counter(
    "acg_compiles_total", "Compile phases observed (warmup-absorbed "
    "program compiles in the CLI and bench paths).")
BREAKDOWNS = REGISTRY.counter(
    "acg_breakdowns_total", "Breakdowns detected by the solve loops.")
RESTARTS = REGISTRY.counter(
    "acg_restarts_total", "Recovery restarts granted by the policy.")
FALLBACKS = REGISTRY.counter(
    "acg_fallbacks_total", "Transport/solver fallbacks taken.")
EVENTS = REGISTRY.counter(
    "acg_events_total", "Structured telemetry events by kind.",
    labelnames=("kind",))
HALO_BYTES = REGISTRY.counter(
    "acg_halo_bytes_total", "Halo-exchange payload bytes moved "
    "(static comm-ledger estimate x iterations).")
ALLREDUCE_BYTES = REGISTRY.counter(
    "acg_allreduce_bytes_total", "Allreduce/psum payload bytes moved "
    "(static comm-ledger estimate x iterations).")
RSS_BYTES = REGISTRY.gauge(
    "acg_process_resident_bytes", "Resident set size of this process.")
DEVICE_MEMORY = REGISTRY.gauge(
    "acg_device_memory_bytes", "Per-device memory where the backend "
    "reports it (jax memory_stats).", labelnames=("device", "kind"))
DRIFT_RATIO = REGISTRY.gauge(
    "acg_soak_latency_drift_ratio", "Soak driver: EWMA solve latency "
    "over the baseline window's (1.0 = no drift).")
PRECOND_APPLIES = REGISTRY.counter(
    "acg_precond_applies_total", "Preconditioner applies (analytic: "
    "one per iteration + setup; cheby bills its per-apply SpMVs).",
    labelnames=("kind",))
HEALTH_GAP = REGISTRY.gauge(
    "acg_health_residual_gap", "Latest in-loop true-residual audit "
    "gap ||r_true - r_rec||/||b|| (acg_tpu.health, --audit-every).")
HEALTH_KAPPA = REGISTRY.gauge(
    "acg_health_kappa_estimate", "Condition-number estimate of the "
    "(preconditioned) operator from the Lanczos tridiagonal of the "
    "last traced solve.")
HEALTH_AUDITS = REGISTRY.counter(
    "acg_health_audits_total", "In-loop true-residual audits "
    "performed across all solves.")
HEALTH_GAP_TRIPS = REGISTRY.counter(
    "acg_health_gap_trips_total", "Audit gaps past --gap-threshold "
    "(each one emitted an accuracy_degraded event).")
# survivability tier (acg_tpu.checkpoint): solver-state snapshots,
# resumes, and the recovery ladder's rollback rung
CKPT_SNAPSHOTS = REGISTRY.counter(
    "acg_ckpt_snapshots_total", "Solver-state snapshots committed "
    "(atomic-rename writes; --ckpt).")
CKPT_BYTES = REGISTRY.counter(
    "acg_ckpt_bytes_total", "Bytes written by committed snapshots.")
CKPT_WRITE_SECONDS = REGISTRY.histogram(
    "acg_ckpt_write_seconds", "Snapshot serialisation + atomic-rename "
    "seconds (billed to the 'ckpt' phase, excluded from solve "
    "latency).", buckets=PHASE_SECONDS_BUCKETS)
CKPT_RESUMES = REGISTRY.counter(
    "acg_ckpt_resumes_total", "Solves reconstructed from an on-disk "
    "snapshot (--resume).")
CKPT_ROLLBACKS = REGISTRY.counter(
    "acg_ckpt_rollbacks_total", "Breakdowns answered by rolling the "
    "loop carry back to the last snapshot (the recovery ladder's "
    "first rung).")
CKPT_REPARTITIONS = REGISTRY.counter(
    "acg_ckpt_repartition_resumes_total", "Shape-portable resumes: "
    "snapshots reassembled through the row-permutation sidecar onto "
    "a different partition or tier (--resume-repartition).")
# elastic-recovery tier (acg_tpu.supervisor, --supervise): child
# relaunches and time-to-recovery
RECOVERY_RELAUNCHES = REGISTRY.counter(
    "acg_recovery_relaunches_total", "Supervisor child relaunches by "
    "failure reason (crash/peer-lost/failure/backend).",
    labelnames=("reason",))
RECOVERY_MTTR = REGISTRY.histogram(
    "acg_recovery_mttr_seconds", "Seconds from the first failing "
    "child exit to the eventual converged run (--supervise; observed "
    "once per recovered incident).", buckets=SOLVE_SECONDS_BUCKETS)
RECOVERY_REGROWS = REGISTRY.counter(
    "acg_recovery_regrows_total", "Grow-on-recovery relaunches: a "
    "shrunken child healthy long enough was relaunched back toward "
    "the original mesh width (--grow-after).")
# solver-service tier (acg_tpu.serve, --serve): request accounting,
# the operator/program caches, and the admission-control ladder
SERVE_REQUESTS = REGISTRY.counter(
    "acg_serve_requests_total", "Requests answered by the solver "
    "service, by outcome (ok/error/shed/expired/invalid).",
    labelnames=("outcome",))
SERVE_CACHE_HITS = REGISTRY.counter(
    "acg_serve_cache_hits_total", "Serve cache hits (operator = "
    "ingested matrix + device planes; program = constructed solver "
    "whose jitted programs are compile-warm).", labelnames=("cache",))
SERVE_CACHE_MISSES = REGISTRY.counter(
    "acg_serve_cache_misses_total", "Serve cache misses (each one "
    "paid an ingest or a program construction + compile).",
    labelnames=("cache",))
SERVE_CACHE_EVICTIONS = REGISTRY.counter(
    "acg_serve_cache_evictions_total", "Serve cache LRU evictions.",
    labelnames=("cache",))
SERVE_CACHE_INVALIDATIONS = REGISTRY.counter(
    "acg_serve_cache_invalidations_total", "Serve cache entries "
    "dropped because a request poisoned them (request isolation).",
    labelnames=("cache",))
SERVE_SHED = REGISTRY.counter(
    "acg_serve_shed_total", "Requests refused by admission control, "
    "by reason (queue-full/slo-burn/deadline/shutdown).",
    labelnames=("reason",))
SERVE_COALESCED = REGISTRY.counter(
    "acg_serve_coalesced_total", "Requests served through a coalesced "
    "multi-RHS batched solve instead of singly.")
SERVE_DEGRADED = REGISTRY.counter(
    "acg_serve_degraded_total", "Requests served in degraded mode "
    "(the SLO-burn ladder downgraded the solve configuration).")
SERVE_WARM_RESTORES = REGISTRY.counter(
    "acg_serve_warm_restores_total", "Operator-cache entries "
    "re-ingested at daemon start from the persisted serve state "
    "(self-healing warm restore).")
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "acg_serve_queue_depth", "Requests currently queued in the "
    "solver service.")
SERVE_QUEUE_HIGH_WATER = REGISTRY.gauge(
    "acg_serve_queue_depth_high_water", "High-water mark of the serve "
    "request queue (worst backlog observed this process).")
SERVE_INFLIGHT = REGISTRY.gauge(
    "acg_serve_inflight", "Requests currently in flight in the solver "
    "service (admitted, not yet answered).")
SERVE_STAGE_SECONDS = REGISTRY.histogram(
    "acg_serve_stage_seconds", "Per-request stage seconds in the "
    "solver service (admit/queue-wait/coalesce/cache/compile/solve/"
    "demux/respond) -- the request observatory's tail-latency "
    "attribution.", labelnames=("stage",),
    buckets=PHASE_SECONDS_BUCKETS)
# ABFT checksum-protected SpMV (acg_tpu.health, --abft)
ABFT_CHECKS = REGISTRY.counter(
    "acg_abft_checks_total", "In-loop Huang-Abraham checksum "
    "verifications of the SpMV.")
ABFT_TRIPS = REGISTRY.counter(
    "acg_abft_trips_total", "Checksum mismatches past the ABFT "
    "threshold (silent SpMV corruption detected on device).")
ABFT_MISMATCH = REGISTRY.gauge(
    "acg_abft_mismatch_last", "Latest relative checksum mismatch "
    "|sum(Ax) - (c, x)| / scale.")
# timeline-tracing tier (acg_tpu.tracing): span-timeline recording and
# profiler-capture analysis
TRACE_SPANS = REGISTRY.counter(
    "acg_trace_spans_total", "Timeline spans/instants recorded by the "
    "span recorder (--timeline), by category.",
    labelnames=("cat",))
TRACE_EXPORTS = REGISTRY.counter(
    "acg_trace_exports_total", "Chrome trace-event timeline files "
    "written (--timeline).")
TRACE_OP_SECONDS = REGISTRY.gauge(
    "acg_trace_op_seconds", "Measured per-op-class device seconds "
    "from the last analyzed --trace capture.", labelnames=("op",))
TRACE_OVERLAP = REGISTRY.gauge(
    "acg_trace_overlap_efficiency", "Fraction of collective device "
    "time hidden under compute in the last analyzed capture (1.0 = "
    "fully overlapped; absent collectives leave the gauge untouched).")
TRACE_EXPOSED_SECONDS = REGISTRY.gauge(
    "acg_trace_exposed_collective_seconds", "Collective device time "
    "NOT overlapped by compute in the last analyzed capture.")
# communication observatory (acg_tpu.commbench, --commbench): fitted
# alpha-beta per collective kind and the measured segment split
COMMBENCH_RUNS = REGISTRY.counter(
    "acg_commbench_runs_total", "Completed --commbench microbenchmark "
    "suites (collective sweeps + segment decomposition).")
COMMBENCH_ALPHA = REGISTRY.gauge(
    "acg_commbench_alpha_seconds", "Fitted per-collective latency "
    "alpha from the last commbench run (t = alpha + beta * bytes).",
    labelnames=("kind",))
COMMBENCH_BETA = REGISTRY.gauge(
    "acg_commbench_beta_seconds_per_byte", "Fitted per-collective "
    "inverse bandwidth beta from the last commbench run.",
    labelnames=("kind",))
COMMBENCH_SEGMENT = REGISTRY.gauge(
    "acg_commbench_segment_seconds", "Measured per-iteration segment "
    "seconds (spmv / halo / reduction) from the last commbench "
    "segment decomposition.", labelnames=("segment",))
# live-observatory tier (acg_tpu.observatory, --slo): declared
# service-level objectives and their error-budget burn
SLO_TARGET = REGISTRY.gauge(
    "acg_slo_target", "Declared per-solve service-level objective "
    "targets (--slo latency=S,iters=N,gap=G).",
    labelnames=("objective",))
SLO_BREACHES = REGISTRY.counter(
    "acg_slo_breaches_total", "Completed solves that breached a "
    "declared objective (each breach also emits an slo-breach event).",
    labelnames=("objective",))
SLO_BURN = REGISTRY.gauge(
    "acg_slo_burn_ratio", "Fraction of observed solves breaching each "
    "declared objective (cumulative error-budget burn; 0 = none, "
    "1 = every solve).", labelnames=("objective",))
# decision observatory (acg_tpu.planner, --autotune): how programs
# were chosen and how honest the cost model's predictions are
PLAN_DECISIONS = REGISTRY.counter(
    "acg_plan_decisions_total", "Program-selection decisions by "
    "provenance: planned (cost-model chose), flag-forced (caller "
    "overrode), fallback (degraded/probe-failed path).",
    labelnames=("source",))
PLAN_MISPREDICTION = REGISTRY.gauge(
    "acg_plan_misprediction_ratio", "Predicted / measured "
    "seconds-per-solve of the last planned solve (1.0 = the cost "
    "model was exactly right; drives self-correction).")

_armed = False


def arm() -> None:
    """Arm the process-wide hooks.  All recording is host-side
    bookkeeping, so arming cannot perturb the compiled programs; the
    hooks stay cheap early-returns until this is called."""
    global _armed
    _armed = True
    REGISTRY.on_collect(update_resource_gauges)


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def record_solve(seconds: float, iterations: int, converged: bool,
                 solver: str = "cg") -> None:
    """One completed solve (called from the solvers' solve() tails).
    Also closes out the live-observatory status document's in-flight
    solve (its own arm gate; no-op disarmed)."""
    from acg_tpu import observatory
    observatory.end_solve(bool(converged), int(iterations),
                          float(seconds))
    if not _armed:
        return
    SOLVES.labels(solver=solver,
                  converged="true" if converged else "false").inc()
    ITERATIONS.inc(max(int(iterations), 0))
    SOLVE_SECONDS.observe(max(float(seconds), 0.0))
    SOLVE_ITERATIONS.observe(max(int(iterations), 0))


def record_phase(name: str, seconds: float) -> None:
    """One pipeline-phase timing (fed from telemetry's phase timer and
    the solvers' add_timing); a compile phase also counts a compile."""
    if not _armed:
        return
    PHASE_SECONDS.labels(phase=str(name)).observe(max(float(seconds),
                                                      0.0))
    if name == "compile":
        COMPILES.inc()


def record_event_kind(kind: str) -> None:
    if not _armed:
        return
    EVENTS.labels(kind=str(kind)).inc()


def record_breakdown() -> None:
    if _armed:
        BREAKDOWNS.inc()


def record_restart() -> None:
    if _armed:
        RESTARTS.inc()


def record_fallback() -> None:
    if _armed:
        FALLBACKS.inc()


def record_precond(kind: str, applies: int) -> None:
    """One solve's preconditioner applies (the PCG tier's solve()
    tails, acg_tpu.precond)."""
    if _armed:
        PRECOND_APPLIES.labels(kind=str(kind)).inc(max(int(applies), 0))


def record_health_audit(gap, naudits: int) -> None:
    """One solve's audit summary (the numerical-health tier's solve()
    tails): the latest finite gap lands on the gauge, the audit count
    on the counter."""
    if not _armed:
        return
    if gap is not None and math.isfinite(float(gap)):
        HEALTH_GAP.set(float(gap))
    HEALTH_AUDITS.inc(max(int(naudits), 0))


def record_rollback() -> None:
    if _armed:
        CKPT_ROLLBACKS.inc()


def record_snapshot(nbytes: int, seconds: float) -> None:
    """One committed solver-state snapshot (the chunk drivers' write
    tails, acg_tpu.checkpoint)."""
    if not _armed:
        return
    CKPT_SNAPSHOTS.inc()
    CKPT_BYTES.inc(max(int(nbytes), 0))
    CKPT_WRITE_SECONDS.observe(max(float(seconds), 0.0))


def record_resume() -> None:
    if _armed:
        CKPT_RESUMES.inc()


def record_repartition() -> None:
    if _armed:
        CKPT_REPARTITIONS.inc()


def record_relaunch(reason: str) -> None:
    """One supervisor child relaunch (--supervise), by failure
    reason."""
    if _armed:
        RECOVERY_RELAUNCHES.labels(reason=str(reason)).inc()


def record_recovery_mttr(seconds: float) -> None:
    """One recovered incident's mean-time-to-recovery observation:
    first failing child exit -> eventual converged run."""
    if _armed:
        RECOVERY_MTTR.observe(max(float(seconds), 0.0))


def record_regrow() -> None:
    """One grow-on-recovery relaunch (--supervise --grow-after): a
    shrunken-but-healthy child relaunched toward the original width."""
    if _armed:
        RECOVERY_REGROWS.inc()


def record_serve_request(outcome: str) -> None:
    if _armed:
        SERVE_REQUESTS.labels(outcome=str(outcome)).inc()


def record_serve_cache(event: str, cache: str) -> None:
    """One serve-cache event: ``event`` in hit/miss/evict/invalidate,
    ``cache`` in operator/program."""
    if not _armed:
        return
    fam = {"hit": SERVE_CACHE_HITS, "miss": SERVE_CACHE_MISSES,
           "evict": SERVE_CACHE_EVICTIONS,
           "invalidate": SERVE_CACHE_INVALIDATIONS}[event]
    fam.labels(cache=str(cache)).inc()


def record_serve_shed(reason: str) -> None:
    if _armed:
        SERVE_SHED.labels(reason=str(reason)).inc()


def record_serve_coalesced(nrequests: int) -> None:
    if _armed:
        SERVE_COALESCED.inc(max(int(nrequests), 0))


def record_serve_degraded() -> None:
    if _armed:
        SERVE_DEGRADED.inc()


def record_serve_warm_restore(nentries: int) -> None:
    if _armed:
        SERVE_WARM_RESTORES.inc(max(int(nentries), 0))


_serve_queue_high_water = 0


def record_serve_queue_depth(depth: int) -> None:
    global _serve_queue_high_water
    if _armed:
        d = max(int(depth), 0)
        SERVE_QUEUE_DEPTH.set(d)
        if d > _serve_queue_high_water:
            _serve_queue_high_water = d
            SERVE_QUEUE_HIGH_WATER.set(d)


def record_serve_inflight(n: int) -> None:
    if _armed:
        SERVE_INFLIGHT.set(max(int(n), 0))


def record_serve_stage(stage: str, seconds: float) -> None:
    """One per-request stage observation (acg_tpu.reqtrace)."""
    if _armed:
        SERVE_STAGE_SECONDS.labels(stage=str(stage)).observe(
            max(float(seconds), 0.0))


def record_abft(nchecks: int, rel_last, ntrips: int) -> None:
    """One solve attempt's ABFT summary (fed from health.note_audit)."""
    if not _armed:
        return
    ABFT_CHECKS.inc(max(int(nchecks), 0))
    ABFT_TRIPS.inc(max(int(ntrips), 0))
    if rel_last is not None and math.isfinite(float(rel_last)):
        ABFT_MISMATCH.set(float(rel_last))


def record_health_kappa(kappa: float) -> None:
    if _armed and kappa and math.isfinite(float(kappa)):
        HEALTH_KAPPA.set(float(kappa))


def record_gap_trip() -> None:
    if _armed:
        HEALTH_GAP_TRIPS.inc()


def record_trace_span(cat: str) -> None:
    """One recorded timeline span/instant (acg_tpu.tracing)."""
    if _armed:
        TRACE_SPANS.labels(cat=str(cat)).inc()


def record_timeline_export() -> None:
    if _armed:
        TRACE_EXPORTS.inc()


def record_trace_analysis(analysis: dict) -> None:
    """One --trace capture analysis: per-op-class measured seconds on
    the gauges, overlap efficiency where collectives were measured."""
    if not _armed or not analysis.get("available"):
        return
    for cls, secs in analysis.get("op_seconds", {}).items():
        TRACE_OP_SECONDS.labels(op=str(cls)).set(float(secs))
    eff = analysis.get("overlap_efficiency")
    if eff is not None and math.isfinite(float(eff)):
        TRACE_OVERLAP.set(float(eff))
        TRACE_EXPOSED_SECONDS.set(
            float(analysis.get("exposed_collective_seconds", 0.0)))


def record_slo_target(objective: str, target: float) -> None:
    """One declared objective's target gauge (observatory.install_slo:
    a scrape shows what the run promised before the first solve)."""
    if _armed:
        SLO_TARGET.labels(objective=str(objective)).set(float(target))


def record_slo(objective: str, breached: bool, burn: float) -> None:
    """One judged objective after a completed solve: the breach counter
    and the cumulative burn-fraction gauge (observatory.slo_observe)."""
    if not _armed:
        return
    if breached:
        SLO_BREACHES.labels(objective=str(objective)).inc()
    SLO_BURN.labels(objective=str(objective)).set(float(burn))


def record_comm(ledger: dict, iterations: int) -> None:
    """Fold one solve's communication volume out of the perfmodel
    tier's static ledger: per-iteration halo/psum bytes x the solve's
    iteration count."""
    if not _armed or not ledger:
        return
    its = max(int(iterations), 0)
    HALO_BYTES.inc(int(ledger.get("halo_bytes_per_iteration", 0)) * its)
    ALLREDUCE_BYTES.inc(
        int(ledger.get("allreduce_bytes_per_iteration", 0)) * its)


def observe_solver_comm(solver, iterations: int) -> None:
    """``record_comm`` from a solver's own ``comm_profile()`` hook
    (PR 3); solvers without one are a no-op."""
    if not _armed:
        return
    prof = getattr(solver, "comm_profile", None)
    if prof is None:
        return
    try:
        record_comm(prof(), iterations)
    except Exception:  # noqa: BLE001 -- metrics must never sink a solve
        pass


def record_commbench(doc: dict) -> None:
    """Fold one commbench document into the registry: alpha/beta per
    fitted collective kind plus the measured segment split (no-op
    disarmed, like every recorder here)."""
    if not _armed or not isinstance(doc, dict):
        return
    COMMBENCH_RUNS.inc()
    for kind, fit in (doc.get("collectives") or {}).items():
        if isinstance(fit, dict) and "alpha_s" in fit:
            COMMBENCH_ALPHA.labels(str(kind)).set(float(fit["alpha_s"]))
            COMMBENCH_BETA.labels(str(kind)).set(
                float(fit.get("beta_s_per_byte", 0.0)))
    segs = (doc.get("segments") or {})
    for name, seg in (segs.get("segments") or {}).items():
        try:
            COMMBENCH_SEGMENT.labels(str(name)).set(
                float(seg["s_per_iteration"]))
        except (KeyError, TypeError, ValueError):
            continue


def record_plan_decision(source: str) -> None:
    """One program-selection decision: ``planned`` | ``flag-forced`` |
    ``fallback`` (no-op disarmed)."""
    if not _armed:
        return
    PLAN_DECISIONS.labels(str(source)).inc()


def record_plan_misprediction(ratio: float) -> None:
    """Predicted/measured seconds-per-solve of one planned solve."""
    if not _armed:
        return
    try:
        r = float(ratio)
    except (TypeError, ValueError):
        return
    if r > 0 and math.isfinite(r):
        PLAN_MISPREDICTION.set(r)


def update_resource_gauges() -> None:
    """Refresh RSS and (where the backend reports memory_stats) the
    per-device memory gauges; registered as a collect callback so every
    scrape/flush sees fresh values."""
    try:
        with open("/proc/self/statm") as f:
            RSS_BYTES.set(int(f.read().split()[1])
                          * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import jax

        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue  # CPU backend reports none
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    DEVICE_MEMORY.labels(device=str(d.id),
                                         kind=key).set(stats[key])
    except Exception:  # noqa: BLE001 -- no backend is a fine state for
        pass           # a metrics scrape


# -- sinks ----------------------------------------------------------------

def expose() -> str:
    return REGISTRY.expose()


def snapshot_dict() -> dict:
    return REGISTRY.snapshot()


def write_textfile(path, registry: Registry | None = None) -> None:
    """Atomic textfile flush (write sibling temp + rename): a scraper
    of ``--metrics-file`` output never reads a torn write -- the
    node-exporter textfile-collector contract."""
    reg = registry or REGISTRY
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(reg.expose())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_flush_path: str | None = None
_flush_installed = False


def _flush_now() -> None:
    if _flush_path is None:
        return
    try:
        write_textfile(_flush_path)
    except OSError as e:
        sys.stderr.write(f"acg-tpu: --metrics-file {_flush_path}: "
                         f"{e}\n")


def install_flush_handlers(path) -> None:
    """Arrange for ``--metrics-file`` to be written on normal exit AND
    on SIGTERM (a soak run killed by an orchestrator must still leave
    its final scrape behind).  The SIGTERM handler chains to whatever
    was installed before it, preserving the prior exit semantics."""
    global _flush_path, _flush_installed
    _flush_path = os.fspath(path)
    if _flush_installed:
        return
    _flush_installed = True
    atexit.register(_flush_now)
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            _flush_now()
            if prev == signal.SIG_IGN:
                return  # the run was ignoring SIGTERM; keep it alive
            if callable(prev) and prev != signal.SIG_DFL:
                prev(signum, frame)
            else:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        # not the main thread: atexit still covers the normal path
        pass


def serve(port: int, registry: Registry | None = None):
    """Serve ``GET /metrics`` on a daemon thread (``--metrics-port``):
    stdlib only, bound on all interfaces like every Prometheus
    exporter.  Returns the live server (``.server_address[1]`` is the
    real port -- pass 0 to let the OS pick, the test hook)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry or REGISTRY

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 -- stdlib handler contract
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = reg.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer(("", int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="acg-metrics", daemon=True)
    t.start()
    return server
