"""ICI communication observatory: mesh collective microbenchmarks,
a measured alpha-beta (latency / inverse-bandwidth) calibration, and
measured segment decomposition of the iteration time.

The reference's whole comm-strategy argument (CPU- vs GPU-initiated,
NCCL vs NVSHMEM, SURVEY.md section 2) is justified by MEASURED transfer
latencies; our ``--explain`` roofline and the fused tier's overlap
pricing have so far run on estimates -- ring-hop counts from the mesh
shape and one host triad probe standing in for ICI bandwidth
(perfmodel.ICI_GBS, explicitly a stand-in).  This module is the
calibration step the s-step/pipelining literature assumes before any
latency-hiding claim (Ghysels-Vanroose; PAPERS.md arXiv 2501.03743):

* **Collective microbenchmarks** run over the solver's own mesh --
  psum/all_reduce scalar latency, ``all_to_all`` and
  ``collective_permute`` bandwidth sweeps across message sizes, and the
  one-sided ``halo_dma`` systolic exchange including PER-EDGE put/wait
  timing by ring distance (a globally-uniform count gate per rotation
  round, so the interpret-mode emulation's op pairing holds) -- each
  kind fitted to ``t = alpha + beta * bytes``.
* **Measured segment decomposition**: SpMV-only / halo-only /
  reduction-only probe programs built from the SAME TierOps composition
  the recurrence builder dispatches (``recurrence.build_*_segment_
  probes`` -- the ``lower_solve`` discipline: same SpMV selection, same
  psum ladder), each run for K chained repetitions inside one dispatch,
  so a measured s/iter splits into measured segments instead of
  replayed op estimates.
* **The calibration document**: an ``acg-tpu-commbench/1`` JSON doc
  (``--commbench FILE``) with a content-hashed ``calibration_id``,
  validated by :func:`validate_commbench` and consumed by
  ``--explain --calibration FILE`` (perfmodel prices comm from the
  fitted alpha-beta instead of ring-hop guesses), by the fused tier's
  exposed-halo overlap pricing, and -- as a provenance key -- by the
  stats-json manifest / convergence-log meta line / bench_diff case
  keying, so differently-calibrated captures never diff silently.

Everything here is an analysis pass: nothing mutates solver state, and
with the observatory disarmed every dispatched solver program stays
byte-identical (pinned in tests/test_commbench.py alongside the
perfmodel/metrics/tracing pins).  jax imports stay inside functions --
the validator and the bench_diff keying must answer without
initialising a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

import numpy as np

COMMBENCH_SCHEMA = "acg-tpu-commbench/1"

# the provenance value a run without a calibration records in its
# stats-json manifest / convergence-log meta line; bench_diff keys only
# on REAL ids, so uncalibrated captures keep diffing against old ones
UNCALIBRATED = "uncalibrated"

# collective kinds the suite benchmarks -- the SAME kind names
# tracing.analyze_trace's per-kind breakdown reports, so a fit can be
# confronted with a capture kind by kind
KINDS = ("all_reduce", "all_to_all", "collective_permute", "dma")

# message-size sweeps (payload bytes per shard).  The CPU sweep keeps
# the 8-part interpret-mode CI smoke under seconds; the TPU sweep
# reaches into the bandwidth-dominated regime where beta is resolvable
CPU_SWEEP = (256, 8192, 131072)
TPU_SWEEP = (256, 4096, 65536, 1048576, 8388608)

# chained collective rounds per timed dispatch (amortises dispatch
# latency out of the per-round figure) and timing repeats (min-of)
DEFAULT_REPS = 24
TIMED_REPEATS = 3
SEGMENT_REPS = 16


# -- the alpha-beta fit ---------------------------------------------------

def fit_alpha_beta(points) -> dict | None:
    """Least-squares fit of ``t = alpha + beta * bytes`` over
    ``[(bytes, seconds), ...]`` with both coefficients clamped
    nonnegative (a negative latency or inverse bandwidth is a
    measurement artifact; the clamped refit keeps the other coefficient
    honest).  Returns ``{"alpha_s", "beta_s_per_byte", "npoints",
    "r2"}`` or None when nothing usable was measured."""
    pts = [(float(b), float(s)) for b, s in points
           if s > 0 and b >= 0 and np.isfinite(s) and np.isfinite(b)]
    if not pts:
        return None
    x = np.asarray([p[0] for p in pts], dtype=np.float64)
    y = np.asarray([p[1] for p in pts], dtype=np.float64)
    if len(pts) == 1 or np.ptp(x) == 0:
        b0, s0 = float(x[0]), float(np.min(y))
        return {"alpha_s": s0 if b0 == 0 else 0.0,
                "beta_s_per_byte": (s0 / b0) if b0 > 0 else 0.0,
                "npoints": len(pts), "r2": None}
    A = np.stack([np.ones_like(x), x], axis=1)
    coef, *_ = np.linalg.lstsq(A, y, rcond=None)
    alpha, beta = float(coef[0]), float(coef[1])
    if beta < 0.0:
        # bandwidth buried in noise: pure-latency fit
        alpha, beta = float(np.mean(y)), 0.0
    elif alpha < 0.0:
        # latency buried in noise: pure-bandwidth fit through origin
        alpha, beta = 0.0, float((x @ y) / (x @ x))
    pred = alpha + beta * x
    ss_res = float(np.sum((y - pred) ** 2))
    ss_tot = float(np.sum((y - np.mean(y)) ** 2))
    r2 = (1.0 - ss_res / ss_tot) if ss_tot > 0 else None
    return {"alpha_s": alpha, "beta_s_per_byte": beta,
            "npoints": len(pts),
            "r2": (round(r2, 6) if r2 is not None else None)}


def predict_seconds(fit, nbytes) -> float | None:
    """``alpha + beta * bytes`` for one fitted kind; None when the fit
    is absent/unusable."""
    if not isinstance(fit, dict) or "alpha_s" not in fit:
        return None
    return (float(fit["alpha_s"])
            + float(fit.get("beta_s_per_byte", 0.0))
            * max(float(nbytes), 0.0))


# -- timing ---------------------------------------------------------------

def _time_dispatch(runner, repeats: int = TIMED_REPEATS) -> float:
    """Min-of-``repeats`` wall seconds of one synced dispatch of
    ``runner`` (the runner must return a device value to block on).
    The first (untimed) call absorbs the compile."""
    from acg_tpu._platform import device_sync

    device_sync(runner())
    ts = []
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        device_sync(runner())
        ts.append(time.perf_counter() - t0)
    return min(ts)


# -- collective microbenchmark programs -----------------------------------

def _collective_program(mesh, kind: str, nbytes: int, reps: int):
    """One benchmark program: ``reps`` CHAINED rounds of one collective
    over the mesh's parts axis inside a single jitted shard_map dispatch
    (each round's input is the previous round's output, so XLA can
    neither elide nor reorder rounds).  Returns ``(runner,
    bytes_per_shard)`` -- the realised per-shard payload, which is what
    the alpha-beta fit is over."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from acg_tpu._platform import shard_map as _sm
    from acg_tpu.parallel.mesh import PARTS_AXIS

    nparts = int(mesh.shape[PARTS_AXIS])
    item = 4  # f32 payloads throughout -- the solve vectors' dtype class
    if kind == "all_reduce":
        m = max(int(nbytes) // item, 1)
        shape = (m,)
        scale = jnp.float32(1.0 / nparts)

        def round_(v):
            # psum of identical shards = nparts * v; the rescale keeps
            # the chained value exactly 1.0 (1/8 etc. are exact in f32)
            return lax.psum(v, PARTS_AXIS) * scale
        payload = m * item
    elif kind == "all_to_all":
        m = max(int(nbytes) // (item * nparts), 1)
        shape = (nparts, m)

        def round_(v):
            return lax.all_to_all(v, PARTS_AXIS, 0, 0)
        payload = nparts * m * item
    elif kind == "collective_permute":
        m = max(int(nbytes) // item, 1)
        shape = (m,)
        perm = [(i, (i + 1) % nparts) for i in range(nparts)]

        def round_(v):
            return lax.ppermute(v, PARTS_AXIS, perm)
        payload = m * item
    else:
        raise ValueError(f"unknown collective kind {kind!r}")

    def body(vs):
        v = vs[0]
        v = lax.fori_loop(0, int(reps), lambda i, v: round_(v), v)
        return v[None]

    prog = jax.jit(_sm(body, mesh=mesh, in_specs=P(PARTS_AXIS),
                       out_specs=P(PARTS_AXIS)))
    x = jax.device_put(np.ones((nparts,) + shape, np.float32),
                       NamedSharding(mesh, P(PARTS_AXIS)))
    return (lambda: prog(x)), payload


def bench_collectives(mesh, sizes_bytes, reps: int = DEFAULT_REPS,
                      repeats: int = TIMED_REPEATS) -> dict:
    """Sweep the XLA collective kinds across message sizes on the mesh;
    one ``{"alpha_s", "beta_s_per_byte", ..., "points": [...]}`` entry
    per kind."""
    out: dict = {}
    for kind in ("all_reduce", "all_to_all", "collective_permute"):
        points = []
        for nbytes in sizes_bytes:
            runner, payload = _collective_program(mesh, kind,
                                                  int(nbytes), reps)
            secs = _time_dispatch(runner, repeats) / reps
            points.append({"bytes": int(payload),
                           "seconds": float(secs)})
        fit = fit_alpha_beta([(p["bytes"], p["seconds"])
                              for p in points]) or {}
        out[kind] = {**fit, "points": points}
    return out


def _dma_counts(nparts: int, maxcnt: int,
                distance: int | None) -> np.ndarray:
    """The per-neighbour count matrix of a benchmark exchange:
    ``distance=None`` is the dense systolic exchange; a ring distance d
    gates the puts to distance-d pairs only -- a gate that is globally
    UNIFORM per rotation round, which is exactly the pattern the
    interpret-mode DMA emulation supports (halo_dma module docs)."""
    cnt = np.zeros((nparts, nparts), np.int32)
    for p in range(nparts):
        for q in range(nparts):
            if p == q:
                continue
            d = min((q - p) % nparts, (p - q) % nparts)
            if distance is None or d == int(distance):
                cnt[p, q] = maxcnt
    return cnt


def _dma_program(mesh, maxcnt: int, reps: int, interpret: bool,
                 distance: int | None = None):
    """``reps`` chained one-sided halo_dma exchanges of a
    ``(nparts, maxcnt)`` f32 window plane (the put-with-signal systolic
    schedule itself, no pack/unpack).  Returns ``(runner,
    bytes_per_shard, peers_per_shard)``."""
    import jax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from acg_tpu._platform import shard_map as _sm
    from acg_tpu.parallel.halo_dma import dma_exchange
    from acg_tpu.parallel.mesh import PARTS_AXIS

    nparts = int(mesh.shape[PARTS_AXIS])
    cnt = _dma_counts(nparts, int(maxcnt), distance)
    peers = int((cnt[0] > 0).sum())
    gated = distance is not None

    def body(sb, sc, rc):
        sb, sc, rc = sb[0], sc[0], rc[0]

        def round_(i, buf):
            return dma_exchange(buf, sc, rc, axis=PARTS_AXIS,
                                interpret=interpret,
                                gate_by_counts=True if gated else None)
        out = lax.fori_loop(0, int(reps), round_, sb)
        return out[None]

    pspec = P(PARTS_AXIS)
    prog = jax.jit(_sm(body, mesh=mesh, in_specs=(pspec,) * 3,
                       out_specs=pspec))
    sh = NamedSharding(mesh, pspec)
    sb = jax.device_put(np.ones((nparts, nparts, maxcnt), np.float32),
                        sh)
    # row p of the stacked count arrays is shard p's per-neighbour
    # view: what it sends to each q, and what it receives from each q
    sc = jax.device_put(np.ascontiguousarray(cnt), sh)
    rc = jax.device_put(np.ascontiguousarray(cnt.T), sh)
    return (lambda: prog(sb, sc, rc)), peers * maxcnt * 4, peers


def bench_dma(mesh, sizes_bytes, reps: int = DEFAULT_REPS,
              repeats: int = TIMED_REPEATS,
              interpret: bool | None = None) -> dict:
    """The one-sided transport's bandwidth sweep: dense systolic
    exchanges across window sizes, fitted alpha-beta over the per-shard
    outgoing bytes."""
    import jax

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    from acg_tpu.parallel.mesh import PARTS_AXIS
    nparts = int(mesh.shape[PARTS_AXIS])
    points = []
    for nbytes in sizes_bytes:
        maxcnt = max(int(nbytes) // (4 * max(nparts - 1, 1)), 1)
        runner, payload, _ = _dma_program(mesh, maxcnt, reps, interpret)
        secs = _time_dispatch(runner, repeats) / reps
        points.append({"bytes": int(payload), "seconds": float(secs)})
    fit = fit_alpha_beta([(p["bytes"], p["seconds"])
                          for p in points]) or {}
    return {**fit, "points": points,
            "interpret": bool(interpret)}


def bench_dma_edges(mesh, window_bytes: int,
                    reps: int = DEFAULT_REPS,
                    repeats: int = TIMED_REPEATS,
                    interpret: bool | None = None) -> list[dict]:
    """PER-EDGE one-sided put/wait timing by ring distance: one gated
    exchange per distance d (every shard puts one window_bytes window
    to its distance-d peer(s) and waits the matching receives) -- the
    on-silicon transport validation row PR 13 left open, measured here
    wherever the transport runs (interpret mode on CPU meshes, compiled
    puts on TPU)."""
    import jax

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    from acg_tpu.parallel.mesh import PARTS_AXIS
    nparts = int(mesh.shape[PARTS_AXIS])
    maxcnt = max(int(window_bytes) // 4, 1)
    rows = []
    for d in range(1, nparts // 2 + 1):
        runner, payload, peers = _dma_program(mesh, maxcnt, reps,
                                              interpret, distance=d)
        secs = _time_dispatch(runner, repeats) / reps
        rows.append({"distance": int(d),
                     "window_bytes": int(maxcnt * 4),
                     "peers_per_shard": int(peers),
                     "put_wait_seconds": float(secs)})
    return rows


# -- measured segment decomposition ---------------------------------------

def segment_decomposition(solver, b, reps: int = SEGMENT_REPS,
                          repeats: int = TIMED_REPEATS) -> dict:
    """Measured SpMV-only / halo-only / reduction-only segments of the
    solver's iteration: probe programs built from the SAME TierOps
    composition the recurrence builder dispatches (recurrence.build_*_
    segment_probes), each run ``reps`` chained times inside one
    dispatch.  The halo segment is CONTAINED in the SpMV segment (the
    dispatched SpMV embeds the exchange), so the explained s/iter is
    ``spmv + reduction``; whatever the measured s/iter holds beyond
    that is the axpy/control remainder.  Degrades to ``{"available":
    False, "why": ...}`` -- a probe failure must never sink an explain
    pass."""
    from acg_tpu import recurrence

    try:
        if getattr(solver, "problem", None) is not None:
            probes = recurrence.build_dist_segment_probes(solver, b,
                                                          reps)
        else:
            probes = recurrence.build_single_segment_probes(solver, b,
                                                            reps)
    except Exception as e:  # noqa: BLE001 -- observability degrades
        return {"available": False,
                "why": f"{type(e).__name__}: {e}"}
    segs: dict = {}
    try:
        for name, runner, calls in probes:
            secs = _time_dispatch(runner, repeats) / reps
            segs[name] = {"s_per_call": float(secs),
                          "calls_per_iteration": float(calls),
                          "s_per_iteration": float(secs) * float(calls)}
    except Exception as e:  # noqa: BLE001
        return {"available": False,
                "why": f"{type(e).__name__}: {e}"}
    explained = sum(v["s_per_iteration"] for k, v in segs.items()
                    if k != "halo")
    return {"available": True, "reps": int(reps),
            "segments": segs,
            "explained_s_per_iteration": float(explained),
            "note": "halo is contained in the spmv segment; "
                    "explained = spmv + reduction"}


# -- the calibration document ---------------------------------------------

def calibration_id(doc: dict) -> str:
    """Content-hashed id: any edit to the measurements produces a
    different id, so two captures keyed by it can never silently claim
    the same calibration."""
    payload = {k: v for k, v in doc.items() if k != "calibration_id"}
    h = hashlib.sha256(json.dumps(payload, sort_keys=True,
                                  default=str).encode()).hexdigest()
    backend = "x"
    man = doc.get("manifest")
    if isinstance(man, dict) and isinstance(man.get("backend"), dict):
        backend = str(man["backend"].get("platform", "x"))
    return f"cb-{backend}-{int(doc.get('nparts', 0))}p-{h[:10]}"


def _num(v) -> float | None:
    """Coerce a JSON value to a finite float, or None -- the validator
    must REPORT a malformed value, never raise on one."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    v = float(v)
    return v if np.isfinite(v) else None


def validate_commbench(doc) -> list[str]:
    """Problems with a commbench document (empty list = valid): schema,
    id integrity (content hash must match -- a hand-edited doc must not
    pass as the measurement it no longer is), and per-kind fit/point
    sanity.  Every check is type-defensive: a malformed value becomes a
    named problem, never an exception (rejecting such docs gracefully
    is this function's whole job)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["not a JSON object"]
    if doc.get("schema") != COMMBENCH_SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, expected "
                        f"{COMMBENCH_SCHEMA!r}")
        return problems
    cid = doc.get("calibration_id")
    if not isinstance(cid, str) or not cid:
        problems.append("missing calibration_id")
    elif cid != calibration_id(doc):
        problems.append("calibration_id does not match the document "
                        "content (edited after capture?)")
    nparts = doc.get("nparts")
    if not isinstance(nparts, int) or isinstance(nparts, bool) \
            or nparts < 1:
        problems.append(f"nparts must be a positive int "
                        f"(got {nparts!r})")
    colls = doc.get("collectives")
    if not isinstance(colls, dict) or not colls:
        problems.append("missing collectives section")
        return problems
    fitted = 0
    for kind, entry in colls.items():
        if kind not in KINDS:
            problems.append(f"unknown collective kind {kind!r}")
            continue
        if not isinstance(entry, dict):
            problems.append(f"{kind}: not an object")
            continue
        if "alpha_s" not in entry:
            continue  # an unfitted kind is allowed (e.g. dma skipped)
        alpha = _num(entry["alpha_s"])
        beta = _num(entry.get("beta_s_per_byte", 0.0))
        if alpha is None or beta is None or alpha < 0 or beta < 0:
            problems.append(f"{kind}: alpha/beta not nonnegative "
                            f"numbers")
        pts = entry.get("points")
        if not isinstance(pts, list) or not pts:
            problems.append(f"{kind}: fitted without points")
        else:
            for p in pts:
                nb = _num(p.get("bytes")) if isinstance(p, dict) \
                    else None
                sec = _num(p.get("seconds")) if isinstance(p, dict) \
                    else None
                if nb is None or sec is None or nb < 0 or sec <= 0:
                    problems.append(f"{kind}: bad point {p!r}")
                    break
        fitted += 1
    if not fitted:
        problems.append("no fitted collective kinds")
    edges = doc.get("edges") or []
    if not isinstance(edges, list):
        problems.append("edges is not a list")
        edges = []
    for row in edges:
        d = _num(row.get("distance")) if isinstance(row, dict) else None
        sec = (_num(row.get("put_wait_seconds"))
               if isinstance(row, dict) else None)
        if d is None or sec is None or d < 1 or sec <= 0:
            problems.append(f"bad edge row {row!r}")
            break
    return problems


def load_calibration(path) -> dict:
    """Read + validate a saved commbench document; raises ValueError
    with every problem named (the --calibration refusal text)."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise ValueError(f"not JSON ({e})")
    problems = validate_commbench(doc)
    if problems:
        raise ValueError("not a valid acg-tpu-commbench/1 document: "
                         + "; ".join(problems))
    return doc


def write_document(doc: dict, dest) -> None:
    """Write the doc to a path (``"-"`` = stdout)."""
    if dest in (None, "-"):
        json.dump(doc, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return
    with open(dest, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


# -- calibrated comm pricing ----------------------------------------------

def _halo_fit(cal: dict, led: dict) -> tuple[dict | None, str]:
    """``(fit, kind_used)`` for the ledger's halo transport: the dma
    fit when the one-sided transport is armed AND was benchmarked,
    falling back to the all_to_all fit -- ``kind_used`` names the fit
    actually applied, so provenance never claims a fit that was not
    there."""
    kinds = cal.get("collectives", {})
    kind = "dma" if led.get("transport") == "dma" else "all_to_all"
    fit = kinds.get(kind)
    if not isinstance(fit, dict) or "alpha_s" not in fit:
        kind, fit = "all_to_all", kinds.get("all_to_all")
    if not isinstance(fit, dict) or "alpha_s" not in fit:
        return None, kind
    return fit, kind


def halo_exchange_seconds(cal: dict, led: dict) -> float | None:
    """Seconds of ONE halo exchange priced from the calibration's
    fitted transport kind (``dma`` when the ledger armed the one-sided
    transport and the dma kind was benchmarked, else ``all_to_all``),
    over the PADDED per-shard plane the transport actually moves
    (``halo_plane_bytes_per_exchange``; the unpadded per-edge totals
    are a lower bound the wire never sees)."""
    if not led.get("halo_bytes_per_iteration"):
        return 0.0
    fit, _kind = _halo_fit(cal, led)
    nb = led.get("halo_plane_bytes_per_exchange")
    if nb is None:
        nb = (led.get("halo_bytes_per_iteration", 0)
              / max(int(led.get("nparts", 1)), 1))
    return predict_seconds(fit, nb)


def comm_seconds(cal: dict, led: dict) -> dict | None:
    """Per-iteration communication seconds priced from the fitted
    alpha-beta model -- the calibrated replacement for the
    bytes-over-ICI_GBS ring-hop guess.  None when the ledger or the
    needed fits are unusable."""
    if not isinstance(led, dict) or "error" in led:
        return None
    kinds = cal.get("collectives", {})
    nred = float(led.get("allreduce_per_iteration", 0) or 0)
    ar_bytes = float(led.get("allreduce_bytes_per_iteration", 0) or 0)
    ar_s = 0.0
    if nred > 0:
        per_red = ar_bytes / nred
        p = predict_seconds(kinds.get("all_reduce"), per_red)
        if p is None:
            return None
        ar_s = nred * p
    halo_one = halo_exchange_seconds(cal, led)
    if halo_one is None:
        return None
    nex = float(led.get("halo_exchanges_per_iteration", 1) or 1)
    halo_s = (halo_one * nex
              if led.get("halo_bytes_per_iteration") else 0.0)
    _fit, kind = _halo_fit(cal, led)
    return {"allreduce_s": float(ar_s), "halo_s": float(halo_s),
            "total_s": float(ar_s + halo_s),
            "halo_kind": kind,
            "calibration_id": str(cal.get("calibration_id", ""))}


# -- the --commbench CLI mode ---------------------------------------------

def _fmt_gbs(beta: float) -> str:
    if beta <= 0:
        return "inf GB/s"
    return f"{1.0 / beta / 1e9:,.2f} GB/s"


def collect_document(args, dtype, vec_dtype, err) -> dict:
    """Run the whole observatory over the configured case and mesh and
    return the commbench document (also printing the human summary to
    ``err``)."""
    import jax
    import jax.numpy as jnp

    from acg_tpu import perfmodel, telemetry
    from acg_tpu.parallel.mesh import PARTS_AXIS, solve_mesh

    csr = perfmodel._explain_matrix(args)
    on_tpu = jax.default_backend() == "tpu"
    # standalone default: up to 8 parts; under a live --explain
    # --commbench run, match run_explain's dist-tier default so the
    # calibration describes the very mesh the verdict prices
    nparts = args.nparts or min(len(jax.devices()),
                                4 if getattr(args, "explain", False)
                                else 8)
    if nparts < 2:
        raise SystemExit("acg-tpu: --commbench benchmarks the mesh "
                         "collectives; need --nparts >= 2 (or more "
                         "than one visible device)")
    mesh = solve_mesh(nparts)
    interpret = not on_tpu
    sweep = TPU_SWEEP if on_tpu else CPU_SWEEP
    reps = DEFAULT_REPS
    err.write(f"== commbench: {nparts}-part mesh "
              f"({'compiled ICI' if on_tpu else 'interpret/CPU'}), "
              f"{len(sweep)}-size sweep x {reps} chained rounds ==\n")

    colls = bench_collectives(mesh, sweep, reps=reps)
    dma_entry = None
    edges: list[dict] = []
    try:
        dma_entry = bench_dma(mesh, sweep, reps=reps,
                              interpret=interpret)
        edges = bench_dma_edges(mesh, max(sweep), reps=reps,
                                interpret=interpret)
    except Exception as e:  # noqa: BLE001 -- the one-sided transport
        # may be unavailable (e.g. unvalidated multi-chip ICI); the
        # XLA kinds still calibrate
        dma_entry = {"unavailable": f"{type(e).__name__}: {e}"}
        err.write(f"  dma transport bench unavailable: "
                  f"{type(e).__name__}: {e}\n")
    colls["dma"] = dma_entry
    for kind in KINDS:
        entry = colls.get(kind)
        if not isinstance(entry, dict) or "alpha_s" not in entry:
            why = (entry or {}).get("unavailable", "not benchmarked")
            err.write(f"  {kind:<19}: ({why})\n")
            continue
        err.write(f"  {kind:<19}: alpha {entry['alpha_s']:.3e} s, "
                  f"beta {entry['beta_s_per_byte']:.3e} s/B "
                  f"({_fmt_gbs(entry['beta_s_per_byte'])}), "
                  f"{entry['npoints']} point(s)"
                  + (f", r2 {entry['r2']:.3f}"
                     if entry.get("r2") is not None else "") + "\n")
    for row in edges:
        err.write(f"  dma edge d={row['distance']}: "
                  f"{row['window_bytes']:,} B window put+wait "
                  f"{row['put_wait_seconds']:.3e} s "
                  f"({row['peers_per_shard']} peer(s)/shard)\n")
    scalar_lat = None
    ar_pts = (colls.get("all_reduce") or {}).get("points") or []
    if ar_pts:
        scalar_lat = min(p["seconds"] for p in ar_pts)
        err.write(f"  scalar all_reduce latency: {scalar_lat:.3e} s\n")

    # the case's measured segment decomposition, through the same
    # dist-tier construction --explain uses
    segs: dict = {"available": False, "why": "dist tier construction "
                                             "failed"}
    case: dict = {"matrix": str(args.A), "n": int(csr.shape[0]),
                  "nnz": int(csr.nnz)}
    try:
        from acg_tpu.solvers.stats import StoppingCriteria

        # the SAME dist-tier construction run_explain analyses (one
        # copy -- the calibration must describe the very mesh the
        # explain verdict prices)
        solver = perfmodel.build_explain_dist_solver(
            args, csr, nparts, dtype, vec_dtype)
        b = np.ones(csr.shape[0])
        segs = segment_decomposition(solver, b)
        K = max(8, min(args.max_iterations, 60))
        solver.stats.tsolve = 0.0
        solver.solve(b, criteria=StoppingCriteria(maxits=K), warmup=1,
                     host_result=False, raise_on_divergence=False)
        case["measured_s_per_iteration"] = solver.stats.tsolve / K
        case["timed_iterations"] = K
        case["transport"] = solver.comm
    except Exception as e:  # noqa: BLE001
        err.write(f"acg-tpu: commbench segment pass failed: "
                  f"{type(e).__name__}: {e}\n")
        segs = {"available": False, "why": f"{type(e).__name__}: {e}"}
    if segs.get("available"):
        parts_txt = ", ".join(
            f"{k} {v['s_per_iteration']:.3e} s/iter"
            for k, v in segs["segments"].items())
        err.write(f"  segments: {parts_txt}\n")
        meas = case.get("measured_s_per_iteration")
        if meas:
            err.write(f"  explained {segs['explained_s_per_iteration']:.3e}"
                      f" of measured {meas:.3e} s/iter "
                      f"({segs['explained_s_per_iteration'] / meas:.0%}; "
                      f"remainder = axpy/control)\n")

    man = telemetry.run_manifest(metric="commbench",
                                 matrix=str(args.A), dtype=args.dtype)
    doc = {
        "schema": COMMBENCH_SCHEMA,
        "manifest": man,
        "nparts": int(nparts),
        "mesh_shape": {PARTS_AXIS: int(nparts)},
        "interpret": bool(interpret),
        "reps": int(reps),
        "sweep_bytes": [int(s) for s in sweep],
        "collectives": colls,
        "scalar_allreduce_latency_s": scalar_lat,
        "edges": edges,
        "segments": segs,
        "case": case,
    }
    doc["calibration_id"] = calibration_id(doc)
    err.write(f"  calibration id: {doc['calibration_id']}\n\n")
    from acg_tpu import metrics
    metrics.record_commbench(doc)
    return doc


def run_commbench(args, dtype, vec_dtype) -> int:
    """The CLI ``--commbench`` driver (standalone mode): run the suite,
    validate the document against our own validator (a doc we cannot
    re-read is a bug, not a capture), and write it."""
    err = sys.stderr
    doc = collect_document(args, dtype, vec_dtype, err)
    problems = validate_commbench(doc)
    if problems:
        err.write("acg-tpu: commbench produced an invalid document: "
                  + "; ".join(problems) + "\n")
        return 1
    try:
        write_document(doc, args.commbench)
    except OSError as e:
        err.write(f"acg-tpu: --commbench {args.commbench}: {e}\n")
        return 1
    if args.commbench not in (None, "-"):
        err.write(f"acg-tpu: commbench document written to "
                  f"{args.commbench}\n")
    return 0
