"""Numerical health observatory: in-loop true-residual audits, Lanczos
spectrum estimation, and accuracy gates across the solver tiers.

Pipelined CG trades attainable accuracy for hidden latency: the
recursively-updated residual drifts away from the true residual
``b - A x`` as rounding accumulates through the extra recurrences, and
the drift grows with pipeline depth (Cornelis & Vanroose,
arXiv:1801.04728; the global-reduction-pipelined variants of
arXiv:1905.06850 inherit the same trade).  Nothing in the existing
observability stack (telemetry ring, cost model, service metrics)
watches *numerical* health -- a solve can report ``converged`` from a
recurrence residual that no longer resembles ``b - A x``.  This module
closes that gap with three layers:

1. **In-loop true-residual audit** (``--audit-every K``): every K
   iterations the compiled loop recomputes ``b - A x`` through the
   tier's OWN SpMV/halo machinery and carries the relative gap
   ``||r_true - r_rec|| / ||b||`` in a small audit vector riding the
   loop carry (and, when telemetry is armed, an extra ``gap`` column in
   the convergence ring).  A gap past ``--gap-threshold`` emits a
   structured ``accuracy_degraded`` event; ``--on-gap replace`` exits
   the loop through the breakdown path so the existing
   :class:`~acg_tpu.solvers.resilience.RecoveryDriver` restarts from
   the recomputed true residual -- a residual-replacement restart --
   and ``--on-gap abort`` raises.  Disarmed (the default) every tier's
   lowered program is byte-identical (static jit argument, the
   telemetry/faults/precond discipline; pinned in
   tests/test_hlo_structure.py).

2. **Post-hoc spectrum estimation**: the telemetry ring already records
   the per-iteration ``(alpha, beta)`` CG coefficients, which ARE the
   entries of the Lanczos tridiagonal ``T_k`` of the (preconditioned)
   operator.  :func:`spectrum_estimate` rebuilds ``T_k``, reports
   estimated extremal eigenvalues and ``kappa(M^-1 A)``, and
   :func:`predicted_iterations` turns the classical CG error bound into
   a predicted-vs-measured iteration verdict (the ``--explain``
   "convergence" section and the ``health:`` stats section).

3. **Device-side stagnation/divergence detectors**
   (``--stall-window N``): a windowed residual-non-decrease counter and
   dot-product sign anomalies (a negative ``(r, r)``/``(r, z)`` is
   arithmetic poison, not a property of an SPD system) feed the
   existing breakdown path.

Surfaces: the append-only ``health:`` stats section (stats schema
bumped additively to ``acg-tpu-stats/5``), ``acg_health_*`` Prometheus
gauges/counters (:mod:`acg_tpu.metrics`), the ``--explain``
convergence verdict, and gap drift tracked by ``--soak`` alongside
latency drift.

Matrix-free generalization (ROADMAP item 5, acg_tpu.ops.operator):
every mechanism here consumes the operator ONLY through applies -- the
audit recomputes ``b - A x`` through the tier's SpMV selection, and the
ABFT column checksum ``c = A^T 1`` is computed *through the apply* at
setup (``spmv_(A, ones)`` in the solve programs) -- so arming
``--audit-every``/``--abft`` over a matrix-free operator needs no code
here at all: the dispatch in :mod:`acg_tpu.ops.spmv` routes the applies
and the audited trajectories stay bitwise-equal to the assembled
tier's (tests/test_matfree.py).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

ACTIONS = ("warn", "replace", "abort")

# audit-vector slot layout (the sdt (4,) array riding the loop carry)
AUD_GAP = 0        # latest audited relative gap ||r_true - r_rec||/||b||
AUD_GAP_MAX = 1    # running max over the solve's audits
AUD_COUNT = 2      # audits performed
AUD_STALL = 3      # consecutive non-decreasing-residual iterations
AUD_SLOTS = 4
# ABFT extension (spec.abft -- the Huang-Abraham checksum SpMV test,
# part of the survivability tier): four more slots, present ONLY when
# abft is armed so an abft-off spec keeps the historical 4-slot vector
ABFT_REL = 4       # latest relative checksum mismatch
ABFT_REL_MAX = 5   # running max
ABFT_COUNT = 6     # checks performed
ABFT_TRIPS = 7     # checks whose mismatch exceeded the threshold
ABFT_SLOTS = 8


@dataclasses.dataclass(frozen=True)
class HealthSpec:
    """One parsed numerical-health selection: immutable and hashable so
    it rides the solve programs' STATIC jit arguments (the FaultSpec /
    PrecondSpec design) -- a given spec compiles its own cache entry
    and ``None`` compiles the byte-identical unaudited program.

    ``every``: audit period in iterations (0 = no audit).
    ``threshold``: relative-gap trip level (0 = record-only).
    ``action``: what a tripped gap does -- ``warn`` (event only),
    ``replace`` (breakdown-path exit; the recovery driver restarts from
    the recomputed true residual = residual replacement), ``abort``
    (breakdown-path exit with no restart budget).
    ``stall_window``: consecutive non-decreasing-residual iterations
    before the stagnation detector trips the breakdown path (0 = off).
    ``abft``: arm the Huang-Abraham checksum-protected SpMV (the
    survivability tier): the column checksum ``c = A^T 1`` (= ``A 1``
    for the SPD systems this suite solves) is computed once through the
    tier's own SpMV, and every ``every`` iterations the in-loop test
    compares ``sum(A p)`` against ``(c, p)`` -- an identity that holds
    to rounding, so SILENT bit-level corruption of the SpMV output
    (``sdc:flip``) is detected on device at machine-epsilon scale,
    far below any useful gap threshold, and routed into the breakdown
    -> rollback/recovery path.  ``abft_threshold``: relative mismatch
    trip level (0 = a dtype/size-derived default,
    :func:`abft_default_threshold`).
    """

    every: int = 0
    threshold: float = 0.0
    action: str = "warn"
    stall_window: int = 0
    abft: bool = False
    abft_threshold: float = 0.0

    def __post_init__(self):
        if self.every < 0:
            raise ValueError("audit period (every) must be >= 0")
        if self.threshold < 0:
            raise ValueError("gap threshold must be >= 0")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown on-gap action {self.action!r} "
                             f"(one of {', '.join(ACTIONS)})")
        if self.stall_window < 0:
            raise ValueError("stall window must be >= 0")
        if self.action != "warn" and not (self.every and self.threshold):
            raise ValueError(
                f"on-gap action {self.action!r} needs an armed audit "
                f"(every > 0) AND a positive gap threshold -- a gate "
                f"that could never trip must refuse, not silently warn")
        if self.abft and not self.every:
            raise ValueError(
                "the ABFT checksum test fires at the audit cadence; "
                "arm it with a positive audit period (every > 0)")
        if self.abft_threshold < 0:
            raise ValueError("ABFT threshold must be >= 0 (0 = the "
                             "dtype-derived default)")
        if self.abft_threshold and not self.abft:
            raise ValueError("abft_threshold needs abft armed -- a "
                             "threshold that could never be consulted "
                             "must refuse")

    @property
    def armed(self) -> bool:
        return self.every > 0 or self.stall_window > 0

    @property
    def arms_detect(self) -> bool:
        """Whether this spec needs the breakdown-detection machinery in
        the loop (early exit): tripping gaps, the stagnation/sign
        detectors, and the ABFT test (always a tripper: a detected
        checksum mismatch that could not exit the loop would be a
        detector wired to nothing) do; a record-only gap audit does
        not."""
        return ((self.action != "warn" and self.threshold > 0
                 and self.every > 0) or self.stall_window > 0
                or self.abft)

    def __str__(self) -> str:
        parts = [f"audit-every={self.every}"]
        if self.threshold:
            parts.append(f"gap-threshold={self.threshold:g}")
        parts.append(f"on-gap={self.action}")
        if self.stall_window:
            parts.append(f"stall-window={self.stall_window}")
        if self.abft:
            parts.append("abft")
            if self.abft_threshold:
                parts.append(f"abft-threshold={self.abft_threshold:g}")
        return ",".join(parts)


def make_spec(every: int = 0, threshold: float = 0.0,
              action: str = "warn",
              stall_window: int = 0, abft: bool = False,
              abft_threshold: float = 0.0) -> HealthSpec | None:
    """``HealthSpec`` or None when nothing is armed (the CLI entry
    point; None keeps every call site's kwargs untouched so disarmed
    programs stay byte-identical)."""
    spec = HealthSpec(every=int(every), threshold=float(threshold),
                      action=str(action), stall_window=int(stall_window),
                      abft=bool(abft),
                      abft_threshold=float(abft_threshold))
    return spec if spec.armed else None


# -- device-side helpers (inside jit; spec fields are static) ------------

def audit_init(sdt, spec: HealthSpec | None = None):
    """The carried audit vector: ``[gap, gap_max, naudits, stall]``,
    gap NaN until the first audit fires (NaN > threshold is False, so
    an unaudited solve can never trip).  With ABFT armed the vector
    grows four checksum slots ``[rel, rel_max, nchecks, ntrips]``
    (rel NaN until the first check) -- abft-off specs keep the
    historical 4-slot layout."""
    import jax.numpy as jnp

    slots = [jnp.nan, 0.0, 0.0, 0.0]
    if spec is not None and spec.abft:
        slots += [jnp.nan, 0.0, 0.0, 0.0]
    return jnp.asarray(slots, dtype=sdt)


def relative_gap(rt, r, dot, bnrm2, sdt):
    """THE gap definition, shared by every tier's audit closure:
    ``||r_true - r_rec|| / ||b||`` from the tier's freshly-computed
    true residual ``rt`` and its recurrence residual ``r``, with the
    difference widened to the scalar dtype before the (tier-supplied,
    possibly psum'd/compensated) dot.  One definition so the tiers'
    gaps stay comparable -- the single-vs-8-part parity tests depend
    on it."""
    import jax.numpy as jnp

    d = (rt - r).astype(sdt)
    return jnp.sqrt(dot(d, d)) / bnrm2


def audit_update(aud, spec: HealthSpec, k, compute_gap):
    """``(aud', fire)``: run the audit when iteration ``k`` is on the
    period (``(k + 1) % every == 0``), else pass the vector through.
    ``compute_gap()`` is the tier's closure producing the relative gap
    through its own SpMV -- it runs inside the taken ``lax.cond``
    branch only, so a non-audited iteration costs nothing beyond the
    predicate (the mesh tiers' collectives are safe inside the cond
    because ``k`` is identical on every shard)."""
    if not spec.every:
        return aud, None
    import jax
    import jax.numpy as jnp

    def do(a):
        gap = jnp.asarray(compute_gap(), a.dtype).reshape(())
        # indexed updates, not a rebuilt stack: the vector's length
        # varies with the ABFT extension and the trailing slots must
        # pass through untouched
        return (a.at[AUD_GAP].set(gap)
                .at[AUD_GAP_MAX].set(jnp.maximum(a[AUD_GAP_MAX], gap))
                .at[AUD_COUNT].add(1))

    fire = (jnp.asarray(k, jnp.int32) + 1) % jnp.int32(spec.every) == 0
    return jax.lax.cond(fire, do, lambda a: a, aud), fire


def stall_update(aud, spec: HealthSpec, progressing):
    """Windowed residual-non-decrease counter: reset on progress,
    increment otherwise (``progressing`` = this iteration's residual
    scalar decreased)."""
    if not spec.stall_window:
        return aud
    import jax.numpy as jnp

    return aud.at[AUD_STALL].set(
        jnp.where(progressing, jnp.zeros((), aud.dtype),
                  aud[AUD_STALL] + 1))


def abft_default_threshold(sdt, n: int) -> float:
    """The relative-mismatch trip level when the spec leaves it 0:
    generous rounding headroom (the checksum identity holds to a few
    ulps of the summation; 64*sqrt(n) eps covers the worst observed
    cancellation) yet orders of magnitude below a single flipped
    element's signature (~2/n of the denominator for near-uniform
    SpMV outputs)."""
    import jax.numpy as jnp

    eps = float(jnp.finfo(jnp.dtype(sdt)).eps)
    return 64.0 * math.sqrt(max(float(n), 1.0)) * eps


def abft_update(aud, spec: HealthSpec, k, y, x, cvec, dot3, sdt,
                n: int):
    """The in-loop Huang-Abraham checksum verification of ``y = A x``:
    at the audit cadence, compare ``sum(y)`` against ``(c, x)`` where
    ``c = A^T 1`` (precomputed through the tier's own SpMV; equal to
    ``A 1`` for the symmetric systems this suite solves).  ``dot3`` is
    the tier's FUSED 3-dot closure (one psum of 3 scalars on the mesh
    tiers, so the armed delta is exactly +1 all_reduce and ZERO extra
    SpMVs/halo exchanges -- the checksum test is what makes SDC
    detection affordable every few iterations).

    The relative mismatch is measured against
    ``sqrt(n (y, y)) + |sum y| + |(c, x)|``: scale-free in the
    residual's decay (a flip of one element stays detectable at
    iteration 400 as at iteration 4) and robust to the cancellation in
    ``sum(y)`` near convergence.  A mismatch past the (default:
    dtype-derived) threshold increments the trip slot the breakdown
    predicate reads."""
    if not (spec.abft and spec.every):
        return aud
    import jax
    import jax.numpy as jnp

    tau = spec.abft_threshold or abft_default_threshold(sdt, n)

    def do(a):
        ys = y.astype(sdt)
        xs = x.astype(sdt)
        st, cp, tt = dot3(ys, jnp.ones_like(ys), cvec, xs, ys, ys)
        denom = (jnp.sqrt(jnp.maximum(tt, 0) * jnp.asarray(n, sdt))
                 + jnp.abs(st) + jnp.abs(cp)
                 + jnp.asarray(jnp.finfo(sdt).tiny, sdt))
        rel = jnp.abs(st - cp) / denom
        tripped = rel > jnp.asarray(tau, sdt)
        return (a.at[ABFT_REL].set(rel)
                .at[ABFT_REL_MAX].set(jnp.maximum(a[ABFT_REL_MAX], rel))
                .at[ABFT_COUNT].add(1)
                .at[ABFT_TRIPS].add(jnp.where(tripped,
                                              jnp.ones((), a.dtype),
                                              jnp.zeros((), a.dtype))))

    fire = (jnp.asarray(k, jnp.int32) + 1) % jnp.int32(spec.every) == 0
    return jax.lax.cond(fire, do, lambda a: a, aud)


def trip(aud, spec: HealthSpec):
    """The breakdown-path predicate this spec contributes: a tripped
    gap (action != warn), an exhausted stall window, and/or an ABFT
    checksum mismatch.  False dtype-correctly when no detector is
    armed."""
    import jax.numpy as jnp

    t = jnp.asarray(False)
    if spec.action != "warn" and spec.threshold > 0 and spec.every:
        t = t | (aud[AUD_GAP] > jnp.asarray(spec.threshold, aud.dtype))
    if spec.stall_window:
        t = t | (aud[AUD_STALL]
                 >= jnp.asarray(spec.stall_window, aud.dtype))
    if spec.abft:
        t = t | (aud[ABFT_TRIPS] > 0)
    return t


def ring_gap(aud, fire, sdt):
    """The ``gap`` column value for this iteration's telemetry record:
    the fresh gap when the audit fired, NaN otherwise (NaN marks
    unaudited iterations in mixed windows)."""
    import jax.numpy as jnp

    if fire is None:
        return jnp.asarray(jnp.nan, sdt)
    return jnp.where(fire, aud[AUD_GAP], jnp.asarray(jnp.nan, sdt))


# -- host-side audit summary ---------------------------------------------

def _clean(v: float):
    v = float(v)
    return v if math.isfinite(v) else None


def summarize_audit(aud, spec: HealthSpec) -> dict:
    """The ``health:`` stats entries for one solve's fetched audit
    vector (plus the armed configuration, so a reader can interpret
    the numbers without the launching shell)."""
    a = np.asarray(aud, dtype=np.float64).reshape(-1)
    out = {
        "audit_every": int(spec.every),
        "on_gap": spec.action,
        "gap_threshold": float(spec.threshold),
        "naudits": int(a[AUD_COUNT]) if math.isfinite(a[AUD_COUNT])
        else 0,
        "gap_last": _clean(a[AUD_GAP]),
        "gap_max": _clean(a[AUD_GAP_MAX]),
    }
    if spec.stall_window:
        out["stall_window"] = int(spec.stall_window)
        out["stall_count"] = _clean(a[AUD_STALL])
    if spec.abft and a.size >= ABFT_SLOTS:
        out["abft"] = {
            "threshold": float(spec.abft_threshold) or None,
            "nchecks": int(a[ABFT_COUNT]) if math.isfinite(a[ABFT_COUNT])
            else 0,
            "rel_last": _clean(a[ABFT_REL]),
            "rel_max": _clean(a[ABFT_REL_MAX]),
            "ntrips": int(a[ABFT_TRIPS]) if math.isfinite(a[ABFT_TRIPS])
            else 0,
        }
    return out


# the stats.health keys the audit summary owns (cleared when a new
# solve's first attempt reports, so a reused solver never shows a
# previous solve's numbers)
_AUDIT_KEYS = ("audit_every", "on_gap", "gap_threshold", "naudits",
               "gap_last", "gap_max", "stall_window", "stall_count",
               "abft", "spectrum")


def note_audit(stats, aud, spec: HealthSpec, what: str,
               fresh: bool = True) -> bool:
    """Record one solve ATTEMPT's audit vector onto ``stats.health``,
    feed the ``acg_health_*`` metrics, and emit the structured
    ``accuracy_degraded`` event when this attempt's gap exceeded the
    threshold.  ``fresh=False`` (the recovery loop's later attempts and
    the post-restart tail) MERGES with the attempts already recorded:
    ``naudits`` accumulates, ``gap_max`` keeps the worst gap of the
    whole solve -- a recovered solve must still show the drift that
    tripped it -- and ``gap_last`` survives a final attempt too short
    to audit.  Returns True when this attempt exceeded the threshold
    (the caller's recovery loop uses this to tell a gap trip from an
    arithmetic breakdown in its log)."""
    from acg_tpu import metrics, telemetry

    summary = summarize_audit(aud, spec)
    attempt_naudits = summary["naudits"]
    attempt_gap_max = summary.get("gap_max")
    # copy: the fresh=False merge below mutates summary["abft"] in place,
    # and the metrics/event tail must see only THIS attempt's numbers
    attempt_abft = summary.get("abft")
    if attempt_abft is not None:
        attempt_abft = dict(attempt_abft)
    if fresh:
        for k in _AUDIT_KEYS:
            stats.health.pop(k, None)
    else:
        prev = stats.health
        summary["naudits"] += int(prev.get("naudits") or 0)
        pm = prev.get("gap_max")
        if pm is not None:
            summary["gap_max"] = (max(pm, summary["gap_max"])
                                  if summary["gap_max"] is not None
                                  else pm)
        if summary.get("gap_last") is None:
            summary["gap_last"] = prev.get("gap_last")
        pa = prev.get("abft")
        if pa is not None and attempt_abft is not None:
            ab = summary["abft"]
            ab["nchecks"] += int(pa.get("nchecks") or 0)
            ab["ntrips"] += int(pa.get("ntrips") or 0)
            pmx = pa.get("rel_max")
            if pmx is not None:
                ab["rel_max"] = (max(pmx, ab["rel_max"])
                                 if ab["rel_max"] is not None else pmx)
            if ab.get("rel_last") is None:
                ab["rel_last"] = pa.get("rel_last")
    stats.health.update(summary)
    # the Prometheus counter gets only THIS attempt's increment (it is
    # cumulative across the process by construction)
    metrics.record_health_audit(summary.get("gap_last"),
                                attempt_naudits)
    if attempt_abft is not None:
        metrics.record_abft(attempt_abft.get("nchecks") or 0,
                            attempt_abft.get("rel_last"),
                            attempt_abft.get("ntrips") or 0)
        if attempt_abft.get("ntrips"):
            telemetry.record_event(
                stats, "abft_mismatch",
                f"{what}: ABFT checksum mismatch "
                f"{attempt_abft.get('rel_max'):.3e} "
                f"({attempt_abft['ntrips']} tripped check(s)) -- "
                f"silent SpMV corruption detected on device")
    exceeded = (spec.threshold > 0
                and attempt_gap_max is not None
                and attempt_gap_max > spec.threshold)
    if exceeded:
        telemetry.record_event(
            stats, "accuracy_degraded",
            f"{what}: true-residual gap {attempt_gap_max:.3e} "
            f"exceeds threshold {spec.threshold:g} "
            f"(audit every {spec.every}, on-gap {spec.action})")
        metrics.record_gap_trip()
    return exceeded


# -- Lanczos spectrum estimation from the recorded (alpha, beta) ----------

def lanczos_tridiagonal(alphas, betas, pipelined: bool = False,
                        window_start: int = 0):
    """``(diag, offdiag)`` of the Lanczos tridiagonal ``T_m`` implied by
    a run of CG coefficients -- the classical CG <-> Lanczos identity::

        T[k, k]     = 1/alpha_k + beta_{k-1}/alpha_{k-1}   (beta_{-1}=0)
        T[k, k+1]   = sqrt(beta_k) / alpha_k

    ``pipelined`` marks Ghysels-Vanroose traces, whose recorded beta at
    iteration k is the CLASSIC ``beta_{k-1}`` (computed at the top of
    the iteration from the carried gamma) -- the rows are re-aligned
    here.  ``window_start > 0`` (a wrapped telemetry ring) drops the
    leading row whose ``beta_{k-1}/alpha_{k-1}`` term predates the
    window; the inner tridiagonal of a Lanczos run is itself a valid
    Lanczos matrix of the same operator, so the estimate stays sound,
    just over a shorter recurrence.  Returns ``(None, None)`` when
    fewer than 2 usable rows survive."""
    a = np.asarray(alphas, dtype=np.float64)
    b = np.asarray(betas, dtype=np.float64)
    m = min(a.size, b.size)
    a, b = a[:m], b[:m]
    if m < 2:
        return None, None
    if pipelined:
        beta_prev = b.copy()                       # row k holds beta_{k-1}
        beta_cur = np.append(b[1:], np.nan)
    else:
        lead = 0.0 if window_start == 0 else np.nan
        beta_prev = np.concatenate([[lead], b[:-1]])
        beta_cur = b
    alpha_prev = np.concatenate([[np.nan], a[:-1]])
    with np.errstate(divide="ignore", invalid="ignore"):
        d = 1.0 / a + np.where(beta_prev == 0.0, 0.0,
                               beta_prev / alpha_prev)
        e = np.sqrt(np.maximum(beta_cur, 0.0)) / a
    start = 0 if np.isfinite(d[0]) else 1
    d, e, a = d[start:], e[start:], a[start:]
    # longest healthy prefix: a poisoned tail (breakdown window, NaN
    # alpha, negative pivot) must not corrupt the whole estimate
    ok = np.isfinite(d) & (a > 0)
    n = int(np.argmin(ok)) if not ok.all() else d.size
    if n < 2:
        return None, None
    d = d[:n]
    e = e[:n - 1]
    if not np.isfinite(e).all():
        # an off-diagonal became non-finite before the diagonal did:
        # keep the prefix before it
        n = int(np.argmin(np.isfinite(e))) + 1
        if n < 2:
            return None, None
        d, e = d[:n], e[:n - 1]
    return d, e


def _tridiag_eigvalsh(d, e):
    try:
        from scipy.linalg import eigh_tridiagonal

        return eigh_tridiagonal(d, e, eigvals_only=True)
    except Exception:  # noqa: BLE001 -- scipy variant/LAPACK issues
        T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
        return np.linalg.eigvalsh(T)


def spectrum_estimate(trace, precond: str | None = None) -> dict | None:
    """Estimated extremal eigenvalues and condition number of the
    (preconditioned) operator from one solve's telemetry window.

    The Ritz values of ``T_m`` converge to ``M^-1 A``'s extremal
    eigenvalues from inside, so ``kappa`` here is a LOWER bound that
    tightens with the iteration count -- good enough to grade a
    preconditioner and to drive the CG iteration bound, and free: the
    scalars were already recorded.  None when the window carries too
    few usable coefficients."""
    if trace is None or trace.records is None:
        return None
    rec = np.asarray(trace.records, dtype=np.float64)
    if rec.ndim != 2 or rec.shape[0] < 2 or rec.shape[1] < 3:
        return None
    # the CA recurrences (acg_tpu.recurrence: *-sstepS / *-plL solver
    # names) record CLASSIC-aligned rows by construction -- s-step
    # records each inner step's plain CG scalars, p(l) records
    # (q^2, 1/d, l^2, d) at solution-advance time, and alpha = 1/d /
    # beta = l^2 satisfy the classic CG<->Lanczos identity exactly --
    # so only the Ghysels-Vanroose names carry the re-alignment marker
    # (their spec names deliberately avoid the "pipelined" substring;
    # pinned in tests/test_recurrence.py)
    pipelined = "pipelined" in str(getattr(trace, "solver", ""))
    d, e = lanczos_tridiagonal(rec[:, 1], rec[:, 2],
                               pipelined=pipelined,
                               window_start=trace.first_iteration)
    if d is None:
        return None
    ev = _tridiag_eigvalsh(d, e)
    lmin = float(ev.min())
    lmax = float(ev.max())
    if not (math.isfinite(lmin) and math.isfinite(lmax)) or lmax <= 0:
        return None
    est: dict = {
        "m": int(d.size),
        "operator": ("M^-1 A" if precond and precond != "none" else "A"),
        "lambda_min": lmin,
        "lambda_max": lmax,
        "window_only": bool(getattr(trace, "wrapped", False)),
    }
    if lmin > 0:
        kappa = lmax / lmin
        est["kappa"] = kappa
        # asymptotic CG convergence factor (sqrt(k)-1)/(sqrt(k)+1)
        sk = math.sqrt(kappa)
        est["convergence_factor"] = (sk - 1.0) / (sk + 1.0)
    else:
        # a non-positive Ritz value: either the run broke down or the
        # window is too short to separate the low end -- report, don't
        # divide
        est["kappa"] = None
    return est


def predicted_iterations(kappa: float, rtol: float) -> int | None:
    """Iterations the classical CG bound predicts to reduce the A-norm
    error by ``rtol``: ``2 ((sqrt(k)-1)/(sqrt(k)+1))^j <= rtol``.  An
    upper bound on a worst-case spectrum -- clustered eigenvalues
    converge faster, so measured <= predicted is the healthy verdict.
    None when the inputs cannot drive the bound."""
    if not kappa or kappa <= 0 or not rtol or not 0 < rtol < 1:
        return None
    sk = math.sqrt(kappa)
    rate = (sk - 1.0) / (sk + 1.0)
    if rate <= 0:
        return 1
    return max(1, int(math.ceil(math.log(2.0 / rtol)
                                / -math.log(rate))))


def convergence_report(trace, niterations: int, rtol: float,
                       precond: str | None = None,
                       kappa_ref: float | None = None) -> dict | None:
    """The ``spectrum`` entry of the ``health:`` section (and the
    ``--explain`` convergence verdict): spectrum estimate + the
    predicted-vs-measured iteration comparison, plus the
    preconditioner-effectiveness score when an unpreconditioned
    ``kappa_ref`` is available to compare against."""
    est = spectrum_estimate(trace, precond=precond)
    if est is None:
        return None
    kappa = est.get("kappa")
    pred = predicted_iterations(kappa, rtol) if kappa else None
    est["measured_iterations"] = int(niterations)
    if pred is not None:
        est["predicted_iterations"] = pred
        est["rtol"] = float(rtol)
        est["bound_ratio"] = (float(niterations) / pred) if pred else None
    if kappa_ref is not None and kappa:
        # kappa(A) / kappa(M^-1 A): > 1 means the preconditioner
        # genuinely compressed the spectrum (the sqrt of this ratio is
        # the asymptotic iteration-count reduction)
        est["kappa_unpreconditioned"] = float(kappa_ref)
        est["precond_effectiveness"] = float(kappa_ref) / kappa
    return est


def attach_spectrum(stats, trace, rtol: float,
                    precond: str | None = None,
                    kappa_ref: float | None = None) -> dict | None:
    """Compute and record the post-hoc spectrum report onto
    ``stats.health`` (no-op without a usable trace) and feed the
    ``acg_health_kappa_estimate`` gauge."""
    rep = convergence_report(trace, stats.niterations, rtol,
                             precond=precond, kappa_ref=kappa_ref)
    if rep is None:
        return None
    stats.health["spectrum"] = rep
    from acg_tpu import metrics, observatory

    if rep.get("kappa"):
        metrics.record_health_kappa(rep["kappa"])
        # live-observatory tier: the kappa CG-bound is the status
        # endpoint's preferred ETA source (no-op disarmed)
        observatory.note_kappa(rep["kappa"],
                               rep.get("predicted_iterations"))
    return rep
